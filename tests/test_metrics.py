"""Python metric accumulators + chunk_eval op tests
(reference: python/paddle/fluid/metrics.py:1, evaluator.py:1,
operators/chunk_eval_op.cc)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, metrics
from tests.op_test import run_op


def _auc_reference(scores, labels):
    """Exact pairwise (Mann-Whitney) ROC AUC."""
    pos = scores[labels == 1]
    neg = scores[labels == 0]
    gt = (pos[:, None] > neg[None, :]).sum()
    eq = (pos[:, None] == neg[None, :]).sum()
    return (gt + 0.5 * eq) / (len(pos) * len(neg))


def test_auc_accumulator_matches_exact():
    rng = np.random.RandomState(0)
    auc = metrics.Auc(num_thresholds=4095)
    all_scores, all_labels = [], []
    for _ in range(5):  # batch accumulation
        scores = rng.rand(200).astype(np.float32)
        labels = rng.randint(0, 2, 200)
        auc.update(np.stack([1 - scores, scores], 1), labels)
        all_scores.append(scores)
        all_labels.append(labels)
    got = auc.eval()
    want = _auc_reference(np.concatenate(all_scores),
                          np.concatenate(all_labels))
    assert abs(got - want) < 5e-3, (got, want)


def test_precision_recall_accumulators():
    p = metrics.Precision()
    r = metrics.Recall()
    preds = np.array([0.9, 0.8, 0.2, 0.6, 0.1])
    labels = np.array([1, 0, 1, 1, 0])
    p.update(preds, labels)
    r.update(preds, labels)
    # thresholded at 0.5: predictions [1,1,0,1,0]; tp=2 fp=1 fn=1
    assert p.eval() == pytest.approx(2 / 3)
    assert r.eval() == pytest.approx(2 / 3)
    # accumulate a second batch
    p.update(np.array([0.7]), np.array([1]))
    assert p.eval() == pytest.approx(3 / 4)


def test_accuracy_weighted_mean():
    acc = metrics.Accuracy()
    acc.update(value=0.5, weight=10)
    acc.update(value=1.0, weight=30)
    assert acc.eval() == pytest.approx(0.875)
    acc.reset()
    with pytest.raises(ValueError):
        acc.eval()


def test_edit_distance_metric():
    m = metrics.EditDistance()
    m.update(np.array([[0.0], [2.0], [1.0]]), 3)
    m.update(np.array([[0.0]]), 1)
    avg, err = m.eval()
    assert avg == pytest.approx(3.0 / 4)
    assert err == pytest.approx(2.0 / 4)


def test_composite_metric():
    c = metrics.CompositeMetric()
    c.add_metric(metrics.Precision())
    c.add_metric(metrics.Recall())
    c.update(np.array([0.9, 0.1]), np.array([1, 1]))
    res = c.eval()
    assert res[0] == pytest.approx(1.0)   # precision
    assert res[1] == pytest.approx(0.5)   # recall


def _iob_chunks(tags, L, num_types):
    """Reference chunk extraction (IOB: tag = 2*type + {B:0, I:1})."""
    chunks = []
    start = None
    ctype = None
    for t in range(L):
        tag = tags[t]
        if tag >= 2 * num_types:  # O
            if start is not None:
                chunks.append((start, t - 1, ctype))
                start = None
            continue
        typ, pos = tag // 2, tag % 2
        if pos == 0 or start is None or typ != ctype:  # B or broken I
            if start is not None:
                chunks.append((start, t - 1, ctype))
            start, ctype = t, typ
    if start is not None:
        chunks.append((start, L - 1, ctype))
    return set(chunks)


def test_chunk_eval_matches_bruteforce():
    rng = np.random.RandomState(1)
    B, T, NT = 6, 12, 3
    o_tag = 2 * NT
    inf = rng.randint(0, o_tag + 1, (B, T)).astype(np.int64)
    lab = rng.randint(0, o_tag + 1, (B, T)).astype(np.int64)
    seq_len = rng.randint(4, T + 1, B).astype(np.int32)
    n_inf = run_op("chunk_eval",
                   {"Inference": inf, "Label": lab, "SeqLen": seq_len},
                   attrs={"num_chunk_types": NT},
                   out_slot="NumInferChunks")
    n_lab = run_op("chunk_eval",
                   {"Inference": inf, "Label": lab, "SeqLen": seq_len},
                   attrs={"num_chunk_types": NT},
                   out_slot="NumLabelChunks")
    n_cor = run_op("chunk_eval",
                   {"Inference": inf, "Label": lab, "SeqLen": seq_len},
                   attrs={"num_chunk_types": NT},
                   out_slot="NumCorrectChunks")
    wi = wl = wc = 0
    for b in range(B):
        ci = _iob_chunks(inf[b], seq_len[b], NT)
        cl = _iob_chunks(lab[b], seq_len[b], NT)
        wi += len(ci)
        wl += len(cl)
        wc += len(ci & cl)
    assert n_inf[0] == wi, (n_inf, wi)
    assert n_lab[0] == wl, (n_lab, wl)
    assert n_cor[0] == wc, (n_cor, wc)


def test_chunk_evaluator_accumulates():
    ev = metrics.ChunkEvaluator()
    ev.update(10, 8, 6)
    ev.update(5, 7, 4)
    p, r, f1 = ev.eval()
    assert p == pytest.approx(10 / 15)
    assert r == pytest.approx(10 / 15)
    assert f1 == pytest.approx(10 / 15)


def test_chunk_eval_layer_in_program():
    B, T, NT = 3, 6, 2
    rng = np.random.RandomState(2)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        from paddle_tpu import layers

        inf = layers.data("inf", shape=[B, T], dtype="int64",
                          append_batch_size=False, lod_level=1)
        lab = layers.data("lab", shape=[B, T], dtype="int64",
                          append_batch_size=False)
        (prec, rec, f1, ni, nl, nc) = layers.chunk_eval(
            inf, lab, chunk_scheme="IOB", num_chunk_types=NT)
    exe = fluid.Executor()
    tags = rng.randint(0, 2 * NT + 1, (B, T)).astype(np.int64)
    res = exe.run(main,
                  feed={"inf": tags, "inf.seq_len": np.full(B, T, np.int32),
                        "lab": tags},
                  fetch_list=[prec, rec, f1, ni, nl, nc])
    # identical sequences → perfect P/R/F1
    assert res[0][0] == pytest.approx(1.0)
    assert res[1][0] == pytest.approx(1.0)
    assert res[3][0] == res[4][0] == res[5][0]


# -- round 3: in-graph evaluator + multi-session serving ---------------------

def test_in_graph_chunk_evaluator_accumulates_on_device():
    """fluid.evaluator.ChunkEvaluator (reference evaluator.py:251):
    counters are persistable graph state updated inside the step; P/R/F1
    come from the accumulated device totals."""
    import paddle_tpu.evaluator as evaluator

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        pred = layers.data(name="pred", shape=[6], dtype="int64")
        lab = layers.data(name="lab", shape=[6], dtype="int64")
        slen = layers.data(name="slen", shape=[], dtype="int32")
        ev = evaluator.ChunkEvaluator(pred, lab, chunk_scheme="IOB",
                                      num_chunk_types=2, seq_len=slen)
        exe = fluid.Executor()
        exe.run(startup)
        # IOB with 2 types: tag 0 = B-0, 1 = I-0, 2 = B-1, 3 = I-1,
        # 4 = O.  Perfect batch then a half-right batch.
        perfect = np.array([[0, 1, 4, 2, 3, 4]], np.int64)
        noisy = np.array([[0, 4, 4, 2, 3, 4]], np.int64)
        slen_v = np.array([6], np.int32)
        exe.run(main, feed={"pred": perfect, "lab": perfect,
                            "slen": slen_v},
                fetch_list=[ev.batch_metrics[0]])
        p1, r1, f1 = ev.eval()
        assert (p1, r1) == (1.0, 1.0)
        exe.run(main, feed={"pred": noisy, "lab": perfect,
                            "slen": slen_v},
                fetch_list=[ev.batch_metrics[0]])
        p2, r2, _ = ev.eval()
        # accumulated: infer 2+2=4... noisy has chunks [0],[2,3] → 2
        # infer chunks, 1 correct ([2,3]); totals: infer 4, label 4,
        # correct 3
        assert abs(p2 - 0.75) < 1e-6 and abs(r2 - 0.75) < 1e-6
        ev.reset()
        assert ev.eval() == (0.0, 0.0, 0.0)


def test_predictor_clone_shares_weights_and_serves(tmp_path):
    """Predictor.clone (reference AnalysisPredictor::Clone): clones
    share device params + executable cache and serve concurrently."""
    import threading

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data(name="x", shape=[8], dtype="float32")
        out_v = layers.fc(x, size=4, act="softmax")
        exe = fluid.Executor()
        exe.run(startup)
        d = str(tmp_path / "m")
        fluid.io.save_inference_model(d, ["x"], [out_v], exe,
                                      main_program=main)
    base = fluid.Predictor(d)
    feed = {"x": rng.rand(8, 8).astype(np.float32)}
    (ref,) = base.run(feed)
    clones = [base.clone() for _ in range(4)]
    assert all(c._params is base._params for c in clones)
    assert all(c._compiled is base._compiled for c in clones)

    results = {}
    errors = []

    def serve(i, c):
        try:
            for _ in range(5):
                (o,) = c.run(feed)
            results[i] = o
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=serve, args=(i, c))
               for i, c in enumerate(clones)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    for o in results.values():
        np.testing.assert_allclose(o, ref, rtol=1e-6)
