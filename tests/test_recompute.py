"""Rematerialization (fluid.recompute_scope -> jax.checkpoint):
marked segments recompute activations in the backward; math is
IDENTICAL with and without the scope, and the remat primitive actually
appears in the traced step.
"""

from __future__ import annotations

import numpy as np

import jax

import paddle_tpu as fluid
from paddle_tpu import layers


def _build(use_recompute):
    x = layers.data("x", shape=[16])
    y = layers.data("y", shape=[1], dtype="int64")
    h = layers.fc(x, size=32, act="relu", name="pre")
    if use_recompute:
        with fluid.recompute_scope():
            h = layers.fc(h, size=32, act="relu", name="mid1")
            h = layers.fc(h, size=32, act="tanh", name="mid2")
    else:
        h = layers.fc(h, size=32, act="relu", name="mid1")
        h = layers.fc(h, size=32, act="tanh", name="mid2")
    logits = layers.fc(h, size=4, name="post")
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
    fluid.optimizer.MomentumOptimizer(learning_rate=0.1,
                                      momentum=0.9).minimize(loss)
    return loss


def _run(use_recompute, steps=4):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 5
    scope = fluid.Scope()
    losses = []
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        loss = _build(use_recompute)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(2)
        xv = rng.randn(32, 16).astype(np.float32)
        yv = rng.randint(0, 4, (32, 1)).astype(np.int64)
        for _ in range(steps):
            lv, = exe.run(main, feed={"x": xv, "y": yv},
                          fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    return main, losses


def test_recompute_scope_matches_plain_training():
    _, plain = _run(False)
    main_r, remat = _run(True)
    np.testing.assert_allclose(remat, plain, rtol=1e-6, atol=1e-7)
    assert remat[-1] < remat[0]
    # the scope actually stamped the ops
    tagged = [op.desc.type for op in main_r.global_block().ops
              if op.desc.attrs.get("__recompute__") is not None]
    assert "mul" in tagged and len(tagged) >= 4


def test_recompute_emits_remat_primitive():
    """The traced step of a recompute program contains the checkpoint
    primitive; the plain program does not."""
    from paddle_tpu.core.executor import interpret_program

    def jaxpr_of(use_recompute):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 5
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), fluid.unique_name.guard():
            loss = _build(use_recompute)
            exe = fluid.Executor()
            exe.run(startup)
            gs = fluid.global_scope()
            state = {k: v for k, v in gs.vars.items() if v is not None
                     and not k.startswith("__")}
            feeds = {"x": np.zeros((8, 16), np.float32),
                     "y": np.zeros((8, 1), np.int64)}

            def step(st, fd):
                env = dict(st)
                env.update(fd)
                env = interpret_program(main, env,
                                        jax.random.PRNGKey(0),
                                        fetch_names=(loss.name,))
                return env[loss.name]

            return str(jax.make_jaxpr(step)(state, feeds))

    with_r = jaxpr_of(True)
    without = jaxpr_of(False)
    assert "remat" in with_r or "checkpoint" in with_r
    assert "remat" not in without and "checkpoint" not in without


def test_recompute_scope_nests_and_restores():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = layers.data("x", shape=[4])
        layers.fc(x, size=4)                       # untagged
        with fluid.recompute_scope():
            layers.fc(x, size=4)                   # tagged
        layers.fc(x, size=4)                       # untagged again
    tags = [op.desc.attrs.get("__recompute__")
            for op in main.global_block().ops]
    assert any(t is not None for t in tags)
    assert tags[0] is None and tags[-1] is None


def test_transformer_recompute_option_parity():
    """build_model(recompute=True) wraps each encoder/decoder layer in
    a remat scope; trajectory identical to the plain build."""
    from paddle_tpu.models import transformer

    def run(rc):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 4
        scope = fluid.Scope()
        losses = []
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), fluid.unique_name.guard():
            m = transformer.build_model(
                src_vocab_size=64, trg_vocab_size=64, max_length=8,
                n_layer=2, n_head=2, d_model=16, d_inner_hid=32,
                dropout=0.0, recompute=rc)
            exe = fluid.Executor()
            exe.run(startup)
            feed = transformer.make_fake_batch(4, 8, 60, 60)
            for _ in range(3):
                lv, = exe.run(main, feed=feed, fetch_list=[m["loss"]])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
        if rc:
            tagged = sum(
                1 for op in main.global_block().ops
                if op.desc.attrs.get("__recompute__") is not None)
            assert tagged > 20  # both stacks tagged
        return losses

    np.testing.assert_allclose(run(True), run(False), rtol=1e-6)
