"""Fused attention op.

The reference composes attention from matmul/softmax primitives
(nets.py scaled_dot_product_attention; the 2018 codebase has no fused
kernel — SURVEY.md §5.7 marks this a capability gap to fill natively).
`flash_attention` is the single-op attention: inputs Q/K/V laid out
(N, H, T, D) plus an optional additive Bias; the default implementation
is a numerically-stable lax composition (XLA fuses it well on TPU), and
ops/pallas/flash_attention.py provides the tiled Pallas kernel used when
`use_pallas` is set and we're on TPU (forward via custom_vjp).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first, opt_in, out


def _xla_attention(q, k, v, bias, scale, causal):
    logits = jnp.einsum("nhqd,nhkd->nhqk", q, k) * scale
    if bias is not None:
        logits = logits + bias
    if causal:
        t_q, t_k = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((t_q, t_k), jnp.bool_))
        logits = jnp.where(mask, logits, -1e9)
    weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    o = jnp.einsum("nhqk,nhkd->nhqd", weights.astype(q.dtype), v)
    return o


@register_op("flash_attention")
def flash_attention(ctx, ins, attrs):
    q, k, v = first(ins, "Q"), first(ins, "K"), first(ins, "V")
    bias = opt_in(ins, "Bias")
    scale = attrs.get("scale", None)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    causal = attrs.get("causal", False)
    if attrs.get("use_pallas", False):
        from .pallas.flash_attention import pallas_flash_attention

        o = pallas_flash_attention(q, k, v, bias, scale, causal)
    else:
        o = _xla_attention(q, k, v, bias, scale, causal)
    return out(Out=o)


@register_op("fused_vocab_softmax_ce")
def fused_vocab_softmax_ce(ctx, ins, attrs):
    """Final vocab projection + label-smoothed softmax-CE in one fused
    op (ops/pallas/vocab_ce.py): Hidden (..., D) @ W (D, V) logits are
    never materialized in HBM.  With use_pallas unset (or on CPU) runs
    an XLA chunked-equivalent composition for numerics parity."""
    hidden = first(ins, "Hidden")
    w = first(ins, "W")
    labels = first(ins, "Label")
    eps = float(attrs.get("epsilon", 0.0))
    if attrs.get("use_pallas", False):
        from .pallas.vocab_ce import fused_vocab_ce

        loss = fused_vocab_ce(
            hidden, w, labels, eps,
            int(attrs.get("block_t", 1024)),
            int(attrs.get("block_v", 2048)))
    else:
        v = w.shape[1]
        z = (hidden @ w).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(z, axis=-1)
        zt = jnp.take_along_axis(
            z, labels.reshape(labels.shape + (1,)).astype(jnp.int32),
            axis=-1)[..., 0]
        loss = lse - (1.0 - eps) * zt - (eps / v) * jnp.sum(z, axis=-1)
    return out(Loss=loss)
