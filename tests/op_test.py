"""OpTest-equivalent harness.

reference: python/paddle/fluid/tests/unittests/op_test.py:132 — per-op
forward check against a reference computation plus analytic-vs-numeric
gradient comparison (get_numeric_gradient:43, check_grad:414).  Here the
analytic grads come from jax AD over the registered op impl; the numeric
side is central finite differences.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import OpContext, get_op_impl


def run_op(op_type, ins_np, attrs=None, out_slot="Out", n_outs=None):
    """Execute one op impl on numpy inputs.  ins_np: {slot: array or
    [arrays]}."""
    impl = get_op_impl(op_type)
    ins = {}
    for slot, v in ins_np.items():
        vs = v if isinstance(v, (list, tuple)) else [v]
        ins[slot] = [jnp.asarray(a) for a in vs]
    ctx = OpContext(jax.random.PRNGKey(0), 0)
    outs = impl(ctx, ins, dict(attrs or {}))
    res = outs[out_slot]
    if n_outs is None:
        return np.asarray(res[0])
    return [np.asarray(r) for r in res[:n_outs]]


def check_output(op_type, ins_np, expected, attrs=None, out_slot="Out",
                 rtol=1e-5, atol=1e-6):
    got = run_op(op_type, ins_np, attrs, out_slot)
    np.testing.assert_allclose(got, expected, rtol=rtol, atol=atol,
                               err_msg=f"op {op_type} forward mismatch")


def check_grad(op_type, ins_np, grad_slot, attrs=None, out_slot="Out",
               eps=1e-3, max_relative_error=5e-3):
    """Compare jax.grad of sum(op(out_slot)) w.r.t. ins_np[grad_slot]
    against numeric central differences (reference check_grad semantics
    with sum-cotangent)."""
    impl = get_op_impl(op_type)
    attrs = dict(attrs or {})

    base = {s: (v if isinstance(v, (list, tuple)) else [v])
            for s, v in ins_np.items()}

    def f(x):
        ins = {s: [jnp.asarray(a) for a in vs] for s, vs in base.items()}
        ins[grad_slot] = [x] + [jnp.asarray(a)
                                for a in base[grad_slot][1:]]
        ctx = OpContext(jax.random.PRNGKey(0), 0)
        return jnp.sum(impl(ctx, ins, attrs)[out_slot][0])

    x0 = np.asarray(base[grad_slot][0], dtype=np.float64).astype(np.float32)
    analytic = np.asarray(jax.grad(f)(jnp.asarray(x0)))

    # one vmapped+jitted evaluation over ALL 2*size perturbed inputs:
    # per-element eager loops retrace the op for every probe and made
    # the registry-wide sweep dominate CI time
    flat0 = x0.reshape(-1)
    n = flat0.size
    probes = np.tile(flat0, (2 * n, 1))
    idx = np.arange(n)
    probes[idx, idx] += eps
    probes[n + idx, idx] -= eps

    f_batch = jax.jit(jax.vmap(lambda fx: f(fx.reshape(x0.shape))))
    vals = np.asarray(f_batch(jnp.asarray(probes, jnp.float32)),
                      dtype=np.float64)
    numeric = ((vals[:n] - vals[n:]) / (2 * eps)).reshape(x0.shape)

    denom = np.maximum(np.abs(numeric), 1.0)
    rel = np.abs(analytic - numeric) / denom
    assert rel.max() <= max_relative_error, (
        f"op {op_type} grad mismatch: max rel err {rel.max():.4g}\n"
        f"analytic={analytic.reshape(-1)[:5]} numeric={numeric.reshape(-1)[:5]}")
