"""Device mesh construction.

Replaces the reference's device topology handling (NCCLContextMap over
places, platform/nccl_helper.h:86; multi-trainer ranks at
parallel_executor.cc:254).  A Mesh names the parallelism axes; shardings
reference axes by name and XLA routes collectives over ICI (fast, within
slice) vs DCN (across slices) according to mesh layout.

Conventional axis names: "dp" (data), "mp" (tensor/model), "sp"
(sequence/context), "pp" (pipeline), "ep" (expert).
"""

from __future__ import annotations

import contextvars
from typing import Dict, NamedTuple, Optional, Sequence

import numpy as np


def make_mesh(axes: Dict[str, int], devices=None):
    """Build a jax.sharding.Mesh with named axes, e.g.
    make_mesh({"dp": 4, "mp": 2})."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = int(np.prod(list(axes.values())))
    if n > len(devices):
        raise ValueError(
            f"mesh needs {n} devices, only {len(devices)} available")
    arr = np.asarray(devices[:n]).reshape(tuple(axes.values()))
    return Mesh(arr, tuple(axes.keys()))


_default_mesh = None


def set_default_mesh(mesh):
    global _default_mesh
    _default_mesh = mesh


class ExecContext(NamedTuple):
    """What a CompiledProgram trace exposes to mesh-aware op impls:
    the mesh, the name of the mesh axis the batch dim is sharded over
    (so sp/pp shard_maps keep dp-sharded activations sharded instead of
    assuming the axis is literally called "dp"), and the pipeline
    microbatch count (0 = pipelining off)."""

    mesh: object
    batch_axis: str = "dp"
    pipeline_microbatches: int = 0


# ContextVar, not a module global: two CompiledPrograms tracing
# concurrently (threads, or a nested trace) must not cross-contaminate
# the mesh seen by mesh-aware op impls.
_exec_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "paddle_tpu_exec_ctx", default=None)


class executing_mesh:
    """Trace-time marker: the mesh a CompiledProgram is being traced
    under.  Mesh-aware op impls (sequence-parallel flash attention, the
    pipeline engine) read it via get_executing_mesh() /
    get_exec_context() to route onto shard_map collectives; it is set
    only while the wrapper traces its step."""

    def __init__(self, mesh, batch_axis: str = "dp",
                 pipeline_microbatches: int = 0):
        self._ctx = ExecContext(mesh, batch_axis, pipeline_microbatches)

    def __enter__(self):
        self._token = _exec_ctx.set(self._ctx)
        return self._ctx.mesh

    def __exit__(self, *exc):
        _exec_ctx.reset(self._token)
        return False


def get_executing_mesh():
    ctx = _exec_ctx.get()
    return None if ctx is None else ctx.mesh


def get_exec_context() -> Optional[ExecContext]:
    return _exec_ctx.get()


def get_default_mesh(create_dp: bool = True):
    """The process-wide mesh; lazily a pure-DP mesh over all devices."""
    global _default_mesh
    if _default_mesh is None and create_dp:
        import jax

        _default_mesh = make_mesh({"dp": len(jax.devices())})
    return _default_mesh
