"""Functional collectives over the mesh.

Replaces the reference's NCCL op handles and raw nccl ops
(details/all_reduce_op_handle.cc, operators/nccl/nccl_op.cu.cc,
collective_server).  These are thin shard_map wrappers around XLA
collectives (psum / all_gather / ppermute / all_to_all) for code that
wants explicit communication (ring attention, expert dispatch); ordinary
data/tensor parallelism never calls these — GSPMD inserts collectives
from sharding annotations alone.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def compat_shard_map(fn, mesh, in_specs, out_specs, check=False,
                     auto=frozenset()):
    """shard_map with two jax API drifts smoothed over: the import
    location (jax.shard_map vs jax.experimental.shard_map) and the
    replication-check kwarg rename (check_rep -> check_vma).  `check`
    feeds whichever kwarg this jax has.

    `auto`: mesh axes left to GSPMD (partial-auto shard_map) — the
    composed grad-sync path maps manually over the data axes while mp
    stays auto-partitioned.  CAUTION: only psum-family collectives
    (psum/pmean/pmax) survive partial-auto on this XLA; all_gather /
    all_to_all hard-abort the SPMD partitioner (the reason
    quantized_all_reduce_psum exists)."""
    import inspect

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    params = inspect.signature(shard_map).parameters
    kw = {("check_vma" if "check_vma" in params else "check_rep"):
          check}
    if auto:
        if "auto" not in params:
            raise NotImplementedError(
                "this jax's shard_map has no partial-auto support; "
                "composed-mesh grad sync needs it")
        kw["auto"] = frozenset(auto)
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, **kw)


def _shard_map(fn, mesh, in_specs, out_specs):
    return compat_shard_map(fn, mesh, in_specs, out_specs)


def psum(x, axis_name):
    return jax.lax.psum(x, axis_name)


def all_reduce(x, mesh, axis: str, shard_dim: int = 0, op: str = "sum"):
    """Reduce per-device values stacked along `shard_dim` to one
    replicated result with that dim removed (the PE all-reduce,
    details/all_reduce_op_handle.cc: N per-device grads → one summed
    grad everywhere)."""
    spec = [None] * x.ndim
    spec[shard_dim] = axis

    def f(xs):
        if op == "sum":
            r = jax.lax.psum(xs, axis)
        elif op == "max":
            r = jax.lax.pmax(xs, axis)
        elif op == "mean":
            r = jax.lax.pmean(xs, axis)
        else:
            raise ValueError(op)
        return jax.numpy.squeeze(r, shard_dim)

    out_spec = [None] * (x.ndim - 1)
    return _shard_map(f, mesh, (P(*spec),), P(*out_spec))(x)


def all_gather(x, mesh, axis: str, shard_dim: int = 0):
    spec = [None] * x.ndim
    spec[shard_dim] = axis

    def f(xs):
        return jax.lax.all_gather(xs, axis, axis=shard_dim, tiled=True)

    return _shard_map(f, mesh, (P(*spec),), P(*[None] * x.ndim))(x)


def reduce_scatter(x, mesh, axis: str, shard_dim: int = 0):
    """Replicated-in, sharded-out sum (the kReduce build-strategy mode,
    build_strategy.h:55)."""
    def f(xs):
        return jax.lax.psum_scatter(xs, axis, scatter_dimension=shard_dim,
                                    tiled=True)

    out_spec = [None] * x.ndim
    out_spec[shard_dim] = axis
    return _shard_map(f, mesh, (P(*[None] * x.ndim),), P(*out_spec))(x)


def ppermute(x, mesh, axis: str, perm, shard_dim: int = 0):
    """Neighbor exchange over the ring (ICI) — building block for ring
    attention."""
    spec = [None] * x.ndim
    spec[shard_dim] = axis

    def f(xs):
        return jax.lax.ppermute(xs, axis, perm)

    return _shard_map(f, mesh, (P(*spec),), P(*spec))(x)


def all_to_all(x, mesh, axis: str, split_dim: int, concat_dim: int):
    """Ulysses-style head/sequence exchange."""
    n = mesh.shape[axis]
    in_spec = [None] * x.ndim
    in_spec[concat_dim] = axis

    def f(xs):
        return jax.lax.all_to_all(xs, axis, split_axis=split_dim,
                                  concat_axis=concat_dim, tiled=True)

    out_spec = [None] * x.ndim
    out_spec[split_dim] = axis
    return _shard_map(f, mesh, (P(*in_spec),), P(*out_spec))(x)


def barrier(mesh, axis: str):
    """Synchronization barrier (the reference's send_barrier /
    fetch_barrier ops) — a trivial psum forces a cross-replica sync."""
    def f():
        return jax.lax.psum(jnp.ones(()), axis)

    return _shard_map(f, mesh, (), P())()


# ---------------------------------------------------------------------------
# Quantized gradient all-reduce (EQuARX, arxiv 2506.17615)
# ---------------------------------------------------------------------------

# Tensors below this element count ride the exact psum instead of the
# quantized exchange: at small sizes the per-block scale sidecar and the
# two-phase latency cost more than the byte saving, and biases /
# layernorm scales are exactly the tensors where quantization error
# hurts most per byte moved (docs/DIST.md, error model).
DEFAULT_QUANT_BLOCK = 256
DEFAULT_QUANT_FLOOR = 4096


def _numel(shape) -> int:
    out = 1
    for d in shape:
        out *= int(d)
    return out


def quantize_blockwise(x, block_size: int = DEFAULT_QUANT_BLOCK):
    """Symmetric per-block int8 quantization of a flat (..., block)
    array: scale = max|block| / 127 (0-blocks get scale 1 so they
    round-trip to exact zeros).  Deterministic: jnp.rint is
    round-half-even, and the scale depends only on the block's values —
    every rank quantizing the same bytes produces the same bytes.

    Returns (q int8 of x.shape, scales f32 of x.shape[:-1])."""
    assert x.shape[-1] == block_size, (x.shape, block_size)
    amax = jnp.max(jnp.abs(x), axis=-1)
    scales = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.rint(x / scales[..., None]), -127, 127)
    return q.astype(jnp.int8), scales


def dequantize_blockwise(q, scales, dtype=jnp.float32):
    return q.astype(dtype) * scales[..., None].astype(dtype)


def quantized_all_reduce_local(x, axis: str, n_ranks: int,
                               block_size: int = DEFAULT_QUANT_BLOCK,
                               min_quant_numel: int = DEFAULT_QUANT_FLOOR,
                               op: str = "mean"):
    """Blockwise-int8 all-reduce of a per-rank partial value — for use
    INSIDE a shard_map over `axis` where every rank holds a full-shaped
    partial sum (the dp gradient-sync situation).  EQuARX-style
    two-phase exchange:

      phase 1 (reduce-scatter): split into one chunk per rank,
        quantize each chunk per `block_size` block (int8 payload + f32
        scale sidecar, ~1/[2·block] overhead), all_to_all so rank i
        receives everyone's chunk i, dequantize into f32 and
        accumulate locally;
      phase 2 (all-gather): re-quantize the reduced chunk and
        all_gather payload + scales, dequantize.

    vs the bf16 ring all-reduce this moves ~half the bytes per phase
    (int8 vs bf16) at the cost of two quantization roundings; the
    elementwise error bound is documented in docs/DIST.md and pinned by
    tests/test_quantized_allreduce.py.

    Determinism: quantization is value-deterministic, the accumulation
    is a fixed-order sum over the rank dim, and phase 2's gathered
    bytes are identical on every rank — all ranks agree BITWISE on the
    result (the property dp grad sync needs so replicated params never
    drift apart).

    Falls back to the exact jax.lax.psum for tensors smaller than
    `min_quant_numel` (or than one block per rank) and for non-float
    inputs.  op: "sum" or "mean" (mean divides by n_ranks — the dp
    gradient convention where each rank differentiates its local-batch
    mean loss)."""
    if op not in ("sum", "mean"):
        raise ValueError(f"unknown reduce op {op!r}")
    inv = 1.0 / n_ranks if op == "mean" else 1.0

    def exact(v):
        r = jax.lax.psum(v, axis)
        return r * jnp.asarray(inv, r.dtype) if op == "mean" else r

    size = _numel(x.shape)
    if (not jnp.issubdtype(x.dtype, jnp.floating)
            or size < max(min_quant_numel, n_ranks * block_size)):
        return exact(x)

    orig_dtype = x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    pad = (-size) % (n_ranks * block_size)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    # (n_ranks, blocks_per_chunk, block)
    chunks = flat.reshape(n_ranks, -1, block_size)

    # phase 1: quantize every outgoing chunk, exchange, accumulate
    q, scales = quantize_blockwise(chunks, block_size)
    q = jax.lax.all_to_all(q, axis, split_axis=0, concat_axis=0,
                           tiled=False)
    scales = jax.lax.all_to_all(scales, axis, split_axis=0,
                                concat_axis=0, tiled=False)
    reduced = jnp.sum(dequantize_blockwise(q, scales), axis=0)

    # phase 2: re-quantize the reduced chunk, gather all chunks back
    q2, s2 = quantize_blockwise(reduced, block_size)
    q2 = jax.lax.all_gather(q2, axis, axis=0, tiled=True)
    s2 = jax.lax.all_gather(s2, axis, axis=0, tiled=True)
    out = dequantize_blockwise(q2, s2).reshape(-1)
    if pad:
        out = out[:size]
    return (out * inv).reshape(x.shape).astype(orig_dtype)


def quantized_all_reduce_psum(x, axes, n_ranks: int, rank_index,
                              block_size: int = DEFAULT_QUANT_BLOCK,
                              min_quant_numel: int = DEFAULT_QUANT_FLOOR,
                              op: str = "mean"):
    """The EQuARX two-phase exchange in its psum-only form — for
    shard_map regions where all_to_all/all_gather cannot lower (a
    partial-auto region with GSPMD-owned axes, or a multi-axis data
    group): SAME quantization steps, SAME error model, but the data
    movement is a single psum.

      phase 1: quantize every chunk per block (identical bytes to the
        wire path), dequantize locally, psum over `axes` — every rank
        now holds every reduced chunk (the wire path's rank i holds
        only chunk i);
      phase 2: re-quantize ALL reduced chunks (rank i's chunk i
        quantizes identically on every rank — same input bytes, same
        rint), dequantize.  No gather needed: the phase-2 result is
        already replicated, bitwise-identically, everywhere.

    Determinism: quantization is value-deterministic and psum produces
    bitwise-identical results on every participating rank, so all
    ranks agree bitwise — the dp grad-sync invariant.  `rank_index` is
    accepted for signature symmetry with a future chunk-local variant
    and unused (every rank computes all chunks).

    Byte honesty: this form moves f32 psum bytes, not int8 payloads —
    the numerics/error-model guarantees hold, the wire-byte saving
    does NOT (docs/DIST.md §hybrid).  Pure single-axis dp keeps the
    real all_to_all/all_gather exchange."""
    del rank_index
    if op not in ("sum", "mean"):
        raise ValueError(f"unknown reduce op {op!r}")
    inv = 1.0 / n_ranks if op == "mean" else 1.0

    def exact(v):
        r = jax.lax.psum(v, axes)
        return r * jnp.asarray(inv, r.dtype) if op == "mean" else r

    size = _numel(x.shape)
    if (not jnp.issubdtype(x.dtype, jnp.floating)
            or size < max(min_quant_numel, n_ranks * block_size)):
        return exact(x)

    orig_dtype = x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    pad = (-size) % (n_ranks * block_size)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    chunks = flat.reshape(n_ranks, -1, block_size)

    # phase 1: quantize outgoing chunks, reduce via psum of the
    # dequantized payloads (numerically the wire path's fixed-order
    # rank sum up to all-reduce ordering; bitwise-identical everywhere)
    q, scales = quantize_blockwise(chunks, block_size)
    reduced = jax.lax.psum(dequantize_blockwise(q, scales), axes)

    # phase 2: re-quantize the reduced chunks — replicated input bytes
    # make the rounding identical on every rank, so no gather is needed
    q2, s2 = quantize_blockwise(reduced, block_size)
    out = dequantize_blockwise(q2, s2).reshape(-1)
    if pad:
        out = out[:size]
    return (out * inv).reshape(x.shape).astype(orig_dtype)


def quantized_all_reduce(x, mesh, axis: str, shard_dim: int = 0,
                         op: str = "mean",
                         block_size: int = DEFAULT_QUANT_BLOCK,
                         min_quant_numel: int = DEFAULT_QUANT_FLOOR):
    """Host-level wrapper mirroring `all_reduce`: per-rank partial
    values stacked along `shard_dim` reduce to one replicated result
    with that dim removed, through the blockwise-int8 two-phase
    exchange above.  The executor's dp grad-sync hook calls the _local
    form directly inside its own shard_map; this wrapper is the
    standalone/test surface."""
    n = mesh.shape[axis]
    spec = [None] * x.ndim
    spec[shard_dim] = axis

    def f(xs):
        v = jnp.squeeze(xs, shard_dim)
        return quantized_all_reduce_local(
            v, axis, n, block_size=block_size,
            min_quant_numel=min_quant_numel, op=op)

    out_spec = [None] * (x.ndim - 1)
    return _shard_map(f, mesh, (P(*spec),), P(*out_spec))(x)
