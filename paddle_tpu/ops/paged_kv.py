"""Paged KV-cache ops: the decode-serving analog of the ragged family.

Continuous-batching autoregressive decode (serving/decode.py) keeps
every slot's K/V in fixed-size PAGES of one shared pool, addressed
through a per-slot page table — the Ragged Paged Attention design
(PAPERS.md arxiv 2604.15464) on the repo's padded-dense + lengths
convention.  Three ops own the cache contract:

- `paged_kv_write`: commit ONE token's K/V per slot at its current
  length (the decode-step write).  Functional: pools in, pools out —
  the engine donates the buffers so XLA updates them in place.
- `paged_kv_prefill_write`: commit a whole prompt's K/V (the
  prefill-on-join write), positions 0..seq_len-1 per slot.
- `paged_attention`: one query token per slot attends over its pages,
  masked to its true length.  Default impl is an XLA dense-gather twin
  (layout-matched, the CPU/parity fallback); `use_pallas` routes to the
  tiled kernel (ops/pallas/paged_attention.py).
- `paged_kv_import`: scatter another pool's exported rows into this
  pool's pages (the disagg prefill→decode handoff,
  serving/disagg.py) — same drop-mode idiom, one fixed shape for any
  prompt length.

All three are born in the head-major (S, H*D) / (P, page, H*D) layout
(ISSUE 8): a page write is a plain row scatter and no transpose exists
at any boundary.  Writes for inactive/out-of-range slots are dropped by
scatter mode="drop" (index pushed out of bounds), so one fixed-shape
executable serves any join/leave pattern — the zero-recompile contract.

Opt-in int8 pools (the EQuARX blockwise scheme of
parallel/collectives.py applied per cache row): KScale/VScale sidecar
pools (P, page, 1) carry one f32 scale per written token row; the
write op quantizes (symmetric, absmax/127), both attention paths
dequantize.

`add_position_encoding_at` is the decode-step twin of
add_position_encoding: the sinusoid at ONE position per row (the
slot's current length), same formula so prefill and decode agree.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first, opt_in, out

_INT8_MAX = 127.0


def _quantize_rows(x):
    """Per-row symmetric int8: x (..., HD) -> (codes int8, scale f32
    (..., 1)); zero rows quantize to scale 1 (all-zero codes)."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1,
                     keepdims=True)
    scale = jnp.where(absmax > 0, absmax / _INT8_MAX, 1.0)
    codes = jnp.clip(jnp.round(x.astype(jnp.float32) / scale),
                     -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
    return codes, scale


def _write_rows(pool, phys, off, rows):
    """pool (P, page, HD) <- rows at [phys, off]; OOB phys drops."""
    return pool.at[phys, off].set(rows.astype(pool.dtype), mode="drop")


@register_op("paged_kv_write")
def paged_kv_write(ctx, ins, attrs):
    """One decode step's K/V commit.

    K/V (S, HD); KCache/VCache (P, page, HD); PageTable (S, max_pages)
    int32; WritePos (S,) int32 (the position being committed = current
    length); optional Active (S,) — 0/false rows write nothing.  With
    int8 caches, KScale/VScale (P, page, 1) f32 sidecars are required
    inputs and updated alongside.
    Outputs: KCacheOut/VCacheOut (+KScaleOut/VScaleOut for int8)."""
    k, v = first(ins, "K"), first(ins, "V")
    kc, vc = first(ins, "KCache"), first(ins, "VCache")
    pt = first(ins, "PageTable").astype(jnp.int32)
    wp = first(ins, "WritePos").astype(jnp.int32)
    active = opt_in(ins, "Active")
    ks, vs = opt_in(ins, "KScale"), opt_in(ins, "VScale")
    n_pages, page, _ = kc.shape
    s = k.shape[0]
    page_idx = wp // page
    off = wp % page
    # logical page past the table is a config error; clamp the GATHER
    # (the scatter below is dropped anyway when inactive)
    phys = jnp.take_along_axis(
        pt, jnp.clip(page_idx, 0, pt.shape[1] - 1)[:, None],
        axis=1)[:, 0]
    drop = page_idx >= pt.shape[1]
    if active is not None:
        drop = drop | (active.astype(jnp.int32) == 0)
    phys = jnp.where(drop, n_pages, phys)   # OOB -> mode="drop"
    int8 = kc.dtype == jnp.int8
    if int8:
        if ks is None or vs is None:
            raise ValueError("int8 KV cache needs KScale/VScale "
                             "sidecar pools")
        k_q, k_sc = _quantize_rows(k)
        v_q, v_sc = _quantize_rows(v)
        res = out(KCacheOut=_write_rows(kc, phys, off, k_q),
                  VCacheOut=_write_rows(vc, phys, off, v_q))
        res.update(out(
            KScaleOut=ks.at[phys, off].set(k_sc, mode="drop"),
            VScaleOut=vs.at[phys, off].set(v_sc, mode="drop")))
        return res
    return out(KCacheOut=_write_rows(kc, phys, off, k),
               VCacheOut=_write_rows(vc, phys, off, v))


@register_op("paged_kv_prefill_write")
def paged_kv_prefill_write(ctx, ins, attrs):
    """A whole prompt's K/V commit (prefill-on-join).

    K/V (S, T, HD); caches/table as in paged_kv_write; SeqLen (S,)
    int32 — positions t >= SeqLen[s] (padding, and every position of a
    non-joining slot, whose SeqLen is 0) are dropped."""
    k, v = first(ins, "K"), first(ins, "V")
    kc, vc = first(ins, "KCache"), first(ins, "VCache")
    pt = first(ins, "PageTable").astype(jnp.int32)
    seq_len = first(ins, "SeqLen").astype(jnp.int32)
    ks, vs = opt_in(ins, "KScale"), opt_in(ins, "VScale")
    n_pages, page, _ = kc.shape
    s, t, _ = k.shape
    pos = jnp.arange(t, dtype=jnp.int32)[None, :]            # (1, T)
    page_idx = pos // page                                    # (1, T)
    off = jnp.broadcast_to(pos % page, (s, t))
    phys = jnp.take_along_axis(
        pt, jnp.clip(jnp.broadcast_to(page_idx, (s, t)), 0,
                     pt.shape[1] - 1), axis=1)                # (S, T)
    valid = (pos < seq_len[:, None]) & (page_idx < pt.shape[1])
    phys = jnp.where(valid, phys, n_pages)   # OOB -> mode="drop"
    if kc.dtype == jnp.int8:
        if ks is None or vs is None:
            raise ValueError("int8 KV cache needs KScale/VScale "
                             "sidecar pools")
        k_q, k_sc = _quantize_rows(k)
        v_q, v_sc = _quantize_rows(v)
        res = out(KCacheOut=_write_rows(kc, phys, off, k_q),
                  VCacheOut=_write_rows(vc, phys, off, v_q))
        res.update(out(
            KScaleOut=ks.at[phys, off].set(k_sc, mode="drop"),
            VScaleOut=vs.at[phys, off].set(v_sc, mode="drop")))
        return res
    return out(KCacheOut=_write_rows(kc, phys, off, k),
               VCacheOut=_write_rows(vc, phys, off, v))


def paged_import_rows(pool, rows, pt_row, num_valid):
    """One slot's exported dense rows -> this pool's pages (the disagg
    prefill→decode KV handoff, serving/disagg.py).

    rows (T_cap, C) is a token-major page gather of the SOURCE pool
    (positions 0..T_cap-1, T_cap = max_pages * page); pt_row
    (max_pages,) int32 names the RECEIVING slot's physical pages;
    positions >= num_valid (export padding — whatever the zeroed source
    table pointed at) are dropped via the OOB-scatter idiom, so one
    fixed shape imports any prompt length.  Rows are already in pool
    dtype (int8 codes and scale sidecars travel verbatim — bitwise, no
    requantization)."""
    n_pages, page, _ = pool.shape
    t_cap = rows.shape[0]
    pos = jnp.arange(t_cap, dtype=jnp.int32)
    page_idx = pos // page
    off = pos % page
    pt_row = pt_row.astype(jnp.int32)
    phys = pt_row[jnp.clip(page_idx, 0, pt_row.shape[0] - 1)]
    valid = (pos < num_valid) & (page_idx < pt_row.shape[0])
    phys = jnp.where(valid, phys, n_pages)   # OOB -> mode="drop"
    return pool.at[phys, off].set(rows.astype(pool.dtype), mode="drop")


@register_op("paged_kv_import")
def paged_kv_import(ctx, ins, attrs):
    """Import one slot's exported KV rows into a cache pool.

    Rows (T_cap, C) token-major export of the source pool; Cache
    (P, page, C); PageTable (max_pages,) int32 — the receiving slot's
    pages; NumValid scalar int32 — rows at positions >= it drop.
    Output: CacheOut (P, page, C).  Serving-only (the disagg handoff
    path); applies identically to int8 code pools and their scale
    sidecars."""
    rows = first(ins, "Rows")
    cache = first(ins, "Cache")
    pt = first(ins, "PageTable").astype(jnp.int32)
    nv = first(ins, "NumValid").astype(jnp.int32).reshape(())
    return out(CacheOut=paged_import_rows(cache, rows, pt, nv))


def _gather_pool(pool, pt):
    """(P, page, HD) gathered through (S, maxp) -> (S, maxp*page, HD)
    — a free reshape after the gather, no transpose."""
    g = pool[pt]                                  # (S, maxp, page, HD)
    s, maxp, page, hd = g.shape
    return g.reshape(s, maxp * page, hd)


def _xla_paged_attention(q, kc, vc, pt, lengths, n_head, scale,
                         ks=None, vs=None):
    """Dense-gather twin, layout-matched to the Pallas kernel: gather
    every slot's pages to a dense (S, T_cap, HD) view, mask to the true
    length, head-split via free minor-dim reshapes (the
    _xla_attention_nthd pattern — no transpose)."""
    s, hd = q.shape
    d = hd // n_head
    k = _gather_pool(kc, pt).astype(jnp.float32)
    v = _gather_pool(vc, pt).astype(jnp.float32)
    if ks is not None:
        k = k * _gather_pool(ks, pt).astype(jnp.float32)
    if vs is not None:
        v = v * _gather_pool(vs, pt).astype(jnp.float32)
    t_cap = k.shape[1]
    valid = (jnp.arange(t_cap, dtype=jnp.int32)[None, :]
             < lengths[:, None])                  # (S, T_cap)
    # zero invalid v rows: pages past a slot's length are undefined
    # pool memory (possibly another slot's evicted garbage) and
    # 0 * NaN would poison the weighted sum even at weight 0
    v = jnp.where(valid[:, :, None], v, 0.0)
    q4 = q.astype(jnp.float32).reshape(s, n_head, d)
    k4 = k.reshape(s, t_cap, n_head, d)
    v4 = v.reshape(s, t_cap, n_head, d)
    logits = jnp.einsum("shd,sthd->sht", q4, k4) * scale
    logits = jnp.where(valid[:, None, :], logits, -1e30)
    w = jax.nn.softmax(logits, axis=-1)
    o = jnp.einsum("sht,sthd->shd", w, v4)
    return o.reshape(s, hd).astype(q.dtype)


@register_op("paged_attention")
def paged_attention(ctx, ins, attrs):
    """Decode-step ragged paged attention (see module docstring).

    Q (S, H*D) head-grouped; KCache/VCache (P, page, H*D); PageTable
    (S, max_pages) int32; Lengths (S,) int32.  attrs: n_head
    (required), scale (default d^-0.5), use_pallas (default False —
    the XLA dense-gather twin; the kernel interprets on CPU)."""
    q = first(ins, "Q")
    kc, vc = first(ins, "KCache"), first(ins, "VCache")
    pt = first(ins, "PageTable").astype(jnp.int32)
    lengths = first(ins, "Lengths").astype(jnp.int32)
    ks, vs = opt_in(ins, "KScale"), opt_in(ins, "VScale")
    n_head = int(attrs.get("n_head") or 0)
    if not n_head:
        raise ValueError("paged_attention needs the n_head attr "
                         "(operands are head-grouped (S, H*D))")
    if q.shape[-1] % n_head:
        raise ValueError(f"paged_attention: minor dim {q.shape[-1]} "
                         f"not divisible by n_head {n_head}")
    scale = attrs.get("scale")
    if scale is None:
        scale = (q.shape[-1] // n_head) ** -0.5
    if (kc.dtype == jnp.int8) != (ks is not None):
        raise ValueError("int8 KV caches require KScale/VScale inputs "
                         "(and float caches must not carry them)")
    if attrs.get("use_pallas", False):
        from .pallas.paged_attention import ragged_paged_attention

        return out(Out=ragged_paged_attention(
            q, kc, vc, pt, lengths, n_head=n_head, scale=float(scale),
            k_scales=ks, v_scales=vs))
    return out(Out=_xla_paged_attention(q, kc, vc, pt, lengths, n_head,
                                        float(scale), ks=ks, vs=vs))


@register_op("speculative_accept")
def speculative_accept(ctx, ins, attrs):
    """Greedy longest-accepted-prefix acceptance for speculative decode.

    The verify program scores k drafted tokens per slot in one forward
    (the step body at folded batch S*(k+1), staggered lengths); its
    argmax Predictions (S, k+1) are what the SEQUENTIAL engine would
    have produced at positions L..L+k given the drafted prefix.  A
    draft token is accepted iff every earlier draft matched — so the
    committed stream is bit-identical to the sequential engine:

      match_i   = (Drafts[:, i-1] == Predictions[:, i-1]) & (i <= DraftLen)
      Accepted  = sum(cumprod(match))          # in 0..k, -1 if inactive
      Tokens[j] = Predictions[j] if j <= Accepted else -1

    Predictions[a] is the model's own next token after the accepted
    prefix, so every verify emits Accepted+1 tokens (>= 1): the engine
    never stalls even at accept rate 0.  Inputs: Drafts (S, k) int,
    Predictions (S, k+1) int, DraftLen (S,) int32 (ragged drafts ride
    this companion — no recompiles), optional Active (S,).  Outputs:
    Accepted (S,) int32, Tokens (S, k+1) int32 (-1 padding)."""
    drafts = first(ins, "Drafts").astype(jnp.int32)
    preds = first(ins, "Predictions").astype(jnp.int32)
    dlen = first(ins, "DraftLen").astype(jnp.int32)
    active = opt_in(ins, "Active")
    if preds.ndim != 2 or drafts.ndim != 2:
        raise ValueError("speculative_accept: Drafts (S, k) and "
                         "Predictions (S, k+1) must be rank-2")
    s, k1 = preds.shape
    k = k1 - 1
    if drafts.shape != (s, k):
        raise ValueError(
            f"speculative_accept: Drafts {drafts.shape} must be "
            f"(S, k) = ({s}, {k}) for Predictions {preds.shape}")
    idx = jnp.arange(1, k + 1, dtype=jnp.int32)[None, :]      # (1, k)
    match = (drafts == preds[:, :k]) & (idx <= dlen[:, None])
    accepted = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                       axis=1).astype(jnp.int32)              # (S,)
    if active is not None:
        accepted = jnp.where(active.astype(jnp.int32) != 0,
                             accepted, -1).astype(jnp.int32)
    pos = jnp.arange(k1, dtype=jnp.int32)[None, :]            # (1, k+1)
    tokens = jnp.where(pos <= accepted[:, None], preds,
                       -1).astype(jnp.int32)
    return out(Accepted=accepted, Tokens=tokens)


@register_op("add_position_encoding_at")
def add_position_encoding_at(ctx, ins, attrs):
    """X (S, D) + sinusoid(Position[s]) — the single-token decode twin
    of add_position_encoding (same formula, per-row position instead of
    0..T-1), so a decoded token sees exactly the encoding its position
    would have had inside a prefill."""
    x = first(ins, "X")
    position = first(ins, "Position").astype(jnp.float32)
    alpha = attrs.get("alpha", 1.0)
    beta = attrs.get("beta", 1.0)
    d = x.shape[-1]
    pos = position[:, None]                              # (S, 1)
    div = jnp.exp(jnp.arange(0, d, 2, dtype=jnp.float32)
                  * (-jnp.log(10000.0) / d))
    pe = jnp.zeros((x.shape[0], d), jnp.float32)
    pe = pe.at[:, 0::2].set(jnp.sin(pos * div))
    pe = pe.at[:, 1::2].set(jnp.cos(pos * div[: d // 2]))
    return out(Out=(alpha * x + beta * pe).astype(x.dtype))
