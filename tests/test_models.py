"""Model zoo smoke tests: build + one train step, loss finite & decreasing
where cheap.  Mirrors the reference's benchmark-model coverage
(benchmark/fluid/models/*) at tiny configs.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.models import (bert, deepfm, mnist, resnet,
                               stacked_dynamic_lstm, transformer, vgg)


def _run_steps(build_fn, batch_fn, steps=3, fetch_key="loss"):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        model = build_fn()
        exe = fluid.Executor()
        exe.run(startup)
        losses = []
        for i in range(steps):
            feed = batch_fn(i)
            (lv,) = exe.run(main, feed=feed,
                            fetch_list=[model[fetch_key]])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert all(np.isfinite(l) for l in losses), losses
    return losses


def test_mnist_model():
    rng = np.random.RandomState(0)

    def batch(i):
        return {"pixel": rng.rand(8, 1, 28, 28).astype(np.float32),
                "label": rng.randint(0, 10, (8, 1)).astype(np.int64)}

    losses = _run_steps(mnist.build_model, batch, steps=5)
    assert losses[-1] < losses[0] * 1.5


def test_resnet_cifar_model():
    rng = np.random.RandomState(0)

    def batch(i):
        return {"data": rng.rand(4, 3, 32, 32).astype(np.float32),
                "label": rng.randint(0, 10, (4, 1)).astype(np.int64)}

    _run_steps(lambda: resnet.build_model(dataset="cifar10",
                                          learning_rate=0.001),
               batch, steps=2)


def test_vgg_model():
    rng = np.random.RandomState(0)

    def batch(i):
        return {"data": rng.rand(2, 3, 32, 32).astype(np.float32),
                "label": rng.randint(0, 10, (2, 1)).astype(np.int64)}

    _run_steps(lambda: vgg.build_model(dataset="cifar10"), batch, steps=2)


def test_transformer_model_tiny():
    def batch(i):
        return transformer.make_fake_batch(2, max_length=16,
                                           src_vocab=100, trg_vocab=100,
                                           seed=i)

    losses = _run_steps(
        lambda: transformer.build_model(
            src_vocab_size=100, trg_vocab_size=100, max_length=16,
            n_layer=2, n_head=2, d_model=32, d_inner_hid=64,
            warmup_steps=10),
        batch, steps=3)
    # label-smoothed CE over 100 classes starts near ln(100)≈4.6
    assert losses[0] < 10.0


def test_stacked_lstm_model_tiny():
    def batch(i):
        return stacked_dynamic_lstm.make_fake_batch(4, max_len=12,
                                                    vocab_size=50, seed=i)

    _run_steps(
        lambda: stacked_dynamic_lstm.build_model(
            vocab_size=50, emb_dim=16, hidden_dim=16, stacked_num=2,
            max_len=12),
        batch, steps=2)


def test_deepfm_model_tiny():
    def batch(i):
        return deepfm.make_fake_batch(8, num_fields=5, num_dense=3,
                                      vocab_size=1000, seed=i)

    losses = _run_steps(
        lambda: deepfm.build_model(num_fields=5, num_dense=3,
                                   vocab_size=1000, embedding_dim=8,
                                   dnn_hidden=(16, 16)),
        batch, steps=3)
    assert losses[0] < 2.0  # sigmoid CE starts near ln(2)


def test_bert_model_tiny():
    def batch(i):
        return bert.make_fake_batch(2, max_len=16, vocab_size=100,
                                    max_predictions=4, seed=i)

    _run_steps(
        lambda: bert.build_model(vocab_size=100, max_len=16, n_layer=2,
                                 n_head=2, d_model=32, d_inner=64,
                                 max_predictions=4, warmup_steps=10),
        batch, steps=2)


def test_word2vec_nce_trains():
    """Book model: N-gram LM with NCE (reference book/test_word2vec.py)."""
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.models import word2vec

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        model = word2vec.build_model(dict_size=200, batch_size=32,
                                     learning_rate=0.05)
        exe = fluid.Executor()
        exe.run(startup)
        feed = word2vec.make_fake_batch(32, dict_size=200)
        losses = [
            float(exe.run(main, feed=feed,
                          fetch_list=[model["loss"]])[0].reshape(()))
            for _ in range(40)
        ]
    assert np.isfinite(losses).all()
    assert np.mean(losses[-10:]) < np.mean(losses[:10])


def test_recommender_system_trains():
    """Book model: two-tower recommender (reference
    book/test_recommender_system.py)."""
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.models import recommender

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        model = recommender.build_model(batch_size=32)
        exe = fluid.Executor()
        exe.run(startup)
        feed = recommender.make_fake_batch(32)
        losses = [
            float(exe.run(main, feed=feed,
                          fetch_list=[model["loss"]])[0].reshape(()))
            for _ in range(25)
        ]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_resnet_nhwc_matches_nchw():
    """data_format="NHWC" (TPU-preferred channels-last) is numerically
    the same network: identical init (seeded), loss trajectories match
    within conv reduction-order noise."""
    from paddle_tpu.models import resnet

    def run(fmt):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 9
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), fluid.unique_name.guard():
            m = resnet.build_model(dataset="cifar10", learning_rate=0.1,
                                   data_format=fmt)
            exe = fluid.Executor()
            exe.run(startup)
            rng = np.random.RandomState(0)
            feed = {"data": rng.rand(4, 3, 32, 32).astype(np.float32),
                    "label": rng.randint(0, 10, (4, 1)).astype(np.int64)}
            losses = []
            for _ in range(3):
                lv, = exe.run(main, feed=feed, fetch_list=[m["loss"]])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
        return losses

    # rtol covers conv reduction-order noise COMPOUNDED through two
    # lr=0.1 SGD updates (the 3rd-step loss drifts ~2.4e-3 rel on this
    # jax's XLA:CPU conv algorithms; steps 1-2 agree to 1e-6).  A real
    # layout bug produces O(1) divergence from step 1.
    np.testing.assert_allclose(run("NCHW"), run("NHWC"), rtol=6e-3,
                               atol=1e-4)


def test_transformer_flash_cross_parity():
    """flash_cross=True (cross attention through the flash op — the
    long-context path) matches the composed-cross program's loss."""
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer

    def run(flash_cross):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 5
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), fluid.unique_name.guard():
            model = transformer.build_model(
                src_vocab_size=64, trg_vocab_size=64, max_length=16,
                n_layer=2, n_head=2, d_model=32, d_inner_hid=64,
                dropout=0.0, with_optimizer=True, learning_rate=0.5,
                warmup_steps=10, use_flash=True,
                flash_cross=flash_cross)
            exe = fluid.Executor()
            exe.run(startup)
            batch = transformer.make_fake_batch(
                4, max_length=16, src_vocab=64, trg_vocab=64)
            losses = []
            for _ in range(3):
                (lv,) = exe.run(main, feed=batch,
                                fetch_list=[model["loss"]])
                losses.append(float(np.ravel(lv)[0]))
        return losses

    np.testing.assert_allclose(run(True), run(False), rtol=2e-4,
                               atol=2e-4)
