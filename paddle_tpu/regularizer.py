"""Weight-decay regularizers appended to gradients.

reference: python/paddle/fluid/regularizer.py — L1Decay/L2Decay append ops
rewriting each gradient before the optimizer update.
"""

from __future__ import annotations


class WeightDecayRegularizer:
    def append_regularization_op(self, param, grad, block):
        raise NotImplementedError


class L2Decay(WeightDecayRegularizer):
    def __init__(self, regularization_coeff: float = 0.0):
        self.coeff = regularization_coeff

    def append_regularization_op(self, param, grad, block):
        decay = block.create_var(
            name=f"{param.name}.l2decay", dtype=grad.dtype,
            shape=grad.shape, stop_gradient=True)
        block.append_op(type="scale", inputs={"X": [param]},
                        outputs={"Out": [decay]},
                        attrs={"scale": self.coeff, "bias": 0.0,
                               "bias_after_scale": True})
        block.append_op(type="sum", inputs={"X": [grad, decay]},
                        outputs={"Out": [grad]})
        return grad


class L1Decay(WeightDecayRegularizer):
    def __init__(self, regularization_coeff: float = 0.0):
        self.coeff = regularization_coeff

    def append_regularization_op(self, param, grad, block):
        sign = block.create_var(
            name=f"{param.name}.l1sign", dtype=grad.dtype,
            shape=grad.shape, stop_gradient=True)
        block.append_op(type="sign", inputs={"X": [param]},
                        outputs={"Out": [sign]})
        decay = block.create_var(
            name=f"{param.name}.l1decay", dtype=grad.dtype,
            shape=grad.shape, stop_gradient=True)
        block.append_op(type="scale", inputs={"X": [sign]},
                        outputs={"Out": [decay]},
                        attrs={"scale": self.coeff, "bias": 0.0,
                               "bias_after_scale": True})
        block.append_op(type="sum", inputs={"X": [grad, decay]},
                        outputs={"Out": [grad]})
        return grad


L2DecayRegularizer = L2Decay
L1DecayRegularizer = L1Decay


def append_regularization_ops(params_grads, regularization=None):
    """Apply per-param regularizer (or the optimizer-wide default) to each
    gradient (reference regularizer.py append_regularization_ops)."""
    out = []
    for param, grad in params_grads:
        reg = param.regularizer or regularization
        if reg is not None:
            block = grad.block
            grad = reg.append_regularization_op(param, grad, block) or grad
        out.append((param, grad))
    return out
