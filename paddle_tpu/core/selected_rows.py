"""SparseGrad: the TPU-native SelectedRows gradient.

TPU-native analog of the reference's SelectedRows sparse tensor
(reference: paddle/fluid/framework/selected_rows.h:32 — a rows-index +
value-tensor pair produced by embedding backward and consumed by the
optimizers' sparse update kernels, math/selected_rows_functor.h).

A `lookup_table` op with is_sparse=True makes the Executor differentiate
w.r.t. the *gathered rows* instead of the whole table (core/executor.py),
so the table gradient materializes as (ids, rows) — O(touched rows), not
O(vocab).  Optimizer ops with sparse support (sgd/momentum/adam/adagrad,
ops/optim.py) apply scatter updates to the touched rows only, with
duplicate ids merged by segment-sum exactly like the reference's
MergeAdd functor (math/selected_rows_functor.h MergeAdd).  Any op without
sparse support receives the densified gradient transparently
(run_ops densifies on input).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class SparseGrad:
    """Gradient of an embedding table as touched rows.

    rows: (N, D) float — gradient rows, one per lookup position (ids may
          repeat; scatter-add semantics make that equivalent to the
          summed gradient).
    ids:  (N,) int32 — row indices into the table.
    dense_shape: static (vocab, D) of the full table.
    """

    def __init__(self, ids, rows, dense_shape):
        self.ids = ids
        self.rows = rows
        self.dense_shape = tuple(dense_shape)

    def tree_flatten(self):
        return (self.ids, self.rows), self.dense_shape

    @classmethod
    def tree_unflatten(cls, aux, children):
        ids, rows = children
        return cls(ids, rows, aux)

    def to_dense(self):
        """Scatter-add into a zeros table (what the dense VJP would have
        produced)."""
        table = jnp.zeros(self.dense_shape, dtype=self.rows.dtype)
        return table.at[self.ids].add(self.rows)

    def merged(self):
        """(valid, ids, rows) with duplicate ids summed (reference
        MergeAdd): sorted unique ids; `valid` masks real entries.  Invalid
        slots carry id 0 and zero rows, so add-form scatters are no-ops."""
        order = jnp.argsort(self.ids)
        sid = self.ids[order]
        srows = self.rows[order]
        head = jnp.concatenate(
            [jnp.ones((1,), bool), sid[1:] != sid[:-1]])
        seg = jnp.cumsum(head) - 1
        n = self.ids.shape[0]
        merged_rows = jax.ops.segment_sum(srows, seg, num_segments=n)
        # position of each segment's head in the sorted order
        first_pos = jax.ops.segment_min(jnp.arange(n), seg, num_segments=n)
        valid = jnp.arange(n) < seg[-1] + 1
        merged_ids = jnp.where(valid, sid[jnp.clip(first_pos, 0, n - 1)], 0)
        merged_rows = jnp.where(valid[:, None], merged_rows, 0.0)
        return valid, merged_ids.astype(jnp.int32), merged_rows

    def __repr__(self):
        return (f"SparseGrad(ids={getattr(self.ids, 'shape', None)}, "
                f"rows={getattr(self.rows, 'shape', None)}, "
                f"dense_shape={self.dense_shape})")


def densify(value):
    """Pass arrays through; densify SparseGrads (used by run_ops for ops
    without a sparse kernel — mirrors the reference's
    get_tensor_from_selected_rows op)."""
    if isinstance(value, SparseGrad):
        return value.to_dense()
    return value
