"""Pallas TPU kernels — the custom-kernel tier.

Analog of the reference's hand-written CUDA kernels and JIT codegen tier
(operators/math/*.cu, operators/jit/ xbyak codegen, SURVEY.md §2.2): ops
whose fusion XLA can't do on its own get tiled Pallas implementations.
"""
