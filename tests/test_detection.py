"""Detection op tests vs numpy references + SSD-head smoke test
(reference pattern: test_prior_box_op.py, test_box_coder_op.py,
test_iou_similarity_op.py, test_multiclass_nms_op.py,
test_yolov3_loss_op.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from tests.op_test import run_op


def _iou_np(a, b):
    ix1 = max(a[0], b[0])
    iy1 = max(a[1], b[1])
    ix2 = min(a[2], b[2])
    iy2 = min(a[3], b[3])
    iw = max(ix2 - ix1, 0.0)
    ih = max(iy2 - iy1, 0.0)
    inter = iw * ih
    ua = ((a[2] - a[0]) * (a[3] - a[1])
          + (b[2] - b[0]) * (b[3] - b[1]) - inter)
    return inter / ua if ua > 0 else 0.0


def test_iou_similarity_matches_numpy():
    rng = np.random.RandomState(0)
    # sorting the (2,2) corner pairs elementwise yields valid
    # [x1,y1,x2,y2] boxes directly
    x = np.sort(rng.rand(5, 4).astype(np.float32).reshape(5, 2, 2),
                axis=1).reshape(5, 4)
    y = np.sort(rng.rand(7, 4).astype(np.float32).reshape(7, 2, 2),
                axis=1).reshape(7, 4)
    got = run_op("iou_similarity", {"X": x, "Y": y})
    for i in range(5):
        for j in range(7):
            assert got[i, j] == pytest.approx(_iou_np(x[i], y[j]),
                                              abs=1e-5)


def test_prior_box_reference():
    feat = np.zeros((1, 8, 4, 4), np.float32)
    img = np.zeros((1, 3, 64, 64), np.float32)
    boxes = run_op("prior_box", {"Input": feat, "Image": img},
                   attrs={"min_sizes": [16.0], "max_sizes": [32.0],
                          "aspect_ratios": [2.0], "flip": True,
                          "clip": True, "variances": [0.1, 0.1, 0.2, 0.2]},
                   out_slot="Boxes")
    # priors per cell: ar 1 + ar 2 + ar 0.5 + max-size box = 4
    assert boxes.shape == (4, 4, 4, 4)
    # cell (0,0): center at (0.5*16, 0.5*16) = (8, 8); min_size 16 ar=1
    # box: [0, 0, 16, 16] / 64
    np.testing.assert_allclose(boxes[0, 0, 0], [0.0, 0.0, 0.25, 0.25],
                               atol=1e-6)
    # max-size box sqrt(16*32) = 22.63
    s = np.sqrt(16.0 * 32.0) / 2
    np.testing.assert_allclose(
        boxes[0, 0, 3],
        np.clip([(8 - s) / 64, (8 - s) / 64, (8 + s) / 64, (8 + s) / 64],
                0, 1), atol=1e-5)
    var = run_op("prior_box", {"Input": feat, "Image": img},
                 attrs={"min_sizes": [16.0], "variances": [0.1, 0.1,
                                                           0.2, 0.2]},
                 out_slot="Variances")
    np.testing.assert_allclose(var[0, 0, 0], [0.1, 0.1, 0.2, 0.2])


def test_box_coder_roundtrip():
    rng = np.random.RandomState(1)
    M, N = 6, 3
    prior = np.sort(rng.rand(M, 2, 2),
                    axis=1).reshape(M, 4).astype(np.float32)
    pvar = np.full((M, 4), 0.1, np.float32)
    gt = np.sort(rng.rand(N, 2, 2),
                 axis=1).reshape(N, 4).astype(np.float32)
    enc = run_op("box_coder",
                 {"PriorBox": prior, "PriorBoxVar": pvar, "TargetBox": gt},
                 attrs={"code_type": "encode_center_size"},
                 out_slot="OutputBox")
    assert enc.shape == (N, M, 4)
    dec = run_op("box_coder",
                 {"PriorBox": prior, "PriorBoxVar": pvar,
                  "TargetBox": enc},
                 attrs={"code_type": "decode_center_size"},
                 out_slot="OutputBox")
    # decoding the encoding recovers each gt against every prior
    for n in range(N):
        for m in range(M):
            np.testing.assert_allclose(dec[n, m], gt[n], rtol=1e-4,
                                       atol=1e-5)


def _nms_np(boxes, scores, score_th, nms_th, top_k):
    order = np.argsort(-scores)[:top_k]
    keep = []
    for i in order:
        if scores[i] <= score_th:
            continue
        ok = True
        for j in keep:
            if _iou_np(boxes[i], boxes[j]) > nms_th:
                ok = False
                break
        if ok:
            keep.append(i)
    return keep


def test_multiclass_nms_matches_numpy():
    rng = np.random.RandomState(2)
    N, M, C = 2, 20, 3
    centers = rng.rand(N, M, 2) * 0.8 + 0.1
    sizes = rng.rand(N, M, 2) * 0.2 + 0.05
    bboxes = np.concatenate([centers - sizes / 2, centers + sizes / 2],
                            axis=2).astype(np.float32)
    scores = rng.rand(N, C, M).astype(np.float32)
    attrs = {"background_label": 0, "score_threshold": 0.3,
             "nms_top_k": 10, "nms_threshold": 0.4, "keep_top_k": 8}
    got = run_op("multiclass_nms", {"BBoxes": bboxes, "Scores": scores},
                 attrs=attrs)
    counts = run_op("multiclass_nms",
                    {"BBoxes": bboxes, "Scores": scores}, attrs=attrs,
                    out_slot="NmsRoisNum")
    for n in range(N):
        expect = []
        for c in range(1, C):
            for i in _nms_np(bboxes[n], scores[n, c], 0.3, 0.4, 10):
                expect.append((c, scores[n, c, i], tuple(bboxes[n, i])))
        expect.sort(key=lambda e: -e[1])
        expect = expect[:8]
        assert counts[n] == len(expect)
        for k, (c, s, bx) in enumerate(expect):
            assert int(got[n, k, 0]) == c
            assert got[n, k, 1] == pytest.approx(s, rel=1e-5)
            np.testing.assert_allclose(got[n, k, 2:], bx, rtol=1e-5)
        # padding rows carry -1
        if len(expect) < 8:
            assert (got[n, len(expect):, 0] == -1).all()


def test_yolov3_loss_basics():
    rng = np.random.RandomState(3)
    N, A, K, H, W = 2, 3, 5, 8, 8
    x = (rng.randn(N, A * (5 + K), H, W) * 0.1).astype(np.float32)
    gtbox = np.zeros((N, 4, 4), np.float32)
    gtlabel = np.full((N, 4), -1, np.int64)
    # one real gt per image, sized so its best anchor (16, 30 px at
    # 256 px input) belongs to this head's anchor_mask [0, 1, 2]
    gtbox[:, 0] = [0.5, 0.5, 0.06, 0.1]
    gtlabel[:, 0] = 2
    loss = run_op("yolov3_loss",
                  {"X": x, "GTBox": gtbox, "GTLabel": gtlabel},
                  attrs={"anchors": [10, 13, 16, 30, 33, 23, 30, 61,
                                     62, 45, 59, 119],
                         "anchor_mask": [0, 1, 2], "class_num": K,
                         "ignore_thresh": 0.7, "downsample_ratio": 32},
                  out_slot="Loss")
    assert loss.shape == (N,)
    assert (loss > 0).all() and np.isfinite(loss).all()
    # an image with NO gt only pays the no-objectness cost, so its loss
    # must be strictly smaller
    gtlabel2 = np.full((N, 4), -1, np.int64)
    loss2 = run_op("yolov3_loss",
                   {"X": x, "GTBox": gtbox, "GTLabel": gtlabel2},
                   attrs={"anchors": [10, 13, 16, 30, 33, 23, 30, 61,
                                      62, 45, 59, 119],
                          "anchor_mask": [0, 1, 2], "class_num": K,
                          "ignore_thresh": 0.7, "downsample_ratio": 32},
                   out_slot="Loss")
    assert (loss2 < loss).all()


def test_yolov3_trains():
    """A one-head YOLO toy model must reduce its loss."""
    N, A, K, H, W = 2, 3, 4, 4, 4
    rng = np.random.RandomState(4)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        feat = layers.data("feat", shape=[N, 8, H, W],
                           append_batch_size=False)
        gtb = layers.data("gtb", shape=[N, 2, 4], append_batch_size=False)
        gtl = layers.data("gtl", shape=[N, 2], dtype="int64",
                          append_batch_size=False)
        head = layers.conv2d(feat, num_filters=A * (5 + K), filter_size=1)
        loss_v = layers.detection.yolov3_loss(
            head, gtb, gtl, anchors=[10, 13, 16, 30, 33, 23],
            anchor_mask=[0, 1, 2], class_num=K, ignore_thresh=0.7,
            downsample_ratio=32)
        loss = layers.reduce_mean(loss_v)
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        feed = {
            "feat": rng.randn(N, 8, H, W).astype(np.float32),
            "gtb": np.tile(np.array([[0.4, 0.6, 0.2, 0.3],
                                     [0.7, 0.3, 0.1, 0.2]],
                                    np.float32), (N, 1, 1)),
            "gtl": np.tile(np.array([1, 3], np.int64), (N, 1)),
        }
        losses = [float(exe.run(main, feed=feed,
                                fetch_list=[loss])[0].reshape(()))
                  for _ in range(25)]
    assert losses[-1] < losses[0] * 0.8
    assert np.isfinite(losses).all()


def test_ssd_head_smoke():
    """SSD head: priors from a feature map + ssd_loss trains."""
    P = 16  # 4x4 cell grid, 1 prior per cell
    rng = np.random.RandomState(5)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        feat = layers.data("feat", shape=[1, 8, 4, 4],
                           append_batch_size=False)
        img = layers.data("img", shape=[1, 3, 64, 64],
                          append_batch_size=False)
        priors, _pvar = layers.detection.prior_box(
            feat, img, min_sizes=[24.0], clip=True)
        priors2d = layers.reshape(priors, [P, 4])
        loc = layers.data("loc", shape=[P, 4], append_batch_size=False)
        conf = layers.data("conf", shape=[P, 3], append_batch_size=False)
        gtb = layers.data("gtb", shape=[2, 4], append_batch_size=False)
        gtl = layers.data("gtl", shape=[2, 1], dtype="int64",
                          append_batch_size=False)
        # low threshold so some priors match (with zero positives the
        # negative-balanced conf loss is correctly 0, like the
        # reference's ratio-limited hard negative mining)
        loss = layers.detection.ssd_loss(loc, conf, gtb, gtl, priors2d,
                                         overlap_threshold=0.1)
    exe = fluid.Executor()
    feed = {
        "feat": np.zeros((1, 8, 4, 4), np.float32),
        "img": np.zeros((1, 3, 64, 64), np.float32),
        "loc": rng.randn(P, 4).astype(np.float32) * 0.1,
        "conf": rng.randn(P, 3).astype(np.float32),
        "gtb": np.array([[0.1, 0.1, 0.4, 0.4],
                         [0.5, 0.5, 0.9, 0.9]], np.float32),
        "gtl": np.array([[1], [2]], np.int64),
    }
    (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
    assert np.isfinite(lv).all() and lv.reshape(-1)[0] > 0


# ---------------------------------------------------------------------------
# extended detection set
# ---------------------------------------------------------------------------

def test_anchor_generator_reference_cell():
    feat = np.zeros((1, 8, 2, 2), np.float32)
    anchors = run_op("anchor_generator", {"Input": feat},
                     attrs={"anchor_sizes": [32.0], "aspect_ratios": [1.0],
                            "stride": [16.0, 16.0]},
                     out_slot="Anchors")
    assert anchors.shape == (2, 2, 1, 4)
    # reference anchor_generator_op.h values: stride 16, size 32, ratio
    # 1 → base_w = round(sqrt(256)) = 16 scaled ×2 = 32, center
    # 0.5*(16-1) = 7.5 → [-8, -8, 23, 23]
    np.testing.assert_allclose(anchors[0, 0, 0],
                               [-8.0, -8.0, 23.0, 23.0], atol=1e-5)
    # ratio 2: base_w = round(sqrt(256/2)) = 11, base_h = 22, ×2 → 22×44
    a2 = run_op("anchor_generator", {"Input": feat},
                attrs={"anchor_sizes": [32.0], "aspect_ratios": [2.0],
                       "stride": [16.0, 16.0]}, out_slot="Anchors")
    w = a2[0, 0, 0, 2] - a2[0, 0, 0, 0] + 1
    h = a2[0, 0, 0, 3] - a2[0, 0, 0, 1] + 1
    assert (w, h) == (22.0, 44.0)


def test_density_prior_box_counts():
    feat = np.zeros((1, 8, 2, 2), np.float32)
    img = np.zeros((1, 3, 32, 32), np.float32)
    boxes = run_op("density_prior_box", {"Input": feat, "Image": img},
                   attrs={"densities": [2], "fixed_sizes": [8.0],
                          "fixed_ratios": [1.0]}, out_slot="Boxes")
    # density 2 → 4 shifted priors per cell per ratio
    assert boxes.shape == (2, 2, 4, 4)
    # all boxes are 8x8 in a 32px image → 0.25 normalized
    sz = boxes[..., 2] - boxes[..., 0]
    interior = sz[sz > 0.24]
    np.testing.assert_allclose(interior, 0.25, rtol=1e-5)


def test_box_clip():
    boxes = np.array([[[-5.0, -3.0, 50.0, 20.0]]], np.float32)
    im_info = np.array([[30.0, 40.0, 1.0]], np.float32)
    got = run_op("box_clip", {"Input": boxes, "ImInfo": im_info},
                 out_slot="Output")
    np.testing.assert_allclose(got[0, 0], [0, 0, 39, 20])


def test_bipartite_match_greedy():
    # classic greedy: global max first, rows/cols retired
    dist = np.array([[0.6, 0.9, 0.2],
                     [0.8, 0.7, 0.1]], np.float32)
    idx = run_op("bipartite_match", {"DistMat": dist},
                 out_slot="ColToRowMatchIndices")
    d = run_op("bipartite_match", {"DistMat": dist},
               out_slot="ColToRowMatchDist")
    # best 0.9 → (row0, col1); then 0.8 → (row1, col0); col2 unmatched
    np.testing.assert_array_equal(idx[0], [1, 0, -1])
    np.testing.assert_allclose(d[0], [0.8, 0.9, 0.0], rtol=1e-6)
    # per_prediction: col2's best row (row0 @0.2) below threshold stays
    # unmatched; with threshold 0.1 it matches
    idx2 = run_op("bipartite_match", {"DistMat": dist},
                  attrs={"match_type": "per_prediction",
                         "dist_threshold": 0.15},
                  out_slot="ColToRowMatchIndices")
    np.testing.assert_array_equal(idx2[0], [1, 0, 0])


def test_target_assign():
    x = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    match = np.array([[1, -1, 0]], np.int32)
    got = run_op("target_assign", {"X": x, "MatchIndices": match},
                 attrs={"mismatch_value": -7})
    wt = run_op("target_assign", {"X": x, "MatchIndices": match},
                attrs={"mismatch_value": -7}, out_slot="OutWeight")
    np.testing.assert_allclose(got, [[3, 4], [-7, -7], [1, 2]])
    np.testing.assert_allclose(wt[:, 0], [1, 0, 1])


def test_generate_proposals_smoke():
    rng = np.random.RandomState(6)
    N, A, H, W = 1, 3, 4, 4
    scores = rng.rand(N, A, H, W).astype(np.float32)
    deltas = (rng.randn(N, 4 * A, H, W) * 0.1).astype(np.float32)
    im_info = np.array([[64.0, 64.0, 1.0]], np.float32)
    anchors = run_op("anchor_generator",
                     {"Input": np.zeros((N, 8, H, W), np.float32)},
                     attrs={"anchor_sizes": [16.0, 32.0],
                            "aspect_ratios": [1.0, 2.0],
                            "stride": [16.0, 16.0]}, out_slot="Anchors")
    variances = np.full(anchors.shape, 1.0, np.float32)
    post_n = 8
    rois = run_op("generate_proposals",
                  {"Scores": scores,
                   "BboxDeltas": deltas,
                   "ImInfo": im_info,
                   "Anchors": anchors[..., :A, :],
                   "Variances": variances[..., :A, :]},
                  attrs={"pre_nms_topN": 20, "post_nms_topN": post_n,
                         "nms_thresh": 0.7, "min_size": 1.0},
                  out_slot="RpnRois")
    counts = run_op("generate_proposals",
                    {"Scores": scores, "BboxDeltas": deltas,
                     "ImInfo": im_info, "Anchors": anchors[..., :A, :],
                     "Variances": variances[..., :A, :]},
                    attrs={"pre_nms_topN": 20, "post_nms_topN": post_n,
                           "nms_thresh": 0.7, "min_size": 1.0},
                    out_slot="RpnRoisNum")
    assert rois.shape == (N, post_n, 4)
    n_valid = int(counts[0])
    assert 1 <= n_valid <= post_n
    v = rois[0, :n_valid]
    # valid rois are inside the image and non-degenerate
    assert (v[:, 0] >= 0).all() and (v[:, 2] <= 63).all()
    assert (v[:, 2] > v[:, 0]).all() and (v[:, 3] > v[:, 1]).all()
    # padding is zeros
    np.testing.assert_allclose(rois[0, n_valid:], 0.0)


# ---------------------------------------------------------------------------
# detection_map (reference: operators/detection_map_op.h CalcTrueAndFalse
# Positive + CalcMAP — hand-computed parity cases)
# ---------------------------------------------------------------------------

def _dm(det, gt, **attrs):
    a = dict(class_num=2, background_label=0, overlap_threshold=0.5,
             evaluate_difficult=True, ap_type="integral")
    a.update(attrs)
    return float(run_op("detection_map",
                        {"DetectRes": np.asarray(det, np.float32),
                         "Label": np.asarray(gt, np.float32)},
                        attrs=a, out_slot="MAP"))


def test_detection_map_visited_gt_is_fp():
    """A detection whose max-overlap gt was already claimed by a
    higher-scored det is an FP — it does NOT fall through to the
    next-best gt (detection_map_op.h:393-404 assigns argmax regardless
    of visited state).  det2's argmax is gt A (IoU .68 > .47 for B);
    A is visited, so FP even though B clears the threshold."""
    det = [[[1, 0.9, 0.00, 0.00, 0.50, 0.50],    # TP on A (IoU 1.0)
            [1, 0.8, 0.05, 0.05, 0.55, 0.55]]]   # argmax A -> visited FP
    gt = [[[1, 0.00, 0.00, 0.50, 0.50, 0],       # A
           [1, 0.15, 0.15, 0.65, 0.65, 0]]]      # B
    # npos=2; sorted [TP, FP]: integral AP = 1.0 * (1/2) = 0.5
    np.testing.assert_allclose(_dm(det, gt, overlap_threshold=0.4), 0.5,
                               atol=1e-6)


def test_detection_map_difficult_gt_ignored():
    """evaluate_difficult=False: a det matching a difficult gt counts
    neither tp nor fp, and the gt is excluded from npos."""
    det = [[[1, 0.9, 0.00, 0.00, 0.50, 0.50],    # matches difficult A
            [1, 0.8, 0.60, 0.60, 0.90, 0.90]]]   # TP on B
    gt = [[[1, 0.00, 0.00, 0.50, 0.50, 1],       # A difficult
           [1, 0.62, 0.62, 0.88, 0.88, 0]]]      # B
    # npos=1 (B); only det2 recorded: TP -> AP = 1.0
    np.testing.assert_allclose(
        _dm(det, gt, evaluate_difficult=False), 1.0, atol=1e-6)
    # with evaluate_difficult=True both count: 2 TPs, npos=2 -> 1.0
    np.testing.assert_allclose(
        _dm(det, gt, evaluate_difficult=True), 1.0, atol=1e-6)


def test_detection_map_strict_threshold_and_clip():
    """IoU exactly == threshold is NOT a match (strict >); detection
    boxes clip to [0,1] before IoU like the reference's ClipBBox."""
    det = [[[1, 0.9, 0.0, 0.0, 0.5, 1.0]]]
    gt = [[[1, 0.0, 0.0, 1.0, 1.0, 0]]]          # IoU = 0.5 exactly
    assert _dm(det, gt, overlap_threshold=0.5) == 0.0
    # det spills outside the frame: clipped to [0,1] it IS the gt box
    det2 = [[[1, 0.9, -0.5, -0.5, 1.5, 1.5]]]
    np.testing.assert_allclose(
        _dm(det2, gt, overlap_threshold=0.5), 1.0, atol=1e-6)


def test_detection_map_11point():
    """11-point AP: TP then FP with npos=2 -> recall tops at 0.5, max
    precision 1.0 for the 6 points r<=0.5, 0 beyond -> 6/11."""
    det = [[[1, 0.9, 0.00, 0.00, 0.50, 0.50],
            [1, 0.8, 0.05, 0.05, 0.55, 0.55]]]
    gt = [[[1, 0.00, 0.00, 0.50, 0.50, 0],
           [1, 0.15, 0.15, 0.65, 0.65, 0]]]
    np.testing.assert_allclose(
        _dm(det, gt, overlap_threshold=0.4, ap_type="11point"), 6 / 11,
        atol=1e-6)


def test_detection_map_layer_in_program():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        d = layers.data("d", shape=[1, 2, 6], append_batch_size=False)
        g = layers.data("g", shape=[1, 2, 6], append_batch_size=False)
        m = layers.detection.detection_map(d, g, class_num=2,
                                           overlap_threshold=0.4)
        exe = fluid.Executor()
        exe.run(startup)
        det = np.array([[[1, 0.9, 0.0, 0.0, 0.5, 0.5],
                         [1, 0.8, 0.6, 0.6, 0.9, 0.9]]], np.float32)
        gt = np.array([[[1, 0.05, 0.05, 0.45, 0.45, 0],
                        [1, 0.62, 0.62, 0.88, 0.88, 0]]], np.float32)
        (v,) = exe.run(main, feed={"d": det, "g": gt}, fetch_list=[m])
    np.testing.assert_allclose(np.asarray(v), 1.0, atol=1e-6)
