"""Gang worker for the multi-process fault-tolerance chaos harness
(tests/test_gang.py and the run_ci.sh gang-chaos smoke): one rank of a
REAL supervised training gang.

Launched by `resilience.Supervisor` (or tools/launch_gang.py), so it
reads its identity from the PADDLE_TRAINER_ID / PADDLE_TRAINERS /
PADDLE_COORDINATOR env contract via `parallel.init_distributed()` —
which also auto-registers the distributed HEALTH PLANE (heartbeats +
peer-loss monitor + poison key) on the KV store.  Each rank trains its
own single-device model (KV-store-only gang, NO cross-process XLA —
the container jax has no CPU collectives; same constraint as
tests/test_dist.py's dead-peer test), but the health plane, the
checkpoint-save barriers, and the supervisor protocol are the real
multi-process articles.

Protocol:
- "STEP <epoch> <step>" after every completed step,
- chaos is env-armed (`chaos.kill_rank` / `chaos.hang_rank` with a
  once-file so a relaunched gang does not re-fire),
- on a GangError (peer lost / stalled / poisoned) or a poisoned
  checkpoint barrier: print "PEER_LOST <json>" (detection latency
  attached) and exit `PEER_LOST_EXIT_CODE`,
- on SIGTERM: the Trainer drain path exits `PREEMPT_EXIT_CODE`,
- on clean completion: final persistables land in
  `<out-root>/rank<k>.npz`, the goodput ledger report (observe pillar
  8) in `<out-root>/rank<k>.goodput.json`, and the worker prints
  "DONE" — unless the done-rendezvous finds the gang broken (a peer
  died AFTER this rank finished), in which case the same structured
  "PEER_LOST <json>" + `PEER_LOST_EXIT_CODE` exit as the mid-train
  path, so the supervisor classifies the attempt correctly.

mode=barrier_poison: rank 1 writes the poison key and dies; rank 0
enters a sharded-save barrier and must get a
CheckpointBarrierPoisonedError in bounded time (seconds, not the
600 s barrier timeout) — printed as "BARRIER_POISONED <json>".
"""

import argparse
import json
import os
import sys
import time

# Script-mode env pins: one CPU device per rank; the platform pin must
# go through jax.config (sitecustomize imports jax before this script
# runs — same workaround as tests/dist_worker.py).
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import layers, observe  # noqa: E402
from paddle_tpu.contrib import CheckpointConfig, Trainer  # noqa: E402
from paddle_tpu.contrib.trainer import EndStepEvent  # noqa: E402
from paddle_tpu.data import decorator  # noqa: E402
from paddle_tpu.parallel import init_distributed  # noqa: E402
from paddle_tpu.resilience import (PEER_LOST_EXIT_CODE,  # noqa: E402
                                   CheckpointBarrierPoisonedError,
                                   GangError, TrainingPreempted, chaos,
                                   health)

BATCHES_PER_EPOCH = 12
BATCH = 8


def train_func():
    x = layers.data(name="x", shape=[6], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    h = layers.fc(x, size=16, act="relu")
    h = layers.dropout(h, dropout_prob=0.3)
    pred = layers.fc(h, size=1)
    return layers.mean(layers.square_error_cost(pred, y))


def opt_func():
    return fluid.optimizer.Adam(learning_rate=0.01)


def make_reader(rank):
    def base():
        # per-rank deterministic stream (seed differs by rank so the
        # two models' trajectories are distinct artifacts)
        r = np.random.RandomState(11 + rank)
        for _ in range(BATCHES_PER_EPOCH):
            yield {"x": r.rand(BATCH, 6).astype(np.float32),
                   "y": r.rand(BATCH, 1).astype(np.float32)}

    return decorator.shuffle(base, 4, seed=29 + rank)


def run_barrier_poison(rank, ckpt_root):
    """Deterministic bounded-barrier proof: rank 0 is already WAITING
    inside a checkpoint barrier when rank 1 writes the poison key and
    dies abruptly — the barrier must abort with a structured
    CheckpointBarrierPoisonedError within the ~1 s poison-poll cadence,
    never after the full (here 120 s) timeout.  (A per-rank LOCAL save
    skips barriers by design, so the barrier is driven directly — it is
    exactly what a gang-wide sharded save calls.)"""
    del ckpt_root
    kv = health.kv_client()
    assert kv is not None
    if rank == 1:
        time.sleep(1.5)  # rank 0 is inside the barrier by now
        health.write_poison(kv, rank=1,
                            reason="chaos: deliberate gang abort",
                            kind="manual", missing_ranks=[1])
        sys.stdout.flush()
        os._exit(7)  # abrupt: no barrier arrival, no cleanup
    t0 = time.monotonic()
    try:
        fluid.io._barrier("gang_test:poisoned", timeout_s=120.0)
        print("BARRIER_UNEXPECTED_OK", flush=True)
        os._exit(1)
    except CheckpointBarrierPoisonedError as e:
        payload = e.as_dict()
        payload["elapsed_wall_s"] = round(time.monotonic() - t0, 3)
        print("BARRIER_POISONED " + json.dumps(payload), flush=True)
    os._exit(0)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt-root", required=True)
    ap.add_argument("--out-root", required=True)
    ap.add_argument("--log-root", required=True)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--step-interval", type=int, default=3)
    ap.add_argument("--pace-s", type=float, default=0.12,
                    help="sleep per step so detection can land mid-train")
    ap.add_argument("--mode", default="train",
                    choices=["train", "barrier_poison"])
    args = ap.parse_args()

    rank, nranks = init_distributed()  # env contract + health plane
    assert jax.process_count() == nranks, jax.process_count()
    # multiprocess runtime: jax.devices()[0] is rank 0's device — pin
    # computation to THIS process's device (the gang is KV-only)
    jax.config.update("jax_default_device", jax.local_devices()[0])
    plane = health.get_health_plane()
    assert plane is not None, "init_distributed did not register health"

    if args.mode == "barrier_poison":
        run_barrier_poison(rank, args.ckpt_root)
        return

    trainer = Trainer(
        train_func, opt_func,
        checkpoint_config=CheckpointConfig(
            os.path.join(args.ckpt_root, f"rank{rank}"),
            step_interval=args.step_interval,
            epoch_interval=10 ** 6, max_num_checkpoints=4),
        telemetry=observe.TelemetryConfig(
            interval=100,
            log_path=os.path.join(args.log_root, f"rank{rank}.jsonl")),
        preempt_drain=True)

    def handler(event):
        if isinstance(event, EndStepEvent):
            gpos = event.epoch * BATCHES_PER_EPOCH + event.step
            print(f"STEP {event.epoch} {event.step}", flush=True)
            chaos.kill_rank(rank, gpos)
            chaos.hang_rank(rank, gpos)
            if args.pace_s > 0:
                time.sleep(args.pace_s)

    t0 = time.monotonic()
    try:
        trainer.train(num_epochs=args.epochs,
                      reader=make_reader(rank), event_handler=handler)
    except TrainingPreempted as e:
        print("PREEMPTED " + json.dumps(e.as_dict()), flush=True)
        os._exit(e.exit_code)
    except (GangError, CheckpointBarrierPoisonedError) as e:
        payload = e.as_dict()
        payload["detected_at_train_s"] = round(time.monotonic() - t0, 3)
        payload["rank"] = rank
        print("PEER_LOST " + json.dumps(payload), flush=True)
        # os._exit: jax.distributed teardown would hang on dead peers
        os._exit(PEER_LOST_EXIT_CODE)
    params = {v.name: np.asarray(trainer.scope.find_var(v.name))
              for v in trainer.train_program.list_vars()
              if v.persistable}
    os.makedirs(args.out_root, exist_ok=True)
    np.savez(os.path.join(args.out_root, f"rank{rank}.npz"), **params)
    def dump_goodput():
        # pillar-8 artifact: this process's wall-clock decomposition
        # (instrumented waits land via the attached ledger); a
        # relaunched rank's report carries the restart-replay badput
        # the chaos test asserts on
        with open(os.path.join(args.out_root,
                               f"rank{rank}.goodput.json"), "w") as f:
            json.dump(trainer.goodput(), f)

    # orderly leave: announce done and wait for the laggards so a
    # finished rank's silence is never mistaken for death (resumed
    # ranks run different numbers of remaining steps)
    plane.leave()
    if not plane.wait_gang_done(timeout_s=60.0):
        # the gang broke while we waited for the laggards (a peer died
        # after we finished — ranks drift apart, so a mid-train kill
        # for the victim can be post-train for us): surface the SAME
        # structured detection the mid-train path prints, so the
        # supervisor classifies the attempt as peer_lost, not a bare
        # crash.  A plain done-wait timeout still falls through to
        # DONE — our own work is complete either way.
        try:
            plane.check()
        except (GangError, CheckpointBarrierPoisonedError) as e:
            payload = e.as_dict()
            payload["detected_at_train_s"] = round(
                time.monotonic() - t0, 3)
            payload["rank"] = rank
            payload["at"] = "done_wait"
            dump_goodput()
            print("PEER_LOST " + json.dumps(payload), flush=True)
            os._exit(PEER_LOST_EXIT_CODE)
    dump_goodput()
    print("DONE", flush=True)
    sys.stdout.flush()
    os._exit(0)  # skip distributed teardown (peer may already be gone)


if __name__ == "__main__":
    main()
