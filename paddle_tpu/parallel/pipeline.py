"""Pipeline parallelism: a GPipe microbatch scheduler over a mesh axis.

The 1.2 reference predates pipeline parallelism (Paddle's
PipelineOptimizer landed later); pp is first-class on TPU pods, so the
primitive lives here alongside dp/tp/fsdp/sp/ep.  TPU-first design:
stages are S copies of one stage function whose stacked parameters
(leading dim S) shard over the mesh's `pp` axis; the schedule is a
`lax.scan` over T = n_micro + S - 1 ticks inside `shard_map`, with
`lax.ppermute` handing each microbatch's activation to the next stage
every tick — the classic GPipe wavefront (bubble fraction
(S-1)/(n_micro + S - 1); raise n_micro to amortize).  Reverse-mode AD
flows through ppermute/scan (ppermute transposes to the reverse
permutation), so `jax.grad` of a loss on the pipeline output yields
per-stage parameter gradients without any hand-written backward
schedule.

Constraints (documented, enforced):
- every stage maps activations of one fixed shape to the same shape
  (transformer-block pipelines satisfy this; embed/head layers run
  outside the pipelined region),
- stage_params is a pytree whose every leaf has leading dim S.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def gpipe(stage_fn, mesh, axis: str = "pp"):
    """Build a pipelined apply: `fn(stacked_params, micro_x) -> out`.

    stage_fn(params_s, x) -> y with y.shape == x.shape;
    stacked_params: pytree, leaves (S, ...) — stage s uses leaf[s];
    micro_x: (n_micro, B_micro, ...) microbatched input.
    Returns out (n_micro, B_micro, ...) = stage_{S-1}(...stage_0(x)).
    """
    import inspect

    try:
        from jax import shard_map as _sm
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map as _sm
    # jax 0.8 renamed check_rep -> check_vma
    _kw = ("check_vma" if "check_vma" in
           inspect.signature(_sm).parameters else "check_rep")

    def shard_map(f, **kwargs):
        kwargs[_kw] = kwargs.pop("check_rep")
        return _sm(f, **kwargs)

    from jax.sharding import PartitionSpec as P

    s = mesh.shape[axis]
    perm = [(i, i + 1) for i in range(s - 1)]

    def pipelined(stacked_params, micro_x):
        n_micro = micro_x.shape[0]
        ticks = n_micro + s - 1

        @partial(
            shard_map, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P(axis), stacked_params),
                      P()),
            out_specs=P(),
            check_rep=False)
        def run(params, xs):
            # inside: params leaves are (1, ...) — this device's stage
            params = jax.tree.map(lambda l: l[0], params)
            rank = lax.axis_index(axis)
            zero = jnp.zeros_like(xs[0])

            def tick(buf_in, t):
                mb = t - rank
                active = (mb >= 0) & (mb < n_micro)
                # stage 0 pulls its microbatch; others take the buffer
                x_in = jnp.where(
                    rank == 0,
                    xs[jnp.clip(t, 0, n_micro - 1)], buf_in)
                y = stage_fn(params, x_in)
                y = jnp.where(active, y, zero)
                handoff = lax.ppermute(y, axis, perm)
                return handoff, y

            _, ys = lax.scan(tick, zero, jnp.arange(ticks))
            # microbatch m leaves the last stage at tick m + (S-1):
            # ys[s-1:] on the last rank is the pipeline output
            outs = lax.dynamic_slice_in_dim(ys, s - 1, n_micro, 0)
            # broadcast the last stage's result to every pp rank so the
            # out_spec P() (replicated) is truthful
            last = jnp.zeros((), outs.dtype) + (rank == s - 1)
            outs = lax.psum(outs * last.astype(outs.dtype), axis)
            return outs

        return run(stacked_params, micro_x)

    return pipelined


def gpipe_loss_and_grad(stage_fn, loss_fn, mesh, axis: str = "pp"):
    """Convenience: (stacked_params, micro_x, micro_y) ->
    (mean loss, grads w.r.t. stacked_params) through the pipeline."""
    fwd = gpipe(stage_fn, mesh, axis)

    def loss(params, micro_x, micro_y):
        out = fwd(params, micro_x)
        return jnp.mean(jax.vmap(loss_fn)(out, micro_y))

    return jax.value_and_grad(loss)
