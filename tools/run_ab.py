"""A/B benchmark driver (VERDICT r3 item 1b): run bench.py once per
perf-feature configuration on the real chip and write a combined
AB artifact with the winners, so every bench default reflects a
measured win.

Usage: python tools/run_ab.py [--steps N] [--out AB_r12.json]
Each variant is a separate bench.py subprocess (fresh backend, no cache
cross-talk); the probe inside bench.py keeps a dead backend from
burning the timeout.  r11: every pair's summary carries goodput
context (`<name>_goodput` — each side's harness-wall step fraction +
effective_mfu, observe pillar 8) so a throughput verdict bought with
badput is visible in the artifact itself.  r12: the speculative-decode
pair (`decode_spec_k4`, ISSUE 20) compares same-stream twins measured
INSIDE one variant entry — bench --speculate runs the sequential twin
itself, asserts token parity, and records both tokens/s.

r06 added the scan-bound lstm variants (unroll sweep + the Pallas fused
recurrence kernel vs the scan base).  r08 adds the dp-mesh pair
(ISSUE 10): dp8_bf16 (implicit GSPMD gradient all-reduce) vs
dp8_int8ar (EQuARX blockwise-int8 quantized exchange, --grad-sync
int8), with per-pair comm_bytes context in the summary — on the 8-CPU
virtual mesh the pair records correctness + comm-byte deltas; the
grad-sync default only flips on a chip throughput win.  r10 adds the
hybrid-parallel ladder (ISSUE 13): fsdp2/4/8 (ZeRO-sharded optimizer
state — the summary's fsdp_opt_state_scaling records the per-device
opt-state byte drop vs dp8) and the composed dp2mp2 pair (Megatron mp
sharding × dp, int8 riding the psum-form exchange).  The fsdp claim
is MEMORY; throughput decides defaults, device-tagged as always.  r07 added the
head-major layout
variants (ISSUE 8): transformer_headmajor / transformer_pallas_headmajor
record the layout at the short-seq headline shape — the latter is the
r05 pallas-attn crossover question (136.7k vs 157.1k tok/s at len256:
does deleting the boundary transposes flip it?) — and
longctx_8k_headmajor is the headline lever (the r05 profile's ~15.9 s
of copy/transpose).  Every transformer/longctx entry now carries
`layout_share` so the summary's throughput verdicts come with the
layout-traffic delta attached.  Entries recorded off-chip carry
their producing backend in each entry's `device` field — a
CPU-recorded win ("cpu (assumed v5e peak)") documents the harness but
does NOT flip a TPU bench default.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

VARIANTS = [
    # (key, argv fragment)
    ("resnet50_nchw", ["--model", "resnet50", "--layout", "NCHW"]),
    ("resnet50_nhwc", ["--model", "resnet50", "--layout", "NHWC"]),
    # flags are explicit on both sides so the variant set stays
    # meaningful if a default ever flips.  NOTE the r05 lesson baked
    # into wins(): fused-CE's higher MFU at len256 was a NUMERATOR
    # artifact (dense-equivalent twin vs the base program's own XLA
    # count) while wall-clock lost — wins() therefore compares
    # throughput, which is numerator-free.
    ("transformer_base", ["--model", "transformer", "--no-fused-ce"]),
    ("transformer_fused_ce", ["--model", "transformer", "--fused-ce"]),
    ("transformer_fused_qkv", ["--model", "transformer", "--fused-qkv",
                               "--no-fused-ce"]),
    ("transformer_fused_both", ["--model", "transformer", "--fused-ce",
                                "--fused-qkv"]),
    ("transformer_pallas_attn", ["--model", "transformer",
                                 "--pallas-attn", "--no-fused-ce"]),
    # head-major layouts (ISSUE 8): activations stay in the flash
    # kernels' head-grouped convention end-to-end — zero transposes at
    # kernel boundaries.  NOTE head-major also routes decoder CROSS
    # attention through the flash op (the composed path would
    # reintroduce the transposes), recorded in each entry's
    # head_major/flash fields.
    ("transformer_headmajor", ["--model", "transformer",
                               "--head-major", "--no-fused-ce"]),
    # the r05 short-seq crossover question: pallas-attn lost 136.7k vs
    # 157.1k tok/s at len256 with the transpose round-trip; this is the
    # same kernel with the round-trip deleted
    ("transformer_pallas_headmajor", ["--model", "transformer",
                                      "--pallas-attn", "--head-major",
                                      "--no-fused-ce"]),
    # long-context (VERDICT r4 item 7): Pallas flash (self+cross) +
    # fused-CE + recompute is the default longctx stack; the xla twin
    # runs the same shape through the XLA flash composition to check
    # the kernel actually pays at 8k
    ("longctx_8k_pallas", ["--model", "longctx"]),
    # the XLA flash composition CANNOT fit 8k without remat (r05 chip:
    # 38.45G HBM needed, jax AD keeps per-layer attention residuals the
    # Pallas kernel's custom VJP recomputes from lse) — so the xla side
    # runs its best VIABLE config (with recompute); the pallas side
    # runs its own best (without).  Backend-best vs backend-best.
    ("longctx_8k_xla", ["--model", "longctx", "--xla-attn",
                        "--recompute"]),
    # the longctx default flipped to no-recompute after this A/B
    # measured 0.3035 vs 0.2405 (bs2/8k fits without remat); the
    # recompute variant stays recorded for the memory-constrained case
    ("longctx_8k_recompute", ["--model", "longctx", "--recompute"]),
    # head-major longctx: THE identified r05 lever — the recorded
    # device profile showed ~15.9 s copy/transpose in-flight against
    # ~5.0 s flash-kernel time; head-major deletes that traffic class
    ("longctx_8k_headmajor", ["--model", "longctx", "--head-major"]),
    # shape probes (r05 chip session): both LOSE to the defaults
    # (bs4 longctx 0.2322 vs 0.2405; bs128 transformer 0.3046 vs
    # 0.3254 — bs64/len256 confirmed as the sweet spot)
    ("longctx_8k_bs4", ["--model", "longctx", "--batch", "4"]),
    ("transformer_bs128", ["--model", "transformer", "--batch", "128"]),
    # scaling proof: 16k tokens on ONE chip, MFU RISES with T (flash
    # fraction grows; dense attention stopped existing back at 8k)
    ("longctx_16k_bs1", ["--model", "longctx", "--seq", "16384",
                         "--batch", "1"]),
    # scan-bound lstm (ISSUE 5): the r05 outlier at 0.078 MFU.  The
    # unroll sweep is the cheap XLA-side lever (bit-identical
    # numerics); pallas_rnn is the fused recurrence kernel.  wins()
    # compares tokens/sec as everywhere — lstm MFU numerators are NOT
    # comparable across these variants (scan entries count loop bodies
    # once, pallas entries use the kernel registry).
    ("lstm_base", ["--model", "lstm"]),
    ("lstm_unroll2", ["--model", "lstm", "--rnn-unroll", "2"]),
    ("lstm_unroll4", ["--model", "lstm", "--rnn-unroll", "4"]),
    ("lstm_unroll8", ["--model", "lstm", "--rnn-unroll", "8"]),
    ("lstm_pallas_rnn", ["--model", "lstm", "--pallas-rnn"]),
    # dp-mesh gradient exchange (ISSUE 10, docs/DIST.md): the bf16 side
    # is the default implicit GSPMD all-reduce, the int8 side the
    # EQuARX blockwise-quantized two-phase exchange.  On the 8-CPU
    # virtual mesh this pair records CORRECTNESS + the comm-bytes delta
    # (each entry carries comm_bytes from the sharded step's comm
    # bucket); the wall-clock verdict that could flip the --grad-sync
    # default needs a real multi-chip slice, per the device-tag rule.
    ("dp8_bf16", ["--model", "transformer", "--mesh", "dp=8"]),
    ("dp8_int8ar", ["--model", "transformer", "--mesh", "dp=8",
                    "--grad-sync", "int8"]),
    # r10 (ISSUE 13): the fsdp/ZeRO ladder — same data-parallel math
    # as dp=N (loss parity pinned in tests/test_hybrid_parallel.py)
    # with optimizer state sharded ~1/N per device.  The A/B claim is
    # MEMORY (each entry's opt_state_bytes_per_device, summarized as
    # fsdp_opt_state_scaling); throughput decides defaults as
    # everywhere, per the device-tag rule.
    ("fsdp2", ["--model", "transformer", "--mesh", "fsdp=2"]),
    ("fsdp4", ["--model", "transformer", "--mesh", "fsdp=4"]),
    ("fsdp8", ["--model", "transformer", "--mesh", "fsdp=8"]),
    # the composed dp×mp mesh record: Megatron-sharded params + data
    # parallelism in ONE entry (keyed transformer_dp2mp2), with the
    # int8 exchange riding the psum-form on the composed mesh
    ("dp2mp2", ["--model", "transformer", "--mesh", "dp=2,mp=2"]),
    ("dp2mp2_int8ar", ["--model", "transformer", "--mesh", "dp=2,mp=2",
                       "--grad-sync", "int8"]),
    # r09: the paged-KV decode cache precision pair (ISSUE 12 stretch).
    # int8 pools halve KV bytes vs bf16 (per-row f32 scale sidecars,
    # the blockwise scheme of parallel/collectives.py) — whether that
    # converts to tokens/s depends on whether decode attention is
    # pool-bandwidth-bound at the benched geometry.  wins() compares
    # the decode entry's tokens_per_sec as everywhere; the kv default
    # stays bf16 pending a chip wall-clock win (device-tag rule).
    ("serving_decode_kv_bf16", ["--model", "serving_decode"]),
    ("serving_decode_kv_int8", ["--model", "serving_decode",
                                "--kv-int8"]),
    # r12: speculative decode (ISSUE 20).  The sequential side of this
    # pair is measured INSIDE the variant itself — bench --speculate
    # runs a sequential twin engine over the same stream/arch first
    # (token parity asserted) and records sequential_tokens_per_sec —
    # so the verdict compares same-stream twins, never the
    # differently-shaped serving_decode entry above.
    ("serving_decode_spec_k4", ["--model", "serving_decode",
                                "--speculate", "4"]),
]


_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_tag():
    """This invocation's provenance stamp (paddle_tpu.observe.events).
    Falls back to a bare uuid when the package can't import (foreign
    checkout) — the tag must exist either way."""
    try:
        if _ROOT not in sys.path:
            sys.path.insert(0, _ROOT)
        from paddle_tpu.observe.events import git_sha, new_run_id

        return {"run_id": new_run_id(), "git_sha": git_sha(_ROOT)}
    except Exception:  # noqa: BLE001 — provenance must not kill the run
        import uuid

        return {"run_id": uuid.uuid4().hex[:12], "git_sha": None}


def run_variant(args, extra):
    cmd = ([sys.executable, "bench.py", "--steps", str(args.steps)]
           + (args.bench_args.split() if args.bench_args else [])
           + extra)
    t0 = time.time()
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=args.timeout,
                           cwd=os.path.dirname(os.path.dirname(
                               os.path.abspath(__file__))))
    except subprocess.TimeoutExpired:
        return {"error": f"variant timed out after {args.timeout}s"}
    line = None
    for ln in reversed(r.stdout.strip().splitlines()):
        ln = ln.strip()
        if ln.startswith("{"):
            line = ln
            break
    if line is None:
        tail = (r.stderr or r.stdout).strip().splitlines()[-3:]
        return {"error": "no JSON line: " + " | ".join(tail)}
    out = json.loads(line)
    out["wall_s"] = round(time.time() - t0, 1)
    return out


# variant key -> the bench model its argv requests; the throughput
# lookup below must read THAT model's detail entry, not whatever dict
# order yields first (ADVICE r5: a longctx line carrying an extra
# sub-entry would have fed the wrong model's tok/s into the summary)
_VARIANT_MODEL = {
    key: argv[argv.index("--model") + 1]
    for key, argv in VARIANTS if "--model" in argv
}


def _model_entries(detail, model):
    """Sub-entries belonging to `model`: exact key or model-prefixed
    (bench keys resolved shapes into names like longctx_8k,
    resnet50_frozen)."""
    return [sub for name, sub in detail.items()
            if isinstance(sub, dict)
            and (name == model or name.startswith(model + "_"))]


def measure(results, k):
    """Comparable scalar for variant k, or None for NO DATA.

    A failed bench prints {"metric": "bench_failed", "value": 0.0}
    (and run_variant itself may record {"error": ...}): both are NO
    DATA, never a 0.0 that hands the other side a vacuous win.
    The lookup is keyed by the variant's EXPECTED model (falling back
    to a sole sub-entry for foreign/legacy artifacts): multi-entry
    details must never contribute another model's number.
    Prefers THROUGHPUT over MFU: variants can carry different MFU
    numerators (the program's own XLA count vs the dense-equivalent
    twin for Pallas/remat configs), and the r05 chip session caught
    fused-CE "winning" on MFU while losing wall-clock.  tok/s and
    img/s are numerator-free.  No throughput recorded -> None; falling
    back to the MFU value would re-open the cross-numerator comparison
    this function exists to prevent."""
    d = results.get(k, {})
    if "error" in d or "failed" in d or \
            d.get("metric") == "bench_failed":
        return None
    detail = d.get("detail") or {}
    model = _VARIANT_MODEL.get(k)
    if model is not None:
        subs = _model_entries(detail, model)
    else:
        # unknown variant key (hand-rolled artifact): only an
        # unambiguous single-entry detail is trustworthy
        subs = [sub for sub in detail.values() if isinstance(sub, dict)]
        if len(subs) != 1:
            return None
    for sub in subs:
        for key in ("tokens_per_sec", "imgs_per_sec",
                    "examples_per_sec"):
            if key in sub:
                return sub[key]
    return None


def mem_measure(results, k):
    """Peak device bytes for variant k, or None for NO DATA.

    Prefers the expected model entry's `mem_breakdown.peak_bytes`
    (buffer-assignment analysis of the measured step, observe.memory)
    and falls back to the line's host-side `peak_mem_bytes` high-water
    mark.  Same no-data discipline as measure(): a failed variant
    contributes None, never a 0 that fakes a memory win."""
    d = results.get(k, {})
    if "error" in d or "failed" in d or \
            d.get("metric") == "bench_failed":
        return None
    detail = d.get("detail") or {}
    model = _VARIANT_MODEL.get(k)
    subs = (_model_entries(detail, model) if model is not None
            else [sub for sub in detail.values() if isinstance(sub, dict)])
    for sub in subs:
        mb = sub.get("mem_breakdown")
        if isinstance(mb, dict) and mb.get("peak_bytes"):
            return int(mb["peak_bytes"])
    return d.get("peak_mem_bytes") or None


def layout_measure(results, k):
    """The variant's layout_share (layout-bucket byte fraction of the
    measured step, bench.py/_layout_fields), or None for NO DATA —
    context for the head-major pairs; throughput still decides."""
    d = results.get(k, {})
    if "error" in d or "failed" in d or \
            d.get("metric") == "bench_failed":
        return None
    detail = d.get("detail") or {}
    model = _VARIANT_MODEL.get(k)
    subs = (_model_entries(detail, model) if model is not None
            else [sub for sub in detail.values() if isinstance(sub, dict)])
    for sub in subs:
        if isinstance(sub.get("layout_share"), (int, float)):
            return sub["layout_share"]
    return None


def comm_measure(results, k):
    """The variant's comm_bytes (modeled per-device collective bytes
    per step from the sharded compiled module's comm bucket,
    bench.py/_comm_fields), or None for NO DATA — the context every dp
    pair carries: an int8 "win" that didn't actually shrink the
    gradient exchange would be noise, and a loss that did shrink it is
    still the lever to retune.  Throughput decides, as everywhere."""
    d = results.get(k, {})
    if "error" in d or "failed" in d or \
            d.get("metric") == "bench_failed":
        return None
    detail = d.get("detail") or {}
    model = _VARIANT_MODEL.get(k)
    subs = (_model_entries(detail, model) if model is not None
            else [sub for sub in detail.values() if isinstance(sub, dict)])
    for sub in subs:
        if isinstance(sub.get("comm_bytes"), (int, float)):
            return sub["comm_bytes"]
    return None


def opt_state_measure(results, k):
    """The variant's opt_state_bytes_per_device (resident per-device
    accumulator bytes of the sharded step, bench.py/_opt_state_fields),
    or None for NO DATA — the fsdp/ZeRO pairs' point: the memory claim
    is only real if the sharded step's buffer assignment shows it."""
    d = results.get(k, {})
    if "error" in d or "failed" in d or \
            d.get("metric") == "bench_failed":
        return None
    detail = d.get("detail") or {}
    model = _VARIANT_MODEL.get(k)
    subs = (_model_entries(detail, model) if model is not None
            else [sub for sub in detail.values() if isinstance(sub, dict)])
    for sub in subs:
        if isinstance(sub.get("opt_state_bytes_per_device"),
                      (int, float)):
            return sub["opt_state_bytes_per_device"]
    return None


def goodput_measure(results, k):
    """The variant's (goodput, effective_mfu) pair from the expected
    model entry (observe pillar 8: the harness-wall step fraction and
    the headline scaled by it), or None for NO DATA.  Context only —
    a variant whose throughput "win" came with a goodput collapse
    (e.g. a compile-storm per run) is visible in the same artifact;
    throughput still decides, as everywhere."""
    d = results.get(k, {})
    if "error" in d or "failed" in d or \
            d.get("metric") == "bench_failed":
        return None
    detail = d.get("detail") or {}
    model = _VARIANT_MODEL.get(k)
    subs = (_model_entries(detail, model) if model is not None
            else [sub for sub in detail.values() if isinstance(sub, dict)])
    for sub in subs:
        if isinstance(sub.get("goodput"), (int, float)):
            return {"goodput": sub["goodput"],
                    "effective_mfu": sub.get("effective_mfu")}
    return None


def wins(results, a, b):
    # a missing side must yield "no data", never a vacuous win —
    # AB wins gate bench defaults (CLAUDE.md measured-wins-only).
    # THROUGHPUT decides (the r05 MFU-numerator lesson); the memory
    # delta rides the summary via mem_measure for context only.
    ma, mb = measure(results, a), measure(results, b)
    if ma is None or mb is None:
        return None
    return ma > mb


# summary pairs: "<name>_wins" (throughput verdict) + the peak-memory
# context keys.  longctx_recompute documents the r05 remat decision in
# BYTES as well as MFU: remat won memory and lost throughput — both
# sides of that trade now live in the artifact.
_PAIRS = {
    "nhwc": ("resnet50_nhwc", "resnet50_nchw"),
    "fused_ce": ("transformer_fused_ce", "transformer_base"),
    "fused_qkv": ("transformer_fused_qkv", "transformer_base"),
    "pallas_attn": ("transformer_pallas_attn", "transformer_base"),
    "longctx_pallas": ("longctx_8k_pallas", "longctx_8k_xla"),
    "longctx_recompute": ("longctx_8k_recompute", "longctx_8k_pallas"),
    # head-major layout verdicts (ISSUE 8): throughput decides as
    # everywhere; the layout_share delta rides compute_summary so the
    # traffic deletion is visible next to the wall-clock verdict
    "headmajor": ("transformer_headmajor", "transformer_base"),
    "pallas_attn_headmajor": ("transformer_pallas_headmajor",
                              "transformer_base"),
    "longctx_headmajor": ("longctx_8k_headmajor", "longctx_8k_pallas"),
    "lstm_unroll2": ("lstm_unroll2", "lstm_base"),
    "lstm_unroll4": ("lstm_unroll4", "lstm_base"),
    "lstm_unroll8": ("lstm_unroll8", "lstm_base"),
    "lstm_pallas_rnn": ("lstm_pallas_rnn", "lstm_base"),
    # the quantized gradient exchange vs the implicit bf16 all-reduce
    # at the same dp degree; per-pair comm-bytes context rides the
    # summary (<name>_comm_bytes)
    "dp8_int8ar": ("dp8_int8ar", "dp8_bf16"),
    # fsdp-vs-dp at the same device count: the ZeRO memory claim
    # (opt-state + peak deltas ride the summary); throughput still
    # decides defaults
    "fsdp8_zero": ("fsdp8", "dp8_bf16"),
    # the composed-mesh int8 exchange (psum-form) vs its bf16 twin
    "dp2mp2_int8ar": ("dp2mp2_int8ar", "dp2mp2"),
    # int8 KV pools vs the bf16 default for continuous-batching decode
    "decode_kv_int8": ("serving_decode_kv_int8",
                       "serving_decode_kv_bf16"),
}

# intra-entry pairs: both sides live in ONE variant's entry (the bench
# measured them as same-stream twins in the same process).  The
# speculative pair is the canonical case — speedup_vs_sequential is
# spec tokens/s over the sequential twin's, with token parity asserted
# before either number is recorded.
_TWIN_PAIRS = {
    "decode_spec_k4": ("serving_decode_spec_k4", {
        "a_key": "tokens_per_sec",
        "b_key": "sequential_tokens_per_sec",
        "context": ("accept_rate", "accept_hist",
                    "speculation_efficiency", "speedup_vs_sequential",
                    "token_parity", "post_warmup_compiles"),
    }),
}


def compute_summary(results):
    out = {}
    for name, (a, b) in _PAIRS.items():
        out[f"{name}_wins"] = wins(results, a, b)
        pa, pb = mem_measure(results, a), mem_measure(results, b)
        if pa is not None and pb is not None:
            # positive = variant a needs MORE memory than b; the
            # throughput verdict above still decides defaults, but a
            # loss bought with a big memory saving (remat) or a win
            # paid for in HBM is now visible in the same artifact
            out[f"{name}_mem_delta_bytes"] = pa - pb
            out[f"{name}_mem_peaks"] = {a: pa, b: pb}
        la, lb = layout_measure(results, a), layout_measure(results, b)
        if la is not None and lb is not None:
            # negative = variant a moves FEWER layout bytes than b —
            # the head-major traffic-deletion claim, recorded next to
            # the throughput verdict that decides the default
            out[f"{name}_layout_share"] = {a: la, b: lb}
        ca, cb = comm_measure(results, a), comm_measure(results, b)
        if ca is not None and cb is not None:
            # the dp pairs' point: how many collective bytes each side
            # actually moves per step (int8's claim is ~half); recorded
            # next to the throughput verdict that decides the default
            out[f"{name}_comm_bytes"] = {a: ca, b: cb}
        oa, ob = (opt_state_measure(results, a),
                  opt_state_measure(results, b))
        if oa is not None and ob is not None:
            # the fsdp pairs' point: per-device resident opt-state
            # bytes — the ZeRO ~1/N claim in the artifact itself
            out[f"{name}_opt_state_bytes"] = {a: oa, b: ob}
        ga, gb = (goodput_measure(results, a),
                  goodput_measure(results, b))
        if ga is not None and gb is not None:
            # goodput context (observe pillar 8) next to the verdict:
            # each side's harness-wall step fraction + effective_mfu,
            # so a throughput win bought with badput (compile storms,
            # ckpt stalls) is visible in the same artifact
            out[f"{name}_goodput"] = {a: ga, b: gb}
    for name, (variant, spec) in _TWIN_PAIRS.items():
        d = results.get(variant, {})
        detail = d.get("detail") or {}
        entry = None
        for sub_name, sub in detail.items():
            if isinstance(sub, dict) and spec["b_key"] in sub:
                entry = sub
                break
        if entry is None or "error" in (d or {}):
            out[f"{name}_wins"] = None
            continue
        ma, mb = entry.get(spec["a_key"]), entry.get(spec["b_key"])
        out[f"{name}_wins"] = (None if not (ma and mb) else ma > mb)
        out[f"{name}_twin"] = {spec["a_key"]: ma, spec["b_key"]: mb,
                               **{c: entry.get(c)
                                  for c in spec["context"]}}
    # the ZeRO scaling record (ISSUE 13 acceptance): opt-state bytes
    # per device across the fsdp ladder vs the dp=8 replicated
    # baseline — drop >=1.7x at fsdp=2, ~N/1 at fsdp=4/8 (the pinned
    # chip-free assert lives in tests/test_hybrid_parallel.py; this is
    # the recorded artifact form)
    base = opt_state_measure(results, "dp8_bf16")
    ladder = {n: opt_state_measure(results, f"fsdp{n}")
              for n in (2, 4, 8)}
    if base and all(v for v in ladder.values()):
        out["fsdp_opt_state_scaling"] = {
            "dp8_bytes": base,
            **{f"fsdp{n}_bytes": v for n, v in ladder.items()},
            **{f"fsdp{n}_drop_x": round(base / v, 3)
               for n, v in ladder.items()},
            "zero_scaling_ok": bool(
                base / ladder[2] >= 1.7
                and base / ladder[4] >= 4 * 0.75
                and base / ladder[8] >= 8 * 0.75),
        }
    return out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--timeout", type=int, default=1200)
    p.add_argument("--out", default="AB_r12.json")
    p.add_argument("--only", default=None,
                   help="comma-separated variant keys to run")
    p.add_argument("--bench-args", default=None,
                   help="extra bench.py args prepended to every "
                        "variant (e.g. '--batch 16' for an off-chip "
                        "CPU recording — each entry's `device` field "
                        "records the producing backend either way)")
    args = p.parse_args()

    run_tag = _run_tag()
    results = {}
    if args.only and os.path.exists(args.out):
        # selective re-run (post-fix retest): keep the other variants'
        # recorded entries, replace only the re-run ones.  A corrupt
        # artifact (torn write from a killed run) must not crash the
        # retest — start fresh and say so.
        try:
            with open(args.out) as f:
                loaded = json.load(f)
            if not isinstance(loaded, dict):
                raise ValueError(f"expected a dict, got "
                                 f"{type(loaded).__name__}")
        except (OSError, ValueError) as e:
            print(f"warning: existing {args.out} unreadable ({e}); "
                  f"starting fresh", file=sys.stderr)
            loaded = {}
        results = {k: v for k, v in loaded.items() if k != "summary"}
        # auditability: every kept entry must say which run produced it;
        # pre-observability artifacts get an explicit unknown marker
        for v in results.values():
            if isinstance(v, dict) and "run_id" not in v:
                v["run_id"] = None
                v["merged_pre_provenance"] = True
    for key, extra in VARIANTS:
        if args.only and key not in args.only.split(","):
            continue
        print(f"=== {key}: bench.py {' '.join(extra)}", file=sys.stderr)
        out = run_variant(args, extra)
        # the bench line already carries its own run_id/git_sha when the
        # bench ran far enough to print one; error entries get this
        # invocation's tag so they are attributable too
        out.setdefault("run_id", run_tag["run_id"])
        out.setdefault("git_sha", run_tag["git_sha"])
        results[key] = out
        print(json.dumps({key: results[key]}), file=sys.stderr)
        with open(args.out, "w") as f:
            json.dump(results, f, indent=1)

    summary = compute_summary(results)
    summary["run_id"] = run_tag["run_id"]
    summary["git_sha"] = run_tag["git_sha"]
    results["summary"] = summary
    with open(args.out, "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
