"""High-level Trainer / Inferencer with checkpoint-based recovery.

TPU-native analog of the reference contrib trainer
(reference: python/paddle/fluid/contrib/trainer.py — Trainer:100 event
loop over epochs with BeginEpoch/BeginStep/EndStep/EndEpoch events,
CheckpointConfig:100 epoch/step cadence, _save_checkpoint/
_load_checkpoint recovery at :580/:1047; Inferencer).

This is also the framework's failure-recovery story (SURVEY.md §5.3):
synchronous ICI training has no per-worker elasticity, so recovery =
periodic checkpoints + restart-and-resume.  Trainer checkpoints
persistables plus its own (epoch, step) cursor at the configured
cadence, and a restarted Trainer resumes from the newest valid
checkpoint automatically — the TPU equivalent of the reference's
trainer-0 persistables + checkpoint_notify flow.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import io as fluid_io
from ..core.executor import Executor, Scope, scope_guard
from ..core.program import Program, default_main_program, program_guard


class BeginEpochEvent:
    def __init__(self, epoch_id: int):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id: int):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id: int, step_id: int):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id: int, step_id: int, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    """reference contrib/trainer.py CheckpointConfig:100."""

    def __init__(self, checkpoint_dir: Optional[str] = None,
                 max_num_checkpoints: int = 3,
                 epoch_interval: int = 1, step_interval: int = 10):
        self.checkpoint_dir = checkpoint_dir or "checkpoints"
        self.max_num_checkpoints = max(1, int(max_num_checkpoints))
        self.epoch_interval = max(1, int(epoch_interval))
        self.step_interval = max(1, int(step_interval))


class Trainer:
    """Event-driven training loop with checkpoint/resume.

        def train_func():
            loss = build_network()
            return loss                      # or [loss, metric, ...]

        trainer = Trainer(train_func=train_func,
                          optimizer_func=lambda: fluid.optimizer.SGD(0.1),
                          checkpoint_config=CheckpointConfig("ckpts"))
        trainer.train(num_epochs=3, event_handler=handler,
                      reader=batch_dict_reader, feed_order=[...])
    """

    def __init__(self, train_func: Callable, optimizer_func: Callable,
                 place=None, checkpoint_config: Optional[CheckpointConfig]
                 = None, scope: Optional[Scope] = None, telemetry=None,
                 step_deadline_s: Optional[float] = None):
        """telemetry: an observe.TelemetryConfig — enables the
        device-side StepTelemetry accumulator on the train program and
        publishes a window (telemetry means + compile/retrace/dispatch
        runtime stats) every `interval` steps, to the configured JSONL
        event log when one is given.  The accumulator lives inside the
        jitted step; the only added host traffic is ONE fetch per
        window (never per-step — CLAUDE.md tunnel-backend rule).

        step_deadline_s: wall-clock watchdog around each training step
        (resilience.Deadline) — a hung compile/dispatch raises a
        structured WatchdogTimeout instead of stalling forever."""
        self.checkpoint_cfg = checkpoint_config
        self.telemetry_cfg = telemetry
        self.step_deadline_s = step_deadline_s
        self.scope = scope or Scope()
        self.startup_program = Program()
        self.train_program = Program()
        self.place = place
        # fresh unique_name counters so generated var names (optimizer
        # lr/accumulators, tmp params) are deterministic across process
        # restarts — required for checkpoint resume (fluid's Trainer
        # builds under unique_name.guard for the same reason)
        from ..core import unique_name

        with unique_name.guard(), \
                program_guard(self.train_program, self.startup_program):
            outs = train_func()
            if isinstance(outs, (list, tuple)):
                self.train_outputs = list(outs)
            else:
                self.train_outputs = [outs]
            optimizer = optimizer_func()
            optimizer.minimize(self.train_outputs[0])
        self._event_log = None
        if self.telemetry_cfg is not None:
            from .. import observe

            observe.enable_telemetry(self.train_program)
            self._event_log = self.telemetry_cfg.event_log
            if self._event_log is None and self.telemetry_cfg.log_path:
                self._event_log = observe.RunEventLog(
                    self.telemetry_cfg.log_path,
                    meta={"source": "contrib.Trainer"})
        self.exe = Executor(place)
        with scope_guard(self.scope):
            self.exe.run(self.startup_program)
        # resume point restored from the newest checkpoint: the epoch to
        # continue in, plus how many of its batches were already consumed
        self._resume_epoch = 0
        self._resume_step_in_epoch = 0
        if self.checkpoint_cfg:
            self._try_resume()

    # -- checkpointing ---------------------------------------------------
    def _ckpt_root(self) -> str:
        return self.checkpoint_cfg.checkpoint_dir

    def _list_checkpoints(self) -> List[int]:
        root = self._ckpt_root()
        if not os.path.isdir(root):
            return []
        ids = []
        for d in os.listdir(root):
            if d.startswith("ckpt_") and os.path.exists(
                    os.path.join(root, d, "__trainer_state__.json")):
                try:
                    ids.append(int(d.split("_")[1]))
                except ValueError:
                    continue
        return sorted(ids)

    def _emit(self, kind: str, **fields):
        """Checkpoint/resume lifecycle events go to the event log when
        one is configured AND to stderr — a resume that silently
        skipped a corrupt checkpoint is an incident nobody can debug."""
        import sys

        if self._event_log:
            self._event_log.event(kind, **fields)
        print(f"Trainer {kind}: "
              + " ".join(f"{k}={v}" for k, v in fields.items()),
              file=sys.stderr)

    def _save_checkpoint(self, serial: int, epoch: int, step: int):
        root = self._ckpt_root()
        path = os.path.join(root, f"ckpt_{serial}")
        if os.path.isdir(path) and not os.path.exists(
                os.path.join(path, "__trainer_state__.json")):
            # leftover of a save that died mid-write (torn): clear it so
            # stale shard files cannot mix with the fresh save
            shutil.rmtree(path, ignore_errors=True)
        os.makedirs(path, exist_ok=True)
        with scope_guard(self.scope):
            # sharded writer: each process persists only its own array
            # shards (io.py save_sharded) — scales to mp/fsdp state that
            # must never gather to one host
            fluid_io.save_sharded(self.exe, path,
                                  main_program=self.train_program)
        with open(os.path.join(path, "__trainer_state__.json"), "w") as f:
            json.dump({"epoch": epoch, "step": step, "serial": serial}, f)
        # rotate (reference keeps max_num_checkpoints, deleting oldest)
        ids = self._list_checkpoints()
        while len(ids) > self.checkpoint_cfg.max_num_checkpoints:
            victim = os.path.join(root, f"ckpt_{ids.pop(0)}")
            shutil.rmtree(victim, ignore_errors=True)

    def _load_checkpoint(self, path: str) -> dict:
        """Load one checkpoint dir (arrays + trainer cursor) or raise a
        structured CheckpointError (resilience/errors.py)."""
        from ..resilience.errors import (CheckpointCorruptError,
                                         CheckpointNotFoundError)

        with scope_guard(self.scope):
            if os.path.exists(os.path.join(path,
                                           fluid_io.SHARD_MANIFEST)):
                # load each var straight into its target sharding when
                # the program was compiled over a mesh (no host gather)
                wrapper = getattr(self.train_program,
                                  "_compiled_wrapper", None)
                mesh = wrapper._mesh if wrapper is not None else None
                fluid_io.load_sharded(self.exe, path,
                                      main_program=self.train_program,
                                      mesh=mesh)
            else:
                # checkpoint from the pre-sharded combined format
                fluid_io.load_persistables(self.exe, path,
                                           main_program=self.train_program)
        state_path = os.path.join(path, "__trainer_state__.json")
        try:
            with open(state_path) as f:
                return json.load(f)
        except FileNotFoundError as e:
            raise CheckpointNotFoundError(
                f"checkpoint {path!r} has no trainer state (torn save)",
                dirname=path) from e
        except (json.JSONDecodeError, OSError) as e:
            raise CheckpointCorruptError(
                f"unreadable trainer state {state_path!r}: {e}",
                dirname=path, cause=f"{type(e).__name__}: {e}") from e

    def _try_resume(self):
        """Resume from the NEWEST VALID checkpoint: serials are tried
        newest-first, and a torn/corrupt/incomplete one is skipped with
        a loud `ckpt_fallback` record — never a raw numpy/JSON error,
        never a silent fresh start when an older valid serial exists."""
        from ..resilience.errors import CheckpointError

        ids = self._list_checkpoints()
        for serial in reversed(ids):
            path = os.path.join(self._ckpt_root(), f"ckpt_{serial}")
            try:
                st = self._load_checkpoint(path)
            except CheckpointError as e:
                self._emit("ckpt_fallback", serial=serial,
                           error=e.as_dict())
                continue
            self._resume_epoch = int(st.get("epoch", 0))
            self._resume_step_in_epoch = int(st.get("step", 0))
            if serial != ids[-1] or self._event_log:
                self._emit("ckpt_resume", serial=serial,
                           epoch=self._resume_epoch,
                           step=self._resume_step_in_epoch,
                           fallback=serial != ids[-1])
            return
        if ids:
            self._emit("ckpt_resume_failed", tried=list(reversed(ids)))

    # -- the loop --------------------------------------------------------
    def train(self, num_epochs: int, event_handler: Optional[Callable]
              = None, reader: Optional[Callable] = None,
              feed_order: Optional[Sequence[str]] = None):
        """reader: callable -> iterable of feed dicts (or tuples aligned
        with feed_order)."""
        handler = event_handler or (lambda e: None)
        serial = ((self._list_checkpoints() or [-1])[-1] + 1
                  if self.checkpoint_cfg else 0)
        fetch = [o.name for o in self.train_outputs]
        skip = self._resume_step_in_epoch  # mid-epoch fast-forward
        tel_snap = None
        if self.telemetry_cfg is not None:
            from ..observe import runtime_stats

            tel_snap = runtime_stats.snapshot()
            if self._event_log:
                self._event_log.event(
                    "train_begin", num_epochs=num_epochs,
                    resume_epoch=self._resume_epoch,
                    resume_step=self._resume_step_in_epoch)
        for epoch in range(self._resume_epoch, num_epochs):
            handler(BeginEpochEvent(epoch))
            step = 0
            done = 0
            for batch in (reader() if reader else iter(())):
                # resume semantics: a mid-epoch checkpoint records how
                # many batches of its epoch were consumed; with a
                # deterministic reader, skipping them continues exactly
                # where the dead process stopped (already-trained
                # batches are not replayed onto updated params)
                if skip > 0:
                    skip -= 1
                    step += 1
                    continue
                if not isinstance(batch, dict):
                    if feed_order is None:
                        raise ValueError(
                            "tuple batches need feed_order")
                    batch = dict(zip(feed_order, batch))
                begin = BeginStepEvent(epoch, step)
                handler(begin)
                from ..resilience.watchdog import Deadline

                with scope_guard(self.scope), \
                        Deadline(self.step_deadline_s or 0,
                                 what=f"train step {epoch}/{step}"):
                    metrics = self.exe.run(
                        self.train_program, feed=batch,
                        fetch_list=fetch if begin.fetch_metrics else [])
                handler(EndStepEvent(epoch, step, metrics))
                step += 1
                done += 1
                if (self.telemetry_cfg is not None and
                        done % self.telemetry_cfg.interval == 0):
                    tel_snap = self._publish_telemetry(epoch, step,
                                                       tel_snap)
                if (self.checkpoint_cfg and
                        done % self.checkpoint_cfg.step_interval == 0):
                    self._save_checkpoint(serial, epoch, step)
                    serial += 1
                    if self._event_log:
                        self._event_log.event("checkpoint",
                                              serial=serial - 1,
                                              epoch=epoch, step=step)
            if skip > 0:
                raise RuntimeError(
                    f"resume cursor expected at least {skip} more batches "
                    f"in epoch {epoch} than the reader produced — the "
                    f"dataset/reader changed since the checkpoint")
            skip = 0  # fast-forward applies to the resume epoch only
            if (self.checkpoint_cfg and
                    (epoch + 1) % self.checkpoint_cfg.epoch_interval == 0):
                self._save_checkpoint(serial, epoch + 1, 0)
                serial += 1
            handler(EndEpochEvent(epoch))
        if self.telemetry_cfg is not None:
            # flush the partial final window so no steps go unreported
            self._publish_telemetry(num_epochs - 1, -1, tel_snap)
            if self._event_log:
                self._event_log.event("train_end",
                                      num_epochs=num_epochs)

    # -- telemetry -------------------------------------------------------
    last_telemetry = None

    def _publish_telemetry(self, epoch: int, step: int, since):
        """Fetch the device accumulator (ONE host sync), attach the
        window's host runtime stats, and emit a `telemetry` event."""
        from .. import observe

        tel = observe.fetch_telemetry(self.scope, reset=True)
        now = observe.runtime_stats.snapshot()
        if tel is None or tel.steps == 0:
            return now
        self.last_telemetry = tel
        if self._event_log:
            delta = observe.runtime_stats.delta(since or {})
            self._event_log.telemetry_window(
                tel, epoch=epoch, step=step,
                compiles=delta["compiles"],
                compile_time_s=round(delta["compile_time_s"], 3),
                retraces=delta["retraces"],
                dispatches=delta["dispatches"],
                dispatch_time_s=round(delta["dispatch_time_s"], 4),
                peak_mem_bytes=observe.peak_memory_bytes())
        return now

    def save_params(self, dirname: str):
        with scope_guard(self.scope):
            fluid_io.save_params(self.exe, dirname,
                                 main_program=self.train_program)

    def save_inference_model(self, dirname: str,
                             feeded_var_names: Sequence[str],
                             target_vars: Sequence):
        with scope_guard(self.scope):
            fluid_io.save_inference_model(
                dirname, feeded_var_names, list(target_vars), self.exe,
                main_program=self.train_program)

    def stop(self):
        self.exe.close()


class Inferencer:
    """reference contrib/trainer.py Inferencer: load params produced by a
    Trainer and run a forward network."""

    def __init__(self, infer_func: Callable, param_path: str, place=None,
                 shared_scope: Optional[Scope] = None):
        self.scope = shared_scope or Scope()
        self.program = Program()
        startup = Program()
        from ..core import unique_name

        with unique_name.guard(), program_guard(self.program, startup):
            outs = infer_func()
            self.outputs = (list(outs) if isinstance(outs, (list, tuple))
                            else [outs])
        self.exe = Executor(place)
        with scope_guard(self.scope):
            self.exe.run(startup)
            fluid_io.load_params(self.exe, param_path,
                                 main_program=self.program)

    def infer(self, inputs: Dict[str, np.ndarray]):
        with scope_guard(self.scope):
            return self.exe.run(self.program, feed=inputs,
                                fetch_list=[o.name for o in self.outputs])
