"""Sequence + RNN layer functions.

reference: python/paddle/fluid/layers/nn.py (dynamic_lstm, dynamic_gru,
sequence_* family).  Ragged inputs are padded (N, T, ...) vars with a
companion `<name>.seq_len` var (created by layers.data(lod_level=1) and
fed by DataFeeder); these wrappers wire the companion through ops and
propagate it to outputs that stay sequences.
"""

from __future__ import annotations

from ..core.program import Variable, default_main_program
from ..initializer import Constant, Xavier
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr


def seq_len_var(x: Variable):
    """The companion length var of a sequence variable, if any."""
    block = default_main_program().current_block()
    name = f"{x.name}.seq_len"
    return block.var(name) if block.has_var(name) else None


def _propagate_seq_len(src: Variable, dst: Variable):
    sl = seq_len_var(src)
    if sl is None:
        return
    block = default_main_program().current_block()
    new = block.create_var(name=f"{dst.name}.seq_len", shape=sl.shape,
                           dtype=sl.dtype, stop_gradient=True)
    block.append_op(type="assign", inputs={"X": [sl]},
                    outputs={"Out": [new]})


def _emit_companion(out_var: Variable, length_var: Variable,
                    suffix: str = "seq_len"):
    """Materialize a length companion (`<out>.seq_len` /
    `<out>.seq_len2`) from an op's Length output."""
    block = default_main_program().current_block()
    sl = block.create_var(name=f"{out_var.name}.{suffix}",
                          shape=length_var.shape, dtype="int32",
                          stop_gradient=True)
    block.append_op(type="assign", inputs={"X": [length_var]},
                    outputs={"Out": [sl]})
    return sl


def _require_level1(x: Variable, api: str):
    """Layer-level rejection for APIs without nested (lod_level=2)
    support — fails loudly at graph-build time instead of running
    level-1 semantics on the sub-sequence axis (only sequence_pool
    removes a nesting level)."""
    if seq_len2_var(x) is not None:
        raise NotImplementedError(
            f"{api} does not support nested (lod_level=2) inputs; pool "
            f"the inner level first (sequence_pool)")


def _seq_inputs(x: Variable, slot="X"):
    ins = {slot: [x]}
    sl = seq_len_var(x)
    if sl is not None:
        ins["SeqLen"] = [sl]
    sl2 = seq_len2_var(x)
    if sl2 is not None:
        ins["SeqLen2"] = [sl2]
    return ins


def seq_len2_var(x: Variable):
    """The level-2 (nested) length companion, if any (lod_level=2
    inputs: data padded (B, S1, S2, ...) with seq_len (B,) counting
    sub-sequences and seq_len2 (B, S1) counting their items)."""
    block = default_main_program().current_block()
    name = f"{x.name}.seq_len2"
    return block.var(name) if block.has_var(name) else None


# ---------------------------------------------------------------------------
# RNNs
# ---------------------------------------------------------------------------

def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None,
                 use_pallas=False, unroll=1):
    """reference layers/nn.py dynamic_lstm — input must be (N, T, 4*hidden)
    (the x-projection fc is applied by the caller, as in fluid); size is
    4*hidden.

    Scan-bound perf levers (docs/RNN.md): `unroll` unrolls the lax.scan
    recurrence by that factor; `use_pallas` routes it through the
    blocked fused Pallas kernel (no peepholes / non-default
    activations)."""
    helper = LayerHelper("lstm", name=name)
    hidden = size // 4
    w = helper.create_parameter(param_attr, shape=[hidden, 4 * hidden],
                                dtype=dtype)
    bias_size = 7 * hidden if use_peepholes else 4 * hidden
    b = helper.create_parameter(ParamAttr._to_attr(bias_attr) or ParamAttr(),
                                shape=[1, bias_size], dtype=dtype,
                                is_bias=True)
    hidden_out = helper.create_variable_for_type_inference(dtype)
    cell_out = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    last_c = helper.create_variable_for_type_inference(dtype)
    ins = _seq_inputs(input, "Input")
    ins.update({"Weight": [w], "Bias": [b]})
    if h_0 is not None:
        ins["H0"] = [h_0]
    if c_0 is not None:
        ins["C0"] = [c_0]
    helper.append_op(
        type="dynamic_lstm", inputs=ins,
        outputs={"Hidden": [hidden_out], "Cell": [cell_out],
                 "LastH": [last_h], "LastC": [last_c]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation,
               "use_pallas": use_pallas, "unroll": unroll})
    _propagate_seq_len(input, hidden_out)
    _propagate_seq_len(input, cell_out)
    return hidden_out, cell_out


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None, h_0=None, c_0=None,
                  unroll=1):
    """LSTM with recurrent projection (reference layers/nn.py
    dynamic_lstmp:655) — input (N, T, 4*hidden) pre-projected by the
    caller's fc; size is 4*hidden, proj_size the projection width.
    Returns (projection (N, T, proj_size), cell (N, T, hidden))."""
    helper = LayerHelper("lstmp", name=name)
    hidden = size // 4
    w = helper.create_parameter(param_attr, shape=[proj_size, 4 * hidden],
                                dtype=dtype)
    w_proj = helper.create_parameter(param_attr, shape=[hidden, proj_size],
                                     dtype=dtype)
    bias_size = 7 * hidden if use_peepholes else 4 * hidden
    b = helper.create_parameter(ParamAttr._to_attr(bias_attr) or ParamAttr(),
                                shape=[1, bias_size], dtype=dtype,
                                is_bias=True)
    proj_out = helper.create_variable_for_type_inference(dtype)
    cell_out = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    last_c = helper.create_variable_for_type_inference(dtype)
    ins = _seq_inputs(input, "Input")
    ins.update({"Weight": [w], "ProjWeight": [w_proj], "Bias": [b]})
    if h_0 is not None:
        ins["H0"] = [h_0]
    if c_0 is not None:
        ins["C0"] = [c_0]
    helper.append_op(
        type="lstmp", inputs=ins,
        outputs={"Projection": [proj_out], "Cell": [cell_out],
                 "LastH": [last_h], "LastC": [last_c]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation,
               "proj_activation": proj_activation, "unroll": unroll})
    _propagate_seq_len(input, proj_out)
    _propagate_seq_len(input, cell_out)
    return proj_out, cell_out


def lod_reset(x, y=None, target_lod=None):
    """Re-segment a token stream (reference layers/nn.py lod_reset:5900).

    Divergence note: the reference accepts a runtime Y whose LoD (or
    rows) define the new structure; under jit the new segmentation fixes
    the output's padded shape, so it must be static — pass `target_lod`
    as a python list of offsets (a python-list `y` of lengths is
    converted).  A traced tensor Y is rejected."""
    if target_lod is None:
        if isinstance(y, (list, tuple)):
            off = [0]
            for l in y:
                off.append(off[-1] + int(l))
            target_lod = off
        else:
            raise ValueError(
                "lod_reset needs a static target_lod (list of offsets) "
                "or a python-list y of lengths; a runtime tensor lod "
                "would make the padded output shape dynamic under jit")
    helper = LayerHelper("lod_reset")
    out = helper.create_variable_for_type_inference(x.dtype)
    length = helper.create_variable_for_type_inference("int32")
    ins = _seq_inputs(x)
    helper.append_op(type="lod_reset", inputs=ins,
                     outputs={"Out": [out], "Length": [length]},
                     attrs={"target_lod": [int(v) for v in target_lod]})
    _emit_companion(out, length)
    return out


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, dtype="float32",
                name=None, unroll=1):
    """reference layers/nn.py dynamic_gru — input (N, T, 3*size)."""
    helper = LayerHelper("gru", name=name)
    w = helper.create_parameter(param_attr, shape=[size, 3 * size],
                                dtype=dtype)
    b = helper.create_parameter(ParamAttr._to_attr(bias_attr) or ParamAttr(),
                                shape=[1, 3 * size], dtype=dtype,
                                is_bias=True)
    hidden_out = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    ins = _seq_inputs(input, "Input")
    ins.update({"Weight": [w], "Bias": [b]})
    if h_0 is not None:
        ins["H0"] = [h_0]
    helper.append_op(
        type="dynamic_gru", inputs=ins,
        outputs={"Hidden": [hidden_out], "LastH": [last_h]},
        attrs={"is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "activation": candidate_activation, "unroll": unroll})
    _propagate_seq_len(input, hidden_out)
    return hidden_out


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """Single LSTM step (reference layers/nn.py lstm_unit): fc([x, h]) →
    lstm_unit op."""
    from . import nn as nn_layers
    from .tensor import concat as concat_layer

    helper = LayerHelper("lstm_unit", name=name)
    size = cell_t_prev.shape[-1]
    # fluid computes the gate projection with one fc over [x, h]
    xh = concat_layer([x_t, hidden_t_prev], axis=1)
    gates = nn_layers.fc(xh, size=4 * size, param_attr=param_attr,
                         bias_attr=bias_attr)
    c = helper.create_variable_for_type_inference(x_t.dtype)
    h = helper.create_variable_for_type_inference(x_t.dtype)
    helper.append_op(type="lstm_unit",
                     inputs={"X": [gates], "C_prev": [cell_t_prev]},
                     outputs={"C": [c], "H": [h]},
                     attrs={"forget_bias": float(forget_bias)})
    return h, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid"):
    helper = LayerHelper("gru_unit")
    hidden_dim = size // 3
    w = helper.create_parameter(param_attr, shape=[hidden_dim, 3 * hidden_dim],
                                dtype=input.dtype)
    b = helper.create_parameter(ParamAttr._to_attr(bias_attr) or ParamAttr(),
                                shape=[1, 3 * hidden_dim], dtype=input.dtype,
                                is_bias=True)
    out_h = helper.create_variable_for_type_inference(input.dtype)
    gate = helper.create_variable_for_type_inference(input.dtype)
    reset_h = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="gru_unit",
        inputs={"Input": [input], "HiddenPrev": [hidden], "Weight": [w],
                "Bias": [b]},
        outputs={"Hidden": [out_h], "Gate": [gate],
                 "ResetHiddenPrev": [reset_h]},
        attrs={"activation": activation,
               "gate_activation": gate_activation})
    return out_h, reset_h, gate


def row_conv(input, future_context_size, param_attr=None, act=None):
    _require_level1(input, "row_conv")
    helper = LayerHelper("row_conv", act=act)
    f = helper.create_parameter(
        param_attr, shape=[future_context_size, input.shape[-1]],
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="row_conv",
                     inputs={"X": [input], "Filter": [f]},
                     outputs={"Out": [out]})
    _propagate_seq_len(input, out)
    return helper.append_activation(out)


# ---------------------------------------------------------------------------
# sequence_* family
# ---------------------------------------------------------------------------

def sequence_pool(input, pool_type, is_test=False):
    helper = LayerHelper("sequence_pool")
    out = helper.create_variable_for_type_inference(input.dtype)
    max_index = helper.create_variable_for_type_inference("int32")
    helper.append_op(type="sequence_pool",
                     inputs=_seq_inputs(input),
                     outputs={"Out": [out], "MaxIndex": [max_index]},
                     attrs={"pooltype": pool_type.upper()})
    if seq_len2_var(input) is not None:
        # pooling a nested sequence removes the innermost level: the
        # output is a level-1 sequence carrying the level-1 lengths
        _propagate_seq_len(input, out)
    return out


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_softmax(input, use_cudnn=False, name=None):
    helper = LayerHelper("sequence_softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="sequence_softmax",
                     inputs=_seq_inputs(input),
                     outputs={"Out": [out]})
    _propagate_seq_len(input, out)
    return out


def sequence_expand(x, y, ref_level=-1, name=None):
    """reference layers/nn.py sequence_expand.  With a NESTED y
    (lod_level=2: seq_len + seq_len2 companions), each x sequence
    broadcasts across y's sub-sequence slots and the output is itself
    nested (reference sequence_expand_op.h ref_level=0 over a 2-level
    Y lod)."""
    _require_level1(x, "sequence_expand")
    helper = LayerHelper("sequence_expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    ins = {"X": [x], "Y": [y]}
    xl = seq_len_var(x)
    if xl is not None:
        ins["SeqLen"] = [xl]
    yl, yl2 = seq_len_var(y), seq_len2_var(y)
    if yl2 is not None:
        if yl is not None:
            ins["YLen"] = [yl]
        ins["YLen2"] = [yl2]
        length = helper.create_variable_for_type_inference("int32")
        outputs = {"Out": [out], "Length": [length]}
        # a dense (N, D) x expands to a LEVEL-1 output (S1 repeated
        # items); a sequence x (N, Tx, ...) expands to a nested one
        x_is_seq = len(x.shape) >= 3
        if x_is_seq:
            length2 = helper.create_variable_for_type_inference("int32")
            outputs["Length2"] = [length2]
        helper.append_op(type="sequence_expand", inputs=ins,
                         outputs=outputs)
        _emit_companion(out, length)
        if x_is_seq:
            _emit_companion(out, length2, "seq_len2")
        return out
    helper.append_op(type="sequence_expand", inputs=ins,
                     outputs={"Out": [out]})
    _propagate_seq_len(y, out)
    return out


def sequence_expand_as(x, y, name=None):
    _require_level1(x, "sequence_expand_as")
    helper = LayerHelper("sequence_expand_as", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="sequence_expand_as",
                     inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    _propagate_seq_len(y, out)
    return out


def sequence_concat(input, name=None):
    """reference layers/nn.py sequence_concat: out_i = concat of every
    input's i-th sequence.  Handles ragged level-1 inputs (valid
    prefixes pack back-to-back) and NESTED (lod_level=2) inputs, where
    each row's sub-sequence lists concatenate (reference
    lod_tensor.h:76-104 multi-level append)."""
    items = list(input) if isinstance(input, (list, tuple)) else [input]
    helper = LayerHelper("sequence_concat", name=name)
    out = helper.create_variable_for_type_inference(items[0].dtype)
    nested = [seq_len2_var(i) is not None for i in items]
    ins = {"X": items}
    lens = [seq_len_var(i) for i in items]
    outputs = {"Out": [out]}
    length = helper.create_variable_for_type_inference("int32")
    outputs["Length"] = [length]
    if any(nested):
        if not all(nested):
            raise NotImplementedError(
                "sequence_concat: mixing nested (lod_level=2) and "
                "flat inputs is not supported — expand the flat input "
                "first")
        ins["SeqLen"] = lens
        ins["SeqLen2"] = [seq_len2_var(i) for i in items]
        length2 = helper.create_variable_for_type_inference("int32")
        outputs["Length2"] = [length2]
    elif all(l is not None for l in lens):
        ins["SeqLen"] = lens
    elif any(l is not None for l in lens):
        raise ValueError(
            "sequence_concat: every ragged input needs its .seq_len "
            "companion (mixing ragged and dense inputs is ambiguous)")
    helper.append_op(type="sequence_concat", inputs=ins, outputs=outputs)
    if "SeqLen" in ins:
        # only ragged/nested outputs carry companions — dense-input
        # concat stays companion-free as before
        _emit_companion(out, length)
    if any(nested):
        _emit_companion(out, length2, "seq_len2")
    return out


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="sequence_mask", inputs={"X": [x]},
                     outputs={"Y": [out]},
                     attrs={"maxlen": maxlen if maxlen else -1,
                            "out_dtype": dtype})
    return out


def sequence_reverse(x, name=None):
    helper = LayerHelper("sequence_reverse", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="sequence_reverse", inputs=_seq_inputs(x),
                     outputs={"Y": [out]})
    _propagate_seq_len(x, out)
    return out


def sequence_pad(x, pad_value, maxlen=None, name=None):
    helper = LayerHelper("sequence_pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    length = helper.create_variable_for_type_inference("int64")
    ins = _seq_inputs(x)
    ins["PadValue"] = [pad_value]
    helper.append_op(type="sequence_pad", inputs=ins,
                     outputs={"Out": [out], "Length": [length]},
                     attrs={"padded_length": maxlen if maxlen else -1})
    return out, length


def sequence_unpad(x, length, name=None):
    _require_level1(x, "sequence_unpad")
    helper = LayerHelper("sequence_unpad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="sequence_unpad",
                     inputs={"X": [x], "Length": [length]},
                     outputs={"Out": [out]})
    return out


def sequence_slice(input, offset, length, name=None):
    _require_level1(input, "sequence_slice")
    helper = LayerHelper("sequence_slice", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="sequence_slice",
                     inputs={"X": [input], "Offset": [offset],
                             "Length": [length]},
                     outputs={"Out": [out]})
    return out


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    _require_level1(input, "sequence_enumerate")
    helper = LayerHelper("sequence_enumerate", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="sequence_enumerate", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"win_size": win_size, "pad_value": pad_value})
    _propagate_seq_len(input, out)
    return out


def sequence_erase(input, tokens, name=None):
    _require_level1(input, "sequence_erase")
    helper = LayerHelper("sequence_erase", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="sequence_erase", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"tokens": list(tokens)})
    _propagate_seq_len(input, out)
    return out


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None,
                  name=None):
    helper = LayerHelper("sequence_conv", name=name, act=act,
                         bias_attr=bias_attr)
    d = input.shape[-1]
    f = helper.create_parameter(param_attr,
                                shape=[filter_size * d, num_filters],
                                dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = _seq_inputs(input)
    ins["Filter"] = [f]
    helper.append_op(type="sequence_conv", inputs=ins,
                     outputs={"Out": [out]},
                     attrs={"contextLength": filter_size,
                            "contextStart": -(filter_size // 2),
                            "contextStride": filter_stride})
    _propagate_seq_len(input, out)
    pre_act = helper.append_bias_op(out, dim_start=2)
    return helper.append_activation(pre_act)


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    helper = LayerHelper("im2sequence", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)

    def _pp(v, n):
        return list(v) if isinstance(v, (list, tuple)) else [v] * n

    helper.append_op(type="im2sequence", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"kernels": _pp(filter_size, 2),
                            "strides": _pp(stride, 2),
                            "paddings": _pp(padding, 4)})
    return out


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    _require_level1(input, "add_position_encoding")
    helper = LayerHelper("add_position_encoding", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="add_position_encoding", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"alpha": alpha, "beta": beta})
    _propagate_seq_len(input, out)
    return out


def sequence_scatter(input, index, updates, name=None):
    """reference layers/nn.py sequence_scatter — index/updates are
    per-sequence (padded) with index's .seq_len companion giving true
    counts."""
    helper = LayerHelper("sequence_scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input], "Ids": [index], "Updates": [updates]}
    sl = seq_len_var(index)
    if sl is not None:
        ins["IdsLen"] = [sl]
    helper.append_op(type="sequence_scatter", inputs=ins,
                     outputs={"Out": [out]})
    return out


def sequence_reshape(input, new_dim, name=None):
    """reference layers/nn.py sequence_reshape."""
    helper = LayerHelper("sequence_reshape", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_len = helper.create_variable_for_type_inference("int32")
    ins = _seq_inputs(input)
    helper.append_op(type="sequence_reshape", inputs=ins,
                     outputs={"Out": [out], "OutLen": [out_len]},
                     attrs={"new_dim": int(new_dim)})
    _emit_companion(out, out_len)
    return out
