"""Self-healing multi-process gang supervisor (docs/RESILIENCE.md,
distributed failure model).

The reference framework assumed a supervising runtime that detects
trainer death and recovers from checkpoints; synchronous TPU gangs
need the same thing one level up from the health plane: something
that OWNS the worker processes.  `Supervisor` spawns the N ranks of a
gang (fresh coordinator endpoint per attempt), watches their exit
codes, and on a broken gang kills the remainder within a grace period
and relaunches — resuming from the newest valid checkpoint via the
Trainer machinery the workers already carry.

Exit-code registry (the supervisor's whole protocol):

| code                     | meaning                                   |
|--------------------------|-------------------------------------------|
| 0                        | clean completion                          |
| 77  `PREEMPT_EXIT_CODE`  | drained after SIGTERM; emergency ckpt landed — relaunch resumes |
| 43  `PEER_LOST_EXIT_CODE`| deliberate exit after detecting peer loss / poison (GangError) |
| 128+N / negative         | killed by signal N (SIGKILL'd rank, OOM)  |
| anything else            | crash                                     |

Restart policy: every relaunch consumes the `max_restarts` budget;
preempt-drain restarts relaunch immediately (the checkpoint already
landed — waiting helps nobody), failure restarts back off on the
deterministic `retry_call` schedule (base * 2**failures, capped),
with an injectable `sleep` so tests assert the schedule.  Budget
exhaustion raises `GangFailedError` carrying every attempt's per-rank
exit codes.  A `finally` sweep guarantees no orphan processes
outlive `run()` regardless of how it exits.

The supervisor itself is jax-free — it manages processes and sets the
PADDLE_TRAINER_* env contract `parallel.init_distributed` reads
(trainer id, world size, coordinator endpoint); `tools/launch_gang.py`
is the CLI wrapper.
"""

from __future__ import annotations

import os
import signal as _signal
import socket
import subprocess
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

from .errors import GangFailedError
from .health import PEER_LOST_EXIT_CODE
from .preempt import PREEMPT_EXIT_CODE


def classify_exit(rc: Optional[int]) -> str:
    """One word per exit code, per the registry above."""
    if rc is None:
        return "running"
    if rc == 0:
        return "ok"
    if rc == PREEMPT_EXIT_CODE:
        return "preempt_drain"
    if rc == PEER_LOST_EXIT_CODE:
        return "peer_lost"
    if rc < 0:
        try:
            return f"signal:{_signal.Signals(-rc).name}"
        except ValueError:
            return f"signal:{-rc}"
    if rc > 128:
        try:
            return f"signal:{_signal.Signals(rc - 128).name}"
        except ValueError:
            return f"signal:{rc - 128}"
    return f"crash:{rc}"


def _free_port(host: str) -> int:
    s = socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


class GangResult:
    """Outcome of a supervised run: per-attempt exit codes and how
    many relaunches it took."""

    def __init__(self, attempts: List[Dict[str, Any]]):
        self.attempts = attempts
        self.restarts = len(attempts) - 1

    @property
    def ok(self) -> bool:
        return bool(self.attempts) and self.attempts[-1]["reason"] == "ok"

    def as_dict(self) -> Dict[str, Any]:
        return {"ok": self.ok, "restarts": self.restarts,
                "attempts": self.attempts}


class Supervisor:
    """Spawn-and-heal a gang of `num_workers` processes.

    worker_cmd: the argv to run for every rank, or a callable
        `(rank, num_workers, coordinator) -> argv` for per-rank
        commands.  Each rank's env carries PADDLE_TRAINER_ID /
        PADDLE_TRAINERS / PADDLE_COORDINATOR (fresh port per attempt,
        so a relaunch never races a dying coordinator socket) plus
        `env` overrides.
    log_dir: when set, rank stdout/stderr go to
        `<log_dir>/attempt<k>_rank<r>.out/.err` (default: inherited).
    elastic: relaunch a broken gang at the SURVIVING world size
        (ISSUE 13, gang elasticity): ranks that died BY SIGNAL
        (SIGKILL, OOM — the machine-lost signature) are treated as
        lost capacity and the next attempt spawns
        `num_workers - lost` ranks (floor 1); deliberate exits
        (peer_lost 43, preempt 77, crashes) relaunch at full size —
        the process died, not the machine.  Workers read the new
        world size from PADDLE_TRAINERS and are expected to reshard
        their state from checkpoints (io.load_sharded is
        mesh-shape-agnostic).  Each shrink is recorded in the attempt
        dict (`shrunk_to`).
    host_coordinator: host the jax coordination SERVICE in the
        supervisor process (one fresh service per attempt) instead of
        inside worker rank 0.  This makes EVERY rank killable with
        structured detection by the survivors: with the default
        rank-0-hosted service, killing rank 0 takes the KV store down
        and jaxlib hard-aborts every surviving client the moment the
        service socket closes — before any health-plane verdict can
        land.  Workers need no changes (PADDLE_COORDINATOR points at
        the supervisor's service; a rank-0 worker's own vestigial
        service is pushed to an ephemeral port via
        JAX_COORDINATOR_BIND_ADDRESS).
    sleep: injectable for deterministic backoff tests.
    """

    def __init__(self, worker_cmd: Union[Sequence[str], Callable],
                 num_workers: int, *,
                 max_restarts: Optional[int] = None,
                 grace_s: Optional[float] = None,
                 backoff_base_s: Optional[float] = None,
                 backoff_max_s: Optional[float] = None,
                 env: Optional[Dict[str, str]] = None,
                 log_dir: Optional[str] = None,
                 coordinator_host: str = "127.0.0.1",
                 host_coordinator: bool = False,
                 elastic: bool = False,
                 poll_s: float = 0.2,
                 sleep: Callable[[float], None] = time.sleep,
                 event_log=None):
        from ..flags import FLAGS

        self.worker_cmd = worker_cmd
        self.num_workers = int(num_workers)
        if self.num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self.max_restarts = int(FLAGS.supervisor_max_restarts
                                if max_restarts is None else max_restarts)
        self.grace_s = float(FLAGS.supervisor_grace_s
                             if grace_s is None else grace_s)
        self.backoff_base_s = float(
            FLAGS.supervisor_backoff_base_s if backoff_base_s is None
            else backoff_base_s)
        self.backoff_max_s = float(
            FLAGS.supervisor_backoff_max_s if backoff_max_s is None
            else backoff_max_s)
        self.env = dict(env or {})
        self.log_dir = log_dir
        self.coordinator_host = coordinator_host
        self.host_coordinator = bool(host_coordinator)
        self.elastic = bool(elastic)
        self.poll_s = float(poll_s)
        self.sleep = sleep
        self.event_log = event_log
        self.backoffs_slept: List[float] = []  # test-observable schedule
        self._log_files: List[Any] = []
        self._service = None  # per-attempt hosted coordination service

    def _start_service(self, coordinator: str) -> None:
        """Host the coordination service here (host_coordinator=True):
        generous service-side heartbeat windows so the SERVICE never
        declares a task dead before our health plane does (its verdict
        would hard-abort the surviving clients)."""
        from jaxlib import xla_extension

        self._service = xla_extension.get_distributed_runtime_service(
            coordinator, self.num_workers, heartbeat_interval=10,
            max_missing_heartbeats=10)

    def _stop_service(self) -> None:
        if self._service is not None:
            try:
                self._service.shutdown()
            except Exception:  # noqa: BLE001 — dead clients may linger
                pass
            self._service = None

    # -- spawning ---------------------------------------------------------
    def _cmd_for(self, rank: int, coordinator: str) -> List[str]:
        if callable(self.worker_cmd):
            return list(self.worker_cmd(rank, self.num_workers,
                                        coordinator))
        return list(self.worker_cmd)

    def _spawn_gang(self, attempt: int) -> Dict[int, subprocess.Popen]:
        port = _free_port(self.coordinator_host)
        coordinator = f"{self.coordinator_host}:{port}"
        if self.host_coordinator:
            self._start_service(coordinator)
        procs: Dict[int, subprocess.Popen] = {}
        for rank in range(self.num_workers):
            env = dict(os.environ)
            env.update(self.env)
            env["PADDLE_TRAINER_ID"] = str(rank)
            env["PADDLE_TRAINERS"] = str(self.num_workers)
            env["PADDLE_COORDINATOR"] = coordinator
            if self.host_coordinator:
                # rank 0 still instantiates its own (unused) service;
                # park it on an ephemeral port so it can't collide
                env["JAX_COORDINATOR_BIND_ADDRESS"] = \
                    f"{self.coordinator_host}:0"
            stdout = stderr = None
            if self.log_dir:
                os.makedirs(self.log_dir, exist_ok=True)
                base = os.path.join(self.log_dir,
                                    f"attempt{attempt}_rank{rank}")
                stdout = open(base + ".out", "w")
                stderr = open(base + ".err", "w")
                self._log_files += [stdout, stderr]
            procs[rank] = subprocess.Popen(
                self._cmd_for(rank, coordinator), env=env,
                stdout=stdout, stderr=stderr)
        if self.event_log is not None:
            self.event_log.event("gang_start", attempt=attempt,
                                 num_workers=self.num_workers,
                                 coordinator=coordinator)
        return procs

    # -- one attempt ------------------------------------------------------
    def _wait_gang(self, procs: Dict[int, subprocess.Popen]
                   ) -> Dict[int, int]:
        """Wait the gang out.  The moment any rank exits non-zero the
        gang is broken and a three-phase teardown starts:

        1. `grace_s` of HANDS OFF — the preferred exit is survivors
           detecting the break themselves (health plane →
           PEER_LOST_EXIT_CODE; the observable, structured path),
        2. SIGTERM stragglers (a preempt_drain worker writes its
           emergency checkpoint and exits 77) + another `grace_s`,
        3. SIGKILL whatever is left.

        Returns {rank: returncode}."""
        codes: Dict[int, int] = {}
        breaking_t: Optional[float] = None
        phase = 0  # 0 = hands off, 1 = terminated, 2 = killed
        while len(codes) < len(procs):
            for rank, p in procs.items():
                if rank in codes:
                    continue
                rc = p.poll()
                if rc is None:
                    continue
                codes[rank] = rc
                if rc != 0 and breaking_t is None:
                    breaking_t = time.monotonic()
            if len(codes) == len(procs):
                break
            if breaking_t is not None and phase < 2:
                overdue = time.monotonic() - breaking_t
                want = 1 if overdue > self.grace_s else 0
                if overdue > 2 * self.grace_s:
                    want = 2
                if want > phase:
                    phase = want
                    for r2, p2 in procs.items():
                        if r2 not in codes and p2.poll() is None:
                            try:
                                if phase == 1:
                                    p2.terminate()
                                else:
                                    p2.kill()
                            except OSError:
                                pass
            time.sleep(self.poll_s)
        for p in procs.values():
            p.wait()  # reap
        return codes

    @staticmethod
    def _attempt_reason(codes: Dict[int, int]) -> str:
        kinds = {r: classify_exit(rc) for r, rc in codes.items()}
        if all(k == "ok" for k in kinds.values()):
            return "ok"
        if any(k == "peer_lost" for k in kinds.values()):
            return "peer_lost"
        if any(k.startswith(("crash", "signal")) for k in kinds.values()):
            return "crash"
        return "preempt_drain"

    # -- the loop ---------------------------------------------------------
    def run(self) -> GangResult:
        """Run the gang to clean completion, relaunching through
        failures until the restart budget runs out (GangFailedError,
        per-attempt exit codes attached).  No orphans survive this
        call."""
        attempts: List[Dict[str, Any]] = []
        failures = 0
        procs: Dict[int, subprocess.Popen] = {}
        try:
            for attempt in range(self.max_restarts + 1):
                attempt_t0 = time.monotonic()
                procs = self._spawn_gang(attempt)
                try:
                    codes = self._wait_gang(procs)
                finally:
                    self._stop_service()
                reason = self._attempt_reason(codes)
                rec = {"attempt": attempt,
                       "exit_codes": dict(sorted(codes.items())),
                       "classified": {r: classify_exit(rc)
                                      for r, rc in sorted(codes.items())},
                       "reason": reason,
                       # attempt wall clock: a broken attempt's whole
                       # duration is restart badput from the job's
                       # point of view (the goodput ledger inside each
                       # worker decomposes the useful part)
                       "duration_s": round(
                           time.monotonic() - attempt_t0, 3)}
                if self.elastic and reason != "ok":
                    # signal deaths = lost capacity (preempted machine);
                    # the next attempt runs with the survivors only and
                    # workers reshard their checkpoints to the new size
                    lost = [r for r, rc in codes.items()
                            if classify_exit(rc).startswith("signal")]
                    new_n = max(1, self.num_workers - len(lost))
                    if new_n != self.num_workers:
                        rec["shrunk_to"] = new_n
                        self.num_workers = new_n
                attempts.append(rec)
                if self.event_log is not None:
                    self.event_log.event(
                        "gang_restart" if reason != "ok" else "gang_end",
                        **rec)
                if reason == "ok":
                    return GangResult(attempts)
                if attempt == self.max_restarts:
                    break
                if reason == "preempt_drain":
                    delay = 0.0  # ckpt landed; resume immediately
                else:
                    delay = min(self.backoff_base_s * (2.0 ** failures),
                                self.backoff_max_s)
                    failures += 1
                self.backoffs_slept.append(delay)
                if delay > 0:
                    self.sleep(delay)
        finally:
            # no-orphans guarantee, however run() exits
            for p in procs.values():
                if p.poll() is None:
                    try:
                        p.kill()
                        p.wait(timeout=10)
                    except (OSError, subprocess.TimeoutExpired):
                        pass
            self._stop_service()
            for f in self._log_files:
                try:
                    f.close()
                except OSError:
                    pass
            self._log_files = []
        err = GangFailedError(
            f"gang failed after {len(attempts)} attempt(s) "
            f"({self.max_restarts} restart budget): last attempt "
            f"exit codes {attempts[-1]['exit_codes']}",
            attempts=attempts, num_workers=self.num_workers,
            max_restarts=self.max_restarts)
        if self.event_log is not None:
            self.event_log.event("gang_failed", **err.as_dict())
        raise err


def launch_gang(worker_cmd, num_workers: int, **kw) -> GangResult:
    """One-call form: Supervisor(...).run()."""
    return Supervisor(worker_cmd, num_workers, **kw).run()
