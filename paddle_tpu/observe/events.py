"""Structured run events: an append-only JSONL log with provenance.

Every training/bench run gets a run-id + git-sha + backend/mesh stamp
and a stream of typed event records (telemetry windows, checkpoints,
compile storms) — the artifact a dashboards/alerting layer tails, and
the provenance stamp tools/run_ab.py uses to keep mixed-run A/B
artifacts auditable.  One JSON object per line; the file is valid to
tail mid-run (each line is flushed whole).
"""

from __future__ import annotations

import json
import os
import sys
import time
import uuid
import warnings
from typing import Any, Dict, List, Optional


# well-known serving event kinds (paddle_tpu.serving emits these; a
# dashboard tailing an event log can filter on them)
SERVING_EVENTS = (
    "serving_start",                # engine config at start()
    "serving_memory_plan",          # pre-warmup bucket-ladder fit plan
    #                                 (observe.memory probe prediction)
    "serving_warmup",               # bucket-ladder precompile summary
    "serving_window",               # periodic stats snapshot
    "serving_compile_post_warmup",  # LOUD: a shape leaked past buckets
    "serving_drain",                # final snapshot at drain
    "serving_breaker_open",         # LOUD: executor failure burst —
    #                                 admission flipped to DEGRADED
    "serving_breaker_close",        # half-open probe succeeded; RUNNING
    "serving_reload",               # hot weight swap applied (version,
    #                                 pause_ms) — ISSUE 15 straggler:
    #                                 emitted since PR 14, unregistered
)

# continuous-batching decode event kinds (docs/SERVING.md §decode) —
# the ISSUE 15 registry-enforcement sweep flushed these out: every one
# had been emitted since PR 12 without a registry entry, exactly the
# silent-typo rot the hang_kind collision (PR 9) showed
DECODE_EVENTS = (
    "serving_decode_start",        # engine geometry at start()
    "serving_decode_memory_plan",  # plan_fit gate verdict pre-warmup
    "serving_decode_warmup",       # executable precompile summary
    "serving_decode_window",       # periodic DecodeStats snapshot
    "serving_decode_drain",        # final snapshot at drain
    "serving_decode_preempt",      # a slot was evicted (pool dry)
    "serving_decode_evacuate",     # requests pulled off the replica
    #                                (weight roll / scheduler death)
    "serving_decode_reload",       # hot weight swap applied
)

# speculative-decoding event kinds (docs/SERVING.md §speculate): the
# multi-token verified-step path DecodeEngine(speculate_k=k) runs
SPECULATE_EVENTS = (
    "serving_decode_speculate",   # drafter armed at start(): k +
    #                               drafter class (the one-line record
    #                               that says THIS replica speculates)
    "serving_speculate_window",   # periodic speculation snapshot:
    #                               accept_rate, accept_hist,
    #                               speculation_efficiency
)

# serving-fleet event kinds (docs/SERVING.md §fleet): the router layer
# fronting N engine replicas.  Every record carries replica_id where
# one replica is the subject (engines stamp their own events with it
# too, via RunEventLog.bind — N replicas sharing one log stay
# disambiguated).
FLEET_EVENTS = (
    "serving_fleet_start",     # fleet config at start(): kind, replicas
    "serving_fleet_failover",  # LOUD: an in-flight request was pulled
    #                            off a replica and requeued on a
    #                            survivor (committed-token count rides)
    "serving_fleet_eject",     # LOUD: a replica was removed from
    #                            routing (scheduler death / manual)
    "serving_fleet_hedge",     # a slow attempt got a duplicate on
    #                            another replica (idempotent only)
    "serving_fleet_saturated", # LOUD: every replica fast-rejected —
    #                            the structured whole-fleet shed
    "serving_fleet_reload",    # one roll: begin/done phases + version
    "serving_fleet_reload_replica",  # per-replica swap: pause_ms,
    #                                  evacuated count
    "serving_fleet_window",    # periodic fleet-merged stats snapshot
    "serving_fleet_close",     # final merged snapshot at close
)

# disaggregated prefill/decode serving event kinds (docs/SERVING.md
# §disagg): the phase router, the KV-page handoff, and the
# SLO-driven autoscaler's decisions
DISAGG_EVENTS = (
    "serving_disagg_start",       # fleet topology at start()
    "serving_disagg_handoff",     # one KV-page hop: from_replica ->
    #                               to_replica, pages, bytes, handoff_ms
    "serving_disagg_failover",    # LOUD: a worker died mid-request —
    #                               the raw prompt re-prefills on a
    #                               survivor (phase + committed tokens)
    "serving_disagg_eject",       # LOUD: a worker removed from routing
    "serving_disagg_saturated",   # LOUD: one phase's workers all shed
    "serving_disagg_worker_join",  # zero-reject scale-up landed
    "serving_disagg_worker_leave", # zero-reject scale-down retired one
    "serving_disagg_window",      # periodic merged stats snapshot
    "serving_disagg_close",       # final snapshot at close
    "kv_transfer",                # also the router-row reqtrace span
    #                               name (registered for grep parity)
    "autoscale_up",               # Autoscaler added a worker: phase,
    #                               rule, observed value
    "autoscale_down",             # Autoscaler removed one after quiet_s
)

# resilience event kinds (docs/RESILIENCE.md): checkpoint fallback,
# save telemetry, and preemption-drain lifecycle, emitted by
# contrib.Trainer / the chaos CI smoke
RESILIENCE_EVENTS = (
    "ckpt_fallback",        # a serial was skipped (torn/corrupt), with
    #                         the structured CheckpointError as_dict()
    "ckpt_resume",          # resumed; fallback=True when not newest
    "ckpt_resume_failed",   # NO valid serial existed — fresh start
    "ckpt_save",            # one save: snapshot_ms (blocking) vs
    #                         write_ms (background) + bytes + async flag
    "ckpt_async_error",     # LOUD: a background write failed (the
    #                         structured CheckpointWriteError as_dict())
    "preempt_drain",        # SIGTERM/SIGINT received: finishing the
    #                         in-flight step, then emergency-saving
    "ckpt_emergency",       # the drain path's final checkpoint landed
)

# divergence-autopilot event kinds (docs/RESILIENCE.md §autopilot):
# the anomaly-triggered rollback-and-replay loop contrib.Trainer runs
# when built with autopilot= (resilience/autopilot.py)
RECOVERY_EVENTS = (
    "recovery_rollback",  # LOUD: in-process rollback to the newest
    #                       verified-good serial (trigger signal,
    #                       from/to cursor, budget state attached)
    "data_quarantine",    # the poisoned batch window the replay will
    #                       fast-forward past (never re-trained)
    "recovery_halt",      # LOUD: rollback budget exhausted (or no
    #                       verified-good serial) — train() raises
    #                       TrainingDivergedError after this record
)

# input-pipeline resilience event kinds (data/pipeline.py DeviceFeeder
# hardening + Trainer(validate_feed=True) admission checks)
FEED_EVENTS = (
    "feeder_retry",       # transient producer error: bounded
    #                       backoff retry (attempt, produced count)
    "feeder_stall",       # LOUD: the producer starved the queue past
    #                       stall_timeout_s — queue depth attached,
    #                       instead of the loop blocking silently
    "feed_quarantined",   # admission rejected a poisoned batch
    #                       (non-finite / signature drift) before any
    #                       device_put was spent on it
)

# gang fault-tolerance event kinds (docs/RESILIENCE.md, distributed
# failure model): health-plane detections, the dispatch watchdog's
# pre-abort record, straggler telemetry, and the supervisor lifecycle
GANG_EVENTS = (
    "peer_lost",       # LOUD: a peer stopped heartbeating (or the KV
    #                    store died with the coordinator); missing
    #                    ranks + staleness age attached
    "peer_stalled",    # a peer heartbeats but its step counter froze
    "step_hang",       # dispatch watchdog: a step blew its budget —
    #                    emitted BEFORE the abort, with the
    #                    first-compile vs hung-step verdict and the
    #                    runtime_stats deltas observed in the region
    "gang_skew",       # periodic per-rank step/step-rate snapshot
    #                    from heartbeat timestamps (straggler
    #                    telemetry before real multi-chip exists)
    "rank_slow",       # LOUD: one rank's step rate lags the gang
    #                    median by more than the slow factor
    "gang_start",      # supervisor: one gang attempt spawned
    "gang_restart",    # supervisor: attempt ended broken; relaunching
    "gang_end",        # supervisor: attempt ended clean
    "gang_failed",     # LOUD: restart budget exhausted — per-attempt
    #                    exit codes attached
)


# goodput observability event kinds (docs/OBSERVE.md pillar 8):
# the wall-clock decomposition contrib.Trainer emits at train_end
GOODPUT_EVENTS = (
    "goodput_report",  # the full GoodputLedger.report() dict: wall_s,
    #                    per-category seconds/fractions (Σ == wall),
    #                    goodput fraction, replay badput, effective_mfu
)


# alerting event kinds (docs/OBSERVE.md pillar 9): the AlertEngine's
# rule state-machine transitions — the records a pager/dashboard keys
# off, so the kinds are registered AND prefix-validated (an unknown
# alert_* kind is exactly the typo class this registry exists for)
ALERT_EVENTS = (
    "alert_pending",   # a rule breached; for_duration gating running
    "alert_firing",    # LOUD: the breach persisted — the rule fired
    #                    (value/target/severity attached; the
    #                    FlightRecorder bundles on this transition)
    "alert_resolved",  # the firing rule cleared (hysteresis +
    #                    resolve_duration satisfied)
)

# flight-recorder event kinds (docs/OBSERVE.md pillar 9)
FLIGHT_EVENTS = (
    "flight_record",   # one diagnostic bundle written: reason, path,
    #                    truncation flag, per-section errors
)


# numerics observability event kinds (docs/OBSERVE.md pillar 6):
# emitted by contrib.Trainer next to its telemetry windows
NUMERICS_EVENTS = (
    "nonfinite_provenance",  # LOUD: a telemetry window latched a
    #                          poisoned step — carries the joined
    #                          first_nonfinite_op (fluid op type/index/
    #                          group), the guard's skip counter and the
    #                          loss scale, so a skipped update is
    #                          attributable without re-running anything
)


# ---------------------------------------------------------------------------
# Event-kind validation (ISSUE 15 satellite): a dashboard's filter is a
# string match, so a typo'd kind silently drops off every chart — the
# PR 9 hang_kind-vs-kind collision class.  Kinds under the dashboard
# prefixes are validated against the registries above: warn by default,
# raise under tests (strict).
# ---------------------------------------------------------------------------

_VALIDATED_PREFIXES = ("serving_", "fleet_", "gang_", "alert_",
                       "flight_", "autoscale_", "recovery_",
                       "feeder_", "feed_")
_KNOWN_KINDS = set(SERVING_EVENTS) | set(DECODE_EVENTS) \
    | set(FLEET_EVENTS) | set(GANG_EVENTS) | set(RESILIENCE_EVENTS) \
    | set(NUMERICS_EVENTS) | set(GOODPUT_EVENTS) | set(ALERT_EVENTS) \
    | set(FLIGHT_EVENTS) | set(DISAGG_EVENTS) | set(RECOVERY_EVENTS) \
    | set(FEED_EVENTS) | set(SPECULATE_EVENTS)
_strict_kinds = [False]
_warned_kinds: set = set()


def set_strict_kinds(flag: bool) -> bool:
    """Unknown validated-prefix kinds raise instead of warning.
    Returns the previous setting (tests flip and restore); the
    PADDLE_TPU_STRICT_EVENTS env var also enables it."""
    prev = _strict_kinds[0]
    _strict_kinds[0] = bool(flag)
    return prev


def register_event_kinds(*kinds: str) -> None:
    """Extend the known-kind registry (a subsystem adding a new
    dashboard event registers it here — or in the tuples above when it
    ships in-tree)."""
    _KNOWN_KINDS.update(kinds)


def _validate_kind(kind: str) -> None:
    if not kind.startswith(_VALIDATED_PREFIXES) \
            or kind in _KNOWN_KINDS:
        return
    msg = (f"event kind {kind!r} matches a dashboard prefix "
           f"{_VALIDATED_PREFIXES} but is not registered in "
           f"observe.events (SERVING/DECODE/FLEET/GANG registries) — "
           f"a typo here silently drops the event off every dashboard "
           f"filter; register it with register_event_kinds() or fix "
           f"the name")
    if _strict_kinds[0] or os.environ.get("PADDLE_TPU_STRICT_EVENTS"):
        raise ValueError(msg)
    if kind not in _warned_kinds:
        _warned_kinds.add(kind)
        warnings.warn(msg, stacklevel=3)


def new_run_id() -> str:
    """Short unique id for one run/invocation (12 hex chars)."""
    return uuid.uuid4().hex[:12]


def git_sha(cwd: Optional[str] = None) -> Optional[str]:
    """Current git HEAD (short), or None outside a repo / without git."""
    import subprocess

    try:
        r = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                           capture_output=True, text=True, timeout=5,
                           cwd=cwd)
    except (OSError, subprocess.SubprocessError):
        return None
    sha = r.stdout.strip()
    return sha if r.returncode == 0 and sha else None


def _backend_info() -> Dict[str, Any]:
    """Backend/device provenance WITHOUT forcing backend init: only
    reports when jax is already imported and initialized (events logs
    must stay usable from pure-host tools like run_ab)."""
    if "jax" not in sys.modules:
        return {}
    try:
        import jax

        devs = jax.devices()
        return {"backend": jax.default_backend(),
                "n_devices": len(devs),
                "device_kind": devs[0].device_kind if devs else None}
    except Exception:  # noqa: BLE001 — a dead backend must not kill logging
        return {}


class RunEventLog:
    """Append-only JSONL event log for one run.

        with RunEventLog("events.jsonl", mesh_shape={"dp": 8}) as log:
            log.event("checkpoint", serial=3)
            log.telemetry_window(tel, window=10)

    Records carry {ts (unix seconds), run_id, event, ...fields}.  The
    first record is `run_begin` with run provenance (git sha, backend,
    mesh); `close()` appends `run_end`.

    `max_bytes`: size-bound the log for long gang/serving runs (they
    append JSONL unbounded otherwise).  When the file would exceed the
    bound it rolls to `<path>.1` (one generation kept, the classic
    rotate) and the fresh file starts with a `run_rotate` record so a
    tailer knows records continue from a rolled file.  Rotation happens
    under the same write lock as every record (the PR 7 thread-locked
    path), so concurrent background-writer events never interleave or
    land in a half-rotated file.
    """

    def __init__(self, path: str, run_id: Optional[str] = None,
                 mesh_shape: Optional[Dict[str, int]] = None,
                 meta: Optional[Dict[str, Any]] = None,
                 max_bytes: Optional[int] = None):
        if max_bytes is not None and int(max_bytes) < 1024:
            raise ValueError("max_bytes < 1024 would rotate on nearly "
                             "every record")
        self.path = path
        self.run_id = run_id or new_run_id()
        self.max_bytes = None if max_bytes is None else int(max_bytes)
        self.rotations = 0
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._bytes = os.path.getsize(path)
        # async checkpoint writers emit ckpt_save from their background
        # thread; serialize record writes so lines never interleave
        import threading

        self._wlock = threading.Lock()
        begin: Dict[str, Any] = {"git_sha": git_sha(),
                                 "argv": list(sys.argv)}
        begin.update(_backend_info())
        if mesh_shape:
            begin["mesh_shape"] = dict(mesh_shape)
        if meta:
            begin.update(meta)
        self.event("run_begin", **begin)

    def _write_locked(self, rec: Dict[str, Any]) -> None:
        """Write one record; caller holds the lock."""
        line = json.dumps(rec, default=_jsonable) + "\n"
        if (self.max_bytes is not None
                and self._bytes + len(line) > self.max_bytes
                and self._bytes > 0):
            self._f.close()
            os.replace(self.path, self.path + ".1")
            self._f = open(self.path, "a", encoding="utf-8")
            self._bytes = 0
            self.rotations += 1
            marker = json.dumps(
                {"ts": round(time.time(), 3), "run_id": self.run_id,
                 "event": "run_rotate", "rotations": self.rotations,
                 "rolled_to": self.path + ".1"},
                default=_jsonable) + "\n"
            self._f.write(marker)
            self._bytes += len(marker)
        self._f.write(line)
        self._f.flush()
        self._bytes += len(line)

    def event(self, kind: str, **fields: Any) -> Dict[str, Any]:
        """Append one event record (flushed immediately).  Kinds under
        the dashboard prefixes (serving_/fleet_/gang_) are validated
        against the registries at the top of this module — warn by
        default, raise under strict mode (tests)."""
        _validate_kind(kind)
        rec = {"ts": round(time.time(), 3), "run_id": self.run_id,
               "event": kind}
        rec.update(fields)
        with self._wlock:
            self._write_locked(rec)
        return rec

    def telemetry_window(self, telemetry, **extra: Any) -> Dict[str, Any]:
        """Emit one periodic-fetch window (a StepTelemetry or plain
        dict) plus any runtime-stats fields the caller attaches."""
        fields = (telemetry.as_dict() if hasattr(telemetry, "as_dict")
                  else dict(telemetry))
        fields.update(extra)
        return self.event("telemetry", **fields)

    def serving_window(self, stats, **extra: Any) -> Dict[str, Any]:
        """Emit one serving stats snapshot (a serving.ServingStats or a
        plain dict) — the serving analog of telemetry_window."""
        fields = (stats.snapshot() if hasattr(stats, "snapshot")
                  else dict(stats))
        fields.update(extra)
        return self.event("serving_window", **fields)

    def bind(self, **fields: Any) -> "BoundEventLog":
        """A view over this log that stamps `fields` (e.g. replica_id)
        into every record it emits — the way N serving-engine replicas
        share ONE process log without their events becoming
        indistinguishable.  The view shares the file, write lock, and
        run_id; closing the view is a no-op (the owner closes the
        base)."""
        return BoundEventLog(self, fields)

    def close(self):
        if not self._f.closed:
            self.event("run_end")
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class BoundEventLog:
    """RunEventLog view with fixed fields merged into every record
    (see RunEventLog.bind).  Explicit per-event fields win on key
    collision.  Safe to re-bind (views nest by merging)."""

    def __init__(self, base: RunEventLog, fields: Dict[str, Any]):
        while isinstance(base, BoundEventLog):
            fields = {**base._fields, **fields}
            base = base._base
        self._base = base
        self._fields = dict(fields)

    @property
    def run_id(self) -> str:
        return self._base.run_id

    @property
    def path(self) -> str:
        return self._base.path

    def bind(self, **fields: Any) -> "BoundEventLog":
        return BoundEventLog(self, fields)

    def event(self, kind: str, **fields: Any) -> Dict[str, Any]:
        return self._base.event(kind, **{**self._fields, **fields})

    def telemetry_window(self, telemetry, **extra: Any) -> Dict[str, Any]:
        fields = (telemetry.as_dict() if hasattr(telemetry, "as_dict")
                  else dict(telemetry))
        fields.update(extra)
        return self.event("telemetry", **fields)

    def serving_window(self, stats, **extra: Any) -> Dict[str, Any]:
        fields = (stats.snapshot() if hasattr(stats, "snapshot")
                  else dict(stats))
        fields.update(extra)
        return self.event("serving_window", **fields)

    def close(self):
        """No-op: the view does not own the underlying file."""


def _jsonable(v):
    import numpy as np

    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.ndarray):
        return v.tolist()
    return str(v)


def read_events(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL event log back into records.  Raises on corrupt
    lines — an event log that silently drops records is worse than one
    that fails loudly (a torn final line from a killed process is the
    one tolerated exception)."""
    out: List[Dict[str, Any]] = []
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    for i, ln in enumerate(lines):
        if not ln.strip():
            continue
        try:
            out.append(json.loads(ln))
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                break  # torn tail from a killed writer
            raise
    return out
