"""Ring attention + Ulysses vs full single-device attention on the
virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.ring_attention import (ring_attention,
                                                ulysses_attention)


def _full_attention(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("nhqd,nhkd->nhqk", q, k).astype(jnp.float32) * d ** -0.5
    if causal:
        t = s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((t, t), bool)), s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("nhqk,nhkd->nhqd", p.astype(q.dtype), v)


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.RandomState(0)
    n, h, t, d = 2, 8, 64, 16
    mk = lambda: jnp.asarray(rng.randn(n, h, t, d), jnp.float32) * 0.5
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(qkv, causal):
    q, k, v = qkv
    mesh = make_mesh({"sp": 8})
    got = ring_attention(q, k, v, mesh, axis="sp", causal=causal)
    want = _full_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(qkv, causal):
    q, k, v = qkv
    mesh = make_mesh({"sp": 8})
    got = ulysses_attention(q, k, v, mesh, axis="sp", causal=causal)
    want = _full_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_differentiable(qkv):
    q, k, v = qkv
    mesh = make_mesh({"sp": 8})

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(_full_attention(q, k, v, True) ** 2)

    g_ring = jax.grad(loss_ring)(q, k, v)
    g_full = jax.grad(loss_full)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full),
                               rtol=2e-3, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_pallas_chunks(qkv, causal):
    """Ring attention with each rotated chunk through the Pallas flash
    kernel (interpret mode on CPU), incl. grads through the lse merge."""
    q, k, v = qkv
    mesh = make_mesh({"sp": 8})
    got = ring_attention(q, k, v, mesh, axis="sp", causal=causal,
                         use_pallas=True)
    want = _full_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-4)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=causal,
                                      use_pallas=True) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(_full_attention(q, k, v, causal) ** 2)

    g_ring = jax.grad(loss_ring)(q, k, v)
    g_full = jax.grad(loss_full)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full),
                               rtol=5e-3, atol=5e-4)


def test_ulysses_rejects_bad_heads(qkv):
    q, k, v = qkv
    mesh = make_mesh({"sp": 8})
    with pytest.raises(ValueError):
        ulysses_attention(q[:, :3], k[:, :3], v[:, :3], mesh)


def test_sequence_parallel_flash_in_fluid_program():
    """layers.flash_attention(sequence_parallel=True) inside a
    CompiledProgram over an sp mesh: the fluid program's attention runs
    as ring attention (KV ppermute rotation) and the TRAINING
    trajectory matches the unsharded program exactly."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    N, H, T, D = 2, 2, 32, 8

    def run(mesh):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 3
        scope = fluid.Scope()
        losses = []
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), fluid.unique_name.guard():
            x = fluid.layers.data("x", shape=[N, T, H * D],
                                  append_batch_size=False)
            qkv = layers.fc(x, size=3 * H * D, num_flatten_dims=2,
                            bias_attr=False, name="attn_qkv")
            r = layers.reshape(qkv, shape=[0, 0, H, 3 * D])
            r = layers.transpose(r, perm=[0, 2, 1, 3])
            q = layers.slice(r, axes=[3], starts=[0], ends=[D])
            k = layers.slice(r, axes=[3], starts=[D], ends=[2 * D])
            v = layers.slice(r, axes=[3], starts=[2 * D],
                             ends=[3 * D])
            att = layers.flash_attention(q, k, v, causal=True,
                                         sequence_parallel=True)
            loss = layers.reduce_mean(layers.square(att))
            fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
            exe = fluid.Executor()
            exe.run(startup)
            prog = main
            if mesh is not None:
                prog = fluid.CompiledProgram(main).with_data_parallel(
                    loss_name=loss.name, mesh=mesh)
            feed = {"x": np.random.RandomState(0)
                    .randn(N, T, H * D).astype(np.float32)}
            for _ in range(3):
                (lv,) = exe.run(prog, feed=feed, fetch_list=[loss])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
        return losses

    sp = run(make_mesh({"sp": 8}))
    single = run(None)
    assert all(np.isfinite(sp))
    assert sp[-1] < sp[0]
    np.testing.assert_allclose(sp, single, rtol=1e-4, atol=1e-6)


def test_sequence_parallel_ulysses_in_fluid_program():
    """sequence_parallel="ulysses": head/sequence all-to-all strategy
    from the fluid surface, parity with the unsharded program (H=8
    divides sp=8)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    N, H, T, D = 2, 8, 32, 4

    def run(mesh):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 6
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), fluid.unique_name.guard():
            q = fluid.layers.data("q", shape=[N, H, T, D],
                                  append_batch_size=False)
            att = layers.flash_attention(
                q, q, q, causal=True, sequence_parallel="ulysses")
            loss = layers.reduce_mean(layers.square(att))
            exe = fluid.Executor()
            exe.run(startup)
            prog = main
            if mesh is not None:
                prog = fluid.CompiledProgram(main).with_data_parallel(
                    loss_name=None, build_strategy=None, mesh=mesh)
            feed = {"q": np.random.RandomState(2)
                    .randn(N, H, T, D).astype(np.float32)}
            (lv,) = exe.run(prog, feed=feed, fetch_list=[loss])
            return float(np.asarray(lv).reshape(-1)[0])

    u = run(make_mesh({"sp": 8}))
    ref = run(None)
    np.testing.assert_allclose(u, ref, rtol=1e-5)


def test_sequence_parallel_flash_rejects_bias():
    """sequence_parallel + additive Bias must fail loudly (ring path
    supports causal masking only)."""
    import pytest

    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        q = fluid.layers.data("q", shape=[2, 2, 16, 4],
                              append_batch_size=False)
        bias = fluid.layers.data("b", shape=[2, 1, 16, 16],
                                 append_batch_size=False)
        o = layers.flash_attention(q, q, q, bias=bias, causal=True,
                                   sequence_parallel=True)
        loss = layers.reduce_mean(o)
        exe = fluid.Executor()
        exe.run(startup)
        prog = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=None, mesh=make_mesh({"sp": 8}))
        rng = np.random.RandomState(1)
        with pytest.raises(Exception, match="sequence_parallel"):
            exe.run(prog,
                    feed={"q": rng.rand(2, 2, 16, 4).astype(np.float32),
                          "b": np.zeros((2, 1, 16, 16), np.float32)},
                    fetch_list=[loss])
