"""Watchdog + retry: deadline-guarded compile/dispatch and bounded
exponential-backoff retries.

Generalizes bench.py's two hard-won lessons into reusable machinery:

- backend init can HANG, not just error (r03: driver rc=124 with no
  JSON line) — so `probe_backend` runs the init + one tiny matmul in a
  SUBPROCESS with a hard timeout; an in-process try/except never fires
  on a hang,
- a hung XLA compile/dispatch must become a recorded error, not eat
  the caller's whole budget — `Deadline` is the SIGALRM watchdog
  bench.py wrapped each model in, now shared by bench, contrib.Trainer
  (`step_deadline_s`) and `ServingEngine.start()` (warmup deadline).

SIGALRM only exists on the main thread: off the main thread `Deadline`
degrades to a no-op (recorded on the instance) rather than failing —
a watchdog must never be the thing that crashes the worker.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Sequence, Tuple, Type

from .errors import RetriesExhaustedError, WatchdogTimeout


class Deadline:
    """Wall-clock watchdog around a region: raises `WatchdogTimeout`
    (with the region name in `details`) when the body exceeds
    `seconds`.  Best-effort — a C call that never re-enters the
    interpreter cannot be interrupted; `seconds <= 0` disables."""

    def __init__(self, seconds: float, what: str = "guarded region"):
        self.seconds = float(seconds)
        self.what = what
        self.armed = False
        self._old = None

    def __enter__(self):
        import signal

        if self.seconds <= 0:
            return self
        if threading.current_thread() is not threading.main_thread():
            return self  # SIGALRM is main-thread-only; degrade to no-op

        def _fire(signum, frame):
            raise WatchdogTimeout(
                f"{self.what} exceeded {self.seconds:.0f}s deadline",
                what=self.what, deadline_s=self.seconds)

        self._old = signal.signal(signal.SIGALRM, _fire)
        # SIGALRM takes whole seconds; round up so Deadline(0.5) fires
        signal.alarm(max(1, int(-(-self.seconds // 1))))
        self.armed = True
        return self

    def __exit__(self, *exc):
        import signal

        if self.armed:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, self._old)
            self.armed = False
        return False


def probe_backend(timeout_s: float,
                  platform_env: str = "BENCH_PLATFORM") -> Optional[str]:
    """Fail-fast backend health check: init the backend and run one
    tiny matmul in a SUBPROCESS with a hard timeout.  Returns None when
    healthy, else a short failure description (hang vs error is
    distinguished).  `platform_env` names the env var whose value, if
    set, pins jax_platforms inside the probe (the sitecustomize stomps
    JAX_PLATFORMS, so only the config route works)."""
    import os
    import subprocess
    import sys

    code = ("import os, jax;"
            f"plat = os.environ.get({platform_env!r});"
            "plat and jax.config.update('jax_platforms', plat);"
            "import jax.numpy as jnp;"
            "d = jax.devices();"
            "x = jnp.ones((128, 128), jnp.bfloat16);"
            "(x @ x).block_until_ready();"
            "print('BACKEND_OK', d[0].device_kind)")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s, env=dict(os.environ))
    except subprocess.TimeoutExpired:
        return (f"backend init did not complete within {timeout_s:.0f}s "
                f"(hang, not error)")
    if r.returncode != 0 or "BACKEND_OK" not in r.stdout:
        tail = (r.stderr or r.stdout).strip().splitlines()[-3:]
        return "backend init failed: " + " | ".join(tail)
    return None


def retry_call(fn: Callable, *, retries: int = 3,
               base_delay_s: float = 0.5, max_delay_s: float = 30.0,
               retry_on: Tuple[Type[BaseException], ...]
               = (Exception,),
               on_retry: Optional[Callable[[int, BaseException, float],
                                           None]] = None,
               sleep: Callable[[float], None] = time.sleep):
    """Call `fn()` with up to `retries` re-attempts on transient
    failure, sleeping base_delay_s * 2**attempt (capped) between
    attempts — deterministic backoff so tests can assert the schedule
    via an injected `sleep`.  `on_retry(attempt, exc, delay_s)` is the
    observation hook.  Raises `RetriesExhaustedError` (chaining the
    final error) when every attempt fails; non-retryable exceptions
    propagate immediately."""
    if retries < 0:
        raise ValueError("retries must be >= 0")
    last: Optional[BaseException] = None
    for attempt in range(retries + 1):
        try:
            return fn()
        except retry_on as exc:  # noqa: PERF203 — retry loop
            last = exc
            if attempt == retries:
                break
            delay = min(base_delay_s * (2.0 ** attempt), max_delay_s)
            if on_retry is not None:
                on_retry(attempt, exc, delay)
            sleep(delay)
    raise RetriesExhaustedError(
        f"{retries + 1} attempt(s) failed; last error: {last}",
        attempts=retries + 1, last_error=f"{type(last).__name__}: {last}"
    ) from last
