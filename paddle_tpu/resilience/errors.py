"""Structured error hierarchy for the resilience subsystem.

Every failure the subsystem handles — a corrupt checkpoint shard, a
torn save, a hung compile, exhausted retries — surfaces as a typed
exception carrying a machine-readable `details` dict (`as_dict()`),
mirroring the serving-side `ServingError` contract: a recovery layer
(Trainer fallback, CI chaos smoke, an alerting dashboard) dispatches
on `kind`, never by parsing message strings.
"""

from __future__ import annotations

from typing import Any, Dict


class ResilienceError(RuntimeError):
    """Base for structured resilience failures."""

    kind = "resilience_error"

    def __init__(self, message: str, **details: Any):
        super().__init__(message)
        self.details = details

    def as_dict(self) -> Dict[str, Any]:
        out = {"error": self.kind, "message": str(self)}
        out.update(self.details)
        return out


# ---------------------------------------------------------------------------
# Checkpoint integrity (io.py save_sharded/load_sharded, contrib.Trainer)
# ---------------------------------------------------------------------------

class CheckpointError(ResilienceError):
    """Base for checkpoint load/save failures.  `details` always carries
    the checkpoint `dirname`; Trainer attaches the `serial` it was
    attempting so a `ckpt_fallback` event names what it skipped."""

    kind = "checkpoint_error"


class CheckpointNotFoundError(CheckpointError):
    """No manifest at the expected path: the directory is not a
    (complete) checkpoint.  A save that died between shard write and
    manifest write lands here — the manifest is written LAST, so a torn
    checkpoint is indistinguishable from no checkpoint (by design)."""

    kind = "checkpoint_not_found"


class CheckpointCorruptError(CheckpointError):
    """The checkpoint exists but its content fails verification: a
    shard CRC32 mismatch, an unreadable/truncated shard container, a
    manifest or trainer-state file that is not valid JSON."""

    kind = "checkpoint_corrupt"


class CheckpointIncompleteError(CheckpointError):
    """The manifest references shard files/keys that are missing, or
    the present shards do not cover a requested slice."""

    kind = "checkpoint_incomplete"


class CheckpointFormatError(CheckpointError):
    """The checkpoint was written by an incompatible (newer) program
    format version."""

    kind = "checkpoint_format"


class CheckpointWriteError(CheckpointError):
    """An asynchronous checkpoint write failed in the background writer
    thread.  Raised on the NEXT save/close/wait — never swallowed: a
    training run whose checkpoints silently stopped landing has no
    recovery story the day it is preempted.  `details` carries the
    original error and the dirname of the save that failed."""

    kind = "checkpoint_write_failed"


class CheckpointBarrierTimeoutError(CheckpointError):
    """A cross-process checkpoint barrier did not complete within its
    timeout — some peer died (or wedged) inside a sharded save.
    `details` names the barrier `tag`, the `timeout_s`, and
    `missing_ranks`: the process indices that never arrived (empty when
    the runtime cannot attribute ranks — see io._barrier fallback)."""

    kind = "checkpoint_barrier_timeout"


class CheckpointBarrierPoisonedError(CheckpointBarrierTimeoutError):
    """A checkpoint barrier aborted EARLY because the gang's poison key
    was set — some peer (or its health monitor) already declared the
    gang broken, so waiting out the full barrier timeout would only
    delay the restart.  `details` carries everything the parent class
    does plus `poison`: the structured poison payload (origin rank,
    reason, kind) and `elapsed_s`, the bounded time actually spent."""

    kind = "checkpoint_barrier_poisoned"


class CheckpointStateMismatchError(CheckpointError):
    """The checkpoint's recorded build state (generated-name counters,
    train_state schema) does not match the resuming process's build —
    loading would silently bind saved arrays to the WRONG variables.
    Raised loudly instead; `details` names the first divergence.  The
    classic cause: the resuming program was built outside
    `unique_name.guard()` (CLAUDE.md gotcha)."""

    kind = "checkpoint_state_mismatch"


# ---------------------------------------------------------------------------
# Preemption (resilience/preempt.py, contrib.Trainer drain path)
# ---------------------------------------------------------------------------

class TrainingPreempted(ResilienceError):
    """The training loop drained after a preemption signal (SIGTERM/
    SIGINT, or an injected `request_drain`): the in-flight step
    finished, an emergency checkpoint was written, and the run must now
    exit with `exit_code` (resilience.preempt.PREEMPT_EXIT_CODE) so the
    scheduler can tell a drained exit from a crash.  `details` carries
    the drain reason and the emergency checkpoint serial (None when no
    checkpoint_config was active)."""

    kind = "training_preempted"

    @property
    def exit_code(self) -> int:
        return int(self.details.get("exit_code", 1))


# ---------------------------------------------------------------------------
# Divergence autopilot (resilience/autopilot.py, contrib.Trainer)
# ---------------------------------------------------------------------------

class TrainingDivergedError(ResilienceError):
    """The divergence autopilot halted training deliberately: its
    rollback budget is exhausted (or no verified-good checkpoint
    existed to roll back to), so continuing would only skip updates
    forever.  `details` carries the full provenance a post-mortem
    needs without re-running anything: the `trigger` (signal name,
    skip streak / z-score, the latched first_nonfinite_op), the
    rollback count vs `budget`, every quarantined data window, and
    `flight_bundle` — the FlightRecorder bundle path when a recorder
    was attached (None otherwise)."""

    kind = "training_diverged"


# ---------------------------------------------------------------------------
# Watchdog / retry (resilience/watchdog.py)
# ---------------------------------------------------------------------------

class WatchdogTimeout(ResilienceError):
    """A deadline-guarded region (compile, dispatch, warmup) exceeded
    its wall-clock budget.  `message` has a default because the
    timer-thread Deadline fallback raises this via
    PyThreadState_SetAsyncExc, which instantiates the CLASS with no
    arguments (CPython rejects pre-built instances there)."""

    kind = "watchdog_timeout"

    def __init__(self, message: str = "watchdog deadline exceeded",
                 **details: Any):
        super().__init__(message, **details)


class StepHangError(WatchdogTimeout):
    """The dispatch watchdog's verdict on a timed-out training step:
    a `step_hang` event was emitted first, then this.  `details.kind`
    distinguishes `first_compile` (no dispatch had ever completed —
    the long compile-grace budget applied and STILL ran out) from
    `hung_step` (a previously-working step stopped returning: the
    hung-collective signature), plus the runtime_stats deltas observed
    inside the region (compiles/dispatches/retraces)."""

    kind = "step_hang"


class RetriesExhaustedError(ResilienceError):
    """A retried operation failed on every attempt; `details` carries
    the attempt count and the final error."""

    kind = "retries_exhausted"


# ---------------------------------------------------------------------------
# Gang fault tolerance (resilience/health.py, resilience/supervisor.py)
# ---------------------------------------------------------------------------

class GangError(ResilienceError):
    """Base for distributed-gang failures: a peer died or wedged, the
    gang was poisoned, or the supervisor exhausted its restart budget.
    Workers translate any GangError into PEER_LOST_EXIT_CODE so the
    supervisor can tell a coordinated abort from a plain crash."""

    kind = "gang_error"


class PeerLostError(GangError):
    """A peer rank stopped heartbeating (process death, SIGKILL, host
    loss) — or the KV store itself became unreachable, which on this
    runtime means the coordinator process (rank 0) died.  `details`
    carries `missing_ranks`, the staleness `age_s` at detection, and
    the configured `budget_s` window."""

    kind = "peer_lost"


class PeerStalledError(GangError):
    """A peer is still heartbeating (process alive) but its step
    counter has not advanced within the stall timeout — the
    hung-inside-a-collective signature.  `details` names the
    `stalled_ranks`, their last `step`, and the `stall_timeout_s`."""

    kind = "peer_stalled"


class GangPoisonedError(GangError):
    """This rank read the gang poison key: some OTHER rank (or its
    health monitor / dispatch watchdog) declared the gang broken.
    Every rank checking the key between steps is what turns one
    failure into a bounded-time gang-wide abort instead of a hang in
    the next all-reduce.  `details.poison` is the origin's payload
    (origin rank, reason, kind, missing_ranks)."""

    kind = "gang_poisoned"


class GangFailedError(GangError):
    """The supervisor exhausted its restart budget: every attempt's
    per-rank exit codes (and their classification) are in
    `details.attempts` — the post-mortem artifact."""

    kind = "gang_failed"
