"""Pallas flash attention vs composed XLA reference (interpret mode on
CPU; the same kernel runs compiled on TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _ref_attention(q, k, v, bias=None, scale=None, causal=False):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("nhqd,nhkd->nhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((t_q, t_k), bool)), s, -1e30)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
    return jnp.einsum("nhqk,nhkd->nhqd", p.astype(q.dtype), v)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    import paddle_tpu.ops.pallas.flash_attention as fa

    rng = np.random.RandomState(0)
    n, h, t, d = 1, 2, 256, 128
    q = jnp.asarray(rng.randn(n, h, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(n, h, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(n, h, t, d), jnp.float32)
    got = _interpreted(fa, q, k, v, None, None, causal)
    want = _ref_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_flash_padding_bias():
    import paddle_tpu.ops.pallas.flash_attention as fa

    rng = np.random.RandomState(1)
    n, h, t, d = 2, 1, 128, 128
    q = jnp.asarray(rng.randn(n, h, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(n, h, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(n, h, t, d), jnp.float32)
    lens = np.array([96, 128])
    bias = np.zeros((n, 1, 1, t), np.float32)
    for i, L in enumerate(lens):
        bias[i, :, :, L:] = -1e9
    bias = jnp.asarray(bias)
    got = _interpreted(fa, q, k, v, bias, None, False)
    want = _ref_attention(q, k, v, bias=bias)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("t,causal", [(320, False), (384, True), (320, True)])
def test_flash_nondivisible_tk(t, causal):
    """Regression: t_k % block_k != 0 must mask the padded k-tail
    (ADVICE.md round-1 high finding)."""
    import paddle_tpu.ops.pallas.flash_attention as fa

    rng = np.random.RandomState(3)
    n, h, d = 1, 2, 128
    q = jnp.asarray(rng.randn(n, h, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(n, h, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(n, h, t, d), jnp.float32)
    got = _interpreted(fa, q, k, v, None, None, causal, block_k=256)
    want = _ref_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_flash_grad_matches_reference():
    import paddle_tpu.ops.pallas.flash_attention as fa

    rng = np.random.RandomState(2)
    n, h, t, d = 1, 1, 128, 128
    q = jnp.asarray(rng.randn(n, h, t, d), jnp.float32) * 0.5
    k = jnp.asarray(rng.randn(n, h, t, d), jnp.float32) * 0.5
    v = jnp.asarray(rng.randn(n, h, t, d), jnp.float32) * 0.5

    def loss_flash(q, k, v):
        return jnp.sum(_interpreted(fa, q, k, v, None, None, False) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref_attention(q, k, v) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3)


@pytest.mark.parametrize("t,causal,with_bias",
                         [(320, True, False), (320, False, True),
                          (256, True, True)])
def test_flash_bwd_kernel_edge_cases(t, causal, with_bias):
    """Tiled Pallas backward: non-divisible lengths, causal masking and
    bias gradients must all match the XLA composition."""
    import paddle_tpu.ops.pallas.flash_attention as fa

    rng = np.random.RandomState(7)
    n, h, d = 1, 2, 128
    q = jnp.asarray(rng.randn(n, h, t, d), jnp.float32) * 0.3
    k = jnp.asarray(rng.randn(n, h, t, d), jnp.float32) * 0.3
    v = jnp.asarray(rng.randn(n, h, t, d), jnp.float32) * 0.3
    bias = None
    if with_bias:
        b = np.zeros((n, 1, 1, t), np.float32)
        b[:, :, :, t - 32:] = -1e9
        bias = jnp.asarray(b)

    def loss_flash(q, k, v):
        o = _interpreted(fa, q, k, v, bias, None, causal, block_q=128,
                         block_k=256)
        return jnp.sum(o ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref_attention(q, k, v, bias=bias,
                                      causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3,
                                   err_msg=f"d{name} mismatch")


def test_flash_bwd_bias_grad():
    """db must equal the XLA-composed bias gradient (per-batch additive
    key bias, summed over heads and q)."""
    import paddle_tpu.ops.pallas.flash_attention as fa

    rng = np.random.RandomState(8)
    n, h, t, d = 2, 2, 128, 128
    q = jnp.asarray(rng.randn(n, h, t, d), jnp.float32) * 0.3
    k = jnp.asarray(rng.randn(n, h, t, d), jnp.float32) * 0.3
    v = jnp.asarray(rng.randn(n, h, t, d), jnp.float32) * 0.3
    bias0 = jnp.asarray(rng.randn(n, 1, 1, t).astype(np.float32)) * 0.1

    def loss_flash(b):
        return jnp.sum(_interpreted(fa, q, k, v, b, None, False) ** 2)

    def loss_ref(b):
        return jnp.sum(_ref_attention(q, k, v, bias=b) ** 2)

    db_flash = jax.grad(loss_flash)(bias0)
    db_ref = jax.grad(loss_ref)(bias0)
    np.testing.assert_allclose(np.asarray(db_flash), np.asarray(db_ref),
                               rtol=5e-3, atol=5e-3)


# -- helpers ---------------------------------------------------------------


def _interpreted(fa, q, k, v, bias, scale, causal, **kw_extra):
    """On the CPU backend the module auto-selects Pallas interpret mode
    (flash_attention._interpret), so this just calls through."""
    return fa.pallas_flash_attention(q, k, v, bias=bias, scale=scale,
                                     causal=causal, **kw_extra)


def test_transformer_flash_pallas_matches_xla_flash():
    """build_model(flash_pallas=True) — the full NMT transformer
    training through the tiled Pallas kernel (decoder self-attn uses
    in-kernel causal masking + key-padding bias) — tracks the XLA-flash
    trajectory."""
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer

    def run(pallas):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 11
        scope = fluid.Scope()
        losses = []
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), fluid.unique_name.guard():
            m = transformer.build_model(
                src_vocab_size=64, trg_vocab_size=64, max_length=8,
                n_layer=1, n_head=2, d_model=16, d_inner_hid=32,
                dropout=0.0, use_flash=True, flash_pallas=pallas)
            exe = fluid.Executor()
            exe.run(startup)
            feed = transformer.make_fake_batch(4, 8, 60, 60)
            for _ in range(3):
                lv, = exe.run(main, feed=feed, fetch_list=[m["loss"]])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
        return losses

    pallas = run(True)
    xla = run(False)
    assert pallas[-1] < pallas[0]
    np.testing.assert_allclose(pallas, xla, rtol=2e-3, atol=2e-4)
