"""High-level contrib APIs (reference: python/paddle/fluid/contrib/)."""

from .trainer import (BeginEpochEvent, BeginStepEvent,  # noqa: F401
                      CheckpointConfig, EndEpochEvent, EndStepEvent,
                      Inferencer, Trainer)
