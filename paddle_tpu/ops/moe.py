"""Mixture-of-Experts FFN with expert parallelism (GShard / Switch
Transformer routing).

The reference tree (Fluid 1.2) predates MoE; this op exists because
expert parallelism is a first-class scale axis on TPU meshes (ep in
dp/tp/pp/sp/ep).  TPU-first design, not a port: routing, dispatch and
combine are dense einsums over a static expert-capacity buffer — no
dynamic shapes, no scatter — so GSPMD shards the expert dimension over
the mesh's `ep`/`mp` axis and inserts the all-to-alls itself (the
standard GShard lowering; see PAPERS.md GShard/Switch entries for the
published formulation).

Routing (top-1 "switch" or top-2):
- gate logits (B, E) from X @ GateW; probs = softmax
- per-expert capacity C = ceil(B * top_k / E * capacity_factor);
  tokens beyond an expert's capacity are DROPPED (their combine weight
  is zero and the residual path carries them — the Switch convention);
  top-2 combine weights are the GShard normalization p_i / (p1 + p2)
- position of each token in its expert's buffer = exclusive cumsum of
  the dispatch mask (deterministic, order-preserving)
- dispatch: (B, E, C) one-hot plan; expert_in = dispatchᵀ @ X
- experts: per-expert 2-layer FFN as batched einsums (E in the batch
  dim -> one MXU matmul per projection across ALL experts)
- combine: out = Σ_ec gate_prob * dispatch * expert_out

AuxLoss is the Switch load-balancing loss: E * Σ_e (fraction of tokens
routed to e) * (mean router prob of e); add `aux_weight * AuxLoss` to
the training objective to keep routing balanced.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first, opt_in, out


def _act(name):
    return {"relu": jax.nn.relu, "gelu": jax.nn.gelu,
            "tanh": jnp.tanh, "identity": lambda v: v,
            None: jax.nn.relu}[name]


@register_op("moe_ffn")
def moe_ffn(ctx, ins, attrs):
    """X (..., D); GateW (D, E); W1 (E, D, H); B1 (E, H); W2 (E, H, D);
    B2 (E, D).  Outputs Out (..., D), AuxLoss (1,), plus router stats
    (Fraction (E,) tokens-per-expert) for observability."""
    x = first(ins, "X")
    gate_w = first(ins, "GateW")
    w1, b1 = first(ins, "W1"), opt_in(ins, "B1")
    w2, b2 = first(ins, "W2"), opt_in(ins, "B2")
    top_k = int(attrs.get("top_k", 1))
    cap_factor = float(attrs.get("capacity_factor", 1.25))
    act = _act(attrs.get("act", "relu"))
    if top_k not in (1, 2):
        raise ValueError(f"moe_ffn: top_k must be 1 or 2, got {top_k}")
    if top_k > gate_w.shape[1]:
        raise ValueError(
            f"moe_ffn: top_k={top_k} needs at least that many experts, "
            f"got E={gate_w.shape[1]} (the second pass would re-route "
            f"to the same expert)")

    lead = x.shape[:-1]
    d = x.shape[-1]
    e = gate_w.shape[1]
    xf = x.reshape(-1, d)
    b = xf.shape[0]

    # GShard GROUPED formulation: tokens split into G groups with
    # per-group capacity.  G=1 reproduces the ungrouped Switch layout;
    # on a mesh with an `ep` axis G = ep so the group dim shards over
    # ep and the dispatch/combine einsums lower to the GShard
    # all-to-alls (pinned by tests/test_moe.py HLO assertion) instead
    # of all-gathering the dispatch tensor.  Capacity is then per
    # GROUP (C = ceil(B/G * k / E * cf)) — the published GShard
    # semantics.
    ectx = None
    try:
        from ..parallel.mesh import get_exec_context

        ectx = get_exec_context()
    except ImportError:  # pragma: no cover
        pass
    g = 1
    ep_ax = mp_ax = batch_ax = None
    if ectx is not None:
        mesh = ectx.mesh
        if mesh.shape.get("ep", 1) > 1:
            g = mesh.shape["ep"]
            ep_ax = "ep"
            if mesh.shape.get("mp", 1) > 1:
                mp_ax = "mp"
            if mesh.shape.get(ectx.batch_axis, 1) > 1:
                batch_ax = ectx.batch_axis
    if b % g != 0:
        raise ValueError(
            f"moe_ffn on an ep={g} mesh needs the token count ({b}) "
            f"divisible by ep (per-group GShard capacity)")
    bg = b // g
    # C = ceil(B/G * top_k / E * capacity_factor)
    import math

    cap = max(1, int(math.ceil(bg * top_k / e * cap_factor)))

    def wsc(v, *spec):
        if ep_ax is None:
            return v
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.lax.with_sharding_constraint(
            v, NamedSharding(mesh, P(*spec)))

    xg = wsc(xf.reshape(g, bg, d), ep_ax, batch_ax, None)
    logits = jnp.einsum("gbd,de->gbe", xg, gate_w).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)          # (G, Bg, E)

    combine = jnp.zeros((g, bg, e, cap), xf.dtype)
    dispatch = jnp.zeros((g, bg, e, cap), xf.dtype)
    used = jnp.zeros((g, bg, e), bool)
    fill = jnp.zeros((g, e), jnp.float32)  # slots taken by earlier k's
    for k in range(top_k):
        masked = jnp.where(used, -jnp.inf, logits)
        idx = jnp.argmax(masked, axis=-1)            # (G, Bg)
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)
        # deterministic position in the expert buffer (token order
        # WITHIN the group), offset by slots earlier k's already filled
        pos = (jnp.cumsum(onehot, axis=1) - onehot)  # exclusive
        pos = jnp.sum((pos + fill[:, None, :]) * onehot, axis=-1)
        fill = fill + jnp.sum(onehot, axis=1)
        fits = pos < cap                              # (G, Bg)
        gate = jnp.sum(probs * onehot, axis=-1)       # (G, Bg)
        pos_oh = jax.nn.one_hot(
            jnp.where(fits, pos, 0).astype(jnp.int32), cap,
            dtype=jnp.float32)
        # dispatch derives from the ROUTING plan (chosen expert & a
        # fitting slot), not from the gate-weighted combine tensor: a
        # token whose softmax prob underflows to exactly 0.0 still
        # occupies its slot (contributing 0 to the output) instead of
        # silently freeing capacity
        plan_mask = (onehot[..., None] * pos_oh[..., None, :]
                     * fits.astype(jnp.float32)[..., None, None])
        dispatch = dispatch + plan_mask.astype(xf.dtype)
        combine = combine + (plan_mask
                             * gate[..., None, None]).astype(xf.dtype)
        used = used | (onehot > 0)

    if top_k == 2:
        # GShard top-2 normalization: divide by the prob mass of the
        # CHOSEN experts (p1 + p2) so the pair's weights sum to 1; a
        # capacity-dropped choice simply vanishes, leaving the kept
        # expert at p_kept/(p1+p2) — never amplified
        chosen = jnp.sum(probs * used, axis=-1)[..., None, None]
        combine = combine / jnp.maximum(chosen, 1e-9).astype(
            combine.dtype)

    dispatch = wsc(dispatch, ep_ax, batch_ax, None, None)
    combine = wsc(combine, ep_ax, batch_ax, None, None)
    # dispatch all-to-all: (G over ep, ...) -> (E over ep, G, ...)
    expert_in = wsc(jnp.einsum("gbec,gbd->egcd", dispatch, xg),
                    ep_ax, None, None, None)
    h = act(jnp.einsum("egcd,edh->egch", expert_in, w1)
            + (b1[:, None, None, :] if b1 is not None else 0.0))
    h = wsc(h, ep_ax, None, None, mp_ax)
    expert_out = (jnp.einsum("egch,ehd->egcd", h, w2)
                  + (b2[:, None, None, :] if b2 is not None else 0.0))
    expert_out = wsc(expert_out, ep_ax, None, None, None)
    # combine all-to-all: back to (G over ep, Bg, D)
    yf = wsc(jnp.einsum("gbec,egcd->gbd", combine, expert_out),
             ep_ax, batch_ax, None)
    yf = yf.reshape(b, d)

    # Switch load-balancing loss on the top-1 assignment (global stats)
    top1 = jax.nn.one_hot(jnp.argmax(logits, axis=-1), e,
                          dtype=jnp.float32)
    fraction = jnp.mean(top1, axis=(0, 1))           # (E,)
    mean_prob = jnp.mean(probs, axis=(0, 1))
    aux = e * jnp.sum(fraction * mean_prob)

    return {"Out": [yf.reshape(lead + (d,))],
            "AuxLoss": [aux.reshape(1)],
            "Fraction": [fraction]}
