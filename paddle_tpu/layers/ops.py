"""Mechanically-generated layer wrappers.

reference: python/paddle/fluid/layers/ops.py — fluid autogenerates layer
functions from registered OpProtos via layer_function_generator.py; we do
the same from the op registry for single-input/single-output ops.
"""

from __future__ import annotations

from ..layer_helper import LayerHelper

_UNARY_OPS = [
    "sigmoid", "logsigmoid", "exp", "tanh", "tanh_shrink", "softshrink",
    "sqrt", "rsqrt", "abs", "ceil", "floor", "cos", "sin", "round",
    "reciprocal", "log", "square", "softplus", "softsign", "relu",
    "soft_relu", "elu", "relu6", "leaky_relu", "brelu", "stanh",
    "hard_sigmoid", "swish", "gelu", "hard_shrink", "thresholded_relu",
    "selu", "sign", "log_softmax", "logical_not",
]


def _make_unary(op_type: str):
    def layer(x, name=None, **attrs):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(type=op_type, inputs={"X": [x]},
                         outputs={"Out": [out]}, attrs=attrs)
        return out

    layer.__name__ = op_type
    layer.__doc__ = f"{op_type} activation (see ops registry)."
    return layer


_this = globals()
for _op in _UNARY_OPS:
    _this[_op] = _make_unary(_op)

# pow collides with builtin name in fluid too; expose both spellings
_this["pow"] = _make_unary("pow")

__all__ = _UNARY_OPS + ["pow"]
