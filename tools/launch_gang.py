#!/usr/bin/env python
"""CLI for the self-healing gang supervisor (docs/RESILIENCE.md).

    python tools/launch_gang.py --nproc 2 --max-restarts 3 \
        -- python my_train.py --ckpt ckpts/

Spawns the worker command once per rank with the PADDLE_TRAINER_ID /
PADDLE_TRAINERS / PADDLE_COORDINATOR env contract
`parallel.init_distributed` reads (fresh coordinator port per
attempt), translates the exit-code registry (0 ok, 77 preempt-drain,
43 peer-lost, signals), kills the remainder of a broken gang within
`--grace-s`, and relaunches on the deterministic backoff schedule
until the restart budget runs out.  Workers are expected to resume
from their newest valid checkpoint themselves (contrib.Trainer does).

Prints one `GANG_ATTEMPT {json}` line per attempt and a final
`GANG_RESULT {json}` (or `GANG_FAILED {json}`); exits 0 on clean gang
completion, 1 on budget exhaustion.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from paddle_tpu.resilience import GangFailedError  # noqa: E402
from paddle_tpu.resilience.supervisor import Supervisor  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--nproc", type=int, default=2,
                    help="gang size (ranks)")
    ap.add_argument("--max-restarts", type=int, default=None,
                    help="relaunch budget (default FLAGS."
                         "supervisor_max_restarts)")
    ap.add_argument("--grace-s", type=float, default=None,
                    help="SIGTERM->SIGKILL grace for a broken gang's "
                         "survivors (default FLAGS.supervisor_grace_s)")
    ap.add_argument("--backoff-base-s", type=float, default=None)
    ap.add_argument("--backoff-max-s", type=float, default=None)
    ap.add_argument("--log-dir", default=None,
                    help="per-rank stdout/stderr capture directory "
                         "(default: inherit)")
    ap.add_argument("--host-coordinator", action="store_true",
                    help="host the jax coordination service in the "
                         "supervisor (fresh service per attempt) so "
                         "even rank 0 is killable with structured "
                         "detection by the survivors")
    ap.add_argument("--elastic", action="store_true",
                    help="relaunch a broken gang at the SURVIVING "
                         "world size: ranks killed by signal are "
                         "treated as lost capacity; workers read the "
                         "shrunken PADDLE_TRAINERS and reshard their "
                         "sharded checkpoints onto the smaller mesh "
                         "(io.load_sharded is mesh-shape-agnostic; "
                         "docs/DIST.md §hybrid)")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="worker command (prefix with --)")
    args = ap.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no worker command given (append: -- python worker.py)")

    sup = Supervisor(cmd, args.nproc, max_restarts=args.max_restarts,
                     grace_s=args.grace_s,
                     backoff_base_s=args.backoff_base_s,
                     backoff_max_s=args.backoff_max_s,
                     log_dir=args.log_dir,
                     host_coordinator=args.host_coordinator,
                     elastic=args.elastic)
    try:
        result = sup.run()
    except GangFailedError as e:
        for a in e.details["attempts"]:
            print("GANG_ATTEMPT " + json.dumps(a), flush=True)
        print("GANG_FAILED " + json.dumps(e.as_dict()), flush=True)
        return 1
    for a in result.attempts:
        print("GANG_ATTEMPT " + json.dumps(a), flush=True)
    print("GANG_RESULT " + json.dumps(result.as_dict()), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
