"""Pallas fused LSTM recurrence kernel — parity against the scan path
(ISSUE 5 tentpole).

The kernel runs through the Pallas INTERPRETER on the CPU backend
(tests/test_pallas_lowering.py separately proves the Mosaic lowering),
so these tests pin numerics: forward AND gradients must match the
lax.scan reference in ops/rnn.py bit-for-bit semantics-wise —
including seq_len masking (state freezes past each row's end),
is_reverse, and initial states — and the unsupported configurations
(peepholes, non-default activations, nested lod2 inputs) must be
rejected LOUDLY, never silently mis-computed.

Also pins the cheap scan-side lever: `unroll=K` is a scheduling hint,
so dynamic_lstm / dynamic_gru / lstmp outputs must be BIT-identical
to unroll=1.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import OpContext, get_op_impl

from op_test import run_op


def R(seed):
    return np.random.RandomState(seed)


N, T, H = 3, 10, 4  # T deliberately NOT a multiple of the time block


def _lstm_ins(seed=0, with_states=False, with_seq_len=False,
              peephole_bias=False):
    r = R(seed)
    h4 = 4 * H
    ins = {
        "Input": (r.randn(N, T, h4) * 0.3).astype(np.float32),
        "Weight": (r.randn(H, h4) * 0.3).astype(np.float32),
        "Bias": (r.randn(1, 7 * H if peephole_bias else h4)
                 * 0.3).astype(np.float32),
    }
    if with_states:
        ins["H0"] = (r.randn(N, H) * 0.3).astype(np.float32)
        ins["C0"] = (r.randn(N, H) * 0.3).astype(np.float32)
    if with_seq_len:
        ins["SeqLen"] = np.array([T, T - 4, 3], np.int32)
    return ins


def _run_lstm(ins, attrs, slots=("Hidden", "Cell", "LastH", "LastC")):
    impl = get_op_impl("dynamic_lstm")
    jins = {s: [jnp.asarray(a)] for s, a in ins.items()}
    out = impl(OpContext(jax.random.PRNGKey(0), 0), jins, dict(attrs))
    return {s: np.asarray(out[s][0]) for s in slots}


@pytest.mark.parametrize("with_states", [False, True])
@pytest.mark.parametrize("with_seq_len", [False, True])
@pytest.mark.parametrize("is_reverse", [False, True])
def test_forward_matches_scan(with_states, with_seq_len, is_reverse):
    ins = _lstm_ins(seed=7, with_states=with_states,
                    with_seq_len=with_seq_len)
    base = {"use_peepholes": False, "is_reverse": is_reverse}
    ref = _run_lstm(ins, base)
    got = _run_lstm(ins, {**base, "use_pallas": True})
    for slot in ref:
        np.testing.assert_allclose(
            got[slot], ref[slot], rtol=2e-5, atol=2e-6,
            err_msg=f"{slot} (states={with_states}, "
                    f"seq_len={with_seq_len}, reverse={is_reverse})")


@pytest.mark.parametrize("with_seq_len", [False, True])
@pytest.mark.parametrize("is_reverse", [False, True])
def test_grad_matches_scan(with_seq_len, is_reverse):
    """Analytic-vs-analytic: jax.grad through the kernel's custom VJP
    must equal jax.grad through the scan reference, for every
    differentiable input, under a loss that weights Hidden AND Cell
    (and the last states) so no gradient path is vacuously zero."""
    ins = _lstm_ins(seed=11, with_states=True,
                    with_seq_len=with_seq_len)
    impl = get_op_impl("dynamic_lstm")
    slots = ["Input", "Weight", "Bias", "H0", "C0"]

    def loss_fn(use_pallas):
        def f(*vals):
            jins = {s: [v] for s, v in zip(slots, vals)}
            if with_seq_len:
                jins["SeqLen"] = [jnp.asarray(ins["SeqLen"])]
            out = impl(OpContext(jax.random.PRNGKey(0), 0), jins,
                       {"use_peepholes": False,
                        "is_reverse": is_reverse,
                        "use_pallas": use_pallas})
            hs, cs = out["Hidden"][0], out["Cell"][0]
            k1 = jnp.cos(jnp.arange(hs.size, dtype=jnp.float32)
                         .reshape(hs.shape) * 0.1)
            k2 = jnp.sin(jnp.arange(cs.size, dtype=jnp.float32)
                         .reshape(cs.shape) * 0.07)
            return (jnp.sum(hs * k1) + jnp.sum(cs * k2)
                    + 0.5 * jnp.sum(out["LastH"][0])
                    + 0.25 * jnp.sum(out["LastC"][0]))
        return f

    vals = tuple(jnp.asarray(ins[s]) for s in slots)
    argnums = tuple(range(len(slots)))
    g_ref = jax.grad(loss_fn(False), argnums=argnums)(*vals)
    g_pal = jax.grad(loss_fn(True), argnums=argnums)(*vals)
    for slot, a, b in zip(slots, g_pal, g_ref):
        np.testing.assert_allclose(
            a, b, rtol=3e-5, atol=3e-6,
            err_msg=f"d{slot} (seq_len={with_seq_len}, "
                    f"reverse={is_reverse})")


def test_rejects_peepholes_loudly():
    ins = _lstm_ins(seed=3, peephole_bias=True)
    with pytest.raises(ValueError, match="peephole"):
        _run_lstm(ins, {"use_peepholes": True, "use_pallas": True})


def test_rejects_nonstandard_activations_loudly():
    ins = _lstm_ins(seed=4)
    with pytest.raises(ValueError, match="activation"):
        _run_lstm(ins, {"use_peepholes": False, "use_pallas": True,
                        "gate_activation": "relu"})


def test_rejects_nested_lod2_loudly():
    ins = _lstm_ins(seed=5)
    ins["SeqLen"] = np.array([T, T, T], np.int32)
    ins["SeqLen2"] = np.full((N, T), 1, np.int32)
    with pytest.raises(NotImplementedError, match="nested"):
        _run_lstm(ins, {"use_peepholes": False, "use_pallas": True})


def test_fused_lstm_direct_rejections():
    from paddle_tpu.ops.pallas.recurrence import fused_lstm

    x = jnp.zeros((2, 4, 4 * H), jnp.float32)
    w = jnp.zeros((H, 4 * H), jnp.float32)
    with pytest.raises(ValueError, match="peephole"):
        fused_lstm(x, w, use_peepholes=True)
    with pytest.raises(ValueError, match="activation"):
        fused_lstm(x, w, cell_activation="relu")
    with pytest.raises(ValueError, match="4\\*H"):
        fused_lstm(jnp.zeros((2, 4, 13), jnp.float32), w)


# -- scan-path unroll: a scheduling knob, never a numerics knob ------------
#
# `unroll=K` traces the IDENTICAL step function K times per while
# iteration — the math is the same by construction.  XLA:CPU is then
# free to FMA-contract / schedule the unrolled bodies differently,
# which was MEASURED to move results by at most one ulp (4.5e-8 at
# these magnitudes; most elements stay bit-identical).  The assert
# pins exactly that: same values up to 1 ulp, with zero tolerance for
# any real numeric drift that would mean the lever changed semantics.

_ULP = 1.2e-7  # one f32 ulp at magnitude ~1 (tanh-bounded outputs)


def _assert_unroll_equiv(base, unr, what):
    np.testing.assert_allclose(
        unr, base, rtol=0, atol=_ULP,
        err_msg=f"{what}: unroll changed numerics beyond backend "
                f"scheduling (1 ulp)")


def test_dynamic_lstm_unroll_equivalent():
    ins = _lstm_ins(seed=21, with_seq_len=True)
    base = run_op("dynamic_lstm", ins, {"use_peepholes": False},
                  "Hidden")
    for k in (2, 3, 8):
        unr = run_op("dynamic_lstm", ins,
                     {"use_peepholes": False, "unroll": k}, "Hidden")
        _assert_unroll_equiv(base, unr, f"dynamic_lstm unroll={k}")


def test_dynamic_gru_unroll_equivalent():
    r = R(22)
    ins = {"Input": (r.randn(2, 7, 3 * H) * 0.3).astype(np.float32),
           "Weight": (r.randn(H, 3 * H) * 0.3).astype(np.float32)}
    base = run_op("dynamic_gru", ins, {}, "Hidden")
    unr = run_op("dynamic_gru", ins, {"unroll": 4}, "Hidden")
    _assert_unroll_equiv(base, unr, "dynamic_gru unroll=4")


def test_lstmp_unroll_equivalent():
    r = R(23)
    ins = {"Input": (r.randn(2, 6, 4 * H) * 0.3).astype(np.float32),
           "Weight": (r.randn(3, 4 * H) * 0.3).astype(np.float32),
           "ProjWeight": (r.randn(H, 3) * 0.3).astype(np.float32)}
    base = run_op("lstmp", ins, {}, "Projection")
    unr = run_op("lstmp", ins, {"unroll": 5}, "Projection")
    _assert_unroll_equiv(base, unr, "lstmp unroll=5")


# -- kernel cost registry (observe/cost.py injection contract) -------------

def test_lstm_kernel_costs_registered():
    from paddle_tpu.ops import pallas as pallas_pkg
    from paddle_tpu.ops.pallas import recurrence  # noqa: F401

    assert {"lstm_fwd", "lstm_bwd"} <= set(pallas_pkg.KERNEL_COSTS)
    # dense-equivalent: the recurrent GEMM dominates — T*(2*N*H*4H)
    xs = ((T, N, 4 * H), 4)
    flops, nbytes = pallas_pkg.KERNEL_COSTS["lstm_fwd"](
        [xs, ((H, 4 * H), 4)], [((T, N, H), 4)])
    assert flops >= T * 2 * N * H * 4 * H
    assert nbytes is None  # default materialized-buffers model
    bflops, _ = pallas_pkg.KERNEL_COSTS["lstm_bwd"](
        [xs, ((H, 4 * H), 4)], [xs])
    assert bflops >= 2 * flops * 0.9  # bwd = two gemms vs fwd's one
