"""Model/checkpoint IO.

reference: python/paddle/fluid/io.py — save_vars:89, save_params:222,
save_persistables:270, load_vars:313, load_params, load_persistables,
save_inference_model:570, load_inference_model:704.  The reference
implements save/load as `save`/`load_combine` *ops* appended to throwaway
programs; here persistence is host-side (numpy container + JSON manifest
with program-format versioning) since checkpoint IO is not a TPU
computation.  Sharded arrays gather transparently via np.asarray; a
tensorstore/orbax-style sharded writer can slot in behind the same API.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence

import numpy as np

from .core.desc import (PROGRAM_FORMAT_VERSION, dump_program_dict,
                        load_program_dict)
from .core.executor import Executor, Scope, global_scope
from .core.program import Parameter, Program, Variable

MODEL_FILENAME = "__model__"
MANIFEST = "__manifest__.json"
# serialized AOT inference artifact (written by inference.py)
EXPORT_FILENAME = "__model__.export"


def _is_parameter(var: Variable) -> bool:
    return isinstance(var, Parameter)


def _collect(program: Program, predicate) -> List[Variable]:
    return [v for v in program.list_vars() if predicate(v)]


def save_vars(executor: Executor, dirname: str,
              main_program: Optional[Program] = None,
              vars: Optional[Sequence[Variable]] = None,
              predicate=None, filename: Optional[str] = None):
    """Persist variables from the scope (reference io.py:89)."""
    from .core.program import default_main_program

    program = main_program or default_main_program()
    if vars is None:
        vars = _collect(program, predicate or (lambda v: v.persistable))
    scope = global_scope()
    os.makedirs(dirname, exist_ok=True)
    arrays = {}
    names = []
    for v in vars:
        val = scope.find_var(v.name)
        if val is None:
            raise RuntimeError(f"variable {v.name!r} has no value in scope")
        arrays[v.name] = np.asarray(val)
        names.append(v.name)
    fname = filename or "params.npz"
    np.savez(os.path.join(dirname, fname), **arrays)
    manifest = {
        "version": PROGRAM_FORMAT_VERSION,
        "file": fname,
        "vars": names,
    }
    with open(os.path.join(dirname, MANIFEST), "w") as f:
        json.dump(manifest, f, indent=1)


def save_params(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=_is_parameter, filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=lambda v: v.persistable, filename=filename)


def load_vars(executor: Executor, dirname: str,
              main_program: Optional[Program] = None,
              vars: Optional[Sequence[Variable]] = None,
              predicate=None, filename: Optional[str] = None):
    from .core.program import default_main_program

    program = main_program or default_main_program()
    if vars is None:
        vars = _collect(program, predicate or (lambda v: v.persistable))
    with open(os.path.join(dirname, MANIFEST)) as f:
        manifest = json.load(f)
    if manifest.get("version", 0) > PROGRAM_FORMAT_VERSION:
        raise RuntimeError("checkpoint written by a newer format version")
    data = np.load(os.path.join(dirname, filename or manifest["file"]))
    scope = global_scope()
    import jax.numpy as jnp

    for v in vars:
        if v.name not in data:
            raise RuntimeError(f"checkpoint missing variable {v.name!r}")
        arr = data[v.name]
        if tuple(arr.shape) != tuple(v.shape) and -1 not in v.shape:
            raise RuntimeError(
                f"shape mismatch for {v.name!r}: checkpoint "
                f"{arr.shape} vs program {v.shape}")
        scope.set_var(v.name, jnp.asarray(arr))


def load_params(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program,
                     predicate=_is_parameter, filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program,
                     predicate=lambda v: v.persistable, filename=filename)


# ---------------------------------------------------------------------------
# Inference export
# ---------------------------------------------------------------------------

def save_inference_model(dirname: str, feeded_var_names: Sequence[str],
                         target_vars: Sequence[Variable],
                         executor: Executor,
                         main_program: Optional[Program] = None,
                         model_filename: Optional[str] = None,
                         params_filename: Optional[str] = None):
    """Prune to the inference subgraph and export (reference io.py:570):
    writes `__model__` (serialized program) + params."""
    from .core.executor import prune_ops
    from .core.program import default_main_program

    program = (main_program or default_main_program()).clone(for_test=True)
    fetch_names = [t.name for t in target_vars]

    # prune ops to fetch ancestors, then drop unused vars
    program._backward_info = None
    kept_ops = prune_ops(program, fetch_names)
    block = program.global_block()
    block.ops = list(kept_ops)
    used = set(fetch_names) | set(feeded_var_names)
    for op in block.ops:
        used.update(op.desc.input_names())
        used.update(op.desc.output_names())
    block.vars = {n: v for n, v in block.vars.items() if n in used}

    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, model_filename or MODEL_FILENAME),
              "w") as f:
        d = program.to_dict()
        d["feed_var_names"] = list(feeded_var_names)
        d["fetch_var_names"] = fetch_names
        f.write(dump_program_dict(d))
    # a re-saved model invalidates any serialized AOT artifact exported
    # from the previous one (inference.py also hash-checks as a belt)
    for stale in (EXPORT_FILENAME, EXPORT_FILENAME + ".json"):
        p = os.path.join(dirname, stale)
        if os.path.exists(p):
            os.remove(p)
    params = [v for v in program.list_vars() if v.persistable]
    save_vars(executor, dirname, program, vars=params,
              filename=params_filename)
    return fetch_names


def load_inference_model(dirname: str, executor: Executor,
                         model_filename: Optional[str] = None,
                         params_filename: Optional[str] = None):
    """reference io.py:704 — returns (program, feed_names, fetch_vars)."""
    with open(os.path.join(dirname, model_filename or MODEL_FILENAME)) as f:
        d = load_program_dict(f.read())
    program = Program.from_dict(d)
    load_vars(executor, dirname, program,
              predicate=lambda v: v.persistable, filename=params_filename)
    fetch_vars = [program.global_block().var(n)
                  for n in d.get("fetch_var_names", [])]
    return program, d.get("feed_var_names", []), fetch_vars
