"""Recommender system (two-tower matrix factorization + MLP).

reference: python/paddle/fluid/tests/book/test_recommender_system.py —
user tower (user id / gender / age / job embeddings) and movie tower
(movie id embedding + title sequence conv-pool), cosine-scored and
regressed onto the rating.  The reference's LoD title sequence becomes
the padded + seq_len form."""

from __future__ import annotations

import numpy as np

from .. import layers, optimizer
from ..param_attr import ParamAttr


def build_model(user_vocab=500, gender_vocab=2, age_vocab=7, job_vocab=21,
                movie_vocab=800, title_vocab=1000, title_len=12,
                embed_dim=32, batch_size=32, learning_rate=5e-3,
                with_optimizer=True):
    B = batch_size

    def emb_feature(name, vocab):
        ids = layers.data(name, shape=[B, 1], dtype="int64",
                          append_batch_size=False)
        e = layers.embedding(
            ids, size=[vocab, embed_dim],
            param_attr=ParamAttr(name=f"rec.{name}_emb"))
        return layers.reshape(e, shape=[B, embed_dim])

    # --- user tower ---
    usr = emb_feature("user_id", user_vocab)
    gender = emb_feature("gender_id", gender_vocab)
    age = emb_feature("age_id", age_vocab)
    job = emb_feature("job_id", job_vocab)
    usr_combined = layers.fc(
        layers.concat([usr, gender, age, job], axis=1),
        size=200, act="tanh")

    # --- movie tower ---
    mov = emb_feature("movie_id", movie_vocab)
    title = layers.data("title_ids", shape=[B, title_len], dtype="int64",
                        append_batch_size=False, lod_level=1)
    title_emb = layers.embedding(
        title, size=[title_vocab, embed_dim],
        param_attr=ParamAttr(name="rec.title_emb"))
    title_feat = layers.sequence_pool(
        layers.sequence_conv(title_emb, num_filters=embed_dim,
                             filter_size=3, act="tanh"), "sum")
    mov_combined = layers.fc(
        layers.concat([mov, title_feat], axis=1), size=200, act="tanh")

    # --- cosine similarity score scaled to rating range ---
    sim = layers.reduce_sum(
        layers.elementwise_mul(
            layers.l2_normalize(usr_combined, axis=1),
            layers.l2_normalize(mov_combined, axis=1)),
        dim=1, keep_dim=True)
    predict = layers.scale(sim, scale=5.0)

    rating = layers.data("score", shape=[B, 1], append_batch_size=False)
    loss = layers.reduce_mean(layers.square_error_cost(predict, rating))
    if with_optimizer:
        optimizer.AdamOptimizer(learning_rate=learning_rate).minimize(loss)
    feeds = ["user_id", "gender_id", "age_id", "job_id", "movie_id",
             "title_ids", "title_ids.seq_len", "score"]
    return {"loss": loss, "predict": predict, "feeds": feeds}


def make_fake_batch(batch_size=32, seed=0, **vocab_sizes):
    rng = np.random.RandomState(seed)
    v = {"user_vocab": 500, "gender_vocab": 2, "age_vocab": 7,
         "job_vocab": 21, "movie_vocab": 800, "title_vocab": 1000,
         "title_len": 12}
    v.update(vocab_sizes)
    B = batch_size
    uid = rng.randint(0, v["user_vocab"], (B, 1))
    mid = rng.randint(0, v["movie_vocab"], (B, 1))
    return {
        "user_id": uid.astype(np.int64),
        "gender_id": rng.randint(0, v["gender_vocab"],
                                 (B, 1)).astype(np.int64),
        "age_id": rng.randint(0, v["age_vocab"], (B, 1)).astype(np.int64),
        "job_id": rng.randint(0, v["job_vocab"], (B, 1)).astype(np.int64),
        "movie_id": mid.astype(np.int64),
        "title_ids": rng.randint(0, v["title_vocab"],
                                 (B, v["title_len"])).astype(np.int64),
        "title_ids.seq_len": rng.randint(
            3, v["title_len"] + 1, B).astype(np.int32),
        # learnable structure: rating derived from the id pair
        "score": ((uid + mid) % 5 + 1).astype(np.float32),
    }
