"""Serving fleet: N engine replicas behind one health-checked router.

Three robustness cycles made *training* survive any single failure;
this module is the serving analog of the gang supervisor — the layer
that makes a replica death invisible to callers.  One `Fleet` fronts N
engine replicas (all `ServingEngine` or all `DecodeEngine`) behind a
single `submit()` surface:

- **health-scored, least-loaded routing** — every replica carries a
  fleet-side `CircuitBreaker` (injectable clock, the admission-plane
  idiom) fed by routed-request outcomes, plus the engine's own
  admission state and a last-success heartbeat; requests go to the
  healthy replica with the fewest outstanding requests.  A replica
  whose scheduler died is EJECTED (the poison idiom lifted across the
  process boundary: once marked dead it never routes again).
- **structured whole-fleet fast-reject** — when every replica sheds
  (queue full, breaker open, dead), `submit()` raises
  `FleetSaturatedError` in microseconds with per-replica evidence and
  a `retry_after_s` honoring the engines' `CircuitOpenError` cooldowns
  — the TF-Serving fast-reject contract at fleet scope.
- **deadline-budgeted retry + hedging** — failover resubmission runs
  under `resilience.watchdog.retry_call` (deterministic backoff,
  bounded by the request's remaining deadline); a request slower than
  `hedge_after_ms` gets ONE duplicate on a different replica, first
  result wins.  Only idempotent requests hedge — greedy decode and
  pure inference are; callers mark anything else `idempotent=False`.
- **failover for in-flight decode sessions** — when a replica dies or
  is ejected mid-generation, its requests come back as retryable
  `DecodeReplicaFailedError`s carrying requeue descriptors (the
  committed-token prefix included); the fleet resubmits them on a
  survivor and VERIFIES the regeneration reproduces the committed
  prefix token-for-token (greedy decode makes the whole output
  identical to an unkilled control fleet — the PR 12 preemption proof
  lifted across process boundaries).
- **hot weight reload** — `fleet.reload(ckpt_dir)` rolls new params
  through the replicas one at a time: the replica under roll is
  excluded from routing, its in-flight decode sessions evacuate to
  survivors, `io.load_sharded` lands the new arrays in the live
  engine's param dict (same shapes asserted ⇒ the jitted executables
  are reused — ZERO compiles, asserted fleet-wide over the roll), and
  every response is tagged with the `model_version` that produced it.
  No request is rejected during the roll; the other replicas carry the
  traffic.

Everything that crosses the fleet boundary is a structured
`ServingError` (`as_dict()`) and every state change is a
`serving_fleet_*` event through `observe.RunEventLog`; replica engines
stamp their own events with `replica_id` (RunEventLog.bind), so N
replicas sharing one process log stay attributable.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..observe.events import RunEventLog
from ..observe.monitoring import LatencyHistogram, runtime_stats
from ..resilience.errors import RetriesExhaustedError
from ..resilience.watchdog import retry_call
from .admission import (DEGRADED, RUNNING, CircuitBreaker,
                        CircuitOpenError, DeadlineExceededError,
                        QueueFullError, ServingClosedError, ServingError,
                        WeightReloadError)
from .decode import DecodeEngine
from .stats import DecodeStats, ServingStats


class FleetSaturatedError(ServingError):
    """Every replica fast-rejected this request (queue full, breaker
    open, reloading, or dead).  Carries per-replica evidence and
    `retry_after_s` (the soonest any breaker cooldown elapses) so a
    frontend can back off precisely instead of hammering."""

    kind = "fleet_saturated"


class FleetClosedError(ServingError):
    """Submitted to a fleet that is closed (or not started)."""

    kind = "fleet_closed"


class FailoverParityError(ServingError):
    """LOUD: a failed-over request's regeneration did NOT reproduce the
    committed-token prefix the dead replica reported — the greedy
    token-identity invariant broke (weights diverged between replicas,
    or a non-greedy sampler was routed as idempotent)."""

    kind = "failover_parity"


class FleetResponse:
    """What a fleet future resolves to: the engine's result plus the
    routing provenance a caller needs to trust it — which replica
    served it, under which weight version, whether failover or hedging
    was involved, and (with tracing on) the trace_id plus the ordered
    replica hop chain, so "why was THIS request slow" is answerable
    from the response alone."""

    __slots__ = ("value", "replica_id", "model_version", "failovers",
                 "hedged", "attempts", "trace_id", "hops")

    def __init__(self, value, replica_id: int, model_version: int,
                 failovers: int, hedged: bool, attempts: int,
                 trace_id: Optional[str] = None,
                 hops: Sequence[int] = ()):
        self.value = value
        self.replica_id = replica_id
        self.model_version = model_version
        self.failovers = failovers
        self.hedged = hedged
        self.attempts = attempts
        self.trace_id = trace_id
        self.hops = list(hops)     # replica ids in attempt order

    @property
    def tokens(self):
        """Decode-fleet alias."""
        return self.value

    @property
    def outputs(self):
        """Serving-fleet alias."""
        return self.value

    def __repr__(self):
        return (f"FleetResponse(replica={self.replica_id}, "
                f"version={self.model_version}, "
                f"failovers={self.failovers}, hedged={self.hedged})")


class FleetConfig:
    """Routing/failover knobs.

    failure_threshold / cooldown_s: the per-replica fleet-side
        CircuitBreaker (consecutive routed-request failures open it;
        one half-open probe after the cooldown).  `clock` is
        injectable so tests drive cooldowns deterministically.
    max_failovers: per-request bound on requeue hops (a request
        bouncing between dying replicas must fail structured, not
        loop).
    failover_route_retries / retry_base_delay_s: the retry_call budget
        a FAILOVER resubmission gets when the fleet is momentarily
        saturated (e.g. the only survivor is mid-reload).  First
        submits never retry — fast-reject is the contract.
    hedge_after_ms: duplicate an idempotent request on a second
        replica when the first attempt is slower than this (None
        disables hedging).
    default_deadline_ms: per-request deadline when the caller sets
        none; the SAME budget bounds every failover hop.
    """

    def __init__(self, failure_threshold: int = 3,
                 cooldown_s: float = 2.0,
                 max_failovers: int = 3,
                 failover_route_retries: int = 6,
                 retry_base_delay_s: float = 0.05,
                 hedge_after_ms: Optional[float] = None,
                 default_deadline_ms: Optional[float] = None,
                 window: int = 256,
                 clock: Callable[[], float] = time.monotonic):
        if max_failovers < 0 or failover_route_retries < 0:
            raise ValueError("max_failovers/failover_route_retries >= 0")
        if hedge_after_ms is not None and hedge_after_ms <= 0:
            raise ValueError("hedge_after_ms must be > 0")
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self.max_failovers = int(max_failovers)
        self.failover_route_retries = int(failover_route_retries)
        self.retry_base_delay_s = float(retry_base_delay_s)
        self.hedge_after_ms = hedge_after_ms
        self.default_deadline_ms = default_deadline_ms
        self.window = int(window)
        self.clock = clock


class ReplicaHandle:
    """Fleet-side view of one engine replica: identity, load, the
    fleet breaker, and the health evidence routing scores on."""

    def __init__(self, replica_id: int, engine, config: FleetConfig):
        self.replica_id = int(replica_id)
        self.engine = engine
        self.breaker = CircuitBreaker(
            failure_threshold=config.failure_threshold,
            cooldown_s=config.cooldown_s, clock=config.clock)
        self.inflight = 0       # fleet-routed outstanding requests
        self.routed = 0         # lifetime routed count
        self.failures = 0       # lifetime retryable failures observed
        self.dead = False       # ejected: never routes again
        self.dead_reason: Optional[str] = None
        self.reloading = False  # mid-roll: excluded from routing
        self.last_ok_t: Optional[float] = None

    def routable(self) -> bool:
        return (not self.dead and not self.reloading
                and self.engine.admission.state in (RUNNING, DEGRADED))

    def score(self, clock: Callable[[], float]) -> Dict[str, Any]:
        out = {"replica_id": self.replica_id,
               "state": self.engine.admission.state,
               "breaker": self.breaker.snapshot(),
               "inflight": self.inflight, "routed": self.routed,
               "failures": self.failures, "dead": self.dead,
               "dead_reason": self.dead_reason,
               "reloading": self.reloading,
               "model_version": self.engine.model_version}
        if self.last_ok_t is not None:
            out["since_last_ok_s"] = round(clock() - self.last_ok_t, 3)
        return out


class FleetStats:
    """Fleet-level counters + end-to-end latency (the per-replica
    engine stats merge separately via ServingStats/DecodeStats.merge);
    thread-safe."""

    def __init__(self, window: int = 256):
        self._lock = threading.Lock()
        self.window = int(window)
        self.e2e_ms = LatencyHistogram()
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.failovers = 0
        self.hedges = 0
        self.hedge_wins = 0
        self.retries = 0          # failover route retries (backoff hits)
        self.saturated = 0        # whole-fleet fast-rejects
        self.ejects = 0
        self.reloads = 0          # per-replica swaps applied
        self.reload_pause_ms = 0.0
        self.parity_checked = 0   # failovers verified token-identical
        self.parity_failed = 0
        self._emitted_at = 0

    def _bump(self, field: str, by: float = 1):
        with self._lock:
            setattr(self, field, getattr(self, field) + by)

    def record_submit(self):
        self._bump("submitted")

    def record_failed(self):
        self._bump("failed")

    def record_failover(self):
        self._bump("failovers")

    def record_hedge(self):
        self._bump("hedges")

    def record_hedge_win(self):
        self._bump("hedge_wins")

    def record_retry(self):
        self._bump("retries")

    def record_saturated(self):
        self._bump("saturated")

    def record_eject(self):
        self._bump("ejects")

    def record_parity(self, ok: bool):
        self._bump("parity_checked")
        if not ok:
            self._bump("parity_failed")

    def record_reload(self, pause_ms: float):
        with self._lock:
            self.reloads += 1
            if pause_ms > self.reload_pause_ms:
                self.reload_pause_ms = float(pause_ms)

    def record_done(self, e2e_ms: float) -> bool:
        """True when this completion crosses a window boundary (the
        caller emits serving_fleet_window)."""
        self.e2e_ms.record(e2e_ms)
        with self._lock:
            self.completed += 1
            if self.completed - self._emitted_at >= self.window:
                self._emitted_at = self.completed
                return True
            return False

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out = {f: getattr(self, f) for f in (
                "submitted", "completed", "failed", "failovers",
                "hedges", "hedge_wins", "retries", "saturated",
                "ejects", "reloads", "parity_checked", "parity_failed")}
            out["reload_pause_ms"] = round(self.reload_pause_ms, 3)
        out["e2e_ms"] = self.e2e_ms.summary()
        return out


class _FleetRequest:
    """Router-side state of one logical request across attempts."""

    __slots__ = ("payload", "future", "deadline", "idempotent",
                 "t_submit", "lock", "resolved", "tried", "attempts",
                 "failovers", "hedges", "prefix", "trace", "hops",
                 "pending_failover")

    def __init__(self, payload: Dict[str, Any],
                 deadline: Optional[float], idempotent: bool,
                 trace=None):
        self.payload = payload
        self.future: Future = Future()
        self.deadline = deadline        # absolute time.monotonic()
        self.idempotent = bool(idempotent)
        self.t_submit = time.monotonic()
        self.lock = threading.Lock()
        self.resolved = False
        self.tried: set = set()         # replica ids attempted
        self.attempts = 0
        self.failovers = 0
        self.hedges = 0
        self.prefix: List[int] = []     # committed tokens from a failed
        #                                 attempt (parity evidence)
        self.trace = trace              # observe.reqtrace.RequestTrace
        self.hops: List[int] = []       # replica ids in attempt order
        # (t_detected, replica_id, reason) of a failover awaiting its
        # landing replica — closed into a `failover` span on requeue
        self.pending_failover: Optional[tuple] = None

    def remaining_ms(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return (self.deadline - time.monotonic()) * 1e3


class Fleet:
    """Router over N homogeneous engine replicas.

        engines = [DecodeEngine(DecoderLM(seed=0), cfg) for _ in range(2)]
        fleet = Fleet(engines, FleetConfig(hedge_after_ms=500)).start()
        fut = fleet.submit(prompt_ids, max_new_tokens=64)
        resp = fut.result()          # FleetResponse: .tokens, .replica_id,
        ...                          # .model_version, .failovers
        fleet.reload(ckpt_dir)       # rolling hot weight swap
        fleet.close()

    Engines may be pre-started or not (start() warms the cold ones,
    then resets every replica's post-warmup compile window so replica
    K's warmup never counts against replica 0's zero-compile
    contract).  All replicas must be the same kind; the fleet detects
    decode vs single-shot serving from the first engine.
    """

    def __init__(self, engines: Sequence, config: Optional[FleetConfig]
                 = None, event_log: Optional[RunEventLog] = None,
                 log_path: Optional[str] = None, tracer=None):
        """tracer: an observe.ReqTracer — every submit() carries one
        RequestTrace across routing, the replica's queue/dispatch
        boundaries, and any failover/hedge hops (one trace_id per
        logical request, observe pillar 7); responses then carry
        `trace_id` + `hops`.  Host-side only; None disables."""
        if not engines:
            raise ValueError("a fleet needs at least one engine")
        self.config = config or FleetConfig()
        self.tracer = tracer
        decode = isinstance(engines[0], DecodeEngine)
        for e in engines:
            if isinstance(e, DecodeEngine) != decode:
                raise ValueError(
                    "mixed fleet: all replicas must be DecodeEngine or "
                    "all single-shot serving engines")
        self.kind = "decode" if decode else "serving"
        self._own_log = None
        if event_log is None and log_path is not None:
            event_log = self._own_log = RunEventLog(
                log_path, meta={"component": "serving_fleet"})
        self._event_log = event_log
        self.stats = FleetStats(window=self.config.window)
        self.replicas = [ReplicaHandle(i, e, self.config)
                         for i, e in enumerate(engines)]
        for h in self.replicas:
            h.engine.set_replica_id(h.replica_id)
            if event_log is not None and h.engine._event_log is None:
                bound = event_log.bind(replica_id=h.replica_id)
                h.engine._event_log = bound
                h.engine.stats._event_log = bound
        self.model_version = max(e.model_version for e in engines)
        self._lock = threading.Lock()
        self._closed = False
        self._started = False
        self._rolling = False
        self._metrics_registry = None
        self._metrics_server = None
        self.alert_engine = None       # observe pillar 9 (opt-in)
        self.flight_recorder = None

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "Fleet":
        """Warm every cold replica, then open the post-warmup
        zero-compile window for the WHOLE fleet at once."""
        for h in self.replicas:
            if not h.engine._started:
                h.engine.start()
        for h in self.replicas:
            h.engine.stats.reset_compile_base()
        self._started = True
        self._event("serving_fleet_start", fleet_kind=self.kind,
                    n_replicas=len(self.replicas),
                    model_version=self.model_version,
                    hedge_after_ms=self.config.hedge_after_ms,
                    max_failovers=self.config.max_failovers)
        return self

    def close(self, timeout_s: float = 60.0,
              close_replicas: bool = True):
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if close_replicas:
            for h in self.replicas:
                h.engine.close(timeout_s)
        if self.alert_engine is not None:
            self.alert_engine.close()
        if self.flight_recorder is not None:
            self.flight_recorder.close()
        if self._metrics_server is not None:
            self._metrics_server.close()
            self._metrics_server = None
        self._event("serving_fleet_close", **self.snapshot())
        if self._own_log is not None:
            self._own_log.close()

    def __enter__(self) -> "Fleet":
        return self.start() if not self._started else self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- observability --------------------------------------------------
    def _event(self, kind: str, **fields: Any):
        if self._event_log is not None:
            self._event_log.event(kind, **fields)

    def health(self) -> Dict[str, Any]:
        clock = self.config.clock
        scores = [h.score(clock) for h in self.replicas]
        return {"kind": self.kind, "closed": self._closed,
                "model_version": self.model_version,
                "healthy_replicas": sum(h.routable()
                                        for h in self.replicas),
                "replicas": scores}

    def merged_stats(self):
        """One ServingStats/DecodeStats holding every replica's
        telemetry, merged exactly (histogram bin-wise addition,
        counters summed) — the cross-replica aggregation surface."""
        agg = DecodeStats() if self.kind == "decode" else ServingStats()
        for h in self.replicas:
            agg.merge(h.engine.stats)
        return agg

    def metrics_registry(self):
        """The fleet's unified metrics surface (observe pillar 7): one
        MetricsRegistry holding the router collector (per-replica
        health/breaker gauges, failover/hedge counters), the
        fleet-MERGED engine stats (pulled via merged_stats at scrape
        time, so histograms aggregate exactly), the request tracer's
        phase histograms when tracing is on, and the process-wide
        runtime/process/memory collectors.  Built once, cached."""
        if self._metrics_registry is None:
            from ..observe.registry import (MetricsRegistry,
                                            fleet_collector,
                                            serving_stats_collector,
                                            standard_collectors,
                                            tracer_collector)

            reg = standard_collectors(MetricsRegistry())
            reg.register("fleet", fleet_collector(self))
            reg.register("serving",
                         serving_stats_collector(self.merged_stats,
                                                 scope="fleet"))
            if self.tracer is not None:
                reg.register("reqtrace",
                             tracer_collector(self.tracer))
            self._metrics_registry = reg
        return self._metrics_registry

    def start_metrics_server(self, host: str = "127.0.0.1",
                             port: int = 0):
        """Opt-in /metrics + /healthz endpoint over this fleet's
        registry (stdlib ThreadingHTTPServer; binds localhost unless
        told otherwise — the exposition carries per-replica health
        detail).  With `enable_alerts()` active the same server also
        answers /alerts.  port=0 picks an ephemeral port; read `.port`
        / `.url` off the returned MetricsServer.  Stopped by close()."""
        if self._metrics_server is not None:
            return self._metrics_server
        from ..observe.registry import MetricsServer

        self._metrics_server = MetricsServer(
            self.metrics_registry(), health_fn=self.health,
            host=host, port=port,
            alerts_fn=(self.alert_engine.state
                       if self.alert_engine is not None
                       else None)).start()
        return self._metrics_server

    def enable_alerts(self, rules=None, interval_s: float = 5.0,
                      flight_dir: Optional[str] = None,
                      recorder_config: Optional[Dict[str, Any]] = None,
                      start: bool = True, **pack_kw):
        """Opt into observe pillar 9 on this fleet: an AlertEngine
        evaluating the serving-SLO pack (`observe.fleet_rule_pack` —
        error/failover/saturation burn + TTFT/TPOT/queue_wait p99; or
        explicit `rules`) over `metrics_registry()` every `interval_s`
        on a background thread.  `pack_kw` forwards to the pack
        (thresholds/windows).  With `flight_dir` a FlightRecorder
        writes a diagnostic bundle on every firing alert
        (`recorder_config` forwards rate/size bounds).  The `alerts`
        metric family joins /metrics and the /alerts route activates
        on the metrics server.  `start=False` skips the background
        thread (callers drive `alert_engine.evaluate()` — tests, and
        in-process `tools/metrics_dump.py --alerts`).  Pure host: the
        engine thread only reads registry snapshots — zero device
        dispatches.  Stopped by close()."""
        if self.alert_engine is not None:
            return self.alert_engine
        from ..observe.alerts import AlertEngine, fleet_rule_pack
        from ..observe.flightrec import FlightRecorder

        if rules is None:
            rules = fleet_rule_pack(self, **pack_kw)
        elif pack_kw:
            raise ValueError("pack_kw only applies to the default "
                             "rule pack")
        engine = AlertEngine(self.metrics_registry(), rules=rules,
                             interval_s=interval_s,
                             event_log=self._event_log)
        self.metrics_registry().register("alerts", engine.collector())
        if flight_dir is not None:
            self.flight_recorder = FlightRecorder(
                flight_dir, registry=self.metrics_registry(),
                event_log=self._event_log, tracer=self.tracer,
                **(recorder_config or {}))
            self.flight_recorder.attach_engine(engine)
        self.alert_engine = engine
        if self._metrics_server is not None:
            self._metrics_server.alerts_fn = engine.state
        if start:
            engine.start()
        return engine

    def snapshot(self) -> Dict[str, Any]:
        """Fleet counters + the merged per-replica engine telemetry
        (one dict, the serving_fleet_window wire form)."""
        out = self.stats.snapshot()
        out["engines"] = self.merged_stats().snapshot()
        out["post_warmup_compiles"] = \
            out["engines"]["post_warmup_compiles"]
        out["model_version"] = self.model_version
        out["healthy_replicas"] = sum(h.routable()
                                      for h in self.replicas)
        return out

    # -- request path ---------------------------------------------------
    def submit(self, request, *, max_new_tokens: int = 32,
               priority: int = 0, deadline_ms: Optional[float] = None,
               idempotent: bool = True) -> Future:
        """Route one request to the healthiest least-loaded replica;
        returns a Future of a FleetResponse.  Decode fleets take a
        prompt (1-D token array) plus max_new_tokens/priority;
        single-shot fleets take the per-example feed dict.  Raises the
        structured FleetSaturatedError synchronously when every replica
        sheds — a rejected request costs microseconds, never a
        timeout.  idempotent=False opts a request out of hedging AND
        transparent failover (its error surfaces instead)."""
        if self._closed or not self._started:
            raise FleetClosedError(
                "fleet is closed" if self._closed
                else "fleet not started", closed=self._closed)
        ms = (deadline_ms if deadline_ms is not None
              else self.config.default_deadline_ms)
        deadline = time.monotonic() + ms / 1e3 if ms else None
        if self.kind == "decode":
            payload = {"prompt": np.asarray(request),
                       "max_new_tokens": int(max_new_tokens),
                       "priority": int(priority)}
        else:
            payload = {"feed": request}
        trace = None
        if self.tracer is not None:
            trace = self.tracer.new_trace(f"fleet_{self.kind}")
            trace.fleet_owned = True  # engines add spans; WE finish it
        freq = _FleetRequest(payload, deadline, idempotent,
                             trace=trace)
        self.stats.record_submit()
        self._route_once(freq)
        if self.config.hedge_after_ms and freq.idempotent \
                and len(self.replicas) > 1:
            t = threading.Timer(self.config.hedge_after_ms / 1e3,
                                self._fire_hedge, args=(freq,))
            t.daemon = True
            t.start()
        return freq.future

    def generate(self, prompt, max_new_tokens: int = 32,
                 timeout_s: Optional[float] = None,
                 **kw) -> FleetResponse:
        """Synchronous submit()+result() convenience (decode fleets)."""
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           **kw).result(timeout_s)

    def infer(self, feed, timeout_s: Optional[float] = None,
              **kw) -> FleetResponse:
        """Synchronous submit()+result() convenience (serving fleets)."""
        return self.submit(feed, **kw).result(timeout_s)

    # -- routing --------------------------------------------------------
    def _engine_submit(self, handle: ReplicaHandle, freq: _FleetRequest,
                       remaining_ms: Optional[float]) -> Future:
        p = freq.payload
        if self.kind == "decode":
            return handle.engine.submit(
                p["prompt"], max_new_tokens=p["max_new_tokens"],
                priority=p["priority"], deadline_ms=remaining_ms,
                _trace=freq.trace)
        return handle.engine.submit(p["feed"],
                                    deadline_ms=remaining_ms,
                                    _trace=freq.trace)

    def _route_once(self, freq: _FleetRequest,
                    hedge: bool = False) -> ReplicaHandle:
        """One routing pass: try healthy replicas least-loaded-first
        (preferring ones this request has not attempted), accept the
        first that admits, raise FleetSaturatedError with per-replica
        evidence otherwise."""
        if self._closed:
            raise FleetClosedError("fleet is closed", closed=True)
        t_route = time.monotonic()
        remaining_ms = freq.remaining_ms()
        if remaining_ms is not None and remaining_ms <= 0:
            raise DeadlineExceededError(
                "request deadline expired before a replica could be "
                "(re)tried", attempts=freq.attempts,
                failovers=freq.failovers)
        with self._lock:
            avail = [h for h in self.replicas if h.routable()]
            fresh = [h for h in avail
                     if h.replica_id not in freq.tried]
            # a hedge duplicate on an already-tried replica is
            # pointless; a failover prefers a fresh replica but falls
            # back to a retried one rather than dropping the request
            candidates = fresh if (fresh or hedge) else avail
            candidates = sorted(
                candidates,
                key=lambda h: (h.inflight, h.routed, h.replica_id))
        reasons: List[Dict[str, Any]] = []
        retry_after: List[float] = []
        for h in candidates:
            if h.breaker.state != CircuitBreaker.CLOSED \
                    and not h.breaker.allow():
                reasons.append({"replica_id": h.replica_id,
                                "reject": "fleet_breaker_open"})
                retry_after.append(h.breaker.cooldown_remaining_s())
                continue
            try:
                fut = self._engine_submit(h, freq, remaining_ms)
            except (QueueFullError, CircuitOpenError,
                    ServingClosedError) as e:
                reasons.append({"replica_id": h.replica_id,
                                "reject": e.kind})
                ra = e.details.get("retry_after_s")
                if ra:
                    retry_after.append(float(ra))
                continue
            with self._lock:
                h.inflight += 1
                h.routed += 1
                freq.tried.add(h.replica_id)
                freq.attempts += 1
                freq.hops.append(h.replica_id)
            if freq.trace is not None:
                now = time.monotonic()
                freq.trace.add("route", t_route, now,
                               replica_id=h.replica_id, hedge=hedge)
                pf = freq.pending_failover
                if pf is not None and not hedge:
                    # the failover hop closes when the request LANDS
                    # on its next replica: one span from detection to
                    # requeue, naming the dead replica and the
                    # survivor — the hop chain a chrome export renders
                    # across replica rows
                    freq.pending_failover = None
                    t_det, dead_id, reason = pf
                    freq.trace.add("failover", t_det, now,
                                   from_replica=dead_id,
                                   to_replica=h.replica_id,
                                   reason=reason)
            fut.add_done_callback(
                lambda f, h=h: self._on_attempt_done(freq, h, f, hedge))
            return h
        self.stats.record_saturated()
        clock = self.config.clock
        err = FleetSaturatedError(
            f"all {len(self.replicas)} replica(s) shed this request "
            f"({len(candidates)} routable)",
            retry_after_s=(round(min(retry_after), 3)
                           if retry_after else None),
            rejects=reasons,
            replicas=[h.score(clock) for h in self.replicas])
        self._event("serving_fleet_saturated", **err.as_dict())
        raise err

    # -- attempt resolution ---------------------------------------------
    def _on_attempt_done(self, freq: _FleetRequest, h: ReplicaHandle,
                         fut: Future, hedge: bool):
        with self._lock:
            h.inflight -= 1
        exc = fut.exception()
        if freq.trace is not None:
            with freq.lock:
                already = freq.resolved
            if already:
                # a loser attempt (hedge or failover race) resolving
                # after the request did: its work is abandoned — the
                # marker tail-keeps the trace so a hedged request's
                # timeline shows both attempts
                freq.trace.point(
                    "abandoned", replica_id=h.replica_id,
                    error=None if exc is None else type(exc).__name__)
        if exc is None:
            h.breaker.record_success()
            h.last_ok_t = self.config.clock()
            self._finish_ok(freq, h, fut, hedge)
            return
        retryable = (isinstance(exc, ServingError)
                     and getattr(exc, "retryable", False))
        if not retryable:
            # client-side rejection (deadline, bucket miss): replaying
            # it elsewhere cannot help — surface it (hedge losses are
            # opportunistic and stay silent)
            if not hedge:
                self._finish_err(freq, exc)
            return
        evacuated = exc.details.get("reason") == "evacuated"
        if not evacuated:
            # an EVACUATION is a deliberate control action (weight
            # roll / manual eject), not evidence against the replica's
            # health — only real failures feed the breaker
            with self._lock:
                h.failures += 1
            h.breaker.record_failure()
            state = h.engine.admission.state
            if state not in (RUNNING, DEGRADED) and not h.dead:
                # the replica is not coming back on its own (scheduler
                # death drains admission): eject it from routing
                self._eject(h, reason=f"engine {state} after {exc.kind}")
        desc = exc.details.get("descriptor") or {}
        with freq.lock:
            if freq.resolved:
                return
            gen = desc.get("generated") or []
            if len(gen) > len(freq.prefix):
                freq.prefix = [int(t) for t in gen]
        if hedge:
            return  # the primary attempt owns failover
        if not freq.idempotent:
            self._finish_err(freq, exc)
            return
        freq.failovers += 1
        if freq.trace is not None and freq.pending_failover is None:
            freq.pending_failover = (time.monotonic(), h.replica_id,
                                     exc.kind)
        self.stats.record_failover()
        self._event("serving_fleet_failover",
                    replica_id=h.replica_id, reason=exc.kind,
                    committed_tokens=len(freq.prefix),
                    attempts=freq.attempts, failovers=freq.failovers)
        if freq.failovers > self.config.max_failovers:
            self._finish_err(freq, exc)
            return
        # the requeue runs on its OWN thread: this callback fires on
        # the failing engine's scheduler thread (future resolution is
        # inline), and the backoff sleeps below must never block a
        # scheduler that is mid-evacuation or mid-death
        t = threading.Thread(target=self._requeue, args=(freq,),
                             name="fleet-requeue", daemon=True)
        t.start()

    def _requeue(self, freq: _FleetRequest):
        """Deadline-budgeted requeue of an accepted request: an
        accepted request is never dropped because the fleet was
        saturated for a moment (e.g. the lone survivor is mid-reload)
        — retry_call's deterministic backoff until the deadline or the
        retry budget runs out."""
        try:
            retry_call(
                lambda: self._route_once(freq),
                retries=self.config.failover_route_retries,
                base_delay_s=self.config.retry_base_delay_s,
                max_delay_s=1.0,
                retry_on=(FleetSaturatedError,),
                on_retry=lambda _a, _e, _d: self.stats.record_retry())
        except RetriesExhaustedError as e2:
            last = e2.__cause__
            self._finish_err(freq, last if isinstance(last, ServingError)
                             else e2)
        except ServingError as e2:
            self._finish_err(freq, e2)

    def _finish_ok(self, freq: _FleetRequest, h: ReplicaHandle,
                   fut: Future, hedge: bool):
        with freq.lock:
            if freq.resolved:
                return
            freq.resolved = True
        value = fut.result()
        if self.kind == "decode" and freq.prefix:
            # the failover proof: the survivor's regeneration must
            # reproduce the dead replica's committed prefix exactly
            got = [int(t) for t in
                   np.asarray(value)[:len(freq.prefix)]]
            ok = got == freq.prefix
            self.stats.record_parity(ok)
            if not ok:
                err = FailoverParityError(
                    f"regenerated tokens diverged from the "
                    f"{len(freq.prefix)}-token committed prefix of the "
                    f"failed replica", expected=freq.prefix, got=got,
                    replica_id=h.replica_id)
                self._event("serving_fleet_failover",
                            replica_id=h.replica_id, parity="FAILED",
                            **err.details)
                self.stats.record_failed()
                if freq.trace is not None and self.tracer is not None:
                    self.tracer.finish(freq.trace, error=err)
                freq.future.set_exception(err)
                return
        if hedge:
            self.stats.record_hedge_win()
        if freq.trace is not None:
            freq.trace.point("complete", replica_id=h.replica_id,
                             failovers=freq.failovers,
                             hedged=freq.hedges > 0)
        resp = FleetResponse(
            value, replica_id=h.replica_id,
            model_version=getattr(fut, "model_version",
                                  h.engine.model_version),
            failovers=freq.failovers, hedged=freq.hedges > 0,
            attempts=freq.attempts,
            trace_id=(freq.trace.trace_id if freq.trace is not None
                      else None),
            hops=list(freq.hops))
        if freq.trace is not None and self.tracer is not None:
            self.tracer.finish(freq.trace)
        freq.future.set_result(resp)
        if self.stats.record_done(
                (time.monotonic() - freq.t_submit) * 1e3):
            self._event("serving_fleet_window", **self.snapshot())

    def _finish_err(self, freq: _FleetRequest, exc: BaseException):
        with freq.lock:
            if freq.resolved:
                return
            freq.resolved = True
        self.stats.record_failed()
        if freq.trace is not None and self.tracer is not None:
            self.tracer.finish(freq.trace, error=exc)
        freq.future.set_exception(exc)

    # -- hedging --------------------------------------------------------
    def _fire_hedge(self, freq: _FleetRequest):
        if self._closed or freq.resolved:
            return
        try:
            h = self._route_once(freq, hedge=True)
        except ServingError:
            return  # hedging is opportunistic; the primary stands
        with freq.lock:
            freq.hedges += 1
        if freq.trace is not None:
            freq.trace.point("hedge", replica_id=h.replica_id,
                             after_ms=self.config.hedge_after_ms)
        self.stats.record_hedge()
        self._event("serving_fleet_hedge", replica_id=h.replica_id,
                    after_ms=self.config.hedge_after_ms)

    # -- eject ----------------------------------------------------------
    def _eject(self, h: ReplicaHandle, reason: str):
        with self._lock:
            if h.dead:
                return
            h.dead = True
            h.dead_reason = reason
        self.stats.record_eject()
        self._event("serving_fleet_eject", replica_id=h.replica_id,
                    reason=reason,
                    healthy_replicas=sum(x.routable()
                                         for x in self.replicas))

    def eject(self, replica_id: int, reason: str = "manual"):
        """Remove one replica from routing (the poison idiom at fleet
        scope: an operator or external watchdog condemns a replica).
        In-flight decode sessions evacuate and fail over to survivors
        through the normal retryable-error path."""
        h = self.replicas[int(replica_id)]
        self._eject(h, reason)
        if self.kind == "decode":
            h.engine.evacuate()

    # -- hot weight reload ----------------------------------------------
    def reload(self, source, version: Optional[int] = None
               ) -> Dict[str, Any]:
        """Roll new weights through the replicas ONE AT A TIME; no
        request is rejected during the roll.  Per replica: exclude it
        from routing, evacuate its in-flight decode sessions (they
        fail over to the other replicas and regenerate
        token-identically), swap the params at its batch boundary
        (same shapes asserted), re-admit it.  The whole roll is
        asserted compile-free (runtime_stats delta) — a reload that
        recompiles would stall serving for seconds and is a structured
        WeightReloadError, not a silent degradation."""
        if self._closed:
            raise FleetClosedError("fleet is closed", closed=True)
        with self._lock:
            if self._rolling:
                raise WeightReloadError(
                    "a reload roll is already in progress")
            self._rolling = True
        new_version = (self.model_version + 1 if version is None
                       else int(version))
        snap = runtime_stats.snapshot()
        t0 = time.perf_counter()
        self._event("serving_fleet_reload", phase="begin",
                    version=new_version)
        per: List[Dict[str, Any]] = []
        try:
            for h in self.replicas:
                if h.dead:
                    per.append({"replica_id": h.replica_id,
                                "skipped": h.dead_reason})
                    continue
                h.reloading = True
                try:
                    evacuated = 0
                    if self.kind == "decode":
                        evacuated = len(h.engine.evacuate())
                    info = h.engine.reload(source, version=new_version)
                finally:
                    h.reloading = False
                self.stats.record_reload(info["pause_ms"])
                self._event("serving_fleet_reload_replica",
                            replica_id=h.replica_id,
                            pause_ms=info["pause_ms"],
                            evacuated=evacuated, version=new_version)
                per.append({"replica_id": h.replica_id,
                            "pause_ms": info["pause_ms"],
                            "evacuated": evacuated})
            compiles = runtime_stats.delta(snap)["compiles"]
            if compiles:
                raise WeightReloadError(
                    f"{compiles} XLA compile(s) observed during the "
                    f"roll — the same-shape zero-recompile contract "
                    f"broke", compiles=compiles, version=new_version)
            self.model_version = new_version
            out = {"version": new_version, "replicas": per,
                   "compiles": 0,
                   "pause_ms_max": max(
                       [p.get("pause_ms", 0.0) for p in per] or [0.0]),
                   "seconds": round(time.perf_counter() - t0, 3)}
            self._event("serving_fleet_reload", phase="done",
                        version=new_version, compiles=0,
                        pause_ms_max=out["pause_ms_max"],
                        seconds=out["seconds"])
            return out
        finally:
            with self._lock:
                self._rolling = False
