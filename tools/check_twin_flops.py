"""Validate the cpu-twin MFU numerator (bench.py _dense_equiv_flops
platform="cpu") against the chip's own cost analysis.

At long sequence the dense flop-count twin cannot compile on the TPU
(seq 8k = 73 GB of dense scores), so bench.py counts the longctx
numerator from a CPU compile of the same twin program.  Flops are a
property of the optimized HLO, so the two backends should agree to ~1%
(fusion differences move only elementwise flops; the dot flops that
dominate are identical).  This script proves that claim at a shape
BOTH backends can compile (seq 256) and records the delta.

Run on the real chip: `python tools/check_twin_flops.py`
Writes docs/TWIN_FLOPS_r05.json.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    import jax.numpy as jnp

    from bench import _dense_equiv_flops
    from paddle_tpu.models import transformer

    feed = {k: jnp.asarray(v) for k, v in
            transformer.make_fake_batch(8, 256, 32000, 32000).items()}

    def build():
        return transformer.build_model(
            src_vocab_size=32000, trg_vocab_size=32000, max_length=256,
            n_layer=6, n_head=8, d_model=512, d_inner_hid=2048,
            dropout=0.1, use_flash=False, use_amp=True)

    tpu = _dense_equiv_flops(feed, build, platform=None)
    cpu = _dense_equiv_flops(feed, build, platform="cpu")
    rel = (cpu - tpu) / max(tpu, 1.0)
    # r05 measured: cpu twin counts 4.5% FEWER flops than the tpu twin
    # (XLA:CPU fuses/eliminates slightly differently).  The criterion
    # that matters for honesty is NO OVERCLAIM: an MFU whose numerator
    # is the cpu twin must never exceed what the tpu twin would give,
    # so cpu <= tpu*1.02 passes; a small undercount just makes the
    # reported longctx MFU conservative.
    out = {"tpu_twin_flops": tpu, "cpu_twin_flops": cpu,
           "rel_delta_cpu_minus_tpu": round(rel, 6),
           "ok_no_overclaim": bool(cpu <= tpu * 1.02)}
    print(json.dumps(out))
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "TWIN_FLOPS_r05.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
