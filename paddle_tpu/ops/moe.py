"""Mixture-of-Experts FFN with expert parallelism (GShard / Switch
Transformer routing).

The reference tree (Fluid 1.2) predates MoE; this op exists because
expert parallelism is a first-class scale axis on TPU meshes (ep in
dp/tp/pp/sp/ep).  TPU-first design, not a port: routing, dispatch and
combine are dense einsums over a static expert-capacity buffer — no
dynamic shapes, no scatter — so GSPMD shards the expert dimension over
the mesh's `ep`/`mp` axis and inserts the all-to-alls itself (the
standard GShard lowering; see PAPERS.md GShard/Switch entries for the
published formulation).

Routing (top-1 "switch" or top-2):
- gate logits (B, E) from X @ GateW; probs = softmax
- per-expert capacity C = ceil(B * top_k / E * capacity_factor);
  tokens beyond an expert's capacity are DROPPED (their combine weight
  is zero and the residual path carries them — the Switch convention);
  top-2 combine weights are the GShard normalization p_i / (p1 + p2)
- position of each token in its expert's buffer = exclusive cumsum of
  the dispatch mask (deterministic, order-preserving)
- dispatch: (B, E, C) one-hot plan; expert_in = dispatchᵀ @ X
- experts: per-expert 2-layer FFN as batched einsums (E in the batch
  dim -> one MXU matmul per projection across ALL experts)
- combine: out = Σ_ec gate_prob * dispatch * expert_out

AuxLoss is the Switch load-balancing loss: E * Σ_e (fraction of tokens
routed to e) * (mean router prob of e); add `aux_weight * AuxLoss` to
the training objective to keep routing balanced.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first, opt_in, out


def _act(name):
    return {"relu": jax.nn.relu, "gelu": jax.nn.gelu,
            "tanh": jnp.tanh, "identity": lambda v: v,
            None: jax.nn.relu}[name]


@register_op("moe_ffn")
def moe_ffn(ctx, ins, attrs):
    """X (..., D); GateW (D, E); W1 (E, D, H); B1 (E, H); W2 (E, H, D);
    B2 (E, D).  Outputs Out (..., D), AuxLoss (1,), plus router stats
    (Fraction (E,) tokens-per-expert) for observability."""
    x = first(ins, "X")
    gate_w = first(ins, "GateW")
    w1, b1 = first(ins, "W1"), opt_in(ins, "B1")
    w2, b2 = first(ins, "W2"), opt_in(ins, "B2")
    top_k = int(attrs.get("top_k", 1))
    cap_factor = float(attrs.get("capacity_factor", 1.25))
    act = _act(attrs.get("act", "relu"))
    if top_k not in (1, 2):
        raise ValueError(f"moe_ffn: top_k must be 1 or 2, got {top_k}")
    if top_k > gate_w.shape[1]:
        raise ValueError(
            f"moe_ffn: top_k={top_k} needs at least that many experts, "
            f"got E={gate_w.shape[1]} (the second pass would re-route "
            f"to the same expert)")

    lead = x.shape[:-1]
    d = x.shape[-1]
    e = gate_w.shape[1]
    xf = x.reshape(-1, d)
    b = xf.shape[0]
    # C = ceil(B * top_k / E * capacity_factor), the documented formula
    import math

    cap = max(1, int(math.ceil(b * top_k / e * cap_factor)))

    logits = (xf @ gate_w).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)          # (B, E)

    combine = jnp.zeros((b, e, cap), xf.dtype)
    used = jnp.zeros((b, e), bool)
    fill = jnp.zeros((e,), jnp.float32)  # slots taken by earlier k's
    for k in range(top_k):
        masked = jnp.where(used, -jnp.inf, logits)
        idx = jnp.argmax(masked, axis=-1)            # (B,)
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)
        # deterministic position in the expert buffer (token order),
        # offset by the slots previous routing passes already filled
        pos = (jnp.cumsum(onehot, axis=0) - onehot)  # exclusive
        pos = jnp.sum((pos + fill[None, :]) * onehot, axis=-1)  # (B,)
        fill = fill + jnp.sum(onehot, axis=0)
        fits = pos < cap
        gate = jnp.sum(probs * onehot, axis=-1)      # (B,)
        pos_oh = jax.nn.one_hot(
            jnp.where(fits, pos, 0).astype(jnp.int32), cap,
            dtype=jnp.float32)
        plan = (onehot[:, :, None] * pos_oh[:, None, :]
                * jnp.where(fits, gate, 0.0)[:, None, None])
        combine = combine + plan.astype(xf.dtype)
        used = used | (onehot > 0)

    if top_k == 2:
        # GShard top-2 normalization: divide by the prob mass of the
        # CHOSEN experts (p1 + p2) so the pair's weights sum to 1; a
        # capacity-dropped choice simply vanishes, leaving the kept
        # expert at p_kept/(p1+p2) — never amplified
        chosen = jnp.sum(probs * used, axis=-1)[:, None, None]
        combine = combine / jnp.maximum(chosen, 1e-9).astype(
            combine.dtype)

    dispatch = (combine > 0).astype(xf.dtype)        # (B, E, C)
    expert_in = jnp.einsum("bec,bd->ecd", dispatch, xf)
    h = act(jnp.einsum("ecd,edh->ech", expert_in, w1)
            + (b1[:, None, :] if b1 is not None else 0.0))
    expert_out = (jnp.einsum("ech,ehd->ecd", h, w2)
                  + (b2[:, None, :] if b2 is not None else 0.0))
    yf = jnp.einsum("bec,ecd->bd", combine, expert_out)

    # Switch load-balancing loss on the top-1 assignment
    top1 = jax.nn.one_hot(jnp.argmax(logits, axis=-1), e,
                          dtype=jnp.float32)
    fraction = jnp.mean(top1, axis=0)                # (E,)
    mean_prob = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(fraction * mean_prob)

    return {"Out": [yf.reshape(lead + (d,))],
            "AuxLoss": [aux.reshape(1)],
            "Fraction": [fraction]}
