"""High-level contrib APIs (reference: python/paddle/fluid/contrib/)."""

from . import slim  # noqa: F401
from .serving import serve  # noqa: F401
from .trainer import (BeginEpochEvent, BeginStepEvent,  # noqa: F401
                      CheckpointConfig, EndEpochEvent, EndStepEvent,
                      Inferencer, Trainer)
