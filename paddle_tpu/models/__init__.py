"""Model zoo mirroring the reference benchmark models.

reference: benchmark/fluid/models/{mnist,resnet,vgg,stacked_dynamic_lstm,
machine_translation,se_resnext}.py plus the BASELINE.json tracked set
(ResNet-50, Transformer, BERT-base, stacked LSTM, DeepFM).  Each module
exposes build_model(...) appending to the default main/startup programs
and returning the interesting vars.
"""

from . import bert  # noqa: F401
from . import deepfm  # noqa: F401
from . import mnist  # noqa: F401
from . import resnet  # noqa: F401
from . import se_resnext  # noqa: F401
from . import sequence_tagging  # noqa: F401
from . import stacked_dynamic_lstm  # noqa: F401
from . import transformer  # noqa: F401
from . import vgg  # noqa: F401
