"""Unique name generator (reference: python/paddle/fluid/unique_name.py)."""

from __future__ import annotations

import contextlib
from collections import defaultdict


class UniqueNameGenerator:
    def __init__(self):
        self.ids = defaultdict(int)

    def __call__(self, key: str) -> str:
        tmp = self.ids[key]
        self.ids[key] += 1
        return f"{key}_{tmp}"


generator = UniqueNameGenerator()

# Active name-scope prefixes (fluid framework.py name_scope); prefixes are
# cosmetic namespacing applied to generated names.
_scope_stack: list = []


def generate(key: str) -> str:
    if _scope_stack:
        prefix = "/".join(_scope_stack)
        if not key.startswith(prefix + "/"):
            key = prefix + "/" + key
    return generator(key)


@contextlib.contextmanager
def guard(new_generator: UniqueNameGenerator | None = None):
    global generator
    old = generator
    generator = new_generator or UniqueNameGenerator()
    try:
        yield
    finally:
        generator = old
