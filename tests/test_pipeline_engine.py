"""Pipeline parallelism as a framework capability
(parallel/pipeline_engine.py): fluid Programs built with
fluid.pipeline_scope()/pipeline_segment() execute as a GPipe schedule
on meshes with a pp axis — loss parity vs the unpipelined program,
dp x pp composition, inertness without a pp axis, and loud structure
errors."""

from __future__ import annotations

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, optimizer
from paddle_tpu.models import bert, transformer
from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.pipeline_engine import (PipelineStructureError,
                                                 analyze_group)


def _build_transformer(pipeline, n_layer=4, seed=0):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        model = transformer.build_model(
            src_vocab_size=128, trg_vocab_size=128, max_length=16,
            n_layer=n_layer, n_head=2, d_model=32, d_inner_hid=64,
            dropout=0.0, with_optimizer=True, learning_rate=0.5,
            warmup_steps=10, label_smooth_eps=0.1, pipeline=pipeline)
        exe = fluid.Executor()
        exe.run(startup)
    return main, scope, model, exe


def _run_steps(main, scope, model, exe, batch, mesh=None, steps=3,
               micro=0):
    with fluid.scope_guard(scope):
        prog = main
        if mesh is not None:
            bs = fluid.BuildStrategy()
            if micro:
                bs.pipeline_microbatches = micro
            prog = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=model["loss"].name, build_strategy=bs,
                mesh=mesh)
        losses = []
        for _ in range(steps):
            (l,) = exe.run(prog, feed=batch, fetch_list=[model["loss"]])
            losses.append(float(np.ravel(l)[0]))
    return losses


BATCH = transformer.make_fake_batch(8, max_length=16, src_vocab=128,
                                    trg_vocab=128)


def _ref_losses():
    main, scope, model, exe = _build_transformer(False)
    return _run_steps(main, scope, model, exe, BATCH)


REF = None


def _ref():
    global REF
    if REF is None:
        REF = _ref_losses()
    return REF


def test_pipelined_transformer_loss_parity_pp4():
    """3 full training steps (fwd + grad through the GPipe schedule +
    Adam) match the unpipelined program."""
    main, scope, model, exe = _build_transformer(True)
    got = _run_steps(main, scope, model, exe, BATCH,
                     mesh=make_mesh({"pp": 4}))
    np.testing.assert_allclose(got, _ref(), rtol=1e-4, atol=1e-4)


def test_pipelined_transformer_dp_x_pp():
    """dp2 x pp2: batch sharded over dp, stacks pipelined over pp."""
    main, scope, model, exe = _build_transformer(True)
    got = _run_steps(main, scope, model, exe, BATCH,
                     mesh=make_mesh({"dp": 2, "pp": 2}))
    np.testing.assert_allclose(got, _ref(), rtol=1e-4, atol=1e-4)


def test_pipeline_microbatch_override():
    main, scope, model, exe = _build_transformer(True)
    got = _run_steps(main, scope, model, exe, BATCH,
                     mesh=make_mesh({"pp": 2}), micro=4)
    np.testing.assert_allclose(got, _ref(), rtol=1e-4, atol=1e-4)


def test_pipeline_tags_inert_without_pp_axis():
    """The tagged program on a dp-only mesh runs the ordinary
    sequential path — identical losses."""
    main, scope, model, exe = _build_transformer(True)
    got = _run_steps(main, scope, model, exe, BATCH,
                     mesh=make_mesh({"dp": 2}))
    np.testing.assert_allclose(got, _ref(), rtol=1e-5, atol=1e-5)


def test_pipelined_bert_trains():
    """BERT (encoder-only flagship) with pipeline=True descends on a
    pp mesh; dropout active (different masks per microbatch — only
    finiteness/descent is asserted)."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 0
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        model = bert.build_model(
            vocab_size=128, max_len=16, n_layer=4, n_head=2,
            d_model=32, d_inner=64, max_predictions=4,
            learning_rate=2e-3, warmup_steps=5, dropout=0.1,
            pipeline=True)
        exe = fluid.Executor()
        exe.run(startup)
    batch = bert.make_fake_batch(8, max_len=16, vocab_size=128,
                                 max_predictions=4)
    losses = _run_steps(main, scope, model, exe, batch,
                        mesh=make_mesh({"pp": 4}), steps=8)
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses


def test_structure_error_non_identical_segments():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data("x", shape=[8])
        with fluid.pipeline_scope():
            with fluid.pipeline_segment():
                x = layers.fc(x, size=8, act="relu")
            with fluid.pipeline_segment():
                x = layers.fc(x, size=8, act="tanh")  # differs
        loss = layers.mean(x)
        optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        prog = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name, mesh=make_mesh({"pp": 2}))
        with pytest.raises(Exception, match="structurally identical"):
            exe.run(prog, feed={"x": np.ones((4, 8), np.float32)},
                    fetch_list=[loss])


def test_structure_error_layers_not_divisible_by_pp():
    main, scope, model, exe = _build_transformer(True, n_layer=3)
    with pytest.raises(Exception, match="pp \\| n_layers"):
        _run_steps(main, scope, model, exe, BATCH,
                   mesh=make_mesh({"pp": 2}))


def test_segment_outside_scope_raises():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with pytest.raises(RuntimeError, match="pipeline_scope"):
            with fluid.pipeline_segment():
                pass


def test_pipeline_plus_recompute():
    """pipeline=True + recompute=True: stages replay under
    jax.checkpoint; parity with the plain program still holds."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 0
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        model = transformer.build_model(
            src_vocab_size=128, trg_vocab_size=128, max_length=16,
            n_layer=4, n_head=2, d_model=32, d_inner_hid=64,
            dropout=0.0, with_optimizer=True, learning_rate=0.5,
            warmup_steps=10, label_smooth_eps=0.1, pipeline=True,
            recompute=True)
        exe = fluid.Executor()
        exe.run(startup)
    got = _run_steps(main, scope, model, exe, BATCH,
                     mesh=make_mesh({"pp": 4}))
    np.testing.assert_allclose(got, _ref(), rtol=1e-4, atol=1e-4)


def test_pp_x_mp_is_a_designed_error():
    """pp×mp composition (ISSUE 10): on this jax/XLA the manual pp
    region would silently REPLICATE mp-sharded params inside every
    stage (partial-auto shard_map dies in SPMD partitioning with
    'PartitionId instruction is not supported'), so the engine raises
    a designed PipelineStructureError naming the composed axes instead
    of benching mp-degree-fold redundant compute as tensor
    parallelism.  dp×pp (the batch axis) stays supported — see
    test_pipelined_transformer_dp_x_pp.  Mirrored by the
    dryrun_multichip pp×mp case (docs/DIST.md, pp×mp status)."""
    main, scope, model, exe = _build_transformer(True, n_layer=2)
    with pytest.raises(PipelineStructureError,
                       match="cannot compose with in-stage sharded "
                             "axes \\['mp'\\]"):
        _run_steps(main, scope, model, exe, BATCH,
                   mesh=make_mesh({"pp": 2, "mp": 2}), steps=1)
