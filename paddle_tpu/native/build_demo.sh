#!/bin/sh
# Build the C++ train demo (reference: paddle/fluid/train/demo build).
set -e
cd "$(dirname "$0")"
CXX="${CXX:-g++}"
PY_INC="$(python3-config --includes)"
PY_LD="$(python3-config --ldflags --embed 2>/dev/null \
         || python3-config --ldflags)"
# shellcheck disable=SC2086
"$CXX" -O2 -o train_demo train_demo.cc $PY_INC $PY_LD
echo "built $(pwd)/train_demo"
