"""Semantic role labeling — the db_lstm sequence-tagging book model.

reference: python/paddle/fluid/tests/book/test_label_semantic_roles.py:53
(db_lstm) — 8 input features (word, 5 context windows, predicate, mark)
embedded and mixed, a `depth`-deep stack of alternating-direction
dynamic LSTMs with direct edges, a per-tag emission projection, and a
linear-chain CRF objective with crf_decoding inference.

TPU adaptations (SURVEY §5.7 segment style): features are padded
(B, T) int64 with one shared `.seq_len` companion instead of LoD; the
LSTM stack runs over padded batches with masked recurrence
(ops/rnn.py); relu candidate activation and sigmoid cell activation
follow the reference's db_lstm arguments verbatim.
"""

from __future__ import annotations

import numpy as np

from .. import layers, optimizer
from ..param_attr import ParamAttr

FEATURES = ("word", "ctx_n2", "ctx_n1", "ctx_0", "ctx_p1", "ctx_p2")


def db_lstm(feats, predicate, mark, word_dict_len, label_dict_len,
            pred_dict_len, mark_dict_len=2, word_dim=32, mark_dim=5,
            hidden_dim=128, depth=4, emb_lr=2.0):
    """Emission scores (B, T, label_dict_len).  feats: list of the six
    word-feature vars in FEATURES order."""
    word_embs = [
        layers.embedding(
            x, size=[word_dict_len, word_dim],
            param_attr=ParamAttr(name="srl_word_emb",
                                 learning_rate=emb_lr))
        for x in feats
    ]
    pred_emb = layers.embedding(
        predicate, size=[pred_dict_len, word_dim],
        param_attr=ParamAttr(name="srl_vemb"))
    mark_emb = layers.embedding(mark, size=[mark_dict_len, mark_dim])
    emb_layers = word_embs + [pred_emb, mark_emb]

    hidden_0 = layers.sums([
        layers.fc(emb, size=hidden_dim * 4, num_flatten_dims=2)
        for emb in emb_layers
    ])
    lstm_0, _cell = layers.dynamic_lstm(
        hidden_0, size=hidden_dim * 4,
        candidate_activation="relu", gate_activation="sigmoid",
        cell_activation="sigmoid")

    # stack L-LSTM / R-LSTM with direct edges (reference depth loop)
    input_tmp = [hidden_0, lstm_0]
    for i in range(1, depth):
        mix_hidden = layers.sums([
            layers.fc(input_tmp[0], size=hidden_dim * 4,
                      num_flatten_dims=2),
            layers.fc(input_tmp[1], size=hidden_dim * 4,
                      num_flatten_dims=2),
        ])
        lstm, _cell = layers.dynamic_lstm(
            mix_hidden, size=hidden_dim * 4,
            candidate_activation="relu", gate_activation="sigmoid",
            cell_activation="sigmoid", is_reverse=((i % 2) == 1))
        input_tmp = [mix_hidden, lstm]

    return layers.sums([
        layers.fc(input_tmp[0], size=label_dict_len,
                  num_flatten_dims=2, act="tanh"),
        layers.fc(input_tmp[1], size=label_dict_len,
                  num_flatten_dims=2, act="tanh"),
    ])


def build_model(word_dict_len=200, label_dict_len=9, pred_dict_len=50,
                max_length=16, word_dim=32, mark_dim=5, hidden_dim=32,
                depth=4, learning_rate=0.01, with_optimizer=True):
    """Training graph: returns {"loss", "crf_decode", "feeds"}."""
    feats = [layers.data(name=n, shape=[max_length], dtype="int64",
                         lod_level=1) for n in FEATURES]
    predicate = layers.data(name="verb", shape=[max_length],
                            dtype="int64", lod_level=1)
    mark = layers.data(name="mark", shape=[max_length], dtype="int64",
                       lod_level=1)
    target = layers.data(name="target", shape=[max_length],
                         dtype="int64", lod_level=1)

    feature_out = db_lstm(feats, predicate, mark, word_dict_len,
                          label_dict_len, pred_dict_len,
                          word_dim=word_dim, mark_dim=mark_dim,
                          hidden_dim=hidden_dim, depth=depth)
    # the op emits the negative log-likelihood (the minimized cost,
    # matching the reference's usage: avg_cost = mean(crf_cost))
    crf_cost = layers.linear_chain_crf(
        feature_out, target,
        param_attr=ParamAttr(name="srl_crfw"))
    avg_cost = layers.mean(crf_cost)
    crf_decode = layers.crf_decoding(
        feature_out, param_attr=ParamAttr(name="srl_crfw"))
    if with_optimizer:
        optimizer.SGD(learning_rate=learning_rate).minimize(avg_cost)
    feeds = list(FEATURES) + ["verb", "mark", "target"]
    return {"loss": avg_cost, "crf_decode": crf_decode, "feeds": feeds}


def make_fake_batch(batch_size, max_length=16, word_dict_len=200,
                    label_dict_len=9, pred_dict_len=50, seed=0):
    """Synthetic tagged batch: the target tag is a deterministic
    function of the word id so the model can learn it."""
    rng = np.random.RandomState(seed)
    lens = rng.randint(max(2, max_length // 2), max_length + 1,
                       (batch_size,)).astype(np.int32)
    words = rng.randint(0, word_dict_len, (batch_size, max_length))
    batch = {}
    for name in FEATURES:
        shift = {"ctx_n2": -2, "ctx_n1": -1, "ctx_0": 0,
                 "ctx_p1": 1, "ctx_p2": 2}.get(name, 0)
        rolled = np.roll(words, shift, axis=1) if shift else words
        batch[name] = rolled.astype(np.int64)
        batch[f"{name}.seq_len"] = lens
    batch["verb"] = np.tile(
        rng.randint(0, pred_dict_len, (batch_size, 1)),
        (1, max_length)).astype(np.int64)
    batch["verb.seq_len"] = lens
    batch["mark"] = (words % 2).astype(np.int64)
    batch["mark.seq_len"] = lens
    batch["target"] = (words % label_dict_len).astype(np.int64)
    batch["target.seq_len"] = lens
    return batch
