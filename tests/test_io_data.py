"""Checkpoint save/load + inference export + reader/DataFeeder tests.

Mirrors reference tests: test_inference_model_io.py, reader decorator
tests, DataFeeder tests.
"""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import io, layers
from paddle_tpu.data import (DataFeeder, batch, buffered, chain, compose,
                             dataset, firstn, map_readers, shuffle,
                             xmap_readers)


def test_save_load_persistables_roundtrip(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.fc(x, size=3)
        loss = layers.mean(y)
        fluid.optimizer.AdamOptimizer(0.01).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[loss])
        w = main.all_parameters()[0]
        w_before = np.asarray(scope.find_var(w.name))
        io.save_persistables(exe, str(tmp_path), main)
        # clobber and reload
        scope.set_var(w.name, np.zeros_like(w_before))
        io.load_persistables(exe, str(tmp_path), main)
        np.testing.assert_allclose(np.asarray(scope.find_var(w.name)),
                                   w_before)
        # adam moments saved too
        assert scope.find_var(f"{w.name}.moment1") is not None


def test_save_load_inference_model(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data(name="x", shape=[4], dtype="float32")
        h = layers.fc(x, size=8, act="relu")
        out = layers.fc(h, size=2, act="softmax")
        lbl = layers.data(name="lbl", shape=[1], dtype="int64")
        loss = layers.mean(layers.cross_entropy(out, lbl))
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        xv = np.random.RandomState(0).rand(3, 4).astype(np.float32)
        exe.run(main, feed={"x": xv, "lbl": np.zeros((3, 1), np.int64)},
                fetch_list=[loss])  # one train step
        test_prog = main.clone(for_test=True)
        (expected,) = exe.run(test_prog, feed={"x": xv},
                              fetch_list=[out.name])
        io.save_inference_model(str(tmp_path), ["x"], [out], exe, main)

    # fresh scope + fresh executor: the exported dir is self-contained
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe2 = fluid.Executor()
        prog, feed_names, fetch_vars = io.load_inference_model(
            str(tmp_path), exe2)
        assert feed_names == ["x"]
        (got,) = exe2.run(prog, feed={"x": xv}, fetch_list=fetch_vars)
        np.testing.assert_allclose(got, expected, rtol=1e-5)
        # label/loss ops pruned from the exported program
        types = [op.type for op in prog.global_block().ops]
        assert "cross_entropy" not in types and "sgd" not in types


def test_version_check_rejects_future(tmp_path):
    from paddle_tpu.core.desc import load_program_dict

    with pytest.raises(RuntimeError):
        load_program_dict('{"version": 99}')


def test_reader_decorators():
    def r():
        yield from range(10)

    assert list(firstn(r, 3)()) == [0, 1, 2]
    assert list(batch(r, 4)()) == [[0, 1, 2, 3], [4, 5, 6, 7], [8, 9]]
    assert list(batch(r, 4, drop_last=True)()) == [[0, 1, 2, 3],
                                                   [4, 5, 6, 7]]
    assert sorted(shuffle(r, 5)()) == list(range(10))
    assert list(chain(r, r)()) == list(range(10)) * 2
    assert list(map_readers(lambda a, b: a + b, r, r)()) == \
        [2 * i for i in range(10)]
    assert list(compose(r, r)()) == [(i, i) for i in range(10)]
    assert sorted(buffered(r, 2)()) == list(range(10))
    got = sorted(xmap_readers(lambda s: s * 2, r, 3, 4)())
    assert got == [2 * i for i in range(10)]
    ordered = list(xmap_readers(lambda s: s * 2, r, 3, 4, order=True)())
    assert ordered == [2 * i for i in range(10)]


def test_data_feeder_pads_sequences():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        words = layers.data(name="w", shape=[-1], dtype="int64",
                            lod_level=1, append_batch_size=False)
        label = layers.data(name="l", shape=[1], dtype="int64",
                            append_batch_size=True)
        feeder = DataFeeder(feed_list=[words, label], program=main)
    batch_rows = [([1, 2, 3], 0), ([4, 5], 1), ([6], 0)]
    feed = feeder.feed(batch_rows)
    assert feed["w"].shape[0] == 3
    assert feed["w"].shape[1] % 8 == 0  # bucketed padding
    np.testing.assert_array_equal(feed["w.seq_len"], [3, 2, 1])
    np.testing.assert_array_equal(feed["w"][1, :2], [4, 5])
    assert feed["w"][1, 2] == 0
    assert feed["l"].shape == (3, 1)


def test_synthetic_datasets_contract():
    x, y = next(dataset.mnist.train(n=5)())
    assert x.shape == (1, 28, 28) and 0 <= y < 10
    x, y = next(dataset.uci_housing.train(n=5)())
    assert x.shape == (13,) and y.shape == (1,)
    toks, lbl = next(dataset.imdb.train(n=5)())
    assert toks.dtype == np.int64 and lbl in (0, 1)


def test_train_with_feeder_and_reader_pipeline():
    """End-to-end: dataset → shuffle/batch reader → DataFeeder →
    Executor (the reference's canonical training loop shape)."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        img = layers.data(name="img", shape=[1, 28, 28], dtype="float32")
        lbl = layers.data(name="lbl", shape=[1], dtype="int64")
        h = layers.fc(img, size=32, act="relu")
        logits = layers.fc(h, size=10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, lbl))
        fluid.optimizer.AdamOptimizer(1e-3).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        feeder = DataFeeder(feed_list=[img, lbl], program=main)
        reader = batch(shuffle(dataset.mnist.train(n=256), 64), 32,
                       drop_last=True)
        losses = []
        for b in reader():
            rows = [(x, np.asarray([y], np.int64)) for x, y in b]
            (lv,) = exe.run(main, feed=feeder.feed(rows),
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        assert np.isfinite(losses).all() if hasattr(np, 'isfinite') else True
        assert losses[-1] < losses[0] * 2


def test_lod_level2_feed_and_pool():
    """Nested sequences (reference LoD level 2, lod_tensor.h:58): feed a
    batch of paragraphs (lists of sentences of word vectors), pool the
    innermost level, then the outer level."""
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import layers

    B, S1, S2, D = 2, 4, 8, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[B, S1, S2, D],
                        append_batch_size=False, lod_level=2)
        inner = layers.sequence_pool(x, "sum")       # (B, S1, D), lvl-1
        outer = layers.sequence_pool(inner, "sum")   # (B, D)
        feeder = fluid.DataFeeder(feed_list=[x], program=main)

    # sample 0: 2 sentences (3 and 1 words); sample 1: 1 sentence (2)
    rng = np.random.RandomState(0)
    s0 = [rng.rand(3, D).astype(np.float32),
          rng.rand(1, D).astype(np.float32)]
    s1v = [rng.rand(2, D).astype(np.float32)]
    feed = feeder.feed([(s0,), (s1v,)])
    assert feed["x"].shape == (2, S1, S2, D)
    np.testing.assert_array_equal(feed["x.seq_len"], [2, 1])
    assert feed["x.seq_len2"].shape == (2, S1)
    np.testing.assert_array_equal(feed["x.seq_len2"][0, :2], [3, 1])

    exe = fluid.Executor()
    (o,) = exe.run(main, feed=feed, fetch_list=[outer])
    want0 = s0[0].sum(axis=0) + s0[1].sum(axis=0)
    want1 = s1v[0].sum(axis=0)
    np.testing.assert_allclose(o[0], want0, rtol=1e-5)
    np.testing.assert_allclose(o[1], want1, rtol=1e-5)


def test_lod_level3_rejected():
    import pytest

    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with pytest.raises(NotImplementedError):
            layers.data("deep", shape=[2, 3, 4, 5],
                        append_batch_size=False, lod_level=3)


# ---------------------------------------------------------------------------
# Real-format dataset ingestion (VERDICT r3 §2.4 dataset row): parsers
# read the datasets' ACTUAL on-disk formats; fixtures below are
# format-faithful files written locally (zero-egress stand-in for the
# reference's downloads).
# ---------------------------------------------------------------------------

def _write_mnist_fixture(d, n=20, seed=3):
    import gzip
    import struct

    rng = np.random.RandomState(seed)
    imgs = rng.randint(0, 256, (n, 28, 28)).astype(np.uint8)
    lbls = rng.randint(0, 10, (n,)).astype(np.uint8)
    with gzip.open(os.path.join(d, "train-images-idx3-ubyte.gz"),
                   "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28))
        f.write(imgs.tobytes())
    with gzip.open(os.path.join(d, "train-labels-idx1-ubyte.gz"),
                   "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(lbls.tobytes())
    return imgs, lbls


def test_mnist_idx_format_parses(tmp_path):
    from paddle_tpu.data import dataset

    imgs, lbls = _write_mnist_fixture(str(tmp_path))
    samples = list(dataset.mnist.train(data_dir=str(tmp_path))())
    assert len(samples) == 20
    x0, y0 = samples[0]
    assert x0.shape == (784,) and x0.dtype == np.float32
    np.testing.assert_allclose(
        x0, imgs[0].reshape(-1).astype(np.float32) / 255.0 * 2.0 - 1.0)
    assert y0 == int(lbls[0])
    # corrupt magic fails loudly
    import gzip
    import struct

    with gzip.open(os.path.join(str(tmp_path),
                                "train-images-idx3-ubyte.gz"), "wb") as f:
        f.write(struct.pack(">IIII", 1234, 1, 28, 28))
    with pytest.raises(IOError, match="magic"):
        list(dataset.mnist.train(data_dir=str(tmp_path))())


def test_cifar_pickle_tar_parses(tmp_path):
    import io as _io
    import pickle
    import tarfile

    from paddle_tpu.data import dataset

    rng = np.random.RandomState(4)
    data = rng.randint(0, 256, (8, 3072)).astype(np.uint8)
    labels = rng.randint(0, 10, (8,)).tolist()
    tar_path = os.path.join(str(tmp_path), "cifar-10-python.tar.gz")
    with tarfile.open(tar_path, "w:gz") as t:
        for name, sl in (("cifar-10-batches-py/data_batch_1",
                          slice(0, 5)),
                         ("cifar-10-batches-py/test_batch",
                          slice(5, 8))):
            payload = pickle.dumps({b"data": data[sl],
                                    b"labels": labels[sl]})
            info = tarfile.TarInfo(name)
            info.size = len(payload)
            t.addfile(info, _io.BytesIO(payload))
    train = list(dataset.cifar.train10(data_dir=str(tmp_path))())
    test = list(dataset.cifar.test10(data_dir=str(tmp_path))())
    assert len(train) == 5 and len(test) == 3
    np.testing.assert_allclose(train[0][0],
                               data[0].astype(np.float32) / 255.0)
    assert train[0][1] == labels[0]


def test_uci_housing_table_parses(tmp_path):
    from paddle_tpu.data import dataset

    rng = np.random.RandomState(5)
    table = rng.rand(10, 14) * 10
    p = os.path.join(str(tmp_path), "housing.data")
    with open(p, "w") as f:
        for row in table:
            f.write(" ".join(f"{v:.6f}" for v in row) + "\n")
    train = list(dataset.uci_housing.train(data_dir=str(tmp_path))())
    test = list(dataset.uci_housing.test(data_dir=str(tmp_path))())
    assert len(train) == 8 and len(test) == 2  # 0.8 split
    # reference normalization: (x - avg) / (max - min) per feature
    maxs, mins = table.max(0), table.min(0)
    avgs = table.mean(0)
    want = (table[0, :13] - avgs[:13]) / (maxs[:13] - mins[:13])
    np.testing.assert_allclose(train[0][0], want.astype(np.float32),
                               rtol=1e-5)
    np.testing.assert_allclose(train[0][1],
                               [np.float32(table[0, 13])], rtol=1e-5)


def test_imdb_aclimdb_tar_parses(tmp_path):
    import io as _io
    import tarfile

    from paddle_tpu.data import dataset

    docs = {
        "aclImdb/train/pos/0_9.txt": b"a great great movie!",
        "aclImdb/train/neg/0_2.txt": b"a terrible movie.",
        "aclImdb/test/pos/0_8.txt": b"great fun",
    }
    tar_path = os.path.join(str(tmp_path), "aclImdb_v1.tar.gz")
    with tarfile.open(tar_path, "w:gz") as t:
        for name, text in docs.items():
            info = tarfile.TarInfo(name)
            info.size = len(text)
            t.addfile(info, _io.BytesIO(text))
    # reference defaults (labeled-docs pattern, cutoff=150) would drop
    # every word of this tiny fixture; build explicitly with cutoff=0
    wd = dataset.imdb.build_dict(tar_path, cutoff=0)
    # the dict pattern spans train+test pos/neg: 'great' freq 3 -> id 0;
    # '<unk>' is always last, like the reference's build_dict
    assert wd[b"great"] == 0 and wd[b"<unk>"] == len(wd) - 1
    # the default pattern excludes unsup/ and urls_*.txt members
    assert "unsup" not in dataset.imdb.DICT_PATTERN
    assert dataset.imdb.build_dict.__defaults__[1] == 150
    samples = list(dataset.imdb.train(wd, data_dir=str(tmp_path))())
    assert len(samples) == 2
    (pos_ids, pos_lbl), (neg_ids, neg_lbl) = samples
    assert pos_lbl == 0 and neg_lbl == 1      # reference: pos=0, neg=1
    assert pos_ids == [wd[b"a"], wd[b"great"], wd[b"great"],
                       wd[b"movie"]]          # punctuation stripped
    test_s = list(dataset.imdb.test(wd, data_dir=str(tmp_path))())
    assert len(test_s) == 1 and test_s[0][1] == 0


def test_fit_a_line_book_flow(tmp_path):
    """Book ch.1 fit_a_line (reference tests/book/test_fit_a_line.py):
    uci_housing reader -> batch decorator -> linear regression via
    square_error_cost -> SGD -> save/load inference model -> predict."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data(name="x", shape=[13], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        y_predict = layers.fc(input=x, size=1, act=None)
        cost = layers.square_error_cost(input=y_predict, label=y)
        avg_cost = layers.mean(cost)
        fluid.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)
        exe = fluid.Executor()
        exe.run(startup)

        # pin data_dir to an empty dir: the deterministic synthetic
        # fallback must be used even when $PADDLE_DATASET_HOME points
        # at a real housing.data (un-normalized labels would change
        # the convergence profile this test asserts)
        reader = batch(dataset.uci_housing.train(data_dir=str(tmp_path)),
                       batch_size=20)
        feeder = fluid.DataFeeder(feed_list=[x, y], place=None)
        first = last = None
        for _ in range(12):
            for data in reader():
                (lv,) = exe.run(main, feed=feeder.feed(data),
                                fetch_list=[avg_cost])
                lv = float(np.asarray(lv).reshape(-1)[0])
                if first is None:
                    first = lv
                last = lv
        assert last < first * 0.5, (first, last)

        d = str(tmp_path / "fit_a_line")
        io.save_inference_model(d, ["x"], [y_predict], exe,
                                main_program=main)
    pred = fluid.Predictor(d)
    out = pred.run({"x": np.zeros((4, 13), np.float32)})
    assert np.asarray(out[0]).shape == (4, 1)


def test_image_transforms():
    """dataset/image.py analog (data/image.py): resize_short keeps
    aspect ratio, crops/flips behave, simple_transform yields CHW
    float32 with mean subtracted."""
    from paddle_tpu.data import image

    rng = np.random.RandomState(0)
    im = rng.randint(0, 256, (40, 60, 3)).astype(np.uint8)

    r = image.resize_short(im, 20)
    assert r.shape == (20, 30, 3)  # shorter edge 20, aspect kept
    # a constant image stays constant under bilinear resize
    const = np.full((17, 33, 3), 77, np.uint8)
    rc = image.resize_short(const, 24)
    assert (rc == 77).all()

    c = image.center_crop(r, 16)
    assert c.shape == (16, 16, 3)
    np.testing.assert_array_equal(c, r[2:18, 7:23])

    f = image.left_right_flip(im)
    np.testing.assert_array_equal(f[:, 0], im[:, -1])

    rcrop = image.random_crop(r, 16, rng=np.random.RandomState(1))
    assert rcrop.shape == (16, 16, 3)

    out = image.simple_transform(im, 32, 24, is_train=False,
                                 mean=[1.0, 2.0, 3.0])
    assert out.shape == (3, 24, 24) and out.dtype == np.float32
    out_tr = image.simple_transform(im, 32, 24, is_train=True,
                                    rng=np.random.RandomState(2))
    assert out_tr.shape == (3, 24, 24)

    # grayscale + per-channel mean must FAIL loudly, not broadcast a
    # (H, W) image into a bogus (3, H, W) tensor
    gray = rng.randint(0, 256, (40, 60)).astype(np.uint8)
    g = image.simple_transform(gray, 32, 24, is_train=False, mean=[7.0])
    assert g.shape == (24, 24)
    with pytest.raises(ValueError, match="per-channel"):
        image.simple_transform(gray, 32, 24, is_train=False,
                               mean=[1.0, 2.0, 3.0])


# --- movielens / wmt14 / wmt16 real-format ingestion (round 5) --------

def _write_movielens_fixture(d):
    """Format-faithful ml-1m.zip: '::'-separated latin-1 .dat files."""
    import zipfile

    movies = (
        "1::Toy Story (1995)::Animation|Children's|Comedy\n"
        "2::Jumanji (1995)::Adventure|Children's|Fantasy\n"
        "3::Heat (1995)::Action|Crime|Thriller\n")
    users = (
        "1::F::1::10::48067\n"
        "2::M::56::16::70072\n"
        "3::M::25::15::55117\n")
    rng = np.random.RandomState(0)
    lines = []
    for i in range(40):
        lines.append("%d::%d::%d::97830948%d\n" % (
            rng.randint(1, 4), rng.randint(1, 4), rng.randint(1, 6), i))
    with zipfile.ZipFile(os.path.join(d, "ml-1m.zip"), "w") as z:
        z.writestr("ml-1m/movies.dat", movies.encode("latin-1"))
        z.writestr("ml-1m/users.dat", users.encode("latin-1"))
        z.writestr("ml-1m/ratings.dat", "".join(lines).encode("latin-1"))


def test_movielens_zip_parses(tmp_path):
    from paddle_tpu.data import dataset

    d = str(tmp_path)
    _write_movielens_fixture(d)
    train = list(dataset.movielens.train(data_dir=d)())
    test = list(dataset.movielens.test(data_dir=d)())
    # the reference's random split: disjoint, covers all 40 ratings
    assert len(train) + len(test) == 40 and len(test) >= 1
    s = train[0]
    uid, gender, age_idx, job, mid, cats, title, rating = s
    assert 1 <= uid <= 3 and gender in (0, 1)
    assert 0 <= age_idx < 7  # age mapped through age_table
    assert 1 <= mid <= 3
    assert all(isinstance(c, int) for c in cats)
    assert all(isinstance(w, int) for w in title)
    # rating 1..5 scaled *2-5 -> [-3, 5]
    assert -3.0 <= rating[0] <= 5.0
    # meta helpers
    assert dataset.movielens.max_user_id(d) == 3
    assert dataset.movielens.max_movie_id(d) == 3
    assert dataset.movielens.max_job_id(d) == 16
    tdict = dataset.movielens.get_movie_title_dict(d)
    assert "toy" in tdict and "heat" in tdict
    cats_all = dataset.movielens.movie_categories(d)
    assert "Animation" in cats_all and "Thriller" in cats_all
    # age bucket: user 1 has age 1 -> index 0; user 2 age 56 -> index 6
    by_uid = {x[0]: x for x in train + test}
    assert by_uid[1][2] == 0 and by_uid[2][2] == 6


def test_recommender_trains_from_movielens_files(tmp_path):
    """VERDICT r4 item 5: the recommender book model trains from
    real-format movielens files end to end."""
    import paddle_tpu as fluid
    from paddle_tpu.data import dataset
    from paddle_tpu.models import recommender

    d = str(tmp_path)
    _write_movielens_fixture(d)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 0
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        model = recommender.build_model(
            user_vocab=dataset.movielens.max_user_id(d) + 1,
            movie_vocab=dataset.movielens.max_movie_id(d) + 1,
            job_vocab=dataset.movielens.max_job_id(d) + 1,
            title_vocab=len(dataset.movielens.get_movie_title_dict(d)),
            title_len=8, batch_size=8, learning_rate=5e-3)
        exe = fluid.Executor()
        exe.run(startup)
        batches = dataset.movielens.batches_for_model(
            dataset.movielens.train(data_dir=d), batch_size=8,
            title_len=8)
        losses = []
        for _ in range(30):  # multiple epochs over the tiny fixture
            for feed in batches():
                (lv,) = exe.run(main, feed=feed,
                                fetch_list=[model["loss"]])
                losses.append(float(np.ravel(lv)[0]))
    assert np.isfinite(losses).all()
    # average over epochs (batch losses are noisy on 8-sample batches)
    assert np.mean(losses[-4:]) < np.mean(losses[:4]) * 0.7, \
        (np.mean(losses[:4]), np.mean(losses[-4:]))


def _write_wmt14_fixture(d):
    """Tar with src.dict/trg.dict + tab-separated parallel text."""
    import io as _io
    import tarfile as _tf

    src_vocab = ["<s>", "<e>", "<unk>", "a", "b", "c", "d"]
    trg_vocab = ["<s>", "<e>", "<unk>", "w", "x", "y", "z"]
    train = ("a b c\tw x y\n"
             "b c d\tx y z\n"
             "a a QQQ\tw w RRR\n"          # OOV -> <unk>
             + " ".join(["a"] * 81) + "\t" + " ".join(["w"] * 81)
             + "\n"                        # >80 tokens: dropped
             "malformed line with no tab\n")
    test = "c b a\ty x w\n"
    p = os.path.join(d, "wmt14.tgz")
    with _tf.open(p, "w:gz") as t:
        for name, text in (("wmt14/src.dict", "\n".join(src_vocab)),
                           ("wmt14/trg.dict", "\n".join(trg_vocab)),
                           ("train/train", train),
                           ("test/test", test)):
            blob = text.encode("utf-8")
            info = _tf.TarInfo(name)
            info.size = len(blob)
            t.addfile(info, _io.BytesIO(blob))
    return p


def test_wmt14_tar_parses(tmp_path):
    from paddle_tpu.data import dataset

    d = str(tmp_path)
    _write_wmt14_fixture(d)
    samples = list(dataset.wmt14.train(dict_size=7, data_dir=d)())
    # 3 usable lines: the 81-token pair dropped, malformed skipped
    assert len(samples) == 3
    src, trg, nxt = samples[0]          # "a b c" / "w x y"
    assert src == [0, 3, 4, 5, 1]       # <s> a b c <e>
    assert trg == [0, 3, 4, 5]          # <s> w x y
    assert nxt == [3, 4, 5, 1]          # w x y <e>
    # OOV maps to UNK_IDX=2
    src3, trg3, _ = samples[2]
    assert src3 == [0, 3, 3, 2, 1] and trg3 == [0, 3, 3, 2]
    # test split + reverse dict
    tst = list(dataset.wmt14.test(dict_size=7, data_dir=d)())
    assert tst[0][0] == [0, 5, 4, 3, 1]
    rsrc, rtrg = dataset.wmt14.get_dict(7, reverse=True, data_dir=d)
    assert rsrc[3] == "a" and rtrg[6] == "z"


def _write_wmt16_fixture(d):
    import io as _io
    import tarfile as _tf

    # en de; 'the' most frequent en word, 'der' most frequent de word
    train = ("the cat sat\tder kater sass\n"
             "the dog ran\tder hund lief\n"
             "the cat ran\tder kater lief\n")
    val = "the dog sat\tder hund sass\n"
    test = "the cat ran\tder kater lief\n"
    p = os.path.join(d, "wmt16.tar.gz")
    with _tf.open(p, "w:gz") as t:
        for name, text in (("wmt16/train", train), ("wmt16/val", val),
                           ("wmt16/test", test)):
            blob = text.encode("utf-8")
            info = _tf.TarInfo(name)
            info.size = len(blob)
            t.addfile(info, _io.BytesIO(blob))
    return p


def test_wmt16_tar_parses_and_builds_dicts(tmp_path):
    from paddle_tpu.data import dataset

    d = str(tmp_path)
    _write_wmt16_fixture(d)
    tp = os.path.join(d, "wmt16.tar.gz")
    en = dataset.wmt16.build_dict(tp, 20, "en")
    # specials reserved 0/1/2; most frequent word first after them
    assert (en["<s>"], en["<e>"], en["<unk>"]) == (0, 1, 2)
    assert en["the"] == 3
    samples = list(dataset.wmt16.train(20, 20, src_lang="en",
                                       data_dir=d)())
    assert len(samples) == 3
    src, trg, nxt = samples[0]
    assert src[0] == 0 and src[-1] == 1 and src[1] == en["the"]
    de = dataset.wmt16.build_dict(tp, 20, "de")
    assert trg[0] == 0 and trg[1] == de["der"]
    assert nxt[-1] == 1
    # src_lang='de' swaps the columns
    sw = list(dataset.wmt16.train(20, 20, src_lang="de",
                                  data_dir=d)())
    assert sw[0][0][1] == de["der"] and sw[0][1][1] == en["the"]
    # dict_size truncation keeps the top-frequency words
    small = dataset.wmt16.build_dict(tp, 4, "en")
    assert len(small) == 4 and "the" in small
    with pytest.raises(ValueError, match="src_lang"):
        dataset.wmt16.train(20, 20, src_lang="fr", data_dir=d)


def test_machine_translation_trains_from_wmt16_files(tmp_path):
    """VERDICT r4 item 5: the NMT book model trains from real-format
    wmt16 files end to end (padded+seq_len batching)."""
    import paddle_tpu as fluid
    from paddle_tpu.data import dataset
    from paddle_tpu.models import machine_translation as mt

    d = str(tmp_path)
    _write_wmt16_fixture(d)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 0
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        loss, feeds = mt.seq_to_seq_net(
            src_vocab_size=20, trg_vocab_size=20, embed_dim=16,
            hidden_dim=32, batch_size=3, max_src_len=8, max_trg_len=8)
        fluid.optimizer.Adam(learning_rate=0.02).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        batches = dataset.padded_nmt_batches(
            dataset.wmt16.train(20, 20, data_dir=d), batch_size=3,
            max_src_len=8, max_trg_len=8)
        losses = []
        for _ in range(15):
            for feed in batches():
                (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
                losses.append(float(np.ravel(lv)[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


# --- round-5 dataset breadth: imikolov/conll05/mq2007/sentiment/
#     voc2012/flowers real-format parsers ---------------------------------

def _write_imikolov_fixture(d):
    import io as _io
    import tarfile as _tf

    train = ("the cat sat on the mat\n"
             "the dog sat on the log\n"
             "the cat ran\n")
    valid = "the dog ran rarewordhere\n"
    p = os.path.join(d, "simple-examples.tgz")
    with _tf.open(p, "w:gz") as t:
        for name, text in (
                ("./simple-examples/data/ptb.train.txt", train),
                ("./simple-examples/data/ptb.valid.txt", valid)):
            blob = text.encode("utf-8")
            info = _tf.TarInfo(name)
            info.size = len(blob)
            t.addfile(info, _io.BytesIO(blob))


def test_imikolov_ptb_parses(tmp_path):
    from paddle_tpu.data import dataset

    d = str(tmp_path)
    _write_imikolov_fixture(d)
    wd = dataset.imikolov.build_dict(min_word_freq=1, data_dir=d)
    # 'the' appears 6x -> most frequent -> id 0; <unk> is LAST
    assert wd["the"] == 0
    assert wd["<unk>"] == len(wd) - 1
    # freq > 1 cut: 'rarewordhere' and 'log'/'mat'/'ran'... appear once
    assert "rarewordhere" not in wd
    grams = list(dataset.imikolov.train(wd, n=3, data_dir=d)())
    # every 3-gram over <s> line <e>; first line has 8 tokens -> 6 grams
    assert len(grams[0]) == 3
    s_id, e_id = wd["<s>"], wd["<e>"]
    assert grams[0][0] == s_id
    unk = wd["<unk>"]
    # SEQ mode
    seqs = list(dataset.imikolov.train(
        wd, n=0, data_type=dataset.imikolov.SEQ, data_dir=d)())
    assert len(seqs) == 3
    src, trg = seqs[0]
    assert src[0] == s_id and trg[-1] == e_id
    assert src[1:] == trg[:-1]


def _write_conll05_fixture(d):
    import gzip as _gz
    import io as _io
    import tarfile as _tf

    # two sentences; sentence 1 has 2 predicates (lemma rows 1 and 2 in
    # column 0, one bracket-tag column per predicate), sentence 2 one
    words = "The\ncat\nsat\n\nDogs\nbark\n\n"
    props = ("-\t(A0*)\t(A0*\n"
             "meow\t(V*)\t*)\n"
             "sit\t(A1*)\t(V*)\n"
             "\n"
             "-\t(A0*)\n"
             "bark\t(V*)\n"
             "\n").replace("\t", " ")
    wbuf, pbuf = _io.BytesIO(), _io.BytesIO()
    with _gz.GzipFile(fileobj=wbuf, mode="wb") as g:
        g.write(words.encode())
    with _gz.GzipFile(fileobj=pbuf, mode="wb") as g:
        g.write(props.encode())
    p = os.path.join(d, "conll05st-tests.tar.gz")
    from paddle_tpu.data.dataset import conll05

    with _tf.open(p, "w:gz") as t:
        for name, blob in ((conll05.WORDS_MEMBER, wbuf.getvalue()),
                           (conll05.PROPS_MEMBER, pbuf.getvalue())):
            info = _tf.TarInfo(name)
            info.size = len(blob)
            t.addfile(info, _io.BytesIO(blob))
    with open(os.path.join(d, "wordDict.txt"), "w") as f:
        f.write("bos\neos\nThe\ncat\nsat\nDogs\nbark\n")
    with open(os.path.join(d, "verbDict.txt"), "w") as f:
        f.write("meow\nsit\nbark\n")
    with open(os.path.join(d, "targetDict.txt"), "w") as f:
        f.write("B-A0\nI-A0\nB-A1\nI-A1\nB-V\nI-V\nO\n")


def test_conll05_props_parse_and_windows(tmp_path):
    from paddle_tpu.data import dataset

    d = str(tmp_path)
    _write_conll05_fixture(d)
    wd, vd, ld = dataset.conll05.get_dict(d)
    assert set(vd) == {"meow", "sit", "bark"}
    # label dict: sorted tags A0, A1, V -> B-A0=0 I-A0=1 ... O=6
    assert ld["B-A0"] == 0 and ld["B-V"] == 4 and ld["O"] == 6
    samples = list(dataset.conll05.test(data_dir=d)())
    # sentence 1 contributes 2 predicate samples, sentence 2 one
    assert len(samples) == 3
    words, c_n2, c_n1, c_0, c_p1, c_p2, pred, mark, labels = samples[0]
    # predicate col 1 of sentence 1: V at token 1 ('cat'), A0 at 0
    assert labels == [ld["B-A0"], ld["B-V"], ld["B-A1"]]
    assert pred == [vd["meow"]] * 3
    # window around verb_index=1: positions 0,1,2 (+2 clipped) marked
    assert mark == [1, 1, 1]
    assert c_0 == [wd["cat"]] * 3 and c_n1 == [wd["The"]] * 3
    assert c_n2 == [wd["bos"]] * 3  # off the left edge
    # multi-token span: second predicate of sentence 1
    _w, _n2, _n1, _c0, _p1, _p2, _pr, _mk, labels2 = samples[1]
    assert labels2 == [ld["B-A0"], ld["I-A0"], ld["B-V"]]


def _write_mq2007_fixture(d):
    lines = []
    rng = np.random.RandomState(0)
    # qid 12 is all-zero relevance: query_filter must drop it
    for qid, rels in ((10, [2, 0, 1]), (11, [0, 0, 1]),
                      (12, [0, 0, 0])):
        for r in rels:
            feats = " ".join(f"{i + 1}:{rng.rand():.6f}"
                             for i in range(46))
            lines.append(f"{r} qid:{qid} {feats} #docid = GX{qid}\n")
    with open(os.path.join(d, "train.txt"), "w") as f:
        f.writelines(lines)


def test_mq2007_letor_parses(tmp_path):
    from paddle_tpu.data import dataset

    d = str(tmp_path)
    _write_mq2007_fixture(d)
    # the all-zero qid 12 is filtered (reference query_filter)
    pts = list(dataset.mq2007.train("pointwise", data_dir=d)())
    assert len(pts) == 6
    rel0, vec0 = pts[0]
    assert rel0 == 2 and vec0.shape == (46,)  # sorted desc per query
    pairs = list(dataset.mq2007.train("pairwise", data_dir=d)())
    # qid 10 rels [2,1,0] -> 3 ordered pairs; qid 11 [1,0,0] -> 2
    assert len(pairs) == 5
    lbl, better, worse = pairs[0]
    assert lbl[0] == 1 and better.shape == worse.shape == (46,)
    lists = list(dataset.mq2007.train("listwise", data_dir=d)())
    assert len(lists) == 2
    rels, vecs = lists[0]
    assert rels.shape == (3, 1) and vecs.shape == (3, 46)
    assert rels[0, 0] >= rels[1, 0] >= rels[2, 0]
    with pytest.raises(ValueError, match="format"):
        list(dataset.mq2007.train("bogus", data_dir=d)())
    # the synthetic fallback validates the format too (a typo must not
    # silently degrade to listwise on machines without the files)
    with pytest.raises(ValueError, match="format"):
        dataset.mq2007.train("listwse", data_dir=str(tmp_path / "no"))


def _write_sentiment_fixture(d):
    root = os.path.join(d, "movie_reviews")
    for cat, texts in (("pos", ["a great great film .",
                                "great fun movie !"]),
                       ("neg", ["a terrible film .",
                                "boring boring movie ."])):
        os.makedirs(os.path.join(root, cat))
        for i, t in enumerate(texts):
            with open(os.path.join(root, cat, f"cv{i}.txt"), "w") as f:
                f.write(t)


def test_sentiment_movie_reviews_parse(tmp_path):
    from paddle_tpu.data import dataset

    d = str(tmp_path)
    _write_sentiment_fixture(d)
    wd = dict(dataset.sentiment.get_word_dict(data_dir=d))
    # 'great' (3) and '.' (3) are the most frequent words
    assert wd["great"] in (0, 1) and wd["."] in (0, 1)
    train = list(dataset.sentiment.reader_creator(d, is_test=False)())
    test = list(dataset.sentiment.reader_creator(d, is_test=True)())
    assert len(train) + len(test) == 4
    ids, label = train[0]
    assert label in (0, 1)
    assert all(isinstance(i, int) for i in ids)


def _write_voc2012_fixture(d):
    import io as _io
    import tarfile as _tf

    from PIL import Image

    from paddle_tpu.data.dataset import voc2012

    rng = np.random.RandomState(5)
    p = os.path.join(d, "VOCtrainval_11-May-2012.tar")
    ims = {}
    with _tf.open(p, "w") as t:
        def add(name, blob):
            info = _tf.TarInfo(name)
            info.size = len(blob)
            t.addfile(info, _io.BytesIO(blob))

        names = ["2007_000001", "2007_000002"]
        # the reference maps train()->'trainval' and test()->'train'
        add(voc2012.SET_FILE.format("trainval"),
            "\n".join(names).encode())
        add(voc2012.SET_FILE.format("train"),
            names[0].encode())
        for name in names:
            im = rng.randint(0, 256, (20, 24, 3)).astype(np.uint8)
            buf = _io.BytesIO()
            Image.fromarray(im).save(buf, "JPEG")
            add(voc2012.DATA_FILE.format(name), buf.getvalue())
            mask = rng.randint(0, 21, (20, 24)).astype(np.uint8)
            pim = Image.fromarray(mask, mode="P")
            pim.putpalette([i for _ in range(85) for i in (0, 0, 0)])
            buf = _io.BytesIO()
            pim.save(buf, "PNG")
            add(voc2012.LABEL_FILE.format(name), buf.getvalue())
            ims[name] = mask
    return ims


def test_voc2012_tar_parses(tmp_path):
    from paddle_tpu.data import dataset

    d = str(tmp_path)
    masks = _write_voc2012_fixture(d)
    samples = list(dataset.voc2012.train(data_dir=d)())
    assert len(samples) == 2
    im, mask = samples[0]
    assert im.shape == (20, 24, 3) and im.dtype == np.uint8
    assert mask.shape == (20, 24) and mask.dtype == np.uint8
    np.testing.assert_array_equal(mask, masks["2007_000001"])
    # test() follows the reference's 'train' list mapping
    assert len(list(dataset.voc2012.test(data_dir=d)())) == 1


def _write_flowers_fixture(d):
    import io as _io
    import tarfile as _tf

    import scipy.io as scio
    from PIL import Image

    rng = np.random.RandomState(6)
    n = 4
    with _tf.open(os.path.join(d, "102flowers.tgz"), "w:gz") as t:
        for i in range(1, n + 1):
            im = rng.randint(0, 256, (40, 30, 3)).astype(np.uint8)
            buf = _io.BytesIO()
            Image.fromarray(im).save(buf, "JPEG")
            blob = buf.getvalue()
            info = _tf.TarInfo(f"jpg/image_{i:05d}.jpg")
            info.size = len(blob)
            t.addfile(info, _io.BytesIO(blob))
    scio.savemat(os.path.join(d, "imagelabels.mat"),
                 {"labels": np.array([[5, 3, 5, 1]], np.uint8)})
    scio.savemat(os.path.join(d, "setid.mat"),
                 {"trnid": np.array([[1, 3]], np.uint16),
                  "tstid": np.array([[2]], np.uint16),
                  "valid": np.array([[4]], np.uint16)})


def test_flowers_real_format_parses(tmp_path):
    from paddle_tpu.data import dataset

    d = str(tmp_path)
    _write_flowers_fixture(d)
    train = list(dataset.flowers.train(data_dir=d)())
    assert len(train) == 2
    im, lbl = train[0]
    assert im.shape == (3, 224, 224) and im.dtype == np.float32
    assert lbl == 4  # 1-based label 5 -> 0-based 4
    test = list(dataset.flowers.test(data_dir=d)())
    assert len(test) == 1 and test[0][1] == 2


def test_sentiment_model_trains_from_movie_reviews_files(tmp_path):
    """The sentiment book model (stacked dynamic LSTM) trains from a
    real-format movie_reviews directory end to end."""
    import paddle_tpu as fluid
    from paddle_tpu.data import dataset
    from paddle_tpu.models import stacked_dynamic_lstm

    d = str(tmp_path)
    _write_sentiment_fixture(d)
    vocab = len(dataset.sentiment.get_word_dict(data_dir=d))
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 0
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        model = stacked_dynamic_lstm.build_model(
            vocab_size=vocab, emb_dim=16, hidden_dim=16,
            stacked_num=2, max_len=8, learning_rate=5e-2)
        exe = fluid.Executor()
        exe.run(startup)
        batches = dataset.padded_text_batches(
            dataset.sentiment.reader_creator(d, is_test=False),
            batch_size=2, max_len=8)
        losses = []
        for _ in range(10):
            for feed in batches():
                (lv,) = exe.run(main, feed=feed,
                                fetch_list=[model["loss"]])
                losses.append(float(np.ravel(lv)[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_word2vec_trains_from_imikolov_files(tmp_path):
    """The word2vec book model trains from real-format PTB files."""
    import paddle_tpu as fluid
    from paddle_tpu.data import dataset
    from paddle_tpu.models import word2vec

    d = str(tmp_path)
    _write_imikolov_fixture(d)
    wd = dataset.imikolov.build_dict(min_word_freq=0, data_dir=d)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 0
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        model = word2vec.build_model(
            dict_size=len(wd), embed_dim=8, hidden_dim=16, window=2,
            batch_size=4, use_nce=False, learning_rate=5e-2)
        exe = fluid.Executor()
        exe.run(startup)
        batches = dataset.ngram_batches(
            dataset.imikolov.train(wd, n=3, data_dir=d),
            batch_size=4, window=2)
        losses = []
        for _ in range(15):
            for feed in batches():
                (lv,) = exe.run(main, feed=feed,
                                fetch_list=[model["loss"]])
                losses.append(float(np.ravel(lv)[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_srl_model_trains_from_conll05_files(tmp_path):
    """The SRL book model (db_lstm + CRF) trains from real-format
    conll05 files end to end."""
    import paddle_tpu as fluid
    from paddle_tpu.data import dataset
    from paddle_tpu.models import sequence_tagging

    d = str(tmp_path)
    _write_conll05_fixture(d)
    wd, vd, ld = dataset.conll05.get_dict(d)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 0
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        model = sequence_tagging.build_model(
            word_dict_len=len(wd), label_dict_len=len(ld),
            pred_dict_len=len(vd), max_length=8, word_dim=8,
            hidden_dim=8, depth=2, learning_rate=0.05)
        exe = fluid.Executor()
        exe.run(startup)
        batches = dataset.srl_batches(
            dataset.conll05.test(data_dir=d), batch_size=3,
            max_length=8)
        losses = []
        for _ in range(12):
            for feed in batches():
                (lv,) = exe.run(main, feed=feed,
                                fetch_list=[model["loss"]])
                losses.append(float(np.ravel(lv)[0]))
    assert losses, "fixture produced no full batch"
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def _mp_range_reader_a():
    yield from range(0, 5)


def _mp_range_reader_b():
    yield from range(100, 103)


def test_multiprocess_reader_merges_both_modes():
    """multiprocess_reader: one process per reader, samples merged
    (pipe and queue transports)."""
    from paddle_tpu.data import multiprocess_reader

    for use_pipe in (True, False):
        got = sorted(multiprocess_reader(
            [_mp_range_reader_a, _mp_range_reader_b],
            use_pipe=use_pipe)())
        assert got == [0, 1, 2, 3, 4, 100, 101, 102], (use_pipe, got)
    with pytest.raises(ValueError):
        multiprocess_reader([])


def _mp_crashing_reader():
    yield 1
    raise IOError("corrupt shard")


def test_multiprocess_reader_surfaces_child_crash():
    """A crashed child must raise in the parent, not masquerade as
    normal exhaustion (silently truncated data)."""
    from paddle_tpu.data import multiprocess_reader

    for use_pipe in (True, False):
        with pytest.raises(RuntimeError, match="corrupt shard"):
            list(multiprocess_reader([_mp_crashing_reader],
                                     use_pipe=use_pipe)())
