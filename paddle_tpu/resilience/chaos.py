"""Deterministic fault injection for the resilience subsystem.

Every recovery behavior in this repo is proven by injecting its fault
(tests/test_resilience.py, the run_ci.sh chaos smoke), not by hoping:

- **failpoints** — named kill-switches compiled into the production
  code path at the exact spots a process can die (e.g.
  `ckpt:before_manifest` between the shard write and the manifest
  write in io.save_sharded).  Unarmed they are a dict lookup; armed
  they raise `ChaosKilled`, simulating preemption at that instant.
- **NaN injection** — poison one named feed at step k of a reader
  (host-side; the NaN propagates to loss and every gradient, which is
  exactly the production failure mode a bad batch causes).
- **checkpoint corruption** — flip or truncate bytes of a shard
  container so CRC/container verification must catch it.
- **executor faults** — `FlakyPredictor` wraps a real Predictor and
  fails (or delays) the first N `run()` calls: the serving circuit
  breaker's failure-burst-then-recover story.
- **hang** — a sleep the watchdog must interrupt.

Injectors are deterministic (step counts, call counts — never random),
so every chaos test is reproducible.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterable, Iterator, Optional

from .errors import ResilienceError


class ChaosKilled(ResilienceError):
    """Raised by an armed failpoint — the simulated process death."""

    kind = "chaos_killed"


# ---------------------------------------------------------------------------
# Failpoints
# ---------------------------------------------------------------------------

_armed: Dict[str, int] = {}
_delays: Dict[str, tuple] = {}  # name -> (seconds, remaining hits)


def arm(name: str, times: int = 1) -> None:
    """Arm failpoint `name` to fire on its next `times` hits."""
    _armed[name] = int(times)


def arm_delay(name: str, seconds: float, times: int = 1) -> None:
    """Arm delaypoint `name` to SLEEP `seconds` on its next `times`
    hits — the slow-disk/slow-fsync injection the async-checkpoint
    tests use to prove the step loop is not blocked by the write
    phase (a failpoint kills; a delaypoint stalls)."""
    _delays[name] = (float(seconds), int(times))


def disarm(name: str) -> None:
    _armed.pop(name, None)
    _delays.pop(name, None)


def clear() -> None:
    """Disarm every failpoint and delaypoint (test teardown)."""
    _armed.clear()
    _delays.clear()


def failpoint(name: str) -> None:
    """Production-code hook: no-op unless `arm(name)` was called, then
    raises ChaosKilled (once per armed count)."""
    left = _armed.get(name)
    if not left:
        return
    if left <= 1:
        _armed.pop(name, None)
    else:
        _armed[name] = left - 1
    raise ChaosKilled(f"failpoint {name!r} fired (simulated death)",
                      failpoint=name)


def delaypoint(name: str) -> None:
    """Production-code hook: no-op unless `arm_delay(name, s)` was
    called, then sleeps the armed duration (once per armed count)."""
    entry = _delays.get(name)
    if not entry:
        return
    seconds, left = entry
    if left <= 1:
        _delays.pop(name, None)
    else:
        _delays[name] = (seconds, left - 1)
    time.sleep(seconds)


# ---------------------------------------------------------------------------
# NaN / feed poisoning
# ---------------------------------------------------------------------------

def poison_feed(feed: Dict[str, Any], names: Optional[Iterable[str]]
                = None) -> Dict[str, Any]:
    """Copy of `feed` with NaN written into the first element of each
    named float input (all float inputs when names is None)."""
    import numpy as np

    out = dict(feed)
    targets = list(names) if names is not None else [
        n for n, v in feed.items()
        if np.asarray(v).dtype.kind == "f"]
    if not targets:
        raise ValueError("no float feed to poison")
    for n in targets:
        arr = np.array(feed[n], copy=True)
        if arr.dtype.kind != "f":
            raise ValueError(f"feed {n!r} is {arr.dtype}, not float")
        arr.reshape(-1)[0] = np.nan
        out[n] = arr
    return out


def nan_reader(reader: Callable[[], Iterable], at_step: int,
               names: Optional[Iterable[str]] = None,
               feed_order: Optional[Iterable[str]] = None
               ) -> Callable[[], Iterator]:
    """Wrap a Trainer-style reader so the batch at index `at_step`
    (0-based, per epoch) is NaN-poisoned.  Tuple batches need
    `feed_order` to name their fields."""

    def wrapped():
        for i, batch in enumerate(reader()):
            if i != at_step:
                yield batch
                continue
            if not isinstance(batch, dict):
                if feed_order is None:
                    raise ValueError("tuple batches need feed_order")
                batch = dict(zip(feed_order, batch))
            yield poison_feed(batch, names)

    return wrapped


# ---------------------------------------------------------------------------
# Checkpoint corruption
# ---------------------------------------------------------------------------

def corrupt_file(path: str, mode: str = "flip",
                 offset_frac: float = 0.5) -> str:
    """Corrupt `path` in place: mode="flip" inverts 64 bytes in the
    middle (container still opens; content/CRC is wrong), mode=
    "truncate" cuts the file in half (container itself unreadable).
    Returns the path."""
    import os

    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"{path} is empty; nothing to corrupt")
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 2))
        return path
    if mode != "flip":
        raise ValueError(f"unknown corruption mode {mode!r}")
    off = min(max(0, int(size * offset_frac)), size - 1)
    n = min(64, size - off)
    with open(path, "r+b") as f:
        f.seek(off)
        chunk = f.read(n)
        f.seek(off)
        f.write(bytes(b ^ 0xFF for b in chunk))
    return path


def corrupt_shard(ckpt_dir: str, proc: int = 0,
                  mode: str = "flip") -> str:
    """Corrupt one shard container of a sharded checkpoint directory
    (io.py layout: shards_p{proc}.npz)."""
    import os

    path = os.path.join(ckpt_dir, f"shards_p{proc}.npz")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no shard file at {path}")
    return corrupt_file(path, mode=mode)


def tear_checkpoint(ckpt_dir: str) -> None:
    """Make an existing checkpoint directory look like a save that died
    between the shard write and the manifest write (shards present, no
    manifest, no trainer state) — the end-state the
    `ckpt:before_manifest` failpoint produces live."""
    import os

    from .. import io as fluid_io

    removed = 0
    for name in (fluid_io.SHARD_MANIFEST, "__trainer_state__.json"):
        p = os.path.join(ckpt_dir, name)
        if os.path.exists(p):
            os.remove(p)
            removed += 1
    if removed == 0:
        raise FileNotFoundError(
            f"{ckpt_dir} has no manifest/trainer state to tear")


# ---------------------------------------------------------------------------
# Executor faults (serving breaker, watchdog)
# ---------------------------------------------------------------------------

class InjectedExecutorError(ResilienceError):
    """The failure FlakyPredictor injects."""

    kind = "injected_executor_error"


class FlakyPredictor:
    """Predictor proxy whose `run()` fails for the first `fail_first`
    calls (optionally delaying `delay_s` before each call) and then
    behaves normally — a deterministic executor-failure burst.  All
    other attributes (compile_signature, get_input_names, ...) pass
    through, so warmup and shape validation are unaffected."""

    def __init__(self, predictor, fail_first: int = 0,
                 delay_s: float = 0.0):
        self._predictor = predictor
        self.fail_first = int(fail_first)
        self.delay_s = float(delay_s)
        self.calls = 0
        self.failures_injected = 0

    def run(self, feed):
        self.calls += 1
        if self.delay_s > 0:
            time.sleep(self.delay_s)
        if self.calls <= self.fail_first:
            self.failures_injected += 1
            raise InjectedExecutorError(
                f"injected executor failure {self.calls}/"
                f"{self.fail_first}", call=self.calls)
        return self._predictor.run(feed)

    def __getattr__(self, name):
        return getattr(self._predictor, name)


def hang(seconds: float) -> None:
    """An injected hang the watchdog must interrupt (sleep re-enters
    the interpreter, so SIGALRM can fire)."""
    end = time.monotonic() + seconds
    while time.monotonic() < end:
        time.sleep(0.05)
