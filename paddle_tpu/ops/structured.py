"""Structured-prediction and sampling losses.

TPU-native implementations of the reference's structured loss operators:
- nce                  (reference: paddle/fluid/operators/nce_op.cc:1)
- hierarchical_sigmoid (reference: hierarchical_sigmoid_op.cc:1 +
                        operators/math/matrix_bit_code.h SimpleCode)
- linear_chain_crf     (reference: linear_chain_crf_op.cc:1)
- crf_decoding         (reference: crf_decoding_op.cc:1)
- edit_distance        (reference: edit_distance_op.cc)
- warpctc / ctc_align  (reference: warpctc_op.cc, ctc_align_op.cc)
- sampling_id          (reference: sampling_id_op.cc)
- precision_recall     (reference: metrics/precision_recall_op.cc)

Design notes: every loss is a pure jnp/lax forward — gradients come from
jax AD over the traced program, so none of the reference's hand-written
backward kernels are needed (e.g. linear_chain_crf_grad's beta recursion
is subsumed by autodiff through the alpha recursion).  Variable-length
sequences use the padded + seq_len representation (SURVEY.md §5.7)
instead of LoD offsets; recursions are lax.scan over the time axis so
everything stays one fused XLA computation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .common import first, opt_in, out


# ---------------------------------------------------------------------------
# NCE
# ---------------------------------------------------------------------------

@register_op("nce")
def nce(ctx, ins, attrs):
    """Noise-contrastive estimation (reference nce_op.cc:1).

    inputs: Input (B, D), Label (B, num_true), Weight (C, D),
            Bias (C,) optional, CustomDistProbs (C,) optional.
    attrs: num_total_classes, num_neg_samples, sampler
           (0=uniform, 1=log_uniform, 2=custom_dist), seed, is_test.
    outputs: Cost (B, 1), SampleLogits, SampleLabels.

    Shares one negative sample set across the batch (the reference
    samples per row from the same sampler; sharing is the standard
    TPU-friendly variant and an unbiased estimator all the same).
    """
    x = first(ins, "Input")
    label = first(ins, "Label").astype(jnp.int32)
    w = first(ins, "Weight")
    b = opt_in(ins, "Bias")
    num_classes = int(attrs["num_total_classes"])
    num_neg = int(attrs.get("num_neg_samples", 10))
    sampler = int(attrs.get("sampler", 0))

    if label.ndim == 1:
        label = label[:, None]
    num_true = label.shape[1]

    key = ctx.rng()
    if sampler == 1:
        # log-uniform (Zipfian): P(k) = log(1 + 1/(k+1)) / log(C+1)
        u = jax.random.uniform(key, (num_neg,))
        neg = (jnp.exp(u * jnp.log(float(num_classes + 1))) - 1.0)
        neg = jnp.clip(neg.astype(jnp.int32), 0, num_classes - 1)
        probs_fn = lambda k: (jnp.log1p(1.0 / (k.astype(jnp.float32) + 1.0))
                              / jnp.log(float(num_classes + 1)))
    elif sampler == 2:
        dist = first(ins, "CustomDistProbs")
        neg = jax.random.categorical(
            key, jnp.log(jnp.maximum(dist, 1e-20)), shape=(num_neg,))
        probs_fn = lambda k: jnp.take(dist, k)
    else:
        neg = jax.random.randint(key, (num_neg,), 0, num_classes)
        probs_fn = lambda k: jnp.full(k.shape, 1.0 / num_classes)

    def logits_for(classes):
        # classes: (..., ) ids → (B, ...) logits
        wk = jnp.take(w, classes, axis=0)           # (..., D)
        z = jnp.einsum("bd,...d->b...", x, wk)
        if b is not None:
            z = z + jnp.take(b, classes)
        return z

    w_true = jnp.take(w, label, axis=0)             # (B, num_true, D)
    true_logit = jnp.einsum("bd,btd->bt", x, w_true)
    if b is not None:
        true_logit = true_logit + jnp.take(b, label)
    neg_logit = logits_for(neg)                     # (B, S)

    q_true = probs_fn(label)                        # (B, num_true)
    q_neg = probs_fn(neg)[None, :]                  # (1, S)
    # NCE logistic objective with k = num_neg (reference nce_op.h)
    true_adj = true_logit - jnp.log(num_neg * q_true + 1e-20)
    neg_adj = neg_logit - jnp.log(num_neg * q_neg + 1e-20)
    cost_true = jnp.sum(jax.nn.softplus(-true_adj), axis=1)
    cost_neg = jnp.sum(jax.nn.softplus(neg_adj), axis=1)
    cost = ((cost_true + cost_neg) / num_true)[:, None]
    sample_weight = opt_in(ins, "SampleWeight")
    if sample_weight is not None:
        cost = cost * sample_weight.reshape(-1, 1)

    sample_logits = jnp.concatenate(
        [true_logit, neg_logit], axis=1)
    sample_labels = jnp.concatenate(
        [label, jnp.tile(neg[None, :], (x.shape[0], 1))], axis=1)
    return out(Cost=cost, SampleLogits=sample_logits,
               SampleLabels=sample_labels)


# ---------------------------------------------------------------------------
# Hierarchical sigmoid (complete-binary-tree SimpleCode)
# ---------------------------------------------------------------------------

def _simple_code_paths(label, num_classes):
    """Vectorized SimpleCode (reference math/matrix_bit_code.h:SimpleCode):
    for class c the code is c + num_classes; walking the implicit complete
    binary tree, step i uses internal node (code >> (i+1)) - 1 and bit
    (code >> i) & 1.  Returns (node_idx, bits, mask) each (B, L)."""
    code = label.astype(jnp.int32) + num_classes
    max_len = max(int(num_classes - 1).bit_length(), 1)
    steps = jnp.arange(max_len)
    node = (code[:, None] >> (steps[None, :] + 1)) - 1
    bits = (code[:, None] >> steps[None, :]) & 1
    mask = node >= 0
    node = jnp.maximum(node, 0)
    return node, bits.astype(jnp.float32), mask.astype(jnp.float32)


@register_op("hierarchical_sigmoid")
def hierarchical_sigmoid(ctx, ins, attrs):
    """reference hierarchical_sigmoid_op.cc:1.

    inputs: X (B, D), Label (B,) or (B,1), W (num_classes-1, D),
            Bias (num_classes-1,) optional.
    outputs: Out (B, 1) cost, PreOut (B, L) path logits.
    """
    x = first(ins, "X")
    label = first(ins, "Label")
    w = first(ins, "W")
    b = opt_in(ins, "Bias")
    num_classes = int(attrs["num_classes"])
    label = label.reshape(label.shape[0])
    node, bits, mask = _simple_code_paths(label, num_classes)

    w_path = jnp.take(w, node, axis=0)              # (B, L, D)
    z = jnp.einsum("bd,bld->bl", x, w_path)
    if b is not None:
        z = z + jnp.take(b.reshape(-1), node)
    # cost per node: softplus(z) - bit * z  (== BCE with target=bit on
    # logit z, the reference's sigmoid + sum_by_bit_code formulation)
    cost = (jax.nn.softplus(z) - bits * z) * mask
    return out(Out=jnp.sum(cost, axis=1, keepdims=True), PreOut=z)


# ---------------------------------------------------------------------------
# Linear-chain CRF
# ---------------------------------------------------------------------------

def _crf_split_transition(transition):
    """Paddle layout (linear_chain_crf_op.cc): row 0 = start weights,
    row 1 = stop weights, rows 2.. = (num_tags, num_tags) transitions."""
    start = transition[0]
    stop = transition[1]
    trans = transition[2:]
    return start, stop, trans


@register_op("linear_chain_crf")
def linear_chain_crf(ctx, ins, attrs):
    """Negative log-likelihood of tag paths (reference
    linear_chain_crf_op.cc:1), padded batch + SeqLen lengths.

    inputs: Emission (B, T, N), Transition (N+2, N), Label (B, T),
            SeqLen (B,).
    outputs: LogLikelihood (B, 1) — actually the reference emits the
    *negative* log-likelihood as the minimized cost; we match that —
    plus Alpha for parity.
    Gradient comes from jax AD through the alpha recursion (replacing
    the hand-written beta recursion of linear_chain_crf_grad).
    """
    emission = first(ins, "Emission")
    transition = first(ins, "Transition")
    label = first(ins, "Label").astype(jnp.int32)
    seq_len = first(ins, "SeqLen").astype(jnp.int32)
    if label.ndim == 3 and label.shape[-1] == 1:
        label = label[..., 0]
    B, T, N = emission.shape
    start, stop, trans = _crf_split_transition(transition)

    # ---- partition function: alpha recursion in log space -------------
    em_t = jnp.moveaxis(emission, 1, 0)             # (T, B, N)
    alpha0 = start[None, :] + em_t[0]               # (B, N)

    def step(alpha, inp):
        t, em = inp
        # (B, N, N): alpha[b, i] + trans[i, j]
        scores = alpha[:, :, None] + trans[None, :, :]
        new = jax.scipy.special.logsumexp(scores, axis=1) + em
        active = (t < seq_len)[:, None]
        alpha = jnp.where(active, new, alpha)
        return alpha, alpha

    alpha_f, alphas = lax.scan(step, alpha0, (jnp.arange(1, T), em_t[1:]))
    logZ = jax.scipy.special.logsumexp(alpha_f + stop[None, :], axis=1)

    # ---- gold path score ---------------------------------------------
    batch_ix = jnp.arange(B)
    t_ix = jnp.arange(T)[None, :]
    valid = t_ix < seq_len[:, None]                  # (B, T)
    em_score = jnp.sum(
        jnp.where(valid,
                  jnp.take_along_axis(emission, label[..., None],
                                      axis=2)[..., 0], 0.0), axis=1)
    prev_lab = label[:, :-1]
    next_lab = label[:, 1:]
    trans_valid = (t_ix[:, 1:] < seq_len[:, None])
    tr_score = jnp.sum(
        jnp.where(trans_valid, trans[prev_lab, next_lab], 0.0), axis=1)
    start_score = start[label[:, 0]]
    last_idx = jnp.maximum(seq_len - 1, 0)
    stop_score = stop[label[batch_ix, last_idx]]
    gold = em_score + tr_score + start_score + stop_score

    nll = (logZ - gold)[:, None]
    alpha_full = jnp.concatenate([alpha0[:, None, :],
                                  jnp.moveaxis(alphas, 0, 1)], axis=1)
    return out(LogLikelihood=nll, Alpha=alpha_full)


@register_op("crf_decoding")
def crf_decoding(ctx, ins, attrs):
    """Viterbi decode (reference crf_decoding_op.cc:1).

    inputs: Emission (B, T, N), Transition (N+2, N), SeqLen (B,),
            Label optional (when given, output is the 0/1 correctness
            mask like the reference).
    outputs: ViterbiPath (B, T) int32 (padded positions = 0).
    """
    emission = first(ins, "Emission")
    transition = first(ins, "Transition")
    seq_len = first(ins, "SeqLen").astype(jnp.int32)
    label = opt_in(ins, "Label")
    B, T, N = emission.shape
    start, stop, trans = _crf_split_transition(transition)
    em_t = jnp.moveaxis(emission, 1, 0)

    score0 = start[None, :] + em_t[0]

    def fwd(carry, inp):
        t, em = inp
        score = carry
        cand = score[:, :, None] + trans[None, :, :]    # (B, i, j)
        best_prev = jnp.argmax(cand, axis=1)            # (B, N)
        new = jnp.max(cand, axis=1) + em
        active = (t < seq_len)[:, None]
        score = jnp.where(active, new, score)
        return score, best_prev

    score_f, backptrs = lax.scan(fwd, score0,
                                 (jnp.arange(1, T), em_t[1:]))
    # stop weights apply at each sequence's true last step; since score_f
    # froze at the last active step, add stop now
    last_tag = jnp.argmax(score_f + stop[None, :], axis=1)  # (B,)

    # backtrace from each row's last position
    def back(carry, t):
        tag = carry
        bp = backptrs[t - 1]                            # (B, N) for step t
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        # only hop when t is within the sequence
        tag_prev = jnp.where(t < seq_len, prev, tag)
        return tag_prev, tag

    # ys = tags at positions T-1..1 (reverse order); final carry = tag 0
    first_tag, tags_rev = lax.scan(back, last_tag,
                                   jnp.arange(T - 1, 0, -1))
    path = jnp.concatenate([first_tag[:, None],
                            tags_rev[::-1].swapaxes(0, 1)], axis=1)
    t_ix = jnp.arange(T)[None, :]
    path = jnp.where(t_ix < seq_len[:, None], path, 0).astype(jnp.int64)
    if label is not None:
        lab = label.astype(path.dtype)
        if lab.ndim == 3 and lab.shape[-1] == 1:
            lab = lab[..., 0]
        correct = (path == lab) & (t_ix < seq_len[:, None])
        return out(ViterbiPath=correct.astype(jnp.int64))
    return out(ViterbiPath=path)


# ---------------------------------------------------------------------------
# Edit distance
# ---------------------------------------------------------------------------

@register_op("edit_distance")
def edit_distance(ctx, ins, attrs):
    """Levenshtein distance between padded hypothesis/reference id
    sequences (reference edit_distance_op.cc; LoD → padded + lengths).

    inputs: Hyps (B, T1), Refs (B, T2), HypsLen (B,), RefsLen (B,).
    attrs: normalized (divide by ref length).
    outputs: Out (B, 1) float32, SequenceNum (1,).
    """
    hyp = first(ins, "Hyps").astype(jnp.int32)
    ref = first(ins, "Refs").astype(jnp.int32)
    hlen = first(ins, "HypsLen").astype(jnp.int32)
    rlen = first(ins, "RefsLen").astype(jnp.int32)
    ignored = attrs.get("ignored_tokens") or []
    if ignored:
        hyp, hlen = _compact_remove(hyp, hlen, ignored)
        ref, rlen = _compact_remove(ref, rlen, ignored)
    B, T1 = hyp.shape
    T2 = ref.shape[1]

    def one(h, r, hl, rl):
        # DP rows over hypothesis; row[j] = distance(h[:i], r[:j])
        row0 = jnp.arange(T2 + 1, dtype=jnp.float32)

        def body(row, i):
            def inner(carry, j):
                row_new_prev, prev_diag = carry
                # cost of aligning h[i] with r[j]
                sub = prev_diag + jnp.where(h[i] == r[j], 0.0, 1.0)
                ins_ = row[j + 1] + 1.0
                dele = row_new_prev + 1.0
                val = jnp.minimum(jnp.minimum(sub, ins_), dele)
                return (val, row[j + 1]), val

            (_, _), vals = lax.scan(inner, (i + 1.0, row[0]),
                                    jnp.arange(T2))
            new_row = jnp.concatenate([jnp.asarray([i + 1.0]), vals])
            # freeze rows beyond the hypothesis length
            new_row = jnp.where(i < hl, new_row, row)
            return new_row, None

        row_f, _ = lax.scan(body, row0, jnp.arange(T1))
        # index at rl picks the distance against the true ref prefix
        return row_f[jnp.clip(rl, 0, T2)]

    dist = jax.vmap(one)(hyp, ref, hlen, rlen)
    if attrs.get("normalized", False):
        dist = dist / jnp.maximum(rlen.astype(jnp.float32), 1.0)
    return out(Out=dist[:, None],
               SequenceNum=jnp.asarray([B], jnp.int64))


def _compact_remove(x, lengths, tokens):
    """Remove every occurrence of `tokens` from padded rows, shifting the
    survivors left and shrinking lengths (used by edit_distance's
    ignored_tokens, matching the reference's pre-filter)."""
    B, T = x.shape
    valid = jnp.arange(T)[None, :] < lengths[:, None]
    keep = valid
    for t in tokens:
        keep = keep & (x != int(t))
    pos = jnp.cumsum(keep, axis=1) - 1
    new_len = jnp.max(jnp.where(keep, pos + 1, 0), axis=1)
    scatter_pos = jnp.where(keep, pos, T)
    res = jnp.zeros((B, T + 1), x.dtype)
    res = jax.vmap(lambda r, p, v: r.at[p].set(v))(res, scatter_pos, x)
    return res[:, :T], new_len.astype(lengths.dtype)


# ---------------------------------------------------------------------------
# CTC (warpctc analog) + ctc_align
# ---------------------------------------------------------------------------

@register_op("warpctc")
def warpctc(ctx, ins, attrs):
    """CTC loss (reference warpctc_op.cc — dynload'd warp-ctc; here the
    standard log-space alpha recursion via optax.ctc_loss, jax AD gives
    the gradient).

    inputs: Logits (B, T, C) — padded batch-major (the reference takes
            LoD time-major; padded is our ragged form), Label (B, U),
            LogitsLen (B,), LabelLen (B,).
    attrs: blank (default 0), norm_by_times.
    outputs: Loss (B, 1), WarpCTCGrad omitted (AD subsumes it).
    """
    import optax

    logits = first(ins, "Logits")
    labels = first(ins, "Label").astype(jnp.int32)
    logit_len = first(ins, "LogitsLen").astype(jnp.int32)
    label_len = first(ins, "LabelLen").astype(jnp.int32)
    blank = int(attrs.get("blank", 0))
    T = logits.shape[1]
    U = labels.shape[1]
    logit_pad = (jnp.arange(T)[None, :] >= logit_len[:, None]
                 ).astype(jnp.float32)
    label_pad = (jnp.arange(U)[None, :] >= label_len[:, None]
                 ).astype(jnp.float32)
    loss = optax.ctc_loss(logits, logit_pad, labels, label_pad,
                          blank_id=blank)
    if attrs.get("norm_by_times", False):
        loss = loss / jnp.maximum(logit_len.astype(loss.dtype), 1.0)
    return out(Loss=loss[:, None])


@register_op("ctc_align")
def ctc_align(ctx, ins, attrs):
    """Greedy CTC decode post-process (reference ctc_align_op.cc): remove
    repeated tokens then blanks.  inputs: Input (B, T) predicted ids,
    SeqLen (B,); attrs: blank, merge_repeated.  outputs: Output (B, T)
    right-padded with `padding_value`, OutLen (B,)."""
    x = first(ins, "Input").astype(jnp.int32)
    seq_len = opt_in(ins, "SeqLen")
    B, T = x.shape
    blank = int(attrs.get("blank", 0))
    merge = bool(attrs.get("merge_repeated", True))
    pad_val = int(attrs.get("padding_value", 0))
    if seq_len is None:
        seq_len = jnp.full((B,), T, jnp.int32)
    else:
        seq_len = seq_len.astype(jnp.int32)

    t_ix = jnp.arange(T)[None, :]
    valid = t_ix < seq_len[:, None]
    prev = jnp.concatenate([jnp.full((B, 1), -1, jnp.int32), x[:, :-1]],
                           axis=1)
    keep = valid & (x != blank)
    if merge:
        keep = keep & (x != prev)
    # stable compaction: target position = cumsum(keep) - 1
    pos = jnp.cumsum(keep, axis=1) - 1
    out_len = jnp.max(jnp.where(keep, pos + 1, 0), axis=1)
    res = jnp.full((B, T), pad_val, x.dtype)
    scatter_pos = jnp.where(keep, pos, T)  # dropped → out-of-range slot
    res = jnp.pad(res, ((0, 0), (0, 1)))
    res = jax.vmap(lambda r, p, v: r.at[p].set(v))(res, scatter_pos, x)
    return out(Output=res[:, :T].astype(jnp.int64),
               OutLen=out_len.astype(jnp.int32))


# ---------------------------------------------------------------------------
# chunk_eval (IOB tagging chunks)
# ---------------------------------------------------------------------------

def _iob_begin_end(tags, seq_len, num_chunk_types):
    """begin/end/type markers for IOB-encoded tags: tag = 2*type + {B:0,
    I:1}; O = 2*num_chunk_types (reference chunk_eval_op.h IOB scheme)."""
    B_, T = tags.shape
    t_ix = jnp.arange(T)[None, :]
    valid = t_ix < seq_len[:, None]
    is_o = tags >= 2 * num_chunk_types
    ctype = jnp.where(is_o, -1, tags // 2)
    is_b = (~is_o) & (tags % 2 == 0)
    prev_ctype = jnp.concatenate(
        [jnp.full((B_, 1), -2, ctype.dtype), ctype[:, :-1]], axis=1)
    prev_in = jnp.concatenate(
        [jnp.zeros((B_, 1), bool), (~is_o)[:, :-1]], axis=1)
    # chunk starts at B, or at I not continuing a same-type chunk
    begin = (~is_o) & (is_b | ~(prev_in & (prev_ctype == ctype))) & valid
    next_ctype = jnp.concatenate(
        [ctype[:, 1:], jnp.full((B_, 1), -2, ctype.dtype)], axis=1)
    next_begin = jnp.concatenate(
        [begin[:, 1:], jnp.zeros((B_, 1), bool)], axis=1)
    next_valid = jnp.concatenate(
        [valid[:, 1:], jnp.zeros((B_, 1), bool)], axis=1)
    cont = next_valid & (next_ctype == ctype) & ~next_begin
    end = (~is_o) & valid & ~cont
    return begin, end, ctype, valid


@register_op("chunk_eval")
def chunk_eval(ctx, ins, attrs):
    """Chunk-level precision/recall/F1 for IOB sequence tagging
    (reference: paddle/fluid/operators/chunk_eval_op.cc, metrics
    consumed by ChunkEvaluator).  inputs: Inference (B, T), Label (B, T),
    SeqLen (B,).  outputs: Precision, Recall, F1-Score (scalars) +
    NumInferChunks/NumLabelChunks/NumCorrectChunks (int64)."""
    inf = first(ins, "Inference").astype(jnp.int32)
    lab = first(ins, "Label").astype(jnp.int32)
    seq_len = first(ins, "SeqLen").astype(jnp.int32)
    if inf.ndim == 3:
        inf = inf[..., 0]
    if lab.ndim == 3:
        lab = lab[..., 0]
    nct = int(attrs["num_chunk_types"])
    excluded = list(attrs.get("excluded_chunk_types") or [])
    if excluded:
        # excluded chunk types count as outside (O) on both sides
        # (reference chunk_eval_op.h isExcludedChunkType)
        o_tag = 2 * nct
        for t in excluded:
            inf = jnp.where(inf // 2 == int(t), o_tag, inf)
            lab = jnp.where(lab // 2 == int(t), o_tag, lab)
    ib, ie, it, valid = _iob_begin_end(inf, seq_len, nct)
    lb, le, lt, _ = _iob_begin_end(lab, seq_len, nct)

    num_inf = jnp.sum(ib)
    num_lab = jnp.sum(lb)
    # A chunk (i, j, τ) is correct iff both sequences start a τ-chunk at
    # i, both stay inside it (same type, no internal begin on either
    # side), and both end at j.  Tags need NOT be equal: a broken-I start
    # on one side matches a B start on the other (both are chunk begins).
    both_begin = ib & lb & (it == lt)
    in_inf = it >= 0
    in_lab = lt >= 0
    T = inf.shape[1]

    def step(carry, t):
        continuing = (carry & ~ib[:, t] & ~lb[:, t]
                      & in_inf[:, t] & in_lab[:, t]
                      & (it[:, t] == lt[:, t]))
        matching = both_begin[:, t] | continuing
        done = matching & le[:, t] & ie[:, t]
        nxt = matching & ~le[:, t] & ~ie[:, t]
        return nxt, done

    _, dones = lax.scan(step, jnp.zeros(inf.shape[0], bool),
                        jnp.arange(T))
    num_correct = jnp.sum(dones)

    prec = jnp.where(num_inf > 0, num_correct / num_inf, 0.0)
    rec = jnp.where(num_lab > 0, num_correct / num_lab, 0.0)
    f1 = jnp.where(num_correct > 0, 2 * prec * rec / (prec + rec), 0.0)
    return out(**{"Precision": prec.reshape((1,)).astype(jnp.float32),
                  "Recall": rec.reshape((1,)).astype(jnp.float32),
                  "F1-Score": f1.reshape((1,)).astype(jnp.float32),
                  "NumInferChunks": num_inf.reshape((1,)),
                  "NumLabelChunks": num_lab.reshape((1,)),
                  "NumCorrectChunks": num_correct.reshape((1,))})


# ---------------------------------------------------------------------------
# sampling_id
# ---------------------------------------------------------------------------

@register_op("sampling_id")
def sampling_id(ctx, ins, attrs):
    """Sample column ids from per-row probability distributions
    (reference sampling_id_op.cc)."""
    x = first(ins, "X")
    key = ctx.rng()
    ids = jax.random.categorical(key, jnp.log(jnp.maximum(x, 1e-20)),
                                 axis=-1)
    return out(Out=ids.astype(jnp.int64))


# ---------------------------------------------------------------------------
# precision_recall
# ---------------------------------------------------------------------------

@register_op("precision_recall")
def precision_recall(ctx, ins, attrs):
    """Multi-class precision/recall/F1, macro & micro averaged
    (reference metrics/precision_recall_op.cc).

    inputs: MaxProbs (B,1)+Indices (B,1) OR Predictions; Labels (B,1);
            Weights (B,1) optional; StatesInfo (C,4) optional running
            [TP, FP, TN, FN] per class.
    outputs: BatchMetrics (6,), AccumMetrics (6,), AccumStatesInfo (C,4).
    Metric order matches the reference: macro-P, macro-R, macro-F1,
    micro-P, micro-R, micro-F1.
    """
    idx = opt_in(ins, "Indices")
    if idx is None:
        preds = first(ins, "Predictions")
        idx = jnp.argmax(preds, axis=-1)
    idx = idx.reshape(-1).astype(jnp.int32)
    labels = first(ins, "Labels").reshape(-1).astype(jnp.int32)
    weights = opt_in(ins, "Weights")
    wt = (jnp.ones_like(idx, jnp.float32) if weights is None
          else weights.reshape(-1).astype(jnp.float32))
    C = int(attrs["class_number"])

    onehot_pred = jax.nn.one_hot(idx, C, dtype=jnp.float32)
    onehot_lab = jax.nn.one_hot(labels, C, dtype=jnp.float32)
    correct = (idx == labels).astype(jnp.float32) * wt
    tp = jnp.einsum("b,bc->c", correct, onehot_lab)
    pred_c = jnp.einsum("b,bc->c", wt, onehot_pred)
    lab_c = jnp.einsum("b,bc->c", wt, onehot_lab)
    fp = pred_c - tp
    fn = lab_c - tp
    total = jnp.sum(wt)
    tn = total - tp - fp - fn
    batch_states = jnp.stack([tp, fp, tn, fn], axis=1)

    prev = opt_in(ins, "StatesInfo")
    accum_states = (batch_states if prev is None
                    else batch_states + prev.astype(jnp.float32))

    def metrics(states):
        tp_, fp_, _tn, fn_ = (states[:, 0], states[:, 1], states[:, 2],
                              states[:, 3])
        prec = jnp.where(tp_ + fp_ > 0, tp_ / (tp_ + fp_ + 1e-12), 0.0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / (tp_ + fn_ + 1e-12), 0.0)
        f1 = jnp.where(prec + rec > 0,
                       2 * prec * rec / (prec + rec + 1e-12), 0.0)
        macro = jnp.stack([jnp.mean(prec), jnp.mean(rec), jnp.mean(f1)])
        stp, sfp, sfn = jnp.sum(tp_), jnp.sum(fp_), jnp.sum(fn_)
        mp = jnp.where(stp + sfp > 0, stp / (stp + sfp + 1e-12), 0.0)
        mr = jnp.where(stp + sfn > 0, stp / (stp + sfn + 1e-12), 0.0)
        mf = jnp.where(mp + mr > 0, 2 * mp * mr / (mp + mr + 1e-12), 0.0)
        return jnp.concatenate([macro, jnp.stack([mp, mr, mf])])

    return out(BatchMetrics=metrics(batch_states),
               AccumMetrics=metrics(accum_states),
               AccumStatesInfo=accum_states)


@register_op("mean_iou")
def mean_iou(ctx, ins, attrs):
    """Mean intersection-over-union for semantic segmentation (reference
    mean_iou_op.cc / mean_iou_op.h): per-class correct/wrong counts from
    int predictions vs labels; IoU_c = correct_c / (wrong_c + correct_c)
    averaged over classes that appear; optional InWrongs/InCorrects/
    InMeanIou accumulator lists add onto the outputs (streaming eval)."""
    pred = first(ins, "Predictions").reshape(-1)
    label = first(ins, "Labels").reshape(-1)
    num_classes = int(attrs["num_classes"])

    match = pred == label
    # reference mean_iou_op.h:92-99 — a correct pixel increments
    # correct[pred]; a wrong pixel increments BOTH wrong[label] and
    # wrong[pred] (union counting)
    correct = jnp.zeros((num_classes,), jnp.int32).at[pred].add(
        match.astype(jnp.int32), mode="drop")
    wrong = jnp.zeros((num_classes,), jnp.int32).at[label].add(
        (~match).astype(jnp.int32), mode="drop")
    wrong = wrong.at[pred].add((~match).astype(jnp.int32), mode="drop")
    for prev in ins.get("InCorrects", []):
        correct = correct + prev.astype(jnp.int32)
    for prev in ins.get("InWrongs", []):
        wrong = wrong + prev.astype(jnp.int32)

    denom = wrong + correct
    valid = denom > 0
    iou = jnp.where(valid, correct / jnp.maximum(denom, 1), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid), 1)
    for prev in ins.get("InMeanIou", []):
        miou = miou + prev.reshape(())
    return {"OutMeanIou": [miou.reshape(1).astype(jnp.float32)],
            "OutWrong": [wrong], "OutCorrect": [correct]}


@register_op("modified_huber_loss")
def modified_huber_loss(ctx, ins, attrs):
    """Binary-classification modified Huber loss (reference
    modified_huber_loss_op.cc): labels in {0,1} are scaled to {-1,+1};
    loss = max(0, 1-yf)^2 when yf >= -1 else -4yf."""
    x = first(ins, "X")
    y = first(ins, "Y").astype(x.dtype)
    yf = (2.0 * y - 1.0) * x
    loss = jnp.where(yf >= -1.0,
                     jnp.square(jnp.maximum(0.0, 1.0 - yf)),
                     -4.0 * yf)
    return {"Out": [loss.astype(x.dtype)],
            "IntermediateVal": [yf.astype(x.dtype)]}


@register_op("positive_negative_pair")
def positive_negative_pair(ctx, ins, attrs):
    """Learning-to-rank pair statistics (reference
    positive_negative_pair_op.cc): within each query group, count item
    pairs whose score order agrees (positive), disagrees (negative), or
    ties (neutral) with the label order; ties in label are skipped.
    Optional weight column averages (w_i + w_j)/2 per pair; optional
    Accumulate* inputs stream across batches."""
    score = first(ins, "Score")
    label = first(ins, "Label").reshape(-1).astype(jnp.float32)
    query = first(ins, "QueryID").reshape(-1)
    weight = opt_in(ins, "Weight")
    col = int(attrs.get("column", -1))
    s = score[:, col].astype(jnp.float32)
    n = s.shape[0]
    w = (jnp.ones((n,), jnp.float32) if weight is None
         else weight.reshape(-1).astype(jnp.float32))

    # dense pairwise comparison (upper triangle counts each pair once);
    # the reference iterates itertools-style per query — O(N^2) either
    # way, but the dense form is one fused XLA kernel
    upper = jnp.triu(jnp.ones((n, n), jnp.bool_), k=1)
    same_q = query[:, None] == query[None, :]
    dl = label[:, None] - label[None, :]
    ds = s[:, None] - s[None, :]
    pair_ok = upper & same_q & (dl != 0)
    pw = (w[:, None] + w[None, :]) * 0.5
    pos = jnp.sum(jnp.where(pair_ok & (ds * dl > 0), pw, 0.0))
    neg = jnp.sum(jnp.where(pair_ok & (ds != 0) & (ds * dl < 0), pw, 0.0))
    neu = jnp.sum(jnp.where(pair_ok & (ds == 0), pw, 0.0))
    acc_p = opt_in(ins, "AccumulatePositivePair")
    acc_n = opt_in(ins, "AccumulateNegativePair")
    acc_u = opt_in(ins, "AccumulateNeutralPair")
    if acc_p is not None:
        pos = pos + acc_p.reshape(())
    if acc_n is not None:
        neg = neg + acc_n.reshape(())
    if acc_u is not None:
        neu = neu + acc_u.reshape(())
    return {"PositivePair": [pos.reshape(1)],
            "NegativePair": [neg.reshape(1)],
            "NeutralPair": [neu.reshape(1)]}
