"""Paged continuous-batching decode: parity + contract suite (ISSUE 12).

The load-bearing property: a request's generated tokens are a function
of ITS prompt and the weights alone — never of who shares the slot
batch, when it joined, or whether it was preempted and regenerated.
Pinned by decoding every request through the continuous-batching
engine (ragged joins, leaves, forced preemption, both kernel paths)
and comparing token-for-token against a NAIVE full-KV reference that
recomputes the whole forward per emitted token (no cache at all).

This file is also the dedicated Pallas parity suite for the
paged-attention kernel (the recurrence.py precedent): the op sweep
covers the XLA twin's forward; the kernel path is exercised here via
interpret mode at small shapes (interpret mode is emulation-slow —
batch stays <= 8).
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models.decoder_lm import DecoderLM, make_prompts
from paddle_tpu.observe.monitoring import runtime_stats
from paddle_tpu.serving.decode import (DecodeBucketMissError,
                                       DecodeConfig, DecodeEngine,
                                       DecodeMemoryError, PagePool)

from op_test import run_op

VOCAB = 48


@pytest.fixture(scope="module")
def lm():
    return DecoderLM(vocab_size=VOCAB, n_layer=2, n_head=2, d_model=32,
                     d_inner=64, kv_dtype="float32", seed=7)


@pytest.fixture(scope="module")
def lm_params(lm):
    scope = lm.init_params()
    return {n: np.asarray(v) for n, v in scope.vars.items()
            if v is not None and not n.startswith("__")}


# -- the naive full-KV reference -------------------------------------------

def _layer_norm(x, w, b, eps=1e-5):
    mean = x.mean(-1, keepdims=True)
    var = ((x - mean) ** 2).mean(-1, keepdims=True)
    return (x - mean) / np.sqrt(var + eps) * w + b


def _pos_encoding(t, d):
    pos = np.arange(t, dtype=np.float32)[:, None]
    div = np.exp(np.arange(0, d, 2, dtype=np.float32)
                 * (-np.log(10000.0) / d))
    pe = np.zeros((t, d), np.float32)
    pe[:, 0::2] = np.sin(pos * div)
    pe[:, 1::2] = np.cos(pos * div[: d // 2])
    return pe


def _ref_forward(params, lm, tokens):
    """Full-recompute causal forward; logits at the LAST position."""
    d, h_n = lm.d_model, lm.n_head
    dh = d // h_n
    x = params["tok_emb"][tokens] * np.sqrt(d)
    x = x + _pos_encoding(len(tokens), d)
    for i in range(lm.n_layer):
        h = _layer_norm(x, params[f"layer_norm_{2 * i}.w_0"],
                        params[f"layer_norm_{2 * i}.b_0"])
        q = h @ params[f"attn_qkv.w_{3 * i}"]
        k = h @ params[f"attn_qkv.w_{3 * i + 1}"]
        v = h @ params[f"attn_qkv.w_{3 * i + 2}"]
        t = len(tokens)
        ctx = np.zeros((t, d), np.float32)
        for hh in range(h_n):
            sl = slice(hh * dh, (hh + 1) * dh)
            logits = (q[:, sl] @ k[:, sl].T) * dh ** -0.5
            mask = np.tril(np.ones((t, t), bool))
            logits = np.where(mask, logits, -1e30)
            w = np.exp(logits - logits.max(-1, keepdims=True))
            w /= w.sum(-1, keepdims=True)
            ctx[:, sl] = w @ v[:, sl]
        x = x + ctx @ params[f"attn_out.w_{i}"]
        h = _layer_norm(x, params[f"layer_norm_{2 * i + 1}.w_0"],
                        params[f"layer_norm_{2 * i + 1}.b_0"])
        h = np.maximum(h @ params[f"ffn_in.w_{i}"]
                       + params[f"ffn_in.b_{i}"], 0.0)
        x = x + h @ params[f"ffn_out.w_{i}"] + params[f"ffn_out.b_{i}"]
    x = _layer_norm(x, params[f"layer_norm_{2 * lm.n_layer}.w_0"],
                    params[f"layer_norm_{2 * lm.n_layer}.b_0"])
    return x[-1] @ params["lm_head.w_0"]


def reference_decode(params, lm, prompt, max_new, eos=None):
    """Greedy full-KV decode, one request at a time, recomputing the
    whole forward per token — the naive design the paged engine must
    match token-for-token."""
    tokens = list(int(t) for t in prompt)
    out = []
    for _ in range(max_new):
        nxt = int(np.argmax(_ref_forward(params, lm,
                                         np.asarray(tokens))))
        out.append(nxt)
        if eos is not None and nxt == eos:
            break
        tokens.append(nxt)
    return out


# -- op-level parity --------------------------------------------------------

def _rand_pool_case(seed, s=3, h=2, dh=8, p=7, page=4, maxp=2):
    rng = np.random.RandomState(seed)
    hd = h * dh
    kc = rng.randn(p, page, hd).astype(np.float32)
    vc = rng.randn(p, page, hd).astype(np.float32)
    # disjoint per-slot pages (the allocator's invariant)
    pt = rng.permutation(p)[:s * maxp].reshape(s, maxp) \
        .astype(np.int32)
    q = rng.randn(s, hd).astype(np.float32)
    lens = rng.randint(1, page * maxp + 1, s).astype(np.int32)
    return q, kc, vc, pt, lens, h


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_paged_attention_pallas_matches_xla_twin(seed):
    q, kc, vc, pt, lens, h = _rand_pool_case(seed)
    ins = {"Q": q, "KCache": kc, "VCache": vc, "PageTable": pt,
           "Lengths": lens}
    ref = run_op("paged_attention", ins, {"n_head": h})
    got = run_op("paged_attention", ins, {"n_head": h,
                                          "use_pallas": True})
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_paged_attention_masks_stale_pool_content():
    """Positions at/after `lengths` must not influence the output even
    when their pages hold garbage from an evicted slot."""
    q, kc, vc, pt, lens, h = _rand_pool_case(3)
    ins = {"Q": q, "KCache": kc, "VCache": vc, "PageTable": pt,
           "Lengths": lens}
    base = run_op("paged_attention", ins, {"n_head": h})
    # poison everything past each slot's length through its own table
    kc2, vc2 = kc.copy(), vc.copy()
    page = kc.shape[1]
    for s in range(len(lens)):
        flat = pt[s].repeat(page) * page + np.tile(np.arange(page),
                                                   pt.shape[1])
        for j in flat[lens[s]:]:
            kc2[j // page, j % page] = 1e3
            vc2[j // page, j % page] = np.nan
    got = run_op("paged_attention",
                 {"Q": q, "KCache": kc2, "VCache": vc2,
                  "PageTable": pt, "Lengths": lens}, {"n_head": h})
    np.testing.assert_allclose(got, base, rtol=1e-6, atol=1e-6)


def test_paged_kv_int8_roundtrip_error_bound():
    """int8 cache rows (per-row scale sidecars) must reconstruct within
    the symmetric-quantization bound absmax/127."""
    rng = np.random.RandomState(0)
    s, hd, p, page = 4, 16, 6, 4
    kc = np.zeros((p, page, hd), np.int8)
    sc = np.ones((p, page, 1), np.float32)
    pt = np.arange(s * 1, dtype=np.int32).reshape(s, 1) + 1
    k = rng.randn(s, hd).astype(np.float32)
    wp = np.zeros(s, np.int32)
    ins = {"K": k, "V": k, "KCache": kc, "VCache": kc, "KScale": sc,
           "VScale": sc, "PageTable": pt, "WritePos": wp}
    codes = run_op("paged_kv_write", ins, out_slot="KCacheOut")
    scales = run_op("paged_kv_write", ins, out_slot="KScaleOut")
    recon = codes.astype(np.float32) * scales
    for i in range(s):
        bound = np.abs(k[i]).max() / 127.0 * 0.5 + 1e-7
        np.testing.assert_allclose(recon[pt[i, 0], 0], k[i],
                                   atol=bound)


# -- engine parity ----------------------------------------------------------

def _drain_close(engine):
    assert engine.drain(timeout_s=120), "drain timed out"
    snap = engine.stats.snapshot()
    engine.close()
    return snap


def test_continuous_batching_matches_reference(lm, lm_params):
    """Ragged joins/leaves: more requests than slots, varied prompt
    lengths and generation budgets — every request's tokens must equal
    the naive one-at-a-time full-KV reference, with ZERO post-warmup
    compiles across the whole stream."""
    cfg = DecodeConfig(num_slots=2, page_size=4, max_len=48,
                       num_pages=24, prefill_buckets=(8, 16),
                       decode_chunk=4, kv_dtype="float32")
    eng = DecodeEngine(lm, cfg, memory_budget_bytes=False).start()
    snap = runtime_stats.snapshot()
    prompts = make_prompts(5, VOCAB, min_len=3, max_len=14, seed=11)
    budgets = [6, 3, 8, 1, 5]
    futs = [eng.submit(p, max_new_tokens=b)
            for p, b in zip(prompts, budgets)]
    outs = [f.result(120).tolist() for f in futs]
    assert runtime_stats.delta(snap)["compiles"] == 0, \
        "XLA compile after warmup (shape leaked across joins/leaves)"
    stats = _drain_close(eng)
    for p, b, got in zip(prompts, budgets, outs):
        assert got == reference_decode(lm_params, lm, p, b), \
            f"prompt len {len(p)} diverged from the reference"
    assert stats["completed"] == 5
    assert stats["post_warmup_compiles"] == 0
    assert stats["tokens_generated"] == sum(budgets)
    assert stats["prefills"] >= 3  # joins happened across iterations


def test_forced_preemption_matches_reference(lm, lm_params):
    """Pool sized so two slots cannot both reach their full length:
    the lower-priority slot is evicted mid-generation (pages returned,
    request requeued) and its regenerated tokens must STILL match the
    reference exactly."""
    cfg = DecodeConfig(num_slots=2, page_size=4, max_len=40,
                       num_pages=11, prefill_buckets=(8,),
                       decode_chunk=4, kv_dtype="float32")
    eng = DecodeEngine(lm, cfg, memory_budget_bytes=False).start()
    lo = eng.submit(np.arange(1, 8, dtype=np.int64), max_new_tokens=24,
                    priority=0)
    hi = eng.submit(np.arange(2, 9, dtype=np.int64), max_new_tokens=24,
                    priority=5)
    lo_t, hi_t = lo.result(120).tolist(), hi.result(120).tolist()
    stats = _drain_close(eng)
    assert stats["preemptions"] >= 1, \
        f"pool geometry did not force a preemption: {stats}"
    assert hi_t == reference_decode(
        lm_params, lm, np.arange(2, 9), 24)
    assert lo_t == reference_decode(
        lm_params, lm, np.arange(1, 8), 24), \
        "preempted+regenerated request diverged from the reference"
    assert stats["post_warmup_compiles"] == 0


def test_pallas_kernel_path_matches_reference(lm_params):
    """The same stream through the Pallas ragged-paged-attention
    kernel (interpret mode on CPU; small shapes — emulation is slow)."""
    lm_p = DecoderLM(vocab_size=VOCAB, n_layer=2, n_head=2, d_model=32,
                     d_inner=64, kv_dtype="float32", use_pallas=True,
                     seed=7)
    cfg = DecodeConfig(num_slots=2, page_size=4, max_len=32,
                       num_pages=16, prefill_buckets=(8,),
                       decode_chunk=3, kv_dtype="float32")
    eng = DecodeEngine(lm_p, cfg, memory_budget_bytes=False).start()
    prompts = make_prompts(3, VOCAB, min_len=3, max_len=7, seed=5)
    futs = [eng.submit(p, max_new_tokens=4) for p in prompts]
    outs = [f.result(300).tolist() for f in futs]
    stats = _drain_close(eng)
    for p, got in zip(prompts, outs):
        assert got == reference_decode(lm_params, lm_p, p, 4)
    assert stats["post_warmup_compiles"] == 0


def test_eos_stops_generation(lm, lm_params):
    """An eos_id config stops a slot early; the emitted tokens include
    the eos and match the reference's eos semantics."""
    prompts = make_prompts(3, VOCAB, min_len=3, max_len=10, seed=3)
    refs = [reference_decode(lm_params, lm, p, 10, eos=None)
            for p in prompts]
    # pick an eos that actually appears mid-stream for at least one
    eos = None
    for cand in refs[0][1:-1]:
        eos = int(cand)
        break
    cfg = DecodeConfig(num_slots=2, page_size=4, max_len=48,
                       num_pages=24, prefill_buckets=(16,),
                       decode_chunk=4, eos_id=eos,
                       kv_dtype="float32")
    eng = DecodeEngine(lm, cfg, memory_budget_bytes=False).start()
    futs = [eng.submit(p, max_new_tokens=10) for p in prompts]
    outs = [f.result(120).tolist() for f in futs]
    _drain_close(eng)
    for p, got in zip(prompts, outs):
        want = reference_decode(lm_params, lm, p, 10, eos=eos)
        assert got == want
    assert any(o and o[-1] == eos and len(o) < 10 for o in outs), \
        "no request actually stopped at eos (weak test input)"


def test_int8_kv_cache_decodes(lm_params):
    """Opt-in int8 KV (blockwise per-row scales): the engine runs the
    full join/decode cycle, emits the right token COUNTS, and the
    overwhelming majority of tokens match the f32 reference (int8
    rounding may legitimately flip a near-tie argmax)."""
    lm8 = DecoderLM(vocab_size=VOCAB, n_layer=2, n_head=2, d_model=32,
                    d_inner=64, kv_dtype="int8", seed=7)
    cfg = DecodeConfig(num_slots=2, page_size=4, max_len=32,
                       num_pages=16, prefill_buckets=(8,),
                       decode_chunk=4, kv_dtype="int8")
    eng = DecodeEngine(lm8, cfg, memory_budget_bytes=False).start()
    prompts = make_prompts(3, VOCAB, min_len=3, max_len=7, seed=9)
    futs = [eng.submit(p, max_new_tokens=5) for p in prompts]
    outs = [f.result(120).tolist() for f in futs]
    stats = _drain_close(eng)
    assert all(len(o) == 5 for o in outs)
    assert stats["post_warmup_compiles"] == 0
    match = total = 0
    for p, got in zip(prompts, outs):
        want = reference_decode(lm_params, lm8, p, 5)
        match += sum(g == w for g, w in zip(got, want))
        total += 5
    assert match / total >= 0.6, \
        f"int8 KV diverged wildly from f32: {match}/{total}"


# -- layout + pool + config contracts ---------------------------------------

def test_programs_carry_zero_transposes(lm):
    """The ISSUE 8 invariant carried into decode: head-major from
    birth — no transpose op in either program, and no copy/transpose
    instruction attributed to the attention ops in the compiled decode
    step (the chip-free half of the boundary audit)."""
    from paddle_tpu.core.executor import Executor, scope_guard
    from paddle_tpu.observe import cost as obs_cost

    for prog in (lm.step["main"], lm.prefill(8)["main"]):
        n = sum(1 for op in prog.global_block().ops
                if op.type == "transpose")
        assert n == 0, f"{n} transpose ops in a decode program"

    scope = lm.init_params()
    st = lm.step
    s, p, page, maxp = 2, 8, 4, 4
    feed = {"tokens": jnp.zeros((s,), jnp.int32),
            "write_pos": jnp.zeros((s,), jnp.int32),
            "lengths": jnp.ones((s,), jnp.int32),
            "active": jnp.ones((s,), jnp.int32),
            "page_table": jnp.zeros((s, maxp), jnp.int32)}
    feed.update(lm.fresh_pools(p, page))
    with scope_guard(scope):
        compiled = Executor().compiled_step(
            st["main"], feed=feed,
            fetch_list=[st["next_token"]] + st["cache_outs"],
            scope=scope)
    proto = obs_cost.compiled_hlo_proto(compiled)
    # the PR 8 criterion: no copy/transpose attributed to a transpose
    # fluid op (there are no transpose ops to attribute to — the
    # baseline layout had one at every kernel boundary); layout
    # choices INSIDE the XLA twin's einsums are not boundary traffic
    offenders = obs_cost.copyish_instructions(proto,
                                              op_types={"transpose"})
    assert offenders == [], offenders
    # the on-chip half: no copy/transpose adjacent to the kernel's
    # custom call (vacuous on the interpreting CPU backend, exercised
    # for plumbing like the flash smoke)
    assert obs_cost.flash_boundary_layout(proto,
                                          kernel_prefix="paged") == []


def test_page_pool_allocator():
    pool = PagePool(6)
    a = pool.alloc(2)
    b = pool.alloc(3)
    assert len(a) == 2 and len(b) == 3 and pool.free_pages == 1
    assert pool.alloc(2) is None and pool.free_pages == 1
    pool.free(a)
    c = pool.alloc(3)
    assert c is not None and pool.in_use == 6
    assert len(set(b) | set(c)) == 6  # disjoint, covering the pool


def test_submit_rejections(lm):
    cfg = DecodeConfig(num_slots=2, page_size=4, max_len=24,
                       num_pages=12, prefill_buckets=(8,),
                       decode_chunk=2, kv_dtype="float32")
    eng = DecodeEngine(lm, cfg, memory_budget_bytes=False).start()
    with pytest.raises(DecodeBucketMissError):
        eng.submit(np.ones(9, np.int64))    # over the bucket ladder
    with pytest.raises(DecodeBucketMissError):
        eng.submit(np.ones(8, np.int64), max_new_tokens=17)  # > max_len
    out = eng.generate(np.ones(4, np.int64), max_new_tokens=2,
                       timeout_s=120)
    assert len(out) == 2
    eng.close()


def test_config_validation():
    with pytest.raises(ValueError):
        DecodeConfig(num_pages=2, page_size=4, max_len=64)
    with pytest.raises(ValueError):
        DecodeConfig(prefill_buckets=(64, 32))
    with pytest.raises(ValueError):
        DecodeConfig(prefill_buckets=(512,), max_len=256)


def test_memory_gate_rejects_impossible_pool(lm):
    """An absurd pool against a tiny explicit budget must be rejected
    pre-warmup with the structured DecodeMemoryError (the plan_fit
    gate), before any full-size compile."""
    cfg = DecodeConfig(num_slots=2, page_size=4, max_len=64,
                       num_pages=4096, prefill_buckets=(8,),
                       kv_dtype="float32")
    eng = DecodeEngine(lm, cfg, memory_budget_bytes=64 * 1024)
    with pytest.raises(DecodeMemoryError) as e:
        eng.start()
    d = e.value.as_dict()
    assert d["error"] == "decode_memory" and d["budget_bytes"]


def test_decode_stats_merge_compatible(lm):
    """TTFT/TPOT histograms are LatencyHistogram and merge exactly
    (the PR 11 cross-window contract)."""
    from paddle_tpu.observe.monitoring import LatencyHistogram

    a, b = LatencyHistogram(), LatencyHistogram()
    cfg = DecodeConfig(num_slots=2, page_size=4, max_len=32,
                       num_pages=16, prefill_buckets=(8,),
                       decode_chunk=4, kv_dtype="float32")
    eng = DecodeEngine(lm, cfg, memory_budget_bytes=False).start()
    eng.generate(np.ones(4, np.int64), max_new_tokens=3,
                 timeout_s=120)
    snap = _drain_close(eng)
    assert snap["ttft_ms"]["count"] >= 1
    assert snap["tpot_ms"]["count"] >= 1
    a.merge(eng.stats.ttft_ms)
    b.merge(eng.stats.tpot_ms)
    assert a.summary()["count"] == snap["ttft_ms"]["count"]
    assert b.summary()["count"] == snap["tpot_ms"]["count"]
