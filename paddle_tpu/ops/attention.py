"""Fused attention op.

The reference composes attention from matmul/softmax primitives
(nets.py scaled_dot_product_attention; the 2018 codebase has no fused
kernel — SURVEY.md §5.7 marks this a capability gap to fill natively).
`flash_attention` is the single-op attention: inputs Q/K/V laid out
(N, H, T, D) — or, with layout="nthd" + the n_head attr, head-grouped
(N, T, H*D), the head-major end-to-end contract that deletes every
boundary transpose (ISSUE 8) — plus an optional additive Bias; the
default implementation is a numerically-stable lax composition (XLA
fuses it well on TPU), and ops/pallas/flash_attention.py provides the
tiled Pallas kernel used when `use_pallas` is set and we're on TPU
(forward via custom_vjp).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import first, opt_in, out


def _xla_attention(q, k, v, bias, scale, causal):
    logits = jnp.einsum("nhqd,nhkd->nhqk", q, k) * scale
    if bias is not None:
        logits = logits + bias
    if causal:
        t_q, t_k = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((t_q, t_k), jnp.bool_))
        logits = jnp.where(mask, logits, -1e9)
    weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    o = jnp.einsum("nhqk,nhkd->nhqd", weights.astype(q.dtype), v)
    return o


def _xla_attention_nthd(q, k, v, bias, scale, causal, n_head):
    """XLA composition over head-grouped (N, T, H*D) operands.  The
    4D views are free reshapes (minor-dim split/merge) and the einsums
    carry the head dim as a dot batch dim — XLA folds the operand
    orderings into the dot dimension numbers, no boundary transpose."""
    n, t_q, hd = q.shape
    d = hd // n_head
    q4 = q.reshape(n, t_q, n_head, d)
    k4 = k.reshape(n, k.shape[1], n_head, d)
    v4 = v.reshape(n, v.shape[1], n_head, d)
    logits = jnp.einsum("nqhd,nkhd->nhqk", q4, k4) * scale
    if bias is not None:
        logits = logits + bias
    if causal:
        t_kk = logits.shape[-1]
        mask = jnp.tril(jnp.ones((t_q, t_kk), jnp.bool_))
        logits = jnp.where(mask, logits, -1e9)
    weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    o = jnp.einsum("nhqk,nkhd->nqhd", weights.astype(q.dtype), v4)
    return o.reshape(n, t_q, hd)


@register_op("flash_attention")
def flash_attention(ctx, ins, attrs):
    q, k, v = first(ins, "Q"), first(ins, "K"), first(ins, "V")
    bias = opt_in(ins, "Bias")
    layout = attrs.get("layout", "nhtd")
    n_head = attrs.get("n_head", None)
    if layout == "nthd":
        # head-major end-to-end contract (ISSUE 8): operands are
        # (N, T, H*D) head-grouped — exactly what the attn_qkv
        # projection emits — and nothing transposes at this boundary
        if not n_head:
            raise ValueError("flash_attention layout='nthd' needs the "
                             "n_head attr (operands are (N, T, H*D))")
        if q.shape[-1] % int(n_head):
            raise ValueError(
                f"flash_attention nthd: minor dim {q.shape[-1]} not "
                f"divisible by n_head {n_head}")
        head_dim = q.shape[-1] // int(n_head)
        t_axis, h_count = 1, int(n_head)
    elif layout == "nhtd":
        head_dim = q.shape[-1]
        t_axis, h_count = 2, q.shape[1]
    else:
        raise ValueError(f"flash_attention: unknown layout {layout!r}")
    scale = attrs.get("scale", None)
    if scale is None:
        scale = head_dim ** -0.5
    causal = attrs.get("causal", False)
    if attrs.get("sequence_parallel", False):
        # long-context path: shard the sequence axis over the mesh's
        # sp axis and run ring attention (KV rotation via ppermute) or
        # Ulysses (head/sequence all-to-all), parallel/ring_attention.
        # Only ACTIVE inside a CompiledProgram traced under a mesh WITH
        # an sp axis — but the strategy value validates everywhere so a
        # typo'd flag can never silently no-op.
        strategy0 = attrs.get("sequence_parallel")
        if strategy0 not in (True, "ring", "ulysses"):
            raise ValueError(
                f"sequence_parallel must be True/'ring'/'ulysses', "
                f"got {strategy0!r}")
        from ..parallel.mesh import get_exec_context

        ectx = get_exec_context()
        mesh = None if ectx is None else ectx.mesh
        # the compiled program's actual batch axis (not a hardcoded
        # "dp"): a non-default batch axis name must still keep batch
        # sharding inside the sp shard_map
        batch_axis = "dp" if ectx is None else ectx.batch_axis
        if mesh is not None and mesh.shape.get("sp", 1) > 1:
            if bias is not None:
                raise ValueError(
                    "sequence_parallel flash_attention does not take "
                    "an additive Bias: ring attention supports causal "
                    "masking only — drop padding bias (full-length "
                    "sequences / packed batches) or disable "
                    "sequence_parallel")
            sp = mesh.shape["sp"]
            if q.shape[t_axis] % sp != 0:
                raise ValueError(
                    f"sequence_parallel flash_attention: sequence "
                    f"length {q.shape[t_axis]} must be divisible by "
                    f"the sp axis size ({sp}) — pad T to a multiple")
            strategy = "ring" if strategy0 is True else strategy0
            if strategy == "ulysses":
                if h_count % sp != 0:
                    raise ValueError(
                        f"ulysses sequence_parallel: the sp axis "
                        f"({sp}) must divide n_head ({h_count}) — "
                        f"use 'ring' for head counts below the sp "
                        f"degree")
                from ..parallel.ring_attention import ulysses_attention

                o = ulysses_attention(
                    q, k, v, mesh, axis="sp", scale=scale,
                    causal=causal, use_pallas=attrs.get("use_pallas"),
                    batch_axis=batch_axis, layout=layout,
                    n_head=h_count)
                return out(Out=o)
            from ..parallel.ring_attention import ring_attention

            # use_pallas None = ring's auto (Pallas on TPU); the batch
            # axis keeps dp-sharded activations dp-sharded inside the
            # shard_map instead of all-gathering per dp group
            o = ring_attention(q, k, v, mesh, axis="sp", scale=scale,
                               causal=causal,
                               use_pallas=attrs.get("use_pallas"),
                               batch_axis=batch_axis, layout=layout,
                               n_head=h_count)
            return out(Out=o)
        # no sp axis in this compile: fall through to the local kernel
    if attrs.get("use_pallas", False):
        def _kernel_bias_ok(b):
            # the tiled kernel takes a KEY-padding bias broadcastable
            # TO (N, 1, 1, Tk): every (right-aligned) dim must be 1 or
            # match the target
            target = (q.shape[0], 1, 1, k.shape[t_axis])
            if b.ndim > 4:
                return False
            for bd, td in zip(reversed(b.shape), reversed(target)):
                if bd != 1 and bd != td:
                    return False
            return True

        if bias is not None and not _kernel_bias_ok(bias):
            # richer biases ((Tq, Tk) shapes, per-head biases) take the
            # documented XLA fallback — express causal+padding as
            # causal=True + a key bias to stay on the kernel
            if layout == "nthd":
                o = _xla_attention_nthd(q, k, v, bias, scale, causal,
                                        h_count)
            else:
                o = _xla_attention(q, k, v, bias, scale, causal)
            return out(Out=o)
        from .pallas.flash_attention import pallas_flash_attention

        o = pallas_flash_attention(q, k, v, bias, scale, causal,
                                   layout=layout, n_head=h_count)
    elif layout == "nthd":
        o = _xla_attention_nthd(q, k, v, bias, scale, causal, h_count)
    else:
        o = _xla_attention(q, k, v, bias, scale, causal)
    return out(Out=o)


@register_op("fused_vocab_softmax_ce")
def fused_vocab_softmax_ce(ctx, ins, attrs):
    """Final vocab projection + label-smoothed softmax-CE in one fused
    op (ops/pallas/vocab_ce.py): Hidden (..., D) @ W (D, V) logits are
    never materialized in HBM.  With use_pallas unset (or on CPU) runs
    an XLA chunked-equivalent composition for numerics parity."""
    hidden = first(ins, "Hidden")
    w = first(ins, "W")
    labels = first(ins, "Label")
    eps = float(attrs.get("epsilon", 0.0))
    if attrs.get("use_pallas", False):
        from .pallas.vocab_ce import (DEFAULT_BLOCK_T, DEFAULT_BLOCK_V,
                                      fused_vocab_ce)

        # fall back to the kernel module's defaults — they encode the
        # measured on-chip VMEM budget (r05: a stale 1024/2048 fallback
        # here kept overriding the retuned defaults and every compile
        # failed identically)
        loss = fused_vocab_ce(
            hidden, w, labels, eps,
            int(attrs.get("block_t", DEFAULT_BLOCK_T)),
            int(attrs.get("block_v", DEFAULT_BLOCK_V)))
    else:
        v = w.shape[1]
        z = (hidden @ w).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(z, axis=-1)
        zt = jnp.take_along_axis(
            z, labels.reshape(labels.shape + (1,)).astype(jnp.int32),
            axis=-1)[..., 0]
        loss = lse - (1.0 - eps) * zt - (eps / v) * jnp.sum(z, axis=-1)
    return out(Loss=loss)
