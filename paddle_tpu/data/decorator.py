"""Reader decorators.

reference: python/paddle/reader/decorator.py:58-338 — a reader is a
zero-arg callable returning an iterable of samples; decorators compose
readers.
"""

from __future__ import annotations

import itertools
import queue
import random as _random
import threading
from typing import Callable, Iterable, List


def map_readers(func, *readers):
    """Apply func elementwise across readers (decorator.py map_readers)."""

    def reader():
        rs = [r() for r in readers]
        for items in zip(*rs):
            yield func(*items)

    return reader


def shuffle(reader, buf_size: int):
    """Pool-shuffle with a bounded buffer (decorator.py shuffle)."""

    def reader_():
        buf: List = []
        for sample in reader():
            buf.append(sample)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return reader_


def chain(*readers):
    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers, check_alignment: bool = True):
    """Zip readers into tuple samples (decorator.py compose)."""

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        iters = itertools.zip_longest(*rs)
        for outputs in iters:
            if check_alignment and any(o is None for o in outputs):
                raise RuntimeError("readers have different lengths")
            yield sum((make_tuple(o) for o in outputs), ())

    return reader


def buffered(reader, size: int):
    """Background-thread prefetch buffer (decorator.py buffered) — the
    host-side analog of the reference's double-buffer reader op."""

    end = object()

    def reader_():
        q: queue.Queue = queue.Queue(maxsize=size)

        def fill():
            try:
                for sample in reader():
                    q.put(sample)
                q.put(end)
            except BaseException as e:  # propagate to the consumer
                q.put(_ReaderError(e))

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            sample = q.get()
            if sample is end:
                break
            if isinstance(sample, _ReaderError):
                raise sample.error
            yield sample

    return reader_


class _ReaderError:
    """Exception carrier across reader threads."""

    def __init__(self, error: BaseException):
        self.error = error


def firstn(reader, n: int):
    def reader_():
        yield from itertools.islice(reader(), n)

    return reader_


def batch(reader, batch_size: int, drop_last: bool = False):
    """Group samples into lists (paddle.batch)."""

    def reader_():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return reader_


def xmap_readers(mapper, reader, process_num: int, buffer_size: int,
                 order: bool = False):
    """Parallel map over a thread pool (decorator.py xmap_readers)."""

    end = object()

    def reader_():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)

        def feed():
            try:
                for i, sample in enumerate(reader()):
                    in_q.put((i, sample))
                for _ in range(process_num):
                    in_q.put(end)
            except BaseException as e:
                out_q.put(_ReaderError(e))

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, sample = item
                try:
                    out_q.put((i, mapper(sample)))
                except BaseException as e:
                    out_q.put(_ReaderError(e))
                    return

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()

        finished = 0
        pending = {}
        next_idx = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            if isinstance(item, _ReaderError):
                raise item.error
            if not order:
                yield item[1]
            else:
                pending[item[0]] = item[1]
                while next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
        if order:
            for i in sorted(pending):
                yield pending[i]

    return reader_
