"""In-step update guard: skip non-finite optimizer updates on device.

A single NaN/Inf step corrupts training silently — telemetry *counts*
nonfinite grads (observe/metrics.py) but the optimizer applies them
anyway, and every parameter is NaN one step later.  The guard closes
that hole INSIDE the one jitted step (CLAUDE.md invariant: no host
round-trips, no extra dispatches, no callbacks):

1. after gradients are computed, an all-finite reduction runs over the
   loss and every gradient leaf (SparseGrad rows included),
2. the optimizer/update ops execute unconditionally (tracing is
   unconditional under jit anyway), then every value they wrote is
   `jnp.where(all_finite, new, old)`-selected against its pre-update
   snapshot — a poisoned step is a full state no-op,
3. the telemetry accumulator (`__telemetry__`, which the guard rides)
   gains `skipped_update_steps` plus the dynamic loss-scale state.

Dynamic loss scaling (`amp.decorate(..., use_dynamic_loss_scaling=
True)`, the fp16/bf16 underflow story): the loss is multiplied by a
device-resident scale before autodiff, gradients are unscaled before
the finite check and the update ops, and the scale adapts — halved
(decr_ratio) after `decr_every_n_nan_or_inf` consecutive overflow
steps, multiplied by incr_ratio after `incr_every_n_steps` consecutive
good steps (reference: fluid's update_loss_scaling op semantics).

The executor hooks (`core/executor.py interpret_program`) call the
helpers below; everything here is pure jnp over values already live in
the trace.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional


@dataclass
class LossScaleConfig:
    """Dynamic loss-scale schedule (reference: fluid
    update_loss_scaling_op attrs)."""

    init_loss_scaling: float = 2.0 ** 15
    incr_every_n_steps: int = 1000
    decr_every_n_nan_or_inf: int = 1
    incr_ratio: float = 2.0
    decr_ratio: float = 0.5
    min_loss_scaling: float = 1.0
    max_loss_scaling: float = 2.0 ** 24

    def __post_init__(self):
        if self.init_loss_scaling <= 0:
            raise ValueError("init_loss_scaling must be > 0")
        if self.incr_every_n_steps < 1 or self.decr_every_n_nan_or_inf < 1:
            raise ValueError("loss-scale step intervals must be >= 1")
        if not (self.incr_ratio > 1.0 and 0.0 < self.decr_ratio < 1.0):
            raise ValueError("need incr_ratio > 1 and 0 < decr_ratio < 1")


class UpdateGuardConfig:
    """Program-level guard switch; `loss_scaling=None` guards updates
    at scale 1.0 (finite-check only)."""

    def __init__(self, loss_scaling: Optional[LossScaleConfig] = None):
        self.loss_scaling = loss_scaling

    @property
    def init_loss_scale(self) -> float:
        return (self.loss_scaling.init_loss_scaling
                if self.loss_scaling else 1.0)


def enable_update_guard(program,
                        loss_scaling: Optional[LossScaleConfig] = None
                        ) -> UpdateGuardConfig:
    """Opt a Program's compiled step into the non-finite update guard.

    Implies device-side telemetry (the skip counter and loss-scale
    scalar live in the `__telemetry__` executor state).  Bumps the
    program version so an already-cached unguarded step fn is not
    reused."""
    from ..observe import metrics as _metrics

    cfg = UpdateGuardConfig(loss_scaling)
    program._update_guard = cfg
    _metrics.enable_telemetry(program)
    program._bump()
    return cfg


def guard_config(program) -> Optional[UpdateGuardConfig]:
    return getattr(program, "_update_guard", None)


# ---------------------------------------------------------------------------
# Trace-time helpers (called from core/executor.py inside the jit)
# ---------------------------------------------------------------------------

def all_finite(loss, grads: Dict[str, Any]):
    """Scalar bool: loss and every gradient leaf finite.  SparseGrad
    contributes its rows (ids are ints, always finite)."""
    import jax.numpy as jnp

    from ..core.selected_rows import SparseGrad

    ok = jnp.all(jnp.isfinite(jnp.asarray(loss).astype(jnp.float32)))
    for g in grads.values():
        parts = (g.rows,) if isinstance(g, SparseGrad) else (g,)
        for a in parts:
            ok = ok & jnp.all(jnp.isfinite(a.astype(jnp.float32)))
    return ok


def scale_grads(grads: Dict[str, Any], factor) -> Dict[str, Any]:
    """grads * factor, preserving SparseGrad structure and leaf dtypes
    (master grads are f32; the multiply must not upcast bf16 leaves)."""
    from ..core.selected_rows import SparseGrad

    def one(g):
        if isinstance(g, SparseGrad):
            return SparseGrad(g.ids, (g.rows * factor).astype(g.rows.dtype),
                              g.dense_shape)
        return (g * factor).astype(g.dtype)

    return {k: one(g) for k, g in grads.items()}


def snapshot_env(env: Dict[str, Any], names) -> Dict[str, Any]:
    """Pre-update values of every arrayish env entry in `names` — what
    a skipped step rolls back to."""
    import numpy as np

    return {n: env[n] for n in names
            if n in env and (hasattr(env[n], "dtype")
                             or isinstance(env[n], np.ndarray))}


def select_updates(finite, env: Dict[str, Any],
                   pre: Dict[str, Any]) -> None:
    """env[n] = where(finite, updated, pre-update) for every
    snapshotted name the update ops rewrote — pure selects, so the step
    stays ONE fused XLA computation (no lax.cond branch dispatch, no
    host sync)."""
    import jax.numpy as jnp

    for n, old in pre.items():
        new = env.get(n)
        if new is None or new is old:
            continue
        env[n] = jnp.where(finite, new, old).astype(
            getattr(new, "dtype", None) or jnp.asarray(new).dtype)


def guard_telemetry_update(tel: Dict[str, Any], finite,
                           cfg: UpdateGuardConfig) -> Dict[str, Any]:
    """Accumulate the skip counter and advance the loss-scale schedule
    (device-side, inside the trace)."""
    import jax.numpy as jnp

    out = dict(tel)
    skipped = (~finite).astype(jnp.int32)
    out["skipped_update_steps"] = tel["skipped_update_steps"] + skipped
    ls = cfg.loss_scaling
    if ls is None:
        return out
    scale = jnp.asarray(tel["loss_scale"], jnp.float32)
    good = jnp.asarray(tel["ls_good_steps"], jnp.int32)
    bad = jnp.asarray(tel["ls_bad_steps"], jnp.int32)
    good = jnp.where(finite, good + 1, 0).astype(jnp.int32)
    bad = jnp.where(finite, 0, bad + 1).astype(jnp.int32)
    decr = bad >= ls.decr_every_n_nan_or_inf
    scale = jnp.where(
        decr, jnp.maximum(scale * ls.decr_ratio, ls.min_loss_scaling),
        scale)
    bad = jnp.where(decr, 0, bad).astype(jnp.int32)
    incr = good >= ls.incr_every_n_steps
    scale = jnp.where(
        incr, jnp.minimum(scale * ls.incr_ratio, ls.max_loss_scaling),
        scale)
    good = jnp.where(incr, 0, good).astype(jnp.int32)
    out["loss_scale"] = scale
    out["ls_good_steps"] = good
    out["ls_bad_steps"] = bad
    return out
