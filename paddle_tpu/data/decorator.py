"""Reader decorators.

reference: python/paddle/reader/decorator.py:58-338 — a reader is a
zero-arg callable returning an iterable of samples; decorators compose
readers.
"""

from __future__ import annotations

import itertools
import queue
import random as _random
import threading
from typing import Callable, Iterable, List


def map_readers(func, *readers):
    """Apply func elementwise across readers (decorator.py map_readers)."""

    def reader():
        rs = [r() for r in readers]
        for items in zip(*rs):
            yield func(*items)

    return reader


def shuffle(reader, buf_size: int, seed=None):
    """Pool-shuffle with a bounded buffer (decorator.py shuffle).

    With `seed` the shuffle order is drawn from a PRIVATE
    `random.Random(seed)` re-seeded on every `reader_()` call — the
    stream is then a pure function of (seed, underlying reader), so a
    process killed and relaunched replays the exact same feed order.
    contrib.Trainer's bit-exact resume guarantee requires deterministic
    readers; the seedless form uses the global RNG and is NOT
    resume-safe (documented in docs/RESILIENCE.md)."""

    def reader_():
        rng = _random.Random(seed) if seed is not None else _random
        buf: List = []
        for sample in reader():
            buf.append(sample)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            rng.shuffle(buf)
            yield from buf

    return reader_


def chain(*readers):
    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers, check_alignment: bool = True):
    """Zip readers into tuple samples (decorator.py compose)."""

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        iters = itertools.zip_longest(*rs)
        for outputs in iters:
            if check_alignment and any(o is None for o in outputs):
                raise RuntimeError("readers have different lengths")
            yield sum((make_tuple(o) for o in outputs), ())

    return reader


def buffered(reader, size: int):
    """Background-thread prefetch buffer (decorator.py buffered) — the
    host-side analog of the reference's double-buffer reader op."""

    end = object()

    def reader_():
        q: queue.Queue = queue.Queue(maxsize=size)

        def fill():
            try:
                for sample in reader():
                    q.put(sample)
                q.put(end)
            except BaseException as e:  # propagate to the consumer
                q.put(_ReaderError(e))

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            sample = q.get()
            if sample is end:
                break
            if isinstance(sample, _ReaderError):
                raise sample.error
            yield sample

    return reader_


class _ReaderError:
    """Exception carrier across reader threads."""

    def __init__(self, error: BaseException):
        self.error = error


def firstn(reader, n: int):
    def reader_():
        yield from itertools.islice(reader(), n)

    return reader_


def batch(reader, batch_size: int, drop_last: bool = False):
    """Group samples into lists (paddle.batch)."""

    def reader_():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return reader_


def xmap_readers(mapper, reader, process_num: int, buffer_size: int,
                 order: bool = False):
    """Parallel map over a thread pool (decorator.py xmap_readers)."""

    end = object()

    def reader_():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)

        def feed():
            try:
                for i, sample in enumerate(reader()):
                    in_q.put((i, sample))
                for _ in range(process_num):
                    in_q.put(end)
            except BaseException as e:
                out_q.put(_ReaderError(e))

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, sample = item
                try:
                    out_q.put((i, mapper(sample)))
                except BaseException as e:
                    out_q.put(_ReaderError(e))
                    return

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()

        finished = 0
        pending = {}
        next_idx = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            if isinstance(item, _ReaderError):
                raise item.error
            if not order:
                yield item[1]
            else:
                pending[item[0]] = item[1]
                while next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
        if order:
            for i in sorted(pending):
                yield pending[i]

    return reader_


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Run each reader in its OWN process and interleave their samples
    (reference: python/paddle/reader/decorator.py multiprocess_reader —
    process count == reader count, merged through a queue or pipes).
    Readers must be picklable (top-level functions / closures over
    picklable state).  Samples pass through a multiprocessing.Queue
    (use_pipe=False) or one Pipe per reader (use_pipe=True, the
    reference default); order across readers is arrival order."""
    import multiprocessing

    if not isinstance(readers, (list, tuple)) or not readers:
        raise ValueError("multiprocess_reader needs a non-empty list "
                         "of readers")
    _END = "__multiprocess_reader_end__"
    _ERR = "__multiprocess_reader_err__"

    def _work(r, emit):
        # a crashed child must SURFACE, not masquerade as exhaustion —
        # the parent re-raises instead of training on truncated data
        try:
            for sample in r():
                emit(sample)
            emit(_END)
        except Exception as e:  # noqa: BLE001 — crossing processes
            emit((_ERR, f"{type(e).__name__}: {e}"))

    def _handle(item):
        """→ ('end'|'err'|'sample', payload)."""
        if isinstance(item, str) and item == _END:
            return "end", None
        if (isinstance(item, tuple) and len(item) == 2
                and item[0] == _ERR):
            raise RuntimeError(
                f"multiprocess_reader: child reader failed: {item[1]}")
        return "sample", item

    def _queue_reader():
        q = multiprocessing.Queue(queue_size)
        procs = [multiprocessing.Process(target=_work,
                                         args=(r, q.put), daemon=True)
                 for r in readers]
        for p in procs:
            p.start()
        finished = 0
        while finished < len(readers):
            kind, item = _handle(q.get())
            if kind == "end":
                finished += 1
            else:
                yield item
        for p in procs:
            p.join()

    def _pipe_reader():
        conns, procs = [], []
        for r in readers:
            parent, child = multiprocessing.Pipe(duplex=False)
            p = multiprocessing.Process(target=_work,
                                        args=(r, child.send),
                                        daemon=True)
            p.start()
            conns.append(parent)
            procs.append(p)
        live = list(conns)
        while live:
            for conn in list(live):
                if not conn.poll(0.01):
                    continue
                kind, item = _handle(conn.recv())
                if kind == "end":
                    live.remove(conn)
                else:
                    yield item
        for p in procs:
            p.join()

    return _pipe_reader if use_pipe else _queue_reader


class Fake:
    """Cache the FIRST sample of a reader and replay it `data_num`
    times (reference decorator.py:509 — frozen-feed speed testing;
    bench.py's data_mode="frozen" is the device-side analog)."""

    def __init__(self):
        self.data = None

    def __call__(self, reader, data_num):
        def fake_reader():
            if self.data is None:
                self.data = next(reader())
            for _ in range(data_num):
                yield self.data

        return fake_reader
