"""Control-flow tests: While / Switch / IfElse / StaticRNN / DynamicRNN,
tensor arrays, beam search, gradients().

reference test pattern: python/paddle/fluid/tests/unittests/
test_while_op.py, test_recurrent_op.py, test_dyn_rnn.py,
test_beam_search_op.py, test_calc_gradient.py.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


@pytest.fixture()
def exe():
    return fluid.Executor()


def test_while_sum(exe):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = layers.fill_constant(shape=[1], dtype="int32", value=0)
        n = layers.fill_constant(shape=[1], dtype="int32", value=10)
        acc = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
        cond = layers.less_than(i, n)
        w = layers.While(cond)
        with w.block():
            layers.assign(acc + layers.cast(i, "float32"), acc)
            layers.increment(i, value=1, in_place=True)
            layers.less_than(i, n, cond=cond)
    acc_v, i_v = exe.run(main, fetch_list=[acc, i])
    assert acc_v[0] == 45.0
    assert i_v[0] == 10


def test_while_with_array(exe):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = layers.fill_constant(shape=[1], dtype="int32", value=0)
        n = layers.fill_constant(shape=[1], dtype="int32", value=5)
        arr = layers.create_array("float32", element_shape=[2], capacity=8)
        cond = layers.less_than(i, n)
        w = layers.While(cond)
        with w.block():
            v = layers.expand(layers.reshape(
                layers.cast(i, "float32"), [1]), [2])
            layers.array_write(v, i, arr)
            layers.increment(i, value=1, in_place=True)
            layers.less_than(i, n, cond=cond)
        stacked, _ = layers.array_to_tensor(arr)
        length = layers.array_length(arr)
    s, ln = exe.run(main, fetch_list=[stacked, length])
    np.testing.assert_allclose(s[:5, 0], np.arange(5, dtype=np.float32))
    np.testing.assert_allclose(s[5:], 0.0)
    assert ln[0] == 5


def test_nested_while(exe):
    # sum_{i<3} sum_{j<4} 1 == 12
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = layers.fill_constant([1], "int32", 0)
        ni = layers.fill_constant([1], "int32", 3)
        total = layers.fill_constant([1], "float32", 0.0)
        cond_o = layers.less_than(i, ni)
        wo = layers.While(cond_o)
        with wo.block():
            j = layers.fill_constant([1], "int32", 0)
            nj = layers.fill_constant([1], "int32", 4)
            cond_i = layers.less_than(j, nj)
            wi = layers.While(cond_i)
            with wi.block():
                layers.assign(total + 1.0, total)
                layers.increment(j, 1)
                layers.less_than(j, nj, cond=cond_i)
            layers.increment(i, 1)
            layers.less_than(i, ni, cond=cond_o)
    (t,) = exe.run(main, fetch_list=[total])
    assert t[0] == 12.0


def test_switch_lr_schedule(exe):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        step = layers.data("step", shape=[1], append_batch_size=False)
        lr = layers.fill_constant([1], "float32", 0.0)
        b1 = layers.fill_constant([1], "float32", 100.0)
        b2 = layers.fill_constant([1], "float32", 200.0)
        with layers.Switch() as sw:
            with sw.case(layers.less_than(step, b1)):
                layers.assign(layers.fill_constant([1], "float32", 0.1), lr)
            with sw.case(layers.less_than(step, b2)):
                layers.assign(layers.fill_constant([1], "float32", 0.01), lr)
            with sw.default():
                layers.assign(layers.fill_constant([1], "float32", 0.001), lr)
    for s, want in [(50.0, 0.1), (150.0, 0.01), (500.0, 0.001)]:
        (v,) = exe.run(main, feed={"step": np.array([s], np.float32)},
                       fetch_list=[lr])
        assert v[0] == pytest.approx(want)


def test_ifelse_per_row(exe):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[6, 1], append_batch_size=False)
        zero = layers.fill_constant([6, 1], "float32", 0.0)
        cond = layers.greater_than(x, zero)
        ie = layers.IfElse(cond)
        with ie.true_block():
            ie.output(layers.scale(ie.input(x), scale=2.0))
        with ie.false_block():
            ie.output(layers.scale(ie.input(x), scale=-1.0))
        (out,) = ie()
    xv = np.array([[-2.0], [3.0], [0.5], [-1.0], [0.0], [4.0]], np.float32)
    (o,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
    want = np.where(xv > 0, xv * 2.0, -xv)
    np.testing.assert_allclose(o, want)


def test_static_rnn_forward_and_grad(exe):
    T, B, D, H = 5, 4, 3, 8
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, startup2):
        x = layers.data("x", shape=[T, B, D], append_batch_size=False)
        h0 = layers.fill_constant([B, H], "float32", 0.0)
        rnn = layers.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            h_prev = rnn.memory(init=h0)
            h = layers.fc(input=[xt, h_prev], size=H, act="tanh",
                          bias_attr=False)
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        out = rnn()
        loss = layers.reduce_mean(out)
        opt = fluid.optimizer.SGD(learning_rate=0.5)
        opt.minimize(loss)
    exe.run(startup2)
    xv = np.random.RandomState(0).randn(T, B, D).astype(np.float32)
    losses = [float(exe.run(main2, feed={"x": xv},
                            fetch_list=[loss])[0]) for _ in range(6)]
    # gradient flows through the scan: loss must move
    assert losses[0] != losses[-1]
    assert np.isfinite(losses).all()


def test_static_rnn_cumsum_semantics(exe):
    T, B, D = 4, 3, 2
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[T, B, D], append_batch_size=False)
        z = layers.fill_constant([B, D], "float32", 0.0)
        rnn = layers.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            acc = rnn.memory(init=z)
            s = layers.elementwise_add(acc, xt)
            rnn.update_memory(acc, s)
            rnn.step_output(s)
        out = rnn()
    xv = np.random.rand(T, B, D).astype(np.float32)
    (o,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(o, np.cumsum(xv, axis=0), rtol=1e-6)


def test_dynamic_rnn_masking(exe):
    B, T, D = 3, 5, 2
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[B, T, D], append_batch_size=False,
                        lod_level=1)
        drnn = layers.DynamicRNN()
        with drnn.block():
            xt = drnn.step_input(x)
            acc = drnn.memory(shape=[D], value=0.0)
            s = layers.elementwise_add(acc, xt)
            drnn.update_memory(acc, s)
            drnn.output(s)
        out = drnn()
        last = layers.sequence_last_step(out)
    xv = np.random.rand(B, T, D).astype(np.float32)
    sl = np.array([2, 5, 3], np.int32)
    o, lastv = exe.run(main, feed={"x": xv, "x.seq_len": sl},
                       fetch_list=[out, last])
    ref = np.cumsum(xv, axis=1)
    for b, l in enumerate(sl):
        ref[b, l:] = 0.0
    np.testing.assert_allclose(o, ref, rtol=1e-6)
    ref_last = np.stack([np.cumsum(xv, 1)[b, l - 1] for b, l in enumerate(sl)])
    np.testing.assert_allclose(lastv, ref_last, rtol=1e-6)


def test_dynamic_rnn_trains(exe):
    B, T, D, H = 4, 6, 3, 8
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[B, T, D], append_batch_size=False,
                        lod_level=1)
        y = layers.data("y", shape=[B, 1], append_batch_size=False)
        drnn = layers.DynamicRNN()
        with drnn.block():
            xt = drnn.step_input(x)
            h_prev = drnn.memory(shape=[H], value=0.0)
            h = layers.fc(input=[xt, h_prev], size=H, act="tanh",
                          bias_attr=False)
            drnn.update_memory(h_prev, h)
            drnn.output(h)
        out = drnn()
        last = layers.sequence_last_step(out)
        pred = layers.fc(last, size=1)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe.run(startup)
    rng = np.random.RandomState(1)
    feed = {"x": rng.rand(B, T, D).astype(np.float32),
            "x.seq_len": np.array([3, 6, 2, 5], np.int32),
            "y": rng.rand(B, 1).astype(np.float32)}
    losses = [float(exe.run(main, feed=feed, fetch_list=[loss])[0])
              for _ in range(10)]
    assert losses[-1] < losses[0]


def test_gradients_basic(exe):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = layers.data("a", shape=[3], append_batch_size=False)
        y = layers.reduce_sum(layers.elementwise_mul(a, a))
        (ga,) = fluid.gradients(y, a)
    av = np.array([1.0, -2.0, 3.0], np.float32)
    (g,) = exe.run(main, feed={"a": av}, fetch_list=[ga])
    np.testing.assert_allclose(g, 2 * av, rtol=1e-6)


def test_gradients_with_cotangent(exe):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = layers.data("a", shape=[3], append_batch_size=False)
        w = layers.data("w", shape=[3], append_batch_size=False)
        y = layers.elementwise_mul(a, a)
        (ga,) = fluid.gradients([y], [a], target_gradients=[w])
    av = np.array([1.0, 2.0, 3.0], np.float32)
    wv = np.array([1.0, 0.0, 2.0], np.float32)
    (g,) = exe.run(main, feed={"a": av, "w": wv}, fetch_list=[ga])
    np.testing.assert_allclose(g, 2 * av * wv, rtol=1e-6)


def test_gradients_wrt_intermediate_var(exe):
    # grad w.r.t. a var that is itself produced by an op: the producer
    # must not overwrite the traced binding (would silently yield zeros)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = layers.data("a", shape=[3], append_batch_size=False)
        b = layers.scale(a, scale=2.0)
        y = layers.reduce_sum(layers.elementwise_mul(b, b))
        (gb,) = fluid.gradients(y, b)
    av = np.array([1.0, 2.0, 3.0], np.float32)
    (g,) = exe.run(main, feed={"a": av}, fetch_list=[gb])
    np.testing.assert_allclose(g, 2 * (2 * av), rtol=1e-6)  # dy/db = 2b


def test_gradients_ignores_unrelated_unfed_branch(exe):
    # ops off the inputs→targets path (over unfed data) must not be
    # re-traced by calc_gradient
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = layers.data("a", shape=[3], append_batch_size=False)
        yy = layers.data("yy", shape=[4], append_batch_size=False)
        _unused = layers.reduce_sum(yy)
        t = layers.reduce_sum(layers.elementwise_mul(a, a))
        (ga,) = fluid.gradients(t, a)
    av = np.array([1.0, 2.0, 3.0], np.float32)
    (g,) = exe.run(main, feed={"a": av}, fetch_list=[ga])
    np.testing.assert_allclose(g, 2 * av, rtol=1e-6)


def test_logical_wrappers_write_into_out():
    # layers.logical_not/logical_and must be the control_flow (out=) forms,
    # not the autogenerated unary wrappers (import-order shadowing guard)
    assert layers.logical_not.__module__ == "paddle_tpu.layers.control_flow"
    assert layers.less_than.__module__ == "paddle_tpu.layers.control_flow"


def test_double_grad(exe):
    # d2/dx2 sum(x^3) = 6x
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = layers.data("a", shape=[3], append_batch_size=False)
        y = layers.reduce_sum(
            layers.elementwise_mul(layers.elementwise_mul(a, a), a))
        (g1,) = fluid.gradients(y, a)      # 3x^2
        s = layers.reduce_sum(g1)
        (g2,) = fluid.gradients(s, a)      # 6x
    av = np.array([1.0, 2.0, -1.0], np.float32)
    g1v, g2v = exe.run(main, feed={"a": av}, fetch_list=[g1, g2])
    np.testing.assert_allclose(g1v, 3 * av * av, rtol=1e-5)
    np.testing.assert_allclose(g2v, 6 * av, rtol=1e-5)


def test_beam_search_step(exe):
    B, K, V = 2, 3, 6
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        pid = layers.data("pid", shape=[B, K], dtype="int64",
                          append_batch_size=False)
        psc = layers.data("psc", shape=[B, K], append_batch_size=False)
        sc = layers.data("sc", shape=[B, K, V], append_batch_size=False)
        ids, scores, parent = layers.beam_search(pid, psc, sc, beam_size=K,
                                                 end_id=1)
    rng = np.random.RandomState(0)
    pidv = np.zeros((B, K), np.int64)
    pscv = rng.rand(B, K).astype(np.float32)
    scv = np.log(rng.dirichlet(np.ones(V), size=(B, K))).astype(np.float32)
    idv, scov, parv = exe.run(
        main, feed={"pid": pidv, "psc": pscv, "sc": scv},
        fetch_list=[ids, scores, parent])
    # numpy reference: top-k of pre_scores + logp over (K*V)
    flat = (pscv[:, :, None] + scv).reshape(B, K * V)
    order = np.argsort(-flat, axis=1)[:, :K]
    np.testing.assert_allclose(np.sort(scov, 1),
                               np.sort(np.take_along_axis(flat, order, 1), 1),
                               rtol=1e-5)
    np.testing.assert_array_equal(np.sort(parv, 1),
                                  np.sort(order // V, 1))
    np.testing.assert_array_equal(np.sort(idv, 1), np.sort(order % V, 1))


def test_beam_search_finished_beams_frozen(exe):
    B, K, V = 1, 2, 4
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        pid = layers.data("pid", shape=[B, K], dtype="int64",
                          append_batch_size=False)
        psc = layers.data("psc", shape=[B, K], append_batch_size=False)
        sc = layers.data("sc", shape=[B, K, V], append_batch_size=False)
        ids, scores, parent = layers.beam_search(pid, psc, sc, beam_size=K,
                                                 end_id=1)
    # beam 0 finished (id=1) with high score; it must survive unchanged
    pidv = np.array([[1, 0]], np.int64)
    pscv = np.array([[5.0, 0.0]], np.float32)
    scv = np.full((B, K, V), -2.0, np.float32)
    idv, scov, parv = exe.run(
        main, feed={"pid": pidv, "psc": pscv, "sc": scv},
        fetch_list=[ids, scores, parent])
    assert idv[0, 0] == 1            # end token re-emitted
    assert scov[0, 0] == pytest.approx(5.0)   # score frozen
    assert parv[0, 0] == 0


def test_beam_search_decode_backtrace(exe):
    T, B, K = 3, 1, 2
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[T, B, K], dtype="int64",
                          append_batch_size=False)
        par = layers.data("par", shape=[T, B, K], dtype="int32",
                          append_batch_size=False)
        sents = layers.beam_search_decode(ids, par, end_id=0)
    # step0: beams pick tokens [5, 6]; step1 beam0<-parent1, beam1<-parent0;
    # step2 both from parent 0
    idv = np.array([[[5, 6]], [[7, 8]], [[9, 9]]], np.int64)
    parv = np.array([[[0, 0]], [[1, 0]], [[0, 0]]], np.int32)
    (s,) = exe.run(main, feed={"ids": idv, "par": parv}, fetch_list=[sents])
    # hypothesis 0 at final step: t2 token 9 <- parent 0 (t1 token 7 beam0)
    # t1 beam0 parent=1 -> t0 token 6
    np.testing.assert_array_equal(s[0, 0], [6, 7, 9])
    np.testing.assert_array_equal(s[0, 1], [6, 7, 9])


def test_machine_translation_train_and_beam_decode(exe):
    from paddle_tpu.models import machine_translation as mt

    B, Tsrc, Ttrg, V = 4, 8, 7, 50
    train_prog, train_startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(train_prog, train_startup):
        avg_cost, _feeds = mt.seq_to_seq_net(
            src_vocab_size=V, trg_vocab_size=V, embed_dim=16, hidden_dim=32,
            batch_size=B, max_src_len=Tsrc, max_trg_len=Ttrg)
        fluid.optimizer.Adam(learning_rate=2e-3).minimize(avg_cost)
    exe.run(train_startup)
    rng = np.random.RandomState(0)
    feed = {
        "src_word_id": rng.randint(2, V, (B, Tsrc)).astype(np.int64),
        "src_word_id.seq_len": rng.randint(3, Tsrc + 1, B).astype(np.int32),
        "trg_word_id": rng.randint(2, V, (B, Ttrg)).astype(np.int64),
        "trg_word_id.seq_len": rng.randint(3, Ttrg + 1, B).astype(np.int32),
        "trg_next_id": rng.randint(2, V, (B, Ttrg)).astype(np.int64),
    }
    losses = [float(exe.run(train_prog, feed=feed,
                            fetch_list=[avg_cost])[0]) for _ in range(8)]
    assert losses[-1] < losses[0]

    K, L = 3, 6
    infer_prog, infer_startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(infer_prog, infer_startup):
        sents, scores, _ifeeds = mt.beam_search_net(
            src_vocab_size=V, trg_vocab_size=V, embed_dim=16, hidden_dim=32,
            batch_size=B, max_src_len=Tsrc, beam_size=K, max_decode_len=L,
            start_id=0, end_id=1)
    out_s, out_sc = exe.run(
        infer_prog,
        feed={"src_word_id": feed["src_word_id"],
              "src_word_id.seq_len": feed["src_word_id.seq_len"]},
        fetch_list=[sents, scores])
    assert out_s.shape == (B, K, L)
    assert out_sc.shape == (B, K)
    # beams are score-sorted per batch row
    assert (np.diff(out_sc, axis=1) <= 1e-5).all()
    assert np.isfinite(out_sc).all()


def test_error_context_names_failing_op():
    main, startup = fluid.Program(), fluid.Program()
    exe = fluid.Executor()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], append_batch_size=False)
        y = layers.data("y", shape=[5], append_batch_size=False)
        z = layers.elementwise_add(x, y)  # shape mismatch at trace time
    with pytest.raises(Exception) as ei:
        exe.run(main, feed={"x": np.zeros(4, np.float32),
                            "y": np.zeros(5, np.float32)},
                fetch_list=[z])
    assert "elementwise_add" in str(ei.value)


def test_tensor_array_to_tensor_concat_and_stack(exe):
    """tensor_array_to_tensor: axis-concat (default) and use_stack
    variants over a written array (reference
    tensor_array_to_tensor_op.cc)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[3, 2, 4], append_batch_size=False)
        arr = layers.create_array("float32", element_shape=[2, 4],
                                  capacity=3)
        for i in range(3):
            xi = layers.squeeze(
                layers.slice(x, axes=[0], starts=[i], ends=[i + 1]),
                axes=[0])
            layers.array_write(
                xi, layers.fill_constant([1], "int64", i), arr)
        cat, cat_idx = layers.tensor_array_to_tensor(arr, axis=1)
        stk, stk_idx = layers.tensor_array_to_tensor(arr, axis=0,
                                                     use_stack=True)
    exe.run(startup)
    xv = np.random.RandomState(0).randn(3, 2, 4).astype(np.float32)
    c, ci, s, si = exe.run(main, feed={"x": xv},
                           fetch_list=[cat, cat_idx, stk, stk_idx])
    np.testing.assert_allclose(c, np.concatenate(list(xv), axis=1))
    np.testing.assert_array_equal(ci, [4, 4, 4])
    np.testing.assert_allclose(s, xv)
    np.testing.assert_array_equal(si, [1, 1, 1])


def test_lod_rank_table_and_reorder(exe):
    """lod_rank_table sorts by length desc (stable); reorder permutes
    the batch AND the .seq_len companion; gradients route through the
    permutation."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 3, 2], append_batch_size=False,
                        lod_level=1)
        table = layers.lod_rank_table(x)
        y = layers.reorder_lod_tensor_by_rank(x, table)
        ylen = layers.seq_len_var(y)
    exe.run(startup)
    xv = np.arange(24, dtype=np.float32).reshape(4, 3, 2)
    sl = np.array([2, 3, 1, 3], np.int32)
    tb, yv, yl = exe.run(
        main, feed={"x": xv, "x.seq_len": sl},
        fetch_list=[table, y, ylen])
    # lengths [2,3,1,3] -> stable desc order: idx 1 (3), 3 (3), 0, 2
    np.testing.assert_array_equal(tb, [1, 3, 0, 2])
    np.testing.assert_allclose(yv, xv[[1, 3, 0, 2]])
    np.testing.assert_array_equal(yl, [3, 3, 2, 1])


def test_lod_rank_table_requires_sequence():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("plain", shape=[4, 3],
                        append_batch_size=False)
        with pytest.raises(ValueError, match="seq_len"):
            layers.lod_rank_table(x)


def test_tensor_array_to_tensor_axis_validation(exe):
    """Stack accepts the insert-at-end position (axis == entry rank);
    concat rejects it and scalar entries, at BUILD time."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[3, 2, 4], append_batch_size=False)
        arr = layers.create_array("float32", element_shape=[2, 4],
                                  capacity=3)
        for i in range(3):
            xi = layers.squeeze(
                layers.slice(x, axes=[0], starts=[i], ends=[i + 1]),
                axes=[0])
            layers.array_write(
                xi, layers.fill_constant([1], "int64", i), arr)
        tail, _ = layers.tensor_array_to_tensor(arr, axis=2,
                                                use_stack=True)
        assert tuple(tail.shape) == (2, 4, 3)
        with pytest.raises(ValueError, match="out of range"):
            layers.tensor_array_to_tensor(arr, axis=2)  # concat bound
        with pytest.raises(ValueError, match="out of range"):
            layers.tensor_array_to_tensor(arr, axis=3, use_stack=True)
        scal = layers.create_array("float32", element_shape=[],
                                   capacity=3)
        with pytest.raises(ValueError, match="scalar"):
            layers.tensor_array_to_tensor(scal, axis=0)
    exe.run(startup)
    xv = np.random.RandomState(1).randn(3, 2, 4).astype(np.float32)
    (tv,) = exe.run(main, feed={"x": xv}, fetch_list=[tail])
    np.testing.assert_allclose(tv, np.stack(list(xv), axis=2))


def test_static_rnn_unroll_equivalent(exe):
    """The macro-op scan path where the Pallas kernel cannot apply:
    StaticRNN(unroll=K) must compute the same recurrence.  XLA:CPU schedules/FMA-fuses
    the unrolled bodies differently by ~1 ulp per step (measured in
    tests/test_pallas_recurrence.py for the fused RNN ops); the
    recurrence COMPOUNDS that over T steps, hence the few-ulp atol."""
    T, B, D = 6, 3, 2

    def build(unroll):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[T, B, D],
                            append_batch_size=False)
            z = layers.fill_constant([B, D], "float32", 0.0)
            rnn = layers.StaticRNN(unroll=unroll)
            with rnn.step():
                xt = rnn.step_input(x)
                acc = rnn.memory(init=z)
                s = layers.tanh(layers.elementwise_add(acc, xt))
                rnn.update_memory(acc, s)
                rnn.step_output(s)
            out = rnn()
        return main, out

    xv = np.random.RandomState(5).randn(T, B, D).astype(np.float32)
    main1, out1 = build(1)
    (base,) = exe.run(main1, feed={"x": xv}, fetch_list=[out1])
    for k in (2, 4):
        maink, outk = build(k)
        (got,) = exe.run(maink, feed={"x": xv}, fetch_list=[outk])
        np.testing.assert_allclose(got, base, rtol=0, atol=5e-6)


def test_dynamic_rnn_unroll_equivalent(exe):
    B, T, D = 3, 5, 2
    lens = np.array([5, 3, 1], np.int32)

    def build(unroll):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[B, T, D],
                            append_batch_size=False, lod_level=1)
            drnn = layers.DynamicRNN(unroll=unroll)
            with drnn.block():
                xt = drnn.step_input(x)
                mem = drnn.memory(shape=[D], value=0.0)
                s = layers.tanh(layers.elementwise_add(mem, xt))
                drnn.update_memory(mem, s)
                drnn.output(s)
            out = drnn()
        return main, out

    xv = np.random.RandomState(6).randn(B, T, D).astype(np.float32)
    feed = {"x": xv, "x.seq_len": lens}
    main1, out1 = build(1)
    (base,) = exe.run(main1, feed=feed, fetch_list=[out1])
    main3, out3 = build(3)
    (got,) = exe.run(main3, feed=feed, fetch_list=[out3])
    np.testing.assert_allclose(got, base, rtol=0, atol=5e-6)
