"""Built-in datasets.

reference: python/paddle/dataset/ — mnist, cifar, uci_housing, imdb,
imikolov, movielens, wmt14/16 auto-download readers.  This environment
is zero-egress, so downloading is impossible; instead each dataset has
BOTH:

- a real-format file parser (`reader_creator` / `data_dir=` arg) that
  ingests the dataset's actual on-disk format — MNIST idx-ubyte .gz
  (dataset/mnist.py:43 reader_creator), CIFAR python-pickle tar
  (dataset/cifar.py reader_creator), UCI housing whitespace table with
  the reference's avg/min-max normalization (uci_housing.py:68
  load_data) — used whenever files are present (point `data_dir` or
  $PADDLE_DATASET_HOME at them), and
- a deterministic synthetic generator with the real shapes/dtypes/label
  spaces as the zero-egress fallback.

The reader contract is the reference's: zero-arg callable yielding
samples.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np


def _dataset_home(sub):
    home = os.environ.get("PADDLE_DATASET_HOME")
    return os.path.join(home, sub) if home else None


def _synthetic_classification(n, feature_shape, num_classes, seed,
                              flatten=False):
    rng = np.random.RandomState(seed)
    centers = rng.randn(num_classes, *feature_shape).astype(np.float32)

    def reader():
        r = np.random.RandomState(seed + 1)
        for _ in range(n):
            y = int(r.randint(num_classes))
            x = centers[y] + 0.5 * r.randn(*feature_shape).astype(np.float32)
            if flatten:
                x = x.reshape(-1)
            yield x, y

    return reader


class mnist:
    """28x28 grayscale digits, labels 0-9 (dataset/mnist.py)."""

    TRAIN_IMAGES = "train-images-idx3-ubyte.gz"
    TRAIN_LABELS = "train-labels-idx1-ubyte.gz"
    TEST_IMAGES = "t10k-images-idx3-ubyte.gz"
    TEST_LABELS = "t10k-labels-idx1-ubyte.gz"

    @staticmethod
    def reader_creator(image_filename, label_filename):
        """Parse the REAL idx-ubyte format (dataset/mnist.py:43): gzip'd
        big-endian headers (magic 2051 images / 2049 labels), raw u8
        pixels scaled to [-1, 1) exactly like the reference
        (`images / 255.0 * 2.0 - 1.0`); yields (flat f32 784, int)."""

        def reader():
            with gzip.open(image_filename, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                if magic != 2051:
                    raise IOError(
                        f"bad idx3 magic {magic} in {image_filename}")
                images = np.frombuffer(f.read(n * rows * cols),
                                       np.uint8).reshape(n, rows * cols)
            with gzip.open(label_filename, "rb") as f:
                magic, ln = struct.unpack(">II", f.read(8))
                if magic != 2049:
                    raise IOError(
                        f"bad idx1 magic {magic} in {label_filename}")
                labels = np.frombuffer(f.read(ln), np.uint8)
            if ln != n:
                raise IOError(f"mnist: {n} images but {ln} labels")
            imgs = images.astype(np.float32) / 255.0 * 2.0 - 1.0
            for i in range(n):
                yield imgs[i], int(labels[i])

        return reader

    @staticmethod
    def _files_in(data_dir, img, lbl):
        if data_dir is None:
            data_dir = _dataset_home("mnist")
        if data_dir is None:
            return None
        pi, pl = os.path.join(data_dir, img), os.path.join(data_dir, lbl)
        return (pi, pl) if (os.path.exists(pi)
                            and os.path.exists(pl)) else None

    @staticmethod
    def train(n=60000, seed=0, data_dir=None):
        real = mnist._files_in(data_dir, mnist.TRAIN_IMAGES,
                               mnist.TRAIN_LABELS)
        if real:
            return mnist.reader_creator(*real)
        return _synthetic_classification(n, (1, 28, 28), 10, seed)

    @staticmethod
    def test(n=10000, seed=7, data_dir=None):
        real = mnist._files_in(data_dir, mnist.TEST_IMAGES,
                               mnist.TEST_LABELS)
        if real:
            return mnist.reader_creator(*real)
        return _synthetic_classification(n, (1, 28, 28), 10, seed)


class cifar:
    @staticmethod
    def reader_creator(filename, sub_name):
        """Parse the REAL python-pickle tar format (dataset/cifar.py
        reader_creator): members whose name contains `sub_name` hold
        dicts with b'data' (N, 3072 u8) and b'labels'/b'fine_labels';
        pixels scale to [0, 1] f32 like the reference."""

        def reader():
            with tarfile.open(filename, mode="r") as f:
                names = [m.name for m in f if sub_name in m.name]
                for name in sorted(names):
                    batch = pickle.load(f.extractfile(name),
                                        encoding="bytes")
                    data = batch[b"data"]
                    labels = batch.get(b"labels",
                                       batch.get(b"fine_labels"))
                    if labels is None:
                        raise IOError(f"no labels in {name}")
                    for row, label in zip(data, labels):
                        yield ((np.asarray(row, np.uint8) / 255.0)
                               .astype(np.float32), int(label))

        return reader

    @staticmethod
    def _tar(data_dir, fname):
        if data_dir is None:
            data_dir = _dataset_home("cifar")
        if data_dir is None:
            return None
        p = os.path.join(data_dir, fname)
        return p if os.path.exists(p) else None

    @staticmethod
    def train10(n=50000, seed=1, data_dir=None):
        p = cifar._tar(data_dir, "cifar-10-python.tar.gz")
        if p:
            return cifar.reader_creator(p, "data_batch")
        return _synthetic_classification(n, (3, 32, 32), 10, seed)

    @staticmethod
    def test10(n=10000, seed=8, data_dir=None):
        p = cifar._tar(data_dir, "cifar-10-python.tar.gz")
        if p:
            return cifar.reader_creator(p, "test_batch")
        return _synthetic_classification(n, (3, 32, 32), 10, seed)

    @staticmethod
    def train100(n=50000, seed=2, data_dir=None):
        p = cifar._tar(data_dir, "cifar-100-python.tar.gz")
        if p:
            return cifar.reader_creator(p, "train")
        return _synthetic_classification(n, (3, 32, 32), 100, seed)


class flowers:
    @staticmethod
    def train(n=6149, seed=3):
        return _synthetic_classification(n, (3, 224, 224), 102, seed)

    @staticmethod
    def test(n=1020, seed=9):
        return _synthetic_classification(n, (3, 224, 224), 102, seed)


class uci_housing:
    """13 features → scalar price (dataset/uci_housing.py)."""

    FEATURE_NUM = 14

    @staticmethod
    def load_data(filename, feature_num=14, ratio=0.8):
        """Parse the REAL whitespace table and normalize exactly like
        the reference (uci_housing.py:68): per-feature
        (x - avg) / (max - min) on the 13 inputs, 80/20 split."""
        data = np.fromfile(filename, sep=" ")
        data = data.reshape(data.shape[0] // feature_num, feature_num)
        maxs, mins = data.max(axis=0), data.min(axis=0)
        avgs = data.sum(axis=0) / data.shape[0]
        for i in range(feature_num - 1):
            data[:, i] = (data[:, i] - avgs[i]) / (maxs[i] - mins[i])
        offset = int(data.shape[0] * ratio)
        return data[:offset], data[offset:]

    @staticmethod
    def _real_reader(data_dir, part):
        if data_dir is None:
            data_dir = _dataset_home("uci_housing")
        if data_dir is None:
            return None
        p = os.path.join(data_dir, "housing.data")
        if not os.path.exists(p):
            return None
        tr, te = uci_housing.load_data(p)
        rows = tr if part == "train" else te

        def reader():
            for row in rows:
                yield (row[:-1].astype(np.float32),
                       np.asarray([row[-1]], np.float32))

        return reader

    @staticmethod
    def train(n=404, seed=4, data_dir=None):
        real = uci_housing._real_reader(data_dir, "train")
        if real:
            return real
        rng = np.random.RandomState(seed)
        w = rng.randn(13).astype(np.float32)

        def reader():
            r = np.random.RandomState(seed + 1)
            for _ in range(n):
                x = r.randn(13).astype(np.float32)
                y = float(x @ w + 0.1 * r.randn())
                yield x, np.asarray([y], np.float32)

        return reader

    @staticmethod
    def test(n=404, seed=4, data_dir=None):
        real = uci_housing._real_reader(data_dir, "test")
        if real:
            return real
        # forward the SAME data_dir: a typo'd explicit dir must not
        # re-resolve the env home and hand back real train data
        return uci_housing.train(n, seed, data_dir=data_dir)


class imdb:
    """Variable-length token sequences, binary sentiment
    (dataset/imdb.py)."""

    word_dict_size = 5147
    TAR = "aclImdb_v1.tar.gz"

    # -- real-format path (dataset/imdb.py tokenize/build_dict/
    # reader_creator over the aclImdb tar: pos label 0, neg label 1) --
    @staticmethod
    def tokenize(tar_path, pattern):
        import re
        import string

        rx = re.compile(pattern)
        with tarfile.open(tar_path) as tarf:
            for tf in tarf:
                if rx.match(tf.name):
                    text = tarf.extractfile(tf).read().rstrip(b"\n\r")
                    text = text.translate(
                        None, string.punctuation.encode("latin-1"))
                    yield text.lower().split()

    # the reference's corpus pattern/cutoff (dataset/imdb.py word_dict):
    # labeled train+test docs only (unsup/ and urls_*.txt excluded),
    # words kept above 150 occurrences
    DICT_PATTERN = r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$"

    @staticmethod
    def build_dict(tar_path, pattern=DICT_PATTERN, cutoff=150):
        freq: dict = {}
        for doc in imdb.tokenize(tar_path, pattern):
            for w in doc:
                freq[w] = freq.get(w, 0) + 1
        words = sorted((w for w, c in freq.items() if c > cutoff),
                       key=lambda w: (-freq[w], w))
        idx = {w: i for i, w in enumerate(words)}
        idx[b"<unk>"] = len(idx)
        return idx

    @staticmethod
    def reader_creator(tar_path, pos_pattern, neg_pattern, word_idx):
        unk = word_idx[b"<unk>"]

        def reader():
            for pattern, label in ((pos_pattern, 0), (neg_pattern, 1)):
                for doc in imdb.tokenize(tar_path, pattern):
                    yield [word_idx.get(w, unk) for w in doc], label

        return reader

    @staticmethod
    def _tar(data_dir):
        if data_dir is None:
            data_dir = _dataset_home("imdb")
        if data_dir is None:
            return None
        p = os.path.join(data_dir, imdb.TAR)
        return p if os.path.exists(p) else None

    @staticmethod
    def word_dict(data_dir=None):
        p = imdb._tar(data_dir)
        if p:
            return imdb.build_dict(p)
        return {i: i for i in range(imdb.word_dict_size)}

    @staticmethod
    def train(word_dict=None, n=25000, seed=5, max_len=200,
              data_dir=None):
        p = imdb._tar(data_dir)
        if p:
            if word_dict is None:
                word_dict = imdb.build_dict(p)
            return imdb.reader_creator(
                p, r"aclImdb/train/pos/.*\.txt$",
                r"aclImdb/train/neg/.*\.txt$", word_dict)
        vocab = imdb.word_dict_size

        def reader():
            r = np.random.RandomState(seed)
            for _ in range(n):
                length = int(r.randint(10, max_len))
                label = int(r.randint(2))
                # class-dependent token bias so models can actually learn
                lo = 0 if label == 0 else vocab // 2
                tokens = r.randint(lo, lo + vocab // 2,
                                   size=(length,)).astype(np.int64)
                yield tokens, label

        return reader

    @staticmethod
    def test(word_dict=None, n=25000, seed=11, max_len=200,
             data_dir=None):
        p = imdb._tar(data_dir)
        if p:
            if word_dict is None:
                word_dict = imdb.build_dict(p)
            return imdb.reader_creator(
                p, r"aclImdb/test/pos/.*\.txt$",
                r"aclImdb/test/neg/.*\.txt$", word_dict)
        # no real tar found for THIS data_dir: fall back to synthetic
        # without re-resolving the env home (a typo'd explicit dir must
        # not silently hand back real train data as the test set)
        return imdb.train(word_dict, n, seed, max_len,
                          data_dir=data_dir)


class imikolov:
    """N-gram LM windows (dataset/imikolov.py)."""

    @staticmethod
    def build_dict(min_word_freq=50):
        return {i: i for i in range(2073)}

    @staticmethod
    def train(word_dict=None, n=5, seed=6, samples=100000):
        vocab = len(word_dict) if word_dict else 2073

        def reader():
            r = np.random.RandomState(seed)
            for _ in range(samples):
                yield tuple(int(x) for x in r.randint(0, vocab, size=(n,)))

        return reader
