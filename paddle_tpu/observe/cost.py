"""Per-op cost attribution: analytic flop/byte accounting over the
*optimized* HLO module, joined to fluid ops and measured device time.

Why XLA's own aggregates are not enough (the r05 roofline lesson):

- `cost_analysis()["bytes accessed"]` OVERCOUNTS real HBM traffic —
  per-instruction estimates inside fusions are summed with utilization
  heuristics, which produced the impossible ROOFLINE_r05 result of an
  MFU "ceiling" (0.269) below an actually measured MFU (0.309).
- Pallas custom calls report ZERO flops, forcing bench.py's
  dense-twin workaround for every Pallas-active config.
- The aggregate has no attribution: r05's longctx device profile found
  ~15.9 s of copy/transpose against ~5.0 s of flash-kernel time only
  by manual trace reading.

This module recomputes both sides analytically from the optimized
HloModuleProto (read with trace.py's dependency-free wire scanner):

- FLOPS: contraction math for dot (exact vs XLA's count) and
  convolution (exact for VALID padding; a small overcount at padded
  edges), 1 flop/element for elementwise arithmetic, reduction sizes
  for reduce/reduce-window, recursive descent into fusions and called
  computations.  Transcendentals (exp/log/tanh/...) are tallied
  separately, matching XLA's flops-vs-transcendentals split.
  `while` bodies are multiplied by the loop's TRIP COUNT when it is
  recoverable from the scan-emitted counted-loop pattern
  (`while_trip_count`) — XLA's own cost analysis counts loop bodies
  ONCE, which undercounted scan-bound models (the r05 LSTM) by ~T and
  made their rooflines fiction.  An unrecoverable loop falls back to
  ×1 and is tagged with the loud `[loop?]` bucket instead of silently
  reading as a straight-line body.
- BYTES: the *materialized-buffers* model — after optimization each
  entry-computation instruction is one kernel that reads its operands
  from HBM once and writes its output once; fusion internals move no
  HBM bytes.  This is a minimum-traffic model: reuse inside a kernel
  is free, multiple uses of one buffer by one kernel count once.  A
  roofline built on it can only be MORE permissive than reality, so a
  ceiling can never fall below an honest measurement again.
- ATTRIBUTION: each instruction's `metadata.op_name` carries the
  executor's `<op_type>:<op_index>` named scopes (observe pillar 1),
  so every cost lands on a fluid op; each instruction is also binned
  into a BUCKET — matmul / conv / elementwise / layout (copy +
  transpose + bitcast-convert, the r05 longctx finding as a standard
  diagnostic) / comm / custom_call.
- PALLAS: custom calls whose scope names a registered kernel
  (`ops/pallas` KERNEL_COSTS, populated next to each kernel's
  DEFAULT_BLOCK_*) get that kernel's declared dense-equivalent
  (flops, bytes) injected at the instruction, so Pallas-active
  programs compute MFU numerators natively (tools/check_twin_flops.py
  asserts registry-vs-dense-twin parity).
"""

from __future__ import annotations

import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .trace import _fields, _first, _utf8, fluid_op_of

# --------------------------------------------------------------------------
# device peaks (shared by tools/roofline.py and op_cost_table)
# --------------------------------------------------------------------------

# bf16 MXU peak FLOP/s and HBM bandwidth by device kind prefix
DEVICE_PEAKS = {
    "TPU v4": (275e12, 1228e9),
    "TPU v5 lite": (197e12, 819e9),
    "TPU v5e": (197e12, 819e9),
    "TPU v5p": (459e12, 2765e9),
    "TPU v5": (459e12, 2765e9),
    "TPU v6 lite": (918e12, 1640e9),
    "TPU v6e": (918e12, 1640e9),
}


def device_peaks(kind: Optional[str] = None):
    """(peak_flops, hbm_bw) for a device kind, or (None, None) when the
    kind is unknown (CPU test backend) — callers must treat None as
    "no roofline denominator", never assume a default chip."""
    if kind is None:
        import jax

        kind = jax.devices()[0].device_kind
    for prefix, peaks in DEVICE_PEAKS.items():
        if kind.startswith(prefix):
            return peaks
    return None, None


# --------------------------------------------------------------------------
# HloModuleProto parsing (field numbers are stable in xla/service/hlo.proto)
# --------------------------------------------------------------------------

# HloModuleProto:      name=1 entry_computation_name=2 computations=3
#                      id=5 entry_computation_id=6
# HloComputationProto: name=1 instructions=2 id=5 root_id=6
# HloInstructionProto: name=1 opcode=2 shape=3 metadata=7 window=15
#                      convolution_dimension_numbers=16
#                      custom_call_target=28 dot_dimension_numbers=30
#                      id=35 operand_ids=36 called_computation_ids=38
#                      feature_group_count=50
# ShapeProto:          element_type=2 dimensions=3 tuple_shapes=4
# OpMetadata:          op_type=1 op_name=2
# DotDimensionNumbers: lhs_contracting=1 rhs_contracting=2 lhs_batch=3
#                      rhs_batch=4
# Window/WindowDimension: dimensions=1 / size=1 stride=2

_ELEM_BYTES = {1: 1, 2: 1, 3: 2, 4: 4, 5: 8, 6: 1, 7: 2, 8: 4, 9: 8,
               10: 2, 11: 4, 12: 8, 15: 8, 16: 2, 18: 16, 19: 1, 20: 1,
               21: 1, 22: 1, 23: 1, 24: 1, 25: 1}


def _varints(v) -> List[int]:
    """Decode a repeated int64 field: packed (bytes of varints) or a
    single already-decoded varint."""
    if isinstance(v, int):
        return [v]
    out, i, n = [], 0, len(v)
    while i < n:
        x = s = 0
        while True:
            b = v[i]
            i += 1
            x |= (b & 0x7F) << s
            if not b & 0x80:
                break
            s += 7
        out.append(x)
    return out


def _repeated_ints(buf: bytes, fno: int) -> List[int]:
    out: List[int] = []
    for f, _wt, v in _fields(buf):
        if f == fno:
            out.extend(_varints(v))
    return out


class Shape:
    __slots__ = ("element_type", "dims", "tuple_shapes")

    def __init__(self, buf: Optional[bytes]):
        self.element_type = 0
        self.dims: List[int] = []
        self.tuple_shapes: List["Shape"] = []
        if not buf:
            return
        for f, _wt, v in _fields(buf):
            if f == 2:
                self.element_type = v
            elif f == 3:
                self.dims.extend(_varints(v))
            elif f == 4:
                self.tuple_shapes.append(Shape(v))

    @property
    def elements(self) -> int:
        if self.tuple_shapes:
            return sum(s.elements for s in self.tuple_shapes)
        n = 1
        for d in self.dims:
            n *= d
        return n

    @property
    def bytes(self) -> int:
        if self.tuple_shapes:
            return sum(s.bytes for s in self.tuple_shapes)
        return self.elements * _ELEM_BYTES.get(self.element_type, 0)

    @property
    def elem_bytes(self) -> int:
        return _ELEM_BYTES.get(self.element_type, 0)


class Instr:
    __slots__ = ("name", "opcode", "shape", "op_name", "id",
                 "operand_ids", "called_ids", "dot_dnums_buf",
                 "window_buf", "conv_dnums_buf", "feature_group_count",
                 "custom_call_target", "literal_buf", "tuple_index",
                 "comparison_direction")

    def __init__(self, buf: bytes):
        self.name = ""
        self.opcode = ""
        self.shape = Shape(None)
        self.op_name = ""
        self.id = 0
        self.operand_ids: List[int] = []
        self.called_ids: List[int] = []
        self.dot_dnums_buf = b""
        self.window_buf = b""
        self.conv_dnums_buf = b""
        self.feature_group_count = 1
        self.custom_call_target = ""
        self.literal_buf = b""
        self.tuple_index = 0
        self.comparison_direction = ""
        for f, _wt, v in _fields(buf):
            if f == 1:
                self.name = _utf8(v)
            elif f == 2:
                self.opcode = _utf8(v)
            elif f == 3:
                self.shape = Shape(v)
            elif f == 7:
                self.op_name = _utf8(_first(v, 2, b""))
            elif f == 8:
                self.literal_buf = v
            elif f == 13:
                self.tuple_index = int(v)
            elif f == 15:
                self.window_buf = v
            elif f == 16:
                self.conv_dnums_buf = v
            elif f == 28:
                self.custom_call_target = _utf8(v)
            elif f == 30:
                self.dot_dnums_buf = v
            elif f == 35:
                self.id = v
            elif f == 36:
                self.operand_ids.extend(_varints(v))
            elif f == 38:
                self.called_ids.extend(_varints(v))
            elif f == 50:
                self.feature_group_count = max(int(v), 1)
            elif f == 63:
                self.comparison_direction = _utf8(v)


class Computation:
    __slots__ = ("name", "id", "root_id", "instructions", "by_id")

    def __init__(self, buf: bytes):
        self.name = ""
        self.id = 0
        self.root_id = 0
        self.instructions: List[Instr] = []
        for f, _wt, v in _fields(buf):
            if f == 1:
                self.name = _utf8(v)
            elif f == 2:
                self.instructions.append(Instr(v))
            elif f == 5:
                self.id = v
            elif f == 6:
                self.root_id = v
        self.by_id = {i.id: i for i in self.instructions}

    @property
    def root(self) -> Optional[Instr]:
        return self.by_id.get(self.root_id) or (
            self.instructions[-1] if self.instructions else None)


class HloModule:
    def __init__(self, proto: bytes):
        # accept either a bare HloModuleProto or an HloProto wrapper
        # (hlo_module=1) — traces embed the wrapper, runtime
        # executables hand out the bare module
        if _first(proto, 2) is None and _first(proto, 1) is not None:
            inner = _first(proto, 1)
            if isinstance(inner, bytes) and _first(inner, 3) is not None:
                proto = inner
        self.entry_id = _first(proto, 6, 0)
        self.computations: Dict[int, Computation] = {}
        for f, _wt, v in _fields(proto):
            if f == 3:
                comp = Computation(v)
                self.computations[comp.id] = comp

    @property
    def entry(self) -> Computation:
        if self.entry_id in self.computations:
            return self.computations[self.entry_id]
        # fall back: the computation with the largest id is the entry
        # in XLA's numbering
        return self.computations[max(self.computations)]


# --------------------------------------------------------------------------
# while-loop trip counts (the scan undercount fix)
# --------------------------------------------------------------------------

def _literal_int(buf: bytes) -> Optional[int]:
    """First integer of a LiteralProto (s32s=4 s64s=5 u32s=6 u64s=7
    packed varints; u8s=3/s8s=15 raw bytes)."""
    if not buf:
        return None
    for f, _wt, v in _fields(buf):
        if f in (4, 5, 6, 7):
            vals = _varints(v)
            if vals:
                return vals[0]
        if f in (3, 15) and isinstance(v, bytes) and v:
            return v[0]
    return None


def _resolve_through(comp: Computation, o: Optional[Instr]):
    """Follow value-preserving wrappers (convert/copy/bitcast) to the
    producing instruction."""
    while (o is not None and o.opcode in ("convert", "copy", "bitcast")
           and o.operand_ids):
        o = comp.by_id.get(o.operand_ids[0])
    return o


def while_trip_count(module: HloModule, comp: Computation,
                     instr: Instr) -> Optional[int]:
    """Known trip count of a counted `while` (the lax.scan / fori_loop
    induction pattern), or None when unrecoverable.

    The scan-emitted pattern: the condition computation's root is
    `compare(get-tuple-element(param, i), constant_T, LT)` and the body
    increments tuple element i by a constant step from a constant init.
    The bound comes from the condition; init/step are refined from the
    while's operand tuple and the body root when visible and default to
    the counted-loop convention (0, 1) otherwise.  A loop whose
    CONDITION does not match (a genuine data-dependent `while` op
    decode loop) returns None — callers fall back to ×1 with the loud
    `[loop?]` bucket, never a silent guess.
    """
    if instr.opcode != "while":
        return None
    called = [module.computations.get(c) for c in instr.called_ids]
    called = [c for c in called if c is not None]
    cond = next((c for c in called if c.root is not None
                 and c.root.opcode == "compare"), None)
    body = next((c for c in called if c is not cond), None)
    if cond is None or body is None:
        return None
    root = cond.root
    ops = [_resolve_through(cond, cond.by_id.get(i))
           for i in root.operand_ids]
    if len(ops) != 2 or any(o is None for o in ops):
        return None

    def gte_index(o):
        if o.opcode != "get-tuple-element" or not o.operand_ids:
            return None
        src = cond.by_id.get(o.operand_ids[0])
        if src is None or src.opcode != "parameter":
            return None
        return o.tuple_index

    direction = root.comparison_direction or "LT"
    a, b = ops
    if gte_index(a) is not None and b.opcode == "constant":
        idx, bound, dir_ok = (gte_index(a), _literal_int(b.literal_buf),
                              direction == "LT")
    elif gte_index(b) is not None and a.opcode == "constant":
        idx, bound, dir_ok = (gte_index(b), _literal_int(a.literal_buf),
                              direction == "GT")
    else:
        return None
    if bound is None or not dir_ok:
        return None

    # refine init from the while operand's tuple element, step from the
    # body root's add-by-constant; both default to the (0, 1) counted-
    # loop convention when optimization hid them
    init, step = 0, 1
    if instr.operand_ids:
        arg = comp.by_id.get(instr.operand_ids[0])
        if arg is not None and arg.opcode == "tuple" \
                and idx < len(arg.operand_ids):
            o = _resolve_through(comp, comp.by_id.get(arg.operand_ids[idx]))
            if o is not None and o.opcode == "constant":
                v = _literal_int(o.literal_buf)
                if v is not None:
                    init = v
    broot = body.root
    if broot is not None and broot.opcode == "tuple" \
            and idx < len(broot.operand_ids):
        o = _resolve_through(body, body.by_id.get(broot.operand_ids[idx]))
        if o is not None and o.opcode == "add":
            for oid in o.operand_ids:
                c = _resolve_through(body, body.by_id.get(oid))
                if c is not None and c.opcode == "constant":
                    v = _literal_int(c.literal_buf)
                    if v:
                        step = v
    if step <= 0:
        return None
    return max(0, -(-(bound - init) // step))


# --------------------------------------------------------------------------
# analytic flop model (mirrors xla HloCostAnalysis conventions)
# --------------------------------------------------------------------------

_TRANSCENDENTAL = {
    "exponential", "exponential-minus-one", "log", "log-plus-one",
    "logistic", "tanh", "sine", "cosine", "tan", "sqrt", "rsqrt",
    "cbrt", "atan2", "power", "erf",
}

# elementwise arithmetic XLA counts at 1 flop/element
_ELEMENTWISE = {
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "compare", "select", "clamp", "and", "or", "xor", "not", "negate",
    "abs", "sign", "floor", "ceil", "round-nearest-afz",
    "round-nearest-even", "remainder", "shift-left",
    "shift-right-arithmetic", "shift-right-logical", "is-finite",
    "count-leading-zeros", "popcnt", "convert", "real", "imag",
    "complex", "stochastic-convert",
}

# pure data movement / bookkeeping: zero flops AND (except where they
# appear at the entry level) no modeled HBM traffic of their own
_NO_BYTES = {"parameter", "constant", "tuple", "get-tuple-element",
             "bitcast", "after-all", "partition-id", "replica-id",
             "domain", "opt-barrier", "optimization-barrier"}

_COMM = {"all-reduce", "all-gather", "all-to-all", "collective-permute",
         "collective-broadcast", "reduce-scatter", "send", "recv",
         "send-done", "recv-done", "all-reduce-start", "all-reduce-done",
         "all-gather-start", "all-gather-done",
         "collective-permute-start", "collective-permute-done"}

_LAYOUT = {"copy", "transpose", "bitcast-convert", "copy-start",
           "copy-done", "reshape"}


def _dot_flops(instr: Instr, operands: List[Instr]) -> float:
    # fma(2) * output elements * contracted width — identical to
    # HloCostAnalysis::HandleDot
    k = 1
    if instr.dot_dnums_buf and operands:
        lhs_contract = _repeated_ints(instr.dot_dnums_buf, 1)
        lhs_dims = operands[0].shape.dims
        for dim in lhs_contract:
            if dim < len(lhs_dims):
                k *= lhs_dims[dim]
    return 2.0 * instr.shape.elements * k


def _conv_flops(instr: Instr, operands: List[Instr]) -> float:
    # fma(2) * output elements * kernel spatial size * input features
    # per group.  Exact for VALID padding; overcounts clipped window
    # positions at padded edges (small for large feature maps).
    if len(operands) < 2:
        return 0.0
    kernel = operands[1].shape.dims
    spatial = _repeated_ints(instr.conv_dnums_buf, 6)
    kin = _repeated_ints(instr.conv_dnums_buf, 3)
    window = 1
    for dim in spatial:
        if dim < len(kernel):
            window *= kernel[dim]
    cin = kernel[kin[0]] if kin and kin[0] < len(kernel) else 1
    return 2.0 * instr.shape.elements * window * cin


def _reduce_ops(module: HloModule, instr: Instr) -> int:
    """Flop-bearing instruction count of a reduce/scatter computation
    (1 for add/max — the common case)."""
    n = 0
    for cid in instr.called_ids:
        comp = module.computations.get(cid)
        if not comp:
            continue
        n += sum(1 for i in comp.instructions
                 if i.opcode in _ELEMENTWISE or i.opcode in _TRANSCENDENTAL)
    return max(n, 1)


def _computation_flops(module: HloModule, comp: Computation,
                       seen: Optional[set] = None) -> Tuple[float, float]:
    """(flops, transcendentals) of every instruction in `comp`,
    descending into fusions/calls (cycle-safe)."""
    seen = set() if seen is None else seen
    if comp.id in seen:
        return 0.0, 0.0
    seen.add(comp.id)
    flops = transc = 0.0
    for instr in comp.instructions:
        f, t = _instr_flops(module, comp, instr, seen)
        flops += f
        transc += t
    return flops, transc


def _instr_flops(module: HloModule, comp: Computation, instr: Instr,
                 seen: Optional[set] = None) -> Tuple[float, float]:
    op = instr.opcode
    elems = instr.shape.elements
    operands = [comp.by_id[i] for i in instr.operand_ids
                if i in comp.by_id]
    if op == "dot":
        return _dot_flops(instr, operands), 0.0
    if op == "convolution":
        return _conv_flops(instr, operands), 0.0
    if op in _TRANSCENDENTAL:
        return 0.0, float(elems)
    if op in _ELEMENTWISE:
        return float(elems), 0.0
    if op == "reduce":
        in_elems = operands[0].shape.elements if operands else 0
        return (max(in_elems - elems, 0) * _reduce_ops(module, instr),
                0.0)
    if op in ("reduce-window", "select-and-scatter"):
        window = 1
        for wd, _wt, v in _fields(instr.window_buf):
            if wd == 1:
                window *= _first(v, 1, 1)
        return float(elems) * window * _reduce_ops(module, instr), 0.0
    if op == "scatter":
        upd = operands[-1].shape.elements if operands else 0
        return float(upd) * _reduce_ops(module, instr), 0.0
    if op in ("fusion", "call", "while", "conditional", "async-start"):
        flops = transc = 0.0
        for cid in instr.called_ids:
            sub = module.computations.get(cid)
            if sub is not None:
                f, t = _computation_flops(module, sub, seen)
                flops += f
                transc += t
        if op == "while":
            # a while body runs trip-count times, not once (the r05
            # scan undercount); unrecoverable loops stay at ×1 and are
            # surfaced via the [loop?] bucket in instruction_costs
            trip = while_trip_count(module, comp, instr)
            if trip is not None:
                flops *= trip
                transc *= trip
        return flops, transc
    # custom-call: zero here; the Pallas registry injects at a higher
    # level so callers can see xla-vs-registry flops separately
    return 0.0, 0.0


# --------------------------------------------------------------------------
# Pallas kernel cost registry injection
# --------------------------------------------------------------------------

_PALLAS_SCOPE_RE = re.compile(r"pallas_([A-Za-z0-9_]+)")


def _pallas_kernel_of(op_name: str) -> Optional[str]:
    """Registered kernel name from an instruction's op_name scope, or
    None when the custom call is not a scoped Pallas kernel."""
    m = _PALLAS_SCOPE_RE.search(op_name or "")
    return m.group(1) if m else None


def _registry_cost(kernel: str, instr: Instr, operands: List[Instr]):
    """(flops, bytes|None) declared by the kernel module, or None when
    the kernel has no registered cost."""
    from ..ops import pallas as pallas_pkg

    fn = pallas_pkg.KERNEL_COSTS.get(kernel)
    if fn is None:
        return None
    op_shapes = [(tuple(o.shape.dims), o.shape.elem_bytes)
                 for o in operands]
    res = instr.shape
    res_shapes = ([(tuple(s.dims), s.elem_bytes)
                   for s in res.tuple_shapes]
                  if res.tuple_shapes else [(tuple(res.dims),
                                             res.elem_bytes)])
    return fn(op_shapes, res_shapes)


# --------------------------------------------------------------------------
# per-instruction cost rows + bucketing
# --------------------------------------------------------------------------

def _bucket(module: HloModule, comp: Computation, instr: Instr) -> str:
    op = instr.opcode
    if op == "custom-call":
        return "custom_call"
    if op == "while":
        # "loop" when the trip count is recovered (flops already carry
        # the multiplication); the LOUD "[loop?]" tag marks a body
        # counted ONCE because the induction pattern was unrecoverable
        # — a roofline reader must never mistake that for real coverage
        trip = while_trip_count(module, comp, instr)
        return "loop" if trip is not None else "[loop?]"
    if op == "dot":
        return "matmul"
    if op == "convolution":
        return "conv"
    if op in _COMM:
        return "comm"
    if op == "fusion":
        ops_inside = set()
        root_op = None
        for cid in instr.called_ids:
            sub = module.computations.get(cid)
            if sub is None:
                continue
            ops_inside.update(i.opcode for i in sub.instructions)
            if root_op is None and sub.root is not None:
                root_op = sub.root.opcode
        if "dot" in ops_inside:
            return "matmul"
        if "convolution" in ops_inside:
            return "conv"
        if root_op in _LAYOUT:
            return "layout"
        return "elementwise"
    if op in _LAYOUT:
        return "layout"
    if op in _NO_BYTES:
        return "noop"
    return "elementwise"


def instruction_costs(proto: bytes) -> List[Dict[str, Any]]:
    """Analytic per-instruction cost rows for the module's entry
    computation (one row per post-fusion kernel).

    Row keys: name, opcode, op_type (fluid attribution or None),
    bucket, flops, transcendentals, bytes, pallas_kernel (set when a
    registered Pallas kernel's cost was injected at a custom call),
    trip_count (while rows: the recovered loop trip count, already
    multiplied into flops; None = unrecoverable, body counted once and
    bucketed "[loop?]").
    `flops` already includes the injected registry flops; `xla_flops`
    carries the pre-injection analytic count.
    """
    # force kernel-cost registration before walking custom calls
    from ..ops.pallas import flash_attention as _fa  # noqa: F401
    from ..ops.pallas import recurrence as _rc  # noqa: F401
    from ..ops.pallas import vocab_ce as _vc  # noqa: F401

    module = HloModule(proto)
    entry = module.entry
    rows: List[Dict[str, Any]] = []
    for instr in entry.instructions:
        operands = [entry.by_id[i] for i in instr.operand_ids
                    if i in entry.by_id]
        flops, transc = _instr_flops(module, entry, instr)
        bucket = _bucket(module, entry, instr)
        if instr.opcode in _NO_BYTES:
            nbytes = 0
        else:
            # materialized-buffers model: unique operands read once,
            # output written once; operands that are themselves
            # bookkeeping (tuple/gte wrapping a buffer) still stand in
            # for one read of their underlying buffer size
            seen_ids = set()
            nbytes = instr.shape.bytes
            for o in operands:
                if o.id in seen_ids:
                    continue
                seen_ids.add(o.id)
                nbytes += o.shape.bytes
        row = {
            "name": instr.name,
            "opcode": instr.opcode,
            "op_type": fluid_op_of(instr.op_name),
            "bucket": bucket,
            "flops": flops,
            "xla_flops": flops,
            "transcendentals": transc,
            "bytes": float(nbytes),
            "pallas_kernel": None,
        }
        if instr.opcode == "while":
            row["trip_count"] = while_trip_count(module, entry, instr)
        if instr.opcode == "custom-call":
            kernel = _pallas_kernel_of(instr.op_name)
            if kernel is not None:
                cost = _registry_cost(kernel, instr, operands)
                if cost is not None:
                    kflops, kbytes = cost
                    row["pallas_kernel"] = kernel
                    row["flops"] = float(kflops)
                    if kbytes is not None:
                        row["bytes"] = float(kbytes)
        rows.append(row)
    return rows


def total_costs(proto: bytes) -> Dict[str, Any]:
    """Whole-program totals over `instruction_costs`.

    flops = analytic flops INCLUDING injected Pallas registry costs;
    `pallas_flops` is the injected share, `custom_calls` /
    `pallas_matched` make an unmatched (uncounted) custom call visible
    instead of silently reading as zero flops."""
    rows = instruction_costs(proto)
    custom = [r for r in rows if r["opcode"] == "custom-call"]
    matched = [r for r in custom if r["pallas_kernel"]]
    return {
        "flops": sum(r["flops"] for r in rows),
        "transcendentals": sum(r["transcendentals"] for r in rows),
        "bytes": sum(r["bytes"] for r in rows),
        "pallas_flops": sum(r["flops"] for r in matched),
        "custom_calls": len(custom),
        "pallas_matched": len(matched),
        "bucket_bytes": _sum_by(rows, "bytes"),
        "bucket_flops": _sum_by(rows, "flops"),
    }


def _sum_by(rows: Iterable[Dict[str, Any]], key: str) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for r in rows:
        out[r["bucket"]] = out.get(r["bucket"], 0.0) + r[key]
    return out


# --------------------------------------------------------------------------
# compiled-program access + the per-op table
# --------------------------------------------------------------------------

def compiled_hlo_proto(compiled) -> bytes:
    """Serialized optimized HloModuleProto of a jax Compiled object."""
    try:
        modules = compiled.runtime_executable().hlo_modules()
    except AttributeError:  # jax version drift: go through _executable
        modules = compiled._executable.xla_executable.hlo_modules()
    return modules[0].as_serialized_hlo_module_proto()


def compiled_xla_flops(compiled) -> float:
    analyses = compiled.cost_analysis()
    if isinstance(analyses, (list, tuple)):
        analyses = analyses[0]
    return float(analyses.get("flops", 0.0))


def program_costs(program, feed=None, fetch_list=None, scope=None,
                  exe=None) -> Dict[str, Any]:
    """Compile a fluid program's one-iteration step (AOT, shared with
    Executor.cost_analysis) and return `total_costs` of the optimized
    module plus XLA's own aggregate flops for cross-checking and the
    step's peak device memory (`peak_hbm_bytes`, the buffer-assignment
    allocation total from the same compile; None when the backend
    exposes no memory analysis)."""
    from ..core.executor import Executor

    exe = exe or Executor()
    compiled = exe.compiled_step(program, feed=feed,
                                 fetch_list=fetch_list, scope=scope)
    proto = compiled_hlo_proto(compiled)
    out = total_costs(proto)
    out["xla_aggregate_flops"] = compiled_xla_flops(compiled)
    from .memory import compiled_peak_bytes

    out["peak_hbm_bytes"] = compiled_peak_bytes(compiled)
    return out


def op_cost_table(program=None, feed=None, fetch_list=None, scope=None,
                  exe=None, profile_dir: Optional[str] = None,
                  peak_flops: Optional[float] = None,
                  hbm_bw: Optional[float] = None,
                  proto: Optional[bytes] = None) -> List[Dict[str, Any]]:
    """Per-framework-op cost rows for a program's optimized step.

    Each row aggregates the entry instructions attributed to one
    (fluid op type, bucket) pair:

        {op_type, bucket, instructions, flops, transcendentals, bytes,
         time_ms, arith_intensity, achieved_flops_frac,
         roofline_time_ms}

    - `time_ms` joins measured per-instruction device time from a
      jax.profiler trace under `profile_dir` (None when no trace is
      given or no event matched — cost attribution works chip-free).
    - `achieved_flops_frac` = (flops / time) / peak_flops when both a
      time and a peak are known, else None.
    - `roofline_time_ms` = max(flops/peak, bytes/bw): the row's own
      roofline lower bound (None off-chip).

    Pass `proto` to analyze an already-serialized optimized module
    instead of compiling `program`.
    """
    if proto is None:
        if program is None:
            raise ValueError("op_cost_table needs a program or a proto")
        from ..core.executor import Executor

        exe = exe or Executor()
        compiled = exe.compiled_step(program, feed=feed,
                                     fetch_list=fetch_list, scope=scope)
        proto = compiled_hlo_proto(compiled)
    rows = instruction_costs(proto)

    times: Dict[str, float] = {}
    if profile_dir is not None:
        from .trace import instr_time_table

        times = {name: t["total_ms"]
                 for name, t in instr_time_table(profile_dir).items()}

    if peak_flops is None and hbm_bw is None:
        peak_flops, hbm_bw = device_peaks()

    grouped: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for r in rows:
        if r["bucket"] == "noop":
            continue
        key = (r["op_type"] or "[unattributed]", r["bucket"])
        g = grouped.setdefault(key, {
            "op_type": key[0], "bucket": key[1], "instructions": 0,
            "flops": 0.0, "transcendentals": 0.0, "bytes": 0.0,
            "time_ms": None,
        })
        g["instructions"] += 1
        g["flops"] += r["flops"]
        g["transcendentals"] += r["transcendentals"]
        g["bytes"] += r["bytes"]
        t = times.get(r["name"])
        if t is not None:
            g["time_ms"] = (g["time_ms"] or 0.0) + t

    out = []
    for g in grouped.values():
        g["arith_intensity"] = (round(g["flops"] / g["bytes"], 3)
                                if g["bytes"] else None)
        g["achieved_flops_frac"] = None
        g["roofline_time_ms"] = None
        if peak_flops:
            if g["time_ms"]:
                g["achieved_flops_frac"] = round(
                    (g["flops"] / (g["time_ms"] / 1e3)) / peak_flops, 4)
            if hbm_bw:
                g["roofline_time_ms"] = round(
                    max(g["flops"] / peak_flops,
                        g["bytes"] / hbm_bw) * 1e3, 4)
        out.append(g)
    out.sort(key=lambda g: (-(g["time_ms"] or 0.0), -g["flops"],
                            -g["bytes"]))
    return out


def layout_byte_share(proto: bytes) -> float:
    """Fraction of the step's modeled HBM traffic spent in the LAYOUT
    bucket (copy/transpose/bitcast-convert + layout-rooted fusions) —
    the r05 longctx diagnostic as one number.  bench.py records it as
    `layout_share` on every transformer/longctx entry and
    tools/perf_gate.py gates its regression (--tol-layout-share), so
    transpose traffic can never silently creep back."""
    rows = instruction_costs(proto)
    total = sum(r["bytes"] for r in rows if r["bucket"] != "noop")
    if not total:
        return 0.0
    layout = sum(r["bytes"] for r in rows if r["bucket"] == "layout")
    return layout / total


# copy/transpose opcodes — the subset of the layout bucket that is pure
# relayout traffic (reshape/bitcast-convert can be free bitcasts; these
# never are)
_COPYISH = {"copy", "transpose", "copy-start", "copy-done"}


def _is_copyish(module: HloModule, instr: Instr) -> bool:
    if instr.opcode in _COPYISH:
        return True
    if instr.opcode == "fusion":
        for cid in instr.called_ids:
            sub = module.computations.get(cid)
            if sub is not None and sub.root is not None \
                    and sub.root.opcode in _COPYISH:
                return True
    return False


def flash_boundary_layout(proto: bytes,
                          kernel_prefix: str = "flash") -> List[Dict[str, str]]:
    """Copy/transpose instructions ADJACENT (operand or user) to Pallas
    flash custom calls in the entry computation — the ISSUE 8 "zero
    transpose traffic at the kernel boundary" proof, asserted empty by
    tests/test_head_major.py and the run_ci.sh layout smoke.  On a
    backend where Pallas runs in interpret mode (CPU) there are no
    custom calls and the list is trivially empty — pair this with
    `copyish_instructions` / the program-level zero-`transpose`-ops
    check for a chip-free proof."""
    module = HloModule(proto)
    entry = module.entry
    users: Dict[int, List[Instr]] = {}
    for instr in entry.instructions:
        for oid in instr.operand_ids:
            users.setdefault(oid, []).append(instr)
    offenders = []
    for instr in entry.instructions:
        if instr.opcode != "custom-call":
            continue
        kern = _pallas_kernel_of(instr.op_name)
        if not kern or not kern.startswith(kernel_prefix):
            continue
        neighbors = [entry.by_id[i] for i in instr.operand_ids
                     if i in entry.by_id]
        neighbors += users.get(instr.id, [])
        for nb in neighbors:
            if _is_copyish(module, nb):
                offenders.append({"custom_call": instr.name,
                                  "kernel": kern,
                                  "neighbor": nb.name,
                                  "opcode": nb.opcode})
    return offenders


def copyish_instructions(proto: bytes,
                         op_types: Optional[set] = None) -> List[Dict[str, Any]]:
    """Entry-computation copy/transpose instructions (incl. fusions
    rooted at one), optionally restricted to rows attributed to the
    given fluid op types.  The chip-free half of the boundary proof:
    with Pallas in interpret mode the flash custom calls don't exist,
    but a head-major program still must not contain transpose kernels
    attributed to its attention ops."""
    module = HloModule(proto)
    entry = module.entry
    out = []
    for instr in entry.instructions:
        if not _is_copyish(module, instr):
            continue
        op_type = fluid_op_of(instr.op_name)
        if op_types is not None and op_type not in op_types:
            continue
        out.append({"name": instr.name, "opcode": instr.opcode,
                    "op_type": op_type,
                    "bytes": float(instr.shape.bytes)})
    return out


def bucket_summary(rows: List[Dict[str, Any]]) -> Dict[str, Dict[str, float]]:
    """Collapse op_cost_table rows to per-bucket totals — the
    layout/copy/transpose share IS the r05 longctx diagnostic."""
    out: Dict[str, Dict[str, float]] = {}
    for r in rows:
        b = out.setdefault(r["bucket"], {"flops": 0.0, "bytes": 0.0,
                                         "time_ms": 0.0,
                                         "instructions": 0})
        b["flops"] += r["flops"]
        b["bytes"] += r["bytes"]
        b["time_ms"] += r["time_ms"] or 0.0
        b["instructions"] += r["instructions"]
    return out


def format_cost_table(rows: List[Dict[str, Any]],
                      top: int = 30) -> str:
    """Human-readable per-op cost report (the r05 manual device-profile
    reading, automated)."""
    hdr = (f"{'Op':<24}{'Bucket':<12}{'Instrs':>7}{'GFLOP':>10}"
           f"{'MB':>10}{'Time(ms)':>10}{'AI':>8}{'Ach.MFU':>9}")
    lines = ["-------> Per-op cost attribution <-------", hdr,
             "-" * len(hdr)]
    for r in rows[:top]:
        lines.append(
            f"{r['op_type']:<24}{r['bucket']:<12}{r['instructions']:>7}"
            f"{r['flops'] / 1e9:>10.3f}{r['bytes'] / 1e6:>10.2f}"
            f"{(r['time_ms'] if r['time_ms'] is not None else -1):>10.3f}"
            f"{(r['arith_intensity'] or 0):>8.1f}"
            f"{(r['achieved_flops_frac'] if r['achieved_flops_frac'] is not None else -1):>9.4f}")
    if len(rows) > top:
        lines.append(f"... ({len(rows) - top} more rows)")
    return "\n".join(lines)
