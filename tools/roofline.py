"""Roofline analysis for the headline models (VERDICT r3 item 1c).

Builds the SAME amp/bf16 train step bench.py times, compiles it, and
reads XLA's own cost analysis of the optimized program (flops, bytes
accessed — Executor.cost_analysis).  The roofline lower bound on step
time is

    t_lb = max(flops / peak_flops, bytes / hbm_bw)

and the implied MFU ceiling is t_compute / t_lb — what fraction of peak
the chip could reach with perfect compute/HBM overlap.  Measured MFU vs
this ceiling separates "overhead we can still close" from "the program
is HBM-bound at this shape and N% is the roof".

Run on the real chip: `python tools/roofline.py [--model all|resnet50|
transformer] [--out ROOFLINE_r04.json]`.  Flash attention is analyzed
through its dense twin (Pallas custom calls are invisible to the cost
model — same convention as bench.py); pass --flash to analyze the
actual flash program's residual byte traffic instead.  On CPU
(BENCH_PLATFORM=cpu) fusion decisions differ — the JSON records the
producing backend so approximate numbers are never mistaken for chip
numbers.

v5e: 197 bf16 TFLOP/s (MXU), 819 GB/s HBM.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_HBM_BW = {
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,
}
_DEFAULT_BW = 819e9


def _roofline(cost, peak, bw):
    flops = float(cost.get("flops", 0.0))
    bytes_accessed = float(cost.get("bytes accessed", 0.0))
    t_compute = flops / peak
    t_memory = bytes_accessed / bw
    t_lb = max(t_compute, t_memory)
    return {
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "arith_intensity_flops_per_byte":
            round(flops / bytes_accessed, 2) if bytes_accessed else None,
        "t_compute_ms": round(t_compute * 1e3, 3),
        "t_memory_ms": round(t_memory * 1e3, 3),
        "bound": "compute" if t_compute >= t_memory else "memory",
        "mfu_ceiling": round(t_compute / t_lb, 4) if t_lb else None,
        "roofline_step_time_ms": round(t_lb * 1e3, 3),
    }


def _resnet_cost(batch_size, data_format, use_amp=True):
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.models import resnet

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        model = resnet.build_model(dataset="flowers", depth=50,
                                   class_dim=1000, learning_rate=0.1,
                                   use_amp=use_amp,
                                   data_format=data_format)
        exe = fluid.Executor()
        exe.run(startup)
        feed = {"data": rng.rand(batch_size, 3, 224, 224)
                .astype(np.float32),
                "label": rng.randint(0, 1000, (batch_size, 1))
                .astype(np.int32)}
        return exe.cost_analysis(main, feed=feed,
                                 fetch_list=[model["loss"]])


def _transformer_cost(batch_size, max_length, use_flash, use_amp=True,
                      use_fused_ce=False, fused_qkv=False):
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.models import transformer

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        model = transformer.build_model(
            src_vocab_size=32000, trg_vocab_size=32000,
            max_length=max_length, n_layer=6, n_head=8, d_model=512,
            d_inner_hid=2048, dropout=0.1, use_amp=use_amp,
            use_flash=use_flash, use_fused_ce=use_fused_ce,
            fused_qkv=fused_qkv)
        exe = fluid.Executor()
        exe.run(startup)
        batch = transformer.make_fake_batch(batch_size, max_length,
                                            32000, 32000)
        feed = {k: np.asarray(v) for k, v in batch.items()}
        return exe.cost_analysis(main, feed=feed,
                                 fetch_list=[model["loss"]])


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="all",
                   choices=["all", "resnet50", "transformer"])
    p.add_argument("--batch", type=int, default=0)
    p.add_argument("--layout", default="NCHW", choices=["NCHW", "NHWC"])
    p.add_argument("--flash", action="store_true",
                   help="analyze the flash program itself (bytes are "
                        "real; flops exclude the Pallas kernel)")
    p.add_argument("--out", default="ROOFLINE_r04.json")
    args = p.parse_args()

    if os.environ.get("BENCH_PLATFORM"):
        import jax

        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import _peak_flops

    peak, kind = _peak_flops()
    bw = next((v for k, v in _HBM_BW.items() if kind.startswith(k)),
              _DEFAULT_BW)

    results = {"device": kind, "peak_flops": peak, "hbm_bw": bw}
    if args.model in ("all", "resnet50"):
        cost = _resnet_cost(args.batch or 128, args.layout)
        results[f"resnet50_{args.layout.lower()}_bs"
                f"{args.batch or 128}"] = _roofline(cost, peak, bw)
    if args.model in ("all", "transformer"):
        cost = _transformer_cost(args.batch or 64, 256, args.flash)
        results[f"transformer_bs{args.batch or 64}_len256"
                + ("_flash" if args.flash else "_dense")] = _roofline(
                    cost, peak, bw)
    with open(args.out, "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
