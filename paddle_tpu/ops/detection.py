"""Detection operators (starter set).

TPU-native implementations of the reference detection suite's core ops
(reference: paddle/fluid/operators/detection/ — prior_box_op.cc,
box_coder_op.cc, iou_similarity_op.cc, multiclass_nms_op.cc,
yolov3_loss_op.cc; 35 files total).

Static-shape design notes:
- multiclass_nms emits a FIXED (N, keep_top_k, 6) tensor padded with -1
  labels plus a per-image valid count, instead of the reference's
  variable-length LoD output — XLA needs static shapes, and the padded
  form is what serving consumers index anyway.
- NMS suppression is an O(K²) masked matrix loop over the per-class
  top-k (lax.fori_loop), the standard accelerator formulation replacing
  the reference's sorted linked-list walk.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .common import first, opt_in, out


# ---------------------------------------------------------------------------
# prior_box
# ---------------------------------------------------------------------------

@register_op("prior_box")
def prior_box(ctx, ins, attrs):
    """SSD prior (anchor) boxes for one feature map (reference
    prior_box_op.cc).

    inputs: Input (N, C, H, W) feature map, Image (N, C, Him, Wim).
    outputs: Boxes (H, W, P, 4) normalized [xmin,ymin,xmax,ymax],
             Variances (H, W, P, 4).
    """
    feat = first(ins, "Input")
    image = first(ins, "Image")
    h, w = feat.shape[2], feat.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    ars = [1.0]
    for ar in attrs.get("aspect_ratios", []):
        ar = float(ar)
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(ar)
        if attrs.get("flip", True) and not any(
                abs(1.0 / ar - e) < 1e-6 for e in ars):
            ars.append(1.0 / ar)
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    step_w = float(attrs.get("step_w", 0.0)) or img_w / w
    step_h = float(attrs.get("step_h", 0.0)) or img_h / h
    offset = float(attrs.get("offset", 0.5))

    # box sizes per prior (reference order: per min_size → aspect ratios
    # then the max_size sqrt box)
    widths, heights = [], []
    for k, ms in enumerate(min_sizes):
        for ar in ars:
            widths.append(ms * ar ** 0.5)
            heights.append(ms / ar ** 0.5)
        if max_sizes:
            bs = (ms * max_sizes[k]) ** 0.5
            widths.append(bs)
            heights.append(bs)
    bw = jnp.asarray(widths) / 2.0
    bh = jnp.asarray(heights) / 2.0
    p = len(widths)

    cx = (jnp.arange(w) + offset) * step_w       # (W,)
    cy = (jnp.arange(h) + offset) * step_h       # (H,)
    cxg = jnp.broadcast_to(cx[None, :, None], (h, w, p))
    cyg = jnp.broadcast_to(cy[:, None, None], (h, w, p))
    boxes = jnp.stack(
        [(cxg - bw) / img_w, (cyg - bh) / img_h,
         (cxg + bw) / img_w, (cyg + bh) / img_h], axis=-1)
    if attrs.get("clip", True):
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), (h, w, p, 4))
    return out(Boxes=boxes.astype(feat.dtype),
               Variances=var.astype(feat.dtype))


# ---------------------------------------------------------------------------
# box_coder
# ---------------------------------------------------------------------------

@register_op("box_coder")
def box_coder(ctx, ins, attrs):
    """Encode/decode boxes against priors in center-size form
    (reference box_coder_op.cc).

    PriorBox (M, 4), PriorBoxVar (M, 4) optional, TargetBox:
      encode_center_size: (N, 4) gt corner boxes → Out (N, M, 4) offsets
      decode_center_size: (N, M, 4) offsets → Out (N, M, 4) corner boxes
    """
    prior = first(ins, "PriorBox")
    pvar = opt_in(ins, "PriorBoxVar")
    target = first(ins, "TargetBox")
    code_type = attrs.get("code_type", "encode_center_size")
    norm = bool(attrs.get("box_normalized", True))
    extra = 0.0 if norm else 1.0

    pw = prior[:, 2] - prior[:, 0] + extra        # (M,)
    ph = prior[:, 3] - prior[:, 1] + extra
    pcx = prior[:, 0] + pw / 2.0
    pcy = prior[:, 1] + ph / 2.0
    if pvar is None:
        pvar = jnp.ones((prior.shape[0], 4), prior.dtype)

    if code_type == "encode_center_size":
        tw = target[:, 2] - target[:, 0] + extra   # (N,)
        th = target[:, 3] - target[:, 1] + extra
        tcx = target[:, 0] + tw / 2.0
        tcy = target[:, 1] + th / 2.0
        ox = ((tcx[:, None] - pcx[None, :]) / pw[None, :]) / pvar[None, :, 0]
        oy = ((tcy[:, None] - pcy[None, :]) / ph[None, :]) / pvar[None, :, 1]
        ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :])) / pvar[None, :, 2]
        oh = jnp.log(jnp.abs(th[:, None] / ph[None, :])) / pvar[None, :, 3]
        o = jnp.stack([ox, oy, ow, oh], axis=-1)
    elif code_type == "decode_center_size":
        # target: (N, M, 4) deltas
        dcx = pvar[None, :, 0] * target[..., 0] * pw[None, :] + pcx[None, :]
        dcy = pvar[None, :, 1] * target[..., 1] * ph[None, :] + pcy[None, :]
        dw = jnp.exp(pvar[None, :, 2] * target[..., 2]) * pw[None, :]
        dh = jnp.exp(pvar[None, :, 3] * target[..., 3]) * ph[None, :]
        o = jnp.stack([dcx - dw / 2.0, dcy - dh / 2.0,
                       dcx + dw / 2.0 - extra, dcy + dh / 2.0 - extra],
                      axis=-1)
    else:
        raise ValueError(f"unknown code_type {code_type!r}")
    return out(OutputBox=o)


# ---------------------------------------------------------------------------
# iou_similarity
# ---------------------------------------------------------------------------

def _iou_matrix(x, y, normalized=True):
    extra = 0.0 if normalized else 1.0
    area_x = (x[:, 2] - x[:, 0] + extra) * (x[:, 3] - x[:, 1] + extra)
    area_y = (y[:, 2] - y[:, 0] + extra) * (y[:, 3] - y[:, 1] + extra)
    ix1 = jnp.maximum(x[:, None, 0], y[None, :, 0])
    iy1 = jnp.maximum(x[:, None, 1], y[None, :, 1])
    ix2 = jnp.minimum(x[:, None, 2], y[None, :, 2])
    iy2 = jnp.minimum(x[:, None, 3], y[None, :, 3])
    iw = jnp.maximum(ix2 - ix1 + extra, 0.0)
    ih = jnp.maximum(iy2 - iy1 + extra, 0.0)
    inter = iw * ih
    union = area_x[:, None] + area_y[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register_op("iou_similarity")
def iou_similarity(ctx, ins, attrs):
    """Pairwise IoU (reference iou_similarity_op.cc): X (N,4), Y (M,4)
    → (N, M)."""
    x = first(ins, "X")
    y = first(ins, "Y")
    return out(Out=_iou_matrix(x, y,
                               bool(attrs.get("box_normalized", True))))


# ---------------------------------------------------------------------------
# multiclass_nms
# ---------------------------------------------------------------------------

def _nms_class(boxes, scores, score_threshold, nms_threshold, top_k,
               normalized=True, nms_eta=1.0):
    """Single-class NMS over top_k candidates: returns
    (scores, keep_mask, idx)."""
    k = min(top_k, scores.shape[0])
    top_scores, order = lax.top_k(scores, k)
    cand = boxes[order]                             # (k, 4)
    iou = _iou_matrix(cand, cand, normalized)       # (k, k)
    valid0 = top_scores > score_threshold

    def body(i, carry):
        keep, thr = carry
        # suppress i if any higher-scored kept box overlaps too much
        mask = (jnp.arange(k) < i) & keep & (iou[i] > thr)
        kept_i = keep[i] & ~jnp.any(mask)
        keep = keep.at[i].set(kept_i)
        # adaptive NMS (reference nms_eta < 1): shrink the threshold
        # after each kept candidate while it stays above 0.5
        if nms_eta < 1.0:
            thr = jnp.where(kept_i & (thr > 0.5), thr * nms_eta, thr)
        return keep, thr

    # candidate 0 is kept whenever valid, and (reference NMSFast) a kept
    # box immediately shrinks the adaptive threshold for later candidates
    thr0 = jnp.asarray(nms_threshold, jnp.float32)
    if nms_eta < 1.0:
        thr0 = jnp.where(valid0[0] & (thr0 > 0.5), thr0 * nms_eta, thr0)
    keep, _ = lax.fori_loop(1, k, body, (valid0, thr0))
    keep = keep & valid0
    return top_scores, keep, order


@register_op("multiclass_nms")
def multiclass_nms(ctx, ins, attrs):
    """reference multiclass_nms_op.cc with a static-shape contract.

    inputs: BBoxes (N, M, 4), Scores (N, C, M).
    outputs: Out (N, keep_top_k, 6) rows [label, score, x1, y1, x2, y2]
             padded with -1; NmsRoisNum (N,) valid counts.
    """
    bboxes = first(ins, "BBoxes")
    scores = first(ins, "Scores")
    background = int(attrs.get("background_label", 0))
    score_th = float(attrs.get("score_threshold", 0.0))
    nms_top_k = int(attrs.get("nms_top_k", 100))
    nms_th = float(attrs.get("nms_threshold", 0.3))
    keep_top_k = int(attrs.get("keep_top_k", 100))
    normalized = bool(attrs.get("normalized", True))
    nms_eta = float(attrs.get("nms_eta", 1.0))
    N, C, M = scores.shape
    NEG = jnp.asarray(-1e30, scores.dtype)  # suppression sentinel, below
    # any real score (keeps validity distinct from legit <=0 scores)

    def per_image(boxes, sc):
        all_scores, all_idx, all_label = [], [], []
        for c in range(C):
            if c == background:
                continue
            s, keep, order = _nms_class(boxes, sc[c], score_th, nms_th,
                                        nms_top_k, normalized, nms_eta)
            all_scores.append(jnp.where(keep, s, NEG))
            all_idx.append(order)
            all_label.append(jnp.full(s.shape, c, jnp.int32))
        cat_s = jnp.concatenate(all_scores)
        cat_i = jnp.concatenate(all_idx)
        cat_l = jnp.concatenate(all_label)
        k = min(keep_top_k, cat_s.shape[0])
        top_s, pick = lax.top_k(cat_s, k)
        valid = top_s > NEG / 2
        lab = jnp.where(valid, cat_l[pick], -1)
        bx = boxes[cat_i[pick]]
        rows = jnp.concatenate(
            [lab[:, None].astype(boxes.dtype), top_s[:, None], bx], axis=1)
        rows = jnp.where(valid[:, None], rows, -1.0)
        if k < keep_top_k:
            rows = jnp.pad(rows, ((0, keep_top_k - k), (0, 0)),
                           constant_values=-1.0)
        count = jnp.sum(valid)
        return rows, count

    rows, counts = jax.vmap(per_image)(bboxes, scores)
    return out(Out=rows, NmsRoisNum=counts.astype(jnp.int32))


# ---------------------------------------------------------------------------
# yolov3_loss
# ---------------------------------------------------------------------------

def _bce(logit, target):
    return jax.nn.softplus(logit) - target * logit


@register_op("yolov3_loss")
def yolov3_loss(ctx, ins, attrs):
    """YOLOv3 training loss (reference yolov3_loss_op.cc).

    inputs: X (N, A*(5+K), H, W) raw head output, GTBox (N, B, 4)
            normalized [cx, cy, w, h], GTLabel (N, B) int (−1 or w==0
            rows are padding).
    attrs: anchors (flat [w0,h0,w1,h1,...] in input-image pixels),
           anchor_mask (indices of this head's anchors), class_num,
           ignore_thresh, downsample_ratio.
    outputs: Loss (N,).

    Assignment follows the reference: each gt is matched to the best-IoU
    anchor over ALL anchors (shape-only IoU); the loss terms apply only
    when that anchor belongs to this head's mask.  Objectness of
    non-assigned predictions is pushed to 0 unless their IoU with some
    gt exceeds ignore_thresh.
    """
    x = first(ins, "X")
    gtbox = first(ins, "GTBox")
    gtlabel = first(ins, "GTLabel").astype(jnp.int32)
    anchors = [float(a) for a in attrs["anchors"]]
    mask = [int(m) for m in attrs.get("anchor_mask",
                                      range(len(anchors) // 2))]
    class_num = int(attrs["class_num"])
    ignore = float(attrs.get("ignore_thresh", 0.7))
    down = int(attrs.get("downsample_ratio", 32))

    N, _, H, W = x.shape
    A = len(mask)
    K = class_num
    img_h, img_w = H * down, W * down
    x = x.reshape(N, A, 5 + K, H, W)
    tx, ty = x[:, :, 0], x[:, :, 1]                 # (N, A, H, W)
    tw, th = x[:, :, 2], x[:, :, 3]
    tobj = x[:, :, 4]
    tcls = x[:, :, 5:]                              # (N, A, K, H, W)

    anchor_w = jnp.asarray([anchors[2 * m] for m in mask])
    anchor_h = jnp.asarray([anchors[2 * m + 1] for m in mask])
    all_w = jnp.asarray(anchors[0::2])
    all_h = jnp.asarray(anchors[1::2])

    B = gtbox.shape[1]
    gt_valid = (gtbox[..., 2] > 0) & (gtlabel >= 0)  # (N, B)

    # best anchor per gt by shape-only IoU (reference: gt at origin)
    gw = gtbox[..., 2] * img_w                      # (N, B)
    gh = gtbox[..., 3] * img_h
    inter = (jnp.minimum(gw[..., None], all_w) *
             jnp.minimum(gh[..., None], all_h))
    union = gw[..., None] * gh[..., None] + all_w * all_h - inter
    best_anchor = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=-1)

    gi = jnp.clip((gtbox[..., 0] * W).astype(jnp.int32), 0, W - 1)
    gj = jnp.clip((gtbox[..., 1] * H).astype(jnp.int32), 0, H - 1)

    # decode predictions to normalized boxes for the ignore mask
    grid_x = (jnp.arange(W)[None, None, None, :])
    grid_y = (jnp.arange(H)[None, None, :, None])
    px = (jax.nn.sigmoid(tx) + grid_x) / W          # (N, A, H, W)
    py = (jax.nn.sigmoid(ty) + grid_y) / H
    pw = jnp.exp(jnp.clip(tw, -10, 10)) * anchor_w[None, :, None, None] / img_w
    ph = jnp.exp(jnp.clip(th, -10, 10)) * anchor_h[None, :, None, None] / img_h

    def pred_gt_iou(pb, gb):
        # pb: (A, H, W, 4) cxcywh; gb: (B, 4) cxcywh → (A, H, W, B)
        px1, py1 = pb[..., 0] - pb[..., 2] / 2, pb[..., 1] - pb[..., 3] / 2
        px2, py2 = pb[..., 0] + pb[..., 2] / 2, pb[..., 1] + pb[..., 3] / 2
        gx1, gy1 = gb[:, 0] - gb[:, 2] / 2, gb[:, 1] - gb[:, 3] / 2
        gx2, gy2 = gb[:, 0] + gb[:, 2] / 2, gb[:, 1] + gb[:, 3] / 2
        ix1 = jnp.maximum(px1[..., None], gx1)
        iy1 = jnp.maximum(py1[..., None], gy1)
        ix2 = jnp.minimum(px2[..., None], gx2)
        iy2 = jnp.minimum(py2[..., None], gy2)
        iw = jnp.maximum(ix2 - ix1, 0.0)
        ih = jnp.maximum(iy2 - iy1, 0.0)
        inter = iw * ih
        pa = pb[..., 2] * pb[..., 3]
        ga = gb[:, 2] * gb[:, 3]
        return inter / jnp.maximum(pa[..., None] + ga - inter, 1e-9)

    pred_boxes = jnp.stack([px, py, pw, ph], axis=-1)  # (N, A, H, W, 4)
    iou_pg = jax.vmap(pred_gt_iou)(pred_boxes, gtbox)  # (N, A, H, W, B)
    iou_max = jnp.max(jnp.where(gt_valid[:, None, None, None, :],
                                iou_pg, 0.0), axis=-1)

    # objectness targets: scatter 1 at assigned (a, gj, gi) cells
    mask_arr = jnp.asarray(mask)
    in_head = jnp.any(best_anchor[..., None] == mask_arr, axis=-1)
    assigned = gt_valid & in_head                    # (N, B)
    local_a = jnp.argmax(
        (best_anchor[..., None] == mask_arr).astype(jnp.int32), axis=-1)

    obj_target = jnp.zeros((N, A, H, W))
    batch_ix = jnp.arange(N)[:, None]
    obj_target = obj_target.at[
        batch_ix, local_a, gj, gi].max(assigned.astype(jnp.float32))

    noobj_mask = (obj_target == 0) & (iou_max <= ignore)
    obj_loss = jnp.sum(
        _bce(tobj, 1.0) * obj_target, axis=(1, 2, 3)) + jnp.sum(
        _bce(tobj, 0.0) * noobj_mask, axis=(1, 2, 3))

    # per-gt coordinate + class losses, gathered at assigned cells
    sel = lambda arr: arr[batch_ix, local_a, gj, gi]   # (N, B)
    scale = 2.0 - gtbox[..., 2] * gtbox[..., 3]        # small-box boost
    tx_t = gtbox[..., 0] * W - gi
    ty_t = gtbox[..., 1] * H - gj
    aw = anchor_w[local_a]
    ah = anchor_h[local_a]
    tw_t = jnp.log(jnp.maximum(gw / jnp.maximum(aw, 1e-9), 1e-9))
    th_t = jnp.log(jnp.maximum(gh / jnp.maximum(ah, 1e-9), 1e-9))
    coord = (_bce(sel(tx), tx_t) + _bce(sel(ty), ty_t)) * scale \
        + (jnp.square(sel(tw) - tw_t)
           + jnp.square(sel(th) - th_t)) * 0.5 * scale
    cls_sel = tcls[batch_ix, local_a, :, gj, gi]       # (N, B, K)
    cls_target = jax.nn.one_hot(gtlabel, K)
    cls_loss = jnp.sum(_bce(cls_sel, cls_target), axis=-1)
    per_gt = jnp.where(assigned, coord + cls_loss, 0.0)
    loss = obj_loss + jnp.sum(per_gt, axis=1)
    return out(Loss=loss)

# ---------------------------------------------------------------------------
# anchor_generator / density_prior_box
# ---------------------------------------------------------------------------

@register_op("anchor_generator")
def anchor_generator(ctx, ins, attrs):
    """Faster-RCNN anchors for one feature map (reference
    detection/anchor_generator_op.cc): per cell, boxes of every
    (anchor_size, aspect_ratio) pair in input-image pixels.

    inputs: Input (N, C, H, W); outputs: Anchors (H, W, A, 4) pixel
    [x1,y1,x2,y2], Variances (H, W, A, 4).
    """
    feat = first(ins, "Input")
    h, w = feat.shape[2], feat.shape[3]
    sizes = [float(s) for s in attrs["anchor_sizes"]]
    ratios = [float(r) for r in attrs["aspect_ratios"]]
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    stride = [float(s) for s in attrs.get("stride", [16.0, 16.0])]
    offset = float(attrs.get("offset", 0.5))

    # reference anchor_generator_op.h:55-84 exactly: the base box comes
    # from the STRIDE area (base_w = round(sqrt(stride_w*stride_h / ar)),
    # base_h = round(base_w * ar)) scaled by anchor_size/stride; centers
    # are i*stride + offset*(stride-1); corners use (side-1)/2 — the
    # RCNN-lineage convention, checkpoint-compatible (size 32 ratio 1 at
    # stride 16 → [-8, -8, 23, 23])
    ws, hs = [], []
    for r in ratios:
        for s in sizes:
            area = stride[0] * stride[1]
            base_w = round((area / r) ** 0.5)
            base_h = round(base_w * r)
            ws.append(float(base_w) * (s / stride[0]))
            hs.append(float(base_h) * (s / stride[1]))
    bw = (jnp.asarray(ws) - 1.0) / 2.0
    bh = (jnp.asarray(hs) - 1.0) / 2.0
    a = len(ws)
    cx = jnp.arange(w) * stride[0] + offset * (stride[0] - 1)
    cy = jnp.arange(h) * stride[1] + offset * (stride[1] - 1)
    cxg = jnp.broadcast_to(cx[None, :, None], (h, w, a))
    cyg = jnp.broadcast_to(cy[:, None, None], (h, w, a))
    anchors = jnp.stack([cxg - bw, cyg - bh, cxg + bw, cyg + bh], axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances), (h, w, a, 4))
    return out(Anchors=anchors.astype(feat.dtype),
               Variances=var.astype(feat.dtype))


@register_op("density_prior_box")
def density_prior_box(ctx, ins, attrs):
    """Dense SSD priors (reference detection/density_prior_box_op.cc):
    for each fixed_size with its density d, a d×d sub-grid of shifted
    boxes per cell per fixed_ratio."""
    feat = first(ins, "Input")
    image = first(ins, "Image")
    h, w = feat.shape[2], feat.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    fixed_sizes = [float(s) for s in attrs["fixed_sizes"]]
    fixed_ratios = [float(r) for r in attrs["fixed_ratios"]]
    densities = [int(d) for d in attrs["densities"]]
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    step_w = float(attrs.get("step_w", 0.0)) or img_w / w
    step_h = float(attrs.get("step_h", 0.0)) or img_h / h
    offset = float(attrs.get("offset", 0.5))

    # reference density_prior_box_op.h:65-90 exactly: one integer
    # step_average = int((step_w + step_h)/2) drives BOTH axes' integer
    # shift = step_average // density, and the sub-grid centers offset by
    # -step_average/2 + shift/2 + d*shift
    step_average = int((step_w + step_h) * 0.5)
    centers_x, centers_y, ws, hs = [], [], [], []
    for size, dens in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw_ = size * ratio ** 0.5
            bh_ = size / ratio ** 0.5
            shift = step_average // dens
            for dy in range(dens):
                for dx in range(dens):
                    centers_x.append(
                        -step_average / 2.0 + shift / 2.0 + dx * shift)
                    centers_y.append(
                        -step_average / 2.0 + shift / 2.0 + dy * shift)
                    ws.append(bw_ / 2.0)
                    hs.append(bh_ / 2.0)
    p = len(ws)
    dx_off = jnp.asarray(centers_x)
    dy_off = jnp.asarray(centers_y)
    bw = jnp.asarray(ws)
    bh = jnp.asarray(hs)
    cx = (jnp.arange(w) + offset) * step_w
    cy = (jnp.arange(h) + offset) * step_h
    cxg = cx[None, :, None] + dx_off[None, None, :]
    cyg = cy[:, None, None] + dy_off[None, None, :]
    cxg = jnp.broadcast_to(cxg, (h, w, p))
    cyg = jnp.broadcast_to(cyg, (h, w, p))
    boxes = jnp.stack(
        [(cxg - bw) / img_w, (cyg - bh) / img_h,
         (cxg + bw) / img_w, (cyg + bh) / img_h], axis=-1)
    if attrs.get("clip", True):
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), (h, w, p, 4))
    return out(Boxes=boxes.astype(feat.dtype),
               Variances=var.astype(feat.dtype))


# ---------------------------------------------------------------------------
# box_clip / bipartite_match / target_assign
# ---------------------------------------------------------------------------

@register_op("box_clip")
def box_clip(ctx, ins, attrs):
    """Clip boxes to image extents (reference detection/box_clip_op.cc).
    Input (..., 4); ImInfo (N, 3) [h, w, scale] when batched, else clip
    to attrs im_shape."""
    boxes = first(ins, "Input")
    im_info = opt_in(ins, "ImInfo")
    if im_info is not None:
        # im_info rows are [h, w, scale] of the NETWORK input; boxes are
        # in original-image coordinates, so clip to (h/scale, w/scale)
        # (reference box_clip_op.h GetImInfo)
        scale = jnp.maximum(im_info[:, 2], 1e-6)
        hmax = im_info[:, 0] / scale - 1.0
        wmax = im_info[:, 1] / scale - 1.0
        shape = (-1,) + (1,) * (boxes.ndim - 2)
        x1 = jnp.clip(boxes[..., 0], 0.0, wmax.reshape(shape))
        y1 = jnp.clip(boxes[..., 1], 0.0, hmax.reshape(shape))
        x2 = jnp.clip(boxes[..., 2], 0.0, wmax.reshape(shape))
        y2 = jnp.clip(boxes[..., 3], 0.0, hmax.reshape(shape))
        return out(Output=jnp.stack([x1, y1, x2, y2], axis=-1))
    h, w = attrs["im_shape"]
    lo = jnp.asarray([0.0, 0.0, 0.0, 0.0])
    hi = jnp.asarray([w - 1.0, h - 1.0, w - 1.0, h - 1.0])
    return out(Output=jnp.clip(boxes, lo, hi))


@register_op("bipartite_match")
def bipartite_match(ctx, ins, attrs):
    """Greedy bipartite matching over a similarity matrix (reference
    detection/bipartite_match_op.cc BipartiteMatch): repeatedly take the
    globally-best (row, col) pair, retiring both; then (match_type
    'per_prediction') also match leftover columns whose best row clears
    dist_threshold.

    inputs: DistMat (R, C) — rows = gt, cols = priors.
    outputs: ColToRowMatchIndices (1, C) int32 (-1 unmatched),
             ColToRowMatchDist (1, C).
    """
    dist = first(ins, "DistMat")
    r, c = dist.shape
    neg = jnp.asarray(-1e9, dist.dtype)

    def body(carry, _):
        d, col_idx, col_dist = carry
        flat = jnp.argmax(d)
        i, j = flat // c, flat % c
        best = d[i, j]
        ok = best > 0
        col_idx = jnp.where(ok, col_idx.at[j].set(i.astype(jnp.int32)),
                            col_idx)
        col_dist = jnp.where(ok, col_dist.at[j].set(best), col_dist)
        d = jnp.where(ok, d.at[i, :].set(neg).at[:, j].set(neg), d)
        return (d, col_idx, col_dist), None

    init = (dist, jnp.full((c,), -1, jnp.int32),
            jnp.zeros((c,), dist.dtype))
    (d_f, col_idx, col_dist), _ = lax.scan(body, init, None,
                                           length=min(r, c))

    if attrs.get("match_type", "bipartite") == "per_prediction":
        thr = float(attrs.get("dist_threshold", 0.5))
        best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)
        best_val = jnp.max(dist, axis=0)
        extra = (col_idx < 0) & (best_val >= thr)
        col_idx = jnp.where(extra, best_row, col_idx)
        col_dist = jnp.where(extra, best_val, col_dist)
    return out(ColToRowMatchIndices=col_idx[None, :],
               ColToRowMatchDist=col_dist[None, :])


@register_op("target_assign")
def target_assign(ctx, ins, attrs):
    """Scatter per-gt attributes onto matched priors (reference
    detection/target_assign_op.cc): Out[j] = X[MatchIndices[j]] where
    matched, else mismatch_value; OutWeight 1/0.

    inputs: X (R, K) gt attributes, MatchIndices (1, C) or (C,).
    """
    x = first(ins, "X")
    match = first(ins, "MatchIndices").reshape(-1).astype(jnp.int32)
    mismatch = attrs.get("mismatch_value", 0)
    matched = match >= 0
    safe = jnp.clip(match, 0, x.shape[0] - 1)
    gathered = jnp.take(x, safe, axis=0)
    fill = jnp.full_like(gathered, mismatch)
    o = jnp.where(matched[:, None], gathered, fill)
    wt = matched.astype(jnp.float32)[:, None]
    return out(Out=o, OutWeight=wt)


# ---------------------------------------------------------------------------
# generate_proposals (RPN)
# ---------------------------------------------------------------------------

@register_op("generate_proposals")
def generate_proposals(ctx, ins, attrs):
    """RPN proposal generation (reference
    detection/generate_proposals_op.cc): decode anchor deltas, clip to
    the image, drop tiny boxes (score masked), NMS, keep post_nms_topN —
    with a static-shape contract: RpnRois is (N, post_nms_topN, 4)
    zero-padded and RpnRoisNum the valid counts.

    inputs: Scores (N, A, H, W), BboxDeltas (N, 4A, H, W),
            ImInfo (N, 3), Anchors (H, W, A, 4), Variances (H, W, A, 4).
    """
    scores = first(ins, "Scores")
    deltas = first(ins, "BboxDeltas")
    im_info = first(ins, "ImInfo")
    anchors = first(ins, "Anchors").reshape(-1, 4)
    variances = first(ins, "Variances").reshape(-1, 4)
    pre_n = int(attrs.get("pre_nms_topN", 6000))
    post_n = int(attrs.get("post_nms_topN", 1000))
    nms_thresh = float(attrs.get("nms_thresh", 0.7))
    min_size = float(attrs.get("min_size", 0.1))
    eta = float(attrs.get("eta", 1.0))

    n, a, h, w = scores.shape
    total = a * h * w
    pre_n = min(pre_n, total)
    # (N, A, H, W) → (N, H*W*A) aligned with anchors (H, W, A)
    sc = jnp.transpose(scores, (0, 2, 3, 1)).reshape(n, -1)
    dl = jnp.transpose(deltas.reshape(n, a, 4, h, w),
                       (0, 3, 4, 1, 2)).reshape(n, -1, 4)

    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + aw / 2.0
    acy = anchors[:, 1] + ah / 2.0

    def per_image(s, d, info):
        cx = acx + d[:, 0] * variances[:, 0] * aw
        cy = acy + d[:, 1] * variances[:, 1] * ah
        bw = aw * jnp.exp(jnp.clip(d[:, 2] * variances[:, 2], -10, 10))
        bh = ah * jnp.exp(jnp.clip(d[:, 3] * variances[:, 3], -10, 10))
        x1 = jnp.clip(cx - bw / 2.0, 0.0, info[1] - 1.0)
        y1 = jnp.clip(cy - bh / 2.0, 0.0, info[0] - 1.0)
        x2 = jnp.clip(cx + bw / 2.0, 0.0, info[1] - 1.0)
        y2 = jnp.clip(cy + bh / 2.0, 0.0, info[0] - 1.0)
        boxes = jnp.stack([x1, y1, x2, y2], axis=1)
        # reference FilterBoxes (generate_proposals_op.cc:161-176):
        # min_size floors to 1.0, sizes measured in ORIGINAL image scale
        # ((x2-x1)/im_scale + 1), centers must lie inside the image
        msize = max(min_size, 1.0)
        scale_ = jnp.maximum(info[2], 1e-6)
        ws_orig = (x2 - x1) / scale_ + 1.0
        hs_orig = (y2 - y1) / scale_ + 1.0
        cx_c = x1 + (x2 - x1 + 1.0) / 2.0
        cy_c = y1 + (y2 - y1 + 1.0) / 2.0
        keep_size = ((ws_orig >= msize) & (hs_orig >= msize)
                     & (cx_c <= info[1]) & (cy_c <= info[0]))
        s_masked = jnp.where(keep_size, s, -1e9)
        top_s, top_i = lax.top_k(s_masked, pre_n)
        cand = boxes[top_i]
        # NMS walks the FULL pre_nms pool (reference NMS loop continues
        # until post_nms_topN survivors are collected), not just the top
        # post_n candidates — suppressed slots backfill from the pool;
        # pixel-coordinate IoU uses the +1 convention
        # (JaccardOverlap normalized=false, generate_proposals_op.cc:269)
        kept_s, keep, order = _nms_class(
            cand, top_s, -1e8, nms_thresh, pre_n, normalized=False,
            nms_eta=eta)
        sel = jnp.where(keep, kept_s, -1e30)
        final_s, pick = lax.top_k(sel, min(post_n, sel.shape[0]))
        valid = final_s > -1e29
        rois = cand[order[pick]]
        rois = jnp.where(valid[:, None], rois, 0.0)
        if rois.shape[0] < post_n:
            rois = jnp.pad(rois, ((0, post_n - rois.shape[0]), (0, 0)))
            valid = jnp.pad(valid, (0, post_n - valid.shape[0]))
        return rois, jnp.sum(valid).astype(jnp.int32)

    rois, counts = jax.vmap(per_image)(sc, dl, im_info)
    return out(RpnRois=rois, RpnRoisNum=counts)
