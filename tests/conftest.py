"""Test harness config: virtual 8-device CPU mesh.

Mirrors the reference's test strategy (SURVEY.md §4): op tests run against
the CPU interpreter; multi-device tests use a virtual 8-device host mesh
(xla_force_host_platform_device_count) standing in for an ICI slice.
Must run before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402  (after env setup)

# The environment's sitecustomize pins the platform to the TPU plugin
# before conftest runs; force the virtual 8-device CPU backend for tests.
jax.config.update("jax_platforms", "cpu")

# Numeric comparisons against float64 numpy references need full-precision
# matmuls; the framework itself keeps the fast TPU default.
jax.config.update("jax_default_matmul_precision", "highest")

# Event-kind registry enforcement (ISSUE 15): under tests an
# unregistered serving_/fleet_/gang_ event kind RAISES instead of
# warning — a typo'd kind silently drops off every dashboard filter,
# and warn-only rot is exactly what the registries exist to stop.
from paddle_tpu.observe import events as _observe_events  # noqa: E402

_observe_events.set_strict_kinds(True)
