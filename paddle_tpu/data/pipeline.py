"""Device-fed input pipeline: double-buffered host→device prefetch.

TPU-native analog of the reference's device-side reader chain
(reference: paddle/fluid/operators/reader/buffered_reader.cc:1 — pinned-
memory double buffering; reader/create_py_reader_op.cc +
lod_tensor_blocking_queue.h — a Python thread feeding a blocking queue
the graph's read op pops; python/paddle/fluid/layers/io.py py_reader:633,
double_buffer:1002).

Design: a daemon thread pulls host batches from the user's reader,
starts their host→device transfers immediately (`jax.device_put` is
asynchronous — the copy overlaps the current training step), and parks
the in-flight device arrays in a bounded queue.  The training loop pops
ready feed dicts, so steady-state step time is max(compute, transfer)
instead of compute + transfer.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from .decorator import _ReaderError

_STOP = object()


class DeviceFeeder:
    """Iterator of device-resident feed dicts with background prefetch.

    reader: callable returning an iterable of feed dicts
            ({name: np.ndarray}) — one dict per step.
    capacity: max in-flight prefetched batches (2 = classic double
              buffering; raise it to ride out producer jitter).
    """

    def __init__(self, reader: Callable[[], Iterable[Dict[str, np.ndarray]]],
                 capacity: int = 2, device=None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self._reader = reader
        self._capacity = capacity
        self._device = device
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- lifecycle (py_reader start/reset parity) -----------------------
    def start(self):
        """Begin prefetching a fresh pass over the reader."""
        self.reset()
        # a fresh pass must not serve the previous pass's cached
        # speed-test batch
        if hasattr(self, "_speed_test_batch"):
            del self._speed_test_batch
        self._queue = queue.Queue(maxsize=self._capacity)
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._producer, args=(self._queue,), daemon=True)
        self._thread.start()
        return self

    def reset(self):
        """Stop the current pass (reference py_reader.reset).  The
        producer owns its queue reference, so a slow reader that outlives
        the join timeout dies quietly on the stop flag instead of
        crashing on a nulled queue."""
        if self._thread is not None and self._thread.is_alive():
            self._stop.set()
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=5)
        self._thread = None
        self._queue = None
        if hasattr(self, "_speed_test_batch"):
            del self._speed_test_batch

    # -- producer -------------------------------------------------------
    def _put(self, q: queue.Queue, item) -> bool:
        """Blocking put that aborts when reset() raises the stop flag."""
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _producer(self, q: queue.Queue):
        import jax

        try:
            for batch in self._reader():
                if self._stop.is_set():
                    return
                # device_put is async: the transfer starts now and
                # overlaps the consumer's current step
                # (buffered_reader.cc's pinned-mem copy)
                placed = {n: jax.device_put(v, self._device)
                          for n, v in batch.items()}
                if not self._put(q, placed):
                    return
            self._put(q, _STOP)
        except BaseException as e:  # surfaced on the consumer side
            self._put(q, _ReaderError(e))

    # -- consumer -------------------------------------------------------
    def __iter__(self):
        if self._queue is None:
            self.start()
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        if self._queue is None:
            raise StopIteration
        from ..flags import FLAGS

        if FLAGS.reader_queue_speed_test_mode:
            # non-destructive mode (reference
            # FLAGS_reader_queue_speed_test_mode): serve the first batch
            # forever so consumer-side throughput excludes producer cost
            if not hasattr(self, "_speed_test_batch"):
                self._speed_test_batch = self._queue.get()
            if self._speed_test_batch is _STOP or isinstance(
                    self._speed_test_batch, _ReaderError):
                item = self._speed_test_batch
            else:
                return self._speed_test_batch
        else:
            item = self._queue.get()
        if item is _STOP:
            self._queue = None
            self._thread = None
            raise StopIteration
        if isinstance(item, _ReaderError):
            self._queue = None
            raise item.error
        return item


class PyReader:
    """fluid-style py_reader facade (reference layers/io.py:633): declare
    feed vars once, decorate with a sample/batch reader, iterate
    device-resident batches.

        reader = PyReader(feed_list=[img, label], capacity=4)
        reader.decorate_batch_generator(my_batches)
        for feed in reader:
            exe.run(main, feed=feed, fetch_list=[loss])
    """

    def __init__(self, feed_list: Sequence, capacity: int = 2):
        self._names: List[str] = []
        for v in feed_list:
            name = v if isinstance(v, str) else v.name
            self._names.append(name)
            # sequence inputs (lod_level > 0) need their .seq_len
            # companion fed too: expect it as the next tuple slot
            # (mirrors DataFeeder, data/data_feeder.py)
            if (not isinstance(v, str)
                    and getattr(v.desc, "lod_level", 0) > 0):
                self._names.append(f"{name}.seq_len")
        self._capacity = capacity
        self._feeder: Optional[DeviceFeeder] = None
        self._gen = None

    def decorate_batch_generator(self, generator):
        """generator: callable -> iterable of tuples/lists/dicts of numpy
        batches aligned with feed_list."""
        names = self._names

        def reader():
            for item in generator():
                if isinstance(item, dict):
                    yield item
                else:
                    if len(item) != len(names):
                        raise ValueError(
                            f"batch has {len(item)} arrays for "
                            f"{len(names)} feed vars {names}")
                    yield dict(zip(names, item))

        self._gen = reader
        return self

    decorate_paddle_reader = decorate_batch_generator

    def start(self):
        if self._gen is None:
            raise RuntimeError("decorate_batch_generator first")
        self._feeder = DeviceFeeder(self._gen, capacity=self._capacity)
        self._feeder.start()
        return self

    def reset(self):
        if self._feeder is not None:
            self._feeder.reset()
            self._feeder = None

    def __iter__(self):
        if self._feeder is None:
            self.start()
        return iter(self._feeder)
