"""Admission control: the robustness half of the serving engine.

A TPU serving frontend dies in one of three boring ways: an unbounded
queue grows until the process OOMs, expired requests burn device time
computing answers nobody is waiting for, or shutdown races in-flight
work and strands callers on futures that never resolve.  This module
owns all three:

- **bounded queue + fast-reject load shedding** — `check()` raises
  `QueueFullError` *at submit time* when the engine is at capacity;
  the caller gets a structured rejection in microseconds instead of a
  timeout after seconds (the TF-Serving batching-queue contract),
- **per-request deadlines** — `deadline_for()` stamps an absolute
  monotonic deadline on each request; the batcher drops expired
  requests *before* dispatch (`DeadlineExceededError`), never after,
- **health/drain state machine** — CREATED → RUNNING → DRAINING →
  STOPPED.  Draining stops admission immediately but lets queued work
  finish, so a rolling restart never drops accepted requests.

All serving errors derive from `ServingError` and carry a structured
`details` dict (`as_dict()`), so a frontend can serialize rejections
without parsing message strings.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

# -- state machine values (strings, so health() dicts are json-ready) ---
CREATED = "created"
RUNNING = "running"
DRAINING = "draining"
STOPPED = "stopped"


class ServingError(RuntimeError):
    """Base for structured serving rejections.

    `details` is machine-readable; `as_dict()` is the wire form a
    frontend returns to the client (and what tests assert on).
    """

    kind = "serving_error"

    def __init__(self, message: str, **details: Any):
        super().__init__(message)
        self.details = details

    def as_dict(self) -> Dict[str, Any]:
        out = {"error": self.kind, "message": str(self)}
        out.update(self.details)
        return out


class QueueFullError(ServingError):
    """Load shed: the bounded queue is at capacity (fast-reject)."""

    kind = "queue_full"


class DeadlineExceededError(ServingError):
    """The request's deadline expired while queued; it was dropped
    before dispatch (no device time was spent on it)."""

    kind = "deadline_exceeded"


class ServingClosedError(ServingError):
    """Submitted to an engine that is not RUNNING (not started yet,
    draining, or stopped)."""

    kind = "serving_closed"


class AdmissionController:
    """Admission decisions + the health/drain state machine.

    The controller is deliberately free of queue mechanics: the batcher
    reports its in-flight count and the controller answers admit/reject,
    so the policy is testable without threads.
    """

    def __init__(self, queue_capacity: int,
                 default_deadline_ms: Optional[float] = None):
        if queue_capacity < 1:
            raise ValueError("queue_capacity must be >= 1")
        if default_deadline_ms is not None and default_deadline_ms <= 0:
            raise ValueError("default_deadline_ms must be > 0")
        self.queue_capacity = int(queue_capacity)
        self.default_deadline_ms = default_deadline_ms
        self._state = CREATED
        self._lock = threading.Lock()

    # -- state machine --------------------------------------------------
    @property
    def state(self) -> str:
        return self._state

    def start(self):
        with self._lock:
            if self._state != CREATED:
                raise ServingClosedError(
                    f"cannot start from state {self._state!r}",
                    state=self._state)
            self._state = RUNNING

    def begin_drain(self):
        with self._lock:
            if self._state in (DRAINING, STOPPED):
                return  # drain is idempotent
            if self._state != RUNNING:
                raise ServingClosedError(
                    f"cannot drain from state {self._state!r}",
                    state=self._state)
            self._state = DRAINING

    def finish_drain(self):
        with self._lock:
            self._state = STOPPED

    # -- admission ------------------------------------------------------
    def check(self, inflight: int):
        """Admit one request given the current in-flight count, or
        raise the structured rejection.  Called under the batcher's
        lock, so the count cannot race past capacity."""
        if self._state != RUNNING:
            raise ServingClosedError(
                f"engine is {self._state}; not accepting requests",
                state=self._state)
        if inflight >= self.queue_capacity:
            raise QueueFullError(
                f"queue at capacity ({self.queue_capacity}); request "
                "shed", capacity=self.queue_capacity, inflight=inflight)

    def deadline_for(self, deadline_ms: Optional[float],
                     now: Optional[float] = None) -> Optional[float]:
        """Absolute monotonic deadline for a request, or None when
        neither the request nor the engine sets one."""
        ms = deadline_ms if deadline_ms is not None \
            else self.default_deadline_ms
        if ms is None:
            return None
        if ms <= 0:
            raise ValueError("deadline_ms must be > 0")
        return (now if now is not None else time.monotonic()) + ms / 1e3

    def health(self, **extra: Any) -> Dict[str, Any]:
        out = {"state": self._state, "capacity": self.queue_capacity}
        out.update(extra)
        return out
