#!/bin/sh
# CI entry (reference analog: paddle/scripts/paddle_build.sh).
# Runs the full gate: native build, test suite on the virtual 8-device
# CPU mesh, API-stability diff, multichip dryrun compile check.
set -e
cd "$(dirname "$0")/.."

echo "== native components =="
sh paddle_tpu/native/build.sh
sh paddle_tpu/native/build_demo.sh

echo "== tests (virtual 8-device CPU mesh) =="
python -m pytest tests/ -q

echo "== API stability =="
python tools/diff_api.py

echo "== multichip dryrun (8 virtual devices) =="
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -c "import __graft_entry__; __graft_entry__.dryrun_multichip(8)"

echo "CI OK"
