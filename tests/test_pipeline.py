"""Device-fed input pipeline tests (reference pattern: reader decorator
tests + buffered_reader semantics)."""

import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.data.pipeline import DeviceFeeder, PyReader


def test_device_feeder_order_and_completeness():
    def reader():
        for i in range(10):
            yield {"x": np.full((2, 2), i, np.float32)}

    feeder = DeviceFeeder(reader, capacity=2)
    seen = [int(np.asarray(b["x"])[0, 0]) for b in feeder]
    assert seen == list(range(10))


def test_device_feeder_prefetches_ahead():
    produced = []
    gate = threading.Event()

    def reader():
        for i in range(6):
            produced.append(i)
            yield {"x": np.zeros((1,), np.float32)}

    feeder = iter(DeviceFeeder(reader, capacity=3).start())
    next(feeder)  # consume one
    deadline = time.time() + 5
    # producer should run ahead: 1 consumed + 3 queued + 1 blocked-in-put
    while len(produced) < 4 and time.time() < deadline:
        time.sleep(0.01)
    assert len(produced) >= 4, f"no prefetch overlap: produced={produced}"
    del gate
    # drain cleanly
    rest = list(feeder)
    assert len(rest) == 5


def test_device_feeder_propagates_reader_error():
    def reader():
        yield {"x": np.zeros((1,), np.float32)}
        raise ValueError("boom in reader")

    feeder = iter(DeviceFeeder(reader, capacity=2).start())
    next(feeder)
    with pytest.raises(ValueError, match="boom in reader"):
        next(feeder)


def test_device_feeder_restartable():
    def reader():
        for i in range(3):
            yield {"x": np.full((1,), i, np.float32)}

    feeder = DeviceFeeder(reader, capacity=2)
    assert len(list(feeder)) == 3
    assert len(list(feeder)) == 3  # fresh pass after exhaustion


def test_pyreader_trains_model():
    B = 4
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data("x", shape=[B, 8], append_batch_size=False)
        y = layers.data("y", shape=[B, 1], append_batch_size=False)
        pred = layers.fc(x, size=1)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)

        rng = np.random.RandomState(0)
        w = rng.rand(8, 1).astype(np.float32)

        def batches():
            r = np.random.RandomState(1)
            for _ in range(20):
                xv = r.rand(B, 8).astype(np.float32)
                yield xv, xv @ w

        reader = PyReader(feed_list=[x, y], capacity=3)
        reader.decorate_batch_generator(batches)
        losses = []
        for feed in reader:
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(lv.reshape(())))
    assert len(losses) == 20
    assert losses[-1] < losses[0]


def test_pyreader_validates_arity():
    reader = PyReader(feed_list=["a", "b"], capacity=1)
    reader.decorate_batch_generator(
        lambda: iter([(np.zeros(1),)]))  # 1 array for 2 vars
    it = iter(reader)
    with pytest.raises(ValueError, match="feed vars"):
        next(it)


def test_bench_synthetic_mode_runs():
    """The fresh-on-device data mode must produce distinct batches per
    step (loss varies) — guards the frozen-feed caveat from round 1."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    B = 4
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data("x", shape=[B, 8], append_batch_size=False)
        pred = layers.fc(x, size=1,
                         param_attr=fluid.ParamAttr(
                             name="w",
                             initializer=fluid.initializer.Constant(1.0)))
        loss = layers.reduce_mean(pred)
        main.global_block().prepend_op(
            "uniform_random", outputs={"Out": ["x"]},
            attrs={"shape": [B, 8], "min": 0.0, "max": 1.0,
                   "dtype": "float32"})
        exe = fluid.Executor()
        exe.run(startup)
        vals = [float(exe.run(main, feed={}, fetch_list=[loss])[0][0])
                for _ in range(3)]
    assert len(set(vals)) == 3, f"batches not fresh: {vals}"
