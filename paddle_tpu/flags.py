"""Typed runtime flags with env-var bridge.

reference: the gflags system (SURVEY.md §5.6) — ~60 DEFINE_* flags read
from env via python __bootstrap__ (python/paddle/fluid/__init__.py:
125-147).  One typed registry replaces point-of-use globals; env vars
`FLAGS_<name>` override defaults at import, matching the reference's
exposure convention.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict


@dataclass
class _FlagDef:
    name: str
    default: Any
    help: str
    type: type


class FlagRegistry:
    def __init__(self):
        self._defs: Dict[str, _FlagDef] = {}
        self._values: Dict[str, Any] = {}

    def define(self, name: str, default, help_: str = ""):
        t = type(default)
        self._defs[name] = _FlagDef(name, default, help_, t)
        env = os.environ.get(f"FLAGS_{name}")
        if env is not None:
            if t is bool:
                self._values[name] = env.lower() in ("1", "true", "yes")
            else:
                self._values[name] = t(env)
        else:
            self._values[name] = default

    def __getattr__(self, name: str):
        values = object.__getattribute__(self, "_values")
        if name in values:
            return values[name]
        raise AttributeError(f"unknown flag {name!r}")

    def __setattr__(self, name: str, value):
        if name in ("_defs", "_values"):
            object.__setattr__(self, name, value)
            return
        if name not in self._defs:
            raise AttributeError(f"unknown flag {name!r}")
        self._values[name] = self._defs[name].type(value)
        if name == "fraction_of_tpu_memory_to_use":
            os.environ["XLA_PYTHON_CLIENT_MEM_FRACTION"] = str(
                self._values[name])

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._values)


FLAGS = FlagRegistry()

# Correctness / debugging (reference: operator.cc:943 FLAGS_check_nan_inf,
# §5.2 determinism flags — XLA is deterministic by default on TPU).
FLAGS.define("check_nan_inf", False,
             "scan every fetch for NaN/Inf after each step")
FLAGS.define("benchmark", False,
             "block after every run for accurate timing "
             "(reference operator.cc:940)")
FLAGS.define("cpu_deterministic", True, "kept for parity; XLA/TPU is "
             "deterministic by default")
# Memory (reference: FLAGS_fraction_of_gpu_memory_to_use & allocator
# strategy — XLA owns HBM; preallocation toggles via env)
FLAGS.define("fraction_of_tpu_memory_to_use", 0.9,
             "exported as XLA_PYTHON_CLIENT_MEM_FRACTION; takes effect "
             "only when set before the first device use")


def _export_mem_fraction():
    # reference: FLAGS_fraction_of_gpu_memory_to_use sizes the buddy
    # allocator chunk (memory/allocation/legacy_allocator.cc); on TPU the
    # XLA client owns HBM preallocation, configured via this env var.
    # Exported only when the user explicitly set the flag, so the XLA
    # default stays in effect otherwise.
    os.environ["XLA_PYTHON_CLIENT_MEM_FRACTION"] = str(
        FLAGS.fraction_of_tpu_memory_to_use)


if "FLAGS_fraction_of_tpu_memory_to_use" in os.environ:
    _export_mem_fraction()
# Executor behavior
FLAGS.define("use_mkldnn", False, "parity no-op (MKLDNN is x86-only)")
FLAGS.define("reader_queue_speed_test_mode", False,
             "non-destructive reader queue for throughput tests: "
             "DeviceFeeder serves its first batch repeatedly so consumer "
             "speed is measured without producer cost (reference "
             "FLAGS_reader_queue_speed_test_mode)")
FLAGS.define("eager_delete_tensor_gb", 0.0,
             "parity no-op; XLA buffer liveness handles eager deletion")
# Host-side parallelism (reference FLAGS_paddle_num_threads sized the CPU
# math thread pool; here it sizes host data-parsing pools — device math
# threads are XLA's business)
FLAGS.define("paddle_num_threads", 2,
             "default worker-thread count for host pipelines "
             "(AsyncExecutor parser shards)")
# Distributed (reference FLAGS_rpc_deadline/max_retry guarded the gRPC
# client; here the deadline bounds jax.distributed bootstrap)
FLAGS.define("rpc_deadline", 180000,
             "multi-host bootstrap timeout in ms "
             "(jax.distributed initialization)")
# Resilience timeouts (docs/RESILIENCE.md has the one table; every knob
# below also answers to the usual FLAGS_<name> env override).  These
# unify the previously scattered knobs: the checkpoint-barrier timeout
# (legacy env PADDLE_TPU_CKPT_BARRIER_TIMEOUT_S still wins, see
# io.barrier_timeout_s), the health-plane heartbeat cadence, and the
# gang supervisor's grace/backoff schedule.
FLAGS.define("ckpt_barrier_timeout_s", 600.0,
             "cross-process checkpoint barrier timeout; legacy env "
             "PADDLE_TPU_CKPT_BARRIER_TIMEOUT_S overrides when set")
FLAGS.define("heartbeat_interval_s", 1.0,
             "health plane: seconds between a rank's KV-store "
             "heartbeats (resilience/health.py)")
FLAGS.define("heartbeat_miss_budget", 5,
             "health plane: a peer whose heartbeat has not changed for "
             "interval*budget seconds is declared lost (PeerLostError)")
FLAGS.define("gang_stall_timeout_s", 0.0,
             "health plane: a peer heartbeating but with a frozen step "
             "counter for this long is declared stalled "
             "(PeerStalledError); 0 disables — the dispatch watchdog "
             "is the primary hung-step detector")
FLAGS.define("supervisor_grace_s", 10.0,
             "gang supervisor: seconds a broken gang's survivors get "
             "between SIGTERM and SIGKILL")
FLAGS.define("supervisor_max_restarts", 3,
             "gang supervisor: total relaunches before GangFailedError")
FLAGS.define("supervisor_backoff_base_s", 1.0,
             "gang supervisor: failure-restart backoff base "
             "(base * 2**failures, deterministic retry_call schedule)")
FLAGS.define("supervisor_backoff_max_s", 30.0,
             "gang supervisor: failure-restart backoff cap")
# Determinism aliases (reference FLAGS_cudnn_deterministic pinned conv
# algos; XLA/TPU kernels are deterministic by construction)
FLAGS.define("cudnn_deterministic", True,
             "parity alias; TPU compilation is deterministic")
FLAGS.define("sync_nccl_allreduce", True,
             "parity alias; GSPMD collectives are synchronous by design")
FLAGS.define("enable_parallel_graph", False,
             "parity no-op; XLA owns scheduling")
FLAGS.define("init_allocated_mem", False,
             "parity no-op; XLA zero-initializes nothing by default and "
             "the framework never reads uninitialized buffers")
FLAGS.define("free_idle_memory", False,
             "parity no-op; XLA allocator retains its HBM arena")
FLAGS.define("inner_op_parallelism", 0,
             "parity no-op; op-internal parallelism is the compiler's")


def init_from_env():
    """Re-read FLAGS_* env vars (the reference's __bootstrap__ pass)."""
    for name, d in FLAGS._defs.items():
        env = os.environ.get(f"FLAGS_{name}")
        if env is not None:
            setattr(FLAGS, name,
                    env.lower() in ("1", "true", "yes")
                    if d.type is bool else d.type(env))
