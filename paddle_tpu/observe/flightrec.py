"""Diagnostic flight recorder — observe pillar 9 (the evidence half).

When something goes wrong at 3 a.m. of a tunnel session — an SLO rule
fires, the dispatch watchdog declares a hang, the process dies on an
unhandled exception — the signals that explain it are all resident in
this process (event log, metrics registry, kept request traces, the
goodput ledger, the latched nonfinite provenance, thread stacks) and
all gone the moment the process is.  The FlightRecorder writes them to
a diagnostic bundle directory at the moment of the trigger:

    <dir>/bundle_<seq>_<reason>/
        MANIFEST.json     trigger, context, wall/monotonic ts, file map
        events_tail.jsonl last N event-log records
        metrics.json      full MetricsRegistry snapshot
        alerts.json       AlertEngine.state() (when attached)
        reqtrace.json     kept-trace chrome export (chrome://tracing)
        goodput.json/.txt ledger report + rendered table
        numerics.json     first-nonfinite provenance (when latched)
        watchdog.json     DispatchWatchdog guarded-region history
        stacks.txt        faulthandler dump of every thread

Triggers: `AlertEngine` firing transitions (`attach_engine`), the
`resilience/watchdog.py` `on_hang` callback (`watchdog_hook` chains an
existing one), unhandled crashes (`install_crash_hooks` wraps
sys.excepthook; an atexit sweep catches a crash whose bundle write was
itself interrupted), and manual `record(reason)`.

Bounded by construction: `min_interval_s` rate-limits bundle writes
(a flapping rule cannot fill the disk), `max_bundles` caps the count,
and `max_bundle_bytes` caps each bundle — capture stops mid-bundle
once the budget is spent, recorded in the manifest (a truncated bundle
that says so beats a full disk).  Every section is best-effort and
independently isolated: a failing source becomes an `errors` entry in
the manifest, never a lost bundle.  Pure host, zero device
dispatches — every source is an existing host-side snapshot surface.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

_SEQ_LOCK = threading.Lock()


def _sanitize(reason: str) -> str:
    out = "".join(c if c.isalnum() or c in "-_" else "_"
                  for c in reason.strip())
    return (out or "trigger")[:48]


class FlightRecorder:
    """Rate-limited, size-bounded diagnostic bundle writer.

        rec = FlightRecorder(dir, registry=fleet.metrics_registry(),
                             event_log=log, tracer=tracer)
        rec.attach_engine(alert_engine)     # bundle on firing alerts
        wd = DispatchWatchdog(..., on_hang=rec.watchdog_hook(prior))
        rec.install_crash_hooks()           # sys.excepthook + atexit

    Sources are all optional; only the attached ones land in bundles.
    `telemetry_fetch` returns the newest StepTelemetry (numerics
    provenance rides it); `goodput` is a GoodputLedger; `watchdog` a
    DispatchWatchdog (its `regions` history is the state captured).
    """

    def __init__(self, directory: str, *, registry=None, event_log=None,
                 tracer=None, goodput=None,
                 telemetry_fetch: Optional[Callable[[], Any]] = None,
                 watchdog=None, min_interval_s: float = 60.0,
                 max_bundles: int = 8,
                 max_bundle_bytes: int = 4 << 20,
                 event_tail_lines: int = 200,
                 clock: Callable[[], float] = time.monotonic):
        self.directory = directory
        self.registry = registry
        self.event_log = event_log
        self.tracer = tracer
        self.goodput = goodput
        self.telemetry_fetch = telemetry_fetch
        self.watchdog = watchdog
        self.alert_engine = None
        self.min_interval_s = float(min_interval_s)
        self.max_bundles = int(max_bundles)
        self.max_bundle_bytes = int(max_bundle_bytes)
        self.event_tail_lines = int(event_tail_lines)
        self.clock = clock
        self._lock = threading.Lock()
        self._seq = 0
        self._last_record_t: Optional[float] = None
        self.bundles: List[str] = []      # written bundle dirs
        self.suppressed = 0               # rate/count-limited triggers
        self._crash_hooks_installed = False
        self._prev_excepthook = None
        self._crash_pending = False       # excepthook fired, bundle
        #                                   write unconfirmed (atexit
        #                                   sweep retries)

    # -- trigger wiring ---------------------------------------------------
    def attach_engine(self, engine) -> "FlightRecorder":
        """Bundle on every alert_firing transition (the engine's hook
        runs on the alert thread — host-only by the engine's own
        contract)."""
        self.alert_engine = engine

        def on_firing(rule, record):
            self.record(f"alert_{rule.id}", context=record)

        engine.add_firing_hook(on_firing)
        return self

    def watchdog_hook(self, prior: Optional[Callable[[Dict[str, Any]],
                                                     None]] = None
                      ) -> Callable[[Dict[str, Any]], None]:
        """An `on_hang` callable for resilience.DispatchWatchdog that
        records a bundle THEN calls `prior` (e.g. Trainer's
        gang-poison closure) — capture first: the poison path may end
        the process."""

        def on_hang(fields: Dict[str, Any]) -> None:
            try:
                self.record(f"hang_{fields.get('kind', 'step')}",
                            context=fields)
            finally:
                if prior is not None:
                    prior(fields)

        return on_hang

    def install_crash_hooks(self) -> "FlightRecorder":
        """Wrap sys.excepthook (bundle on unhandled exception, then
        chain the previous hook) and register an atexit sweep that
        writes the crash bundle if the excepthook's own write never
        completed (a dying interpreter can interrupt it)."""
        if self._crash_hooks_installed:
            return self
        self._crash_hooks_installed = True
        self._prev_excepthook = sys.excepthook

        def hook(exc_type, exc, tb):
            self._crash_pending = True
            try:
                self.record(
                    "crash",
                    context={"exc_type": exc_type.__name__,
                             "exc": str(exc),
                             "traceback": "".join(
                                 traceback.format_exception(
                                     exc_type, exc, tb))[-8192:]},
                    force=True)
                self._crash_pending = False
            finally:
                (self._prev_excepthook or sys.__excepthook__)(
                    exc_type, exc, tb)

        sys.excepthook = hook
        atexit.register(self._atexit_sweep)
        return self

    def uninstall_crash_hooks(self) -> None:
        if not self._crash_hooks_installed:
            return
        self._crash_hooks_installed = False
        if sys.excepthook is not self._prev_excepthook \
                and self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
        try:
            atexit.unregister(self._atexit_sweep)
        except Exception:  # noqa: BLE001
            pass

    def _atexit_sweep(self) -> None:
        if self._crash_pending:
            self.record("crash_atexit", force=True)

    def close(self) -> None:
        self.uninstall_crash_hooks()

    # -- capture ----------------------------------------------------------
    def record(self, reason: str,
               context: Optional[Dict[str, Any]] = None,
               force: bool = False) -> Optional[str]:
        """Write one bundle; returns its directory, or None when
        rate-limited / count-capped (`suppressed` counts those).
        `force` bypasses the rate limit (crash paths — the process is
        ending, the bundle is the whole point) but never the count
        cap."""
        now = self.clock()
        with self._lock:
            if len(self.bundles) >= self.max_bundles:
                self.suppressed += 1
                return None
            if (not force and self._last_record_t is not None
                    and now - self._last_record_t < self.min_interval_s):
                self.suppressed += 1
                return None
            self._last_record_t = now
            self._seq += 1
            seq = self._seq
        bundle = os.path.join(
            self.directory, f"bundle_{seq:03d}_{_sanitize(reason)}")
        os.makedirs(bundle, exist_ok=True)
        manifest: Dict[str, Any] = {
            "reason": reason, "seq": seq,
            "ts": round(time.time(), 3),
            "monotonic": round(now, 3),
            "context": context or {},
            "max_bundle_bytes": self.max_bundle_bytes,
            "files": {}, "errors": {}, "skipped": [],
            "truncated": False,
        }
        budget = [self.max_bundle_bytes]

        def write(name: str, data: bytes) -> None:
            if budget[0] <= 0:
                manifest["skipped"].append(name)
                manifest["truncated"] = True
                return
            if len(data) > budget[0]:
                data = data[:budget[0]]
                manifest["truncated"] = True
            path = os.path.join(bundle, name)
            with open(path, "wb") as f:
                f.write(data)
            budget[0] -= len(data)
            manifest["files"][name] = len(data)

        def section(name: str, fn: Callable[[], Optional[bytes]]
                    ) -> None:
            try:
                data = fn()
            except Exception as e:  # noqa: BLE001 — a dead source must
                manifest["errors"][name] = (  # not lose the bundle
                    f"{type(e).__name__}: {e}")
                return
            if data is not None:
                write(name, data)

        section("events_tail.jsonl", self._events_tail)
        section("metrics.json", self._metrics)
        section("alerts.json", self._alerts)
        section("reqtrace.json", self._reqtrace)
        section("goodput.json", self._goodput_json)
        section("goodput.txt", self._goodput_table)
        section("numerics.json", self._numerics)
        section("watchdog.json", self._watchdog_state)
        section("stacks.txt", self._stacks)
        with open(os.path.join(bundle, "MANIFEST.json"), "w",
                  encoding="utf-8") as f:
            json.dump(manifest, f, indent=1, default=str)
        with self._lock:
            self.bundles.append(bundle)
        if self.event_log is not None:
            try:
                self.event_log.event(
                    "flight_record", reason=reason, path=bundle,
                    seq=seq, truncated=manifest["truncated"],
                    errors=sorted(manifest["errors"]))
            except Exception:  # noqa: BLE001
                pass
        return bundle

    # -- sections (each returns bytes or None) ----------------------------
    def _events_tail(self) -> Optional[bytes]:
        path = getattr(self.event_log, "path", None)
        if not path or not os.path.exists(path):
            return None
        # bounded tail read: never slurp a multi-GB log into memory
        max_bytes = max(self.event_tail_lines * 4096, 1 << 16)
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - max_bytes))
            chunk = f.read()
        lines = chunk.splitlines()
        if size > max_bytes and lines:
            lines = lines[1:]  # first line may be torn by the seek
        return b"\n".join(lines[-self.event_tail_lines:]) + b"\n"

    def _metrics(self) -> Optional[bytes]:
        if self.registry is None:
            return None
        return json.dumps(self.registry.snapshot(), indent=1,
                          default=str).encode("utf-8")

    def _alerts(self) -> Optional[bytes]:
        if self.alert_engine is None:
            return None
        return json.dumps(self.alert_engine.state(), indent=1,
                          default=str).encode("utf-8")

    def _reqtrace(self) -> Optional[bytes]:
        if self.tracer is None:
            return None
        return json.dumps(self.tracer.export_chrome_trace(),
                          default=str).encode("utf-8")

    def _goodput_json(self) -> Optional[bytes]:
        if self.goodput is None:
            return None
        return json.dumps(self.goodput.report(), indent=1,
                          default=str).encode("utf-8")

    def _goodput_table(self) -> Optional[bytes]:
        if self.goodput is None:
            return None
        from .goodput import format_goodput_table

        return format_goodput_table(self.goodput.report()) \
            .encode("utf-8")

    def _numerics(self) -> Optional[bytes]:
        if self.telemetry_fetch is None:
            return None
        tel = self.telemetry_fetch()
        if tel is None or getattr(tel, "first_nonfinite_op", None) \
                is None:
            return None
        return json.dumps(
            {"first_nonfinite_op": tel.first_nonfinite_op,
             "nonfinite_grad_steps": tel.nonfinite_grad_steps,
             "nonfinite_loss_steps": tel.nonfinite_loss_steps,
             "skipped_update_steps": tel.skipped_update_steps,
             "loss_scale": tel.loss_scale},
            indent=1, default=str).encode("utf-8")

    def _watchdog_state(self) -> Optional[bytes]:
        if self.watchdog is None:
            return None
        return json.dumps(
            {"step_deadline_s": self.watchdog.step_deadline_s,
             "compile_grace_s": self.watchdog.compile_grace_s,
             "regions": self.watchdog.regions[-50:]},
            indent=1, default=str).encode("utf-8")

    def _stacks(self) -> Optional[bytes]:
        import faulthandler
        import io

        # faulthandler needs a real fd; round-trip through a temp file
        import tempfile

        with tempfile.TemporaryFile() as f:
            try:
                faulthandler.dump_traceback(file=f, all_threads=True)
            except Exception:  # noqa: BLE001 — fall back to traceback
                buf = io.StringIO()
                for tid, frame in sys._current_frames().items():
                    buf.write(f"# thread {tid}\n")
                    buf.write("".join(traceback.format_stack(frame)))
                return buf.getvalue().encode("utf-8")
            f.seek(0)
            return f.read()

    # -- views ------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"bundles": list(self.bundles),
                    "suppressed": self.suppressed,
                    "max_bundles": self.max_bundles,
                    "min_interval_s": self.min_interval_s,
                    "directory": self.directory}
