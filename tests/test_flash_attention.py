"""Pallas flash attention vs composed XLA reference (interpret mode on
CPU; the same kernel runs compiled on TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _ref_attention(q, k, v, bias=None, scale=None, causal=False):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("nhqd,nhkd->nhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((t_q, t_k), bool)), s, -1e30)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
    return jnp.einsum("nhqk,nhkd->nhqd", p.astype(q.dtype), v)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    import paddle_tpu.ops.pallas.flash_attention as fa

    rng = np.random.RandomState(0)
    n, h, t, d = 1, 2, 256, 128
    q = jnp.asarray(rng.randn(n, h, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(n, h, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(n, h, t, d), jnp.float32)
    got = _interpreted(fa, q, k, v, None, None, causal)
    want = _ref_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_flash_padding_bias():
    import paddle_tpu.ops.pallas.flash_attention as fa

    rng = np.random.RandomState(1)
    n, h, t, d = 2, 1, 128, 128
    q = jnp.asarray(rng.randn(n, h, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(n, h, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(n, h, t, d), jnp.float32)
    lens = np.array([96, 128])
    bias = np.zeros((n, 1, 1, t), np.float32)
    for i, L in enumerate(lens):
        bias[i, :, :, L:] = -1e9
    bias = jnp.asarray(bias)
    got = _interpreted(fa, q, k, v, bias, None, False)
    want = _ref_attention(q, k, v, bias=bias)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("t,causal", [(320, False), (384, True), (320, True)])
def test_flash_nondivisible_tk(t, causal):
    """Regression: t_k % block_k != 0 must mask the padded k-tail
    (ADVICE.md round-1 high finding)."""
    import paddle_tpu.ops.pallas.flash_attention as fa

    rng = np.random.RandomState(3)
    n, h, d = 1, 2, 128
    q = jnp.asarray(rng.randn(n, h, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(n, h, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(n, h, t, d), jnp.float32)
    got = _interpreted(fa, q, k, v, None, None, causal, block_k=256)
    want = _ref_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_flash_grad_matches_reference():
    import paddle_tpu.ops.pallas.flash_attention as fa

    rng = np.random.RandomState(2)
    n, h, t, d = 1, 1, 128, 128
    q = jnp.asarray(rng.randn(n, h, t, d), jnp.float32) * 0.5
    k = jnp.asarray(rng.randn(n, h, t, d), jnp.float32) * 0.5
    v = jnp.asarray(rng.randn(n, h, t, d), jnp.float32) * 0.5

    def loss_flash(q, k, v):
        return jnp.sum(_interpreted(fa, q, k, v, None, None, False) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref_attention(q, k, v) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3)


# -- helpers ---------------------------------------------------------------

import contextlib


@contextlib.contextmanager
def _noop():
    yield


def _interpreted(fa, q, k, v, bias, scale, causal, **kw_extra):
    """Run pallas_flash_attention with the kernel in interpret mode
    (pallas_call(interpret=True)) so it executes on the CPU backend."""
    from jax.experimental import pallas as pl
    import unittest.mock as mock

    real_call = pl.pallas_call

    def patched(kernel, **kw):
        kw["interpret"] = True
        return real_call(kernel, **kw)

    with mock.patch.object(pl, "pallas_call", patched):
        return fa.pallas_flash_attention(q, k, v, bias=bias, scale=scale,
                                         causal=causal, **kw_extra)
