"""DataFeeder: python samples → feed dict of dense arrays.

reference: python/paddle/fluid/data_feeder.py — converts lists of sample
tuples to LoDTensors with lod construction.  Here ragged (lod_level=1)
slots are padded to the longest sequence in the batch (bucketed up to
`pad_to_multiple` to bound XLA retraces) and a `<name>.seq_len` int32
array carries the true lengths (SURVEY.md §5.7 segment-based design).
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from ..core.program import Program, Variable


class DataFeeder:
    def __init__(self, feed_list: Sequence, place=None, program=None,
                 pad_to_multiple: int = 8):
        self.feed_vars: List[Variable] = []
        for v in feed_list:
            if isinstance(v, str):
                from ..core.program import default_main_program

                prog = program or default_main_program()
                v = prog.global_block().var(v)
            self.feed_vars.append(v)
        self.pad_to_multiple = pad_to_multiple

    def feed(self, iterable) -> Dict[str, np.ndarray]:
        """iterable: list of sample tuples aligned with feed_list."""
        rows = list(iterable)
        if not rows:
            raise ValueError("empty batch")
        out: Dict[str, np.ndarray] = {}
        for i, var in enumerate(self.feed_vars):
            column = [row[i] for row in rows]
            if var.lod_level > 1:
                padded, lens, lens2 = self._pad_nested(column, var)
                out[var.name] = padded
                out[f"{var.name}.seq_len"] = lens
                out[f"{var.name}.seq_len2"] = lens2
            elif var.lod_level > 0:
                padded, lens = self._pad(column, var)
                out[var.name] = padded
                out[f"{var.name}.seq_len"] = lens
            else:
                dtype = np.dtype(var.dtype)
                out[var.name] = np.asarray(column, dtype=dtype)
                want = var.shape
                got = out[var.name].shape
                if len(want) == len(got) + 1 and want[-1] == 1:
                    out[var.name] = out[var.name][..., None]
        return out

    def _bucket(self, observed_max: int, declared) -> int:
        """Round a batch's max length up to pad_to_multiple; a static
        declared dim wins (shared by the level-1 and level-2 paths)."""
        m = self.pad_to_multiple
        n = ((observed_max + m - 1) // m) * m
        if declared not in (None, -1, 0):
            return int(declared)
        return n

    def _pad(self, column, var):
        dtype = np.dtype(var.dtype)
        seqs = [np.asarray(s, dtype=dtype) for s in column]
        lens = np.asarray([len(s) for s in seqs], np.int32)
        max_len = self._bucket(
            int(lens.max()),
            var.shape[1] if len(var.shape) >= 2 else None)
        tail = seqs[0].shape[1:]
        padded = np.zeros((len(seqs), max_len) + tail, dtype=dtype)
        for i, s in enumerate(seqs):
            n = min(len(s), max_len)
            padded[i, :n] = s[:n]
        lens = np.minimum(lens, max_len)
        return padded, lens

    def _pad_nested(self, column, var):
        """Nested samples (lod_level=2): each sample is a list of
        sub-sequences; pad to (B, S1, S2, *tail) with level-1 lengths
        (B,) and level-2 lengths (B, S1).  Replaces the reference's
        two-level LoD offset tables (lod_tensor.h:76-104 validity)."""
        dtype = np.dtype(var.dtype)
        nested = [[np.asarray(sub, dtype=dtype) for sub in sample]
                  for sample in column]
        b = len(nested)
        lens1 = np.asarray([len(s) for s in nested], np.int32)
        s1 = self._bucket(
            int(lens1.max()),
            var.shape[1] if len(var.shape) >= 2 else None)
        all_subs = [sub for sample in nested for sub in sample]
        if not all_subs:
            raise ValueError("lod_level=2 batch has no sub-sequences")
        s2 = self._bucket(
            max(len(sub) for sub in all_subs),
            var.shape[2] if len(var.shape) >= 3 else None)
        # feature tail: the declared var shape is authoritative (an
        # empty first sub-sequence must not collapse it); fall back to
        # the first non-empty sub-sequence
        if len(var.shape) >= 4:
            tail = tuple(int(d) for d in var.shape[3:])
        else:
            non_empty = [s for s in all_subs if len(s)]
            tail = non_empty[0].shape[1:] if non_empty else ()
        padded = np.zeros((b, s1, s2) + tail, dtype=dtype)
        lens2 = np.zeros((b, s1), np.int32)
        for i, sample in enumerate(nested):
            for j, sub in enumerate(sample[:s1]):
                n = min(len(sub), s2)
                padded[i, j, :n] = sub[:n]
                lens2[i, j] = n
        lens1 = np.minimum(lens1, s1)
        return padded, lens1, lens2
