"""Profiling.

reference: python/paddle/fluid/profiler.py:221 profiler context manager +
platform/profiler.h RecordEvent ranges + CUPTI DeviceTracer →
chrome-trace (SURVEY.md §5.1).  TPU equivalent: jax.profiler traces
(XPlane/Perfetto, viewable in TensorBoard or ui.perfetto.dev) with the
same op-name annotation convention via TraceAnnotation.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Optional


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: Optional[str] = None,
             profile_path: str = "/tmp/profile"):
    """Drop-in for fluid.profiler.profiler: captures a device+host trace
    for the enclosed region.  With `sorted_key` set (fluid vocabulary:
    "total"/"calls"/"max"/"min"/"ave"), prints the fluid per-op-type
    time table after the trace stops — rows carry fluid op names
    because the executor scopes every op lowering
    (observe/trace.py parses the attribution back out).  `state` is
    accepted for API parity; the trace contains both host and device
    activity."""
    import jax

    os.makedirs(profile_path, exist_ok=True)
    jax.profiler.start_trace(profile_path)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        if sorted_key:
            print_profile_summary(profile_path, sorted_key)


def print_profile_summary(profile_path: str = "/tmp/profile",
                          sorted_key: str = "total"):
    """Parse the newest captured trace under `profile_path` into the
    per-fluid-op time table and print it.  Degrades to a notice (never
    raises) when the trace has no parsable device events — profiling
    must not take down the run it observes."""
    from .observe import trace as _trace

    try:
        table = _trace.format_op_table(profile_path,
                                       sorted_key=sorted_key)
    except Exception as exc:  # noqa: BLE001 — diagnostics only
        print(f"[profiler] trace summary unavailable: {exc}")
        return
    print(table)


def profile_table(profile_path: str = "/tmp/profile"):
    """Programmatic access to the per-op rows of the newest trace
    (list of dicts: op_type/calls/total_ms/avg_ms/max_ms/min_ms/ratio)."""
    from .observe import trace as _trace

    return _trace.op_time_table(profile_path)


@contextlib.contextmanager
def record_event(name: str):
    """RecordEvent RAII range (platform/profiler.h:72): annotates the
    enclosed host region; annotations flow into device traces."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


def start_profiler(state: str = "All",
                   profile_path: str = "/tmp/profile"):
    import jax

    os.makedirs(profile_path, exist_ok=True)
    jax.profiler.start_trace(profile_path)


def stop_profiler(sorted_key: Optional[str] = None,
                  profile_path: str = "/tmp/profile"):
    import jax

    jax.profiler.stop_trace()
    if sorted_key:
        print_profile_summary(profile_path, sorted_key)


def cuda_profiler(*args, **kwargs):
    raise NotImplementedError(
        "cuda_profiler is CUDA-specific; use profiler()/record_event, "
        "which capture TPU device traces")


class Timer:
    """Host-side timer (platform/timer.h) for benchmark reporting."""

    def __init__(self):
        self._start = None
        self.elapsed = 0.0

    def start(self):
        self._start = time.perf_counter()

    def pause(self):
        if self._start is not None:
            self.elapsed += time.perf_counter() - self._start
            self._start = None

    def reset(self):
        self._start = None
        self.elapsed = 0.0
