"""Program/Block/Variable construction + serialization round-trip.

Mirrors the reference's framework unit tests
(python/paddle/fluid/tests/unittests/test_program.py).
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def fresh_programs():
    return fluid.Program(), fluid.Program()


def test_build_simple_program():
    main, startup = fresh_programs()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.fc(x, size=3, act="relu")
    assert y.shape == (-1, 3)
    op_types = [op.type for op in main.global_block().ops]
    assert op_types == ["mul", "elementwise_add", "relu"]
    params = main.all_parameters()
    assert len(params) == 2
    w = [p for p in params if p.shape == (4, 3)]
    assert len(w) == 1
    # startup has matching init ops
    sop_types = [op.type for op in startup.global_block().ops]
    assert len(sop_types) == 2


def test_shape_inference_tracks_batch_dim():
    main, startup = fresh_programs()
    with fluid.program_guard(main, startup):
        x = layers.data(name="img", shape=[1, 28, 28], dtype="float32")
        c = layers.conv2d(x, num_filters=8, filter_size=3, padding=1)
        p = layers.pool2d(c, pool_size=2, pool_stride=2)
    assert c.shape == (-1, 8, 28, 28)
    assert p.shape == (-1, 8, 14, 14)


def test_program_serialization_roundtrip():
    main, startup = fresh_programs()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.fc(x, size=3)
        loss = layers.mean(y)
    d = main.to_dict()
    text = __import__("json").dumps(d)
    restored = fluid.Program.from_dict(__import__("json").loads(text))
    assert [o.type for o in restored.global_block().ops] == \
        [o.type for o in main.global_block().ops]
    assert {v.name for v in restored.list_vars()} == \
        {v.name for v in main.list_vars()}
    assert len(restored.all_parameters()) == len(main.all_parameters())


def test_clone_for_test_strips_backward_and_dropout():
    main, startup = fresh_programs()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        h = layers.fc(x, size=8, act="relu")
        h = layers.dropout(h, dropout_prob=0.5)
        loss = layers.mean(h)
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
    test_prog = main.clone(for_test=True)
    types = [o.type for o in test_prog.global_block().ops]
    assert "backward_marker" not in types
    assert "sgd" not in types
    drop = [o for o in test_prog.global_block().ops if o.type == "dropout"]
    assert drop and drop[0].attrs["is_test"] is True


def test_variable_operator_overloads():
    main, startup = fresh_programs()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = x * 2.0 + 1.0
        z = y - x
        w = z / 2.0
    assert w.shape == (-1, 4)
