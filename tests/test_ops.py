"""Per-op numeric tests (reference: test_<op>_op.py files, 352 of them).

Forward checks against numpy reference math; gradient checks analytic
(jax AD) vs numeric finite differences via the OpTest harness.
"""

import numpy as np
import pytest

from op_test import check_grad, check_output, run_op

rng = np.random.RandomState(42)


# --------------------------------------------------------------------------
# forward correctness
# --------------------------------------------------------------------------

def test_elementwise_add_axis_broadcast():
    x = rng.randn(2, 3, 4).astype(np.float32)
    y = rng.randn(3).astype(np.float32)
    check_output("elementwise_add", {"X": x, "Y": y},
                 x + y.reshape(1, 3, 1), attrs={"axis": 1})


def test_elementwise_trailing_broadcast():
    x = rng.randn(2, 3, 4).astype(np.float32)
    y = rng.randn(4).astype(np.float32)
    check_output("elementwise_mul", {"X": x, "Y": y}, x * y,
                 attrs={"axis": -1})


def test_mul_flattens():
    x = rng.randn(2, 3, 4).astype(np.float32)
    y = rng.randn(12, 5).astype(np.float32)
    check_output("mul", {"X": x, "Y": y},
                 (x.reshape(2, 12) @ y).reshape(2, 5),
                 attrs={"x_num_col_dims": 1, "y_num_col_dims": 1},
                 rtol=1e-4)


def test_matmul_transpose():
    x = rng.randn(2, 4, 3).astype(np.float32)
    y = rng.randn(2, 4, 5).astype(np.float32)
    check_output("matmul", {"X": x, "Y": y},
                 np.einsum("bij,bik->bjk", x, y),
                 attrs={"transpose_X": True}, rtol=1e-4)


def test_softmax_matches_numpy():
    x = rng.randn(3, 7).astype(np.float32)
    e = np.exp(x - x.max(-1, keepdims=True))
    check_output("softmax", {"X": x}, e / e.sum(-1, keepdims=True),
                 rtol=1e-5)


def test_softmax_with_cross_entropy():
    x = rng.randn(4, 5).astype(np.float32)
    lbl = np.array([[0], [3], [2], [4]], dtype=np.int64)
    e = np.exp(x - x.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    expected = -np.log(p[np.arange(4), lbl[:, 0]]).reshape(4, 1)
    check_output("softmax_with_cross_entropy",
                 {"Logits": x, "Label": lbl}, expected, out_slot="Loss",
                 rtol=1e-4)


def test_cross_entropy_ignore_index():
    p = np.full((3, 4), 0.25, dtype=np.float32)
    lbl = np.array([[1], [0], [2]], dtype=np.int64)
    got = run_op("cross_entropy", {"X": p, "Label": lbl},
                 attrs={"ignore_index": 0}, out_slot="Y")
    assert got[1, 0] == 0.0
    np.testing.assert_allclose(got[0, 0], -np.log(0.25), rtol=1e-5)


def test_batch_norm_train_stats():
    x = rng.randn(4, 3, 5, 5).astype(np.float32) * 2 + 1
    scale = np.ones(3, np.float32)
    bias = np.zeros(3, np.float32)
    mean = np.zeros(3, np.float32)
    var = np.ones(3, np.float32)
    y = run_op("batch_norm",
               {"X": x, "Scale": scale, "Bias": bias, "Mean": mean,
                "Variance": var},
               attrs={"momentum": 0.9, "epsilon": 1e-5}, out_slot="Y")
    # normalized output has ~zero mean, unit var per channel
    np.testing.assert_allclose(y.mean(axis=(0, 2, 3)), 0, atol=1e-5)
    np.testing.assert_allclose(y.std(axis=(0, 2, 3)), 1, atol=1e-2)


def test_conv2d_matches_direct():
    x = rng.randn(1, 1, 5, 5).astype(np.float32)
    w = rng.randn(1, 1, 3, 3).astype(np.float32)
    got = run_op("conv2d", {"Input": x, "Filter": w},
                 attrs={"strides": [1, 1], "paddings": [0, 0],
                        "dilations": [1, 1]}, out_slot="Output")
    expected = np.zeros((1, 1, 3, 3), np.float32)
    for i in range(3):
        for j in range(3):
            expected[0, 0, i, j] = (x[0, 0, i:i+3, j:j+3] * w[0, 0]).sum()
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_conv2d_transpose_shape_and_values():
    # output size (H-1)*s - 2p + k
    x = rng.randn(1, 2, 4, 4).astype(np.float32)
    w = rng.randn(2, 3, 3, 3).astype(np.float32)
    got = run_op("conv2d_transpose", {"Input": x, "Filter": w},
                 attrs={"strides": [2, 2], "paddings": [1, 1],
                        "dilations": [1, 1]}, out_slot="Output")
    assert got.shape == (1, 3, 7, 7)
    # scatter-accumulate reference
    expected = np.zeros((1, 3, 9, 9), np.float32)
    for ci in range(2):
        for co in range(3):
            for i in range(4):
                for j in range(4):
                    expected[0, co, 2*i:2*i+3, 2*j:2*j+3] += \
                        x[0, ci, i, j] * w[ci, co]
    expected = expected[:, :, 1:-1, 1:-1]
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-4)


def test_pool2d_avg_exclusive():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    got = run_op("pool2d", {"X": x},
                 attrs={"pooling_type": "avg", "ksize": [2, 2],
                        "strides": [2, 2], "paddings": [0, 0]})
    expected = np.array([[[[2.5, 4.5], [10.5, 12.5]]]], np.float32)
    np.testing.assert_allclose(got, expected)


def test_reduce_ops():
    x = rng.randn(3, 4, 5).astype(np.float32)
    check_output("reduce_sum", {"X": x}, x.sum(axis=1),
                 attrs={"dim": [1], "keep_dim": False}, rtol=1e-5)
    check_output("reduce_max", {"X": x},
                 np.array([x.max()], np.float32).reshape(1,),
                 attrs={"reduce_all": True}, rtol=1e-6)


def test_topk_and_accuracy():
    x = np.array([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1]], np.float32)
    vals = run_op("top_k", {"X": x}, attrs={"k": 1})
    np.testing.assert_allclose(vals, [[0.9], [0.8]])
    idx = run_op("top_k", {"X": x}, attrs={"k": 1}, out_slot="Indices")
    lbl = np.array([[1], [0]], np.int64)
    acc = run_op("accuracy", {"Out": vals, "Indices": idx, "Label": lbl},
                 out_slot="Accuracy")
    np.testing.assert_allclose(acc, [1.0])


def test_lookup_table_padding_idx():
    w = rng.randn(10, 4).astype(np.float32)
    ids = np.array([[1], [0], [5]], np.int64)
    got = run_op("lookup_table", {"Ids": ids, "W": w},
                 attrs={"padding_idx": 0})
    np.testing.assert_allclose(got[0], w[1])
    np.testing.assert_allclose(got[1], 0.0)


def test_dropout_test_mode_scales():
    x = np.ones((4, 4), np.float32)
    got = run_op("dropout", {"X": x},
                 attrs={"dropout_prob": 0.3, "is_test": True})
    np.testing.assert_allclose(got, 0.7, rtol=1e-6)


def test_sequence_pool_masks_padding():
    x = np.ones((2, 4, 3), np.float32)
    x[0, 2:] = 99.0  # padding rows, must be ignored
    sl = np.array([2, 4], np.int32)
    got = run_op("sequence_pool", {"X": x, "SeqLen": sl},
                 attrs={"pooltype": "AVERAGE"})
    np.testing.assert_allclose(got[0], 1.0)
    got_last = run_op("sequence_pool", {"X": x, "SeqLen": sl},
                      attrs={"pooltype": "LAST"})
    np.testing.assert_allclose(got_last[0], 1.0)  # row 1, not padding


def test_sequence_softmax_ignores_padding():
    x = np.zeros((1, 4), np.float32)
    sl = np.array([2], np.int32)
    got = run_op("sequence_softmax", {"X": x, "SeqLen": sl})
    np.testing.assert_allclose(got, [[0.5, 0.5, 0.0, 0.0]], atol=1e-6)


def test_dynamic_lstm_freezes_after_length():
    n, t, h = 2, 5, 3
    x = rng.randn(n, t, 4 * h).astype(np.float32)
    w = rng.randn(h, 4 * h).astype(np.float32) * 0.1
    sl = np.array([2, 5], np.int32)
    hidden = run_op("dynamic_lstm",
                    {"Input": x, "Weight": w, "SeqLen": sl},
                    attrs={"use_peepholes": False}, out_slot="Hidden")
    # row 0 state frozen after step 2
    np.testing.assert_allclose(hidden[0, 2], hidden[0, 1], rtol=1e-6)
    np.testing.assert_allclose(hidden[0, 4], hidden[0, 1], rtol=1e-6)
    assert not np.allclose(hidden[1, 4], hidden[1, 1])


def test_dynamic_gru_reference_convention():
    """h = (1-u)*h_prev + u*candidate (reference
    math/detail/gru_kernel.h:62)."""
    n, t, h = 1, 1, 2
    # zero recurrent weight so gates come purely from the input
    w = np.zeros((h, 3 * h), np.float32)
    big = 100.0  # saturates sigmoid -> u == 1
    x = np.zeros((n, t, 3 * h), np.float32)
    x[0, 0, :h] = big          # update gate -> 1
    x[0, 0, 2 * h:] = 0.5      # candidate pre-activation
    h0 = np.full((n, h), 0.9, np.float32)
    out_h = run_op("dynamic_gru", {"Input": x, "Weight": w, "H0": h0},
                   out_slot="Hidden")
    # u==1 must TAKE the candidate (tanh(0.5)), not keep h_prev
    np.testing.assert_allclose(out_h[0, 0], np.tanh(0.5), rtol=1e-5)


def test_flash_attention_matches_composed():
    n, h, t, d = 2, 2, 8, 4
    q = rng.randn(n, h, t, d).astype(np.float32)
    k = rng.randn(n, h, t, d).astype(np.float32)
    v = rng.randn(n, h, t, d).astype(np.float32)
    scale = d ** -0.5
    logits = np.einsum("nhqd,nhkd->nhqk", q, k) * scale
    e = np.exp(logits - logits.max(-1, keepdims=True))
    w = e / e.sum(-1, keepdims=True)
    expected = np.einsum("nhqk,nhkd->nhqd", w, v)
    check_output("flash_attention", {"Q": q, "K": k, "V": v}, expected,
                 rtol=1e-4, atol=1e-5)
    # causal: position 0 attends only to itself
    got = run_op("flash_attention", {"Q": q, "K": k, "V": v},
                 attrs={"causal": True})
    np.testing.assert_allclose(got[:, :, 0], v[:, :, 0], rtol=1e-4)


def test_flash_attention_grad():
    n, h, t, d = 1, 1, 4, 4
    check_grad("flash_attention",
               {"Q": rng.randn(n, h, t, d).astype(np.float32),
                "K": rng.randn(n, h, t, d).astype(np.float32),
                "V": rng.randn(n, h, t, d).astype(np.float32)},
               "Q", max_relative_error=1e-2)


def test_lr_schedule_noam():
    step = np.array([100.0], np.float32)
    got = run_op("lr_schedule", {"Step": step},
                 attrs={"kind": "noam", "d_model": 512,
                        "warmup_steps": 4000})
    expected = 512 ** -0.5 * min(100 ** -0.5, 100 * 4000 ** -1.5)
    np.testing.assert_allclose(got, [expected], rtol=1e-5)


def test_lr_schedule_piecewise():
    for s, e in [(5, 0.1), (15, 0.01), (25, 0.001)]:
        got = run_op("lr_schedule", {"Step": np.array([float(s)], np.float32)},
                     attrs={"kind": "piecewise",
                            "boundaries": [10.0, 20.0],
                            "values": [0.1, 0.01, 0.001]})
        np.testing.assert_allclose(got, [e], rtol=1e-6)


# --------------------------------------------------------------------------
# gradient checks (analytic vs numeric)
# --------------------------------------------------------------------------

@pytest.mark.parametrize("op,ins,attrs,slot,out_slot", [
    ("relu", {"X": rng.randn(3, 4).astype(np.float32) + 0.1}, {}, "X", "Out"),
    ("tanh", {"X": rng.randn(3, 4).astype(np.float32)}, {}, "X", "Out"),
    ("sigmoid", {"X": rng.randn(3, 4).astype(np.float32)}, {}, "X", "Out"),
    ("softmax", {"X": rng.randn(2, 5).astype(np.float32)}, {}, "X", "Out"),
    ("elementwise_mul",
     {"X": rng.randn(2, 3).astype(np.float32),
      "Y": rng.randn(3).astype(np.float32)}, {"axis": 1}, "X", "Out"),
    ("mul", {"X": rng.randn(2, 3).astype(np.float32),
             "Y": rng.randn(3, 4).astype(np.float32)},
     {"x_num_col_dims": 1, "y_num_col_dims": 1}, "Y", "Out"),
    ("layer_norm", {"X": rng.randn(2, 6).astype(np.float32),
                    "Scale": rng.rand(6).astype(np.float32) + 0.5,
                    "Bias": rng.randn(6).astype(np.float32)},
     {"begin_norm_axis": 1}, "X", "Y"),
    ("softmax_with_cross_entropy",
     {"Logits": rng.randn(3, 4).astype(np.float32),
      "Label": np.array([[0], [2], [1]], np.int64)}, {}, "Logits", "Loss"),
])
def test_grad_matches_numeric(op, ins, attrs, slot, out_slot):
    check_grad(op, ins, slot, attrs=attrs, out_slot=out_slot)


def test_conv2d_grad():
    check_grad("conv2d",
               {"Input": rng.randn(1, 2, 5, 5).astype(np.float32),
                "Filter": rng.randn(3, 2, 3, 3).astype(np.float32) * 0.5},
               "Filter",
               attrs={"strides": [1, 1], "paddings": [1, 1],
                      "dilations": [1, 1]},
               out_slot="Output", max_relative_error=1e-2)


def test_pool2d_with_index_argmax():
    """Mask must contain real flattened-H*W argmax positions
    (ADVICE.md round-1 finding)."""
    x = rng.randn(2, 3, 6, 6).astype(np.float32)
    attrs = {"ksize": [2, 2], "strides": [2, 2]}
    outs = run_op("pool2d_with_index", {"X": x}, attrs=attrs)
    mask = run_op("pool2d_with_index", {"X": x}, attrs=attrs,
                  out_slot="Mask")
    # numpy reference
    want_o = np.zeros((2, 3, 3, 3), np.float32)
    want_m = np.zeros((2, 3, 3, 3), np.int64)
    for n in range(2):
        for c in range(3):
            for i in range(3):
                for j in range(3):
                    win = x[n, c, 2*i:2*i+2, 2*j:2*j+2]
                    a = np.argmax(win)
                    want_o[n, c, i, j] = win.flat[a]
                    di, dj = divmod(a, 2)
                    want_m[n, c, i, j] = (2*i + di) * 6 + (2*j + dj)
    np.testing.assert_allclose(outs, want_o)
    np.testing.assert_array_equal(mask, want_m)


def test_interpolate_align_corners_bilinear():
    """align_corners=True must use scale (in-1)/(out-1) — the reference
    default (operators/interpolate_op.cc)."""
    x = rng.randn(1, 1, 4, 4).astype(np.float32)
    got = run_op("interpolate", {"X": x},
                 attrs={"out_h": 7, "out_w": 7,
                        "interp_method": "bilinear",
                        "align_corners": True})
    ys = np.linspace(0, 3, 7)
    want = np.zeros((1, 1, 7, 7), np.float32)
    for i, sy in enumerate(ys):
        for j, sx in enumerate(ys):
            y0, x0 = int(np.floor(sy)), int(np.floor(sx))
            y1, x1 = min(y0 + 1, 3), min(x0 + 1, 3)
            wy, wx = sy - y0, sx - x0
            want[0, 0, i, j] = (
                x[0, 0, y0, x0] * (1-wy) * (1-wx)
                + x[0, 0, y0, x1] * (1-wy) * wx
                + x[0, 0, y1, x0] * wy * (1-wx)
                + x[0, 0, y1, x1] * wy * wx)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_fused_label_smooth_ce_matches_composition():
    """softmax_with_cross_entropy(label_smooth_eps=eps) must equal the
    one_hot → label_smooth → soft-label CE composition it replaces
    (models/transformer.py loss path)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.core.registry import OpContext, get_op_impl

    rng = np.random.RandomState(0)
    B, V = 6, 37
    logits = jnp.asarray(rng.randn(B, V).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, V, (B, 1)).astype(np.int64))
    eps = 0.1
    impl = get_op_impl("softmax_with_cross_entropy")
    ctx = OpContext(jax.random.PRNGKey(0))
    fused = impl(ctx, {"Logits": [logits], "Label": [labels]},
                 {"label_smooth_eps": eps})["Loss"][0]
    onehot = jax.nn.one_hot(labels[:, 0], V)
    smooth = (1 - eps) * onehot + eps / V
    soft = impl(ctx, {"Logits": [logits], "Label": [smooth]},
                {"soft_label": True})["Loss"][0]
    np.testing.assert_allclose(np.asarray(fused), np.asarray(soft),
                               rtol=1e-5, atol=1e-6)
