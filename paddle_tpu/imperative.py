"""Imperative (eager / proto-dygraph) mode.

TPU-native analog of the reference's imperative embryo
(reference: paddle/fluid/imperative/ — VarBase with var+grad slots
(layer.h:83), OpBase, Tracer::Trace recording ops and building grad ops
on the fly (tracer.h:51,57), autograd RunBackward (layer.h:103);
python/paddle/fluid/imperative/layers.py PyLayer).

Mapping: jax is already eager — each traced op executes immediately on
device.  The reference Tracer's grad-op construction becomes a tape of
(op impl, input VarBases, attrs) entries; `VarBase.backward()` walks the
tape in reverse applying per-op `jax.vjp`, accumulating cotangents into
`VarBase.grad` — autodiff without grad-op makers, matching how the
static-graph side replaces append_backward with jax AD.

    with imperative.guard():
        x = imperative.to_variable(np_x)
        fc = imperative.FC(64, act="relu")
        y = fc(x)
        loss = imperative.trace_op("reduce_mean", {"X": [y]})
        loss.backward()
        g = fc.w.grad
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .core.registry import OpContext, get_op_impl


class VarBase:
    """Eager variable: value + grad slot (reference imperative/layer.h:83).
    """

    def __init__(self, value, stop_gradient: bool = False,
                 name: Optional[str] = None):
        import jax.numpy as jnp

        self.value = jnp.asarray(value)
        self.stop_gradient = stop_gradient
        self.grad = None
        self.name = name
        # autograd bookkeeping (set by the tracer for op outputs)
        self._producer: Optional["_TapeEntry"] = None
        self._out_index: int = 0

    # -- tensor-ish surface ---------------------------------------------
    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        return str(self.value.dtype)

    def numpy(self) -> np.ndarray:
        return np.asarray(self.value)

    def clear_gradient(self):
        self.grad = None

    def __repr__(self):
        return (f"VarBase(shape={self.shape}, dtype={self.dtype}, "
                f"stop_gradient={self.stop_gradient})")

    # -- autograd -------------------------------------------------------
    def backward(self):
        """Reverse the tape from this scalar-ish output
        (reference layer.h:103 RunBackward)."""
        tracer = _active_tracer()
        if tracer is None:
            raise RuntimeError("backward() outside imperative.guard()")
        tracer.run_backward(self)


class _TapeEntry:
    __slots__ = ("op_type", "ins", "attrs", "in_vars", "out_vars", "fn")

    def __init__(self, op_type, ins, attrs, in_vars, out_vars, fn):
        self.op_type = op_type
        self.ins = ins
        self.attrs = attrs
        self.in_vars = in_vars    # [VarBase] (differentiable positions)
        self.out_vars = out_vars  # [VarBase]
        self.fn = fn              # arrays-in → arrays-out pure function


class Tracer:
    """Eager op recorder (reference imperative/tracer.h:51 Tracer::Trace:
    execute the op now, remember how to differentiate it)."""

    def __init__(self):
        self.tape: List[_TapeEntry] = []
        self._op_counter = 0

    # -- forward --------------------------------------------------------
    def trace_op(self, op_type: str, ins: Dict[str, Sequence[VarBase]],
                 attrs: Optional[Dict[str, Any]] = None,
                 out_slots: Optional[Sequence[str]] = None) -> Any:
        import jax

        impl = get_op_impl(op_type)
        attrs = dict(attrs or {})
        self._op_counter += 1
        ctx = OpContext(jax.random.PRNGKey(self._op_counter),
                        op_index=self._op_counter)

        # differentiable leaves: VarBases without stop_gradient
        diff_vars: List[VarBase] = []
        slots = {k: list(v) for k, v in ins.items()}
        positions = []  # (slot, idx) aligned with diff_vars
        for slot, vs in slots.items():
            for i, v in enumerate(vs):
                if isinstance(v, VarBase) and not v.stop_gradient:
                    positions.append((slot, i))
                    diff_vars.append(v)

        def fn(diff_arrays):
            call_ins = {
                slot: [v.value if isinstance(v, VarBase) else v
                       for v in vs]
                for slot, vs in slots.items()
            }
            for (slot, i), a in zip(positions, diff_arrays):
                call_ins[slot][i] = a
            outs = impl(ctx, call_ins, attrs)
            keys = out_slots or sorted(outs)
            return tuple(o for k in keys for o in outs[k])

        out_arrays = fn(tuple(v.value for v in diff_vars))
        out_vars = []
        entry = _TapeEntry(op_type, slots, attrs, diff_vars, out_vars, fn)
        for i, a in enumerate(out_arrays):
            ov = VarBase(a)
            ov._producer = entry
            ov._out_index = i
            out_vars.append(ov)
        if diff_vars:
            self.tape.append(entry)
        if len(out_vars) == 1:
            return out_vars[0]
        return out_vars

    # -- backward -------------------------------------------------------
    def run_backward(self, root: VarBase):
        import jax
        import jax.numpy as jnp

        cot: Dict[int, Any] = {id(root): jnp.ones_like(root.value)}
        # the tape is already in execution order; reverse it
        for entry in reversed(self.tape):
            out_cots = [cot.get(id(ov)) for ov in entry.out_vars]
            if all(c is None for c in out_cots):
                continue
            out_cots = tuple(
                c if c is not None else jnp.zeros_like(ov.value)
                for c, ov in zip(out_cots, entry.out_vars))
            primals = tuple(v.value for v in entry.in_vars)
            _out, vjp_fn = jax.vjp(entry.fn, primals)
            (in_cots,) = vjp_fn(out_cots)
            for v, g in zip(entry.in_vars, in_cots):
                if id(v) in cot:
                    cot[id(v)] = cot[id(v)] + g
                else:
                    cot[id(v)] = g
                # leaves (params / user vars) accumulate into .grad
                if v._producer is None:
                    v.grad = (g if v.grad is None else v.grad + g)
        # non-leaf grads are discarded like the reference (only VarBases
        # the user holds references to matter)

    def reset(self):
        self.tape = []


_tracer_stack: List[Tracer] = []


def _active_tracer() -> Optional[Tracer]:
    return _tracer_stack[-1] if _tracer_stack else None


@contextlib.contextmanager
def guard(place=None):
    """Enable eager mode (reference python dygraph guard)."""
    t = Tracer()
    _tracer_stack.append(t)
    try:
        yield t
    finally:
        _tracer_stack.pop()


def to_variable(value, stop_gradient: bool = False) -> VarBase:
    return VarBase(value, stop_gradient=stop_gradient)


def trace_op(op_type: str, ins, attrs=None, out_slots=None):
    tracer = _active_tracer()
    if tracer is None:
        raise RuntimeError("trace_op outside imperative.guard()")
    return tracer.trace_op(op_type, ins, attrs, out_slots)


class Layer:
    """Eager layer base (reference imperative/layers.py PyLayer / Layer):
    hold parameters, define forward()."""

    def __init__(self, name: Optional[str] = None):
        if name is None:
            # distinct default names per INSTANCE (the deterministic
            # init seeds derive from the name, so two unnamed layers of
            # one class must not share weights) — via core.unique_name
            # so unique_name.guard() resets them for in-process
            # rebuilds, same as static-graph layers (CLAUDE.md gotcha)
            from .core import unique_name

            name = unique_name.generate(self.__class__.__name__)
        self._name = name
        self._params: Dict[str, VarBase] = {}
        self._sublayers: Dict[str, "Layer"] = {}

    def __setattr__(self, key, value):
        if isinstance(value, Layer):
            self.__dict__.setdefault("_sublayers", {})[key] = value
        super().__setattr__(key, value)

    def create_parameter(self, name: str, shape, dtype="float32",
                         initializer=None, fan_in=None) -> VarBase:
        if initializer is None:
            import zlib

            # stable digest, NOT hash(): str hashing is salted per
            # process and would make default inits non-reproducible
            seed = zlib.crc32(f"{self._name}.{name}".encode())
            rng = np.random.RandomState(seed % (2 ** 31))
            # default fan heuristic fits (in, out)-style FC weights;
            # layers with other layouts (conv OIHW) pass fan_in
            if fan_in is None:
                fan_in = int(np.prod(shape[:-1])) or 1
            value = (rng.randn(*shape) / np.sqrt(fan_in)).astype(dtype)
        else:
            value = np.asarray(initializer, dtype=dtype)
        p = VarBase(value, name=f"{self._name}.{name}")
        self._params[name] = p
        return p

    def parameters(self) -> List[VarBase]:
        out = list(self._params.values())
        for sub in self._sublayers.values():
            out.extend(sub.parameters())
        return out

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        return self.forward(*args, **kwargs)


class FC(Layer):
    """Eager fully-connected layer (the reference embryo's test layer)."""

    def __init__(self, input_dim: int, size: int, act: Optional[str] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.w = self.create_parameter("w", [input_dim, size])
        self.b = self.create_parameter(
            "b", [size], initializer=np.zeros([size], np.float32))
        self._act = act

    def forward(self, x: VarBase) -> VarBase:
        y = trace_op("mul", {"X": [x], "Y": [self.w]},
                     {"x_num_col_dims": 1, "y_num_col_dims": 1})
        y = trace_op("elementwise_add", {"X": [y], "Y": [self.b]},
                     {"axis": 1})
        if self._act:
            y = trace_op(self._act, {"X": [y]})
        return y


class Conv2D(Layer):
    """Eager conv layer over the static-graph conv2d kernel (NCHW,
    filter (C_out, C_in/groups, kH, kW))."""

    def __init__(self, num_channels: int, num_filters: int,
                 filter_size: int, stride: int = 1, padding: int = 0,
                 groups: int = 1, act: Optional[str] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        k = ([filter_size, filter_size]
             if isinstance(filter_size, int) else list(filter_size))
        # OIHW: fan_in is C_in/groups * kH * kW (the FC heuristic would
        # count num_filters and drop kW)
        self.w = self.create_parameter(
            "w", [num_filters, num_channels // groups] + k,
            fan_in=(num_channels // groups) * int(np.prod(k)))
        self.b = self.create_parameter(
            "b", [num_filters],
            initializer=np.zeros([num_filters], np.float32))
        self._attrs = {"strides": stride, "paddings": padding,
                       "groups": groups}
        self._act = act

    def forward(self, x: VarBase) -> VarBase:
        y = trace_op("conv2d", {"Input": [x], "Filter": [self.w]},
                     self._attrs, out_slots=["Output"])
        y = trace_op("elementwise_add", {"X": [y], "Y": [self.b]},
                     {"axis": 1})
        if self._act:
            y = trace_op(self._act, {"X": [y]})
        return y


class Embedding(Layer):
    """Eager embedding lookup (lookup_table kernel)."""

    def __init__(self, size, name: Optional[str] = None):
        super().__init__(name)
        self.w = self.create_parameter("w", list(size))

    def forward(self, ids: VarBase) -> VarBase:
        return trace_op("lookup_table",
                        {"Ids": [ids], "W": [self.w]},
                        {"padding_idx": -1})


# ---------------------------------------------------------------------------
# Eager optimizers (reference dygraph pattern: backward() then
# optimizer.minimize applies updates directly to parameter VarBases).
# Updates route through the SAME registered sgd/adam kernels the static
# graph uses (ops/optim.py), so eager and static trajectories match
# exactly — no second optimizer formula to maintain.
# ---------------------------------------------------------------------------

class EagerOptimizer:
    def step(self, parameters: Sequence[VarBase]):
        import jax

        ctx = OpContext(jax.random.PRNGKey(0), 0)
        for p in parameters:
            if p.grad is not None:
                self._apply(ctx, p)

    def _apply(self, ctx, p: VarBase):
        raise NotImplementedError

    def minimize(self, loss: VarBase, parameters: Sequence[VarBase]):
        """backward + apply + clear grads + reset the tape (the tape
        must not grow across steps)."""
        loss.backward()
        self.step(parameters)
        for p in parameters:
            p.clear_gradient()
        tracer = _active_tracer()
        if tracer is not None:
            tracer.reset()
        return loss


class SGDOptimizer(EagerOptimizer):
    def __init__(self, learning_rate: float = 0.01):
        import jax.numpy as jnp

        self.lr = jnp.asarray([learning_rate], jnp.float32)

    def _apply(self, ctx, p: VarBase):
        outs = get_op_impl("sgd")(
            ctx, {"Param": [p.value], "Grad": [p.grad],
                  "LearningRate": [self.lr]}, {})
        p.value = outs["ParamOut"][0]


class AdamOptimizer(EagerOptimizer):
    # per-parameter state keyed by WEAK reference: dead parameters drop
    # their moments (no device-memory leak across model rebuilds), and
    # a recycled id can never inherit a dead parameter's state
    def __init__(self, learning_rate: float = 1e-3, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8):
        import jax.numpy as jnp

        self.lr = jnp.asarray([learning_rate], jnp.float32)
        self.attrs = {"beta1": beta1, "beta2": beta2, "epsilon": epsilon}
        self._state: Dict[int, Any] = {}  # id -> (weakref(p), slots)

    def _apply(self, ctx, p: VarBase):
        import weakref

        import jax.numpy as jnp

        key = id(p)
        hit = self._state.get(key)
        if hit is None or hit[0]() is not p:
            slots = {"Moment1": jnp.zeros_like(p.value),
                     "Moment2": jnp.zeros_like(p.value),
                     "Beta1Pow": jnp.asarray([self.attrs["beta1"]],
                                             jnp.float32),
                     "Beta2Pow": jnp.asarray([self.attrs["beta2"]],
                                             jnp.float32)}
            hit = (weakref.ref(
                p, lambda _ref, k=key, s=self._state: s.pop(k, None)),
                slots)
            self._state[key] = hit
        slots = hit[1]
        outs = get_op_impl("adam")(
            ctx, {"Param": [p.value], "Grad": [p.grad],
                  "LearningRate": [self.lr],
                  "Moment1": [slots["Moment1"]],
                  "Moment2": [slots["Moment2"]],
                  "Beta1Pow": [slots["Beta1Pow"]],
                  "Beta2Pow": [slots["Beta2Pow"]]}, dict(self.attrs))
        p.value = outs["ParamOut"][0]
        slots["Moment1"] = outs["Moment1Out"][0]
        slots["Moment2"] = outs["Moment2Out"][0]
        slots["Beta1Pow"] = outs["Beta1PowOut"][0]
        slots["Beta2Pow"] = outs["Beta2PowOut"][0]
