"""Built-in datasets.

reference: python/paddle/dataset/ — mnist, cifar, uci_housing, imdb,
imikolov, movielens, wmt14/16 auto-download readers.  This environment
is zero-egress, so downloading is impossible; instead each dataset has
BOTH:

- a real-format file parser (`reader_creator` / `data_dir=` arg) that
  ingests the dataset's actual on-disk format — MNIST idx-ubyte .gz
  (dataset/mnist.py:43 reader_creator), CIFAR python-pickle tar
  (dataset/cifar.py reader_creator), UCI housing whitespace table with
  the reference's avg/min-max normalization (uci_housing.py:68
  load_data) — used whenever files are present (point `data_dir` or
  $PADDLE_DATASET_HOME at them), and
- a deterministic synthetic generator with the real shapes/dtypes/label
  spaces as the zero-egress fallback.

The reader contract is the reference's: zero-arg callable yielding
samples.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np


def _dataset_home(sub):
    home = os.environ.get("PADDLE_DATASET_HOME")
    return os.path.join(home, sub) if home else None


def _find_archive(data_dir, sub, names):
    """Probe `data_dir` (or $PADDLE_DATASET_HOME/sub) for the first
    existing archive filename in `names`; None when absent."""
    if data_dir is None:
        data_dir = _dataset_home(sub)
    if data_dir is None:
        return None
    for name in names:
        p = os.path.join(data_dir, name)
        if os.path.exists(p):
            return p
    return None


def _synthetic_classification(n, feature_shape, num_classes, seed,
                              flatten=False):
    rng = np.random.RandomState(seed)
    centers = rng.randn(num_classes, *feature_shape).astype(np.float32)

    def reader():
        r = np.random.RandomState(seed + 1)
        for _ in range(n):
            y = int(r.randint(num_classes))
            x = centers[y] + 0.5 * r.randn(*feature_shape).astype(np.float32)
            if flatten:
                x = x.reshape(-1)
            yield x, y

    return reader


class mnist:
    """28x28 grayscale digits, labels 0-9 (dataset/mnist.py)."""

    TRAIN_IMAGES = "train-images-idx3-ubyte.gz"
    TRAIN_LABELS = "train-labels-idx1-ubyte.gz"
    TEST_IMAGES = "t10k-images-idx3-ubyte.gz"
    TEST_LABELS = "t10k-labels-idx1-ubyte.gz"

    @staticmethod
    def reader_creator(image_filename, label_filename):
        """Parse the REAL idx-ubyte format (dataset/mnist.py:43): gzip'd
        big-endian headers (magic 2051 images / 2049 labels), raw u8
        pixels scaled to [-1, 1) exactly like the reference
        (`images / 255.0 * 2.0 - 1.0`); yields (flat f32 784, int)."""

        def reader():
            with gzip.open(image_filename, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                if magic != 2051:
                    raise IOError(
                        f"bad idx3 magic {magic} in {image_filename}")
                images = np.frombuffer(f.read(n * rows * cols),
                                       np.uint8).reshape(n, rows * cols)
            with gzip.open(label_filename, "rb") as f:
                magic, ln = struct.unpack(">II", f.read(8))
                if magic != 2049:
                    raise IOError(
                        f"bad idx1 magic {magic} in {label_filename}")
                labels = np.frombuffer(f.read(ln), np.uint8)
            if ln != n:
                raise IOError(f"mnist: {n} images but {ln} labels")
            imgs = images.astype(np.float32) / 255.0 * 2.0 - 1.0
            for i in range(n):
                yield imgs[i], int(labels[i])

        return reader

    @staticmethod
    def _files_in(data_dir, img, lbl):
        if data_dir is None:
            data_dir = _dataset_home("mnist")
        if data_dir is None:
            return None
        pi, pl = os.path.join(data_dir, img), os.path.join(data_dir, lbl)
        return (pi, pl) if (os.path.exists(pi)
                            and os.path.exists(pl)) else None

    @staticmethod
    def train(n=60000, seed=0, data_dir=None):
        real = mnist._files_in(data_dir, mnist.TRAIN_IMAGES,
                               mnist.TRAIN_LABELS)
        if real:
            return mnist.reader_creator(*real)
        return _synthetic_classification(n, (1, 28, 28), 10, seed)

    @staticmethod
    def test(n=10000, seed=7, data_dir=None):
        real = mnist._files_in(data_dir, mnist.TEST_IMAGES,
                               mnist.TEST_LABELS)
        if real:
            return mnist.reader_creator(*real)
        return _synthetic_classification(n, (1, 28, 28), 10, seed)


class cifar:
    @staticmethod
    def reader_creator(filename, sub_name):
        """Parse the REAL python-pickle tar format (dataset/cifar.py
        reader_creator): members whose name contains `sub_name` hold
        dicts with b'data' (N, 3072 u8) and b'labels'/b'fine_labels';
        pixels scale to [0, 1] f32 like the reference."""

        def reader():
            with tarfile.open(filename, mode="r") as f:
                names = [m.name for m in f if sub_name in m.name]
                for name in sorted(names):
                    batch = pickle.load(f.extractfile(name),
                                        encoding="bytes")
                    data = batch[b"data"]
                    labels = batch.get(b"labels",
                                       batch.get(b"fine_labels"))
                    if labels is None:
                        raise IOError(f"no labels in {name}")
                    for row, label in zip(data, labels):
                        yield ((np.asarray(row, np.uint8) / 255.0)
                               .astype(np.float32), int(label))

        return reader

    @staticmethod
    def _tar(data_dir, fname):
        return _find_archive(data_dir, "cifar", (fname,))

    @staticmethod
    def train10(n=50000, seed=1, data_dir=None):
        p = cifar._tar(data_dir, "cifar-10-python.tar.gz")
        if p:
            return cifar.reader_creator(p, "data_batch")
        return _synthetic_classification(n, (3, 32, 32), 10, seed)

    @staticmethod
    def test10(n=10000, seed=8, data_dir=None):
        p = cifar._tar(data_dir, "cifar-10-python.tar.gz")
        if p:
            return cifar.reader_creator(p, "test_batch")
        return _synthetic_classification(n, (3, 32, 32), 10, seed)

    @staticmethod
    def train100(n=50000, seed=2, data_dir=None):
        p = cifar._tar(data_dir, "cifar-100-python.tar.gz")
        if p:
            return cifar.reader_creator(p, "train")
        return _synthetic_classification(n, (3, 32, 32), 100, seed)


class flowers:
    @staticmethod
    def train(n=6149, seed=3):
        return _synthetic_classification(n, (3, 224, 224), 102, seed)

    @staticmethod
    def test(n=1020, seed=9):
        return _synthetic_classification(n, (3, 224, 224), 102, seed)


class uci_housing:
    """13 features → scalar price (dataset/uci_housing.py)."""

    FEATURE_NUM = 14

    @staticmethod
    def load_data(filename, feature_num=14, ratio=0.8):
        """Parse the REAL whitespace table and normalize exactly like
        the reference (uci_housing.py:68): per-feature
        (x - avg) / (max - min) on the 13 inputs, 80/20 split."""
        data = np.fromfile(filename, sep=" ")
        data = data.reshape(data.shape[0] // feature_num, feature_num)
        maxs, mins = data.max(axis=0), data.min(axis=0)
        avgs = data.sum(axis=0) / data.shape[0]
        for i in range(feature_num - 1):
            data[:, i] = (data[:, i] - avgs[i]) / (maxs[i] - mins[i])
        offset = int(data.shape[0] * ratio)
        return data[:offset], data[offset:]

    @staticmethod
    def _real_reader(data_dir, part):
        if data_dir is None:
            data_dir = _dataset_home("uci_housing")
        if data_dir is None:
            return None
        p = os.path.join(data_dir, "housing.data")
        if not os.path.exists(p):
            return None
        tr, te = uci_housing.load_data(p)
        rows = tr if part == "train" else te

        def reader():
            for row in rows:
                yield (row[:-1].astype(np.float32),
                       np.asarray([row[-1]], np.float32))

        return reader

    @staticmethod
    def train(n=404, seed=4, data_dir=None):
        real = uci_housing._real_reader(data_dir, "train")
        if real:
            return real
        rng = np.random.RandomState(seed)
        w = rng.randn(13).astype(np.float32)

        def reader():
            r = np.random.RandomState(seed + 1)
            for _ in range(n):
                x = r.randn(13).astype(np.float32)
                y = float(x @ w + 0.1 * r.randn())
                yield x, np.asarray([y], np.float32)

        return reader

    @staticmethod
    def test(n=404, seed=4, data_dir=None):
        real = uci_housing._real_reader(data_dir, "test")
        if real:
            return real
        # forward the SAME data_dir: a typo'd explicit dir must not
        # re-resolve the env home and hand back real train data
        return uci_housing.train(n, seed, data_dir=data_dir)


class imdb:
    """Variable-length token sequences, binary sentiment
    (dataset/imdb.py)."""

    word_dict_size = 5147
    TAR = "aclImdb_v1.tar.gz"

    # -- real-format path (dataset/imdb.py tokenize/build_dict/
    # reader_creator over the aclImdb tar: pos label 0, neg label 1) --
    @staticmethod
    def tokenize(tar_path, pattern):
        import re
        import string

        rx = re.compile(pattern)
        with tarfile.open(tar_path) as tarf:
            for tf in tarf:
                if rx.match(tf.name):
                    text = tarf.extractfile(tf).read().rstrip(b"\n\r")
                    text = text.translate(
                        None, string.punctuation.encode("latin-1"))
                    yield text.lower().split()

    # the reference's corpus pattern/cutoff (dataset/imdb.py word_dict):
    # labeled train+test docs only (unsup/ and urls_*.txt excluded),
    # words kept above 150 occurrences
    DICT_PATTERN = r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$"

    @staticmethod
    def build_dict(tar_path, pattern=DICT_PATTERN, cutoff=150):
        freq: dict = {}
        for doc in imdb.tokenize(tar_path, pattern):
            for w in doc:
                freq[w] = freq.get(w, 0) + 1
        words = sorted((w for w, c in freq.items() if c > cutoff),
                       key=lambda w: (-freq[w], w))
        idx = {w: i for i, w in enumerate(words)}
        idx[b"<unk>"] = len(idx)
        return idx

    @staticmethod
    def reader_creator(tar_path, pos_pattern, neg_pattern, word_idx):
        unk = word_idx[b"<unk>"]

        def reader():
            for pattern, label in ((pos_pattern, 0), (neg_pattern, 1)):
                for doc in imdb.tokenize(tar_path, pattern):
                    yield [word_idx.get(w, unk) for w in doc], label

        return reader

    @staticmethod
    def _tar(data_dir):
        return _find_archive(data_dir, "imdb", (imdb.TAR,))

    @staticmethod
    def word_dict(data_dir=None):
        p = imdb._tar(data_dir)
        if p:
            return imdb.build_dict(p)
        return {i: i for i in range(imdb.word_dict_size)}

    @staticmethod
    def train(word_dict=None, n=25000, seed=5, max_len=200,
              data_dir=None):
        p = imdb._tar(data_dir)
        if p:
            if word_dict is None:
                word_dict = imdb.build_dict(p)
            return imdb.reader_creator(
                p, r"aclImdb/train/pos/.*\.txt$",
                r"aclImdb/train/neg/.*\.txt$", word_dict)
        vocab = imdb.word_dict_size

        def reader():
            r = np.random.RandomState(seed)
            for _ in range(n):
                length = int(r.randint(10, max_len))
                label = int(r.randint(2))
                # class-dependent token bias so models can actually learn
                lo = 0 if label == 0 else vocab // 2
                tokens = r.randint(lo, lo + vocab // 2,
                                   size=(length,)).astype(np.int64)
                yield tokens, label

        return reader

    @staticmethod
    def test(word_dict=None, n=25000, seed=11, max_len=200,
             data_dir=None):
        p = imdb._tar(data_dir)
        if p:
            if word_dict is None:
                word_dict = imdb.build_dict(p)
            return imdb.reader_creator(
                p, r"aclImdb/test/pos/.*\.txt$",
                r"aclImdb/test/neg/.*\.txt$", word_dict)
        # no real tar found for THIS data_dir: fall back to synthetic
        # without re-resolving the env home (a typo'd explicit dir must
        # not silently hand back real train data as the test set)
        return imdb.train(word_dict, n, seed, max_len,
                          data_dir=data_dir)


class imikolov:
    """N-gram LM windows (dataset/imikolov.py)."""

    @staticmethod
    def build_dict(min_word_freq=50):
        return {i: i for i in range(2073)}

    @staticmethod
    def train(word_dict=None, n=5, seed=6, samples=100000):
        vocab = len(word_dict) if word_dict else 2073

        def reader():
            r = np.random.RandomState(seed)
            for _ in range(samples):
                yield tuple(int(x) for x in r.randint(0, vocab, size=(n,)))

        return reader

class movielens:
    """MovieLens 1-M (dataset/movielens.py): `ml-1m.zip` holding
    movies.dat / users.dat / ratings.dat ('::'-separated, latin-1).
    Sample layout is the reference's `usr.value() + mov.value() +
    [[rating]]`:

        [user_id, gender(0=M,1=F), age_bucket_idx, job_id,
         movie_id, [category ids], [title word ids], [rating]]

    with rating scaled `* 2 - 5` (movielens.py:160) and the age mapped
    through `age_table` (movielens.py:41).  Divergence: the category /
    title-word vocabularies are SORTED for determinism (the reference
    enumerates python-set iteration order, movielens.py:132-139).
    data_dir may hold the zip or the extracted ml-1m/ files."""

    age_table = [1, 18, 25, 35, 45, 50, 56]

    @staticmethod
    def _read_members(data_dir):
        """→ {name: text lines} for movies/users/ratings, from
        ml-1m.zip or a plain directory (None when absent)."""
        import io
        import zipfile

        if data_dir is None:
            return None
        names = ("movies.dat", "users.dat", "ratings.dat")
        zp = os.path.join(data_dir, "ml-1m.zip")
        out = {}
        if os.path.exists(zp):
            with zipfile.ZipFile(zp) as z:
                for n in names:
                    with z.open(f"ml-1m/{n}") as f:
                        out[n] = io.TextIOWrapper(
                            io.BytesIO(f.read()),
                            encoding="latin-1").readlines()
            return out
        for n in names:
            p = os.path.join(data_dir, n)
            if not os.path.exists(p):
                p2 = os.path.join(data_dir, "ml-1m", n)
                p = p2 if os.path.exists(p2) else p
            if not os.path.exists(p):
                return None
            with open(p, encoding="latin-1") as f:
                out[n] = f.readlines()
        return out

    @staticmethod
    def load_meta(data_dir):
        """Parse movies.dat/users.dat → (movie_info, user_info,
        title_dict, categories_dict).  movie_info[id] = (id, [cat ids],
        [title word ids]); user_info[id] = (id, gender01, age_idx,
        job)."""
        import re

        members = movielens._read_members(data_dir)
        if members is None:
            raise IOError(
                f"movielens: no ml-1m.zip or *.dat under {data_dir!r} "
                f"(pass data_dir= or set $PADDLE_DATASET_HOME)")
        return movielens._parse_meta(members)

    @staticmethod
    def _parse_meta(members):
        import re
        pattern = re.compile(r"^(.*)\((\d+)\)$")
        raw_movies = []
        title_words, categories = set(), set()
        for line in members["movies.dat"]:
            if not line.strip():
                continue
            mid, title, cats = line.strip().split("::")
            cats = cats.split("|")
            m = pattern.match(title)
            title = m.group(1) if m else title
            words = [w.lower() for w in title.split()]
            raw_movies.append((int(mid), cats, words))
            title_words.update(words)
            categories.update(cats)
        title_dict = {w: i for i, w in enumerate(sorted(title_words))}
        cat_dict = {c: i for i, c in enumerate(sorted(categories))}
        movie_info = {
            mid: (mid, [cat_dict[c] for c in cats],
                  [title_dict[w] for w in words])
            for mid, cats, words in raw_movies
        }
        user_info = {}
        for line in members["users.dat"]:
            if not line.strip():
                continue
            uid, gender, age, job = line.strip().split("::")[:4]
            user_info[int(uid)] = (
                int(uid), 0 if gender == "M" else 1,
                movielens.age_table.index(int(age)), int(job))
        return movie_info, user_info, title_dict, cat_dict

    @staticmethod
    def reader_creator(data_dir, is_test=False, test_ratio=0.1,
                       rand_seed=0):
        # parse the archive ONCE, lazily at first use, shared by every
        # epoch's reader() call (the real ml-1m is ~24 MB; re-parsing
        # per epoch would dominate data time)
        cache = []

        def reader():
            if not cache:
                members = movielens._read_members(data_dir)
                if members is None:
                    raise IOError(
                        f"movielens: no ml-1m.zip or *.dat under "
                        f"{data_dir!r}")
                movie_info, user_info, _, _ = \
                    movielens._parse_meta(members)
                cache.append((members["ratings.dat"], movie_info,
                              user_info))
            ratings, movie_info, user_info = cache[0]
            r = np.random.RandomState(rand_seed)
            for line in ratings:
                if not line.strip():
                    continue
                take = (r.random_sample() < test_ratio) == is_test
                if not take:
                    continue
                uid, mid, rating = line.strip().split("::")[:3]
                usr = user_info[int(uid)]
                mov = movie_info[int(mid)]
                yield (list(usr) + [mov[0], mov[1], mov[2]]
                       + [[float(rating) * 2 - 5.0]])

        return reader

    @staticmethod
    def _dir(data_dir):
        return data_dir or _dataset_home("movielens")

    @staticmethod
    def _present(data_dir):
        """Cheap existence probe (no archive read)."""
        if data_dir is None:
            return False
        if os.path.exists(os.path.join(data_dir, "ml-1m.zip")):
            return True
        return all(
            os.path.exists(os.path.join(data_dir, n))
            or os.path.exists(os.path.join(data_dir, "ml-1m", n))
            for n in ("movies.dat", "users.dat", "ratings.dat"))

    @staticmethod
    def _synthetic(n, seed, user_vocab=100, movie_vocab=200):
        def reader():
            r = np.random.RandomState(seed)
            for _ in range(n):
                uid = int(r.randint(1, user_vocab))
                mid = int(r.randint(1, movie_vocab))
                cats = [int(c) for c in r.randint(0, 18, r.randint(1, 4))]
                title = [int(t) for t in r.randint(0, 500,
                                                   r.randint(1, 8))]
                rating = float((uid + mid) % 5 + 1) * 2 - 5.0
                yield [uid, int(r.randint(0, 2)), int(r.randint(0, 7)),
                       int(r.randint(0, 21)), mid, cats, title,
                       [rating]]

        return reader

    @staticmethod
    def train(n=9000, seed=14, data_dir=None, test_ratio=0.1):
        d = movielens._dir(data_dir)
        if movielens._present(d):
            return movielens.reader_creator(d, is_test=False,
                                            test_ratio=test_ratio)
        return movielens._synthetic(n, seed)

    @staticmethod
    def test(n=1000, seed=15, data_dir=None, test_ratio=0.1):
        d = movielens._dir(data_dir)
        if movielens._present(d):
            return movielens.reader_creator(d, is_test=True,
                                            test_ratio=test_ratio)
        return movielens._synthetic(n, seed)

    @staticmethod
    def max_user_id(data_dir=None):
        _, u, _, _ = movielens.load_meta(movielens._dir(data_dir))
        return max(u)

    @staticmethod
    def max_movie_id(data_dir=None):
        m, _, _, _ = movielens.load_meta(movielens._dir(data_dir))
        return max(m)

    @staticmethod
    def max_job_id(data_dir=None):
        _, u, _, _ = movielens.load_meta(movielens._dir(data_dir))
        return max(v[3] for v in u.values())

    @staticmethod
    def get_movie_title_dict(data_dir=None):
        _, _, t, _ = movielens.load_meta(movielens._dir(data_dir))
        return t

    @staticmethod
    def movie_categories(data_dir=None):
        _, _, _, c = movielens.load_meta(movielens._dir(data_dir))
        return sorted(c)

    @staticmethod
    def batches_for_model(reader, batch_size, title_len=12):
        """Adapt raw movielens samples to models/recommender.py feeds:
        titles pad/truncate to `title_len` with a companion seq_len,
        category list is pooled away (the model's movie tower consumes
        id + title only, like the reference book test)."""

        def gen():
            buf = []
            for s in reader():
                buf.append(s)
                if len(buf) == batch_size:
                    yield movielens._to_feed(buf, title_len)
                    buf = []

        return gen

    @staticmethod
    def _to_feed(buf, title_len):
        b = len(buf)
        title = np.zeros((b, title_len), np.int64)
        tlen = np.zeros((b,), np.int32)
        for i, s in enumerate(buf):
            words = s[6][:title_len]
            title[i, :len(words)] = words
            tlen[i] = max(1, len(words))
        col = lambda j, dt: np.asarray([s[j] for s in buf],
                                       dt).reshape(b, 1)
        return {
            "user_id": col(0, np.int64),
            "gender_id": col(1, np.int64),
            "age_id": col(2, np.int64),
            "job_id": col(3, np.int64),
            "movie_id": col(4, np.int64),
            "title_ids": title,
            "title_ids.seq_len": tlen,
            "score": np.asarray([s[7][0] for s in buf],
                                np.float32).reshape(b, 1),
        }

class wmt14:
    """WMT14 en→fr subset (dataset/wmt14.py): a tar holding
    `*/src.dict`, `*/trg.dict` (one token per line, line number = id)
    and tab-separated parallel text under `train/train`, `test/test`.
    Sample = (src_ids with <s>/<e> framing, <s>+trg_ids,
    trg_ids+<e>); pairs with either side >80 tokens are dropped
    (wmt14.py:107) and OOV maps to UNK_IDX=2 (wmt14.py:53)."""

    START, END, UNK = "<s>", "<e>", "<unk>"
    UNK_IDX = 2

    @staticmethod
    def _tar(data_dir):
        return _find_archive(data_dir, "wmt14",
                             ("wmt14.tgz", "wmt14.tar.gz", "wmt14.tar"))

    @staticmethod
    def _dicts(tar_path, dict_size):
        def to_dict(fd, size):
            return {line.decode("utf-8").strip(): i
                    for i, line in enumerate(fd) if i < size}

        with tarfile.open(tar_path) as f:
            src = [m.name for m in f if m.name.endswith("src.dict")]
            trg = [m.name for m in f if m.name.endswith("trg.dict")]
            if len(src) != 1 or len(trg) != 1:
                raise IOError(
                    f"wmt14: expected exactly one src.dict and one "
                    f"trg.dict in {tar_path!r}")
            return (to_dict(f.extractfile(src[0]), dict_size),
                    to_dict(f.extractfile(trg[0]), dict_size))

    @staticmethod
    def reader_creator(tar_path, file_name, dict_size):
        cache = []  # dicts parsed once, shared by every epoch

        def reader():
            if not cache:
                cache.append(wmt14._dicts(tar_path, dict_size))
            src_dict, trg_dict = cache[0]
            with tarfile.open(tar_path) as f:
                names = [m.name for m in f
                         if m.name.endswith(file_name)]
                for name in names:
                    for line in f.extractfile(name):
                        parts = line.decode("utf-8").strip().split("\t")
                        if len(parts) != 2:
                            continue
                        src_ids = [src_dict.get(w, wmt14.UNK_IDX)
                                   for w in ([wmt14.START]
                                             + parts[0].split()
                                             + [wmt14.END])]
                        trg_ids = [trg_dict.get(w, wmt14.UNK_IDX)
                                   for w in parts[1].split()]
                        if len(src_ids) > 80 or len(trg_ids) > 80:
                            continue
                        yield (src_ids,
                               [trg_dict[wmt14.START]] + trg_ids,
                               trg_ids + [trg_dict[wmt14.END]])

        return reader

    @staticmethod
    def _synthetic(dict_size, n, seed):
        def reader():
            r = np.random.RandomState(seed)
            for _ in range(n):
                ln = int(r.randint(4, 12))
                body = r.randint(3, dict_size, ln)
                src = [0] + [int(x) for x in body] + [1]
                # learnable structure: trg token = succ(src token),
                # wrapped past the 3 reserved ids
                trg = [3 + (int(x) - 2) % (dict_size - 3) for x in body]
                yield src, [0] + trg, trg + [1]

        return reader

    @staticmethod
    def train(dict_size, data_dir=None, n=2000, seed=16):
        tp = wmt14._tar(data_dir)
        if tp:
            return wmt14.reader_creator(tp, "train/train", dict_size)
        return wmt14._synthetic(dict_size, n, seed)

    @staticmethod
    def test(dict_size, data_dir=None, n=200, seed=17):
        tp = wmt14._tar(data_dir)
        if tp:
            return wmt14.reader_creator(tp, "test/test", dict_size)
        return wmt14._synthetic(dict_size, n, seed)

    @staticmethod
    def get_dict(dict_size, reverse=True, data_dir=None):
        tp = wmt14._tar(data_dir)
        if tp is None:
            raise IOError("wmt14.get_dict needs the real tar "
                          "(data_dir= or $PADDLE_DATASET_HOME)")
        src, trg = wmt14._dicts(tp, dict_size)
        if reverse:
            src = {i: w for w, i in src.items()}
            trg = {i: w for w, i in trg.items()}
        return src, trg


class wmt16:
    """WMT16 en↔de multimodal subset (dataset/wmt16.py): a tar holding
    tab-separated `wmt16/train|val|test` (en \\t de).  Vocabularies are
    built from the TRAIN split by descending frequency with <s>, <e>,
    <unk> reserved as ids 0/1/2 (wmt16.py:63-84, built in memory here
    instead of cached dict files); both sides frame with <s>/<e> ids
    from the source dict (same indices in both, wmt16.py:119-122);
    src_lang 'en' or 'de' picks the column."""

    START, END, UNK = "<s>", "<e>", "<unk>"

    @staticmethod
    def _tar(data_dir):
        return _find_archive(data_dir, "wmt16",
                             ("wmt16.tar.gz", "wmt16.tgz", "wmt16.tar"))

    @staticmethod
    def build_dict(tar_path, dict_size, lang):
        from collections import defaultdict

        freq = defaultdict(int)
        with tarfile.open(tar_path) as f:
            for line in f.extractfile("wmt16/train"):
                parts = line.decode("utf-8").strip().split("\t")
                if len(parts) != 2:
                    continue
                sen = parts[0] if lang == "en" else parts[1]
                for w in sen.split():
                    freq[w] += 1
        words = [wmt16.START, wmt16.END, wmt16.UNK]
        # descending frequency; ties broken by insertion order like the
        # reference's sorted(iteritems, key=count)
        for w, _c in sorted(freq.items(), key=lambda kv: kv[1],
                            reverse=True):
            if len(words) == dict_size:
                break
            words.append(w)
        return {w: i for i, w in enumerate(words)}

    @staticmethod
    def reader_creator(tar_path, file_name, src_dict_size,
                       trg_dict_size, src_lang):
        cache = []  # vocab built once (two full train-split scans),
        # shared by every epoch's reader() call

        def reader():
            if not cache:
                trg_lang = "de" if src_lang == "en" else "en"
                cache.append((
                    wmt16.build_dict(tar_path, src_dict_size, src_lang),
                    wmt16.build_dict(tar_path, trg_dict_size,
                                     trg_lang)))
            src_dict, trg_dict = cache[0]
            start, end, unk = (src_dict[wmt16.START],
                               src_dict[wmt16.END],
                               src_dict[wmt16.UNK])
            src_col = 0 if src_lang == "en" else 1
            with tarfile.open(tar_path) as f:
                for line in f.extractfile(file_name):
                    parts = line.decode("utf-8").strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src_ids = ([start]
                               + [src_dict.get(w, unk)
                                  for w in parts[src_col].split()]
                               + [end])
                    trg_ids = [trg_dict.get(w, unk)
                               for w in parts[1 - src_col].split()]
                    yield (src_ids, [start] + trg_ids, trg_ids + [end])

        return reader

    @staticmethod
    def _creator(split, src_dict_size, trg_dict_size, src_lang,
                 data_dir, n, seed):
        if src_lang not in ("en", "de"):
            raise ValueError(f"wmt16: src_lang must be 'en' or 'de', "
                             f"got {src_lang!r}")
        tp = wmt16._tar(data_dir)
        if tp:
            return wmt16.reader_creator(tp, f"wmt16/{split}",
                                        src_dict_size, trg_dict_size,
                                        src_lang)
        return wmt14._synthetic(min(src_dict_size, trg_dict_size), n,
                                seed)

    @staticmethod
    def train(src_dict_size, trg_dict_size, src_lang="en",
              data_dir=None, n=2000, seed=18):
        return wmt16._creator("train", src_dict_size, trg_dict_size,
                              src_lang, data_dir, n, seed)

    @staticmethod
    def test(src_dict_size, trg_dict_size, src_lang="en",
             data_dir=None, n=200, seed=19):
        return wmt16._creator("test", src_dict_size, trg_dict_size,
                              src_lang, data_dir, n, seed)

    @staticmethod
    def validation(src_dict_size, trg_dict_size, src_lang="en",
                   data_dir=None, n=200, seed=20):
        return wmt16._creator("val", src_dict_size, trg_dict_size,
                              src_lang, data_dir, n, seed)


def padded_nmt_batches(reader, batch_size, max_src_len, max_trg_len,
                       drop_too_long=True):
    """Adapt (src_ids, trg_ids, trg_next_ids) NMT samples (wmt14/wmt16)
    to models/machine_translation.seq_to_seq_net feeds: pad to the
    static max lengths with companion seq_len vars (the padded+seq_len
    replacement for the reference's LoD batching, SURVEY.md §5.7).
    drop_too_long=False TRUNCATES over-length samples instead of
    dropping them."""

    def gen():
        buf = []
        for src, trg, nxt in reader():
            if drop_too_long and (len(src) > max_src_len
                                  or len(trg) > max_trg_len):
                continue
            buf.append((src, trg, nxt))
            if len(buf) == batch_size:
                yield _nmt_feed(buf, max_src_len, max_trg_len)
                buf = []

    return gen


def _nmt_feed(buf, max_src_len, max_trg_len):
    b = len(buf)
    src = np.zeros((b, max_src_len), np.int64)
    trg = np.zeros((b, max_trg_len), np.int64)
    nxt = np.zeros((b, max_trg_len), np.int64)
    slen = np.zeros((b,), np.int32)
    tlen = np.zeros((b,), np.int32)
    for i, (s, t, nx) in enumerate(buf):
        s, t = s[:max_src_len], t[:max_trg_len]
        nx = nx[:max_trg_len]
        src[i, :len(s)] = s
        trg[i, :len(t)] = t
        nxt[i, :len(nx)] = nx
        slen[i], tlen[i] = len(s), len(t)
    return {"src_word_id": src, "src_word_id.seq_len": slen,
            "trg_word_id": trg, "trg_word_id.seq_len": tlen,
            "trg_next_id": nxt}
