"""Observe pillar 7 (ISSUE 15): per-request tracing + unified metrics.

The load-bearing properties:

- **guard discipline** (the ISSUE 4 / PR 11 pattern): tracing enabled
  at sample_rate=0 adds ZERO device dispatches, zero retraces, and the
  decode executable lowers byte-identically with or without a tracer —
  spans are host timestamps at queue boundaries only.
- **tail-based keep**: sampling can never hide a pathology — slow,
  errored, preempted, failed-over, hedged traces survive sample_rate=0.
- **exposition exactness**: LatencyHistogram log bins map onto
  cumulative Prometheus `le` buckets bin-for-bin (prefix sums, +Inf ==
  count, sum == sum_ms) — a scraped histogram IS the serving histogram.
- **one metrics plane**: a Fleet/engine/trainer registry scrape
  exposes families from every subsystem over localhost HTTP, and a
  sick collector degrades to `observe_collector_up 0`, never a dead
  scrape.
"""

from __future__ import annotations

import json
import re
import time
import urllib.request

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu import observe
from paddle_tpu.observe import (MetricsRegistry, MetricsServer,
                                ReqTracer, RequestTrace)
from paddle_tpu.observe.monitoring import LatencyHistogram
from paddle_tpu.observe.registry import (MetricFamily, counter, gauge,
                                         histogram,
                                         serving_stats_collector,
                                         standard_collectors,
                                         telemetry_collector,
                                         tracer_collector)


# ---------------------------------------------------------------------------
# RequestTrace / ReqTracer mechanics
# ---------------------------------------------------------------------------

def test_trace_spans_and_phase_breakdown():
    tr = ReqTracer(sample_rate=1.0)
    t = tr.new_trace("decode")
    now = time.monotonic()
    t.add("join_wait", now - 0.020, now - 0.010, replica_id=0, slot=1)
    t.add("dispatch", now - 0.010, now - 0.004, kind="prefill",
          replica_id=0, slot=1)
    t.add("dispatch", now - 0.004, now, kind="decode", replica_id=0,
          slot=1, iterations=2)
    assert tr.finish(t) is True
    assert t.keep_reason == "head_sampled"
    ph = t.phase_ms()
    assert ph["join_wait"] == pytest.approx(10.0, rel=0.2)
    assert ph["dispatch"] == pytest.approx(10.0, rel=0.2)
    assert t.replica_ids() == [0]
    # per-phase aggregates are exact over finished traces
    summ = tr.phase_summary()
    assert summ["dispatch"]["count"] == 2
    assert summ["join_wait"]["count"] == 1
    wire = t.as_dict()
    assert wire["trace_id"] == t.trace_id
    assert len(wire["spans"]) == 3
    # double-finish is idempotent (failover paths can race a late
    # engine resolution)
    assert tr.finish(t) is True
    assert tr.snapshot()["finished"] == 1


def test_head_sampling_deterministic_and_ring_bound():
    tr = ReqTracer(sample_rate=0.25, capacity=8)
    kept = 0
    for _ in range(100):
        t = tr.new_trace()
        if tr.finish(t):
            kept += 1
    assert kept == 25  # deterministic 1-in-4, not probabilistic
    assert tr.snapshot()["ring_size"] == 8  # bounded: oldest evicted
    assert len(tr.traces()) == 8
    with pytest.raises(ValueError):
        ReqTracer(sample_rate=1.5)
    with pytest.raises(ValueError):
        ReqTracer(capacity=0)


def test_tail_keep_slow_error_and_marks():
    tr = ReqTracer(sample_rate=0.0, slow_keep_ms=5.0)
    # a fast clean trace at sample_rate=0 is dropped
    assert tr.finish(tr.new_trace()) is False
    # an error trace survives
    terr = tr.new_trace()
    assert tr.finish(terr, error=RuntimeError("boom")) is True
    assert terr.keep_reason == "error"
    assert terr.error == "RuntimeError: boom"
    # each pathology marker survives
    for mark in ("failover", "hedge", "abandoned", "preempt",
                 "evacuated"):
        t = tr.new_trace()
        t.point(mark, replica_id=0)
        assert tr.finish(t) is True, mark
        assert t.keep_reason == mark
    # a slow trace survives
    slow = tr.new_trace()
    slow.t_create -= 0.050  # 50 ms old
    assert tr.finish(slow) is True
    assert slow.keep_reason == "slow"
    snap = tr.snapshot()
    assert snap["kept"] == snap["tail_kept"] == 7
    assert snap["errors"] == 1


def test_max_spans_bound():
    tr = ReqTracer(max_spans=4)
    t = tr.new_trace()
    now = time.monotonic()
    for i in range(10):
        t.add("dispatch", now, now, slot=i)
    assert len(t.spans) == 4
    assert t.dropped_spans == 6
    tr.finish(t)
    assert t.as_dict()["dropped_spans"] == 6


def test_chrome_export_rows_and_metadata(tmp_path):
    tr = ReqTracer()
    t = tr.new_trace("fleet_decode")
    now = time.monotonic()
    t.add("route", now, now + 0.001)                       # router row
    t.add("dispatch", now + 0.001, now + 0.005, replica_id=0)
    t.add("failover", now + 0.005, now + 0.006,
          from_replica=0, to_replica=1)                    # router row
    t.add("dispatch", now + 0.006, now + 0.010, replica_id=1)
    tr.finish(t)
    path = str(tmp_path / "trace.json")
    out = tr.export_chrome_trace(path)
    with open(path) as f:
        assert json.load(f) == out
    xs = [e for e in out["traceEvents"] if e["ph"] == "X"]
    # rows: pid 0 = router, pid replica_id+1 = replica
    assert {e["pid"] for e in xs} == {0, 1, 2}
    names = {e["pid"]: set() for e in xs}
    for e in xs:
        names[e["pid"]].add(e["name"])
        assert e["args"]["trace_id"] == t.trace_id
        assert e["dur"] >= 1.0  # chrome drops 0-width spans
    assert names[0] == {"route", "failover"}
    meta = {e["args"]["name"] for e in out["traceEvents"]
            if e["ph"] == "M"}
    assert meta == {"router", "replica 0", "replica 1"}
    # empty window exports a valid empty trace
    assert tr.export_chrome_trace(window_s=0.0)["traceEvents"] == []


def test_chrome_export_kv_transfer_flow_events():
    """The disagg handoff pin (ISSUE 18): one trace_id draws the whole
    journey — prefill-worker row, a kv_transfer arrow, decode-worker
    row.  The exporter emits a chrome flow-event pair (ph "s" on the
    SOURCE replica's row at t0, ph "f" bp "e" on the DESTINATION
    replica's row at t1) for every kv_transfer span that names both
    endpoints, so the page hop renders as an arrow between rows."""
    tr = ReqTracer()
    t = tr.new_trace("disagg")
    now = time.monotonic()
    t.add("dispatch", now, now + 0.004, replica_id=0)      # prefill row
    t.add("kv_transfer", now + 0.004, now + 0.006,
          from_replica=0, to_replica=1, pages=3, bytes=4096)
    t.add("dispatch", now + 0.006, now + 0.012, replica_id=1)  # decode
    tr.finish(t)
    out = tr.export_chrome_trace()
    evs = out["traceEvents"]
    # the span itself stays a router-row slice (no replica_id attr)
    kv_x = [e for e in evs if e["ph"] == "X"
            and e["name"] == "kv_transfer"]
    assert len(kv_x) == 1 and kv_x[0]["pid"] == 0
    starts = [e for e in evs if e["ph"] == "s"]
    finishes = [e for e in evs if e["ph"] == "f"]
    assert len(starts) == len(finishes) == 1
    s, f = starts[0], finishes[0]
    assert s["name"] == f["name"] == "kv_transfer"
    assert s["id"] == f["id"]                  # one arrow, paired
    assert s["tid"] == f["tid"]
    assert f["bp"] == "e"                      # bind to enclosing slice
    assert s["pid"] == 1                       # replica 0's row
    assert f["pid"] == 2                       # replica 1's row
    assert s["ts"] < f["ts"]
    assert s["args"]["trace_id"] == t.trace_id
    # both endpoint rows exist: one trace spans prefill AND decode rows
    assert {e["pid"] for e in evs if e["ph"] == "X"} == {0, 1, 2}
    # a kv_transfer span missing an endpoint draws no arrow (and does
    # not crash the exporter)
    t2 = tr.new_trace("disagg")
    t2.add("kv_transfer", now, now + 0.001, from_replica=0,
           to_replica=None)
    tr.finish(t2)
    evs2 = tr.export_chrome_trace()["traceEvents"]
    assert len([e for e in evs2 if e["ph"] == "s"]) == 1  # unchanged


# ---------------------------------------------------------------------------
# Engine integration (single-shot serving + decode)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def mlp_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("reqtrace_mlp"))
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = fluid.layers.data("x", shape=[8], append_batch_size=True)
        pred = fluid.layers.fc(x, size=4)
        exe = fluid.Executor()
        exe.run(startup)
        fluid.io.save_inference_model(d, ["x"], [pred], exe,
                                      main_program=main)
    return d


def test_serving_engine_trace_phases(mlp_dir):
    from paddle_tpu.serving import BucketConfig, ServingEngine

    tracer = ReqTracer(sample_rate=1.0)
    engine = ServingEngine(mlp_dir, {"x": np.zeros(8, np.float32)},
                           buckets=BucketConfig((1, 2)),
                           max_wait_ms=1.0, tracer=tracer)
    engine.start()
    for i in range(4):
        engine.infer({"x": np.full(8, i, np.float32)}, timeout_s=60)
    engine.close()
    traces = tracer.traces()
    assert len(traces) == 4
    for t in traces:
        names = t.span_names()
        assert names == ["queue_wait", "batch_form", "dispatch"], names
        qw, bf, dp = t.spans
        # spans tile the request's lifetime: queue_wait ends exactly
        # where batch_form begins, batch_form where dispatch begins
        assert qw.t1 == bf.t0 and bf.t1 == dp.t0
        assert dp.attrs["batch"] >= 1 and bf.attrs["bucket"] in (1, 2)
        assert t.finished and t.error is None
    summ = tracer.phase_summary()
    assert summ["dispatch"]["count"] >= 1  # batched: <= 4 dispatches
    assert summ["queue_wait"]["count"] == 4


def _tiny_lm():
    from paddle_tpu.models.decoder_lm import DecoderLM

    return DecoderLM(vocab_size=32, n_layer=1, n_head=2, d_model=16,
                     d_inner=32, kv_dtype="float32", seed=3)


def _tiny_engine(tracer=None, num_pages=None):
    from paddle_tpu.serving.decode import DecodeConfig, DecodeEngine

    cfg = DecodeConfig(num_slots=2, page_size=4, max_len=32,
                       num_pages=num_pages or 16, prefill_buckets=(8,),
                       decode_chunk=2, kv_dtype="float32")
    return DecodeEngine(_tiny_lm(), cfg, memory_budget_bytes=False,
                        tracer=tracer)


def test_decode_trace_tail_keeps_preemption():
    """sample_rate=0 on a pool sized to force preemption: the ONLY
    kept traces are the preempted ones (tail keep), and they carry the
    join_wait/dispatch span taxonomy plus the preempt marker."""
    from paddle_tpu.models.decoder_lm import make_prompts

    tracer = ReqTracer(sample_rate=0.0)
    # 2 slots x 8 pages/slot worst case = 16; 9 pages forces eviction
    eng = _tiny_engine(tracer=tracer, num_pages=9).start()
    prompts = make_prompts(4, 32, min_len=3, max_len=6, seed=1)
    futs = [eng.submit(p, max_new_tokens=18, priority=i)
            for i, p in enumerate(prompts)]
    for f in futs:
        f.result(300)
    eng.close()
    assert eng.stats.preemptions >= 1
    kept = tracer.traces()
    assert kept, "preempted traces must survive sample_rate=0"
    for t in kept:
        assert t.keep_reason == "preempt"
        names = t.span_names()
        assert "preempt" in names and "join_wait" in names \
            and "dispatch" in names, names
        # a preempted request re-joins: two join_wait spans
        assert len(t.find("join_wait")) >= 2, names
    # the phase aggregates saw EVERY request, not just the kept ones
    assert tracer.phase_summary()["join_wait"]["count"] >= \
        len(prompts) + len(kept)
    assert tracer.snapshot()["finished"] == len(prompts)


def test_tracing_zero_device_overhead_guard_discipline():
    """The acceptance pin: tracing enabled at sample_rate=0 performs
    the same device work as no tracer at all — equal dispatch counts,
    zero retraces, and the decode executable's lowering is
    byte-identical (spans are host timestamps; nothing reaches the
    traced computation)."""
    from paddle_tpu.models.decoder_lm import make_prompts

    prompts = make_prompts(3, 32, min_len=3, max_len=6, seed=2)

    def run(tracer):
        eng = _tiny_engine(tracer=tracer).start()
        snap = observe.runtime_stats.snapshot()
        futs = [eng.submit(p, max_new_tokens=6) for p in prompts]
        outs = [f.result(300).tolist() for f in futs]
        delta = observe.runtime_stats.delta(snap)
        compiles = eng.stats.post_warmup_compiles()
        params_spec, vec, pt, pool_specs = eng._specs()
        text = jax.jit(eng._build_decode_fn()).lower(
            params_spec, vec, vec, vec, vec, pt, pool_specs).as_text()
        eng.close()
        return outs, delta, compiles, text

    outs_off, delta_off, compiles_off, text_off = run(None)
    outs_on, delta_on, compiles_on, text_on = run(
        ReqTracer(sample_rate=0.0))
    assert outs_on == outs_off  # tokens untouched
    assert compiles_on == compiles_off == 0  # zero-compile contract
    assert delta_on["dispatches"] == delta_off["dispatches"]
    assert delta_on["retraces"] == delta_off["retraces"] == 0
    assert text_on == text_off, \
        "tracing changed the lowered step (must be host-side only)"


# ---------------------------------------------------------------------------
# Metrics registry + Prometheus exposition
# ---------------------------------------------------------------------------

def test_prometheus_histogram_bucket_exactness():
    """The exposition contract: cumulative `le` buckets equal the
    LatencyHistogram's bin prefix sums EXACTLY, +Inf equals count,
    sum equals sum_ms — a scrape loses nothing the histogram knew."""
    h = LatencyHistogram()
    samples = [0.004, 0.5, 3.7, 3.75, 50.0, 51.0, 52.0, 9000.0,
               120000.0]
    for v in samples:
        h.record(v)
    buckets = h.cumulative_buckets()
    # independent ground truth from the raw bins
    edges = [h._edge(i) for i in range(h._nbins)]
    for le, cum in buckets:
        expect = sum(1 for v in samples if h._edge(h._bin(v)) <= le)
        assert cum == expect, (le, cum, expect)
    assert buckets[-1][1] == h.count == len(samples)
    assert all(le in edges or le == edges[-1] for le, _ in buckets)
    # the text form carries the same numbers
    fam = histogram("e2e_ms", "test", h, scope="unit")
    reg = MetricsRegistry().register("t", lambda: [fam])
    text = reg.prometheus_text()
    got = re.findall(r'e2e_ms_bucket\{le="([^"]+)",scope="unit"\} (\d+)',
                     text)
    parsed = [(float(le) if le != "+Inf" else float("inf"), int(c))
              for le, c in got]
    assert parsed[:-1] == [(pytest.approx(le), c)
                           for le, c in buckets]
    assert parsed[-1] == (float("inf"), len(samples))
    assert f"e2e_ms_count{{scope=\"unit\"}} {len(samples)}" in text
    m = re.search(r'e2e_ms_sum\{scope="unit"\} ([0-9.e+-]+)', text)
    assert float(m.group(1)) == pytest.approx(h.sum_ms)
    # cumulative counts are monotone non-decreasing (le ascending)
    assert all(parsed[i][1] <= parsed[i + 1][1]
               for i in range(len(parsed) - 1))


def test_registry_families_labels_and_error_isolation():
    reg = MetricsRegistry()
    reg.register("good", lambda: [
        counter("reqs_total", "requests", 7, model="bert",
                bucket='b"8'),
        gauge("depth", "queue depth", 3.5, replica_id=0)])

    def bad():
        raise RuntimeError("collector died")

    reg.register("bad", bad)
    text = reg.prometheus_text()
    # label values escape quotes; samples carry their labels
    assert 'reqs_total{bucket="b\\"8",model="bert"} 7' in text
    assert 'depth{replica_id="0"} 3.5' in text
    # the sick collector is isolated and visible, not fatal
    assert 'observe_collector_up{collector="bad"} 0' in text
    assert 'observe_collector_up{collector="good"} 1' in text
    snap = reg.snapshot()
    assert snap["reqs_total"]["kind"] == "counter"
    assert snap["depth"]["samples"][0]["value"] == 3.5
    # replacement, not accumulation
    reg.register("good", lambda: [gauge("depth", "", 1.0)])
    assert reg.collector_names() == ["bad", "good"]
    with pytest.raises(ValueError):
        MetricFamily("bad name!", "gauge")
    with pytest.raises(ValueError):
        MetricFamily("x", "summary")


def test_serving_stats_and_telemetry_collectors():
    from paddle_tpu.observe.metrics import StepTelemetry
    from paddle_tpu.serving import DecodeStats

    stats = DecodeStats()
    stats.record_submit()
    stats.record_prefill(1, [2.0])
    stats.record_decode(4, 1, 2, 6, 5, 10, 12.0)
    stats.record_done()
    fams = {f.name: f for f in
            serving_stats_collector(stats, scope="fleet")()}
    assert fams["serving_submitted_total"].samples == \
        [({"scope": "fleet"}, 1.0)]
    assert fams["serving_tokens_generated_total"].samples[0][1] == 7.0
    assert fams["serving_post_warmup_compiles"].kind == "gauge"
    assert fams["serving_slot_occupancy"].samples[0][1] == \
        pytest.approx(0.5)
    hist_fam = fams["serving_ttft_ms"]
    assert hist_fam.kind == "histogram"
    assert hist_fam.samples[0][1]["count"] == 1

    tel = StepTelemetry(
        steps=10, loss_last=0.5, loss_mean=0.6, grad_norm_last=1.25,
        grad_norm_mean=1.5, update_norm_last=0.01,
        update_norm_mean=0.02, nonfinite_grad_steps=0,
        nonfinite_loss_steps=0, skipped_update_steps=1,
        loss_scale=1024.0,
        groups={"attn_qkv": {"grad_norm": 0.7, "update_ratio": 1e-3}})
    fams = {f.name: f for f in
            telemetry_collector(lambda: tel, job="t1")()}
    assert fams["training_loss_last"].samples == \
        [({"job": "t1"}, 0.5)]
    assert fams["training_loss_scale"].samples[0][1] == 1024.0
    grp = fams["training_group_grad_norm"].samples
    assert grp == [({"group": "attn_qkv", "job": "t1"}, 0.7)]
    # before the first window: degraded, not broken
    fams0 = {f.name: f for f in telemetry_collector(lambda: None)()}
    assert fams0["training_telemetry_windows"].samples[0][1] == 0

    # gang heartbeat skew adapter (the HealthMonitor.skew() wire form)
    from paddle_tpu.observe.registry import gang_collector

    skew = {"steps": {0: 10, 1: 8}, "rates": {0: 1.0, 1: 0.5},
            "max_lag_steps": 2, "median_rate": 0.75, "slow_ranks": [1]}
    fams = {f.name: f for f in gang_collector(lambda: skew)()}
    assert fams["gang_rank_steps"].samples == \
        [({"rank": 0}, 10.0), ({"rank": 1}, 8.0)]
    assert fams["gang_rank_step_rate"].samples[1] == ({"rank": 1}, 0.5)
    assert fams["gang_max_lag_steps"].samples[0][1] == 2
    assert fams["gang_slow_ranks"].samples[0][1] == 1


def test_metrics_server_endpoint_and_default_snapshot():
    tr = ReqTracer()
    t = tr.new_trace()
    t.add("dispatch", time.monotonic() - 0.001, time.monotonic(),
          replica_id=0)
    tr.finish(t)
    reg = standard_collectors(MetricsRegistry())
    reg.register("reqtrace", tracer_collector(tr))
    srv = MetricsServer(reg, health_fn=lambda: {"state": "ok",
                                                "n": 2}).start()
    try:
        assert srv.host == "127.0.0.1"  # localhost by default
        body = urllib.request.urlopen(
            srv.url + "/metrics", timeout=10).read().decode()
        hz = json.loads(urllib.request.urlopen(
            srv.url + "/healthz", timeout=10).read())
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(srv.url + "/nope", timeout=10)
    finally:
        srv.close()
    assert hz == {"state": "ok", "n": 2}
    subsystems = {ln.split("_")[0] for ln in body.splitlines()
                  if ln and not ln.startswith("#")}
    assert {"runtime", "process", "reqtrace", "memory"} <= subsystems
    assert re.search(r"^reqtrace_kept_total 1$", body, re.M)
    assert re.search(r'^reqtrace_phase_ms_bucket\{le="[^"]+",'
                     r'phase="dispatch"\} 1$', body, re.M)
    # the module-level snapshot over the process-default registry
    snap = observe.metrics_snapshot()
    assert "runtime_dispatches_total" in snap
    assert "process_uptime_seconds" in snap
    # tools/metrics_dump.py parses the same exposition
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "metrics_dump", os.path.join(os.path.dirname(__file__),
                                     "..", "tools", "metrics_dump.py"))
    md = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(md)
    fams = md.parse_exposition(body)
    assert fams["reqtrace_kept_total"]["kind"] == "counter"
    assert fams["reqtrace_phase_ms"]["kind"] == "histogram"


def test_event_kind_registry_enforcement(tmp_path):
    """Unregistered serving_/fleet_/gang_ kinds warn by default and
    raise under strict mode (conftest turns strict on for the suite);
    registering legitimizes a new kind; non-dashboard prefixes are
    never validated."""
    from paddle_tpu.observe import events

    log = observe.RunEventLog(str(tmp_path / "e.jsonl"))
    # conftest set strict: a typo raises before it can rot a dashboard
    with pytest.raises(ValueError, match="not registered"):
        log.event("serving_windw", completed=1)  # the classic typo
    with pytest.raises(ValueError):
        log.event("gang_skeww")
    prev = events.set_strict_kinds(False)
    try:
        with pytest.warns(UserWarning, match="not registered"):
            log.event("fleet_bogus", x=1)
    finally:
        events.set_strict_kinds(prev)
    # registered kinds (incl. the decode stragglers this PR flushed
    # out) pass silently
    for kind in ("serving_window", "serving_decode_preempt",
                 "serving_fleet_failover", "gang_skew",
                 "serving_reload"):
        log.event(kind, ok=True)
    events.register_event_kinds("serving_custom_extension")
    log.event("serving_custom_extension", x=2)
    # non-dashboard prefixes are unvalidated (telemetry, checkpoint..)
    log.event("my_custom_thing", x=3)
    log.close()
    recs = observe.read_events(str(tmp_path / "e.jsonl"))
    kinds = [r["event"] for r in recs]
    assert "serving_windw" not in kinds  # the typo never landed
    assert "serving_custom_extension" in kinds
    assert "serving_decode_preempt" in kinds
