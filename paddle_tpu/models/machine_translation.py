"""Seq2seq NMT: GRU encoder-decoder with dot attention + beam-search decode.

TPU-native analog of the reference's machine-translation config
(reference: benchmark/fluid/machine_translation.py:1 — the
lstm-encoder-decoder bench model; python/paddle/fluid/tests/book/
test_machine_translation.py — the book model whose inference uses
beam_search/beam_search_decode with While + tensor arrays).

Training uses DynamicRNN (lax.scan + seq_len masking) so the decoder
recurrence is reverse-differentiable; decoding uses a While loop with
fixed-capacity tensor arrays, the dense (batch, beam) `beam_search` op per
step, and `beam_search_decode` backtrace at the end — the static-shape
equivalent of the reference's LoD-linked beam machinery.

Weights are shared between the training and decoding programs through
fixed parameter names, exactly how the reference shares them between
train/infer programs built from the same network function.
"""

from __future__ import annotations

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.param_attr import ParamAttr


def _p(name):
    return ParamAttr(name=name)


def _encoder(src, src_vocab_size, embed_dim, hidden_dim):
    """Embedding → input proj → GRU.  Returns (enc_out (B,T,H), last (B,H))."""
    emb = layers.embedding(src, size=(src_vocab_size, embed_dim),
                           param_attr=_p("nmt.src_emb"))
    proj = layers.fc(emb, size=3 * hidden_dim, num_flatten_dims=2,
                     param_attr=_p("nmt.enc_proj.w"),
                     bias_attr=_p("nmt.enc_proj.b"))
    enc_out = layers.dynamic_gru(proj, size=hidden_dim,
                                 param_attr=_p("nmt.enc_gru.w"),
                                 bias_attr=_p("nmt.enc_gru.b"))
    last = layers.sequence_last_step(enc_out)
    return enc_out, last


def _attention(h, enc_out, enc_mask):
    """Dot attention: h (N,H) against enc_out (N,T,H) with additive mask
    (N,T) of 0/-1e9.  Returns the (N,H) context."""
    scores = layers.reduce_sum(
        layers.elementwise_mul(enc_out, layers.unsqueeze(h, [1])), dim=2)
    scores = layers.elementwise_add(scores, enc_mask)
    attn = layers.softmax(scores)
    ctx = layers.reduce_sum(
        layers.elementwise_mul(enc_out, layers.unsqueeze(attn, [2])), dim=1)
    return ctx


def _dec_step(emb_t, h_prev, enc_out, enc_mask, hidden_dim):
    """One decoder step shared by training and beam decode."""
    ctx = _attention(h_prev, enc_out, enc_mask)
    inp = layers.concat([emb_t, ctx], axis=1)
    gate_in = layers.fc(inp, size=3 * hidden_dim,
                        param_attr=_p("nmt.dec_in.w"),
                        bias_attr=_p("nmt.dec_in.b"))
    h, _, _ = layers.gru_unit(gate_in, h_prev, 3 * hidden_dim,
                              param_attr=_p("nmt.dec_gru.w"),
                              bias_attr=_p("nmt.dec_gru.b"))
    return h


def _enc_additive_mask(seq_len, max_len):
    """(B,T) additive mask: 0 where t < len, -1e9 beyond."""
    mask = layers.sequence_mask(seq_len, maxlen=max_len, dtype="float32")
    return layers.scale(mask, scale=1e9, bias=-1e9)


def seq_to_seq_net(src_vocab_size=1000, trg_vocab_size=1000, embed_dim=64,
                   hidden_dim=128, batch_size=16, max_src_len=20,
                   max_trg_len=20):
    """Training network.  Returns (avg_cost, feeds)."""
    src = layers.data("src_word_id", shape=[batch_size, max_src_len],
                      dtype="int64", append_batch_size=False, lod_level=1)
    trg = layers.data("trg_word_id", shape=[batch_size, max_trg_len],
                      dtype="int64", append_batch_size=False, lod_level=1)
    label = layers.data("trg_next_id", shape=[batch_size, max_trg_len],
                        dtype="int64", append_batch_size=False)

    enc_out, enc_last = _encoder(src, src_vocab_size, embed_dim, hidden_dim)
    src_len = layers.seq_len_var(src)
    enc_mask = _enc_additive_mask(src_len, max_src_len)

    trg_emb = layers.embedding(trg, size=(trg_vocab_size, embed_dim),
                               param_attr=_p("nmt.trg_emb"))

    drnn = layers.DynamicRNN()
    with drnn.block():
        emb_t = drnn.step_input(trg_emb)
        h_prev = drnn.memory(init=enc_last)
        h = _dec_step(emb_t, h_prev, enc_out, enc_mask, hidden_dim)
        drnn.update_memory(h_prev, h)
        drnn.output(h)
    dec_out = drnn()  # (B, T_trg, H) padded

    logits = layers.fc(dec_out, size=trg_vocab_size, num_flatten_dims=2,
                       param_attr=_p("nmt.out.w"), bias_attr=_p("nmt.out.b"))
    cost = layers.softmax_with_cross_entropy(
        logits, layers.unsqueeze(label, [2]))
    trg_len = layers.seq_len_var(trg)
    trg_mask = layers.sequence_mask(trg_len, maxlen=max_trg_len,
                                    dtype="float32")
    cost = layers.elementwise_mul(layers.squeeze(cost, [2]), trg_mask)
    # mean over real (unpadded) tokens
    avg_cost = layers.elementwise_div(
        layers.reduce_sum(cost),
        layers.reduce_sum(trg_mask))
    feeds = ["src_word_id", "src_word_id.seq_len", "trg_word_id",
             "trg_word_id.seq_len", "trg_next_id"]
    return avg_cost, feeds


def beam_search_net(src_vocab_size=1000, trg_vocab_size=1000, embed_dim=64,
                    hidden_dim=128, batch_size=4, max_src_len=20,
                    beam_size=4, max_decode_len=16, start_id=0, end_id=1):
    """Beam-search decoding network (reference book model's decode(), built
    from While + arrays + beam_search + beam_search_decode).

    Returns (sentence_ids (B, K, max_decode_len), final_scores (B, K),
    feeds)."""
    B, K = batch_size, beam_size
    src = layers.data("src_word_id", shape=[B, max_src_len], dtype="int64",
                      append_batch_size=False, lod_level=1)
    enc_out, enc_last = _encoder(src, src_vocab_size, embed_dim, hidden_dim)
    src_len = layers.seq_len_var(src)
    enc_mask = _enc_additive_mask(src_len, max_src_len)  # (B, T)

    # Beam-expand encoder state: (B,...) → (B*K,...), beams contiguous per
    # batch row so `parent + row*K` flattens the reorder gather.
    enc_out_b = layers.reshape(
        layers.expand(layers.unsqueeze(enc_out, [1]), [1, K, 1, 1]),
        [B * K, max_src_len, hidden_dim])
    enc_mask_b = layers.reshape(
        layers.expand(layers.unsqueeze(enc_mask, [1]), [1, K, 1]),
        [B * K, max_src_len])
    hidden = layers.reshape(
        layers.expand(layers.unsqueeze(enc_last, [1]), [1, K, 1]),
        [B * K, hidden_dim])

    pre_ids = layers.fill_constant([B, K], "int64", float(start_id))
    # beams 1..K-1 start at -inf so step 0 only expands beam 0 (standard
    # dense-beam initialization; replaces the op's is_first_step attr)
    beam_iota = layers.reshape(
        layers.range(0, K, 1, "float32", num=K), [1, K])
    neg = layers.scale(
        layers.cast(layers.greater_than(
            layers.expand(beam_iota, [B, 1]),
            layers.fill_constant([B, K], "float32", 0.0)), "float32"),
        scale=-1e9)
    pre_scores = neg  # (B,K): [0, -1e9, ...]

    # flat row offsets: [0,0,..,K,K,..] for parent reordering
    row_offset = layers.scale(
        layers.elementwise_floordiv(
            layers.range(0, B * K, 1, "int32", num=B * K),
            layers.fill_constant([B * K], "int32", float(K))),
        scale=float(K))

    ids_arr = layers.create_array("int64", element_shape=[B, K],
                                  capacity=max_decode_len)
    par_arr = layers.create_array("int32", element_shape=[B, K],
                                  capacity=max_decode_len)

    step = layers.fill_constant([1], "int32", 0)
    max_steps = layers.fill_constant([1], "int32", float(max_decode_len))
    cond = layers.less_than(step, max_steps)
    w = layers.While(cond)
    with w.block():
        emb = layers.embedding(layers.reshape(pre_ids, [B * K]),
                               size=(trg_vocab_size, embed_dim),
                               param_attr=_p("nmt.trg_emb"))
        h = _dec_step(emb, hidden, enc_out_b, enc_mask_b, hidden_dim)
        logits = layers.fc(h, size=trg_vocab_size,
                           param_attr=_p("nmt.out.w"),
                           bias_attr=_p("nmt.out.b"))
        logp = layers.log(layers.softmax(logits))
        logp = layers.reshape(logp, [B, K, trg_vocab_size])
        sel_ids, sel_scores, parent = layers.beam_search(
            pre_ids, pre_scores, logp, beam_size=K, end_id=end_id)
        # reorder hidden by parent beam
        flat_parent = layers.elementwise_add(
            layers.reshape(parent, [B * K]), row_offset)
        layers.assign(layers.gather(h, flat_parent), hidden)
        layers.array_write(sel_ids, step, ids_arr)
        layers.array_write(parent, step, par_arr)
        layers.assign(sel_ids, pre_ids)
        layers.assign(sel_scores, pre_scores)
        layers.increment(step, value=1, in_place=True)
        # continue while step < max AND any beam unfinished
        finished = layers.equal(
            layers.cast(pre_ids, "int32"),
            layers.fill_constant([B, K], "int32", float(end_id)))
        all_done = layers.reduce_all(finished)
        layers.logical_and(
            layers.less_than(step, max_steps),
            layers.logical_not(layers.reshape(all_done, [1])),
            out=cond)

    ids_stack, _ = layers.array_to_tensor(ids_arr)     # (L, B, K)
    par_stack, _ = layers.array_to_tensor(par_arr)     # (L, B, K)
    sentences = layers.beam_search_decode(ids_stack, par_stack,
                                          num_steps=step, end_id=end_id)
    feeds = ["src_word_id", "src_word_id.seq_len"]
    return sentences, pre_scores, feeds
