"""Observe pillar 9: SLO alert engine + diagnostic flight recorder.

Locks in the ISSUE 17 acceptance criteria:
- rule mechanics under a fake clock and synthetic snapshots: threshold
  firing with hysteresis, counter→rate windows, multiwindow burn-rate
  firing and short-window resolve, anomaly z-scores with a baseline
  that freezes while firing, `for_duration_s` pending gating and
  `resolve_duration_s` clear gating, "no data" holding state,
- engine surfaces: transition events into a strict-mode RunEventLog
  (the alert_*/flight_* kinds are registered), the `alerts` collector
  in the prometheus exposition, `signals()` shaped for the autoscaler,
  the `/alerts` HTTP route (404 until an engine attaches — late attach
  works), rule-error isolation, background thread start/close,
- flight recorder: bundle contents per attached source, rate limiting
  + count cap (`force` bypasses only the former), byte-budget
  truncation recorded in the manifest, crash-hook capture + chaining,
  watchdog on_hang chaining (capture BEFORE the prior hook),
  firing-alert auto-capture via `attach_engine`,
- the guard discipline: an AlertEngine evaluating on its background
  thread during training adds zero dispatches, zero retraces, and the
  step lowering is byte-identical with or without it,
- the metrics_dump.py `--alerts` CLI against a live server.
"""

import contextlib
import json
import os
import subprocess
import sys
import time
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, observe
from paddle_tpu.observe.alerts import (AlertEngine, AlertRule,
                                       AnomalyRule, BurnRateRule,
                                       MetricSelector, ThresholdRule,
                                       fleet_rule_pack,
                                       serving_rule_pack,
                                       snapshot_value,
                                       trainer_rule_pack)
from paddle_tpu.observe.events import RunEventLog, read_events
from paddle_tpu.observe.flightrec import FlightRecorder
from paddle_tpu.observe.registry import (MetricsRegistry, MetricsServer,
                                         counter, gauge,
                                         standard_collectors)


# ---------------------------------------------------------------------------
# Synthetic snapshot helpers
# ---------------------------------------------------------------------------

def _fam(kind, *samples):
    return {"kind": kind, "help": "",
            "samples": [{"labels": l, "value": v} for l, v in samples]}


def _hist_fam(*samples):
    """samples: (labels, buckets[(le, cum)...]) — count = last cum."""
    return {"kind": "histogram", "help": "",
            "samples": [{"labels": l,
                         "count": (b[-1][1] if b else 0),
                         "sum_ms": 0.0,
                         "buckets": [list(x) for x in b]}
                        for l, b in samples]}


def _gauge_snap(name, value):
    return {name: _fam("gauge", ({}, value))}


def _counter_snap(name, value):
    return {name: _fam("counter", ({}, value))}


# ---------------------------------------------------------------------------
# snapshot_value
# ---------------------------------------------------------------------------

def test_snapshot_value_counter_sums_gauge_averages():
    snap = {"c": _fam("counter", ({"k": "a"}, 3.0), ({"k": "b"}, 4.0)),
            "g": _fam("gauge", ({"k": "a"}, 2.0), ({"k": "b"}, 6.0))}
    assert snapshot_value(snap, "c") == 7.0
    assert snapshot_value(snap, "g") == 4.0
    assert snapshot_value(snap, "c", labels={"k": "a"}) == 3.0
    assert snapshot_value(snap, "missing") is None
    assert snapshot_value(snap, "c", labels={"k": "zzz"}) is None


def test_snapshot_value_histogram_percentile():
    # 10 obs: 5 under 1ms, 9 under 10ms, all under 100ms
    snap = {"h": _hist_fam(({}, [(1.0, 5), (10.0, 9), (100.0, 10)]))}
    assert snapshot_value(snap, "h", percentile=50) == 1.0
    assert snapshot_value(snap, "h", percentile=90) == 10.0
    assert snapshot_value(snap, "h", percentile=99) == 100.0
    with pytest.raises(ValueError, match="percentile"):
        snapshot_value(snap, "h")


def test_snapshot_value_histogram_label_merge():
    snap = {"h": _hist_fam(
        ({"phase": "queue_wait"}, [(1.0, 1), (10.0, 2)]),
        ({"phase": "decode"}, [(1.0, 100), (10.0, 100)]))}
    # the label filter narrows before the cumulative merge
    assert snapshot_value(snap, "h",
                          labels={"phase": "queue_wait"},
                          percentile=99) == 10.0
    assert snapshot_value(snap, "h", percentile=99) == 1.0


def test_metric_selector_repr_and_call():
    sel = MetricSelector("h", labels={"phase": "x"}, percentile=99)
    assert "h" in repr(sel) and "p99" in repr(sel)
    assert sel({}) is None


# ---------------------------------------------------------------------------
# ThresholdRule: firing, hysteresis, for/resolve duration, no-data
# ---------------------------------------------------------------------------

def test_threshold_fires_and_resolves_with_hysteresis():
    r = ThresholdRule("hot", "load", op=">", threshold=5.0, clear=3.0)
    assert r.step(_gauge_snap("load", 1.0), now=0.0) is None
    assert r.state == "inactive"
    assert r.step(_gauge_snap("load", 10.0), now=1.0) == "alert_firing"
    assert r.firing and r.fired_count == 1
    # hysteresis: below threshold but above clear -> still firing
    assert r.step(_gauge_snap("load", 4.0), now=2.0) is None
    assert r.firing
    assert r.step(_gauge_snap("load", 2.0), now=3.0) == \
        "alert_resolved"
    assert r.state == "inactive"


def test_threshold_for_duration_gates_through_pending():
    r = ThresholdRule("hot", "load", threshold=5.0,
                      for_duration_s=2.0, resolve_duration_s=1.0)
    assert r.step(_gauge_snap("load", 9.0), now=0.0) == "alert_pending"
    assert r.state == "pending"
    assert r.step(_gauge_snap("load", 9.0), now=1.0) is None
    assert r.step(_gauge_snap("load", 9.0), now=2.5) == "alert_firing"
    # resolve_duration: first clear sample only starts the clock
    assert r.step(_gauge_snap("load", 1.0), now=3.0) is None
    assert r.firing
    assert r.step(_gauge_snap("load", 1.0), now=4.5) == \
        "alert_resolved"


def test_threshold_pending_unbreach_returns_to_inactive():
    r = ThresholdRule("hot", "load", threshold=5.0, for_duration_s=10.0)
    assert r.step(_gauge_snap("load", 9.0), now=0.0) == "alert_pending"
    r.step(_gauge_snap("load", 1.0), now=1.0)
    assert r.state == "inactive"
    # a later breach restarts the for_duration clock from scratch
    assert r.step(_gauge_snap("load", 9.0), now=2.0) == "alert_pending"
    assert r.step(_gauge_snap("load", 9.0), now=5.0) is None
    assert r.state == "pending"


def test_no_data_holds_state():
    r = ThresholdRule("hot", "load", threshold=5.0)
    r.step(_gauge_snap("load", 10.0), now=0.0)
    assert r.firing
    # the family disappears (collector died): state must hold
    assert r.step({}, now=1.0) is None
    assert r.firing and r.value is None


def test_threshold_window_turns_counter_into_rate():
    r = ThresholdRule("failover", "fleet_failovers_total",
                      op=">", threshold=0.0, window_s=60.0)
    assert r.step(_counter_snap("fleet_failovers_total", 0), 0.0) \
        is None  # one sample: no rate yet
    assert r.step(_counter_snap("fleet_failovers_total", 0), 1.0) \
        is None
    assert r.state == "inactive"  # rate 0: not a breach
    assert r.step(_counter_snap("fleet_failovers_total", 1), 2.0) == \
        "alert_firing"
    assert r.value == pytest.approx(0.5)  # 1 event / 2 s
    # counter flat, window slides past the event -> rate 0 -> resolved
    assert r.step(_counter_snap("fleet_failovers_total", 1), 63.0) == \
        "alert_resolved"


def test_threshold_rejects_bad_op_and_source():
    with pytest.raises(ValueError, match="op"):
        ThresholdRule("x", "load", op="!=", threshold=1.0)
    with pytest.raises(TypeError, match="source"):
        ThresholdRule("x", 123, threshold=1.0)
    with pytest.raises(ValueError, match="rule_id"):
        ThresholdRule("", "load", threshold=1.0)


# ---------------------------------------------------------------------------
# BurnRateRule
# ---------------------------------------------------------------------------

def _ratio_snap(bad, tot):
    return {"bad": _fam("counter", ({}, bad)),
            "tot": _fam("counter", ({}, tot))}


def test_burn_rate_multiwindow_fire_and_short_window_resolve():
    r = BurnRateRule("err", "bad", "tot", slo=0.01,
                     long_window_s=300.0, short_window_s=30.0)
    assert r.step(_ratio_snap(0, 0), 0.0) is None   # no traffic
    assert r.step(_ratio_snap(0, 100), 10.0) is None
    assert r.state == "inactive"                     # burn 0
    assert r.step(_ratio_snap(5, 200), 20.0) == "alert_firing"
    assert r.value == pytest.approx(2.5)             # (5/200)/0.01
    # recovery: short window sees 200 clean requests -> resolve even
    # though the long window is still over budget
    assert r.step(_ratio_snap(5, 400), 55.0) == "alert_resolved"


def test_burn_rate_one_spike_needs_both_windows():
    r = BurnRateRule("err", "bad", "tot", slo=0.5,
                     long_window_s=100.0, short_window_s=10.0)
    r.step(_ratio_snap(0, 0), 0.0)
    r.step(_ratio_snap(9, 10), 1.0)   # short+long both burn: fires
    assert r.firing
    r2 = BurnRateRule("err2", "bad", "tot", slo=0.5,
                      long_window_s=100.0, short_window_s=10.0)
    r2.step(_ratio_snap(0, 0), 0.0)
    r2.step(_ratio_snap(9, 10), 1.0)
    # 15s of clean traffic: short window burn drops under, long stays
    # over -> must NOT fire again once resolved
    r2.step(_ratio_snap(9, 1000), 16.0)
    assert not r2.firing


def test_burn_rate_rejects_nonpositive_slo():
    with pytest.raises(ValueError, match="slo"):
        BurnRateRule("x", "bad", "tot", slo=0.0)


# ---------------------------------------------------------------------------
# AnomalyRule
# ---------------------------------------------------------------------------

def test_anomaly_spike_fires_baseline_freezes_then_resolves():
    r = AnomalyRule("loss", "training_loss_mean", z=4.0,
                    direction="above", min_samples=3, min_std=0.01)
    for i in range(3):
        assert r.step(_gauge_snap("training_loss_mean", 1.0),
                      float(i)) is None
    assert r.step(_gauge_snap("training_loss_mean", 5.0), 3.0) == \
        "alert_firing"
    base_len = len(r._baseline)
    # the spike keeps coming: baseline must NOT absorb it
    r.step(_gauge_snap("training_loss_mean", 5.0), 4.0)
    assert r.firing and len(r._baseline) == base_len
    assert r.step(_gauge_snap("training_loss_mean", 1.0), 5.0) == \
        "alert_resolved"


def test_anomaly_below_direction_with_rate():
    r = AnomalyRule("tput", "goodput_steps_total", z=3.0,
                    direction="below", rate=True, window_s=100.0,
                    min_samples=3, min_std=0.01)
    # steady 10 steps/s
    for i, v in enumerate([0, 10, 20, 30, 40]):
        r.step(_counter_snap("goodput_steps_total", v), float(i))
    assert r.state == "inactive"
    # throughput collapses: counter stalls
    r.step(_counter_snap("goodput_steps_total", 40), 5.0)
    r.step(_counter_snap("goodput_steps_total", 40), 6.0)
    assert r.firing


def test_anomaly_rejects_bad_direction():
    with pytest.raises(ValueError, match="direction"):
        AnomalyRule("x", "v", direction="sideways")


# ---------------------------------------------------------------------------
# Engine: evaluation, events, collector, signals, thread
# ---------------------------------------------------------------------------

class _MutableRegistry:
    """Registry stand-in: snapshot() returns whatever was last set."""

    def __init__(self, snap=None):
        self.snap = snap or {}

    def snapshot(self):
        if isinstance(self.snap, Exception):
            raise self.snap
        return self.snap


def test_engine_transitions_emit_registered_events(tmp_path):
    log = RunEventLog(str(tmp_path / "ev.jsonl"))
    reg = _MutableRegistry(_gauge_snap("load", 1.0))
    eng = AlertEngine(reg, rules=[
        ThresholdRule("hot", "load", threshold=5.0, clear=3.0)],
        event_log=log)
    assert eng.evaluate(now=0.0) == []
    reg.snap = _gauge_snap("load", 9.0)
    out = eng.evaluate(now=1.0)
    assert [(r.id, k) for r, k in out] == [("hot", "alert_firing")]
    reg.snap = _gauge_snap("load", 1.0)
    eng.evaluate(now=2.0)
    log.close()
    kinds = [e["event"] for e in read_events(log.path)
             if e["event"].startswith("alert_")]
    # strict mode is on suite-wide (conftest): reaching here at all
    # proves the alert_* kinds are registered
    assert kinds == ["alert_firing", "alert_resolved"]
    rec = [e for e in read_events(log.path)
           if e["event"] == "alert_firing"][0]
    assert rec["rule"] == "hot" and rec["value"] == 9.0
    assert rec["target"] == 5.0 and rec["severity"] == "page"


def test_engine_signals_and_state_shape():
    reg = _MutableRegistry(_gauge_snap("load", 9.0))
    eng = AlertEngine(reg, rules=[
        ThresholdRule("hot", "load", threshold=5.0),
        ThresholdRule("cold", "load", op="<", threshold=0.0)])
    eng.evaluate(now=0.0)
    sig = eng.signals()
    assert set(sig) == {"hot", "cold"}
    assert sig["hot"] == {"firing": True, "state": "firing",
                          "value": 9.0, "target": 5.0,
                          "severity": "page"}
    assert sig["cold"]["firing"] is False
    st = eng.state()
    assert st["firing"] == ["hot"]
    assert st["evaluations"] == 1 and st["running"] is False
    assert {r["id"] for r in st["rules"]} == {"hot", "cold"}
    assert eng.firing() == ["hot"]
    json.dumps(st)  # the /alerts body must be JSON-able


def test_engine_collector_in_prometheus_exposition():
    reg = MetricsRegistry()
    val = [9.0]
    reg.register("toy", lambda: [gauge("load", "", val[0])])
    eng = AlertEngine(reg, rules=[
        ThresholdRule("hot", "load", threshold=5.0)])
    reg.register("alerts", eng.collector())
    eng.evaluate(now=0.0)
    text = reg.prometheus_text()
    assert 'alerts_firing{rule="hot",severity="page"} 1' in text
    assert 'alerts_value{rule="hot",severity="page"} 9' in text
    assert 'alerts_target{rule="hot",severity="page"} 5' in text
    assert 'alerts_fired_total{rule="hot",severity="page"} 1' in text
    assert "alerts_evaluations_total 1" in text
    assert "alerts_rules 1" in text
    # the collector only reads rule state: scraping must not advance
    # the evaluation count
    assert eng.evaluations == 1


def test_engine_rule_error_isolated():
    class Bomb(AlertRule):
        def observe(self, snapshot, now):
            raise RuntimeError("boom")

    reg = _MutableRegistry(_gauge_snap("load", 9.0))
    eng = AlertEngine(reg, rules=[
        Bomb("bomb"), ThresholdRule("hot", "load", threshold=5.0)])
    out = eng.evaluate(now=0.0)
    assert [(r.id, k) for r, k in out] == [("hot", "alert_firing")]
    assert eng.eval_errors == 1


def test_engine_sick_registry_counted_not_fatal():
    reg = _MutableRegistry(RuntimeError("scrape failed"))
    eng = AlertEngine(reg, rules=[
        ThresholdRule("hot", "load", threshold=5.0)])
    assert eng.evaluate(now=0.0) == []
    assert eng.eval_errors == 1


def test_engine_duplicate_rule_and_remove():
    eng = AlertEngine(_MutableRegistry())
    eng.add_rule(ThresholdRule("a", "x", threshold=1.0))
    with pytest.raises(ValueError, match="duplicate"):
        eng.add_rule(ThresholdRule("a", "x", threshold=1.0))
    eng.remove_rule("a")
    eng.add_rule(ThresholdRule("a", "x", threshold=1.0))
    assert [r.id for r in eng.rules] == ["a"]


def test_engine_background_thread_start_close():
    reg = _MutableRegistry(_gauge_snap("load", 9.0))
    eng = AlertEngine(reg, rules=[
        ThresholdRule("hot", "load", threshold=5.0)],
        interval_s=0.01)
    with eng:
        assert eng.running
        deadline = time.monotonic() + 5.0
        while eng.evaluations == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
    assert not eng.running
    assert eng.evaluations > 0
    assert eng.firing() == ["hot"]


# ---------------------------------------------------------------------------
# /alerts HTTP route
# ---------------------------------------------------------------------------

def _get(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode("utf-8")


def test_alerts_route_404_then_late_attach():
    reg = MetricsRegistry()
    reg.register("toy", lambda: [gauge("load", "", 9.0)])
    srv = MetricsServer(reg).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{srv.url}/alerts")
        assert ei.value.code == 404
        eng = AlertEngine(reg, rules=[
            ThresholdRule("hot", "load", threshold=5.0)])
        eng.evaluate(now=0.0)
        srv.alerts_fn = eng.state  # late attach: read per-request
        body = json.loads(_get(f"{srv.url}/alerts"))
        assert body["firing"] == ["hot"]
        assert body["rules"][0]["value"] == 9.0
        # the other routes still answer
        assert "load 9" in _get(f"{srv.url}/metrics")
        assert json.loads(_get(f"{srv.url}/healthz"))["ok"] is True
    finally:
        srv.close()


def test_metrics_dump_alerts_cli():
    reg = MetricsRegistry()
    reg.register("toy", lambda: [gauge("load", "", 9.0)])
    eng = AlertEngine(reg, rules=[
        ThresholdRule("hot", "load", threshold=5.0)])
    eng.evaluate(now=0.0)
    srv = MetricsServer(reg, alerts_fn=eng.state).start()
    tool = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "metrics_dump.py")
    try:
        out = subprocess.run(
            [sys.executable, tool, "--url",
             f"{srv.url}/metrics", "--alerts"],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        assert "1 firing / 1 rules" in out.stdout
        assert "hot" in out.stdout and "value=9" in out.stdout
        out2 = subprocess.run(
            [sys.executable, tool, "--url",
             f"{srv.url}/metrics", "--alerts", "--json"],
            capture_output=True, text=True, timeout=60)
        assert json.loads(out2.stdout)["firing"] == ["hot"]
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# Default rule packs
# ---------------------------------------------------------------------------

def test_rule_packs_have_unique_ids_and_install():
    fake_fleet = types.SimpleNamespace(replicas=[1, 2])
    for pack in (fleet_rule_pack(fake_fleet), serving_rule_pack(),
                 trainer_rule_pack()):
        ids = [r.id for r in pack]
        assert len(ids) == len(set(ids))
        AlertEngine(_MutableRegistry(), rules=pack)  # no collisions
    assert "fleet_replicas_down" in \
        {r.id for r in fleet_rule_pack(fake_fleet)}
    assert "fleet_replicas_down" not in \
        {r.id for r in fleet_rule_pack()}


def test_fleet_pack_failover_rule_on_synthetic_counters():
    rules = {r.id: r for r in fleet_rule_pack(
        failover_window_s=10.0)}
    r = rules["fleet_failover_rate"]

    def snap(n):
        return {"fleet_failovers_total": _fam(
            "counter", ({"kind": "generate"}, float(n)))}

    r.step(snap(0), 0.0)
    assert r.step(snap(0), 1.0) is None and r.state == "inactive"
    assert r.step(snap(1), 2.0) == "alert_firing"
    assert r.step(snap(1), 13.0) == "alert_resolved"


def test_trainer_pack_goodput_and_packs_silent_without_data():
    rules = {r.id: r for r in trainer_rule_pack(goodput_floor=0.5)}
    g = rules["train_goodput_drop"]
    # packs stay silent on empty snapshots ("no data")
    for r in rules.values():
        assert r.step({}, 0.0) is None and r.state == "inactive"
    assert g.step(_gauge_snap("goodput_fraction_good", 0.2), 1.0) == \
        "alert_firing"
    # hysteresis clear = floor * 1.2
    assert g.step(_gauge_snap("goodput_fraction_good", 0.55), 2.0) \
        is None and g.firing
    assert g.step(_gauge_snap("goodput_fraction_good", 0.9), 3.0) == \
        "alert_resolved"


def test_serving_pack_compile_tripwire():
    rules = {r.id: r for r in serving_rule_pack()}
    r = rules["serving_post_warmup_compiles"]
    assert r.step(_gauge_snap("serving_post_warmup_compiles", 0.0),
                  0.0) is None
    assert r.step(_gauge_snap("serving_post_warmup_compiles", 1.0),
                  1.0) == "alert_firing"


# ---------------------------------------------------------------------------
# FlightRecorder
# ---------------------------------------------------------------------------

class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_bundle_contents_and_manifest(tmp_path):
    log = RunEventLog(str(tmp_path / "ev.jsonl"))
    for i in range(5):
        log.event("run_note", i=i)
    reg = MetricsRegistry()
    reg.register("toy", lambda: [counter("toy_total", "", 3.0)])
    eng = AlertEngine(reg, rules=[
        ThresholdRule("hot", "toy_total", threshold=1.0)])
    eng.evaluate(now=0.0)
    rec = FlightRecorder(str(tmp_path / "fr"), registry=reg,
                         event_log=log)
    rec.alert_engine = eng
    path = rec.record("test_reason", context={"k": "v"})
    assert path is not None and os.path.isdir(path)
    assert os.path.basename(path) == "bundle_001_test_reason"
    man = json.load(open(os.path.join(path, "MANIFEST.json")))
    assert man["reason"] == "test_reason"
    assert man["context"] == {"k": "v"}
    assert man["errors"] == {} and man["truncated"] is False
    assert set(man["files"]) == {"events_tail.jsonl", "metrics.json",
                                 "alerts.json", "stacks.txt"}
    tail = open(os.path.join(path, "events_tail.jsonl")).read()
    assert '"run_note"' in tail
    metrics = json.load(open(os.path.join(path, "metrics.json")))
    assert metrics["toy_total"]["samples"][0]["value"] == 3.0
    alerts = json.load(open(os.path.join(path, "alerts.json")))
    assert alerts["firing"] == ["hot"]
    stacks = open(os.path.join(path, "stacks.txt")).read()
    assert "test_bundle_contents_and_manifest" in stacks
    # the flight_record event landed (strict mode: kind registered)
    log.close()
    fr = [e for e in read_events(log.path)
          if e["event"] == "flight_record"]
    assert len(fr) == 1 and fr[0]["reason"] == "test_reason"
    assert fr[0]["path"] == path


def test_rate_limit_count_cap_and_force(tmp_path):
    clk = _FakeClock()
    rec = FlightRecorder(str(tmp_path / "fr"), min_interval_s=60.0,
                         max_bundles=3, clock=clk)
    assert rec.record("a") is not None
    assert rec.record("b") is None          # rate-limited
    assert rec.suppressed == 1
    assert rec.record("c", force=True) is not None  # force bypasses
    clk.t = 120.0
    assert rec.record("d") is not None
    assert rec.record("e", force=True) is None  # count cap holds
    assert rec.suppressed == 2
    assert len(rec.bundles) == 3
    snap = rec.snapshot()
    assert snap["suppressed"] == 2 and len(snap["bundles"]) == 3


def test_bundle_byte_budget_truncates_and_records_it(tmp_path):
    reg = MetricsRegistry()
    reg.register("big", lambda: [
        gauge("big_gauge", "x" * 64, float(i), idx=i)
        for i in range(200)])
    rec = FlightRecorder(str(tmp_path / "fr"), registry=reg,
                         max_bundle_bytes=512)
    path = rec.record("big")
    man = json.load(open(os.path.join(path, "MANIFEST.json")))
    assert man["truncated"] is True
    total = sum(man["files"].values())
    assert total <= 512
    assert "stacks.txt" in man["skipped"]  # budget spent before it


def test_section_error_isolated_into_manifest(tmp_path):
    class Sick:
        def snapshot(self):
            raise RuntimeError("scrape died")

    rec = FlightRecorder(str(tmp_path / "fr"), registry=Sick())
    path = rec.record("sick")
    assert path is not None
    man = json.load(open(os.path.join(path, "MANIFEST.json")))
    assert "metrics.json" in man["errors"]
    assert "scrape died" in man["errors"]["metrics.json"]
    assert "stacks.txt" in man["files"]  # later sections still wrote


def test_attach_engine_bundles_on_firing(tmp_path):
    reg = _MutableRegistry(_gauge_snap("load", 1.0))
    eng = AlertEngine(reg, rules=[
        ThresholdRule("hot", "load", threshold=5.0, clear=3.0)])
    rec = FlightRecorder(str(tmp_path / "fr"), min_interval_s=0.0)
    rec.attach_engine(eng)
    eng.evaluate(now=0.0)
    assert rec.bundles == []
    reg.snap = _gauge_snap("load", 9.0)
    eng.evaluate(now=1.0)
    assert len(rec.bundles) == 1
    assert os.path.basename(rec.bundles[0]) == "bundle_001_alert_hot"
    man = json.load(open(os.path.join(rec.bundles[0],
                                      "MANIFEST.json")))
    assert man["context"]["rule"] == "hot"
    assert man["context"]["value"] == 9.0
    alerts = json.load(open(os.path.join(rec.bundles[0],
                                         "alerts.json")))
    assert alerts["firing"] == ["hot"]  # state captured post-fire
    # resolve does not bundle; re-fire does
    reg.snap = _gauge_snap("load", 1.0)
    eng.evaluate(now=2.0)
    reg.snap = _gauge_snap("load", 9.0)
    eng.evaluate(now=3.0)
    assert len(rec.bundles) == 2


def test_watchdog_hook_captures_before_prior(tmp_path):
    rec = FlightRecorder(str(tmp_path / "fr"))
    calls = []

    def prior(fields):
        calls.append((len(rec.bundles), dict(fields)))

    hook = rec.watchdog_hook(prior)
    fields = {"what": "step 3", "kind": "hung_step", "budget_s": 1.0}
    hook(fields)
    # the bundle was already on disk when prior ran
    assert calls == [(1, fields)]
    assert os.path.basename(rec.bundles[0]) == \
        "bundle_001_hang_hung_step"
    man = json.load(open(os.path.join(rec.bundles[0],
                                      "MANIFEST.json")))
    assert man["context"]["what"] == "step 3"
    # prior still runs when the record itself is suppressed
    hook({"kind": "hung_step"})
    assert len(calls) == 2 and rec.suppressed == 1


def test_crash_hooks_capture_and_chain(tmp_path):
    seen = []
    orig_hook = sys.excepthook

    def dummy(*a):
        seen.append(a)

    sys.excepthook = dummy
    rec = FlightRecorder(str(tmp_path / "fr"), min_interval_s=0.0)
    try:
        rec.install_crash_hooks()
        rec.install_crash_hooks()  # idempotent
        assert sys.excepthook is not orig_hook
        try:
            raise ValueError("kaboom")
        except ValueError:
            sys.excepthook(*sys.exc_info())
        assert len(rec.bundles) == 1
        assert os.path.basename(rec.bundles[0]) == "bundle_001_crash"
        man = json.load(open(os.path.join(rec.bundles[0],
                                          "MANIFEST.json")))
        assert man["context"]["exc_type"] == "ValueError"
        assert "kaboom" in man["context"]["traceback"]
        assert len(seen) == 1  # the previous hook was chained
        assert rec._crash_pending is False  # write confirmed: the
        #                                     atexit sweep won't re-fire
        rec.uninstall_crash_hooks()
        assert sys.excepthook is dummy  # the wrapper is gone
    finally:
        rec.uninstall_crash_hooks()
        sys.excepthook = orig_hook


def test_atexit_sweep_only_on_pending_crash(tmp_path):
    rec = FlightRecorder(str(tmp_path / "fr"))
    rec._atexit_sweep()
    assert rec.bundles == []
    rec._crash_pending = True
    rec._atexit_sweep()
    assert len(rec.bundles) == 1
    assert "crash_atexit" in rec.bundles[0]


# ---------------------------------------------------------------------------
# Guard discipline: zero overhead, byte-identical lowering
# ---------------------------------------------------------------------------

def _named_program(lr=0.1):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        x = layers.data(name="x", shape=[8], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        h = layers.fc(x, size=16, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGDOptimizer(learning_rate=lr).minimize(loss)
    return main, startup, scope, loss


def test_engine_is_zero_overhead_and_lowering_identical():
    """The ISSUE 4/8 guard discipline applied to pillar 9: a live
    AlertEngine — trainer rule pack, background thread snapshotting
    the real registry mid-training — adds zero dispatches and zero
    retraces, and the step lowering is BYTE-IDENTICAL with or without
    it.  The engine only ever reads host-side counters."""
    rng_feed = {"x": np.random.RandomState(0)
                .rand(8, 8).astype(np.float32),
                "y": np.random.RandomState(1)
                .rand(8, 1).astype(np.float32)}

    def run_and_count(with_alerts):
        main, startup, scope, loss = _named_program()
        eng = None
        if with_alerts:
            reg = standard_collectors(MetricsRegistry())
            eng = AlertEngine(reg, rules=trainer_rule_pack(),
                              interval_s=0.005)
            reg.register("alerts", eng.collector())
            eng.start()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            snap = observe.runtime_stats.snapshot()
            for _ in range(3):
                exe.run(main, feed=rng_feed, fetch_list=[loss])
            delta = observe.runtime_stats.delta(snap)
            fn, state, feeds = exe._prepare(
                main, rng_feed, [loss.name], scope, 1, True)
            text = fn.lower(state, feeds).as_text()
        if eng is not None:
            deadline = time.monotonic() + 5.0
            while eng.evaluations == 0 \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            eng.close()
            assert eng.evaluations > 0  # it really ran mid-training
        return delta, text

    off, text_off = run_and_count(False)
    on, text_on = run_and_count(True)
    assert on["dispatches"] == off["dispatches"]
    assert on["retraces"] == off["retraces"] == 0
    assert "callback" not in text_on  # pure host: no round-trips
    assert text_on == text_off  # byte-identical step lowering
