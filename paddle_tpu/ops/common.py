"""Shared helpers for op implementations."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def first(ins, slot):
    return ins[slot][0]


def opt_in(ins, slot):
    vals = ins.get(slot)
    return vals[0] if vals else None


def out(**slots):
    return {slot: [v] for slot, v in slots.items()}


def broadcast_y(x, y, axis: int = -1):
    """Fluid elementwise broadcast: align y's dims to x starting at `axis`
    (reference: paddle/fluid/operators/elementwise/elementwise_op_function.h
    — the trailing-alignment rule with explicit axis).  When y outranks x
    (e.g. scalar-constant X from `1.0 / var`), fall back to numpy
    broadcasting, which handles the shape-(1,) constant case."""
    if x.ndim >= y.ndim:
        if x.ndim == y.ndim:
            return y
        if axis == -1:
            axis = x.ndim - y.ndim
        new_shape = ([1] * axis + list(y.shape)
                     + [1] * (x.ndim - axis - y.ndim))
        return y.reshape(new_shape)
    return y


def pair(value, n=2):
    """Normalize an int-or-list spatial attr to a tuple of length n."""
    if isinstance(value, (list, tuple)):
        if len(value) == 1:
            return tuple(value) * n
        return tuple(value)
    return (value,) * n


def to_jnp_dtype(name: str):
    """API dtype → runtime jnp dtype.

    The fluid API declares int64/float64 widely (labels, indices); with
    jax x64 disabled those silently truncate to 32-bit with a warning per
    call site.  Map them explicitly so the declared dtype matches the real
    runtime precision and the warnings disappear.
    """
    name = str(name)
    if not jax.config.jax_enable_x64:
        name = {"int64": "int32", "uint64": "uint32",
                "float64": "float32"}.get(name, name)
    return jnp.dtype(name)
