"""Mixture-of-Experts FFN + expert parallelism (ops/moe.py,
layers.switch_moe).

Routing semantics (top-1 switch / top-2, capacity drops, load-balance
aux loss) against hand-computed expectations, dense-equivalence when
every token fits one expert, and the ep path: expert weights sharded
over mp on the virtual 8-device mesh with sharded == unsharded parity.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.registry import OpContext, get_op_impl


def _run_moe(x, gate_w, w1, b1, w2, b2, **attrs):
    impl = get_op_impl("moe_ffn")
    ins = {"X": [jnp.asarray(x)], "GateW": [jnp.asarray(gate_w)],
           "W1": [jnp.asarray(w1)], "B1": [jnp.asarray(b1)],
           "W2": [jnp.asarray(w2)], "B2": [jnp.asarray(b2)]}
    outs = impl(OpContext(jax.random.PRNGKey(0), 0), ins, dict(attrs))
    return (np.asarray(outs["Out"][0]), float(outs["AuxLoss"][0][0]),
            np.asarray(outs["Fraction"][0]))


def _expert_ffn(x, w1, b1, w2, b2):
    return np.maximum(x @ w1 + b1, 0.0) @ w2 + b2


def test_top1_routing_matches_manual():
    """Each token goes to its argmax expert, output scaled by the
    softmax gate prob of that expert."""
    rng = np.random.RandomState(0)
    b, d, e, h = 5, 4, 3, 8
    x = rng.randn(b, d).astype(np.float32)
    gate_w = rng.randn(d, e).astype(np.float32)
    w1 = rng.randn(e, d, h).astype(np.float32) * 0.3
    b1 = rng.randn(e, h).astype(np.float32) * 0.1
    w2 = rng.randn(e, h, d).astype(np.float32) * 0.3
    b2 = rng.randn(e, d).astype(np.float32) * 0.1

    got, aux, frac = _run_moe(x, gate_w, w1, b1, w2, b2, top_k=1,
                              capacity_factor=e * 2.0)
    logits = x @ gate_w
    probs = np.exp(logits - logits.max(1, keepdims=True))
    probs /= probs.sum(1, keepdims=True)
    want = np.zeros_like(x)
    for i in range(b):
        ex = int(np.argmax(logits[i]))
        want[i] = probs[i, ex] * _expert_ffn(x[i], w1[ex], b1[ex],
                                             w2[ex], b2[ex])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(frac.sum(), 1.0, rtol=1e-6)
    assert aux >= 1.0 - 1e-5  # Switch aux loss is minimized at 1


def test_capacity_overflow_drops_tokens():
    """With capacity 1 and every token preferring the same expert, only
    the FIRST token (deterministic token order) is processed; dropped
    tokens output zero (residual carries them in a real block)."""
    b, d, e, h = 3, 4, 2, 4
    x = np.tile(np.asarray([[1.0, 0.5, -0.3, 0.2]], np.float32),
                (b, 1))
    gate_w = np.zeros((d, e), np.float32)
    gate_w[0, 0] = 5.0  # every token -> expert 0
    rng = np.random.RandomState(1)
    w1 = rng.randn(e, d, h).astype(np.float32) * 0.3
    b1 = np.zeros((e, h), np.float32)
    w2 = rng.randn(e, h, d).astype(np.float32) * 0.3
    b2 = np.zeros((e, d), np.float32)

    # capacity_factor chosen so cap = ceil(3/2)*f = 1
    got, _aux, frac = _run_moe(x, gate_w, w1, b1, w2, b2, top_k=1,
                               capacity_factor=0.5)
    assert np.abs(got[0]).sum() > 0
    np.testing.assert_allclose(got[1], 0.0, atol=1e-6)
    np.testing.assert_allclose(got[2], 0.0, atol=1e-6)
    np.testing.assert_allclose(frac, [1.0, 0.0], atol=1e-6)


def test_top2_routes_to_two_experts():
    """top_k=2: output is the GShard-normalized mix
    (p1*y1 + p2*y2) / (p1 + p2) of the two top experts."""
    rng = np.random.RandomState(2)
    b, d, e, h = 4, 4, 3, 6
    x = rng.randn(b, d).astype(np.float32)
    gate_w = rng.randn(d, e).astype(np.float32)
    w1 = rng.randn(e, d, h).astype(np.float32) * 0.3
    b1 = np.zeros((e, h), np.float32)
    w2 = rng.randn(e, h, d).astype(np.float32) * 0.3
    b2 = np.zeros((e, d), np.float32)

    got, _, _ = _run_moe(x, gate_w, w1, b1, w2, b2, top_k=2,
                         capacity_factor=e * 2.0)
    logits = x @ gate_w
    probs = np.exp(logits - logits.max(1, keepdims=True))
    probs /= probs.sum(1, keepdims=True)
    want = np.zeros_like(x)
    for i in range(b):
        e1, e2 = np.argsort(-logits[i])[:2]
        p1, p2 = probs[i, e1], probs[i, e2]
        y1 = _expert_ffn(x[i], w1[e1], b1[e1], w2[e1], b2[e1])
        y2 = _expert_ffn(x[i], w1[e2], b1[e2], w2[e2], b2[e2])
        want[i] = (p1 * y1 + p2 * y2) / (p1 + p2)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_top2_dropped_choice_never_amplifies():
    """When a token's higher choice is capacity-dropped, the kept
    expert contributes p_kept/(p1+p2) * y — the dropped mass vanishes
    instead of inflating the survivor."""
    d = e = 3
    x = np.asarray([[3, 2, 1], [3, 1, 2]], np.float32)
    gate_w = np.eye(d, dtype=np.float32)  # logits == x
    rng = np.random.RandomState(6)
    w1 = rng.randn(e, d, 4).astype(np.float32) * 0.3
    b1 = np.zeros((e, 4), np.float32)
    w2 = rng.randn(e, 4, d).astype(np.float32) * 0.3
    b2 = np.zeros((e, d), np.float32)

    # cap = ceil(2*2/3 * 0.7) = 1: token1's first choice (e0) is taken
    # by token0; its second choice (e2) is kept
    got, _, _ = _run_moe(x, gate_w, w1, b1, w2, b2, top_k=2,
                         capacity_factor=0.7)
    probs = np.exp(x - x.max(1, keepdims=True))
    probs /= probs.sum(1, keepdims=True)
    # token0: e0+e1 both kept
    p0, p1 = probs[0, 0], probs[0, 1]
    y0 = _expert_ffn(x[0], w1[0], b1[0], w2[0], b2[0])
    y1 = _expert_ffn(x[0], w1[1], b1[1], w2[1], b2[1])
    np.testing.assert_allclose(got[0], (p0 * y0 + p1 * y1) / (p0 + p1),
                               rtol=1e-4, atol=1e-5)
    # token1: e0 dropped, e2 kept at p2/(p0+p2) — NOT amplified to 1
    q0, q2 = probs[1, 0], probs[1, 2]
    z2 = _expert_ffn(x[1], w1[2], b1[2], w2[2], b2[2])
    np.testing.assert_allclose(got[1], q2 / (q0 + q2) * z2,
                               rtol=1e-4, atol=1e-5)


def test_switch_moe_layer_trains_and_balances():
    """layers.switch_moe in a real program: trains, aux loss finite,
    and the block's loss decreases."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 5
    scope = fluid.Scope()
    rng = np.random.RandomState(3)
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        x = layers.data("x", shape=[16])
        y = layers.data("y", shape=[1], dtype="int64")
        h, aux, frac = layers.switch_moe(x, num_experts=4, d_inner=32)
        logits = layers.fc(h, size=4)
        ce = layers.mean(layers.softmax_with_cross_entropy(logits, y))
        loss = layers.elementwise_add(
            ce, layers.scale(layers.reduce_sum(aux), scale=0.01))
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        losses = []
        xv = rng.randn(64, 16).astype(np.float32)
        yv = (np.abs(xv[:, :4]).argmax(1))[:, None].astype(np.int64)
        for _ in range(25):
            lv, av, fv = exe.run(main, feed={"x": xv, "y": yv},
                                 fetch_list=[loss, aux, frac])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
            assert np.isfinite(float(np.asarray(av).reshape(-1)[0]))
        # routing fractions are fetchable and sum to 1 over experts
        np.testing.assert_allclose(np.asarray(fv).sum(), 1.0, rtol=1e-5)
    assert losses[-1] < losses[0]


def test_expert_parallel_sharded_parity():
    """ep: expert weights shard over mp on a dp2 x mp4 mesh (E=4 -> one
    expert per mp slice); the sharded trajectory matches unsharded."""
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.strategies import megatron_transformer_rules

    def run(mesh):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 7
        scope = fluid.Scope()
        losses = []
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), fluid.unique_name.guard():
            x = layers.data("x", shape=[8])
            y = layers.data("y", shape=[1], dtype="int64")
            h, aux, _frac = layers.switch_moe(x, num_experts=4, d_inner=16,
                                       capacity_factor=4.0)
            logits = layers.fc(h, size=3)
            ce = layers.mean(layers.softmax_with_cross_entropy(
                logits, y))
            loss = layers.elementwise_add(
                ce, layers.scale(layers.reduce_sum(aux), scale=0.01))
            fluid.optimizer.MomentumOptimizer(
                learning_rate=0.05, momentum=0.9).minimize(loss)
            exe = fluid.Executor()
            exe.run(startup)
            prog = main
            if mesh is not None:
                bs = fluid.BuildStrategy()
                bs.sharding_rules = megatron_transformer_rules()
                prog = fluid.CompiledProgram(main).with_data_parallel(
                    loss_name=loss.name, build_strategy=bs, mesh=mesh)
            rng = np.random.RandomState(4)
            xv = rng.randn(16, 8).astype(np.float32)
            yv = rng.randint(0, 3, (16, 1)).astype(np.int64)
            for _ in range(4):
                lv, = exe.run(prog, feed={"x": xv, "y": yv},
                              fetch_list=[loss])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
            if mesh is not None:
                w1 = fluid.global_scope().find_var(
                    next(n for n in scope.vars
                         if "moe_expert" in n and ".w" in n))
                shard_shapes = {s.data.shape
                                for s in w1.addressable_shards}
                # E=4 split over mp=4: one expert per slice
                assert any(sh[0] == 1 for sh in shard_shapes), \
                    shard_shapes
        return losses

    sharded = run(make_mesh({"dp": 2, "mp": 4}))
    single = run(None)
    np.testing.assert_allclose(sharded, single, rtol=1e-4, atol=1e-5)
    assert sharded[-1] < sharded[0]


def test_moe_dedicated_ep_axis_parity_and_all_to_all():
    """VERDICT r4 item 3: experts on their OWN ep axis composing with
    dp x mp (dp2 x mp2 x ep2).  The trajectory matches the unsharded
    program, expert weights shard over ep (and their hidden dim over
    mp), and the compiled HLO lowers dispatch/combine to GShard
    all-to-alls — NOT an all-gather of the (G, Bg, E, C) dispatch
    tensor."""
    import re

    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.strategies import megatron_transformer_rules

    def run(mesh):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 11
        scope = fluid.Scope()
        losses = []
        hlo = None
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), fluid.unique_name.guard():
            x = layers.data("x", shape=[8])
            y = layers.data("y", shape=[1], dtype="int64")
            h, aux, _frac = layers.switch_moe(
                x, num_experts=4, d_inner=16, capacity_factor=4.0)
            logits = layers.fc(h, size=3)
            ce = layers.mean(layers.softmax_with_cross_entropy(
                logits, y))
            loss = layers.elementwise_add(
                ce, layers.scale(layers.reduce_sum(aux), scale=0.01))
            fluid.optimizer.MomentumOptimizer(
                learning_rate=0.05, momentum=0.9).minimize(loss)
            exe = fluid.Executor()
            exe.run(startup)
            prog = main
            if mesh is not None:
                bs = fluid.BuildStrategy()
                bs.sharding_rules = megatron_transformer_rules(
                    moe_axis="ep")
                prog = fluid.CompiledProgram(main).with_data_parallel(
                    loss_name=loss.name, build_strategy=bs, mesh=mesh)
            rng = np.random.RandomState(4)
            xv = rng.randn(16, 8).astype(np.float32)
            yv = rng.randint(0, 3, (16, 1)).astype(np.int64)
            feed = {"x": xv, "y": yv}
            for _ in range(4):
                lv, = exe.run(prog, feed=feed, fetch_list=[loss])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
            if mesh is not None:
                w1_name = next(n for n in scope.vars
                               if "moe_expert" in n and ".w_0" in n)
                w1 = scope.find_var(w1_name)
                shard_shapes = {s.data.shape
                                for s in w1.addressable_shards}
                # (E=4, D=8, H=16) over (ep=2, -, mp=2): (2, 8, 8)
                assert (2, 8, 8) in shard_shapes, shard_shapes
                hlo = prog.compiled_hlo_text(feed, [loss.name], scope)
        return losses, hlo

    sharded, hlo = run(make_mesh({"dp": 2, "mp": 2, "ep": 2}))
    single, _ = run(None)
    np.testing.assert_allclose(sharded, single, rtol=1e-4, atol=1e-5)
    assert sharded[-1] < sharded[0]
    n_a2a = len(re.findall(r"all-to-all", hlo))
    assert n_a2a >= 2, f"expected GShard all-to-alls, found {n_a2a}"
    # the dispatch tensor itself must not be all-gathered: no
    # all-gather result should carry the (E, C) = (4, 8) trailing dims
    # of a full dispatch/combine buffer
    for m in re.finditer(r"all-gather\S*\(", hlo):
        line = hlo[m.start() - 200:m.start() + 40]
        assert "4,8,8]" not in line.split("=")[0], (
            "dispatch tensor all-gathered:\n" + line)


def test_moe_transformer_trains_and_shards():
    """Transformer with moe_experts=4: trains on a tiny config, and the
    ep-sharded run (experts over mp) matches the unsharded trajectory."""
    from paddle_tpu.models import transformer
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.strategies import megatron_transformer_rules

    def run(mesh):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 9
        scope = fluid.Scope()
        losses = []
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), fluid.unique_name.guard():
            model = transformer.build_model(
                src_vocab_size=64, trg_vocab_size=64, max_length=8,
                n_layer=1, n_head=4, d_model=32, d_inner_hid=64,
                dropout=0.0, moe_experts=4)
            exe = fluid.Executor()
            exe.run(startup)
            prog = main
            if mesh is not None:
                bs = fluid.BuildStrategy()
                bs.sharding_rules = megatron_transformer_rules()
                prog = fluid.CompiledProgram(main).with_data_parallel(
                    loss_name=model["loss"].name, build_strategy=bs,
                    mesh=mesh)
            feed = transformer.make_fake_batch(8, 8, 64, 64)
            for _ in range(3):
                lv, = exe.run(prog, feed=feed,
                              fetch_list=[model["loss"]])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
        return losses

    sharded = run(make_mesh({"dp": 2, "mp": 4}))
    single = run(None)
    assert all(np.isfinite(sharded))
    assert sharded[-1] < sharded[0]
    np.testing.assert_allclose(sharded, single, rtol=1e-4, atol=1e-5)


def test_moe_program_exports_through_predictor(tmp_path):
    """A switch-MoE program exports via save_inference_model and the
    AOT Predictor's output matches the executor's (routing einsums and
    capacity logic all inside the jitted serving computation)."""
    scope = fluid.Scope()
    rng = np.random.RandomState(11)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data("x", shape=[12])
        h, aux, _frac = layers.switch_moe(x, num_experts=4, d_inner=24,
                                          capacity_factor=4.0)
        out = layers.fc(h, size=3, act="softmax")
        exe = fluid.Executor()
        exe.run(startup)
        xv = rng.rand(8, 12).astype(np.float32)
        (ref,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
        d = str(tmp_path / "moe_model")
        fluid.io.save_inference_model(d, ["x"], [out], exe,
                                      main_program=main)
    pred = fluid.Predictor(d)
    (got,) = pred.run({"x": xv})
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
