"""AsyncExecutor: file-shard training with a threaded host pipeline.

TPU-native analog of the reference AsyncExecutor
(reference: paddle/fluid/framework/async_executor.cc:72-234 — per-thread
ExecutorThreadWorker instances each parsing a file shard and running the
program op-by-op; python/paddle/fluid/async_executor.py wrapper).

Architecture shift: the reference parallelized *compute* across CPU
threads (one program replica per thread, shared params).  On TPU the
device serializes compute anyway, so the thread pool moves to where it
still matters — parsing file shards — and the single jitted train step
consumes a merged device-fed queue (data/pipeline.py DeviceFeeder).
Semantics match: shards are walked once per epoch, fetch vars report
periodically, and parsing overlaps device compute.

The Baidu-pslib distributed-KV path (async_executor.cc init_server/
init_worker) is obsolete on TPU: sharded embedding tables over the mesh
(parallel/, SparseGrad) replace the parameter server — documented
divergence, same capability.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .core.executor import Executor, Scope, global_scope, scope_guard
from .core.program import Program
from .data.data_feed import DataFeedDesc, MultiSlotDataFeed
from .data.pipeline import DeviceFeeder


class AsyncExecutor:
    """reference: python/paddle/fluid/async_executor.py AsyncExecutor."""

    def __init__(self, place=None, run_mode: str = ""):
        self.place = place
        self._exe = Executor(place)

    def run(self, program: Program, data_feed: DataFeedDesc,
            filelist: Sequence[str], thread_num: Optional[int] = None,
            fetch: Sequence = (), mode: str = "", debug: bool = False,
            scope: Optional[Scope] = None,
            report_every: int = 100) -> Dict[str, float]:
        """Train over `filelist` once.  thread_num parser threads split
        the shards (reference async_executor.cc: files round-robin over
        threads; default FLAGS.paddle_num_threads); fetch vars are
        averaged and (debug=True) printed every `report_every` steps.
        Returns {fetch_name: mean_over_run}.
        """
        if thread_num is None:
            from .flags import FLAGS

            thread_num = int(FLAGS.paddle_num_threads)
        if thread_num < 1:
            raise ValueError("thread_num must be >= 1")
        if not filelist:
            raise ValueError("empty filelist")
        feed_parser = MultiSlotDataFeed(data_feed)
        fetch_names = [f if isinstance(f, str) else f.name for f in fetch]

        # shard files over parser threads; each thread's batches merge
        # into one bounded device queue
        shards: List[List[str]] = [list(filelist[i::thread_num])
                                   for i in range(thread_num)]
        shards = [s for s in shards if s]

        import queue as queue_mod
        import threading

        from .data.decorator import _ReaderError

        merged: "queue_mod.Queue" = queue_mod.Queue(maxsize=4 * len(shards))
        _STOP = object()
        abort = threading.Event()

        def _put(item) -> bool:
            while not abort.is_set():
                try:
                    merged.put(item, timeout=0.1)
                    return True
                except queue_mod.Full:
                    continue
            return False

        def worker(paths):
            # shard failures surface on the consumer (reference: the
            # ExecutorThreadWorker aborts the run on reader errors) —
            # never silently truncate the dataset
            try:
                for batch in feed_parser.batches(paths):
                    if not _put(batch):
                        return
                _put(_STOP)
            except BaseException as e:
                _put(_ReaderError(e))

        threads = [threading.Thread(target=worker, args=(s,), daemon=True)
                   for s in shards]
        for t in threads:
            t.start()

        def reader():
            done = 0
            while done < len(threads):
                item = merged.get()
                if item is _STOP:
                    done += 1
                    continue
                if isinstance(item, _ReaderError):
                    raise RuntimeError(
                        "async_executor shard reader failed"
                    ) from item.error
                yield item

        feeder = DeviceFeeder(reader, capacity=4)
        totals = {n: 0.0 for n in fetch_names}
        steps = 0
        target_scope = scope or global_scope()
        try:
            with scope_guard(target_scope):
                for feed in feeder:
                    vals = self._exe.run(program, feed=feed,
                                         fetch_list=list(fetch_names))
                    steps += 1
                    for n, v in zip(fetch_names, vals):
                        totals[n] += float(np.asarray(v).reshape(-1)[0])
                    if debug and steps % report_every == 0:
                        stats = ", ".join(
                            f"{n}={totals[n] / steps:.6f}"
                            for n in fetch_names)
                        print(f"[async_executor] step {steps}: {stats}")
        finally:
            # on any consumer-side exit, unblock and reap BOTH sides:
            # parser threads parked on merged.put (abort flag + drain)
            # AND the DeviceFeeder producer parked on merged.get (one
            # _STOP per worker completes reader()'s done-count)
            abort.set()
            try:
                while True:
                    merged.get_nowait()
            except queue_mod.Empty:
                pass
            for _ in threads:
                try:
                    merged.put_nowait(_STOP)
                except queue_mod.Full:
                    break
            feeder.reset()
            for t in threads:
                t.join(timeout=5)
        if steps == 0:
            raise RuntimeError(
                "no batches produced — check filelist contents and the "
                "DataFeedDesc batch_size vs shard sizes")
        return {n: totals[n] / steps for n in fetch_names}
