"""Host-side runtime accounting: compile/retrace counters, compile
wall-time, dispatch latency, device-memory snapshots.

reference analog: the reference tracked per-op host timings through
platform/profiler RecordEvent; on TPU the expensive host-side events
are XLA COMPILES (seconds each) and jit RETRACES (a shape change
silently recompiling the step), which are invisible without hooks.
Compile events come from `jax.monitoring` (the jit/pjit internals emit
`/jax/core/compile/backend_compile_duration` per backend compile);
retraces are detected in `Executor._prepare` by input-signature change
on an already-built step fn (jax re-traces per new shape/dtype
signature); dispatch timing is the host cost of enqueueing one
`Executor.run` (async — device completion is NOT included; the tunnel
RTT story lives in bench.py).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
# older jax emitted the `_sec`-suffixed name; accept both
_COMPILE_EVENT_ALIASES = (_COMPILE_EVENT, _COMPILE_EVENT + "_sec",
                          "/jax/core/compile/backend_compile_duration_sec")
# jaxpr tracing + mlir lowering: the host-side compilation work a cold
# dispatch pays BEFORE the backend compile — the goodput ledger folds
# it into the "compile" category so a first/replayed step's own time
# stays dispatch-sized
_TRACE_EVENT_PREFIXES = ("/jax/core/compile/jaxpr_trace_duration",
                         "/jax/core/compile/jaxpr_to_mlir_module_duration")

_FIELDS = ("compiles", "compile_time_s", "trace_time_s", "builds",
           "retraces", "dispatches", "dispatch_time_s")


class RuntimeStats:
    """Monotonic counters for the process; use snapshot()/delta() to
    attribute a region (a bench model, a telemetry window)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.compiles = 0           # XLA backend compiles (jax.monitoring)
        self.compile_time_s = 0.0   # total backend-compile wall time
        self.trace_time_s = 0.0     # jaxpr trace + mlir lowering wall
        self.builds = 0             # Executor step fns traced (cache miss)
        self.retraces = 0           # re-compiles of an existing step fn
        #                             caused by a feed signature change
        self.dispatches = 0         # Executor.run dispatch count
        self.dispatch_time_s = 0.0  # host enqueue time (async; excludes
        #                             device execution)
        self.last_dispatch_s = 0.0

    def record_compile(self, duration_s: float):
        with self._lock:
            self.compiles += 1
            self.compile_time_s += float(duration_s)

    def record_trace(self, duration_s: float):
        with self._lock:
            self.trace_time_s += float(duration_s)

    def record_build(self):
        with self._lock:
            self.builds += 1

    def record_retrace(self):
        with self._lock:
            self.retraces += 1

    def record_dispatch(self, duration_s: float):
        with self._lock:
            self.dispatches += 1
            self.dispatch_time_s += float(duration_s)
            self.last_dispatch_s = float(duration_s)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {f: getattr(self, f) for f in _FIELDS}

    def delta(self, since: Dict[str, Any]) -> Dict[str, Any]:
        now = self.snapshot()
        return {f: now[f] - since.get(f, 0) for f in _FIELDS}


runtime_stats = RuntimeStats()

_installed = [False]


def install():
    """Register the jax.monitoring compile listener (idempotent).
    Called on first Executor use; listeners cannot be removed
    individually in jax, so this stays for the process lifetime —
    the callback is a counter bump, nanoseconds per compile."""
    if _installed[0]:
        return
    import jax.monitoring

    def _on_duration(event, duration, **_kw):
        if event in _COMPILE_EVENT_ALIASES:
            runtime_stats.record_compile(duration)
        elif event.startswith(_TRACE_EVENT_PREFIXES):
            runtime_stats.record_trace(duration)

    jax.monitoring.register_event_duration_secs_listener(_on_duration)
    _installed[0] = True


def device_memory_stats(device=None) -> Dict[str, Any]:
    """One device's allocator stats (keys like bytes_in_use,
    peak_bytes_in_use).  {} on backends that don't report (CPU)."""
    import jax

    d = device if device is not None else jax.local_devices()[0]
    try:
        stats = d.memory_stats()
    except Exception:  # noqa: BLE001 — backend-dependent API
        return {}
    return dict(stats) if stats else {}


def peak_memory_bytes() -> Optional[int]:
    """Max peak_bytes_in_use across local devices, or None when no
    device reports memory stats (the CPU test backend)."""
    import jax

    peaks = []
    for d in jax.local_devices():
        stats = device_memory_stats(d)
        if "peak_bytes_in_use" in stats:
            peaks.append(int(stats["peak_bytes_in_use"]))
    return max(peaks) if peaks else None


class LatencyHistogram:
    """Fixed log-spaced latency histogram with percentile estimates.

    Serving telemetry needs p50/p95/p99 over unbounded request streams
    without storing samples: log-spaced bins (default 20/decade from
    10 µs to 60 s ≈ 7% relative resolution) hold counts only, so
    record() is O(1), memory is constant, and merged windows stay
    exact.  percentile() returns the upper edge of the bin holding the
    rank — a ≤7% overestimate, never an underestimate (latency SLOs
    should round pessimistically).  Thread-safe.
    """

    def __init__(self, lo_ms: float = 0.01, hi_ms: float = 60000.0,
                 bins_per_decade: int = 20):
        import math

        if not (0 < lo_ms < hi_ms):
            raise ValueError("need 0 < lo_ms < hi_ms")
        self._lo = lo_ms
        self._k = bins_per_decade
        self._nbins = (int(math.ceil(
            math.log10(hi_ms / lo_ms) * bins_per_decade)) + 2)
        # bin 0 catches < lo_ms; the last bin catches >= hi_ms
        self._counts = [0] * self._nbins
        self._lock = threading.Lock()
        self.count = 0
        self.sum_ms = 0.0
        self.max_ms = 0.0

    def _bin(self, ms: float) -> int:
        import math

        if ms < self._lo:
            return 0
        idx = int(math.log10(ms / self._lo) * self._k) + 1
        return min(idx, self._nbins - 1)

    def _edge(self, idx: int) -> float:
        # upper edge of bin idx (bin 0's edge is lo_ms itself)
        return self._lo * 10.0 ** (idx / self._k)

    def record(self, ms: float):
        ms = float(ms)
        with self._lock:
            self._counts[self._bin(ms)] += 1
            self.count += 1
            self.sum_ms += ms
            if ms > self.max_ms:
                self.max_ms = ms

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Fold `other`'s counts into this histogram IN PLACE (and
        return self) — the "merged windows stay exact" contract:
        bin-wise count addition loses nothing, so percentiles over the
        merged histogram equal percentiles over one histogram that had
        recorded every sample of both.  Bin configs must match
        (lo/bins-per-decade/bin count); merging histograms with
        different edges would silently mis-bin, so it is rejected."""
        if not isinstance(other, LatencyHistogram):
            raise TypeError(f"cannot merge {type(other).__name__} into "
                            f"LatencyHistogram")
        if (self._lo, self._k, self._nbins) != (other._lo, other._k,
                                                other._nbins):
            raise ValueError(
                f"histogram bin configs differ: "
                f"(lo_ms={self._lo}, bins_per_decade={self._k}, "
                f"nbins={self._nbins}) vs (lo_ms={other._lo}, "
                f"bins_per_decade={other._k}, nbins={other._nbins})")
        # lock ordering: snapshot other first, then fold under our lock
        # (never hold both — merge(a, b) vs merge(b, a) would deadlock)
        with other._lock:
            counts = list(other._counts)
            o_count, o_sum, o_max = other.count, other.sum_ms, other.max_ms
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self.count += o_count
            self.sum_ms += o_sum
            if o_max > self.max_ms:
                self.max_ms = o_max
        return self

    def cumulative_buckets(self):
        """[(upper_edge_ms, cumulative_count), ...] over the non-empty
        bins — the Prometheus `le` mapping: each log-spaced bin's upper
        edge becomes an `le` value and the counts are exact prefix
        sums, so a scraped histogram reproduces this histogram's
        percentiles to bin resolution (the exposition contract of
        observe.registry; pinned by tests)."""
        with self._lock:
            counts = list(self._counts)
        out = []
        acc = 0
        for i, c in enumerate(counts):
            if c:
                acc += c
                out.append((self._edge(i), acc))
        return out

    def percentile(self, p: float) -> Optional[float]:
        """p in [0, 100] → latency ms (bin upper edge), None if empty."""
        with self._lock:
            if self.count == 0:
                return None
            rank = p / 100.0 * self.count
            acc = 0
            for i, c in enumerate(self._counts):
                acc += c
                if acc >= rank:
                    # never report past the observed max (the top bins
                    # are coarse)
                    return min(self._edge(i), self.max_ms)
            return self.max_ms

    def summary(self) -> Dict[str, Any]:
        """{count, mean_ms, sum_ms, max_ms, p50_ms, p95_ms, p99_ms} —
        the serving_window wire form."""
        with self._lock:
            count, total, mx = self.count, self.sum_ms, self.max_ms
        out: Dict[str, Any] = {"count": count}
        out["sum_ms"] = round(total, 3)
        out["mean_ms"] = round(total / count, 3) if count else None
        out["max_ms"] = round(mx, 3) if count else None
        for p in (50, 95, 99):
            v = self.percentile(p)
            out[f"p{p}_ms"] = round(v, 3) if v is not None else None
        return out


class dispatch_timer:
    """Context manager stamping one dispatch into runtime_stats."""

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        runtime_stats.record_dispatch(time.perf_counter() - self._t0)
        return False
