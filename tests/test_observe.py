"""Observability subsystem (paddle_tpu.observe): trace attribution,
device-side StepTelemetry, compile/retrace accounting, run events.

Locks in the architecture rules of docs/OBSERVE.md:
- op scopes reach XLA HLO metadata (the trace-attribution pillar),
- the telemetry accumulator lives INSIDE the one jitted step (no
  callbacks in the lowering, survives chain_iterations with zero extra
  dispatches),
- a feed shape change on a cached step counts exactly one retrace,
- the JSONL event log round-trips.
"""

import json
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, observe


def _linreg_program(batch_feed_names=("x", "y")):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    return main, startup, scope, loss


def _feed(rng, n=8):
    return {"x": rng.rand(n, 4).astype(np.float32),
            "y": rng.rand(n, 1).astype(np.float32)}


def test_named_scopes_reach_compiled_hlo_and_no_callbacks():
    main, startup, scope, loss = _linreg_program()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        fn, state, feeds = exe._prepare(
            main, _feed(rng), [loss.name], scope, 1, True)
        lowered = fn.lower(state, feeds)
        stablehlo = lowered.as_text()
        # the ONE-computation invariant: telemetry/observability must
        # not introduce host round-trips
        assert "callback" not in stablehlo
        compiled_hlo = lowered.compile().as_text()
    # every op lowering is scoped "<op_type>:<op_index>" and the scope
    # survives into XLA's op metadata (what device traces attribute by)
    for op_type in ("mul", "mean", "sgd"):
        assert f"{op_type}:" in compiled_hlo, \
            f"scope for {op_type!r} missing from compiled HLO metadata"


def test_telemetry_accumulates_across_chained_iterations():
    main, startup, scope, loss = _linreg_program()
    observe.enable_telemetry(main)
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        feed = _feed(rng)
        exe.run(main, feed=feed, fetch_list=[loss])
        # 4 more steps in ONE dispatch: the accumulator must ride the
        # fori_loop carry, not a per-step host fetch
        exe.run(main, feed=feed, fetch_list=[loss], iterations=4)
    tel = observe.fetch_telemetry(scope)
    assert tel.steps == 5
    assert tel.loss_mean > 0.0
    assert tel.grad_norm_mean > 0.0
    assert tel.update_norm_mean > 0.0
    assert tel.healthy
    # the lowered telemetry-enabled step is still callback-free
    with fluid.scope_guard(scope):
        fn, state, feeds = exe._prepare(
            main, _feed(rng), [loss.name], scope, 4, True)
        assert "callback" not in fn.lower(state, feeds).as_text()
    # fetch(reset=True) starts a fresh window
    with fluid.scope_guard(scope):
        exe.run(main, feed=_feed(rng), fetch_list=[loss])
    tel2 = observe.fetch_telemetry(scope)
    assert tel2.steps == 1


def test_telemetry_counts_nonfinite_loss_and_grads():
    main, startup, scope, loss = _linreg_program()
    observe.enable_telemetry(main)
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        bad = _feed(rng)
        bad["x"][0, 0] = np.nan
        exe.run(main, feed=bad, fetch_list=[loss])
    tel = observe.fetch_telemetry(scope)
    assert tel.steps == 1
    assert tel.nonfinite_loss_steps == 1
    assert tel.nonfinite_grad_steps == 1
    assert not tel.healthy


def test_telemetry_off_is_zero_footprint():
    main, startup, scope, loss = _linreg_program()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        exe.run(main, feed=_feed(rng), fetch_list=[loss])
    assert scope.find_var(observe.TELEMETRY_VAR) is None
    assert observe.fetch_telemetry(scope) is None


def test_retrace_counter_increments_exactly_once_on_shape_change():
    main, startup, scope, loss = _linreg_program()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        exe.run(main, feed=_feed(rng, 8), fetch_list=[loss])
        snap = observe.runtime_stats.snapshot()
        # same signature: cached, no retrace
        exe.run(main, feed=_feed(rng, 8), fetch_list=[loss])
        assert observe.runtime_stats.delta(snap)["retraces"] == 0
        # new batch size = new jit signature = exactly one retrace
        exe.run(main, feed=_feed(rng, 6), fetch_list=[loss])
        d = observe.runtime_stats.delta(snap)
        assert d["retraces"] == 1
        # seen signature again: still one
        exe.run(main, feed=_feed(rng, 6), fetch_list=[loss])
        assert observe.runtime_stats.delta(snap)["retraces"] == 1


def test_compile_accounting_sees_backend_compiles():
    main, startup, scope, loss = _linreg_program()
    rng = np.random.RandomState(1)
    snap = observe.runtime_stats.snapshot()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        exe.run(main, feed=_feed(rng), fetch_list=[loss])
    d = observe.runtime_stats.delta(snap)
    assert d["compiles"] >= 1
    assert d["compile_time_s"] > 0.0
    assert d["builds"] >= 1
    assert d["dispatches"] >= 1


def test_event_log_roundtrip(tmp_path):
    path = os.path.join(str(tmp_path), "events.jsonl")
    with observe.RunEventLog(path, mesh_shape={"dp": 8}) as log:
        rid = log.run_id
        log.event("checkpoint", serial=3, epoch=1)
        log.telemetry_window({"steps": 10, "loss_mean": 0.5},
                             retraces=0)
    events = observe.read_events(path)
    kinds = [e["event"] for e in events]
    assert kinds == ["run_begin", "checkpoint", "telemetry", "run_end"]
    assert all(e["run_id"] == rid for e in events)
    begin = events[0]
    assert "git_sha" in begin and "argv" in begin
    assert begin["mesh_shape"] == {"dp": 8}
    assert events[2]["steps"] == 10 and events[2]["retraces"] == 0
    # a torn final line (killed writer) is tolerated; corruption in the
    # middle is not
    with open(path, "a") as f:
        f.write('{"ts": 1, "run_id"')
    assert len(observe.read_events(path)) == 4
    with open(path, "a") as f:
        f.write('\n{"ok": true}\n')
    with pytest.raises(json.JSONDecodeError):
        observe.read_events(path)


def test_fluid_op_of_scope_parsing():
    assert observe.fluid_op_of("jit(step)/mul:3/dot_general") == "mul"
    assert observe.fluid_op_of(
        "jit(step)/while/body/conv2d:12/convolution") == "conv2d"
    # innermost scope wins (nested macro op -> sub-block op)
    assert observe.fluid_op_of("jit(f)/while_op:2/mul:7/mul") == "mul"
    assert observe.fluid_op_of("jit(f)/transpose/no_scope_here") is None


def test_trace_summary_attributes_fluid_ops(tmp_path, capsys):
    """End-to-end pillar 1: run a step under profiler.profiler(), then
    the parsed per-op table must attribute device time to fluid op
    types (XLA:CPU emits per-instruction events, so this works on the
    test backend)."""
    from paddle_tpu import profiler

    main, startup, scope, loss = _linreg_program()
    rng = np.random.RandomState(0)
    trace_dir = os.path.join(str(tmp_path), "trace")
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        feed = _feed(rng)
        exe.run(main, feed=feed, fetch_list=[loss])  # compile outside
        with profiler.profiler(sorted_key="total",
                               profile_path=trace_dir):
            exe.run(main, feed=feed, fetch_list=[loss])
    printed = capsys.readouterr().out
    assert "Profiling Report" in printed
    rows = profiler.profile_table(trace_dir)
    assert rows, "no attributable device events parsed from trace"
    ops = {r["op_type"] for r in rows}
    fluid_ops = ops - {"[unattributed]"}
    assert fluid_ops, f"no fluid-op attribution in {ops}"
    for r in rows:
        assert r["calls"] >= 1
        assert r["total_ms"] >= 0.0
        assert 0.0 <= r["ratio"] <= 1.0


def test_trainer_telemetry_hook(tmp_path):
    from paddle_tpu.contrib import Trainer

    log_path = os.path.join(str(tmp_path), "run.jsonl")

    def train_func():
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1)
        return layers.mean(layers.square_error_cost(pred, y))

    trainer = Trainer(
        train_func=train_func,
        optimizer_func=lambda: fluid.optimizer.SGDOptimizer(
            learning_rate=0.05),
        telemetry=observe.TelemetryConfig(interval=2, log_path=log_path))

    rng = np.random.RandomState(0)

    def reader():
        for _ in range(5):
            yield _feed(rng)

    trainer.train(num_epochs=1, reader=reader)
    trainer.stop()
    assert trainer.last_telemetry is not None
    events = observe.read_events(log_path)
    kinds = [e["event"] for e in events]
    assert kinds[0] == "run_begin"
    assert "train_begin" in kinds and "train_end" in kinds
    windows = [e for e in events if e["event"] == "telemetry"]
    # 5 steps at interval 2 -> two full windows + the final flush of 1
    assert [w["steps"] for w in windows] == [2, 2, 1]
    for w in windows:
        assert w["loss_mean"] > 0.0
        assert "retraces" in w and "compile_time_s" in w
    assert windows[0]["epoch"] == 0
