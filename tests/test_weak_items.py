"""Round-2 weak-item coverage: remaining vision ops, sequence
scatter/reshape, ModelAverage/EMA, recordio, and broadened check_grad
coverage for previously-untested op families."""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from tests.op_test import check_grad, run_op


# ---------------------------------------------------------------------------
# vision ops
# ---------------------------------------------------------------------------

def test_pool3d_matches_numpy():
    rng = np.random.RandomState(0)
    x = rng.rand(2, 3, 4, 6, 6).astype(np.float32)
    got = run_op("pool3d", {"X": x},
                 attrs={"pooling_type": "max", "ksize": [2, 2, 2],
                        "strides": [2, 2, 2], "paddings": [0, 0, 0]})
    want = x.reshape(2, 3, 2, 2, 3, 2, 3, 2).max(axis=(3, 5, 7))
    np.testing.assert_allclose(got, want)
    gota = run_op("pool3d", {"X": x},
                  attrs={"pooling_type": "avg", "ksize": [2, 2, 2],
                         "strides": [2, 2, 2], "paddings": [0, 0, 0]})
    wanta = x.reshape(2, 3, 2, 2, 3, 2, 3, 2).mean(axis=(3, 5, 7))
    np.testing.assert_allclose(gota, wanta, rtol=1e-6)


def test_spp_output_shape_and_global_level():
    rng = np.random.RandomState(1)
    x = rng.rand(2, 3, 8, 8).astype(np.float32)
    got = run_op("spp", {"X": x},
                 attrs={"pyramid_height": 3, "pooling_type": "max"})
    # levels: 1 + 4 + 16 bins = 21 per channel
    assert got.shape == (2, 3 * 21)
    np.testing.assert_allclose(got[:, :3],
                               x.max(axis=(2, 3)), rtol=1e-6)


def test_roi_pool_simple():
    # identity feature map: rois crop maxima
    x = np.arange(36, dtype=np.float32).reshape(1, 1, 6, 6)
    rois = np.array([[0, 0, 0, 3, 3],     # top-left 4x4 region
                     [0, 2, 2, 5, 5]], np.float32)
    got = run_op("roi_pool", {"X": x, "ROIs": rois},
                 attrs={"pooled_height": 2, "pooled_width": 2,
                        "spatial_scale": 1.0})
    assert got.shape == (2, 1, 2, 2)
    # roi 0 covers rows 0..3, cols 0..3; 2x2 bins of a 4x4 window
    np.testing.assert_allclose(got[0, 0], [[7, 9], [19, 21]])
    np.testing.assert_allclose(got[1, 0], [[21, 23], [33, 35]])


def test_roi_align_constant_map():
    # constant feature map → every aligned value equals the constant
    x = np.full((1, 2, 5, 5), 3.25, np.float32)
    rois = np.array([[0, 0.5, 0.5, 4.0, 4.0]], np.float32)
    got = run_op("roi_align", {"X": x, "ROIs": rois},
                 attrs={"pooled_height": 3, "pooled_width": 3,
                        "spatial_scale": 1.0, "sampling_ratio": 2})
    np.testing.assert_allclose(got, 3.25, rtol=1e-6)


def test_affine_channel():
    rng = np.random.RandomState(2)
    x = rng.rand(2, 3, 4, 4).astype(np.float32)
    s = np.array([1.0, 2.0, 0.5], np.float32)
    b = np.array([0.0, -1.0, 3.0], np.float32)
    got = run_op("affine_channel", {"X": x, "Scale": s, "Bias": b})
    want = x * s[None, :, None, None] + b[None, :, None, None]
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_affine_grid_identity():
    theta = np.tile(np.array([[[1.0, 0, 0], [0, 1.0, 0]]], np.float32),
                    (2, 1, 1))
    got = run_op("affine_grid", {"Theta": theta},
                 attrs={"output_shape": [2, 3, 4, 5]},
                 out_slot="Output")
    assert got.shape == (2, 4, 5, 2)
    np.testing.assert_allclose(got[0, 0, 0], [-1.0, -1.0], atol=1e-6)
    np.testing.assert_allclose(got[0, -1, -1], [1.0, 1.0], atol=1e-6)


def test_crop():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    got = run_op("crop", {"X": x},
                 attrs={"offsets": [0, 1, 1], "shape": [2, 2, 2]})
    np.testing.assert_allclose(got, x[:, 1:3, 1:3])


def test_unpool_inverts_pool_with_index():
    rng = np.random.RandomState(3)
    x = rng.rand(2, 3, 4, 4).astype(np.float32)
    pooled = run_op("pool2d_with_index", {"X": x},
                    attrs={"ksize": [2, 2], "strides": [2, 2]})
    mask = run_op("pool2d_with_index", {"X": x},
                  attrs={"ksize": [2, 2], "strides": [2, 2]},
                  out_slot="Mask")
    up = run_op("unpool", {"X": pooled, "Indices": mask},
                attrs={"unpool_size": [4, 4]})
    # each max value lands back at its argmax position
    nz = up != 0
    np.testing.assert_allclose(up[nz], x[nz])
    assert nz.sum() == pooled.size


# ---------------------------------------------------------------------------
# sequence scatter / reshape
# ---------------------------------------------------------------------------

def test_sequence_scatter():
    x = np.zeros((2, 6), np.float32)
    ids = np.array([[1, 3, 3], [0, 5, 0]], np.int64)
    upd = np.array([[1.0, 2.0, 4.0], [7.0, 9.0, 100.0]], np.float32)
    ids_len = np.array([3, 2], np.int32)
    got = run_op("sequence_scatter",
                 {"X": x, "Ids": ids, "Updates": upd, "IdsLen": ids_len})
    want = np.zeros((2, 6), np.float32)
    want[0, 1] = 1.0
    want[0, 3] = 6.0       # duplicate ids sum
    want[1, 0] = 7.0       # third entry masked by IdsLen
    want[1, 5] = 9.0
    np.testing.assert_allclose(got, want)


def test_sequence_reshape():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    seq_len = np.array([2, 3], np.int32)
    got = run_op("sequence_reshape",
                 {"X": x, "SeqLen": seq_len}, attrs={"new_dim": 2})
    out_len = run_op("sequence_reshape",
                     {"X": x, "SeqLen": seq_len}, attrs={"new_dim": 2},
                     out_slot="OutLen")
    assert got.shape == (2, 6, 2)
    np.testing.assert_allclose(got[0, 0], [0, 1])
    np.testing.assert_array_equal(out_len, [4, 6])


# ---------------------------------------------------------------------------
# ModelAverage / EMA
# ---------------------------------------------------------------------------

def test_model_average_apply_restore():
    B = 4
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data("x", shape=[B, 4], append_batch_size=False)
        y = layers.data("y", shape=[B, 1], append_batch_size=False)
        p = layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="w"),
                      bias_attr=False)
        loss = layers.reduce_mean(layers.square_error_cost(p, y))
        fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
        ma = fluid.optimizer.ModelAverage(
            0.5, min_average_window=2, max_average_window=100)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"x": rng.rand(B, 4).astype(np.float32),
                "y": rng.rand(B, 1).astype(np.float32)}
        snaps = []
        for _ in range(6):
            exe.run(main, feed=feed, fetch_list=[loss])
            snaps.append(np.asarray(scope.find_var("w")).copy())
        current = np.asarray(scope.find_var("w")).copy()
        with ma.apply(exe):
            averaged = np.asarray(scope.find_var("w")).copy()
        restored = np.asarray(scope.find_var("w"))
        np.testing.assert_allclose(restored, current)
        # averaged weights differ from current and sit inside the hull of
        # per-step snapshots
        assert not np.allclose(averaged, current)
        assert averaged.min() >= np.min(snaps) - 1e-6
        assert averaged.max() <= np.max(snaps) + 1e-6


def test_ema_apply_restore():
    B = 4
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data("x", shape=[B, 4], append_batch_size=False)
        y = layers.data("y", shape=[B, 1], append_batch_size=False)
        p = layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="w"),
                      bias_attr=False)
        loss = layers.reduce_mean(layers.square_error_cost(p, y))
        fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
        ema = fluid.optimizer.ExponentialMovingAverage(decay=0.5)
        ema.update()
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(1)
        feed = {"x": rng.rand(B, 4).astype(np.float32),
                "y": rng.rand(B, 1).astype(np.float32)}
        for _ in range(5):
            exe.run(main, feed=feed, fetch_list=[loss])
        current = np.asarray(scope.find_var("w")).copy()
        shadow = np.asarray(scope.find_var("w.ema")).copy()
        assert not np.allclose(shadow, current)
        # apply() installs the bias-corrected shadow (zero-init
        # correction, reference ExponentialMovingAverage semantics)
        corrected = shadow / (1.0 - 0.5 ** 5)
        with ema.apply(exe):
            np.testing.assert_allclose(
                np.asarray(scope.find_var("w")), corrected, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(scope.find_var("w")),
                                   current)


# ---------------------------------------------------------------------------
# recordio
# ---------------------------------------------------------------------------

def test_recordio_roundtrip(tmp_path):
    from paddle_tpu.data import recordio

    rng = np.random.RandomState(4)
    samples = [(rng.rand(3, 4).astype(np.float32),
                rng.randint(0, 10, (2,)).astype(np.int64))
               for _ in range(25)]
    path = os.path.join(tmp_path, "data.recordio")
    n = recordio.write_arrays(path, samples, max_chunk_records=7)
    assert n == 25
    back = list(recordio.read_arrays(path))
    assert len(back) == 25
    for (a, b), (ra, rb) in zip(samples, back):
        np.testing.assert_array_equal(a, ra)
        np.testing.assert_array_equal(b, rb)
        assert ra.dtype == a.dtype and rb.dtype == b.dtype


def test_recordio_crc_detects_corruption(tmp_path):
    from paddle_tpu.data import recordio

    path = os.path.join(tmp_path, "c.recordio")
    recordio.write_arrays(path, [(np.arange(10, dtype=np.float32),)])
    data = bytearray(open(path, "rb").read())
    data[-1] ^= 0xFF  # flip a payload byte
    open(path, "wb").write(bytes(data))
    with pytest.raises(IOError, match="CRC"):
        list(recordio.read_arrays(path))


def test_recordio_reader_composes_with_pipeline(tmp_path):
    from paddle_tpu.data import decorator, recordio

    path = os.path.join(tmp_path, "d.recordio")
    samples = [(np.full((2,), i, np.float32), np.int64(i))
               for i in range(10)]
    recordio.write_arrays(path, samples)
    batched = decorator.batch(recordio.reader_creator(path), batch_size=4)
    batches = list(batched())
    assert len(batches) == 3
    assert len(batches[0]) == 4


# ---------------------------------------------------------------------------
# broadened grad checks (weak item: op test breadth)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("op_type,ins,attrs,slot", [
    ("group_norm",
     {"X": np.random.RandomState(5).rand(2, 4, 3, 3).astype(np.float32),
      "Scale": np.ones(4, np.float32), "Bias": np.zeros(4, np.float32)},
     {"groups": 2, "epsilon": 1e-5}, "Y"),
    ("interpolate",
     {"X": np.random.RandomState(6).rand(2, 3, 4, 4).astype(np.float32)},
     {"out_h": 8, "out_w": 8, "interp_method": "bilinear"}, "Out"),
    ("row_conv",
     {"X": np.random.RandomState(7).rand(2, 5, 4).astype(np.float32),
      "Filter": np.random.RandomState(8).rand(3, 4).astype(np.float32)},
     {}, "Out"),
    ("grid_sampler",
     {"X": np.random.RandomState(9).rand(1, 2, 4, 4).astype(np.float32),
      "Grid": (np.random.RandomState(10).rand(1, 3, 3, 2) * 1.6 - 0.8
               ).astype(np.float32)},
     {}, "Output"),
    ("hinge_loss",
     {"Logits": np.random.RandomState(11).randn(6, 1).astype(np.float32),
      "Labels": np.random.RandomState(12).randint(
          0, 2, (6, 1)).astype(np.float32)},
     {}, "Loss"),
    ("huber_loss",
     {"X": np.random.RandomState(13).randn(6, 1).astype(np.float32),
      "Y": np.random.RandomState(14).randn(6, 1).astype(np.float32)},
     {"delta": 1.0}, "Out"),
    ("kldiv_loss",
     {"X": np.random.RandomState(15).rand(4, 5).astype(np.float32),
      "Target": np.random.RandomState(16).rand(4, 5).astype(np.float32)},
     {"reduction": "mean"}, "Loss"),
])
def test_extra_grad_checks(op_type, ins, attrs, slot):
    grad_slot = next(iter(ins))
    try:
        check_grad(op_type, ins, grad_slot, attrs=attrs, out_slot=slot,
                   max_relative_error=1e-2)
    except KeyError:
        # some ops name their output slot differently; surface clearly
        raise AssertionError(
            f"{op_type}: output slot {slot!r} missing")


# ---------------------------------------------------------------------------
# native components
# ---------------------------------------------------------------------------

def test_native_recordio_codec_interop(tmp_path):
    """Native (C++) codec and pure-python fallback produce byte-compatible
    files (skip when no toolchain)."""
    from paddle_tpu.data import recordio
    from paddle_tpu.native import recordio_lib

    if recordio_lib() is None:
        pytest.skip("native toolchain unavailable")
    rng = np.random.RandomState(6)
    samples = [(rng.rand(4, 2).astype(np.float32),) for _ in range(9)]
    # native writer → python reader
    p1 = os.path.join(tmp_path, "n.recordio")
    recordio.write_arrays(p1, samples, max_chunk_records=4)
    orig = recordio._decode_chunk_native
    recordio._decode_chunk_native = lambda *a, **k: None
    try:
        back = list(recordio.read_arrays(p1))
    finally:
        recordio._decode_chunk_native = orig
    assert len(back) == 9
    np.testing.assert_array_equal(back[5][0], samples[5][0])
    # python writer → native reader
    p2 = os.path.join(tmp_path, "p.recordio")
    orig_e = recordio._encode_chunk_native
    recordio._encode_chunk_native = lambda *a, **k: None
    try:
        recordio.write_arrays(p2, samples, max_chunk_records=4)
    finally:
        recordio._encode_chunk_native = orig_e
    back2 = list(recordio.read_arrays(p2))
    assert len(back2) == 9
    np.testing.assert_array_equal(back2[2][0], samples[2][0])


def test_native_codec_crc_error(tmp_path):
    from paddle_tpu.data import recordio
    from paddle_tpu.native import recordio_lib

    if recordio_lib() is None:
        pytest.skip("native toolchain unavailable")
    path = os.path.join(tmp_path, "c.recordio")
    recordio.write_arrays(path, [(np.arange(6, dtype=np.float32),)])
    data = bytearray(open(path, "rb").read())
    data[-1] ^= 0xFF
    open(path, "wb").write(bytes(data))
    with pytest.raises(IOError):
        list(recordio.read_arrays(path))
