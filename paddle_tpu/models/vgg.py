"""VGG-16 (reference: benchmark/fluid/models/vgg.py)."""

from __future__ import annotations

from .. import layers, nets, optimizer


def vgg16_bn_drop(input):
    def conv_block(inp, num_filter, groups, dropouts):
        return nets.img_conv_group(
            input=inp, pool_size=2, pool_stride=2,
            conv_num_filter=[num_filter] * groups, conv_filter_size=3,
            conv_act="relu", conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts, pool_type="max")

    conv1 = conv_block(input, 64, 2, [0.3, 0])
    conv2 = conv_block(conv1, 128, 2, [0.4, 0])
    conv3 = conv_block(conv2, 256, 3, [0.4, 0.4, 0])
    conv4 = conv_block(conv3, 512, 3, [0.4, 0.4, 0])
    conv5 = conv_block(conv4, 512, 3, [0.4, 0.4, 0])

    drop = layers.dropout(x=conv5, dropout_prob=0.5)
    fc1 = layers.fc(input=drop, size=512, act=None)
    bn = layers.batch_norm(input=fc1, act="relu")
    drop2 = layers.dropout(x=bn, dropout_prob=0.5)
    fc2 = layers.fc(input=drop2, size=512, act=None)
    return fc2


def build_model(dataset="cifar10", class_dim=10, learning_rate=1e-3,
                with_optimizer=True):
    dshape = [3, 32, 32] if dataset == "cifar10" else [3, 224, 224]
    if dataset == "flowers":
        class_dim = 102
    images = layers.data(name="data", shape=dshape, dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    net = vgg16_bn_drop(images)
    predict = layers.fc(input=net, size=class_dim, act="softmax")
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(x=cost)
    batch_acc = layers.accuracy(input=predict, label=label)
    if with_optimizer:
        opt = optimizer.AdamOptimizer(learning_rate=learning_rate)
        opt.minimize(avg_cost)
    return {"loss": avg_cost, "accuracy": batch_acc,
            "feeds": ["data", "label"], "predict": predict}
