"""BERT-base pretraining (MLM + NSP).

reference: BASELINE.json configs ("BERT-base pretraining — gelu,
layer_norm, embedding").  Encoder-only transformer with learned position
embeddings, masked-LM head tied style, next-sentence head.
"""

from __future__ import annotations

import numpy as np

from .. import layers, optimizer
from ..initializer import Constant, Normal, TruncatedNormal
from ..param_attr import ParamAttr
from .transformer import encoder_layer, pre_post_process


def bert_encoder(src_ids, sent_ids, input_mask_bias, vocab_size, max_len,
                 n_layer=12, n_head=12, d_model=768, d_inner=3072,
                 dropout=0.1, use_flash=False, pipeline=False,
                 head_major=False):
    if head_major and not use_flash:
        raise ValueError(
            "head_major=True requires use_flash=True (the head-major "
            "layout rides the flash op; see models/transformer.py)")
    init = TruncatedNormal(0.0, 0.02)
    word_emb = layers.embedding(
        src_ids, size=[vocab_size, d_model],
        param_attr=ParamAttr(name="word_embedding", initializer=init))
    # learned position embedding: ids 0..T-1 per row
    # (1, T, D) position embedding broadcasts over the batch in the add
    pos_ids = layers.reshape(layers.range(0, max_len, 1, "int64"),
                             shape=[1, max_len])
    pos_emb = layers.embedding(
        pos_ids, size=[max_len, d_model],
        param_attr=ParamAttr(name="pos_embedding", initializer=init))
    sent_emb = layers.embedding(
        sent_ids, size=[2, d_model],
        param_attr=ParamAttr(name="sent_embedding", initializer=init))
    emb = layers.elementwise_add(
        layers.elementwise_add(word_emb, sent_emb), pos_emb)
    emb = layers.layer_norm(emb, begin_norm_axis=2)
    if dropout:
        emb = layers.dropout(emb, dropout_prob=dropout,
                             dropout_implementation="upscale_in_train")
    import contextlib

    from ..core.program import pipeline_scope, pipeline_segment

    x = emb
    with pipeline_scope() if pipeline else contextlib.nullcontext():
        for _ in range(n_layer):
            with (pipeline_segment() if pipeline
                  else contextlib.nullcontext()):
                x = encoder_layer(x, input_mask_bias, n_head,
                                  d_model // n_head, d_model // n_head,
                                  d_model, d_inner, dropout,
                                  use_flash=use_flash,
                                  head_major=head_major)
    return pre_post_process(None, x, "n")


def build_model(vocab_size=30522, max_len=128, n_layer=12, n_head=12,
                d_model=768, d_inner=3072, max_predictions=20,
                learning_rate=1e-4, warmup_steps=10000, dropout=0.1,
                with_optimizer=True, use_flash=False, use_amp=False,
                pipeline=False, head_major=False):
    src_ids = layers.data(name="src_ids", shape=[max_len], dtype="int64")
    sent_ids = layers.data(name="sent_ids", shape=[max_len], dtype="int64")
    seq_len = layers.data(name="seq_len", shape=[], dtype="int32")
    mask_pos = layers.data(name="mask_pos", shape=[max_predictions],
                           dtype="int64")
    mask_label = layers.data(name="mask_label", shape=[max_predictions],
                             dtype="int64")
    mask_weight = layers.data(name="mask_weight", shape=[max_predictions],
                              dtype="float32")
    nsp_label = layers.data(name="nsp_label", shape=[1], dtype="int64")

    m = layers.sequence_mask(seq_len, maxlen=max_len, dtype="float32")
    bias = layers.scale(m, scale=1e9, bias=-1e9)
    bias = layers.unsqueeze(layers.unsqueeze(bias, axes=[1]), axes=[1])

    enc = bert_encoder(src_ids, sent_ids, bias, vocab_size, max_len,
                       n_layer, n_head, d_model, d_inner, dropout,
                       use_flash=use_flash, pipeline=pipeline,
                       head_major=head_major)

    # --- masked LM head: gather masked positions per row
    gathered = _gather_rows(enc, mask_pos)
    mlm = layers.fc(gathered, size=d_model, act="gelu", num_flatten_dims=2)
    mlm = layers.layer_norm(mlm, begin_norm_axis=2)
    mlm_logits = layers.fc(mlm, size=vocab_size, num_flatten_dims=2)
    mlm_loss = layers.softmax_with_cross_entropy(
        mlm_logits, layers.unsqueeze(mask_label, axes=[2]))
    mlm_loss = layers.elementwise_mul(
        layers.squeeze(mlm_loss, axes=[2]), mask_weight)
    denom = layers.elementwise_max(
        layers.reduce_sum(mask_weight),
        layers.fill_constant([1], "float32", 1.0))
    mlm_loss = layers.elementwise_div(layers.reduce_sum(mlm_loss), denom)

    # --- NSP head on [CLS] (position 0)
    cls = layers.slice(enc, axes=[1], starts=[0], ends=[1])
    cls = layers.squeeze(cls, axes=[1])
    pooled = layers.fc(cls, size=d_model, act="tanh")
    nsp_logits = layers.fc(pooled, size=2)
    nsp_loss = layers.mean(
        layers.softmax_with_cross_entropy(nsp_logits, nsp_label))

    loss = layers.elementwise_add(mlm_loss, nsp_loss)
    if with_optimizer:
        lr = layers.linear_lr_warmup(
            layers.polynomial_decay(learning_rate, 1000000, 0.0, 1.0),
            warmup_steps, 0.0, learning_rate)
        opt = optimizer.AdamOptimizer(learning_rate=lr)
        if use_amp:
            from .. import amp as amp_mod

            opt = amp_mod.decorate(opt)
        opt.minimize(loss)
    feeds = ["src_ids", "sent_ids", "seq_len", "mask_pos", "mask_label",
             "mask_weight", "nsp_label"]
    return {"loss": loss, "mlm_loss": mlm_loss, "nsp_loss": nsp_loss,
            "feeds": feeds}


def _gather_rows(enc, pos):
    """Per-row gather of masked positions: enc (N,T,D), pos (N,P) →
    (N,P,D) via one_hot matmul (XLA-friendly, no dynamic indexing)."""
    t = enc.shape[1]
    oh = layers.one_hot(pos, depth=t)           # (N, P, T)
    return layers.matmul(oh, enc)               # (N, P, D)


def make_fake_batch(batch_size, max_len=128, vocab_size=30522,
                    max_predictions=20, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "src_ids": rng.randint(0, vocab_size,
                               (batch_size, max_len)).astype(np.int64),
        "sent_ids": rng.randint(0, 2,
                                (batch_size, max_len)).astype(np.int64),
        "seq_len": np.full((batch_size,), max_len, np.int32),
        "mask_pos": rng.randint(0, max_len,
                                (batch_size, max_predictions)).astype(np.int64),
        "mask_label": rng.randint(0, vocab_size,
                                  (batch_size, max_predictions)).astype(np.int64),
        "mask_weight": np.ones((batch_size, max_predictions), np.float32),
        "nsp_label": rng.randint(0, 2, (batch_size, 1)).astype(np.int64),
    }
