"""Tiled flash-attention forward AND backward kernels (Pallas, TPU).

Online-softmax attention: never materializes the (Tq, Tk) score matrix in
HBM — q-blocks stream k/v-blocks through VMEM keeping running max /
normalizer / accumulator (the standard flash algorithm).  This is the
modern TPU equivalent of the LoD no-padding efficiency story
(SURVEY.md §5.7): padding positions are masked via an additive key bias.

The backward is also tiled (two kernels): dk/dv accumulates over q-blocks
and dq over k-blocks, both recomputing p = exp(s - lse) from the saved
logsumexp — end-to-end O(T) memory so long-context training never
materializes the score matrix.  Score blocks are kept in (k, q)
orientation in the backward so the per-q lse/delta vectors broadcast
along the TPU lane dimension (no transposes in-kernel).

Ring-attention support (parallel/ring_attention.py): the kernel takes
dynamic global position offsets (SMEM scalars) so causal masking works
across rotated k/v chunks, and can return the per-row logsumexp whose
cotangent folds into the backward as ds = p*(dp - (delta - dlse)).

Supported bias: additive key-padding bias broadcastable as (N, 1, 1, Tk),
plus in-kernel causal masking.  Richer biases fall back to the XLA
composition in ops/attention.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# Tuned on v5e (seq 2048, d 128): q=256/k=1024 beats the XLA-composed
# attention; both dims are clamped to the actual sequence length.
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 1024
NEG_INF = -1e30

# -- kernel cost registry (observe/cost.py injects these at the custom
# -- call instructions; tools/check_twin_flops.py asserts parity with
# -- the dense twin) ---------------------------------------------------
#
# Dense-equivalent convention: full Tq*Tk scores regardless of causal
# (the twin computes the masked positions too), backward recompute of
# s/p NOT credited.  Per flattened head (NH = N*H):
#   fwd:  s = q k^T and o = p v            -> 2 dots = 4*Tq*Tk*D
#   bwd:  dq, dk, dv, dp = do v^T          -> 4 dots = 8*Tq*Tk*D
# The per-score constants cover the softmax's non-transcendental
# elementwise work as XLA counts it in the dense composition
# (measured: ~8.2 flops/score fwd, ~8.1 bwd; exp is tallied under
# "transcendentals", not flops, in both accountings).
_SOFTMAX_FWD_PER_SCORE = 8.0
_SOFTMAX_BWD_PER_SCORE = 8.0


def _attn_dims(operand_shapes):
    (nh, t_q, d) = operand_shapes[0][0]
    t_k = operand_shapes[1][0][1]
    return nh, t_q, t_k, d


def _io_bytes(operand_shapes, result_shapes):
    total = 0
    for dims, elem in list(operand_shapes) + list(result_shapes):
        n = 1
        for d in dims:
            n *= d
        total += n * elem
    return float(total)


def flash_fwd_cost(operand_shapes, result_shapes):
    nh, t_q, t_k, d = _attn_dims(operand_shapes)
    flops = nh * t_q * t_k * (4.0 * d + _SOFTMAX_FWD_PER_SCORE)
    return flops, _io_bytes(operand_shapes, result_shapes)


def flash_dkv_cost(operand_shapes, result_shapes):
    # carries dk + dv + the shared dp dot (dense-equivalent split with
    # flash_dq_cost: together they sum to the dense backward's 4 dots)
    nh, t_q, t_k, d = _attn_dims(operand_shapes)
    flops = nh * t_q * t_k * (6.0 * d + 0.625 * _SOFTMAX_BWD_PER_SCORE)
    return flops, _io_bytes(operand_shapes, result_shapes)


def flash_dq_cost(operand_shapes, result_shapes):
    nh, t_q, t_k, d = _attn_dims(operand_shapes)
    flops = nh * t_q * t_k * (2.0 * d + 0.375 * _SOFTMAX_BWD_PER_SCORE)
    return flops, _io_bytes(operand_shapes, result_shapes)


def attention_cost(nh, t_q, t_k, d, dtype_bytes=4):
    """Dense-equivalent (flops, bytes) of one fwd+bwd flash attention —
    the sum of the three kernels' registry entries (test/parity
    helper; q/k/v/do/o assumed dtype_bytes wide, lse/delta f32)."""
    q = ((nh, t_q, d), dtype_bytes)
    k = ((nh, t_k, d), dtype_bytes)
    stat = ((nh, 8, t_q), 4)
    lse = ((nh, t_q), 4)
    fwd = flash_fwd_cost([q, k, k], [q, lse])
    dkv = flash_dkv_cost([q, k, k, q, stat, stat], [k, k])
    dq = flash_dq_cost([q, k, k, q, stat, stat], [q])
    return (fwd[0] + dkv[0] + dq[0], fwd[1] + dkv[1] + dq[1])


def _register_costs():
    from . import register_kernel_cost

    register_kernel_cost("flash_fwd", flash_fwd_cost)
    register_kernel_cost("flash_dkv", flash_dkv_cost)
    register_kernel_cost("flash_dq", flash_dq_cost)


_register_costs()


def _pallas_call(*args, **kw):
    from . import pallas_call  # shared interpret gate (package init)

    return pallas_call(*args, **kw)


def _offs(offs_ref):
    """(q_off, k_off) global position offsets from the SMEM scalar input
    (zero when no offsets were passed)."""
    if offs_ref is None:
        return 0, 0
    return offs_ref[0, 0], offs_ref[0, 1]


# -- forward ----------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, offs_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k,
                t_k):
    from jax.experimental import pallas as pl

    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    qb = pl.program_id(1)
    q_off, k_off = _offs(offs_ref)
    # causal: skip k-blocks strictly above the (offset) diagonal
    run = (q_off + (qb + 1) * block_q > k_off + kb * block_k) \
        if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0]                      # (block_q, d)
        k = k_ref[0]                      # (block_k, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

        if bias_ref is not None:
            s = s + bias_ref[0, 0].astype(jnp.float32)

        # Always mask k-positions past the true sequence length: when
        # t_k % block_k != 0 the last k-block is padded and its garbage
        # columns would otherwise corrupt the online softmax and lse.
        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < t_k
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = valid & (q_off + q_pos >= k_off + k_pos)
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_scr[:]                 # (block_q, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)            # (block_q, block_k)
        alpha = jnp.exp(m_prev - m_new)   # (block_q, 1)
        l_new = alpha * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
        # Zero padded v-rows: block padding is undefined memory and
        # 0 * NaN would poison the accumulator even though p==0 there.
        v_rows = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)
        vv = jnp.where(v_rows < t_k, v_ref[0], 0)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(vv.dtype), vv, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(kb == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        # lse replicated over 8 sublanes to satisfy TPU tiling of the
        # (nh, 8, t_q) output layout
        lse = (m_scr[:] + jnp.log(l))[:, 0]
        lse_ref[0] = jnp.broadcast_to(lse[None, :], lse_ref.shape[1:])


def _flash_fwd(q, k, v, bias, offsets, scale, causal, block_q, block_k):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nh, t_q, d = q.shape
    t_k = k.shape[1]
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_k)
    grid = (nh, pl.cdiv(t_q, block_q), pl.cdiv(t_k, block_k))

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda h, i, j: (h, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda h, i, j: (h, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda h, i, j: (h, j, 0)),
    ]
    args = [q, k, v]
    has_bias = bias is not None
    has_offs = offsets is not None
    if has_bias:
        in_specs.append(
            pl.BlockSpec((1, 1, 1, block_k), lambda h, i, j: (h, 0, 0, j)))
        args.append(bias)
    if has_offs:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(offsets)

    def kern(*refs):
        n_in = 3 + has_bias + has_offs
        ins, outs = refs[:n_in], refs[n_in:]
        q_r, k_r, v_r = ins[:3]
        b_r = ins[3] if has_bias else None
        of_r = ins[3 + has_bias] if has_offs else None
        _fwd_kernel(q_r, k_r, v_r, b_r, of_r, *outs, scale=scale,
                    causal=causal, block_q=block_q, block_k=block_k,
                    t_k=t_k)

    o, lse = _pallas_call(
        kern,
        name="flash_fwd",
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda h, i, j: (h, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nh, t_q, d), q.dtype),
            jax.ShapeDtypeStruct((nh, 8, t_q), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )(*args)
    return o, lse[:, 0, :]


# -- backward kernels -------------------------------------------------------
#
# Standard flash backward math, recomputing p from the saved lse:
#   p  = exp(s - lse);      dv = p^T do;       dp = do v^T
#   ds = p * (dp - delta),  delta = rowsum(do * o) - dlse
#   dq = scale * ds k;      dk = scale * ds^T q;   db = sum_q ds
# Score blocks are held transposed, sT: (block_k, block_q), so the per-q
# vectors (lse, delta) broadcast along lanes.

def _bwd_p_ds(q, k, v, do, lse_row, delta_row, bias_col, q_off, k_off, *,
              scale, causal, kb, qb, block_q, block_k, t_q, t_k):
    """Shared (block_k, block_q)-oriented recompute of p and ds.

    q/do must already have invalid rows zeroed by the caller; invalid
    (padded) score positions are masked here via `valid`, never letting
    undefined block padding reach an accumulator (0 * NaN poisons).
    ds is d(loss)/d(s_with_bias): unscaled — the q/k grads multiply by
    `scale` at their accumulation (chain rule through s = scale*qk^T),
    while the bias grad uses ds directly."""
    sT = jax.lax.dot_general(
        k, q, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if bias_col is not None:
        sT = sT + bias_col                  # (block_k, 1) over lanes
    k_pos = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_k, block_q), 0)
    q_pos = qb * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_k, block_q), 1)
    valid = (k_pos < t_k) & (q_pos < t_q)
    if causal:
        valid = valid & (q_off + q_pos >= k_off + k_pos)
    p = jnp.where(valid, jnp.exp(sT - lse_row), 0.0)
    dp = jax.lax.dot_general(
        v, do, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = jnp.where(valid, p * (dp - delta_row), 0.0)
    return p, ds


def _row_clean(ref, base, limit, block):
    """Load a (block, d) tile zeroing rows at absolute position >= limit
    (undefined padding of the final block)."""
    x = ref[0]
    rows = base + jax.lax.broadcasted_iota(jnp.int32, (block, 1), 0)
    return jnp.where(rows < limit, x, 0)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    bias_ref, offs_ref, dk_ref, dv_ref, db_ref, dk_scr,
                    dv_scr, db_scr, *, scale, causal, block_q, block_k,
                    t_q, t_k):
    from jax.experimental import pallas as pl

    kb = pl.program_id(1)
    qb = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qb == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)
        if db_scr is not None:
            db_scr[:] = jnp.zeros_like(db_scr)

    q_off, k_off = _offs(offs_ref)
    # causal: this k-block sees no q-block strictly below the diagonal
    run = (q_off + (qb + 1) * block_q > k_off + kb * block_k) \
        if causal else True

    @pl.when(run)
    def _compute():
        q = _row_clean(q_ref, qb * block_q, t_q, block_q)
        do = _row_clean(do_ref, qb * block_q, t_q, block_q)
        k = k_ref[0]
        v = v_ref[0]
        bias_col = None if bias_ref is None else \
            bias_ref[0].astype(jnp.float32)
        p, ds = _bwd_p_ds(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), do.astype(jnp.float32),
            lse_ref[0, 0][None, :], delta_ref[0, 0][None, :], bias_col,
            q_off, k_off, scale=scale, causal=causal, kb=kb, qb=qb,
            block_q=block_q, block_k=block_k, t_q=t_q, t_k=t_k)
        dv_scr[:] += jax.lax.dot_general(
            p, do.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[:] += scale * jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if db_scr is not None:
            db_scr[:] += jnp.sum(ds, axis=1, keepdims=True)

    @pl.when(qb == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)
        if db_ref is not None:
            db_ref[0] = db_scr[:].astype(db_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   bias_ref, offs_ref, dq_ref, dq_scr, *, scale, causal,
                   block_q, block_k, t_q, t_k):
    from jax.experimental import pallas as pl

    qb = pl.program_id(1)
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q_off, k_off = _offs(offs_ref)
    run = (q_off + (qb + 1) * block_q > k_off + kb * block_k) \
        if causal else True

    @pl.when(run)
    def _compute():
        q = _row_clean(q_ref, qb * block_q, t_q, block_q)
        do = _row_clean(do_ref, qb * block_q, t_q, block_q)
        k = _row_clean(k_ref, kb * block_k, t_k, block_k)
        v = v_ref[0]
        bias_col = None if bias_ref is None else \
            bias_ref[0].astype(jnp.float32)
        _, ds = _bwd_p_ds(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), do.astype(jnp.float32),
            lse_ref[0, 0][None, :], delta_ref[0, 0][None, :], bias_col,
            q_off, k_off, scale=scale, causal=causal, kb=kb, qb=qb,
            block_q=block_q, block_k=block_k, t_q=t_q, t_k=t_k)
        # dq[q,d] = scale * sum_k ds[k,q] * k[k,d]
        dq_scr[:] += scale * jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd(q, k, v, bias, offsets, o, lse, do, dlse, scale, causal,
               block_q, block_k):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nh, t_q, d = q.shape
    t_k = k.shape[1]
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_k)
    nq = pl.cdiv(t_q, block_q)
    nk = pl.cdiv(t_k, block_k)

    # delta = rowsum(do * o) - dlse: tiny (nh, t_q) XLA reduction.  The
    # dlse term carries the cotangent of a returned lse (ring attention's
    # online-softmax merge differentiates through lse).
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    # lse/delta enter the kernels replicated over 8 sublanes —
    # (nh, 8, t_q) with (1, 8, block_q) blocks — because Mosaic rejects
    # a (1, block_q) block on a (nh, t_q) array (sublane dim must be
    # 8-divisible or full; the fwd's lse OUTPUT uses the same layout)
    lse8 = jnp.broadcast_to(lse.astype(jnp.float32)[:, None, :],
                            (nh, 8, t_q))
    delta8 = jnp.broadcast_to(delta[:, None, :], (nh, 8, t_q))
    # bias arrives (nh, 1, 1, t_k); kernels want it as a (block_k, 1)
    # column so it broadcasts over the lane (q) dimension
    bias_t = None if bias is None else bias.reshape(nh, t_k, 1)
    has_bias = bias_t is not None
    has_offs = offsets is not None

    def specs(order):
        """order: 'kq' → grid (h, kb, qb); 'qk' → grid (h, qb, kb)."""
        if order == "kq":
            qi = lambda h, a, b: (h, b, 0)     # noqa: E731
            ki = lambda h, a, b: (h, a, 0)     # noqa: E731
            vi = lambda h, a, b: (h, 0, b)     # noqa: E731  (lse/delta by q)
            bi = lambda h, a, b: (h, a, 0)     # noqa: E731  (bias by k)
        else:
            qi = lambda h, a, b: (h, a, 0)     # noqa: E731
            ki = lambda h, a, b: (h, b, 0)     # noqa: E731
            vi = lambda h, a, b: (h, 0, a)     # noqa: E731
            bi = lambda h, a, b: (h, b, 0)     # noqa: E731
        sp = [
            pl.BlockSpec((1, block_q, d), qi),
            pl.BlockSpec((1, block_k, d), ki),
            pl.BlockSpec((1, block_k, d), ki),
            pl.BlockSpec((1, block_q, d), qi),
            pl.BlockSpec((1, 8, block_q), vi),
            pl.BlockSpec((1, 8, block_q), vi),
        ]
        if has_bias:
            sp.append(pl.BlockSpec((1, block_k, 1), bi))
        if has_offs:
            sp.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        return sp

    args = [q, k, v, do, lse8, delta8]
    if has_bias:
        args.append(bias_t)
    if has_offs:
        args.append(offsets)
    n_in = 6 + has_bias + has_offs

    def unpack(refs):
        ins = refs[:n_in]
        b_r = ins[6] if has_bias else None
        of_r = ins[6 + has_bias] if has_offs else None
        return ins[:6], b_r, of_r, refs[n_in:]

    # dk/dv (+db): grid (h, kb, qb), accumulate over q-blocks
    def dkv_kern(*refs):
        (q_r, k_r, v_r, do_r, lse_r, dl_r), b_r, of_r, rest = unpack(refs)
        if has_bias:
            dk_r, dv_r, db_r, dk_s, dv_s, db_s = rest
        else:
            dk_r, dv_r, dk_s, dv_s = rest
            db_r = db_s = None
        _bwd_dkv_kernel(q_r, k_r, v_r, do_r, lse_r, dl_r, b_r, of_r,
                        dk_r, dv_r, db_r, dk_s, dv_s, db_s, scale=scale,
                        causal=causal, block_q=block_q, block_k=block_k,
                        t_q=t_q, t_k=t_k)

    kq_out_specs = [
        pl.BlockSpec((1, block_k, d), lambda h, a, b: (h, a, 0)),
        pl.BlockSpec((1, block_k, d), lambda h, a, b: (h, a, 0)),
    ]
    kq_out_shape = [
        jax.ShapeDtypeStruct((nh, t_k, d), q.dtype),
        jax.ShapeDtypeStruct((nh, t_k, d), q.dtype),
    ]
    kq_scratch = [
        pltpu.VMEM((block_k, d), jnp.float32),
        pltpu.VMEM((block_k, d), jnp.float32),
    ]
    if has_bias:
        kq_out_specs.append(
            pl.BlockSpec((1, block_k, 1), lambda h, a, b: (h, a, 0)))
        kq_out_shape.append(
            jax.ShapeDtypeStruct((nh, t_k, 1), jnp.float32))
        kq_scratch.append(pltpu.VMEM((block_k, 1), jnp.float32))

    dkv_out = _pallas_call(
        dkv_kern,
        name="flash_dkv",
        grid=(nh, nk, nq),
        in_specs=specs("kq"),
        out_specs=kq_out_specs,
        out_shape=kq_out_shape,
        scratch_shapes=kq_scratch,
    )(*args)
    if has_bias:
        dk, dv, db = dkv_out
        dbias = db.reshape(nh, 1, 1, t_k).astype(bias.dtype)
    else:
        dk, dv = dkv_out
        dbias = None

    # dq: grid (h, qb, kb), accumulate over k-blocks
    def dq_kern(*refs):
        (q_r, k_r, v_r, do_r, lse_r, dl_r), b_r, of_r, rest = unpack(refs)
        dq_r, dq_s = rest
        _bwd_dq_kernel(q_r, k_r, v_r, do_r, lse_r, dl_r, b_r, of_r, dq_r,
                       dq_s, scale=scale, causal=causal, block_q=block_q,
                       block_k=block_k, t_q=t_q, t_k=t_k)

    dq = _pallas_call(
        dq_kern,
        name="flash_dq",
        grid=(nh, nq, nk),
        in_specs=specs("qk"),
        out_specs=pl.BlockSpec((1, block_q, d), lambda h, a, b: (h, a, 0)),
        out_shape=jax.ShapeDtypeStruct((nh, t_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
    )(*args)

    return dq, dk, dv, dbias


# -- custom VJP -------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash(q, k, v, bias, offsets, scale, causal, block_q, block_k):
    return _flash_fwd(q, k, v, bias, offsets, scale, causal, block_q,
                      block_k)


def _flash_vjp_fwd(q, k, v, bias, offsets, scale, causal, block_q,
                   block_k):
    o, lse = _flash_fwd(q, k, v, bias, offsets, scale, causal, block_q,
                        block_k)
    return (o, lse), (q, k, v, bias, offsets, o, lse)


def _flash_vjp_bwd(scale, causal, block_q, block_k, res, cts):
    q, k, v, bias, offsets, o, lse = res
    do, dlse = cts
    dq, dk, dv, dbias = _flash_bwd(q, k, v, bias, offsets, o, lse, do,
                                   dlse, scale, causal, block_q, block_k)
    doffs = None if offsets is None else \
        np.zeros(offsets.shape, dtype=jax.dtypes.float0)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            dbias, doffs)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def pallas_flash_attention(q, k, v, bias=None, scale=None, causal=False,
                           block_q=DEFAULT_BLOCK_Q,
                           block_k=DEFAULT_BLOCK_K,
                           q_offset=None, k_offset=None,
                           return_lse=False):
    """q/k/v: (N, H, T, D); bias: None or broadcastable (N, 1, 1, Tk).

    q_offset/k_offset: optional GLOBAL position offsets (python ints or
    traced scalars) applied in causal masking — ring attention passes the
    rotated chunk's origin so the causal structure survives sharding.
    With return_lse=True also returns the per-row logsumexp (N, H, T),
    differentiable (the dlse cotangent folds into the backward)."""
    n, h, t_q, d = q.shape
    t_k = k.shape[2]
    if scale is None:
        scale = d ** -0.5
    if bias is not None:
        bias = jnp.broadcast_to(bias, (n, 1, 1, t_k))
        bias = jnp.repeat(bias, h, axis=1).reshape(n * h, 1, 1, t_k)
    offsets = None
    if q_offset is not None or k_offset is not None:
        offsets = jnp.stack([
            jnp.asarray(q_offset if q_offset is not None else 0,
                        jnp.int32),
            jnp.asarray(k_offset if k_offset is not None else 0,
                        jnp.int32),
        ]).reshape(1, 2)

    qf = q.reshape(n * h, t_q, d)
    kf = k.reshape(n * h, t_k, d)
    vf = v.reshape(n * h, t_k, d)
    o, lse = _flash(qf, kf, vf, bias, offsets, float(scale), bool(causal),
                    int(block_q), int(block_k))
    o = o.reshape(n, h, t_q, d)
    if return_lse:
        return o, lse.reshape(n, h, t_q)
    return o
