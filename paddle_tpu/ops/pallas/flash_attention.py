"""Tiled flash-attention forward AND backward kernels (Pallas, TPU).

Online-softmax attention: never materializes the (Tq, Tk) score matrix in
HBM — q-blocks stream k/v-blocks through VMEM keeping running max /
normalizer / accumulator (the standard flash algorithm).  This is the
modern TPU equivalent of the LoD no-padding efficiency story
(SURVEY.md §5.7): padding positions are masked via an additive key bias.

The backward is also tiled (two kernels): dk/dv accumulates over q-blocks
and dq over k-blocks, both recomputing p = exp(s - lse) from the saved
logsumexp — end-to-end O(T) memory so long-context training never
materializes the score matrix.  Score blocks are kept in (k, q)
orientation in the backward so the per-q lse/delta vectors broadcast
along the TPU lane dimension (no transposes in-kernel).  delta =
rowsum(do*o) is recomputed in-kernel from the o/do tiles (cheap
elementwise per block) instead of a separate XLA reduction, so NOTHING
but the q/k/v/o/do/lse buffers crosses the kernel boundary.

Two operand layouts, selected by `layout=`:

- "nhtd" (historical): q/k/v arrive (N, H, T, D) and are folded to
  (N*H, T, D) by a free reshape.
- "nthd" (head-major end-to-end, ISSUE 8): q/k/v arrive (N, T, H*D)
  head-grouped — EXACTLY what a (D_model -> H*D) projection emits — and
  the batch*head fold happens in the GRID instead of the data: block
  index maps pick head g%H of batch g//H out of the grouped minor dim.
  No transpose ever exists in the program; the per-head (T, D) slab is
  a strided DMA.  The kernel tile shapes are IDENTICAL to the folded
  layout ((block, d) tiles), so the Mosaic lowering is the proven one.

The additive key-padding bias stays (N, 1, 1, Tk) — one row per batch,
never repeated per head (the index map reuses row g//H); its gradient
is summed over heads outside the kernel.

Ring-attention support (parallel/ring_attention.py): the kernel takes
dynamic global position offsets (SMEM scalars) so causal masking works
across rotated k/v chunks, and can return the per-row logsumexp whose
cotangent folds into the backward as ds = p*(dp - (delta - dlse)).

Supported bias: additive key-padding bias broadcastable as (N, 1, 1, Tk),
plus in-kernel causal masking.  Richer biases fall back to the XLA
composition in ops/attention.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# Tuned on v5e (seq 2048, d 128): q=256/k=1024 beats the XLA-composed
# attention; both dims are clamped to the actual sequence length.
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 1024
NEG_INF = -1e30

# -- kernel cost registry (observe/cost.py injects these at the custom
# -- call instructions; tools/check_twin_flops.py asserts parity with
# -- the dense twin) ---------------------------------------------------
#
# Dense-equivalent convention: full Tq*Tk scores regardless of causal
# (the twin computes the masked positions too), backward recompute of
# s/p NOT credited.  Per flattened head (NH = N*H):
#   fwd:  s = q k^T and o = p v            -> 2 dots = 4*Tq*Tk*D
#   bwd:  dq, dk, dv, dp = do v^T          -> 4 dots = 8*Tq*Tk*D
# The per-score constants cover the softmax's non-transcendental
# elementwise work as XLA counts it in the dense composition
# (measured: ~8.2 flops/score fwd, ~8.1 bwd; exp is tallied under
# "transcendentals", not flops, in both accountings).
_SOFTMAX_FWD_PER_SCORE = 8.0
_SOFTMAX_BWD_PER_SCORE = 8.0


def _attn_dims(operand_shapes, stat_dims):
    """(nh, t_q, t_k, d) from the q/k operands plus the lse statistic's
    dims — (nh, 8, t_q) sublane-replicated, or the pre-r07 (nh, t_q)
    form (tolerated so old recorded protos stay analyzable).  Works for
    BOTH layouts: folded (NH, T, D) operands have nh == q.shape[0]
    (heads-per-batch 1 below), while head-major grouped (N, T, H*D)
    operands recover H = nh // N from the statistic and split the
    grouped minor dim."""
    qd = operand_shapes[0][0]
    kd = operand_shapes[1][0]
    nh, t_q = stat_dims[0], stat_dims[-1]
    heads = max(nh // max(qd[0], 1), 1)
    return nh, t_q, kd[1], qd[2] // heads


def _io_bytes(operand_shapes, result_shapes):
    total = 0
    for dims, elem in list(operand_shapes) + list(result_shapes):
        n = 1
        for d in dims:
            n *= d
        total += n * elem
    return float(total)


def flash_fwd_cost(operand_shapes, result_shapes):
    # result_shapes[-1] is the (nh, 8, t_q) lse output
    nh, t_q, t_k, d = _attn_dims(operand_shapes, result_shapes[-1][0])
    flops = nh * t_q * t_k * (4.0 * d + _SOFTMAX_FWD_PER_SCORE)
    return flops, _io_bytes(operand_shapes, result_shapes)


def flash_dkv_cost(operand_shapes, result_shapes):
    # carries dk + dv + the shared dp dot (dense-equivalent split with
    # flash_dq_cost: together they sum to the dense backward's 4 dots).
    # operand_shapes[5] is the (nh, 8, t_q) lse input.
    nh, t_q, t_k, d = _attn_dims(operand_shapes, operand_shapes[5][0])
    flops = nh * t_q * t_k * (6.0 * d + 0.625 * _SOFTMAX_BWD_PER_SCORE)
    return flops, _io_bytes(operand_shapes, result_shapes)


def flash_dq_cost(operand_shapes, result_shapes):
    nh, t_q, t_k, d = _attn_dims(operand_shapes, operand_shapes[5][0])
    flops = nh * t_q * t_k * (2.0 * d + 0.375 * _SOFTMAX_BWD_PER_SCORE)
    return flops, _io_bytes(operand_shapes, result_shapes)


def attention_cost(nh, t_q, t_k, d, dtype_bytes=4):
    """Dense-equivalent (flops, bytes) of one fwd+bwd flash attention —
    the sum of the three kernels' registry entries (test/parity
    helper; q/k/v/do/o assumed dtype_bytes wide, lse f32)."""
    q = ((nh, t_q, d), dtype_bytes)
    k = ((nh, t_k, d), dtype_bytes)
    stat = ((nh, 8, t_q), 4)
    fwd = flash_fwd_cost([q, k, k], [q, stat])
    dkv = flash_dkv_cost([q, k, k, q, q, stat], [k, k])
    dq = flash_dq_cost([q, k, k, q, q, stat], [q])
    return (fwd[0] + dkv[0] + dq[0], fwd[1] + dkv[1] + dq[1])


def _register_costs():
    from . import register_kernel_cost

    register_kernel_cost("flash_fwd", flash_fwd_cost)
    register_kernel_cost("flash_dkv", flash_dkv_cost)
    register_kernel_cost("flash_dq", flash_dq_cost)


_register_costs()


def _pallas_call(*args, **kw):
    from . import pallas_call  # shared interpret gate (package init)

    return pallas_call(*args, **kw)


def _offs(offs_ref):
    """(q_off, k_off) global position offsets from the SMEM scalar input
    (zero when no offsets were passed)."""
    if offs_ref is None:
        return 0, 0
    return offs_ref[0, 0], offs_ref[0, 1]


def _tile(ref):
    """The (block, d) tile of a q/k/v/o/do ref — both layouts block
    these operands as (1, block, d); the leading dim is squeezed."""
    return ref[0]


# -- block-spec factories ---------------------------------------------------
#
# One grid for both layouts: (N*H, time blocks, time blocks).  The
# difference is ONLY where a (1, block, d) tile lives in the array:
# folded (NH, T, D) indexes (g, t, 0); head-major grouped (N, T, H*D)
# indexes (g // H, t, g % H) — the block unit of the minor dim is d, so
# block index g % H lands on head g % H's d-slice.  lse/delta stay in
# the folded (NH, 8, T) form in both layouts (kernel-internal
# statistics, never touching the model's activation layout).

def _tile_spec(block, d, layout, h, tsel):
    """BlockSpec for a (1, block, d) q/k/v/o/do tile; `tsel` maps the
    non-head grid axes (a, b) to the time block index."""
    from jax.experimental import pallas as pl

    if layout == "nthd":
        return pl.BlockSpec((1, block, d),
                            lambda g, a, b: (g // h, tsel(a, b), g % h))
    return pl.BlockSpec((1, block, d),
                        lambda g, a, b: (g, tsel(a, b), 0))


def _stat_spec(block_q, tsel):
    from jax.experimental import pallas as pl

    return pl.BlockSpec((1, 8, block_q),
                        lambda g, a, b: (g, 0, tsel(a, b)))


# -- forward ----------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, offs_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k,
                t_k):
    from jax.experimental import pallas as pl

    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    qb = pl.program_id(1)
    q_off, k_off = _offs(offs_ref)
    # causal: skip k-blocks strictly above the (offset) diagonal
    run = (q_off + (qb + 1) * block_q > k_off + kb * block_k) \
        if causal else True

    @pl.when(run)
    def _compute():
        q = _tile(q_ref)                  # (block_q, d)
        k = _tile(k_ref)                  # (block_k, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

        if bias_ref is not None:
            s = s + bias_ref[0, 0].astype(jnp.float32)

        # Always mask k-positions past the true sequence length: when
        # t_k % block_k != 0 the last k-block is padded and its garbage
        # columns would otherwise corrupt the online softmax and lse.
        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < t_k
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = valid & (q_off + q_pos >= k_off + k_pos)
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_scr[:]                 # (block_q, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)            # (block_q, block_k)
        alpha = jnp.exp(m_prev - m_new)   # (block_q, 1)
        l_new = alpha * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
        # Zero padded v-rows: block padding is undefined memory and
        # 0 * NaN would poison the accumulator even though p==0 there.
        v_rows = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)
        vv = jnp.where(v_rows < t_k, _tile(v_ref), 0)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(vv.dtype), vv, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(kb == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        # lse replicated over 8 sublanes to satisfy TPU tiling of the
        # (nh, 8, t_q) output layout
        lse = (m_scr[:] + jnp.log(l))[:, 0]
        lse_ref[0] = jnp.broadcast_to(lse[None, :], lse_ref.shape[1:])


def _fwd_dims(q, k, layout, n_head):
    if layout == "nthd":
        n, t_q, hd = q.shape
        return n * n_head, t_q, k.shape[1], hd // n_head
    nh, t_q, d = q.shape
    return nh, t_q, k.shape[1], d


def _flash_fwd(q, k, v, bias, offsets, scale, causal, block_q, block_k,
               layout, n_head):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nh, t_q, t_k, d = _fwd_dims(q, k, layout, n_head)
    h = n_head
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_k)
    grid = (nh, pl.cdiv(t_q, block_q), pl.cdiv(t_k, block_k))

    in_specs = [
        _tile_spec(block_q, d, layout, h, lambda a, b: a),
        _tile_spec(block_k, d, layout, h, lambda a, b: b),
        _tile_spec(block_k, d, layout, h, lambda a, b: b),
    ]
    args = [q, k, v]
    has_bias = bias is not None
    has_offs = offsets is not None
    if has_bias:
        # bias is (N, 1, 1, Tk): one row per BATCH, the index map fans
        # it out over heads — no per-head repeat ever materializes
        in_specs.append(
            pl.BlockSpec((1, 1, 1, block_k),
                         lambda g, a, b: (g // h, 0, 0, b)))
        args.append(bias)
    if has_offs:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(offsets)

    def kern(*refs):
        n_in = 3 + has_bias + has_offs
        ins, outs = refs[:n_in], refs[n_in:]
        q_r, k_r, v_r = ins[:3]
        b_r = ins[3] if has_bias else None
        of_r = ins[3 + has_bias] if has_offs else None
        _fwd_kernel(q_r, k_r, v_r, b_r, of_r, *outs, scale=scale,
                    causal=causal, block_q=block_q, block_k=block_k,
                    t_k=t_k)

    if layout == "nthd":
        o_shape = jax.ShapeDtypeStruct(q.shape, q.dtype)
    else:
        o_shape = jax.ShapeDtypeStruct((nh, t_q, d), q.dtype)
    o, lse8 = _pallas_call(
        kern,
        name="flash_fwd",
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            _tile_spec(block_q, d, layout, h, lambda a, b: a),
            _stat_spec(block_q, lambda a, b: a),
        ],
        out_shape=[
            o_shape,
            jax.ShapeDtypeStruct((nh, 8, t_q), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )(*args)
    return o, lse8


# -- backward kernels -------------------------------------------------------
#
# Standard flash backward math, recomputing p from the saved lse:
#   p  = exp(s - lse);      dv = p^T do;       dp = do v^T
#   ds = p * (dp - delta),  delta = rowsum(do * o) - dlse
#   dq = scale * ds k;      dk = scale * ds^T q;   db = sum_q ds
# Score blocks are held transposed, sT: (block_k, block_q), so the per-q
# vectors (lse, delta) broadcast along lanes.  delta is recomputed from
# the o/do tiles in-kernel (elementwise, cheap) so no (NH, T) statistic
# has to be produced by XLA between the kernels.

def _bwd_p_ds(q, k, v, do, lse_row, delta_row, bias_col, q_off, k_off, *,
              scale, causal, kb, qb, block_q, block_k, t_q, t_k):
    """Shared (block_k, block_q)-oriented recompute of p and ds.

    q/do must already have invalid rows zeroed by the caller; invalid
    (padded) score positions are masked here via `valid`, never letting
    undefined block padding reach an accumulator (0 * NaN poisons).
    ds is d(loss)/d(s_with_bias): unscaled — the q/k grads multiply by
    `scale` at their accumulation (chain rule through s = scale*qk^T),
    while the bias grad uses ds directly."""
    sT = jax.lax.dot_general(
        k, q, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if bias_col is not None:
        sT = sT + bias_col                  # (block_k, 1) over lanes
    k_pos = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_k, block_q), 0)
    q_pos = qb * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_k, block_q), 1)
    valid = (k_pos < t_k) & (q_pos < t_q)
    if causal:
        valid = valid & (q_off + q_pos >= k_off + k_pos)
    p = jnp.where(valid, jnp.exp(sT - lse_row), 0.0)
    dp = jax.lax.dot_general(
        v, do, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = jnp.where(valid, p * (dp - delta_row), 0.0)
    return p, ds


def _row_clean(ref, base, limit, block):
    """Load a (block, d) tile zeroing rows at absolute position >= limit
    (undefined padding of the final block)."""
    x = _tile(ref)
    rows = base + jax.lax.broadcasted_iota(jnp.int32, (block, 1), 0)
    return jnp.where(rows < limit, x, 0)


def _delta_row(do, o, dlse_ref):
    """(1, block_q) delta = rowsum(do * o) [- dlse], recomputed from the
    already-cleaned f32 tiles.  dlse arrives 8-sublane-stored with only
    row 0 populated (the public wrapper slices lse8[:, 0, :]), so the
    sublane SUM recovers it."""
    delta = jnp.sum(do * o, axis=1)[None, :]
    if dlse_ref is not None:
        delta = delta - jnp.sum(dlse_ref[0], axis=0)[None, :]
    return delta


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                    dlse_ref, bias_ref, offs_ref, dk_ref, dv_ref, db_ref,
                    dk_scr, dv_scr, db_scr, *, scale, causal, block_q,
                    block_k, t_q, t_k):
    from jax.experimental import pallas as pl

    kb = pl.program_id(1)
    qb = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qb == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)
        if db_scr is not None:
            db_scr[:] = jnp.zeros_like(db_scr)

    q_off, k_off = _offs(offs_ref)
    # causal: this k-block sees no q-block strictly below the diagonal
    run = (q_off + (qb + 1) * block_q > k_off + kb * block_k) \
        if causal else True

    @pl.when(run)
    def _compute():
        q = _row_clean(q_ref, qb * block_q, t_q, block_q)
        do = _row_clean(do_ref, qb * block_q, t_q, block_q)
        o = _row_clean(o_ref, qb * block_q, t_q, block_q)
        k = _tile(k_ref)
        v = _tile(v_ref)
        bias_col = None if bias_ref is None else \
            bias_ref[0].astype(jnp.float32)
        do32 = do.astype(jnp.float32)
        delta = _delta_row(do32, o.astype(jnp.float32), dlse_ref)
        p, ds = _bwd_p_ds(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), do32,
            lse_ref[0, 0][None, :], delta, bias_col,
            q_off, k_off, scale=scale, causal=causal, kb=kb, qb=qb,
            block_q=block_q, block_k=block_k, t_q=t_q, t_k=t_k)
        dv_scr[:] += jax.lax.dot_general(
            p, do32, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[:] += scale * jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if db_scr is not None:
            db_scr[:] += jnp.sum(ds, axis=1, keepdims=True)

    @pl.when(qb == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)
        if db_ref is not None:
            db_ref[0] = db_scr[:].astype(db_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                   dlse_ref, bias_ref, offs_ref, dq_ref, dq_scr, *,
                   scale, causal, block_q, block_k, t_q, t_k):
    from jax.experimental import pallas as pl

    qb = pl.program_id(1)
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q_off, k_off = _offs(offs_ref)
    run = (q_off + (qb + 1) * block_q > k_off + kb * block_k) \
        if causal else True

    @pl.when(run)
    def _compute():
        q = _row_clean(q_ref, qb * block_q, t_q, block_q)
        do = _row_clean(do_ref, qb * block_q, t_q, block_q)
        o = _row_clean(o_ref, qb * block_q, t_q, block_q)
        k = _row_clean(k_ref, kb * block_k, t_k, block_k)
        v = _tile(v_ref)
        bias_col = None if bias_ref is None else \
            bias_ref[0].astype(jnp.float32)
        do32 = do.astype(jnp.float32)
        delta = _delta_row(do32, o.astype(jnp.float32), dlse_ref)
        _, ds = _bwd_p_ds(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), do32,
            lse_ref[0, 0][None, :], delta, bias_col,
            q_off, k_off, scale=scale, causal=causal, kb=kb, qb=qb,
            block_q=block_q, block_k=block_k, t_q=t_q, t_k=t_k)
        # dq[q,d] = scale * sum_k ds[k,q] * k[k,d]
        dq_scr[:] += scale * jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd(q, k, v, bias, offsets, o, lse8, do, dlse8, scale, causal,
               block_q, block_k, layout, n_head):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nh, t_q, t_k, d = _fwd_dims(q, k, layout, n_head)
    h = n_head
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_k)
    nq = pl.cdiv(t_q, block_q)
    nk = pl.cdiv(t_k, block_k)

    # bias arrives (N, 1, 1, t_k); kernels want it as a (block_k, 1)
    # column so it broadcasts over the lane (q) dimension
    bias_t = None if bias is None else \
        bias.reshape(bias.shape[0], t_k, 1)
    has_bias = bias_t is not None
    has_dlse = dlse8 is not None
    has_offs = offsets is not None

    def specs(order):
        """order: 'kq' → grid (g, kb, qb); 'qk' → grid (g, qb, kb)."""
        if order == "kq":
            q_t = lambda a, b: b     # noqa: E731
            k_t = lambda a, b: a     # noqa: E731
        else:
            q_t = lambda a, b: a     # noqa: E731
            k_t = lambda a, b: b     # noqa: E731
        sp = [
            _tile_spec(block_q, d, layout, h, q_t),
            _tile_spec(block_k, d, layout, h, k_t),
            _tile_spec(block_k, d, layout, h, k_t),
            _tile_spec(block_q, d, layout, h, q_t),   # do
            _tile_spec(block_q, d, layout, h, q_t),   # o
            _stat_spec(block_q, q_t),                 # lse8
        ]
        if has_dlse:
            sp.append(_stat_spec(block_q, q_t))
        if has_bias:
            sp.append(pl.BlockSpec((1, block_k, 1),
                                   lambda g, a, b: (g // h, k_t(a, b), 0)))
        if has_offs:
            sp.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        return sp

    args = [q, k, v, do, o, lse8]
    if has_dlse:
        args.append(dlse8)
    if has_bias:
        args.append(bias_t)
    if has_offs:
        args.append(offsets)
    n_in = 6 + has_dlse + has_bias + has_offs

    def unpack(refs):
        ins = refs[:n_in]
        i = 6
        dl_r = b_r = of_r = None
        if has_dlse:
            dl_r = ins[i]
            i += 1
        if has_bias:
            b_r = ins[i]
            i += 1
        if has_offs:
            of_r = ins[i]
        return ins[:6], dl_r, b_r, of_r, refs[n_in:]

    def grad_spec(block, tsel):
        return _tile_spec(block, d, layout, h, tsel)

    if layout == "nthd":
        dk_shape = jax.ShapeDtypeStruct(k.shape, q.dtype)
        dq_shape = jax.ShapeDtypeStruct(q.shape, q.dtype)
    else:
        dk_shape = jax.ShapeDtypeStruct((nh, t_k, d), q.dtype)
        dq_shape = jax.ShapeDtypeStruct((nh, t_q, d), q.dtype)

    # dk/dv (+db): grid (g, kb, qb), accumulate over q-blocks
    def dkv_kern(*refs):
        (q_r, k_r, v_r, do_r, o_r, lse_r), dl_r, b_r, of_r, rest = \
            unpack(refs)
        if has_bias:
            dk_r, dv_r, db_r, dk_s, dv_s, db_s = rest
        else:
            dk_r, dv_r, dk_s, dv_s = rest
            db_r = db_s = None
        _bwd_dkv_kernel(q_r, k_r, v_r, do_r, o_r, lse_r, dl_r, b_r, of_r,
                        dk_r, dv_r, db_r, dk_s, dv_s, db_s, scale=scale,
                        causal=causal, block_q=block_q, block_k=block_k,
                        t_q=t_q, t_k=t_k)

    kq_out_specs = [grad_spec(block_k, lambda a, b: a),
                    grad_spec(block_k, lambda a, b: a)]
    kq_out_shape = [dk_shape, dk_shape]
    kq_scratch = [
        pltpu.VMEM((block_k, d), jnp.float32),
        pltpu.VMEM((block_k, d), jnp.float32),
    ]
    if has_bias:
        # db stays PER-HEAD (NH, t_k, 1) — grid dim 0 revisits of a
        # shared (N, ...) block would not be consecutive, so the
        # head-sum happens outside (a tiny reduce, not a layout op)
        kq_out_specs.append(
            pl.BlockSpec((1, block_k, 1), lambda g, a, b: (g, a, 0)))
        kq_out_shape.append(
            jax.ShapeDtypeStruct((nh, t_k, 1), jnp.float32))
        kq_scratch.append(pltpu.VMEM((block_k, 1), jnp.float32))

    dkv_out = _pallas_call(
        dkv_kern,
        name="flash_dkv",
        grid=(nh, nk, nq),
        in_specs=specs("kq"),
        out_specs=kq_out_specs,
        out_shape=kq_out_shape,
        scratch_shapes=kq_scratch,
    )(*args)
    if has_bias:
        dk, dv, db = dkv_out
        n_b = bias.shape[0]
        dbias = db.reshape(n_b, nh // n_b, t_k).sum(axis=1) \
            .reshape(n_b, 1, 1, t_k).astype(bias.dtype)
    else:
        dk, dv = dkv_out
        dbias = None

    # dq: grid (g, qb, kb), accumulate over k-blocks
    def dq_kern(*refs):
        (q_r, k_r, v_r, do_r, o_r, lse_r), dl_r, b_r, of_r, rest = \
            unpack(refs)
        dq_r, dq_s = rest
        _bwd_dq_kernel(q_r, k_r, v_r, do_r, o_r, lse_r, dl_r, b_r, of_r,
                       dq_r, dq_s, scale=scale, causal=causal,
                       block_q=block_q, block_k=block_k, t_q=t_q,
                       t_k=t_k)

    dq = _pallas_call(
        dq_kern,
        name="flash_dq",
        grid=(nh, nq, nk),
        in_specs=specs("qk"),
        out_specs=grad_spec(block_q, lambda a, b: a),
        out_shape=dq_shape,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
    )(*args)

    return dq, dk, dv, dbias


# -- custom VJP -------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flash(q, k, v, bias, offsets, scale, causal, block_q, block_k,
           layout, n_head, with_lse):
    o, lse8 = _flash_fwd(q, k, v, bias, offsets, scale, causal, block_q,
                         block_k, layout, n_head)
    return (o, lse8) if with_lse else o


def _flash_vjp_fwd(q, k, v, bias, offsets, scale, causal, block_q,
                   block_k, layout, n_head, with_lse):
    o, lse8 = _flash_fwd(q, k, v, bias, offsets, scale, causal, block_q,
                         block_k, layout, n_head)
    out = (o, lse8) if with_lse else o
    return out, (q, k, v, bias, offsets, o, lse8)


def _flash_vjp_bwd(scale, causal, block_q, block_k, layout, n_head,
                   with_lse, res, cts):
    q, k, v, bias, offsets, o, lse8 = res
    if with_lse:
        do, dlse8 = cts
    else:
        do, dlse8 = cts, None
    dq, dk, dv, dbias = _flash_bwd(q, k, v, bias, offsets, o, lse8, do,
                                   dlse8, scale, causal, block_q,
                                   block_k, layout, n_head)
    doffs = None if offsets is None else \
        np.zeros(offsets.shape, dtype=jax.dtypes.float0)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            dbias, doffs)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def pallas_flash_attention(q, k, v, bias=None, scale=None, causal=False,
                           block_q=DEFAULT_BLOCK_Q,
                           block_k=DEFAULT_BLOCK_K,
                           q_offset=None, k_offset=None,
                           return_lse=False, layout="nhtd",
                           n_head=None):
    """layout="nhtd" (default): q/k/v (N, H, T, D), output (N, H, T, D).
    layout="nthd": q/k/v (N, T, H*D) head-grouped — the head-major
    end-to-end contract; `n_head` is required and the batch*head fold
    happens in the kernel grid, so NO transpose/copy exists at the
    kernel boundary.  bias: None or broadcastable (N, 1, 1, Tk) in
    either layout.

    q_offset/k_offset: optional GLOBAL position offsets (python ints or
    traced scalars) applied in causal masking — ring attention passes the
    rotated chunk's origin so the causal structure survives sharding.
    With return_lse=True also returns the per-row logsumexp —
    (N, H, T) for nhtd, (N, T, H) for nthd — differentiable (the dlse
    cotangent folds into the backward)."""
    if layout == "nthd":
        if n_head is None:
            raise ValueError("layout='nthd' needs n_head (operands are "
                             "(N, T, H*D) head-grouped)")
        n, t_q, hd = q.shape
        if hd % n_head != 0:
            raise ValueError(f"nthd minor dim {hd} not divisible by "
                             f"n_head {n_head}")
        h, d = n_head, hd // n_head
        t_k = k.shape[1]
        qf, kf, vf = q, k, v
    elif layout == "nhtd":
        n, h, t_q, d = q.shape
        t_k = k.shape[2]
        qf = q.reshape(n * h, t_q, d)
        kf = k.reshape(n * h, t_k, d)
        vf = v.reshape(n * h, t_k, d)
    else:
        raise ValueError(f"unknown layout {layout!r}")
    if scale is None:
        scale = d ** -0.5
    if bias is not None:
        bias = jnp.broadcast_to(bias, (n, 1, 1, t_k))
    offsets = None
    if q_offset is not None or k_offset is not None:
        offsets = jnp.stack([
            jnp.asarray(q_offset if q_offset is not None else 0,
                        jnp.int32),
            jnp.asarray(k_offset if k_offset is not None else 0,
                        jnp.int32),
        ]).reshape(1, 2)

    out = _flash(qf, kf, vf, bias, offsets, float(scale), bool(causal),
                 int(block_q), int(block_k), layout, int(h),
                 bool(return_lse))
    if return_lse:
        o, lse8 = out
        lse = lse8[:, 0, :].reshape(n, h, t_q)
        if layout == "nthd":
            # per-chunk statistic for ring merging rides (N, T, H) so
            # it broadcasts against the head-grouped output
            return o, jnp.moveaxis(lse, 1, 2)
        return o.reshape(n, h, t_q, d), lse
    if layout == "nthd":
        return out
    return out.reshape(n, h, t_q, d)
