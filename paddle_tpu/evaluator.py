"""Evaluator API (reference: python/paddle/fluid/evaluator.py:1).

Two tiers, matching the reference:
- `fluid.metrics.*` python accumulators (the reference's recommended
  path — its evaluator docstrings say "Better to use fluid.metrics").
- IN-GRAPH evaluators carrying accumulator STATE as persistable graph
  variables updated by ops every step (reference evaluator.py
  ChunkEvaluator:251 with create_state + counter-sum ops): the
  counters ride inside the jitted step — no per-batch host round-trip
  — and eval() reads the device-resident totals.
"""

from __future__ import annotations

import numpy as np

from .metrics import (Accuracy, Auc, DetectionMAP,  # noqa: F401
                      EditDistance, MetricBase)
from .metrics import ChunkEvaluator as PyChunkEvaluator  # noqa: F401


class Evaluator(MetricBase):
    """Historical extension base (reference evaluator.py Evaluator):
    subclasses implement update()/eval() like any MetricBase."""


class ChunkEvaluator:
    """In-graph chunk precision/recall/F1 (reference evaluator.py
    ChunkEvaluator:251): builds chunk_eval on (input, label), creates
    persistable counter states, and appends counter-accumulation ops to
    the CURRENT program — every executor step updates the totals on
    device inside the jitted step.  eval() computes P/R/F1 from the
    accumulated counters; reset() zeroes them.

    The python-accumulator variant remains available as
    fluid.metrics.ChunkEvaluator (aliased here as PyChunkEvaluator).
    """

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None, seq_len=None):
        from . import layers
        from .core import unique_name
        from .core.program import (default_main_program,
                                   default_startup_program)
        from .initializer import Constant

        (precision, recall, f1, num_infer, num_label,
         num_correct) = layers.chunk_eval(
            input=input, label=label, chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types,
            excluded_chunk_types=excluded_chunk_types, seq_len=seq_len)
        self.batch_metrics = (precision, recall, f1)

        block = default_main_program().current_block()
        sblock = default_startup_program().current_block()
        self._states = []
        for nm, batch_var in (("total_infer_chunks", num_infer),
                              ("total_label_chunks", num_label),
                              ("total_correct_chunks", num_correct)):
            state_name = unique_name.generate(f"chunk_evaluator.{nm}")
            state = block.create_var(name=state_name, shape=(1,),
                                     dtype="float32", persistable=True,
                                     stop_gradient=True)
            sv = sblock.create_var(name=state_name, shape=(1,),
                                   dtype="float32", persistable=True,
                                   stop_gradient=True)
            Constant(0.0)(sv, sblock)
            # state += batch count, in-graph (the output slot IS the
            # persistable state var, so the executor carries it forward
            # like optimizer state)
            cast = block.create_var(
                name=unique_name.generate(f"{state_name}.cast"),
                shape=(1,), dtype="float32")
            block.append_op(type="cast", inputs={"X": [batch_var]},
                            outputs={"Out": [cast]},
                            attrs={"out_dtype": "float32"})
            block.append_op(type="elementwise_add",
                            inputs={"X": [state], "Y": [cast]},
                            outputs={"Out": [state]})
            self._states.append(state)

    def reset(self, executor=None, scope=None):
        """Zero the accumulated counters (reference Evaluator.reset)."""
        from .core.executor import global_scope

        scope = scope or global_scope()
        for s in self._states:
            scope.set_var(s.name, np.zeros((1,), np.float32))

    def eval(self, executor=None, scope=None):
        """(precision, recall, f1) over every step since reset()."""
        from .core.executor import global_scope

        scope = scope or global_scope()
        infer, label, correct = (
            float(np.asarray(scope.find_var(s.name)).reshape(-1)[0])
            for s in self._states)
        precision = correct / infer if infer else 0.0
        recall = correct / label if label else 0.0
        f1 = (2 * precision * recall / (precision + recall)
              if correct else 0.0)
        return precision, recall, f1
