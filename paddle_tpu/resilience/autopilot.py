"""Divergence autopilot: anomaly-triggered in-run rollback-and-replay.

The observe stack *detects* every training pathology (guard skip
counters, latched first-nonfinite op provenance, z-score anomaly
rules) but until this module nothing *recovered* from one: a poisoned
step emitted a loud event and then the run either died or the update
guard skipped forever while an alert paged a human who isn't there.
`RecoveryController` closes the loop with a bounded escalation ladder
(docs/RESILIENCE.md §autopilot), driven by contrib.Trainer:

1. ABSORB — the in-step update guard / dynamic loss scale already
   neutralizes transient non-finite steps on device; below the
   configured streak nothing else happens.
2. ROLLBACK — after `skip_streak` consecutive guard-skipped steps, a
   latched non-finite window, or a loss/grad-norm z-trip (the same
   `AnomalyRule` machinery the AlertEngine runs, evaluated
   synchronously on each telemetry window), the Trainer restores the
   newest *verified-good* checkpoint IN PROCESS (the program was
   built under `unique_name.guard()`, so the restored arrays bind to
   the same variables — the contrib/trainer.py resume contract).
3. QUARANTINE — the data window between the rollback cursor and the
   failure step is never re-trained: the replay fast-forwards the
   resume cursor past those batches, records which, and optionally
   backs the learning rate off on re-entry.
4. HALT — when the rollback budget is exhausted (or no verified-good
   serial exists) the run stops with a structured
   `TrainingDivergedError` carrying full provenance plus a
   FlightRecorder bundle, instead of skipping updates forever.

Discipline: the controller is PURE HOST and consumes only the
telemetry windows the Trainer already fetches (device-accumulate,
periodic-fetch — never per-step).  It adds zero dispatches and the
step lowering is byte-identical with the autopilot on or off
(tests/test_autopilot.py pins it).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .errors import TrainingDivergedError  # noqa: F401  (re-export)


class AutopilotConfig:
    """Escalation-ladder policy for one training run.

    skip_streak: consecutive guard-skipped/non-finite steps (summed
        across telemetry windows; reset by any clean window) that
        escalate from rung 1 (absorb) to rung 2 (rollback).
    loss_spike_z / grad_norm_z: z-score thresholds for the window-mean
        loss ("above") and last grad norm ("both") anomaly rules —
        the finite-divergence triggers the guard cannot see.  None
        disables a rule.
    min_baseline_windows: telemetry windows an anomaly rule absorbs
        into its baseline before it may trip (AnomalyRule
        min_samples).
    max_rollbacks: rollback budget per run; once spent (or with 0),
        the next trigger halts with TrainingDivergedError.
    lr_backoff: optional multiplier (< 1.0) applied to every
        `.learning_rate` variable after a rollback restore — re-entry
        at a gentler step size.  None keeps the LR bit-identical,
        which the chaos parity proof requires.
    """

    def __init__(self, skip_streak: int = 2,
                 loss_spike_z: Optional[float] = 8.0,
                 grad_norm_z: Optional[float] = 8.0,
                 min_baseline_windows: int = 5,
                 max_rollbacks: int = 2,
                 lr_backoff: Optional[float] = None):
        if skip_streak < 1:
            raise ValueError("skip_streak must be >= 1")
        if max_rollbacks < 0:
            raise ValueError("max_rollbacks must be >= 0")
        if lr_backoff is not None and not 0.0 < lr_backoff <= 1.0:
            raise ValueError("lr_backoff must be in (0, 1]")
        self.skip_streak = int(skip_streak)
        self.loss_spike_z = loss_spike_z
        self.grad_norm_z = grad_norm_z
        self.min_baseline_windows = int(min_baseline_windows)
        self.max_rollbacks = int(max_rollbacks)
        self.lr_backoff = lr_backoff


class RecoveryController:
    """Host-side state machine of the autopilot (one per Trainer).

    The Trainer feeds it two streams: `note_checkpoint` after every
    save (with the verified-good verdict) and `observe_window` after
    every telemetry publish.  `observe_window` returns None while the
    guard is absorbing, or a trigger dict once the ladder escalates —
    the Trainer then performs the rollback (it owns the scope and the
    checkpoint files) and reports back via `on_rollback`.
    """

    def __init__(self, config: Optional[AutopilotConfig] = None):
        self.cfg = config or AutopilotConfig()
        self.rollbacks = 0
        self.halted = False
        self.skip_streak = 0
        self.windows_seen = 0
        self.quarantined_batches = 0
        self.quarantine_windows: List[Dict[str, int]] = []
        self.last_trigger: Optional[Dict[str, Any]] = None
        # newest-last [(serial, epoch, step_in_epoch)] of serials whose
        # trailing telemetry window was clean — the rollback anchors
        self._verified: List[Tuple[int, int, int]] = []
        self._rules = self._build_rules()

    # -- z-rules (the AlertEngine's AnomalyRule, run synchronously) ----
    def _build_rules(self):
        from ..observe.alerts import AnomalyRule

        rules = []
        c = self.cfg
        if c.loss_spike_z is not None:
            rules.append(AnomalyRule(
                "autopilot_loss_spike",
                lambda s: s.get("loss_mean"),
                z=c.loss_spike_z, direction="above",
                min_samples=c.min_baseline_windows,
                description="window-mean loss spiked vs baseline"))
        if c.grad_norm_z is not None:
            rules.append(AnomalyRule(
                "autopilot_grad_norm",
                lambda s: s.get("grad_norm"),
                z=c.grad_norm_z, direction="both",
                min_samples=c.min_baseline_windows,
                description="grad-norm excursion vs baseline"))
        return rules

    # -- checkpoint stream ---------------------------------------------
    def note_checkpoint(self, serial: int, epoch: int, step: int,
                        verified: bool) -> None:
        if verified:
            self._verified.append((int(serial), int(epoch), int(step)))

    def verified_serials(self) -> List[Tuple[int, int, int]]:
        """Rollback candidates, oldest-first (Trainer walks them
        newest-first, falling past serials that fail to load)."""
        return list(self._verified)

    def forget_serial(self, serial: int) -> None:
        """Drop a serial that turned out unloadable (torn/corrupt on
        disk despite its clean marking)."""
        self._verified = [v for v in self._verified if v[0] != serial]

    # -- telemetry stream ----------------------------------------------
    @property
    def healthy(self) -> bool:
        """No unresolved anomaly: clean streak, no firing z-rule, not
        halted.  Gates the verified-good marking of saves."""
        if self.halted or self.skip_streak > 0:
            return False
        return all(r.state != "firing" for r in self._rules)

    def observe_window(self, tel, epoch: int, step: int
                       ) -> Optional[Dict[str, Any]]:
        """Consume one published StepTelemetry window.  Returns None
        (keep training) or a trigger dict naming the signal that
        escalated past rung 1."""
        import math

        self.windows_seen += 1
        poisoned_steps = max(
            int(tel.skipped_update_steps),
            int(tel.nonfinite_grad_steps),
            int(tel.nonfinite_loss_steps),
            1 if tel.first_nonfinite_op is not None else 0)
        if poisoned_steps > 0:
            self.skip_streak += poisoned_steps
        else:
            self.skip_streak = 0
        trigger: Optional[Dict[str, Any]] = None
        if self.skip_streak >= self.cfg.skip_streak:
            trigger = {"signal": "skip_streak",
                       "streak": self.skip_streak,
                       "first_nonfinite_op": tel.first_nonfinite_op}
        # z-rules see only finite samples: a NaN window already trips
        # the streak path above, and a NaN in the rolling baseline
        # would poison the z-score of every later window
        snapshot = {}
        for key, v in (("loss_mean", tel.loss_mean),
                       ("grad_norm", tel.grad_norm_last)):
            if v is not None and math.isfinite(float(v)):
                snapshot[key] = float(v)
        for rule in self._rules:
            rule.step(snapshot, now=float(self.windows_seen))
            if rule.state == "firing" and trigger is None:
                trigger = {"signal": rule.id, "z": rule.value,
                           "sample": rule.sample,
                           "first_nonfinite_op": tel.first_nonfinite_op}
        if trigger is not None:
            trigger.update(epoch=epoch, step=step)
            self.last_trigger = dict(trigger)
        return trigger

    def on_rollback(self, window: Dict[str, int]) -> None:
        """The Trainer restored a verified-good serial: consume one
        budget unit, record the quarantined window, and restart the
        anomaly baselines (re-entry begins a fresh regime — keeping a
        baseline that straddles the divergence would re-trip on the
        first healthy window)."""
        self.rollbacks += 1
        self.skip_streak = 0
        self.quarantine_windows.append(dict(window))
        self._rules = self._build_rules()

    def note_quarantined_feed(self, n: int = 1) -> None:
        """Admission-rejected batches (Trainer(validate_feed=True) /
        DeviceFeeder(validate=True)) join the same quarantine ledger —
        poison stopped at the door instead of after a device step."""
        self.quarantined_batches += int(n)

    # -- export ---------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """The metrics-collector view (observe.registry
        recovery_collector) — plain scalars only."""
        return {
            "rollbacks": self.rollbacks,
            "budget": self.cfg.max_rollbacks,
            "halted": int(self.halted),
            "skip_streak": self.skip_streak,
            "quarantined_batches": self.quarantined_batches,
            "quarantine_windows": len(self.quarantine_windows),
            "verified_serials": len(self._verified),
        }
