"""Continuous-batching autoregressive decode over a paged KV cache.

The generative half of the serving subsystem (ISSUE 12): where
`engine.ServingEngine` serves single-shot inference over shape
buckets, this engine serves DECODE — requests that produce tokens one
iteration at a time, live for wildly different lengths, and would
waste most of the chip under static batching (a batch is as slow as
its longest member, and a dense per-request KV buffer reserves
worst-case memory for every slot).  The design is Ragged Paged
Attention's (PAPERS.md arxiv 2604.15464):

- **fixed-slot batch, paged KV pool** — `num_slots` decode lanes whose
  K/V lives in fixed-size PAGES of one shared pool, addressed through
  per-slot page tables.  Pages are allocated on admit, extended as a
  slot grows, and returned the moment it finishes — memory follows the
  RAGGED true lengths, not the worst case.
- **iteration-level (continuous) batching** — new requests join an
  open slot BETWEEN decode iterations (prefill-on-join through a
  bucketed prompt ladder), instead of waiting for a full batch.  The
  admission/circuit-breaker plane (`admission.py`) is wired in from
  day one: bounded queue, fast-reject shedding, deadline drops,
  breaker on executor failures.
- **preemption** — when the pool runs dry, the lowest-priority slot is
  evicted (pages returned, request requeued); greedy decode makes the
  regenerated tokens identical, so preemption is invisible to callers
  except in latency (and in the `preemptions` counter).
- **jitted While-based decode** — each dispatch runs up to
  `decode_chunk` iterations as ONE `lax.while_loop` on device (the one
  loop reserved for decode per CLAUDE.md), exiting early the moment
  any slot finishes so its pages free and a queued request can join.
  Chunking amortizes the ~114 ms tunnel dispatch RTT over many tokens
  (the TTFT/TPOT convention in stats.py).

Every executable has a FIXED shape: the slot batch, the pool, the page
tables, and the chunk bound never change across joins/leaves/
preemptions, so steady state performs ZERO XLA compiles — the same
contract, accounting, and loud-event plumbing as ServingEngine.  The
pool is sized up front with `observe.memory.plan_fit` (two small-pool
probe compiles extrapolate the peak) and impossible configs are
rejected with a structured `DecodeMemoryError` BEFORE warmup, the way
`ServingEngine.start()` rejects bucket ladders.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ..observe.events import RunEventLog
from ..observe.monitoring import runtime_stats
from .admission import (AdmissionController, CircuitBreaker,
                        DeadlineExceededError, ExecutorFailureError,
                        ServingClosedError, ServingError,
                        WeightReloadError)
from .engine import BucketConfig
from .stats import DecodeStats


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


class DecodeBucketMissError(ServingError):
    """The request fits no prefill bucket / exceeds the slot length
    budget (structured: carries the offending lengths and ladder)."""

    kind = "decode_bucket_miss"


class DecodeMemoryError(ServingError):
    """The configured slot/pool geometry's PREDICTED peak memory
    exceeds the device budget — raised by start() BEFORE warmup from
    the observe.memory fit planner's small-pool probes."""

    kind = "decode_memory"


class DecodeReplicaFailedError(ServingError):
    """An accepted request was pulled off its replica mid-generation —
    the scheduler died, the request was evacuated for a weight roll,
    or the engine shut down with it unresolved.

    RETRYABLE by construction: greedy decode regenerates
    token-identically from the prompt alone, so the error carries the
    full requeue `descriptor` (prompt, sampling params, priority, the
    committed-token count and the tokens emitted so far) — a router
    resubmits it on a surviving replica and can verify the
    regeneration reproduces the committed prefix exactly.  `reason` is
    one of "scheduler_failed" / "evacuated" / "shutdown"; `cause`
    carries the original failure when one exists."""

    kind = "decode_replica_failed"
    retryable = True


class DecodeConfig:
    """Geometry + scheduling knobs of the decode engine.

    num_slots: fixed decode lanes (the device batch).
    page_size: tokens per KV page.
    max_len: per-slot budget (prompt + generated); sets the page-table
        width `max_pages_per_slot`.
    num_pages: shared pool size.  Default: slots * pages-per-slot (no
        preemption pressure); size it TIGHTER than the worst case to
        trade preemptions for memory — `kv_page_utilization` and
        `preemptions` in the stats tell you where you landed.
    prefill_buckets: ascending prompt-length ladder; one prefill
        executable compiles per bucket at start() (a prompt pads UP to
        the smallest fitting bucket).
    decode_chunk: max While iterations per decode dispatch (early-exits
        when a slot finishes).
    eos_id: optional stop token.
    kv_dtype: pool storage — "float32" (exact parity), "bfloat16"
        (default production), or "int8" (per-row scale sidecars,
        opt-in; A/B'd in AB_r09.json, default stays bf16 pending a
        chip wall-clock win).
    """

    def __init__(self, num_slots: int = 8, page_size: int = 16,
                 max_len: int = 256, num_pages: Optional[int] = None,
                 prefill_buckets: Sequence[int] = (32, 64, 128),
                 decode_chunk: int = 8, eos_id: Optional[int] = None,
                 kv_dtype: str = "bfloat16"):
        if num_slots < 1 or page_size < 1 or max_len < 2:
            raise ValueError("num_slots/page_size >= 1, max_len >= 2")
        if decode_chunk < 1:
            raise ValueError("decode_chunk must be >= 1")
        self.num_slots = int(num_slots)
        self.page_size = int(page_size)
        self.max_len = int(max_len)
        self.max_pages_per_slot = _cdiv(self.max_len, self.page_size)
        self.num_pages = int(num_pages) if num_pages is not None else \
            self.num_slots * self.max_pages_per_slot
        self.prefill_buckets = BucketConfig._ladder("prefill_buckets",
                                                    prefill_buckets)
        if self.prefill_buckets[-1] > self.max_len:
            raise ValueError(
                f"largest prefill bucket {self.prefill_buckets[-1]} "
                f"exceeds max_len {self.max_len}")
        if self.num_pages < self.max_pages_per_slot:
            raise ValueError(
                f"num_pages {self.num_pages} below max_pages_per_slot "
                f"{self.max_pages_per_slot}: one max-length request "
                f"could never be served, even alone")
        self.decode_chunk = int(decode_chunk)
        self.eos_id = eos_id
        self.kv_dtype = str(kv_dtype)


class DecodeRequest:
    """One accepted generation request."""

    __slots__ = ("prompt", "max_new_tokens", "priority", "future",
                 "deadline", "t_submit", "preempted", "trace",
                 "handoff")

    def __init__(self, prompt: np.ndarray, max_new_tokens: int,
                 priority: int = 0, deadline: Optional[float] = None,
                 trace=None):
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.priority = int(priority)
        self.future: Future = Future()
        self.deadline = deadline
        self.t_submit = time.monotonic()
        self.preempted = 0
        self.trace = trace  # observe.reqtrace.RequestTrace (or None)
        self.handoff = None  # disagg: imported KV package (decode role)

    def descriptor(self, generated: Optional[List[int]] = None
                   ) -> Dict[str, Any]:
        """The requeue wire form a router resubmits on another replica
        (and verifies token-identity against): everything that defines
        the greedy generation, plus what this replica had already
        committed."""
        gen = [int(t) for t in (generated or [])]
        return {"prompt": [int(t) for t in self.prompt],
                "max_new_tokens": self.max_new_tokens,
                "priority": self.priority,
                "deadline": self.deadline,
                "committed_tokens": len(gen),
                "generated": gen,
                "preempted": self.preempted}


class PagePool:
    """Host-side free-list allocator over the device pool's page
    indices.  Single-threaded (the scheduler owns it)."""

    def __init__(self, num_pages: int):
        self.num_pages = int(num_pages)
        self._free = list(range(num_pages - 1, -1, -1))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.num_pages - len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        if n > len(self._free):
            return None
        got = self._free[-n:][::-1]
        del self._free[-n:]
        return got

    def free(self, pages: List[int]):
        self._free.extend(reversed(pages))


class _Slot:
    """Scheduler-side state of one decode lane."""

    __slots__ = ("req", "pages", "committed", "generated", "cur_tok",
                 "remaining", "version")

    def __init__(self, req: DecodeRequest, pages: List[int],
                 version: int = 0):
        self.req = req
        self.pages = pages
        self.committed = len(req.prompt)   # tokens whose KV is pooled
        self.generated: List[int] = []     # tokens produced so far
        self.cur_tok = 0                   # pending (uncommitted) token
        self.remaining = req.max_new_tokens
        self.version = version             # model_version that serves
        #                                    this whole generation

    @property
    def cap_tokens(self) -> int:
        # the LAST generated token is never committed to KV
        return len(self.req.prompt) + self.req.max_new_tokens - 1

    def importance(self):
        # higher tuple = more important (kept under preemption)
        return (self.req.priority, -self.req.t_submit)


class DecodeEngine:
    """Continuous-batching decode endpoint over a DecoderLM.

        lm = DecoderLM(vocab_size=...)
        engine = DecodeEngine(lm, DecodeConfig(num_slots=8))
        engine.start()                       # plan_fit gate + warmup
        fut = engine.submit(prompt_ids, max_new_tokens=64)
        tokens = fut.result()                # np.int32 generated ids
        engine.close()

    model: a models.decoder_lm.DecoderLM (programs + parameter scope).
    Threading: submit() from any thread; ONE scheduler thread owns
    dispatch, the page pool, and the slot table.
    """

    def __init__(self, model, config: Optional[DecodeConfig] = None,
                 queue_capacity: int = 128,
                 default_deadline_ms: Optional[float] = None,
                 event_log: Optional[RunEventLog] = None,
                 log_path: Optional[str] = None,
                 stats_window: int = 64,
                 breaker: Union[CircuitBreaker, bool, None] = None,
                 memory_budget_bytes: Union[int, bool, None] = None,
                 donate_pools: Optional[bool] = None, tracer=None,
                 role: str = "unified", speculate_k: int = 0,
                 drafter=None):
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(
                f"role must be 'unified', 'prefill' or 'decode'; "
                f"got {role!r}")
        # speculative decoding (ISSUE 20, serving/speculate.py): a
        # drafter proposes up to k tokens per slot, ONE fixed-shape
        # verify dispatch (the step program at folded batch S*(k+1))
        # scores them all, greedy longest-accepted-prefix acceptance
        # commits 1..k+1 tokens bit-identical to the sequential engine
        self.speculate_k = int(speculate_k or 0)
        if self.speculate_k < 0:
            raise ValueError(
                f"speculate_k must be >= 0, got {speculate_k}")
        if self.speculate_k and role == "prefill":
            raise ValueError(
                "speculate_k requires a decoding role — a "
                "role='prefill' worker never runs decode steps; put "
                "the drafter on the decode workers (serving/disagg.py)")
        if drafter is not None and not self.speculate_k:
            raise ValueError("drafter given but speculate_k is 0")
        self.drafter = None
        if self.speculate_k:
            from .speculate import NGramDrafter

            self.drafter = (drafter if drafter is not None
                            else NGramDrafter(self.speculate_k))
            if getattr(self.drafter, "k", None) != self.speculate_k:
                raise ValueError(
                    f"drafter.k {getattr(self.drafter, 'k', None)} != "
                    f"speculate_k {self.speculate_k}")
        # disagg phase specialization (serving/disagg.py): a "prefill"
        # engine compiles only the bucket ladder plus a page-EXPORT
        # gather and resolves every request with a KV handoff package;
        # a "decode" engine compiles only the chunk loop plus a
        # fixed-shape page-IMPORT scatter and admits requests through
        # import_handoff().  "unified" is the byte-identical default.
        self.role = role
        self.model = model
        # observe pillar 7: per-request tracing (host spans only —
        # join_wait, per-chunk dispatch, preempt/evacuated markers);
        # None disables, fleet-passed traces ride through regardless
        self.tracer = tracer
        self.config = config or DecodeConfig(kv_dtype=model.kv_dtype)
        if self.config.kv_dtype != model.kv_dtype:
            raise ValueError(
                f"config.kv_dtype {self.config.kv_dtype!r} != model "
                f"kv_dtype {model.kv_dtype!r}")
        self._own_log = None
        if event_log is None and log_path is not None:
            event_log = self._own_log = RunEventLog(
                log_path, meta={"component": "decode_engine"})
        self._event_log = event_log
        self.stats = DecodeStats(event_log=event_log,
                                 window=stats_window)
        if self.speculate_k:
            self.stats.configure_speculation(self.speculate_k)
        if breaker is None:
            breaker = CircuitBreaker(failure_threshold=5, cooldown_s=5.0)
        elif breaker is False:
            breaker = None
        self.admission = AdmissionController(
            queue_capacity, default_deadline_ms=default_deadline_ms,
            breaker=breaker)
        self.memory_budget_bytes = memory_budget_bytes
        self.fit_plan: Optional[Dict[str, Any]] = None
        if donate_pools is None:
            import jax

            donate_pools = jax.default_backend() == "tpu"
        self._donate = bool(donate_pools)

        self.scope = model.init_params()
        import jax
        import jax.numpy as jnp

        from ..core.executor import RNG_STATE_VAR

        self._params = {
            n: jax.device_put(jnp.asarray(v))
            for n, v in self.scope.vars.items()
            if v is not None and n != RNG_STATE_VAR}
        self._cache_names = model.cache_feed_names()
        self._pools: Optional[Dict[str, Any]] = None
        self._decode_exec = None
        self._verify_exec = None   # speculate_k > 0: replaces the
        #                            sequential chunk executable
        self._prefill_execs: Dict[int, Any] = {}
        self._export_exec = None   # role="prefill": page gather
        self._import_exec = None   # role="decode": page scatter
        self.page_pool = PagePool(self.config.num_pages)
        self._page_tables = np.zeros(
            (self.config.num_slots, self.config.max_pages_per_slot),
            np.int32)
        self._slots: List[Optional[_Slot]] = \
            [None] * self.config.num_slots
        self._queue: List[DecodeRequest] = []
        self._unresolved = 0      # accepted requests not yet resolved
        self._cv = threading.Condition()
        self._worker: Optional[threading.Thread] = None
        self._stop = False
        self._started = False
        # fleet surface: replica identity, weight version, and the
        # control requests (evacuation / weight swap) the scheduler
        # services between dispatches
        self.replica_id: Optional[int] = None
        self.model_version = 0
        self._evac_waiters: List[Dict[str, Any]] = []
        self._pending_reload: Optional[Dict[str, Any]] = None

    def set_replica_id(self, replica_id: int) -> None:
        """Name this engine as fleet replica `replica_id` and stamp the
        id on every event it (and its stats) emits — N replicas sharing
        one RunEventLog stay disambiguated (the log's write lock
        already makes the concurrent emits safe; this makes them
        attributable)."""
        self.replica_id = int(replica_id)
        if self._event_log is not None \
                and hasattr(self._event_log, "bind"):
            bound = self._event_log.bind(replica_id=self.replica_id)
            self._event_log = bound
            self.stats._event_log = bound

    # -- jitted executables ---------------------------------------------
    def _feed_env(self, params, pools, **feeds):
        env = dict(params)
        env.update(pools)
        env.update(feeds)
        return env

    def _build_decode_fn(self):
        import jax
        import jax.numpy as jnp

        from ..core.executor import interpret_program

        st = self.model.step
        program = st["main"]
        next_name = st["next_token"]
        cache_outs = st["cache_outs"]
        cache_names = self._cache_names
        fetches = (next_name, *cache_outs)
        chunk = self.config.decode_chunk
        eos = self.config.eos_id

        def chunk_fn(params, tokens, write_pos, active, remaining,
                     page_table, pools):
            outbuf0 = jnp.full((tokens.shape[0], chunk), -1, jnp.int32)

            def cond(c):
                i, _t, _w, act, fin_any, _r, _p, _o = c
                return ((i < chunk) & jnp.logical_not(fin_any)
                        & (jnp.sum(act) > 0))

            def body(c):
                i, tok, wp, act, _fin, rem, pls, outbuf = c
                env = self._feed_env(
                    params, pls, tokens=tok, write_pos=wp,
                    lengths=wp + 1, active=act, page_table=page_table)
                env = interpret_program(program, env, None,
                                        fetch_names=fetches)
                nxt = env[next_name].astype(jnp.int32)
                new_pools = {n: env[o] for n, o in
                             zip(cache_names, cache_outs)}
                produced = act > 0
                outbuf = outbuf.at[:, i].set(jnp.where(produced, nxt,
                                                       -1))
                new_wp = wp + act
                new_rem = rem - act
                fin = produced & (new_rem <= 0)
                if eos is not None:
                    fin = fin | (produced & (nxt == eos))
                new_act = jnp.where(fin, 0, act)
                new_tok = jnp.where(produced, nxt, tok)
                return (i + 1, new_tok, new_wp, new_act, jnp.any(fin),
                        new_rem, new_pools, outbuf)

            init = (jnp.int32(0), tokens, write_pos, active,
                    jnp.bool_(False), remaining, pools, outbuf0)
            (steps, tok, wp, act, _fin, rem, pls, outbuf) = \
                jax.lax.while_loop(cond, body, init)
            return outbuf, steps, tok, wp, act, rem, pls

        return chunk_fn

    def _build_verify_fn(self):
        """Speculative verify: ONE dispatch of the step body at folded
        batch S*(k+1) — row (s, j) scores position committed_s + j,
        staggered lengths make it causal, inactive rows' KV writes
        drop, and greedy acceptance (`speculative_accept`) runs
        in-program.  Returns (accepted (S,), tokens (S, k+1), pools);
        the rejected-tail 'rollback' is the host simply not advancing
        the slot past the accepted position."""
        import jax.numpy as jnp

        from ..core.executor import interpret_program

        ver = self.model.verify(self.speculate_k)
        program = ver["main"]
        acc_name = ver["accepted"]
        tok_name = ver["tokens"]
        cache_outs = ver["cache_outs"]
        cache_names = self._cache_names
        fetches = (acc_name, tok_name, *cache_outs)

        def verify_fn(params, folded, drafts, slot_meta, page_table,
                      pools):
            # folded rows: [tokens, write_pos, lengths, active] at
            # (4, S*(k+1)); slot_meta rows: [draft_len, slot_active]
            # at (2, S).  Packing the small int feeds into two arrays
            # keeps the per-round host->device transfer count low —
            # the verify round races the sequential engine's chunk
            # dispatch, so feed overhead is on the critical path.
            env = self._feed_env(
                params, pools, tokens=folded[0], write_pos=folded[1],
                lengths=folded[2], active=folded[3], drafts=drafts,
                draft_len=slot_meta[0], slot_active=slot_meta[1],
                page_table=page_table)
            env = interpret_program(program, env, None,
                                    fetch_names=fetches)
            new_pools = {n: env[o] for n, o in
                         zip(cache_names, cache_outs)}
            return (env[acc_name].astype(jnp.int32),
                    env[tok_name].astype(jnp.int32), new_pools)

        return verify_fn

    def _build_prefill_fn(self, t_bucket: int):
        import jax.numpy as jnp

        from ..core.executor import interpret_program

        pre = self.model.prefill(t_bucket)
        program = pre["main"]
        next_name = pre["next_token"]
        cache_outs = pre["cache_outs"]
        cache_names = self._cache_names
        fetches = (next_name, *cache_outs)

        def prefill_fn(params, tokens, seq_len, last_idx, page_table,
                       pools):
            env = self._feed_env(
                params, pools, tokens=tokens, seq_len=seq_len,
                last_idx=last_idx, page_table=page_table)
            env = interpret_program(program, env, None,
                                    fetch_names=fetches)
            nxt = env[next_name].astype(jnp.int32)
            return nxt, {n: env[o]
                         for n, o in zip(cache_names, cache_outs)}

        return prefill_fn

    def _build_export_fn(self):
        """role="prefill": gather ONE slot's pool pages into dense
        token-major rows (T_cap, C), T_cap = max_pages_per_slot *
        page_size.  Fixed shape for any slot/prompt — rows past the
        committed length gather whatever the zero page-table padding
        points at and are masked again on import (NumValid)."""

        def export_fn(page_table_row, pools):
            out = {}
            for n, p in pools.items():
                g = p[page_table_row]        # (maxp, page, C)
                out[n] = g.reshape(g.shape[0] * g.shape[1], g.shape[2])
            return out

        return export_fn

    def _build_import_fn(self):
        """role="decode": scatter one handoff's exported rows into this
        worker's OWN pool pages (the receiving slot's page-table row)
        via the drop-mode paged scatter — one fixed shape serves any
        join/handoff/failover pattern, the zero-recompile contract
        across the hop."""
        from ..ops.paged_kv import paged_import_rows

        def import_fn(rows, page_table_row, num_valid, pools):
            return {n: paged_import_rows(pools[n], rows[n],
                                         page_table_row, num_valid)
                    for n in pools}

        return import_fn

    def _specs(self):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        s = cfg.num_slots
        i32 = jnp.int32
        vec = jax.ShapeDtypeStruct((s,), i32)
        pt = jax.ShapeDtypeStruct((s, cfg.max_pages_per_slot), i32)
        pool_specs = self.model.pool_specs(cfg.num_pages,
                                           cfg.page_size)
        params_spec = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
                       for n, v in self._params.items()}
        return params_spec, vec, pt, pool_specs

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "DecodeEngine":
        """Validate the geometry (plan_fit memory gate), AOT-compile
        every executable (decode chunk + one prefill per bucket), then
        open for traffic.  Steady state performs zero XLA compiles."""
        import jax

        with self._cv:
            if self._started:
                raise RuntimeError("engine already started")
            self._started = True
        cfg = self.config
        if self._event_log is not None:
            self._event_log.event(
                "serving_decode_start",
                num_slots=cfg.num_slots, page_size=cfg.page_size,
                num_pages=cfg.num_pages, max_len=cfg.max_len,
                prefill_buckets=list(cfg.prefill_buckets),
                decode_chunk=cfg.decode_chunk, kv_dtype=cfg.kv_dtype,
                role=self.role,
                queue_capacity=self.admission.queue_capacity)
        snap = runtime_stats.snapshot()
        t0 = time.perf_counter()
        # memory gate BEFORE any full-size compile OR pool allocation
        # (DecodeMemoryError) — an impossible geometry never touches
        # the device at its configured size
        self._validate_memory_budget()
        self._pools = {n: jax.device_put(v) for n, v in
                       self.model.fresh_pools(cfg.num_pages,
                                              cfg.page_size).items()}
        params_spec, vec, pt, pool_specs = self._specs()
        i32 = jax.numpy.int32
        n_exec = 0
        if self.role != "prefill":
            if self.speculate_k:
                # the verify executable REPLACES the sequential chunk
                # loop: one fixed folded shape serves any accept
                # pattern (ragged drafts ride the draft_len companion)
                k1 = self.speculate_k + 1
                fmat = jax.ShapeDtypeStruct((4, cfg.num_slots * k1),
                                            i32)
                fpt = jax.ShapeDtypeStruct(
                    (cfg.num_slots * k1, cfg.max_pages_per_slot), i32)
                dspec = jax.ShapeDtypeStruct(
                    (cfg.num_slots, self.speculate_k), i32)
                smeta = jax.ShapeDtypeStruct((2, cfg.num_slots), i32)
                donate = (5,) if self._donate else ()
                self._verify_exec = jax.jit(
                    self._build_verify_fn(),
                    donate_argnums=donate).lower(
                        params_spec, fmat, dspec, smeta, fpt,
                        pool_specs).compile()
            else:
                donate = (6,) if self._donate else ()
                self._decode_exec = jax.jit(
                    self._build_decode_fn(),
                    donate_argnums=donate).lower(
                        params_spec, vec, vec, vec, vec, pt,
                        pool_specs).compile()
            n_exec += 1
        if self.role != "decode":
            for t in cfg.prefill_buckets:
                tok = jax.ShapeDtypeStruct((cfg.num_slots, t), i32)
                last = jax.ShapeDtypeStruct((cfg.num_slots, 1), i32)
                donate_p = (5,) if self._donate else ()
                self._prefill_execs[t] = jax.jit(
                    self._build_prefill_fn(t),
                    donate_argnums=donate_p).lower(
                        params_spec, tok, vec, last, pt,
                        pool_specs).compile()
            n_exec += len(cfg.prefill_buckets)
        row = jax.ShapeDtypeStruct((cfg.max_pages_per_slot,), i32)
        if self.role == "prefill":
            # page-export gather: pools NOT donated — the worker keeps
            # serving from them after every export
            self._export_exec = jax.jit(
                self._build_export_fn()).lower(row, pool_specs).compile()
            n_exec += 1
        if self.role == "decode":
            t_cap = cfg.max_pages_per_slot * cfg.page_size
            rows_spec = {
                n: jax.ShapeDtypeStruct((t_cap, spec.shape[2]),
                                        spec.dtype)
                for n, spec in pool_specs.items()}
            nv = jax.ShapeDtypeStruct((), i32)
            donate_i = (3,) if self._donate else ()
            self._import_exec = jax.jit(
                self._build_import_fn(),
                donate_argnums=donate_i).lower(
                    rows_spec, row, nv, pool_specs).compile()
            n_exec += 1
        if self.drafter is not None:
            # drafter compiles land INSIDE the warmup window, so the
            # zero-post-warmup-compile contract covers drafting too
            self.drafter.start(self)
            if self._event_log is not None:
                self._event_log.event(
                    "serving_decode_speculate",
                    speculate_k=self.speculate_k,
                    drafter=type(self.drafter).__name__)
        delta = runtime_stats.delta(snap)
        self.stats.record_warmup(n_exec,
                                 delta["compiles"],
                                 delta["compile_time_s"],
                                 time.perf_counter() - t0)
        self.admission.start()
        self._worker = threading.Thread(target=self._loop,
                                        name="decode-scheduler",
                                        daemon=True)
        self._worker.start()
        return self

    def _validate_memory_budget(self):
        """Predict the decode step's peak HBM at the CONFIGURED pool
        size from two small-pool probe compiles (observe.memory
        plan_fit: peak is affine in the pool page count) and reject an
        impossible geometry BEFORE the full-size warmup."""
        budget = self.memory_budget_bytes
        if budget is False:
            return
        if budget is None or budget is True:
            from ..observe.memory import device_memory_budget

            budget = device_memory_budget()
        if not budget:
            self.fit_plan = {"skipped": "no device budget known",
                             "budget_bytes": None}
            return
        cfg = self.config
        if cfg.num_pages == cfg.num_slots:
            # plan_fit scales EVERY leading dim equal to `batch`; a
            # pool exactly the slot count would scale the slot feeds
            # with it and corrupt the fit
            self.fit_plan = {"skipped": "num_pages == num_slots "
                                        "(ambiguous probe axis)",
                            "budget_bytes": int(budget)}
            return
        import jax

        from ..core.executor import Executor, scope_guard
        from ..observe.memory import plan_fit

        st = self.model.step
        params_spec, vec, pt, pool_specs = self._specs()
        feed = dict(pool_specs)
        i32 = jax.numpy.int32
        feed.update(tokens=vec, write_pos=vec, lengths=vec,
                    active=vec, page_table=pt)
        try:
            with scope_guard(self.scope):
                plan = plan_fit(
                    st["main"], feed,
                    fetch_list=[st["next_token"]] + st["cache_outs"],
                    scope=self.scope, batch=cfg.num_pages,
                    budget_bytes=int(budget))
        except RuntimeError as e:
            self.fit_plan = {"skipped": str(e),
                             "budget_bytes": int(budget)}
            return
        self.fit_plan = plan
        if self._event_log is not None:
            self._event_log.event("serving_decode_memory_plan", **plan)
        if plan["fits"] is False:
            raise DecodeMemoryError(
                f"decode geometry predicted to exceed the device "
                f"memory budget: peak "
                f"{plan['predicted_peak_bytes'] / 1e9:.2f} GB vs "
                f"budget {budget / 1e9:.2f} GB (num_pages="
                f"{cfg.num_pages}, page_size={cfg.page_size}, "
                f"num_slots={cfg.num_slots})",
                plan=plan, budget_bytes=int(budget))

    def drain(self, timeout_s: float = 120.0) -> bool:
        """Stop admission, let every accepted request finish decoding.
        Idempotent."""
        self.admission.begin_drain()
        end = time.monotonic() + timeout_s
        with self._cv:
            self._cv.notify_all()
            while self._unresolved > 0:
                remaining = end - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(min(remaining, 0.05))
        if self._event_log is not None:
            self.stats.emit("serving_decode_drain", drained=True)
        return True

    def close(self, timeout_s: float = 120.0):
        if self.admission.state == "running":
            self.drain(timeout_s)
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._worker is not None:
            self._worker.join(timeout_s)
        # shutdown never strands a future: anything a timed-out drain
        # left behind resolves with the RETRYABLE structured error
        # (requeue descriptor attached) so a router can still finish
        # the request on another replica
        self._pull_all("shutdown")
        self.admission.finish_drain()
        if self._own_log is not None:
            self._own_log.close()

    def __enter__(self) -> "DecodeEngine":
        return self.start() if not self._started else self

    def __exit__(self, *exc):
        self.close()
        return False

    def health(self) -> Dict[str, Any]:
        return self.admission.health(
            active_slots=sum(s is not None for s in self._slots),
            num_slots=self.config.num_slots,
            queue_depth=len(self._queue),
            pages_in_use=self.page_pool.in_use,
            num_pages=self.config.num_pages,
            completed=self.stats.completed,
            replica_id=self.replica_id,
            role=self.role,
            model_version=self.model_version,
            post_warmup_compiles=self.stats.post_warmup_compiles())

    # -- fleet surface: evacuation + hot weight reload ------------------
    def evacuate(self, timeout_s: float = 30.0) -> List[Dict[str, Any]]:
        """Pull every accepted-but-unresolved request off this replica
        and return their requeue descriptors.  Each future resolves
        with the structured, retryable DecodeReplicaFailedError (the
        same wire form `_fail_everything` uses), so a router that
        chained them fails the requests over; the returned descriptors
        are the same data for routers that track requests themselves.
        Runs on the scheduler thread at a batch boundary (inline when
        the scheduler is not running); the engine keeps serving — new
        submits after the evacuation are admitted normally."""
        with self._cv:
            alive = (self._worker is not None and self._worker.is_alive()
                     and not self._stop)
            if alive:
                waiter = {"ev": threading.Event(), "result": None}
                self._evac_waiters.append(waiter)
                self._cv.notify_all()
        if not alive:
            return self._pull_all("evacuated")
        if not waiter["ev"].wait(timeout_s):
            raise WeightReloadError(
                f"evacuation not serviced within {timeout_s:.0f}s "
                f"(scheduler wedged?)", replica_id=self.replica_id,
                timeout_s=timeout_s)
        return waiter["result"]

    def reload(self, source, version: Optional[int] = None,
               timeout_s: float = 60.0) -> Dict[str, Any]:
        """Hot weight reload: materialize `source` (a sharded-
        checkpoint dir via io.load_sharded, or a name→array mapping),
        assert every array matches the live parameter's shape and dtype
        — the same-shape swap is what guarantees the jitted executables
        are reused with ZERO recompiles — and swap at the scheduler's
        next batch boundary.  Refuses while generations are in flight
        (evacuate() first; the fleet roll does).  Returns {"version",
        "pause_ms"}; raises the structured WeightReloadError on any
        violation, leaving the old weights serving."""
        t0 = time.perf_counter()
        params = self._materialize_params(source)
        self._check_reload_shapes(params)
        new_version = (self.model_version + 1 if version is None
                       else int(version))
        with self._cv:
            alive = (self._worker is not None and self._worker.is_alive()
                     and not self._stop)
            if alive:
                if self._pending_reload is not None:
                    raise WeightReloadError(
                        "another reload is already pending",
                        replica_id=self.replica_id)
                pend = {"params": params, "version": new_version,
                        "ev": threading.Event(), "error": None}
                self._pending_reload = pend
                self._cv.notify_all()
        if not alive:
            active = sum(s is not None for s in self._slots)
            if active:
                raise WeightReloadError(
                    f"{active} generation(s) still in flight; "
                    f"evacuate() first", replica_id=self.replica_id)
            self._params = params
            self.model_version = new_version
        else:
            if not pend["ev"].wait(timeout_s):
                raise WeightReloadError(
                    f"reload not applied within {timeout_s:.0f}s "
                    f"(scheduler wedged?)", replica_id=self.replica_id,
                    timeout_s=timeout_s)
            if pend["error"]:
                raise WeightReloadError(
                    f"reload refused: {pend['error']}",
                    replica_id=self.replica_id)
        pause_ms = (time.perf_counter() - t0) * 1e3
        self.stats.record_reload(pause_ms)
        if self._event_log is not None:
            self._event_log.event(
                "serving_decode_reload", version=new_version,
                pause_ms=round(pause_ms, 3),
                source=source if isinstance(source, str) else "arrays")
        return {"version": new_version, "pause_ms": round(pause_ms, 3)}

    def _materialize_params(self, source) -> Dict[str, Any]:
        """Device-resident name→array dict from a sharded checkpoint
        dir (io.load_sharded into this engine's scope) or a mapping."""
        import jax
        import jax.numpy as jnp

        from ..core.executor import RNG_STATE_VAR

        if isinstance(source, str):
            from .. import io as fluid_io
            from ..core.executor import Executor, scope_guard

            with scope_guard(self.scope):
                fluid_io.load_sharded(Executor(), source,
                                      main_program=self.model.step["main"])
            src = {n: v for n, v in self.scope.vars.items()
                   if v is not None and n != RNG_STATE_VAR}
        else:
            src = dict(source)
        return {n: jax.device_put(jnp.asarray(v))
                for n, v in src.items() if n in self._params}

    def _check_reload_shapes(self, params: Dict[str, Any]):
        missing = sorted(set(self._params) - set(params))
        if missing:
            raise WeightReloadError(
                f"reload source missing {len(missing)} parameter(s): "
                f"{missing[:4]}{' ...' if len(missing) > 4 else ''}",
                replica_id=self.replica_id, missing=missing)
        mismatched = [
            {"name": n, "live": [list(self._params[n].shape),
                                 str(self._params[n].dtype)],
             "new": [list(params[n].shape), str(params[n].dtype)]}
            for n in self._params
            if (tuple(params[n].shape) != tuple(self._params[n].shape)
                or params[n].dtype != self._params[n].dtype)]
        if mismatched:
            raise WeightReloadError(
                f"{len(mismatched)} parameter(s) change shape/dtype — "
                f"a same-shape swap is the zero-recompile contract; "
                f"first: {mismatched[0]}",
                replica_id=self.replica_id, mismatched=mismatched)

    # -- request path ---------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 32,
               priority: int = 0,
               deadline_ms: Optional[float] = None,
               _trace=None) -> Future:
        """Accept one generation request; returns a Future of the
        generated token ids (np.int32, includes the eos token when one
        stopped it).  Raises DecodeBucketMissError / QueueFullError /
        CircuitOpenError / ServingClosedError synchronously.
        `_trace`: a fleet router's RequestTrace to continue."""
        if self.role == "decode":
            raise ValueError(
                "role='decode' engine admits requests only through "
                "import_handoff() — prompts prefill on a prefill "
                "worker (serving/disagg.py)")
        trace = _trace
        if trace is None and self.tracer is not None:
            trace = self.tracer.new_trace("decode")
        prompt = np.asarray(prompt)
        if prompt.ndim != 1 or prompt.size < 1:
            raise DecodeBucketMissError(
                "prompt must be a non-empty 1-D token array",
                got_shape=list(prompt.shape))
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        cfg = self.config
        plen = int(prompt.size)
        if BucketConfig.pick(cfg.prefill_buckets, plen) is None:
            self.stats.record_bucket_miss()
            raise DecodeBucketMissError(
                f"prompt length {plen} exceeds the largest prefill "
                f"bucket {cfg.prefill_buckets[-1]}",
                prompt_len=plen,
                prefill_buckets=list(cfg.prefill_buckets))
        if plen + max_new_tokens > cfg.max_len:
            self.stats.record_bucket_miss()
            raise DecodeBucketMissError(
                f"prompt {plen} + max_new_tokens {max_new_tokens} "
                f"exceeds the per-slot budget max_len {cfg.max_len}",
                prompt_len=plen, max_new_tokens=int(max_new_tokens),
                max_len=cfg.max_len)
        deadline = self.admission.deadline_for(deadline_ms)
        req = DecodeRequest(prompt.astype(np.int32), max_new_tokens,
                            priority=priority, deadline=deadline,
                            trace=trace)
        try:
            with self._cv:
                self.admission.check(self._unresolved)
                self._queue.append(req)
                self._unresolved += 1
                self._cv.notify_all()
        except ServingError as e:
            if e.kind == "queue_full":
                self.stats.record_shed()
            elif e.kind == "circuit_open":
                self.stats.record_circuit_reject()
            if trace is not None and not trace.fleet_owned \
                    and self.tracer is not None:
                trace.point("rejected", reject=e.kind,
                            replica_id=self.replica_id)
                self.tracer.finish(trace, error=e)
            raise
        self.stats.record_submit()
        return req.future

    def generate(self, prompt, max_new_tokens: int = 32,
                 timeout_s: Optional[float] = None,
                 **kw) -> np.ndarray:
        """Synchronous submit()+result() convenience."""
        return self.submit(prompt, max_new_tokens, **kw).result(
            timeout_s)

    def import_handoff(self, handoff: Dict[str, Any],
                       deadline_ms: Optional[float] = None,
                       _trace=None) -> Future:
        """role="decode" entry: accept a prefill worker's KV handoff
        package (the export of `_export_handoffs`) and continue the
        generation from its first token.  The imported slot is seeded
        to EXACTLY the post-prefill state of the unified engine
        (committed prompt KV, pending first token, remaining budget),
        so greedy decode continues bit-identically — the token-parity
        proof holds across the hop.  Returns a Future of the FULL
        generated ids (first token included)."""
        if self.role != "decode":
            raise ValueError(
                "import_handoff() requires role='decode' "
                f"(this engine is role={self.role!r})")
        trace = _trace
        if trace is None and self.tracer is not None:
            trace = self.tracer.new_trace("decode")
        prompt = np.asarray(handoff["prompt"], np.int32)
        committed = int(handoff["committed"])
        max_new = int(handoff["max_new_tokens"])
        cfg = self.config
        if prompt.ndim != 1 or prompt.size < 1 \
                or committed != prompt.size:
            raise ValueError(
                f"handoff package inconsistent: committed {committed} "
                f"vs prompt length {prompt.size}")
        if handoff.get("rows") is None:
            raise ValueError("handoff package carries no KV rows "
                             "(done=True packages resolve at the "
                             "router, not on a decode worker)")
        if committed + max_new > cfg.max_len:
            self.stats.record_bucket_miss()
            raise DecodeBucketMissError(
                f"handoff prompt {committed} + max_new_tokens "
                f"{max_new} exceeds the per-slot budget max_len "
                f"{cfg.max_len}", prompt_len=committed,
                max_new_tokens=max_new, max_len=cfg.max_len)
        deadline = self.admission.deadline_for(deadline_ms)
        req = DecodeRequest(prompt, max_new,
                            priority=int(handoff.get("priority", 0)),
                            deadline=deadline, trace=trace)
        req.handoff = handoff
        try:
            with self._cv:
                self.admission.check(self._unresolved)
                self._queue.append(req)
                self._unresolved += 1
                self._cv.notify_all()
        except ServingError as e:
            if e.kind == "queue_full":
                self.stats.record_shed()
            elif e.kind == "circuit_open":
                self.stats.record_circuit_reject()
            if trace is not None and not trace.fleet_owned \
                    and self.tracer is not None:
                trace.point("rejected", reject=e.kind,
                            replica_id=self.replica_id)
                self.tracer.finish(trace, error=e)
            raise
        self.stats.record_submit()
        return req.future

    # -- scheduler ------------------------------------------------------
    def _loop(self):
        from ..resilience import chaos

        while True:
            with self._cv:
                while (not self._stop and not self._queue
                       and not any(self._slots)
                       and not self._evac_waiters
                       and self._pending_reload is None):
                    self._cv.wait(0.05)
                if self._stop:
                    return
            try:
                self._service_control()
                if self.replica_id is not None:
                    # fleet chaos points (resilience.chaos.kill_replica
                    # / delay_replica): a kill raises here and drives
                    # the REAL abrupt-death path below — exactly what
                    # an executor crash mid-dispatch does; a delay
                    # models a straggling replica for hedge proofs
                    chaos.delaypoint(f"replica:{self.replica_id}:delay")
                    chaos.failpoint(f"replica:{self.replica_id}:kill")
                self._admit()
                self._decode()
            except BaseException as e:  # noqa: BLE001 — the scheduler
                #                         thread must never die silently
                self._fail_everything(e)
                return
            self.stats.maybe_emit()

    def _service_control(self):
        """Evacuations and weight swaps land HERE, on the scheduler
        thread BETWEEN dispatches — the drain-to-batch-boundary
        contract: a control action never interleaves with a dispatch,
        and a swap never touches a live generation (the reload refuses
        unless the slots are empty; the fleet roll evacuates first)."""
        with self._cv:
            evac = self._evac_waiters
            self._evac_waiters = []
            pend = self._pending_reload
            self._pending_reload = None
        if evac:
            descs = self._pull_all("evacuated")
            for w in evac:
                w["result"] = descs
                w["ev"].set()
        if pend is not None:
            active = sum(s is not None for s in self._slots)
            if active:
                pend["error"] = (f"{active} generation(s) still in "
                                 f"flight; evacuate() first")
            else:
                self._params = pend["params"]
                self.model_version = pend["version"]
            pend["ev"].set()

    def _pull_all(self, reason: str, cause: Optional[str] = None
                  ) -> List[Dict[str, Any]]:
        """Remove EVERY accepted-but-unresolved request (active slots +
        queue), resolve each future with the structured, retryable
        DecodeReplicaFailedError carrying its requeue descriptor, free
        the pages, and return the descriptors.  Only safe on the
        scheduler thread or once the scheduler is stopped/dead (the
        slot table is scheduler-owned)."""
        victims: List[tuple] = []
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            self._slots[i] = None
            self.page_pool.free(slot.pages)
            self._page_tables[i, :] = 0
            victims.append((slot.req, slot.generated))
        with self._cv:
            victims += [(r, []) for r in self._queue]
            self._queue = []
            self._unresolved -= len(victims)
            self._cv.notify_all()
        descs: List[Dict[str, Any]] = []
        if not victims:
            return descs
        self.stats.record_evacuation(len(victims))
        if self._event_log is not None:
            self._event_log.event(
                "serving_decode_evacuate", reason=reason, cause=cause,
                requests=len(victims),
                pages_free_after=self.page_pool.free_pages)
        for req, gen in victims:
            d = req.descriptor(gen)
            descs.append(d)
            err = DecodeReplicaFailedError(
                f"request pulled off replica "
                f"{self.replica_id if self.replica_id is not None else '?'}"
                f" ({reason}) after {len(gen)} committed token(s); "
                f"requeue the descriptor on a surviving replica",
                reason=reason, cause=cause,
                replica_id=self.replica_id, descriptor=d)
            if req.trace is not None:
                # the failover hop itself is the ROUTER's span; the
                # replica marks why the request left it
                req.trace.point("evacuated", reason=reason,
                                replica_id=self.replica_id,
                                committed=len(gen))
                if not req.trace.fleet_owned and self.tracer is not None:
                    self.tracer.finish(req.trace, error=err)
            if not req.future.done():
                req.future.set_exception(err)
        return descs

    def _fail_everything(self, exc: BaseException):
        """The scheduler died: stop accepting, then resolve every
        accepted request with the structured retryable error (requeue
        descriptors attached) instead of a bare exception — the
        router-facing half of the failover contract."""
        cause = f"{type(exc).__name__}: {exc}"
        # a dead scheduler must not keep ACCEPTING: later submits get
        # ServingClosedError instead of queueing forever
        try:
            self.admission.begin_drain()
        except ServingError:
            pass
        self._pull_all("scheduler_failed", cause=cause)
        # control waiters must not hang on a dead scheduler either
        with self._cv:
            evac = self._evac_waiters
            self._evac_waiters = []
            pend = self._pending_reload
            self._pending_reload = None
        for w in evac:
            w["result"] = []
            w["ev"].set()
        if pend is not None:
            pend["error"] = f"scheduler died: {cause}"
            pend["ev"].set()

    def _resolve(self, slot_id: int, error: Optional[BaseException]
                 = None, value=None):
        slot = self._slots[slot_id]
        self._slots[slot_id] = None
        self.page_pool.free(slot.pages)
        self._page_tables[slot_id, :] = 0
        with self._cv:
            self._unresolved -= 1
            self._cv.notify_all()
        tr = slot.req.trace
        own_trace = (tr is not None and not tr.fleet_owned
                     and self.tracer is not None)
        if error is not None:
            if not slot.req.future.done():
                slot.req.future.set_exception(error)
            if own_trace:
                self.tracer.finish(tr, error=error)
            return
        if not slot.req.future.done():
            # which weights produced this generation (a router's
            # response tag for the hot-reload roll)
            slot.req.future.model_version = slot.version
            # `value` overrides the token array for role="prefill":
            # the future resolves with the KV handoff package instead
            slot.req.future.set_result(
                value if value is not None
                else np.asarray(slot.generated, np.int32))
        self.stats.record_done()
        if own_trace:
            self.tracer.finish(tr)

    def _requeue(self, slot_id: int):
        """Preempt: pages returned, request re-enters the queue head
        and will regenerate from the prompt (greedy => identical
        tokens)."""
        slot = self._slots[slot_id]
        self._slots[slot_id] = None
        self.page_pool.free(slot.pages)
        self._page_tables[slot_id, :] = 0
        slot.req.preempted += 1
        if slot.req.trace is not None:
            slot.req.trace.point(
                "preempt", slot=slot_id, replica_id=self.replica_id,
                committed=slot.committed, generated=len(slot.generated))
        with self._cv:
            self._queue.insert(0, slot.req)
        self.stats.record_preemption()
        if self._event_log is not None:
            self._event_log.event(
                "serving_decode_preempt", slot=slot_id,
                priority=slot.req.priority,
                committed=slot.committed,
                generated=len(slot.generated),
                pages_freed=len(slot.pages),
                pages_free_after=self.page_pool.free_pages)

    def _set_pages(self, slot_id: int, pages: List[int]):
        self._page_tables[slot_id, :] = 0
        self._page_tables[slot_id, :len(pages)] = pages

    def _admit(self):
        """Fill open slots from the queue (prefill-on-join): pick
        joiners, allocate prompt pages, run ONE bucket-padded prefill
        dispatch over the whole slot batch (non-joiners masked out by
        seq_len 0)."""
        cfg = self.config
        now = time.monotonic()
        joiners: List[int] = []
        while True:
            free_ids = [i for i, s in enumerate(self._slots)
                        if s is None]
            if not free_ids:
                break
            req = None
            with self._cv:
                # priority first, then FIFO; expired requests drop
                # before any device time is spent on them
                self._queue.sort(key=lambda r: (-r.priority,
                                                r.t_submit))
                while self._queue:
                    cand = self._queue[0]
                    if cand.deadline is not None \
                            and now > cand.deadline:
                        self._queue.pop(0)
                        self._unresolved -= 1
                        self.stats.record_deadline_miss()
                        exc = DeadlineExceededError(
                            "deadline expired before a slot opened",
                            queued_ms=round(
                                (now - cand.t_submit) * 1e3, 3))
                        if cand.trace is not None:
                            cand.trace.add(
                                "join_wait", cand.t_submit, now,
                                replica_id=self.replica_id,
                                expired=True)
                            if not cand.trace.fleet_owned \
                                    and self.tracer is not None:
                                self.tracer.finish(cand.trace,
                                                   error=exc)
                        cand.future.set_exception(exc)
                        continue
                    req = cand
                    break
                if req is not None:
                    need = _cdiv(len(req.prompt), cfg.page_size)
                    pages = self.page_pool.alloc(need)
                    if pages is None:
                        req = None  # pool dry: decode frees pages,
                        #             not admission
                    else:
                        self._queue.pop(0)
            if req is None:
                break
            slot_id = free_ids[0]
            self._slots[slot_id] = _Slot(req, pages,
                                         version=self.model_version)
            self._set_pages(slot_id, pages)
            joiners.append(slot_id)
        if not joiners:
            return
        # disagg: handoff joiners import their prefilled KV pages (one
        # fixed-shape scatter each) instead of prefilling
        imports = [i for i in joiners
                   if self._slots[i].req.handoff is not None]
        prefills = [i for i in joiners
                    if self._slots[i].req.handoff is None]
        for i in imports:
            self._dispatch_import(i)
        if prefills:
            self._dispatch_prefill(prefills)

    def _dispatch_import(self, slot_id: int):
        """Scatter one handoff's exported KV rows into this worker's
        pool at the receiving slot's pages, then seed the slot to the
        unified engine's post-prefill state (pending first token) so
        the next decode chunk continues bit-identically."""
        import jax.numpy as jnp

        cfg = self.config
        slot = self._slots[slot_id]
        h = slot.req.handoff
        t_i0 = time.monotonic()
        tr = slot.req.trace
        if tr is not None:
            tr.add("join_wait", slot.req.t_submit, t_i0,
                   replica_id=self.replica_id, slot=slot_id)
        try:
            rows = {n: jnp.asarray(h["rows"][n]) for n in self._pools}
            pools = self._import_exec(
                rows, jnp.asarray(self._page_tables[slot_id]),
                jnp.asarray(np.int32(h["committed"])), self._pools)
        except BaseException as e:
            self.stats.record_executor_failure()
            self._breaker_result(False, 1)
            err = ExecutorFailureError(
                f"KV-page import dispatch failed: "
                f"{type(e).__name__}: {e}",
                error_type=type(e).__name__, joins=1)
            t_i1 = time.monotonic()
            if tr is not None:
                tr.add("dispatch", t_i0, t_i1, kind="import",
                       replica_id=self.replica_id, slot=slot_id,
                       error=type(e).__name__)
            self._resolve(slot_id, error=err)
            return
        t_i1 = time.monotonic()
        if tr is not None:
            tr.add("dispatch", t_i0, t_i1, kind="import",
                   replica_id=self.replica_id, slot=slot_id,
                   pages=len(slot.pages))
        self._breaker_result(True, 1)
        self._pools = pools
        slot.committed = int(h["committed"])
        slot.cur_tok = int(h["first_token"])
        slot.generated = [int(t) for t in h["generated"]]
        slot.remaining = slot.req.max_new_tokens - len(slot.generated)
        self.stats.record_import()
        if self.drafter is not None:
            # no draft-model KV crossed the wire: re-seed the draft
            # pool from the raw prompt (serving/speculate.py)
            self.drafter.on_import(self, slot_id)
        if slot.remaining <= 0 or (cfg.eos_id is not None
                                   and slot.cur_tok == cfg.eos_id):
            self._resolve(slot_id)

    def _dispatch_prefill(self, joiners: List[int]):
        import jax.numpy as jnp

        cfg = self.config
        bucket = BucketConfig.pick(
            cfg.prefill_buckets,
            max(len(self._slots[i].req.prompt) for i in joiners))
        tokens = np.zeros((cfg.num_slots, bucket), np.int32)
        seq_len = np.zeros((cfg.num_slots,), np.int32)
        last_idx = np.zeros((cfg.num_slots, 1), np.int32)
        for i in joiners:
            p = self._slots[i].req.prompt
            tokens[i, :len(p)] = p
            seq_len[i] = len(p)
            last_idx[i, 0] = len(p) - 1
        exec_ = self._prefill_execs[bucket]
        t_p0 = time.monotonic()  # join_wait ends / prefill begins
        for i in joiners:
            tr = self._slots[i].req.trace
            if tr is not None:
                tr.add("join_wait", self._slots[i].req.t_submit, t_p0,
                       replica_id=self.replica_id, slot=i)
        try:
            nxt, pools = exec_(self._params, jnp.asarray(tokens),
                               jnp.asarray(seq_len),
                               jnp.asarray(last_idx),
                               jnp.asarray(self._page_tables),
                               self._pools)
        except BaseException as e:
            self.stats.record_executor_failure()
            self._breaker_result(False, len(joiners))
            err = ExecutorFailureError(
                f"prefill dispatch failed for {len(joiners)} join(s): "
                f"{type(e).__name__}: {e}",
                error_type=type(e).__name__, joins=len(joiners))
            t_p1 = time.monotonic()
            for i in joiners:
                tr = self._slots[i].req.trace
                if tr is not None:
                    tr.add("dispatch", t_p0, t_p1, kind="prefill",
                           replica_id=self.replica_id, slot=i,
                           error=type(e).__name__)
            for i in joiners:
                self._resolve(i, error=err)
            return
        t_p1 = time.monotonic()
        for i in joiners:
            tr = self._slots[i].req.trace
            if tr is not None:
                tr.add("dispatch", t_p0, t_p1, kind="prefill",
                       bucket=bucket, replica_id=self.replica_id,
                       slot=i)
        self._breaker_result(True, len(joiners))
        self._pools = pools
        nxt = np.asarray(nxt)
        now = time.monotonic()
        ttfts = []
        for i in joiners:
            slot = self._slots[i]
            tok = int(nxt[i])
            slot.cur_tok = tok
            slot.generated.append(tok)
            slot.remaining = slot.req.max_new_tokens - 1
            ttfts.append((now - slot.req.t_submit) * 1e3)
        self.stats.record_prefill(len(joiners), ttfts)
        if self.drafter is not None:
            # mirror the join into the draft pool (same buffers, same
            # page tables — the pools share geometry by construction)
            self.drafter.on_prefill(self, joiners, tokens, seq_len,
                                    last_idx)
        if self.role == "prefill":
            # disagg: every joiner resolves NOW with its KV handoff
            # package — the slot and pages recycle immediately, so the
            # prefill worker's TTFT is decoupled from any decode
            # occupancy (the whole point of the split)
            self._export_handoffs(joiners)
            return
        # a request satisfied by its very first token resolves here
        for i in joiners:
            slot = self._slots[i]
            if slot.remaining <= 0 or (cfg.eos_id is not None
                                       and slot.cur_tok == cfg.eos_id):
                self._resolve(i)

    def _export_handoffs(self, joiners: List[int]):
        """role="prefill": gather each joiner's pool pages to host rows
        and resolve its future with the handoff wire package (PR 14
        descriptor fields + the KV rows; docs/SERVING.md §disagg).
        Rows copy VERBATIM in pool dtype — int8 codes and their scale
        sidecars transfer without requantization, so the hop is
        bitwise."""
        import jax.numpy as jnp

        cfg = self.config
        for i in joiners:
            slot = self._slots[i]
            done = slot.remaining <= 0 or (
                cfg.eos_id is not None and slot.cur_tok == cfg.eos_id)
            t_e0 = time.monotonic()
            rows = None
            nbytes = 0
            if not done:
                exported = self._export_exec(
                    jnp.asarray(self._page_tables[i]), self._pools)
                rows = {n: np.asarray(v) for n, v in exported.items()}
                # valid rows only — padding rows never cross the wire
                # in accounting (they do travel in the fixed buffers)
                nbytes = sum(slot.committed * v.shape[1]
                             * v.dtype.itemsize for v in rows.values())
            t_e1 = time.monotonic()
            tr = slot.req.trace
            if tr is not None and not done:
                tr.add("export", t_e0, t_e1,
                       replica_id=self.replica_id, slot=i,
                       pages=len(slot.pages), bytes=nbytes)
            package = {
                "kind": "handoff",
                "prompt": [int(t) for t in slot.req.prompt],
                "first_token": int(slot.cur_tok),
                "generated": [int(t) for t in slot.generated],
                "committed": int(slot.committed),
                "max_new_tokens": slot.req.max_new_tokens,
                "priority": slot.req.priority,
                "done": bool(done),
                "n_pages": len(slot.pages),
                "rows": rows,
                "bytes": int(nbytes),
                "export_ms": round((t_e1 - t_e0) * 1e3, 3),
                "from_replica": self.replica_id,
                "model_version": slot.version,
            }
            self._resolve(i, value=package)

    def _breaker_result(self, ok: bool, n: int):
        res = self.admission.record_dispatch_result(ok)
        if res and self._event_log is not None:
            self._event_log.event(
                f"serving_breaker_{'open' if res == 'opened' else 'close'}",
                state=self.admission.state, component="decode_engine",
                breaker=self.admission.breaker.snapshot(),
                batch=n)

    def _ensure_decode_pages(self) -> List[int]:
        """Extend every active slot's pages to cover the next chunk,
        preempting the least-important slots when the pool runs dry.
        Returns the slot ids still active afterwards."""
        cfg = self.config
        # speculative rounds commit at most k+1 tokens per dispatch
        # (positions committed..committed+k), the chunk loop at most
        # decode_chunk — the page window follows whichever path runs
        window = (self.speculate_k + 1) if self.speculate_k \
            else cfg.decode_chunk
        order = sorted(
            (i for i, s in enumerate(self._slots) if s is not None),
            key=lambda i: self._slots[i].importance(), reverse=True)
        for i in order:
            slot = self._slots[i]
            if slot is None:
                continue  # preempted as a victim earlier in the loop
            target = _cdiv(min(slot.committed + window,
                               slot.cap_tokens), cfg.page_size)
            while slot is not None and target > len(slot.pages):
                got = self.page_pool.alloc(target - len(slot.pages))
                if got is not None:
                    slot.pages.extend(got)
                    self._set_pages(i, slot.pages)
                    break
                # pool dry: evict the least-important active slot
                # (possibly this one)
                victims = [j for j, sj in enumerate(self._slots)
                           if sj is not None]
                victim = min(victims,
                             key=lambda j: self._slots[j].importance())
                self._requeue(victim)
                slot = self._slots[i]
        return [i for i, s in enumerate(self._slots) if s is not None]

    def _decode(self):
        import jax.numpy as jnp

        if self._verify_exec is not None:
            self._decode_speculative()
            return
        if self._decode_exec is None:
            return  # role="prefill": every slot resolved at export
        cfg = self.config
        active_ids = self._ensure_decode_pages()
        if not active_ids:
            return
        s = cfg.num_slots
        tokens = np.zeros((s,), np.int32)
        write_pos = np.zeros((s,), np.int32)
        active = np.zeros((s,), np.int32)
        remaining = np.zeros((s,), np.int32)
        for i in active_ids:
            slot = self._slots[i]
            tokens[i] = slot.cur_tok
            write_pos[i] = slot.committed
            active[i] = 1
            remaining[i] = slot.remaining
        t0 = time.perf_counter()
        t_d0 = time.monotonic()
        try:
            (outbuf, steps, new_tok, new_wp, new_act, new_rem,
             pools) = self._decode_exec(
                self._params, jnp.asarray(tokens),
                jnp.asarray(write_pos), jnp.asarray(active),
                jnp.asarray(remaining),
                jnp.asarray(self._page_tables), self._pools)
        except BaseException as e:
            self.stats.record_executor_failure()
            self._breaker_result(False, len(active_ids))
            err = ExecutorFailureError(
                f"decode dispatch failed for {len(active_ids)} "
                f"slot(s): {type(e).__name__}: {e}",
                error_type=type(e).__name__, slots=len(active_ids))
            t_d1 = time.monotonic()
            for i in active_ids:
                tr = self._slots[i].req.trace
                if tr is not None:
                    tr.add("dispatch", t_d0, t_d1, kind="decode",
                           replica_id=self.replica_id, slot=i,
                           error=type(e).__name__)
            for i in active_ids:
                self._resolve(i, error=err)
            return
        elapsed_ms = (time.perf_counter() - t0) * 1e3
        t_d1 = time.monotonic()
        for i in active_ids:
            tr = self._slots[i].req.trace
            if tr is not None:
                tr.add("dispatch", t_d0, t_d1, kind="decode",
                       iterations=int(steps),
                       replica_id=self.replica_id, slot=i)
        self._breaker_result(True, len(active_ids))
        self._pools = pools
        outbuf = np.asarray(outbuf)
        steps = int(steps)
        new_wp = np.asarray(new_wp)
        new_act = np.asarray(new_act)
        new_rem = np.asarray(new_rem)
        new_tok = np.asarray(new_tok)
        total_tokens = 0
        for i in active_ids:
            slot = self._slots[i]
            produced = int(new_wp[i]) - slot.committed
            toks = [int(t) for t in outbuf[i, :produced] if t >= 0]
            slot.generated.extend(toks)
            total_tokens += len(toks)
            slot.committed = int(new_wp[i])
            slot.cur_tok = int(new_tok[i])
            slot.remaining = int(new_rem[i])
        self.stats.record_decode(
            steps, len(active_ids), cfg.num_slots, total_tokens,
            self.page_pool.in_use, cfg.num_pages, elapsed_ms)
        for i in active_ids:
            if int(new_act[i]) == 0:
                self._resolve(i)

    def _decode_speculative(self):
        """One verify round: draft on the host, score all drafts in
        ONE folded dispatch, commit the accepted prefix (+1 model
        token) per slot.  Token-identical to `_decode`'s sequential
        chunk by the greedy-acceptance argument in
        ops/paged_kv.py `speculative_accept`; rollback of a rejected
        tail is simply not advancing `committed` — the stale rows sit
        past every length and are overwritten before any attention
        reads them."""
        import jax.numpy as jnp

        cfg = self.config
        k = self.speculate_k
        k1 = k + 1
        active_ids = self._ensure_decode_pages()
        if not active_ids:
            return
        s = cfg.num_slots
        proposals, prop_len = self.drafter.draft(self, active_ids)
        folded = np.zeros((4, s * k1), np.int32)
        tokens, write_pos, lengths, active = folded
        slot_meta = np.zeros((2, s), np.int32)
        draft_len, slot_active = slot_meta
        drafts = np.zeros((s, k), np.int32)
        pt = np.zeros((s * k1, cfg.max_pages_per_slot), np.int32)
        ar = np.arange(k1)
        for i in active_ids:
            slot = self._slots[i]
            # cap so emitted (accepted+1) never exceeds the remaining
            # budget and the last write position stays under
            # cap_tokens (committed + remaining == cap_tokens)
            m = int(min(int(prop_len[i]), k, slot.remaining - 1))
            draft_len[i] = m
            drafts[i, :m] = proposals[i, :m]
            slot_active[i] = 1
            base = i * k1
            live = ar <= m          # row 0 always live (m >= 0)
            # dead rows pin to the slot's current position: their
            # writes drop (active 0) and their predictions are
            # discarded, but their feeds stay in-range
            off = np.where(live, ar, 0)
            tokens[base] = slot.cur_tok
            tokens[base + 1:base + k1] = drafts[i]
            write_pos[base:base + k1] = slot.committed + off
            lengths[base:base + k1] = slot.committed + off + 1
            active[base:base + k1] = live
            pt[base:base + k1] = self._page_tables[i]
        drafted_total = int(draft_len.sum())
        t0 = time.perf_counter()
        t_d0 = time.monotonic()
        try:
            accepted, emitted, pools = self._verify_exec(
                self._params, jnp.asarray(folded),
                jnp.asarray(drafts), jnp.asarray(slot_meta),
                jnp.asarray(pt), self._pools)
        except BaseException as e:
            self.stats.record_executor_failure()
            self._breaker_result(False, len(active_ids))
            err = ExecutorFailureError(
                f"speculative verify dispatch failed for "
                f"{len(active_ids)} slot(s): {type(e).__name__}: {e}",
                error_type=type(e).__name__, slots=len(active_ids))
            t_d1 = time.monotonic()
            for i in active_ids:
                tr = self._slots[i].req.trace
                if tr is not None:
                    tr.add("dispatch", t_d0, t_d1, kind="decode",
                           replica_id=self.replica_id, slot=i,
                           error=type(e).__name__)
            for i in active_ids:
                self._resolve(i, error=err)
            return
        elapsed_ms = (time.perf_counter() - t0) * 1e3
        t_d1 = time.monotonic()
        self._breaker_result(True, len(active_ids))
        self._pools = pools
        accepted = np.asarray(accepted)
        emitted = np.asarray(emitted)
        total_tokens = 0
        accept_counts = []
        finished = []
        for i in active_ids:
            slot = self._slots[i]
            a = int(accepted[i])
            accept_counts.append(a)
            toks = emitted[i, :a + 1].tolist()
            if cfg.eos_id is not None and cfg.eos_id in toks:
                # the sequential engine stops at the FIRST eos; tokens
                # the verify scored past it were never really emitted
                toks = toks[:toks.index(cfg.eos_id) + 1]
            n = len(toks)
            slot.generated.extend(toks)
            total_tokens += n
            slot.committed += n
            slot.cur_tok = toks[-1]
            slot.remaining -= n
            tr = slot.req.trace
            if tr is not None:
                tr.add("dispatch", t_d0, t_d1, kind="decode",
                       iterations=1, replica_id=self.replica_id,
                       slot=i)
                tr.add("speculate", t_d0, t_d1, slot=i,
                       drafted=int(draft_len[i]), accepted=a,
                       emitted=n, replica_id=self.replica_id)
            if slot.remaining <= 0 or (cfg.eos_id is not None
                                       and cfg.eos_id in toks):
                finished.append(i)
        self.stats.record_decode(
            1, len(active_ids), cfg.num_slots, total_tokens,
            self.page_pool.in_use, cfg.num_pages, elapsed_ms)
        self.stats.record_verify(drafted_total, total_tokens,
                                 accept_counts)
        for i in finished:
            self._resolve(i)
